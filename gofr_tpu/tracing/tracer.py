"""Lightweight distributed tracing with W3C traceparent propagation.

Fills the role of the reference's OTel wiring (pkg/gofr/otel.go:20-194 and
middleware/tracer.go:15-32) without dragging in the OTel SDK: spans carry
128-bit trace ids and 64-bit span ids, propagate over the ``traceparent``
header, sample by ``TRACER_RATIO``, and export through a pluggable
``SpanExporter`` (console / in-memory / OTLP-compatible JSON POST can be
added behind the same interface, cf. reference exporter.go:23-49).

The active span rides a contextvar shared with the logging package so
every log line inside a request carries trace/span ids
(reference ctx_logger.go).
"""

from __future__ import annotations

import os
import random
import threading
import time
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Any, Protocol

from ..logging.logger import (current_fleet_context, reset_trace_context,
                              set_trace_context)

_current_span: ContextVar["Span | None"] = ContextVar("gofr_current_span", default=None)


def _rand_hex(nbytes: int) -> str:
    # os.urandom: immune to application random.seed() calls (common in ML
    # test setups), so span ids never collide across seeded workers.
    return os.urandom(nbytes).hex()


# Head-sampling decisions use a PRIVATE generator for the same reason
# span ids use os.urandom: an application calling random.seed() (every
# ML test setup does) must not make the sampling sequence — and thus
# which requests get traced — deterministic and identical across
# seeded workers.
_sample_rng = random.Random(os.urandom(8))


def current_span() -> "Span | None":
    """The span active on this thread/task context, if any — the
    module-level accessor for code (control plane, event ledger
    emitters) that has no Tracer instance in hand but wants to stamp
    records with the ambient trace id."""
    return _current_span.get()


def extract_traceparent(header: str | None) -> tuple[str, str] | None:
    """Parse ``00-<trace-id>-<parent-id>-<flags>`` -> (trace_id, parent_id)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    if parts[1] == "0" * 32 or parts[2] == "0" * 16:
        return None
    return parts[1], parts[2]


def _traceparent_sampled(header: str) -> bool:
    """Read the W3C flags byte: bit 0 = sampled."""
    try:
        return bool(int(header.strip().split("-")[3], 16) & 0x01)
    except (IndexError, ValueError):
        return True


def format_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start_time: float
    tracer: "Tracer"
    sampled: bool = True
    end_time: float | None = None
    attributes: dict[str, Any] = field(default_factory=dict)
    status: str = "OK"
    _ctx_token: Any = None
    _log_token: Any = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def set_status(self, status: str) -> None:
        self.status = status

    def end(self) -> None:
        if self.end_time is not None:
            return
        self.end_time = time.time()
        # Token resets are best-effort: ending a span from a different
        # thread/task than the one that started it must not lose the span.
        if self._ctx_token is not None:
            try:
                _current_span.reset(self._ctx_token)
            except ValueError:
                pass
            self._ctx_token = None
        if self._log_token is not None:
            try:
                reset_trace_context(self._log_token)
            except ValueError:
                pass
            self._log_token = None
        if self.sampled:
            self.tracer._export(self)

    @property
    def duration_ms(self) -> float:
        end = self.end_time if self.end_time is not None else time.time()
        return (end - self.start_time) * 1000.0

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.status = f"ERROR: {exc}"
        self.end()


class SpanExporter(Protocol):
    def export(self, span: Span) -> None: ...


class InMemoryExporter:
    """Collects finished spans; the test-side exporter.

    Bounded: a long-lived app wired to this exporter (TRACE_EXPORTER=
    memory left on in a deployment) must not grow without limit — the
    newest ``max_spans`` are kept in a ring and evictions are counted
    in ``dropped`` so a truncated capture is visible, never silent."""

    def __init__(self, max_spans: int = 8192) -> None:
        self.max_spans = max(1, int(max_spans))
        self.spans: list[Span] = []
        self.dropped = 0
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        with self._lock:
            if len(self.spans) >= self.max_spans:
                # evict oldest; O(n) but only ever at the cap, and
                # this exporter is a debugging/test surface
                del self.spans[0]
                self.dropped += 1
            self.spans.append(span)


class ConsoleExporter:
    def __init__(self, logger) -> None:
        self._logger = logger

    def export(self, span: Span) -> None:
        self._logger.debug(
            f"span {span.name} {span.duration_ms:.2f}ms",
            trace=span.trace_id, span=span.span_id, status=span.status,
        )


class Tracer:
    """Creates spans, honors sampling ratio, manages context propagation."""

    def __init__(self, service_name: str = "gofr-app",
                 exporter: SpanExporter | None = None,
                 ratio: float = 1.0) -> None:
        self.service_name = service_name
        self.exporter = exporter
        self.ratio = max(0.0, min(1.0, ratio))

    def _export(self, span: Span) -> None:
        if self.exporter is not None:
            self.exporter.export(span)

    def current_span(self) -> Span | None:
        return _current_span.get()

    def start_span(self, name: str, *, traceparent: str | None = None,
                   attributes: dict[str, Any] | None = None) -> Span:
        """Start a span as a child of the context span or a remote parent."""
        parent = _current_span.get()
        remote = extract_traceparent(traceparent) if parent is None else None
        if parent is not None:
            trace_id, parent_id, sampled = parent.trace_id, parent.span_id, parent.sampled
        elif remote is not None:
            # Honor the upstream sampling decision so distributed traces
            # never lose their middle spans (W3C flags bit 0).
            trace_id, parent_id = remote
            sampled = _traceparent_sampled(traceparent)
        else:
            trace_id, parent_id = _rand_hex(16), None
            sampled = self.ratio >= 1.0 or _sample_rng.random() < self.ratio
        span = Span(name=name, trace_id=trace_id, span_id=_rand_hex(8),
                    parent_id=parent_id, start_time=time.time(), tracer=self,
                    sampled=sampled,
                    attributes=self._with_resource(attributes))
        span._ctx_token = _current_span.set(span)
        span._log_token = set_trace_context(span.trace_id, span.span_id)
        return span

    @staticmethod
    def _with_resource(attributes: dict[str, Any] | None) -> dict[str, Any]:
        """Resource attributes for every span: the process-wide fleet
        context (host_id/rank/generation, set at control-plane join)
        under the explicit attrs — a cross-host trace tells you which
        host each span ran on without any per-callsite plumbing."""
        fleet = current_fleet_context()
        if not fleet:
            return dict(attributes or {})
        fleet.update(attributes or {})
        return fleet

    def emit_span(self, name: str, *, trace_id: str,
                  parent_id: str | None = None, start_time: float,
                  end_time: float, attributes: dict[str, Any] | None = None,
                  status: str = "OK") -> Span:
        """Build and export a FINISHED span from explicit timestamps.

        The host-side assembly path used by the serving engine: spans
        for a retired request are reconstructed after the fact from
        timestamps the hot loop already collected, on the engine
        thread — so this never touches the contextvar and never makes
        a sampling decision (the caller only invokes it for sampled
        traces)."""
        span = Span(name=name, trace_id=trace_id, span_id=_rand_hex(8),
                    parent_id=parent_id, start_time=start_time,
                    tracer=self, sampled=True,
                    attributes=self._with_resource(attributes),
                    status=status)
        span.end_time = end_time
        self._export(span)
        return span

    def inject_headers(self, headers: dict[str, str]) -> dict[str, str]:
        span = _current_span.get()
        if span is not None:
            headers["traceparent"] = format_traceparent(
                span.trace_id, span.span_id, span.sampled)
        return headers
