from .tracer import (
    Span,
    SpanExporter,
    ConsoleExporter,
    InMemoryExporter,
    Tracer,
    current_span,
    extract_traceparent,
    format_traceparent,
)

__all__ = [
    "Span", "SpanExporter", "ConsoleExporter", "InMemoryExporter", "Tracer",
    "current_span", "extract_traceparent", "format_traceparent",
]
