"""Anonymous usage telemetry, opt-out (reference telemetry.go +
metrics/exporters/telemetry.go).

On ``app.Run`` start and stop, a minimal ping (app name/version,
framework version, event) POSTs to the telemetry endpoint — unless
``GOFR_TELEMETRY=false`` (reference constants.go:15 defaults it on;
tests disable it globally, gofr_test.go:30-33). Failures are silent
and bounded: telemetry must never delay boot/shutdown or surface
errors (the deployment may have zero egress).
"""

from __future__ import annotations

import asyncio
import json
import platform
from typing import Any

from .version import FRAMEWORK

TELEMETRY_URL = "https://telemetry.gofr-tpu.dev/api/v1/ping"
TIMEOUT_S = 2.0


def enabled(config: Any) -> bool:
    import os
    # config first; a DictConfig (tests/embedding) falls through to the
    # process env so the global CI opt-out (conftest.py) always works
    value = config.get("GOFR_TELEMETRY") if hasattr(config, "get") else None
    if value in (None, ""):
        value = os.environ.get("GOFR_TELEMETRY", "true")
    return str(value).strip().lower() not in ("false", "0", "no", "off")


def payload(container: Any, event: str) -> dict:
    return {
        "event": event,
        "app_name": getattr(container, "app_name", ""),
        "app_version": getattr(container, "app_version", ""),
        "framework_version": FRAMEWORK,
        "os": platform.system().lower(),
        "python": platform.python_version(),
    }


async def ping(container: Any, event: str,
               url: str = TELEMETRY_URL) -> bool:
    """Fire one event; True iff delivered. Never raises."""
    if not enabled(container.config):
        return False
    try:
        from .service.client import _raw_request
        body = json.dumps(payload(container, event)).encode()
        resp = await asyncio.wait_for(
            _raw_request("POST", url,
                         headers={"Content-Type": "application/json"},
                         body=body, timeout=TIMEOUT_S),
            timeout=TIMEOUT_S + 0.5)
        return bool(getattr(resp, "ok", False))
    except Exception:
        return False  # telemetry is best-effort by definition
