"""Azure Event Hubs backend via the service's Kafka-compatible
endpoint.

The reference ships an Event Hub module
(/root/reference/pkg/gofr/datasource/pubsub/eventhub/) on Azure's AMQP
client library. Event Hubs also natively exposes a Kafka-compatible
endpoint (``{namespace}.servicebus.windows.net:9093`` — a supported,
documented protocol surface of the service), which maps cleanly onto
this framework's from-scratch Kafka wire client: an event hub is a
topic, partitions are partitions, consumer groups are consumer groups.
:class:`EventHubClient` is that adapter — Event-Hub-shaped
configuration over the Kafka protocol layer.

Production Event Hubs requires TLS + SASL/PLAIN on the Kafka endpoint;
pass ``connection_hook`` to wrap the socket (zero-egress CI exercises
the plaintext path against :class:`~gofr_tpu.pubsub.kafka.
MiniKafkaBroker`).
"""

from __future__ import annotations

from typing import Any

from .kafka import KafkaClient


class EventHubClient(KafkaClient):
    """Event-Hub configuration surface over the Kafka wire client."""

    def __init__(self, namespace: str = "127.0.0.1:9092",
                 eventhub: str = "", consumer_group: str = "$Default",
                 connection_hook: Any = None) -> None:
        # bare namespace names get Azure's Kafka endpoint port
        brokers = namespace if ":" in namespace else f"{namespace}:9093"
        super().__init__(brokers=brokers, group_id=consumer_group,
                         client_id="gofr-eventhub")
        self.eventhub = eventhub
        self.connection_hook = connection_hook

    async def connect(self) -> None:
        await super().connect()
        if self.connection_hook is not None:
            await self.connection_hook(self)

    async def publish(self, topic: str = "", value=b"", key: str = "",
                      metadata: dict | None = None) -> None:
        await super().publish(topic or self.eventhub, value, key=key,
                              metadata=metadata)

    async def subscribe(self, topic: str = "", group: str = ""):
        return await super().subscribe(topic or self.eventhub,
                                       group or self.group_id)

    def health_check(self) -> dict:
        out = super().health_check()
        out["backend"] = "eventhub"
        out["details"]["eventhub"] = self.eventhub
        return out
