"""NATS JetStream: persistence, durable pull consumers, redelivery.

The reference's NATS module is JetStream-grade
(/root/reference/pkg/gofr/datasource/pubsub/nats, 3,446 LoC:
streams, durable consumers, explicit acks, redelivery). This layer
adds the same semantics on top of the core-protocol client
(:mod:`.nats`), speaking JetStream's real request-reply API over
``$JS.API.*`` subjects:

- ``$JS.API.STREAM.CREATE.<stream>`` — persistent subject capture
- ``$JS.API.CONSUMER.DURABLE.CREATE.<stream>.<durable>`` — durable
  pull consumer with an ack-wait window
- ``$JS.API.CONSUMER.MSG.NEXT.<stream>.<durable>`` — pull the next
  message; it arrives with a ``$JS.ACK...`` reply subject
- publishing to a captured subject with a reply inbox returns a
  ``PubAck {stream, seq}``; ``+ACK`` to the message's reply subject
  acknowledges, and unacked messages redeliver after ``ack_wait``
  (at-least-once, the contract ``Message.commit`` expects).

:class:`MiniJetStreamServer` extends the mini NATS server with the
stream/consumer engine so the same bytes work hermetically.
"""

from __future__ import annotations

import asyncio
import base64
import itertools
import json
import time
from typing import Any

from .message import Message
from .nats import MiniNATSServer, NATSClient, NATSError, subject_matches

JS_API = "$JS.API"


class JetStreamError(NATSError):
    pass


class JetStreamClient(NATSClient):
    """Core client + JetStream publish/pull-consume.

    The framework surface is unchanged: ``publish`` persists into the
    subject's stream (auto-created ``{topic}`` stream on first use),
    ``subscribe(topic, group)`` is a durable pull consumer named
    ``group``, ``Message.commit`` ACKs, and uncommitted messages
    redeliver after ``ack_wait_s``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 4222,
                 name: str = "gofr-tpu", ack_wait_s: float = 30.0,
                 request_timeout_s: float = 5.0) -> None:
        super().__init__(host, port, name)
        self.ack_wait_s = ack_wait_s
        self.request_timeout_s = request_timeout_s
        self._inbox_prefix = f"_INBOX.{id(self):x}"
        self._inbox_seq = itertools.count(1)
        self._streams: set[str] = set()
        self._consumers: set[tuple[str, str]] = set()
        #: persistent pull inbox per (topic, group): one SUB reused
        #: across every MSG.NEXT, the standard JetStream pull pattern
        self._pull_inboxes: dict[tuple[str, str], tuple[str, int]] = {}

    @staticmethod
    def _js_name(topic: str) -> str:
        """Stream/durable names cannot contain '.' (JetStream rejects
        them; they are subject separators) — map dotted topics to a
        legal name while the stream still captures the dotted subject."""
        return topic.replace(".", "_").replace(">", "FULL").replace(
            "*", "ANY") or "empty"

    async def _reconnect(self) -> None:
        # server-side state (memory-stored streams/consumers on the
        # mini server; interest state everywhere) died with the
        # connection: re-ensure on demand
        self._streams.clear()
        self._consumers.clear()
        self._pull_inboxes.clear()
        await super()._reconnect()

    # ------------------------------------------------------ request/reply
    async def _request(self, subject: str, payload: bytes,
                       headers: dict | None = None) -> bytes:
        """Core NATS request-reply over a one-shot inbox.  With
        ``headers`` the request goes out as HPUB (NATS 2.2 header
        frame: ``NATS/1.0\\r\\n<K: V>...\\r\\n\\r\\n`` prefix)."""
        await self._ensure_connected()
        inbox = f"{self._inbox_prefix}.{next(self._inbox_seq)}"
        sid = await self._ensure_sub(inbox, "")
        try:
            writer = self._require_writer()
            if headers:
                hdr = ("NATS/1.0\r\n"
                       + "".join(f"{k}: {v}\r\n" for k, v in headers.items())
                       + "\r\n").encode()
                writer.write(
                    f"HPUB {subject} {inbox} {len(hdr)} "
                    f"{len(hdr) + len(payload)}\r\n".encode()
                    + hdr + payload + b"\r\n")
            else:
                writer.write(f"PUB {subject} {inbox} {len(payload)}\r\n"
                             .encode() + payload + b"\r\n")
            await writer.drain()
            item = await asyncio.wait_for(self._queues[sid].get(),
                                          self.request_timeout_s)
            if not isinstance(item, tuple):
                raise JetStreamError("connection lost")
            _subject, reply, body = item
            return body
        except asyncio.TimeoutError as exc:
            raise JetStreamError(f"request timeout on {subject}") from exc
        finally:
            await self.unsubscribe(inbox, "")

    async def _api(self, subject: str, payload: dict) -> dict:
        body = json.loads(await self._request(
            subject, json.dumps(payload).encode()) or b"{}")
        err = body.get("error")
        if err and err.get("code") not in (None, 0):
            # "already exists"-class errors are fine for ensure-paths
            if "exists" not in str(err.get("description", "")):
                raise JetStreamError(f"{subject}: {err}")
        return body

    # ----------------------------------------------------------- streams
    async def ensure_stream(self, topic: str) -> None:
        name = self._js_name(topic)
        if name in self._streams:
            return
        await self._api(f"{JS_API}.STREAM.CREATE.{name}",
                        {"name": name, "subjects": [topic],
                         "retention": "limits", "storage": "memory"})
        self._streams.add(name)

    async def ensure_consumer(self, topic: str, group: str) -> None:
        stream, durable = self._js_name(topic), self._js_name(group)
        if (stream, durable) in self._consumers:
            return
        await self.ensure_stream(topic)
        await self._api(
            f"{JS_API}.CONSUMER.DURABLE.CREATE.{stream}.{durable}",
            {"stream_name": stream,
             "config": {"durable_name": durable,
                        "ack_policy": "explicit",
                        "ack_wait": int(self.ack_wait_s * 1e9)}})
        self._consumers.add((stream, durable))

    # ----------------------------------------------------------- publish
    async def publish(self, topic: str, value: bytes | str | dict,
                      key: str = "", metadata: dict | None = None) -> None:
        if isinstance(value, dict):
            value = json.dumps(value).encode()
        elif isinstance(value, str):
            value = value.encode()
        await self.ensure_stream(topic)
        start = time.perf_counter()
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_total_count",
                                           topic=topic)
        ack = json.loads(await self._request(topic, value) or b"{}")
        if "stream" not in ack:
            raise JetStreamError(f"no PubAck for {topic}: {ack}")
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_success_count",
                                           topic=topic)
            self.metrics.record_histogram("app_pubsub_publish_latency",
                                          time.perf_counter() - start)

    # --------------------------------------------------------- subscribe
    async def _pull_inbox(self, topic: str, group: str) -> tuple[str, int]:
        """One persistent inbox subscription per consumer, reused for
        every pull (re-created after a reconnect)."""
        key = (topic, group)
        entry = self._pull_inboxes.get(key)
        if entry is None:
            inbox = f"{self._inbox_prefix}.{next(self._inbox_seq)}"
            sid = await self._ensure_sub(inbox, "")
            entry = self._pull_inboxes[key] = (inbox, sid)
        return entry

    async def subscribe(self, topic: str, group: str = "default") -> Message:
        stream, durable = self._js_name(topic), self._js_name(group)
        while True:
            await self._ensure_connected()
            await self.ensure_consumer(topic, group)
            inbox, sid = await self._pull_inbox(topic, group)
            writer = self._require_writer()
            req = json.dumps({"batch": 1, "expires": int(450e6)})
            subject = f"{JS_API}.CONSUMER.MSG.NEXT.{stream}.{durable}"
            writer.write(
                f"PUB {subject} {inbox} {len(req)}\r\n".encode()
                + req.encode() + b"\r\n")
            await writer.drain()
            try:
                item = await asyncio.wait_for(self._queues[sid].get(), 0.5)
            except asyncio.TimeoutError:
                continue              # empty pull window: poll again
            if not isinstance(item, tuple):
                continue              # connection died: redial above
            _subject, ack_subject, payload = item
            if not ack_subject:       # 404-style status, nothing pending
                continue
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_pubsub_subscribe_total_count", topic=topic)

            def committer(subject=ack_subject) -> None:
                asyncio.ensure_future(self._ack(subject))
            return Message(topic=topic, value=payload, committer=committer)

    async def _ack(self, subject: str) -> None:
        try:
            writer = self._require_writer()
            writer.write(f"PUB {subject} 4\r\n+ACK\r\n".encode())
            await writer.drain()
        except (NATSError, ConnectionError) as exc:
            if self.logger is not None:
                self.logger.error(f"jetstream ack failed: {exc!r}")

    def health_check(self) -> dict:
        out = super().health_check()
        out["backend"] = "nats-jetstream"
        return out


# --------------------------------------------------------------- server

class _Stream:
    def __init__(self, name: str, subjects: list[str]) -> None:
        self.name = name
        self.subjects = subjects
        #: seq i+1 -> (subject, payload, raw header block or b"")
        self.messages: list[tuple[str, bytes, bytes]] = []


class _Consumer:
    def __init__(self, stream: str, durable: str, ack_wait_s: float) -> None:
        self.stream = stream
        self.durable = durable
        self.ack_wait_s = ack_wait_s
        self.cursor = 0                        # next NEW sequence - 1
        #: seq -> redeliver_at deadline
        self.outstanding: dict[int, float] = {}


class MiniJetStreamServer(MiniNATSServer):
    """Mini NATS server + the JetStream engine: streams capture
    publishes, durable pull consumers track outstanding acks and
    redeliver after the ack-wait window."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__(host, port)
        self.streams: dict[str, _Stream] = {}
        self.consumers: dict[tuple[str, str], _Consumer] = {}

    async def _publish(self, subject: str, reply: str, payload: bytes,
                       hdrs: bytes = b"") -> None:
        if subject.startswith(JS_API + "."):
            await self._handle_api(subject[len(JS_API) + 1:], reply,
                                   payload)
            return
        if subject.startswith("$JS.ACK."):
            self._handle_ack(subject)
            return
        stored = None
        for stream in self.streams.values():
            if any(subject_matches(p, subject) for p in stream.subjects):
                stream.messages.append((subject, payload, hdrs))
                stored = (stream.name, len(stream.messages))
        if stored and reply:
            await self._route(reply, json.dumps(
                {"stream": stored[0], "seq": stored[1]}).encode())
        # core subscribers still get the message
        await self._route(subject, payload)

    async def _handle_api(self, op: str, reply: str,
                          payload: bytes) -> None:
        body = json.loads(payload or b"{}")
        out: dict

        if op.startswith("STREAM.CREATE."):
            name = op.rsplit(".", 1)[-1]
            if name in self.streams:
                out = {"error": {"code": 400,
                                 "description": "stream name already exists"}}
            else:
                self.streams[name] = _Stream(
                    name, body.get("subjects") or [name])
                out = {"config": {"name": name}, "created": True}
        elif op.startswith("CONSUMER.DURABLE.CREATE."):
            _, _, _, stream, durable = op.split(".", 4)
            if stream not in self.streams:
                out = {"error": {"code": 404,
                                 "description": "stream not found"}}
            elif (stream, durable) in self.consumers:
                out = {"error": {"code": 400,
                                 "description": "consumer already exists"}}
            else:
                ack_wait = body.get("config", {}).get("ack_wait", 30e9)
                self.consumers[(stream, durable)] = _Consumer(
                    stream, durable, ack_wait / 1e9)
                out = {"name": durable, "created": True}
        elif op.startswith("CONSUMER.MSG.NEXT."):
            _, _, _, stream, durable = op.split(".", 4)
            consumer = self.consumers.get((stream, durable))
            if consumer is None or reply == "":
                return
            seq = self._next_seq(consumer)
            if seq is None:
                return                        # empty pull: let it expire
            ack_subject = (f"$JS.ACK.{stream}.{durable}.1.{seq}.{seq}."
                           f"{int(time.time())}.0")
            await self._route(reply,
                              self.streams[stream].messages[seq - 1][1],
                              reply=ack_subject)
            return
        elif op.startswith("STREAM.MSG.GET."):
            # direct get: {"seq": n} | {"last_by_subj": subject} — the
            # JetStream API the KV facade's reads ride on
            name = op.rsplit(".", 1)[-1]
            stream_obj = self.streams.get(name)
            if stream_obj is None:
                out = {"error": {"code": 404,
                                 "description": "stream not found"}}
            else:
                found = None
                if "last_by_subj" in body:
                    want = body["last_by_subj"]
                    for i in range(len(stream_obj.messages) - 1, -1, -1):
                        if stream_obj.messages[i][0] == want:
                            found = (i + 1, stream_obj.messages[i])
                            break
                elif "seq" in body:
                    seq = int(body["seq"])
                    if 1 <= seq <= len(stream_obj.messages):
                        found = (seq, stream_obj.messages[seq - 1])
                if found is None:
                    out = {"error": {"code": 404,
                                     "description": "no message found"}}
                else:
                    seq, (subj, payload, hdrs) = found
                    msg = {"subject": subj, "seq": seq,
                           "data": base64.b64encode(payload).decode()}
                    if hdrs:
                        msg["hdrs"] = base64.b64encode(hdrs).decode()
                    out = {"message": msg}
        else:
            out = {"error": {"code": 400, "description": f"bad op {op}"}}
        if reply:
            await self._route(reply, json.dumps(out).encode())

    def _next_seq(self, consumer: _Consumer) -> int | None:
        now = time.monotonic()
        for seq, deadline in sorted(consumer.outstanding.items()):
            if deadline <= now:               # redeliver expired first
                consumer.outstanding[seq] = now + consumer.ack_wait_s
                return seq
        stream = self.streams[consumer.stream]
        if consumer.cursor < len(stream.messages):
            consumer.cursor += 1
            consumer.outstanding[consumer.cursor] = \
                now + consumer.ack_wait_s
            return consumer.cursor
        return None

    def _handle_ack(self, subject: str) -> None:
        # $JS.ACK.<stream>.<durable>.<delivered>.<sseq>...
        parts = subject.split(".")
        if len(parts) < 6:
            return
        stream, durable, seq = parts[2], parts[3], int(parts[5])
        consumer = self.consumers.get((stream, durable))
        if consumer is not None:
            consumer.outstanding.pop(seq, None)
