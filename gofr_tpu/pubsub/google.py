"""Google Cloud Pub/Sub backend speaking the REST protocol, plus an
in-process emulator.

The reference ships a Google Pub/Sub module behind the common pub/sub
interface (/root/reference/pkg/gofr/datasource/pubsub/google/google.go)
using Google's client library; this backend speaks the service's REST
surface directly (the same JSON API the official emulator serves, so
``PUBSUB_EMULATOR_HOST``-style deployments work unchanged):

- ``PUT  /v1/projects/{p}/topics/{t}`` — create topic
- ``POST /v1/projects/{p}/topics/{t}:publish`` — base64 data + attrs
- ``PUT  /v1/projects/{p}/subscriptions/{s}`` — create subscription
- ``POST /v1/projects/{p}/subscriptions/{s}:pull`` — long-poll pull
- ``POST /v1/projects/{p}/subscriptions/{s}:acknowledge``

The framework's consumer groups map to subscriptions named
``{group}-{topic}`` — every group gets each message once (fan-out
across groups, competing consumers within one), exactly the reference
semantics. ``Message.commit`` acknowledges; unacked messages redeliver
after the ack deadline (at-least-once).

:class:`MiniPubSubEmulator` implements the same REST surface on the
framework's own HTTP server with deadline-based redelivery — the
hermetic test stand-in for gcloud's emulator.

Against real GCP, inject an OAuth bearer token via ``auth_headers``
(zero-egress CI never exercises that path).
"""

from __future__ import annotations

import asyncio
import base64
import itertools
import json
import time
from typing import Any

from .message import Message


class GooglePubSubError(Exception):
    pass


class GooglePubSubClient:
    """REST Pub/Sub client on the resilient in-house HTTP service
    client (retry/CB/timeout ride along for free)."""

    def __init__(self, endpoint: str = "http://127.0.0.1:8085",
                 project: str = "gofr", *,
                 ack_deadline_s: int = 10,
                 auth_headers: dict | None = None,
                 timeout: float = 30.0) -> None:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.project = project
        self.ack_deadline_s = ack_deadline_s
        self.auth_headers = dict(auth_headers or {})
        self.timeout = timeout
        self.logger: Any = None
        self.metrics: Any = None
        self.tracer: Any = None
        self._http: Any = None
        self._known: set[str] = set()       # created topics/subs

    def use_logger(self, logger: Any) -> None:
        self.logger = logger

    def use_metrics(self, metrics: Any) -> None:
        self.metrics = metrics

    def use_tracer(self, tracer: Any) -> None:
        self.tracer = tracer

    def _service(self):
        if self._http is None:
            from ..service.client import HTTPService
            self._http = HTTPService(self.endpoint, timeout=self.timeout,
                                     logger=self.logger,
                                     metrics=self.metrics,
                                     service_name="google-pubsub")
        return self._http

    async def _call(self, method: str, path: str, payload: dict | None,
                    ok_conflict: bool = False) -> dict:
        resp = await self._service().request(
            method, path, json=payload, headers=self.auth_headers)
        if resp.status == 409 and ok_conflict:
            return {}
        if resp.status >= 400:
            raise GooglePubSubError(
                f"{method} {path} -> {resp.status}: {resp.body[:200]!r}")
        return json.loads(resp.body or b"{}")

    # ------------------------------------------------------------ admin
    def _topic_path(self, topic: str) -> str:
        return f"/v1/projects/{self.project}/topics/{topic}"

    def _sub_path(self, sub: str) -> str:
        return f"/v1/projects/{self.project}/subscriptions/{sub}"

    async def _ensure_topic(self, topic: str) -> None:
        if topic in self._known:
            return
        await self._call("PUT", self._topic_path(topic), {},
                         ok_conflict=True)
        self._known.add(topic)

    async def _ensure_subscription(self, topic: str, sub: str) -> None:
        if sub in self._known:
            return
        await self._ensure_topic(topic)
        await self._call(
            "PUT", self._sub_path(sub),
            {"topic": f"projects/{self.project}/topics/{topic}",
             "ackDeadlineSeconds": self.ack_deadline_s},
            ok_conflict=True)
        self._known.add(sub)

    def create_topic(self, name: str) -> None:
        task = asyncio.ensure_future(self._ensure_topic(name))
        task.add_done_callback(self._log_ack_errors)

    def delete_topic(self, name: str) -> None:
        async def _delete() -> None:
            await self._call("DELETE", self._topic_path(name), None,
                             ok_conflict=True)
            self._known.discard(name)
        task = asyncio.ensure_future(_delete())
        task.add_done_callback(self._log_ack_errors)

    # ---------------------------------------------------------- publish
    async def publish(self, topic: str, value: bytes | str | dict,
                      key: str = "", metadata: dict | None = None) -> None:
        if isinstance(value, dict):
            value = json.dumps(value).encode()
        elif isinstance(value, str):
            value = value.encode()
        await self._ensure_topic(topic)
        attributes = {str(k): str(v) for k, v in (metadata or {}).items()}
        if key:
            attributes["ordering_key"] = key
        start = time.perf_counter()
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_total_count",
                                           topic=topic)
        await self._call(
            "POST", self._topic_path(topic) + ":publish",
            {"messages": [{"data": base64.b64encode(value).decode(),
                           "attributes": attributes}]})
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_success_count",
                                           topic=topic)
            self.metrics.record_histogram("app_pubsub_publish_latency",
                                          time.perf_counter() - start)

    # -------------------------------------------------------- subscribe
    async def subscribe(self, topic: str, group: str = "default") -> Message:
        sub = f"{group}-{topic}"
        await self._ensure_subscription(topic, sub)
        while True:
            out = await self._call(
                "POST", self._sub_path(sub) + ":pull",
                {"maxMessages": 1, "returnImmediately": False})
            received = out.get("receivedMessages") or []
            if not received:
                await asyncio.sleep(0.05)
                continue
            entry = received[0]
            ack_id = entry["ackId"]
            msg = entry.get("message", {})
            data = base64.b64decode(msg.get("data", ""))
            attrs = msg.get("attributes") or {}
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_pubsub_subscribe_total_count", topic=topic)

            def committer(a=ack_id, s=sub) -> None:
                task = asyncio.ensure_future(self._ack(s, a))
                task.add_done_callback(self._log_ack_errors)
            return Message(topic=topic, value=data,
                           key=attrs.get("ordering_key", ""),
                           metadata=attrs, committer=committer)

    async def _ack(self, sub: str, ack_id: str) -> None:
        await self._call("POST", self._sub_path(sub) + ":acknowledge",
                         {"ackIds": [ack_id]})

    def _log_ack_errors(self, task: "asyncio.Task") -> None:
        exc = task.exception() if not task.cancelled() else None
        if exc is not None and self.logger is not None:
            self.logger.error(f"pubsub background call failed: {exc!r}")

    # ------------------------------------------------------------ misc
    def health_check(self) -> dict:
        # stateless REST client: connections are per-request, so health
        # is config presence; pull/publish failures surface via logs,
        # metrics, and the subscriber runtime's backoff
        return {"status": "UP",
                "backend": "google-pubsub",
                "details": {"endpoint": self.endpoint,
                            "project": self.project}}

    async def close(self) -> None:
        self._http = None


# ------------------------------------------------------------- emulator

class MiniPubSubEmulator:
    """The gcloud-emulator stand-in on the framework's own HTTP server:
    topics, subscriptions, base64 messages, ack-deadline redelivery."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self.topics: dict[str, set[str]] = {}     # topic -> sub names
        #: sub -> {"topic", "deadline", "queue": [msg], "outstanding":
        #:         {ack_id: (msg, redeliver_at)}}
        self.subs: dict[str, dict] = {}
        self._ids = itertools.count(1)
        self._server: Any = None

    async def start(self) -> None:
        from ..http.server import HTTPServer
        from ..http.responder import ResponseData

        async def handler(request) -> ResponseData:
            try:
                status, payload = self._route(
                    request.method, request.path,
                    json.loads(request.body) if request.body else {})
            except GooglePubSubError as exc:
                status, payload = 400, {"error": {"message": str(exc)}}
            return ResponseData(status=status,
                                body=json.dumps(payload).encode(),
                                content_type="application/json")

        self._server = HTTPServer(handler, host=self.host, port=self.port)
        await self._server.start()
        self.port = self._server.bound_port

    # one dispatcher keeps the wire surface in a single place
    def _route(self, method: str, path: str, body: dict) -> tuple[int, dict]:
        parts = path.strip("/").split("/")
        # /v1/projects/{p}/topics/{t}[:verb] | subscriptions/{s}[:verb]
        if len(parts) != 5 or parts[0] != "v1" or parts[1] != "projects":
            return 404, {"error": {"message": f"bad path {path}"}}
        kind, last = parts[3], parts[4]
        name, _, verb = last.partition(":")

        if kind == "topics":
            if method == "PUT" and not verb:
                if name in self.topics:
                    return 409, {"error": {"message": "exists"}}
                self.topics[name] = set()
                return 200, {"name": f"projects/{parts[2]}/topics/{name}"}
            if method == "DELETE" and not verb:
                self.topics.pop(name, None)
                return 200, {}
            if verb == "publish":
                return self._publish(name, body)
        elif kind == "subscriptions":
            if method == "PUT" and not verb:
                return self._create_sub(name, body)
            if verb == "pull":
                return self._pull(name, body)
            if verb == "acknowledge":
                return self._ack(name, body)
        return 404, {"error": {"message": f"bad route {method} {path}"}}

    def _publish(self, topic: str, body: dict) -> tuple[int, dict]:
        self.topics.setdefault(topic, set())
        ids = []
        for msg in body.get("messages", []):
            mid = str(next(self._ids))
            ids.append(mid)
            entry = {"data": msg.get("data", ""),
                     "attributes": msg.get("attributes") or {},
                     "messageId": mid,
                     "publishTime": time.strftime(
                         "%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
            for sub_name in self.topics[topic]:
                self.subs[sub_name]["queue"].append(entry)
        return 200, {"messageIds": ids}

    def _create_sub(self, name: str, body: dict) -> tuple[int, dict]:
        if name in self.subs:
            return 409, {"error": {"message": "exists"}}
        topic = (body.get("topic") or "").rsplit("/", 1)[-1]
        if topic not in self.topics:
            return 404, {"error": {"message": f"no topic {topic}"}}
        self.subs[name] = {"topic": topic, "queue": [],
                           "deadline": int(body.get("ackDeadlineSeconds",
                                                    10)),
                           "outstanding": {}}
        self.topics[topic].add(name)
        return 200, {"name": name}

    def _redeliver_expired(self, sub: dict) -> None:
        now = time.monotonic()
        expired = [a for a, (_, t) in sub["outstanding"].items() if t <= now]
        for ack_id in expired:
            msg, _ = sub["outstanding"].pop(ack_id)
            sub["queue"].append(msg)

    def _pull(self, name: str, body: dict) -> tuple[int, dict]:
        sub = self.subs.get(name)
        if sub is None:
            return 404, {"error": {"message": f"no subscription {name}"}}
        self._redeliver_expired(sub)
        n = max(1, int(body.get("maxMessages", 1)))
        out = []
        while sub["queue"] and len(out) < n:
            msg = sub["queue"].pop(0)
            ack_id = f"ack-{next(self._ids)}"
            sub["outstanding"][ack_id] = (
                msg, time.monotonic() + sub["deadline"])
            out.append({"ackId": ack_id, "message": msg})
        return 200, {"receivedMessages": out}

    def _ack(self, name: str, body: dict) -> tuple[int, dict]:
        sub = self.subs.get(name)
        if sub is None:
            return 404, {"error": {"message": f"no subscription {name}"}}
        for ack_id in body.get("ackIds", []):
            sub["outstanding"].pop(ack_id, None)
        return 200, {}

    async def close(self) -> None:
        if self._server is not None:
            await self._server.shutdown()
