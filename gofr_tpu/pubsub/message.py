"""Pub/sub message — implements the Request protocol so a broker message
drives a handler exactly like an HTTP request (reference
datasource/pubsub/message.go:13-115)."""

from __future__ import annotations

import json
from typing import Any, Callable


class Message:
    def __init__(self, topic: str, value: bytes,
                 key: str = "", metadata: dict | None = None,
                 committer: Callable | None = None) -> None:
        self.topic = topic
        self.value = value
        self.key = key
        self.metadata = dict(metadata or {})
        self._committer = committer
        self.committed = False

    # -- commit (at-least-once: commit on handler success,
    #    reference subscriber.go:75-78)
    def commit(self) -> None:
        if not self.committed and self._committer is not None:
            self._committer()
        self.committed = True

    # -- Request protocol
    def param(self, key: str) -> str:
        return str(self.metadata.get(key, ""))

    def params(self, key: str) -> list[str]:
        value = self.metadata.get(key)
        return [str(value)] if value is not None else []

    def path_param(self, key: str) -> str:
        if key == "topic":
            return self.topic
        return str(self.metadata.get(key, ""))

    def bind(self, target: Any = None) -> Any:
        try:
            data = json.loads(self.value)
        except (json.JSONDecodeError, UnicodeDecodeError):
            data = self.value
        if target is None:
            return data
        import dataclasses

        from ..http.request import BindError, bind_dataclass
        if dataclasses.is_dataclass(target) and isinstance(target, type):
            if not isinstance(data, dict):
                raise BindError(
                    f"cannot bind message to {target.__name__}")
            return bind_dataclass(data, target)
        return data

    def host_name(self) -> str:
        return self.topic
