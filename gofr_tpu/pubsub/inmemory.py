"""In-process broker: topics, durable-until-commit delivery, TPU-aware
partition assignment.

The hermetic stand-in for Kafka/NATS (the reference ships broker
clients behind one interface, datasource/pubsub/interface.go:11-31;
tests mock them, SURVEY §4). Semantics: per-topic FIFO queues,
at-least-once redelivery for uncommitted messages, consumer groups
(each group sees every message once), ``create_topic``/``delete_topic``
admin surface, and publish/subscribe health + metrics.

``partition_for`` implements the north star's "ICI-topology-aware
placement": keys are consistently hashed onto the serving mesh's
devices so a pod slice's workers pull disjoint shards.
"""

from __future__ import annotations

import asyncio
import hashlib
from collections import defaultdict
from typing import Any

from .message import Message


class _GroupQueue:
    def __init__(self) -> None:
        self.queue: asyncio.Queue = asyncio.Queue()
        self.pending: dict[int, tuple] = {}
        self.next_id = 0


class InMemoryBroker:
    BACKLOG_CAP = 10_000

    def __init__(self, logger: Any = None, metrics: Any = None) -> None:
        self.logger = logger
        self.metrics = metrics
        self._topics: dict[str, dict[str, _GroupQueue]] = defaultdict(dict)
        # retained messages replayed to groups created later (earliest-
        # offset semantics, bounded)
        self._backlog: dict[str, list[tuple]] = defaultdict(list)
        self._connected = True

    # ----------------------------------------------------------- admin
    def create_topic(self, name: str) -> None:
        self._topics.setdefault(name, {})

    def delete_topic(self, name: str) -> None:
        self._topics.pop(name, None)
        # drop the retained backlog too, or a recreated topic replays
        # pre-delete messages and deleted topics leak their cap forever
        self._backlog.pop(name, None)

    @property
    def topics(self) -> list[str]:
        return sorted(self._topics.keys())

    def health_check(self) -> dict:
        return {"status": "UP" if self._connected else "DOWN",
                "backend": "inmemory",
                "topics": len(self._topics)}

    def close(self) -> None:
        self._connected = False

    # --------------------------------------------------------- publish
    async def publish(self, topic: str, value: bytes | str | dict,
                      key: str = "", metadata: dict | None = None) -> None:
        if isinstance(value, dict):
            import json
            value = json.dumps(value).encode()
        elif isinstance(value, str):
            value = value.encode()
        groups = self._topics.setdefault(topic, {})
        item = (value, key, dict(metadata or {}))
        backlog = self._backlog[topic]
        backlog.append(item)
        if len(backlog) > self.BACKLOG_CAP:
            del backlog[:len(backlog) - self.BACKLOG_CAP]
        for gq in groups.values():
            await gq.queue.put(item)
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_pubsub_publish_total_count", topic=topic)
            self.metrics.increment_counter(
                "app_pubsub_publish_success_count", topic=topic)

    # -------------------------------------------------------- subscribe
    async def subscribe(self, topic: str, group: str = "default") -> Message:
        groups = self._topics.setdefault(topic, {})
        gq = groups.get(group)
        if gq is None:
            gq = groups[group] = _GroupQueue()
            # new group starts from the earliest retained message
            for item in self._backlog[topic]:
                gq.queue.put_nowait(item)
        value, key, metadata = await gq.queue.get()
        msg_id = gq.next_id
        gq.next_id += 1
        gq.pending[msg_id] = (value, key, metadata)

        def committer() -> None:
            gq.pending.pop(msg_id, None)

        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_pubsub_subscribe_total_count", topic=topic)
        return Message(topic=topic, value=value, key=key, metadata=metadata,
                       committer=committer)

    def redeliver_uncommitted(self, topic: str, group: str = "default") -> int:
        """Requeue everything delivered-but-uncommitted (crash recovery)."""
        gq = self._topics.get(topic, {}).get(group)
        if gq is None:
            return 0
        n = 0
        for value, key, metadata in gq.pending.values():
            gq.queue.put_nowait((value, key, metadata))
            n += 1
        gq.pending.clear()
        return n


def partition_for(key: str, num_partitions: int) -> int:
    """Stable key -> partition hash (ICI-topology-aware work sharding:
    partitions map 1:1 onto mesh devices/hosts)."""
    if num_partitions <= 1:
        return 0
    digest = hashlib.sha256(key.encode()).digest()
    return int.from_bytes(digest[:8], "big") % num_partitions
