"""NATS backend: a core-protocol wire client plus an in-process mini
server for hermetic tests.

The reference ships a NATS JetStream module (datasource/pubsub/nats,
3,446 LoC) behind the common pub/sub interface
(datasource/pubsub/interface.go:11-31). This client speaks the NATS
core text protocol (INFO/CONNECT/PUB/SUB/MSG/PING/PONG) over asyncio
TCP — no driver dependency — and maps the framework's consumer groups
onto NATS queue groups. Core NATS is at-most-once: ``Message.commit``
is a no-op acknowledgment (JetStream-style redelivery is the in-memory
broker's job in tests).

:class:`MiniNATSServer` is the broker analog of miniredis (SURVEY §4):
a protocol-faithful in-process server (subjects, ``*``/``>`` wildcards,
queue-group balancing) so client tests and examples run with zero
external infrastructure.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import time
from typing import Any

from .message import Message


class NATSError(Exception):
    pass


#: sentinel pushed into delivery queues when the connection dies so
#: blocked consumers wake and raise instead of hanging forever
_CLOSED = object()


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS subject matching: tokens split on '.', '*' matches one
    token, '>' matches the rest."""
    p_tokens = pattern.split(".")
    s_tokens = subject.split(".")
    for i, p in enumerate(p_tokens):
        if p == ">":
            return True
        if i >= len(s_tokens):
            return False
        if p != "*" and p != s_tokens[i]:
            return False
    return len(p_tokens) == len(s_tokens)


class NATSClient:
    """Core-protocol client; the framework's pub/sub surface
    (publish / subscribe / create_topic / health)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 4222,
                 name: str = "gofr-tpu") -> None:
        self.host = host
        self.port = port
        self.name = name
        self.logger: Any = None
        self.metrics: Any = None
        self.tracer: Any = None
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._read_task: asyncio.Task | None = None
        self._sids = itertools.count(1)
        # sid -> delivery queue; (topic, group) -> sid
        self._queues: dict[int, asyncio.Queue] = {}
        self._subs: dict[tuple[str, str], int] = {}
        self._server_info: dict = {}
        self._connected = False

    def use_logger(self, logger: Any) -> None:
        self.logger = logger

    def use_metrics(self, metrics: Any) -> None:
        self.metrics = metrics

    def use_tracer(self, tracer: Any) -> None:
        self.tracer = tracer

    # ------------------------------------------------------- connection
    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        line = await self._reader.readline()
        if not line.startswith(b"INFO "):
            raise NATSError(f"expected INFO, got {line[:40]!r}")
        self._server_info = json.loads(line[5:])
        options = {"verbose": False, "pedantic": False, "name": self.name,
                   "lang": "python", "version": "1", "protocol": 1,
                   "headers": True}  # NATS 2.2+: permits HPUB/HMSG
        self._writer.write(f"CONNECT {json.dumps(options)}\r\nPING\r\n"
                           .encode())
        await self._writer.drain()
        self._connected = True
        self._read_task = asyncio.ensure_future(self._read_loop())
        # PONG arrives via the read loop; connection is usable now
        if self.logger is not None:
            self.logger.info(f"NATS connected {self.host}:{self.port}")

    async def _read_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    break
                if line.startswith(b"MSG "):
                    parts = line[4:].strip().split(b" ")
                    # MSG <subject> <sid> [reply-to] <#bytes>
                    subject = parts[0].decode()
                    sid = int(parts[1])
                    reply = parts[2].decode() if len(parts) == 4 else ""
                    nbytes = int(parts[-1])
                    payload = await self._reader.readexactly(nbytes)
                    await self._reader.readexactly(2)  # trailing \r\n
                    queue = self._queues.get(sid)
                    if queue is not None:
                        await queue.put((subject, reply, payload))
                elif line.startswith(b"HMSG "):
                    # HMSG <subject> <sid> [reply-to] <#hdr> <#total> —
                    # headered delivery (we advertise headers:true, so a
                    # real 2.2+ server may send these, e.g. 503 "no
                    # responders" status replies or KV tombstones)
                    parts = line[5:].strip().split(b" ")
                    subject = parts[0].decode()
                    sid = int(parts[1])
                    reply = parts[2].decode() if len(parts) == 5 else ""
                    hdr_len, total = int(parts[-2]), int(parts[-1])
                    blob = await self._reader.readexactly(total)
                    await self._reader.readexactly(2)
                    queue = self._queues.get(sid)
                    if queue is not None:
                        # headers are transport detail at this layer;
                        # deliver the payload (empty for status frames)
                        await queue.put((subject, reply, blob[hdr_len:]))
                elif line.startswith(b"PING"):
                    if self._writer is not None:
                        self._writer.write(b"PONG\r\n")
                        await self._writer.drain()
                elif line.startswith(b"-ERR"):
                    if self.logger is not None:
                        self.logger.error(f"NATS {line.strip().decode()}")
                # PONG / +OK / INFO updates: nothing to do
        except (asyncio.CancelledError, ConnectionError,
                asyncio.IncompleteReadError):
            pass
        finally:
            self._connected = False
            for queue in self._queues.values():
                queue.put_nowait(_CLOSED)  # wake blocked consumers

    def _require_writer(self) -> asyncio.StreamWriter:
        if self._writer is None or not self._connected:
            raise NATSError("not connected")
        return self._writer

    async def _reconnect(self) -> None:
        """Drop dead state and redial; subscriptions re-issue on demand
        (subscribe() finds _subs empty and SUBs again)."""
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._subs.clear()
        self._queues.clear()
        await self.connect()

    async def _ensure_connected(self) -> None:
        if not self._connected:
            await self._reconnect()

    # ---------------------------------------------------------- publish
    async def publish(self, topic: str, value: bytes | str | dict,
                      key: str = "", metadata: dict | None = None) -> None:
        if isinstance(value, dict):
            value = json.dumps(value).encode()
        elif isinstance(value, str):
            value = value.encode()
        await self._ensure_connected()
        writer = self._require_writer()
        start = time.perf_counter()
        writer.write(f"PUB {topic} {len(value)}\r\n".encode()
                     + value + b"\r\n")
        await writer.drain()
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_total_count",
                                           topic=topic)
            self.metrics.increment_counter("app_pubsub_publish_success_count",
                                           topic=topic)
            self.metrics.record_histogram("app_pubsub_publish_latency",
                                          time.perf_counter() - start)

    # -------------------------------------------------------- subscribe
    async def _ensure_sub(self, topic: str, group: str) -> int:
        sid = self._subs.get((topic, group))
        if sid is None:
            sid = next(self._sids)
            self._subs[(topic, group)] = sid
            self._queues[sid] = asyncio.Queue()
            writer = self._require_writer()
            queue_part = f" {group}" if group else ""
            writer.write(f"SUB {topic}{queue_part} {sid}\r\n".encode())
            await writer.drain()
        return sid

    async def subscribe(self, topic: str, group: str = "default") -> Message:
        await self._ensure_connected()
        sid = await self._ensure_sub(topic, group)
        item = await self._queues[sid].get()
        if item is _CLOSED:
            # connection died while blocked; the subscriber runtime's
            # backoff loop retries subscribe(), which reconnects
            raise NATSError("connection lost")
        subject, _reply, payload = item
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_subscribe_total_count",
                                           topic=topic)
        return Message(topic=subject, value=payload,
                       committer=lambda: None)  # core NATS: at-most-once

    async def unsubscribe(self, topic: str, group: str = "default") -> None:
        sid = self._subs.pop((topic, group), None)
        if sid is not None:
            self._queues.pop(sid, None)
            writer = self._require_writer()
            writer.write(f"UNSUB {sid}\r\n".encode())
            await writer.drain()

    # ------------------------------------------------------------ admin
    def create_topic(self, name: str) -> None:
        pass  # NATS subjects are implicit

    def delete_topic(self, name: str) -> None:
        pass

    def health_check(self) -> dict:
        return {"status": "UP" if self._connected else "DOWN",
                "backend": "nats",
                "details": {"addr": f"{self.host}:{self.port}",
                            "server": self._server_info.get("server_id", "")}}

    async def close(self) -> None:
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        self._connected = False


class MiniNATSServer:
    """In-process NATS core server for tests/examples: subjects with
    wildcards, queue groups (round-robin), PING/PONG."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None
        # conn id -> writer; subscriptions: (conn_id, sid, pattern, group)
        self._conns: dict[int, asyncio.StreamWriter] = {}
        self._subs: list[tuple[int, int, str, str]] = []
        self._conn_ids = itertools.count(1)
        self._rr = itertools.count()

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        conn_id = next(self._conn_ids)
        self._conns[conn_id] = writer
        info = {"server_id": "mini", "version": "0.0-mini", "proto": 1,
                "max_payload": 1 << 20}
        writer.write(f"INFO {json.dumps(info)}\r\n".encode())
        await writer.drain()
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                verb = line.split(b" ", 1)[0].strip().upper()
                if verb == b"CONNECT":
                    pass
                elif verb == b"PING":
                    writer.write(b"PONG\r\n")
                    await writer.drain()
                elif verb == b"SUB":
                    parts = line.decode().strip().split()
                    # SUB <subject> [queue] <sid>
                    if len(parts) == 3:
                        _, subject, sid = parts
                        group = ""
                    else:
                        _, subject, group, sid = parts
                    self._subs.append((conn_id, int(sid), subject, group))
                elif verb == b"UNSUB":
                    sid = int(line.decode().strip().split()[1])
                    self._subs = [s for s in self._subs
                                  if not (s[0] == conn_id and s[1] == sid)]
                elif verb == b"PUB":
                    parts = line.decode().strip().split()
                    # PUB <subject> [reply-to] <#bytes>
                    subject, nbytes = parts[1], int(parts[-1])
                    reply = parts[2] if len(parts) == 4 else ""
                    payload = await reader.readexactly(nbytes)
                    await reader.readexactly(2)
                    await self._publish(subject, reply, payload)
                elif verb == b"HPUB":
                    # HPUB <subject> [reply-to] <#hdr-bytes> <#total-bytes>
                    parts = line.decode().strip().split()
                    subject = parts[1]
                    reply = parts[2] if len(parts) == 5 else ""
                    hdr_len, total = int(parts[-2]), int(parts[-1])
                    blob = await reader.readexactly(total)
                    await reader.readexactly(2)
                    await self._publish(subject, reply, blob[hdr_len:],
                                        hdrs=blob[:hdr_len])
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            self._conns.pop(conn_id, None)
            self._subs = [s for s in self._subs if s[0] != conn_id]

    async def _publish(self, subject: str, reply: str, payload: bytes,
                       hdrs: bytes = b"") -> None:
        """One inbound PUB/HPUB; the JetStream subclass intercepts API
        subjects and stream captures here."""
        await self._route(subject, payload, reply=reply)

    async def _route(self, subject: str, payload: bytes,
                     reply: str = "") -> None:
        matched = [s for s in self._subs if subject_matches(s[2], subject)]
        # queue groups get one member each; plain subs all get a copy
        by_group: dict[str, list] = {}
        targets = []
        for sub in matched:
            if sub[3]:
                by_group.setdefault(sub[3], []).append(sub)
            else:
                targets.append(sub)
        for members in by_group.values():
            targets.append(members[next(self._rr) % len(members)])
        for conn_id, sid, _, _ in targets:
            writer = self._conns.get(conn_id)
            if writer is None:
                continue
            reply_part = f" {reply}" if reply else ""
            writer.write(
                f"MSG {subject} {sid}{reply_part} {len(payload)}\r\n"
                .encode() + payload + b"\r\n")
            try:
                await writer.drain()
            except ConnectionError:
                pass

    async def close(self) -> None:
        for writer in list(self._conns.values()):
            try:
                writer.close()
            except Exception:
                pass
        if self._server is not None:
            self._server.close()
            # py3.12 wait_closed() blocks forever on servers that never
            # ran serve_forever (gh-109564); bound it
            try:
                await asyncio.wait_for(self._server.wait_closed(), 0.5)
            except asyncio.TimeoutError:
                pass
