"""MQTT 3.1.1 backend: a wire-protocol client plus an in-process mini
broker for hermetic tests.

The reference ships an eclipse/paho-backed MQTT module
(datasource/pubsub/mqtt, 1,273 LoC) with QoS and retained-message
support behind the common pub/sub interface. This client implements
the MQTT 3.1.1 packet layer directly over asyncio TCP: CONNECT/CONNACK,
PUBLISH with QoS 0/1 (PUBACK), SUBSCRIBE/SUBACK, PINGREQ/PINGRESP,
DISCONNECT. At-least-once maps exactly onto the framework's
commit-on-success contract (reference subscriber.go:75-78): for
inbound QoS-1 messages ``Message.commit`` sends the PUBACK, so an
uncommitted (failed) handler leaves the message unacknowledged for
broker redelivery.

:class:`MiniMQTTBroker` is the in-process broker analog of miniredis:
topic routing with ``+``/``#`` wildcards, retained messages, QoS 0/1.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import time
from typing import Any

from .message import Message

# packet types (MQTT 3.1.1 §2.2.1)
CONNECT, CONNACK = 1, 2
PUBLISH, PUBACK = 3, 4
SUBSCRIBE, SUBACK = 8, 9
UNSUBSCRIBE, UNSUBACK = 10, 11
PINGREQ, PINGRESP = 12, 13
DISCONNECT = 14


class MQTTError(Exception):
    pass


#: sentinel pushed into delivery queues when the connection dies so
#: blocked consumers wake and raise instead of hanging forever
_CLOSED = object()


def encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        byte = n % 128
        n //= 128
        out.append(byte | (0x80 if n else 0))
        if not n:
            return bytes(out)


async def read_varint(reader: asyncio.StreamReader) -> int:
    value, shift = 0, 0
    for _ in range(4):
        byte = (await reader.readexactly(1))[0]
        value |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return value
        shift += 7
    raise MQTTError("malformed remaining-length varint")


def _utf8(s: str) -> bytes:
    data = s.encode()
    return len(data).to_bytes(2, "big") + data


def _packet(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + encode_varint(len(body)) + body


async def read_packet(reader: asyncio.StreamReader) -> tuple[int, int, bytes]:
    head = (await reader.readexactly(1))[0]
    length = await read_varint(reader)
    body = await reader.readexactly(length) if length else b""
    return head >> 4, head & 0x0F, body


def topic_matches(pattern: str, topic: str) -> bool:
    """MQTT filter matching: '/' levels, '+' one level, '#' the rest."""
    p_levels = pattern.split("/")
    t_levels = topic.split("/")
    for i, p in enumerate(p_levels):
        if p == "#":
            return True
        if i >= len(t_levels):
            return False
        if p != "+" and p != t_levels[i]:
            return False
    return len(p_levels) == len(t_levels)


class MQTTClient:
    """3.1.1 client exposing the framework pub/sub surface."""

    def __init__(self, host: str = "127.0.0.1", port: int = 1883,
                 client_id: str = "gofr-tpu", qos: int = 1,
                 retain: bool = False) -> None:
        self.host = host
        self.port = port
        self.client_id = client_id
        self.qos = qos
        self.retain = retain
        self.logger: Any = None
        self.metrics: Any = None
        self.tracer: Any = None
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._read_task: asyncio.Task | None = None
        self._packet_ids = itertools.count(1)
        self._pending_acks: dict[int, asyncio.Future] = {}
        self._suback: dict[int, asyncio.Future] = {}
        # topic filter -> queue of (topic, payload, packet_id|None)
        self._queues: dict[str, asyncio.Queue] = {}
        self._connected = False

    def use_logger(self, logger: Any) -> None:
        self.logger = logger

    def use_metrics(self, metrics: Any) -> None:
        self.metrics = metrics

    def use_tracer(self, tracer: Any) -> None:
        self.tracer = tracer

    # ------------------------------------------------------- connection
    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        body = (_utf8("MQTT") + bytes([4])      # protocol level 4 = 3.1.1
                + bytes([0x02])                  # clean session
                + (60).to_bytes(2, "big")        # keepalive
                + _utf8(self.client_id))
        self._writer.write(_packet(CONNECT, 0, body))
        await self._writer.drain()
        ptype, _, ack = await read_packet(self._reader)
        if ptype != CONNACK or ack[1] != 0:
            raise MQTTError(f"connect refused: type={ptype} code={ack[1:]}")
        self._connected = True
        self._read_task = asyncio.ensure_future(self._read_loop())
        if self.logger is not None:
            self.logger.info(f"MQTT connected {self.host}:{self.port}")

    async def _read_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                ptype, flags, body = await read_packet(self._reader)
                if ptype == PUBLISH:
                    qos = (flags >> 1) & 0x03
                    tlen = int.from_bytes(body[:2], "big")
                    topic = body[2:2 + tlen].decode()
                    rest = body[2 + tlen:]
                    packet_id = None
                    if qos > 0:
                        packet_id = int.from_bytes(rest[:2], "big")
                        rest = rest[2:]
                    for pattern, queue in self._queues.items():
                        if topic_matches(pattern, topic):
                            await queue.put((topic, rest, packet_id))
                            # one delivery per inbound packet even with
                            # overlapping filters — a QoS1 id must be
                            # PUBACKed exactly once
                            break
                elif ptype == PUBACK:
                    packet_id = int.from_bytes(body[:2], "big")
                    fut = self._pending_acks.pop(packet_id, None)
                    if fut is not None and not fut.done():
                        fut.set_result(True)
                elif ptype in (SUBACK, UNSUBACK):
                    packet_id = int.from_bytes(body[:2], "big")
                    fut = self._suback.pop(packet_id, None)
                    if fut is not None and not fut.done():
                        fut.set_result(True)
                elif ptype == PINGREQ and self._writer is not None:
                    self._writer.write(_packet(PINGRESP, 0, b""))
                    await self._writer.drain()
        except (asyncio.CancelledError, ConnectionError,
                asyncio.IncompleteReadError):
            pass
        finally:
            self._connected = False
            for queue in self._queues.values():
                queue.put_nowait(_CLOSED)  # wake blocked consumers
            dead = MQTTError("connection lost")
            for fut in list(self._pending_acks.values()) \
                    + list(self._suback.values()):
                if not fut.done():
                    fut.set_exception(dead)
            self._pending_acks.clear()
            self._suback.clear()

    def _require_writer(self) -> asyncio.StreamWriter:
        if self._writer is None or not self._connected:
            raise MQTTError("not connected")
        return self._writer

    async def _reconnect(self) -> None:
        """Drop dead state and redial; _queues is cleared so the next
        subscribe() re-sends SUBSCRIBE for its filter."""
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._queues.clear()
        await self.connect()

    async def _ensure_connected(self) -> None:
        if not self._connected:
            await self._reconnect()

    # ---------------------------------------------------------- publish
    async def publish(self, topic: str, value: bytes | str | dict,
                      key: str = "", metadata: dict | None = None) -> None:
        if isinstance(value, dict):
            value = json.dumps(value).encode()
        elif isinstance(value, str):
            value = value.encode()
        await self._ensure_connected()
        writer = self._require_writer()
        start = time.perf_counter()
        flags = (self.qos << 1) | (1 if self.retain else 0)
        body = _utf8(topic)
        ack: asyncio.Future | None = None
        if self.qos > 0:
            packet_id = next(self._packet_ids) % 65535 + 1
            body += packet_id.to_bytes(2, "big")
            ack = asyncio.get_running_loop().create_future()
            self._pending_acks[packet_id] = ack
        writer.write(_packet(PUBLISH, flags, body + value))
        await writer.drain()
        if ack is not None:
            try:
                await asyncio.wait_for(ack, timeout=10)
            finally:
                self._pending_acks.pop(packet_id, None)  # no leak on timeout
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_total_count",
                                           topic=topic)
            self.metrics.increment_counter("app_pubsub_publish_success_count",
                                           topic=topic)
            self.metrics.record_histogram("app_pubsub_publish_latency",
                                          time.perf_counter() - start)

    # -------------------------------------------------------- subscribe
    async def _ensure_sub(self, topic: str) -> asyncio.Queue:
        queue = self._queues.get(topic)
        if queue is None:
            writer = self._require_writer()
            packet_id = next(self._packet_ids) % 65535 + 1
            fut = asyncio.get_running_loop().create_future()
            self._suback[packet_id] = fut
            body = packet_id.to_bytes(2, "big") + _utf8(topic) \
                + bytes([self.qos])
            # register before SUBACK so retained messages replayed right
            # after it aren't dropped by the read loop
            queue = self._queues[topic] = asyncio.Queue()
            writer.write(_packet(SUBSCRIBE, 0x02, body))
            await writer.drain()
            try:
                await asyncio.wait_for(fut, timeout=10)
            except asyncio.TimeoutError:
                # no SUBACK: deregister so a retry re-sends SUBSCRIBE
                # instead of waiting forever on a dead queue
                self._queues.pop(topic, None)
                self._suback.pop(packet_id, None)
                raise
        return queue

    async def subscribe(self, topic: str, group: str = "default") -> Message:
        """MQTT has no queue groups; ``group`` is accepted for interface
        compatibility (shared subscriptions are MQTT 5)."""
        await self._ensure_connected()
        queue = await self._ensure_sub(topic)
        item = await queue.get()
        if item is _CLOSED:
            # connection died while blocked; the subscriber runtime's
            # backoff loop retries subscribe(), which reconnects
            raise MQTTError("connection lost")
        actual_topic, payload, packet_id = item
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_subscribe_total_count",
                                           topic=topic)

        def committer() -> None:
            # QoS1 inbound: PUBACK on commit = at-least-once on success
            if packet_id is not None and self._writer is not None:
                self._writer.write(
                    _packet(PUBACK, 0, packet_id.to_bytes(2, "big")))
        return Message(topic=actual_topic, value=payload,
                       committer=committer)

    # ------------------------------------------------------------ admin
    def create_topic(self, name: str) -> None:
        pass  # MQTT topics are implicit

    def delete_topic(self, name: str) -> None:
        pass

    def health_check(self) -> dict:
        return {"status": "UP" if self._connected else "DOWN",
                "backend": "mqtt",
                "details": {"addr": f"{self.host}:{self.port}",
                            "client_id": self.client_id, "qos": self.qos}}

    async def close(self) -> None:
        if self._writer is not None and self._connected:
            try:
                self._writer.write(_packet(DISCONNECT, 0, b""))
                await self._writer.drain()
            except (ConnectionError, OSError):
                pass
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        self._connected = False


class _Session:
    def __init__(self, writer: asyncio.StreamWriter) -> None:
        self.writer = writer
        self.subs: list[tuple[str, int]] = []  # (filter, max qos)


class MiniMQTTBroker:
    """In-process 3.1.1 broker: wildcard routing, retained messages,
    QoS 0/1 (inbound QoS1 is PUBACKed; outbound redelivery on missing
    PUBACK is left to tests that need it)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None
        self._sessions: dict[int, _Session] = {}
        self._ids = itertools.count(1)
        self._retained: dict[str, bytes] = {}
        self._out_ids = itertools.count(1)

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        session_id = next(self._ids)
        session = _Session(writer)
        try:
            ptype, _, _ = await read_packet(reader)
            if ptype != CONNECT:
                return
            writer.write(_packet(CONNACK, 0, bytes([0, 0])))
            await writer.drain()
            self._sessions[session_id] = session
            while True:
                ptype, flags, body = await read_packet(reader)
                if ptype == PUBLISH:
                    await self._on_publish(writer, flags, body)
                elif ptype == SUBSCRIBE:
                    await self._on_subscribe(session, body)
                elif ptype == UNSUBSCRIBE:
                    packet_id = body[:2]
                    # body: id + utf8 filters
                    offset, filters = 2, []
                    while offset < len(body):
                        ln = int.from_bytes(body[offset:offset + 2], "big")
                        filters.append(body[offset + 2:offset + 2 + ln]
                                       .decode())
                        offset += 2 + ln
                    session.subs = [s for s in session.subs
                                    if s[0] not in filters]
                    writer.write(_packet(UNSUBACK, 0, packet_id))
                    await writer.drain()
                elif ptype == PINGREQ:
                    writer.write(_packet(PINGRESP, 0, b""))
                    await writer.drain()
                elif ptype == DISCONNECT:
                    break
                # PUBACK from subscribers: accepted, no redelivery queue
        except (ConnectionError, asyncio.IncompleteReadError, MQTTError):
            pass
        finally:
            self._sessions.pop(session_id, None)
            try:
                writer.close()
            except Exception:
                pass

    async def _on_publish(self, writer: asyncio.StreamWriter, flags: int,
                          body: bytes) -> None:
        qos = (flags >> 1) & 0x03
        retain = flags & 0x01
        tlen = int.from_bytes(body[:2], "big")
        topic = body[2:2 + tlen].decode()
        rest = body[2 + tlen:]
        if qos > 0:
            packet_id, rest = rest[:2], rest[2:]
            writer.write(_packet(PUBACK, 0, packet_id))
            await writer.drain()
        if retain:
            if rest:
                self._retained[topic] = rest
            else:
                self._retained.pop(topic, None)  # empty retained = clear
        await self._deliver(topic, rest)

    async def _deliver(self, topic: str, payload: bytes,
                       only: _Session | None = None,
                       only_filter: str | None = None) -> None:
        for session in ([only] if only else list(self._sessions.values())):
            for pattern, max_qos in session.subs:
                if only_filter is not None and pattern != only_filter:
                    continue
                if not topic_matches(pattern, topic):
                    continue
                flags = (min(max_qos, 1) << 1)
                body = _utf8(topic)
                if min(max_qos, 1) > 0:
                    body += (next(self._out_ids) % 65535 + 1).to_bytes(2, "big")
                session.writer.write(_packet(PUBLISH, flags, body + payload))
                try:
                    await session.writer.drain()
                except ConnectionError:
                    pass
                break  # one delivery per session even with overlapping subs

    async def _on_subscribe(self, session: _Session, body: bytes) -> None:
        packet_id = body[:2]
        offset, codes = 2, bytearray()
        new_filters = []
        while offset < len(body):
            ln = int.from_bytes(body[offset:offset + 2], "big")
            pattern = body[offset + 2:offset + 2 + ln].decode()
            qos = body[offset + 2 + ln]
            session.subs.append((pattern, qos))
            new_filters.append(pattern)
            codes.append(min(qos, 1))
            offset += 2 + ln + 1
        session.writer.write(_packet(SUBACK, 0, packet_id + bytes(codes)))
        await session.writer.drain()
        # retained messages replay to the new subscriber only
        for pattern in new_filters:
            for topic, payload in list(self._retained.items()):
                if topic_matches(pattern, topic):
                    await self._deliver(topic, payload, only=session,
                                        only_filter=pattern)

    async def close(self) -> None:
        for session in list(self._sessions.values()):
            try:
                session.writer.close()
            except Exception:
                pass
        self._sessions.clear()
        if self._server is not None:
            self._server.close()
            # py3.12 wait_closed() blocks forever on servers that never
            # ran serve_forever (gh-109564); bound it
            try:
                await asyncio.wait_for(self._server.wait_closed(), 0.5)
            except asyncio.TimeoutError:
                pass
