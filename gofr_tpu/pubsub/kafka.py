"""Kafka backend: a binary wire-protocol client plus an in-process
mini broker for hermetic tests.

The reference's primary broker module
(/root/reference/pkg/gofr/datasource/pubsub/kafka/kafka.go:35-63:
brokers, consumer group, offset management, batch writer) behind the
common pub/sub interface (interface.go:11-31). This implementation
speaks the Kafka binary protocol directly over asyncio TCP — no driver
dependency — using the v0 wire versions of each API, which every Kafka
broker still accepts:

==== ===================== =======================================
key  API                   use here
==== ===================== =======================================
0    Produce               publish (acks=1, CRC32 message set v0)
1    Fetch                 long-poll consume per partition
2    ListOffsets           earliest/latest start position
3    Metadata              topic/partition discovery
8/9  OffsetCommit/Fetch    consumer-group offsets (commit-on-success)
10   FindCoordinator       group coordinator discovery
11   JoinGroup             membership + client-side assignment
12   Heartbeat             rebalance detection
14   SyncGroup             assignment distribution
19/20 Create/DeleteTopics  admin surface
==== ===================== =======================================

Consumer groups follow the real Kafka model: partitions are the unit
of parallelism, the JoinGroup leader computes the assignment
client-side and distributes it via SyncGroup (the assignment payload
is opaque to the broker, as in Kafka; this client uses JSON). Commit
is per-message offset+1, giving the reference's at-least-once
commit-on-success semantics.

:class:`MiniKafkaBroker` is the broker analog of miniredis (SURVEY
§4): partitioned logs, generation-checked group membership with
rebalance-in-progress errors, long-poll fetch — so client tests and
examples run with zero external infrastructure.
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import json
import struct
import time
import zlib
from typing import Any

from .message import Message


class KafkaError(Exception):
    def __init__(self, code: int, what: str = "") -> None:
        super().__init__(f"kafka error {code}{': ' + what if what else ''}")
        self.code = code


# error codes (subset)
E_NONE = 0
E_UNKNOWN_TOPIC = 3
E_ILLEGAL_GENERATION = 22
E_UNKNOWN_MEMBER = 25
E_REBALANCE_IN_PROGRESS = 27

# api keys
PRODUCE, FETCH, LIST_OFFSETS, METADATA = 0, 1, 2, 3
OFFSET_COMMIT, OFFSET_FETCH, FIND_COORDINATOR = 8, 9, 10
JOIN_GROUP, HEARTBEAT, SYNC_GROUP = 11, 12, 14
CREATE_TOPICS, DELETE_TOPICS = 19, 20


# ------------------------------------------------------------ wire enc/dec

def _i8(v): return struct.pack(">b", v)
def _i16(v): return struct.pack(">h", v)
def _i32(v): return struct.pack(">i", v)
def _i64(v): return struct.pack(">q", v)


def _str(s: str | None) -> bytes:
    if s is None:
        return _i16(-1)
    b = s.encode()
    return _i16(len(b)) + b


def _bytes(b: bytes | None) -> bytes:
    if b is None:
        return _i32(-1)
    return _i32(len(b)) + b


def _array(items: list[bytes]) -> bytes:
    return _i32(len(items)) + b"".join(items)


class _Reader:
    """Cursor over a response/request body."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        out = self.data[self.pos:self.pos + n]
        if len(out) < n:
            raise KafkaError(-1, "short buffer")
        self.pos += n
        return out

    def i8(self): return struct.unpack(">b", self._take(1))[0]
    def i16(self): return struct.unpack(">h", self._take(2))[0]
    def i32(self): return struct.unpack(">i", self._take(4))[0]
    def i64(self): return struct.unpack(">q", self._take(8))[0]

    def string(self) -> str | None:
        n = self.i16()
        return None if n < 0 else self._take(n).decode()

    def bytes_(self) -> bytes | None:
        n = self.i32()
        return None if n < 0 else self._take(n)

    def remaining(self) -> int:
        return len(self.data) - self.pos


def _encode_message_set(entries: list[tuple[bytes | None, bytes]],
                        base_offset: int = 0) -> bytes:
    """Message set v0: [offset int64, size int32, crc, magic, attrs,
    key, value] per message; CRC covers magic..value."""
    out = []
    for i, (key, value) in enumerate(entries):
        body = _i8(0) + _i8(0) + _bytes(key) + _bytes(value)
        msg = struct.pack(">I", zlib.crc32(body)) + body
        out.append(_i64(base_offset + i) + _i32(len(msg)) + msg)
    return b"".join(out)


def _decode_message_set(data: bytes) -> list[tuple[int, bytes | None, bytes]]:
    """-> [(offset, key, value)]; trailing partial messages (legal in
    Kafka fetch responses) are ignored."""
    out = []
    r = _Reader(data)
    while r.remaining() >= 12:
        offset = r.i64()
        size = r.i32()
        if r.remaining() < size:
            break
        raw = r._take(size)
        body = _Reader(raw)
        crc = struct.unpack(">I", body._take(4))[0]
        if crc != zlib.crc32(raw[4:]):
            raise KafkaError(2, "corrupt message (crc mismatch)")
        body.i8()   # magic
        body.i8()   # attributes
        key = body.bytes_()
        value = body.bytes_()
        out.append((offset, key, value if value is not None else b""))
    return out


# ---------------------------------------------------------------- client

class _GroupConsumer:
    """Per (topic, group) membership + fetch state."""

    def __init__(self) -> None:
        self.member_id = ""
        self.generation = -1
        self.partitions: list[int] = []
        self.offsets: dict[int, int] = {}
        self.buffer: collections.deque = collections.deque()
        self.joined = False


class KafkaClient:
    """Wire-protocol Kafka client exposing the framework pub/sub
    surface (publish / subscribe / create_topic / health), with
    consumer-group offset commit per message (at-least-once)."""

    def __init__(self, brokers: str | list[str] = "127.0.0.1:9092",
                 group_id: str = "gofr", client_id: str = "gofr-tpu",
                 auto_offset: str = "earliest",
                 fetch_max_wait_ms: int = 250,
                 session_timeout_ms: int = 30000) -> None:
        if isinstance(brokers, str):
            brokers = [b.strip() for b in brokers.split(",") if b.strip()]
        self.brokers = brokers or ["127.0.0.1:9092"]
        self.group_id = group_id
        self.client_id = client_id
        self.auto_offset = auto_offset
        self.fetch_max_wait_ms = fetch_max_wait_ms
        self.session_timeout_ms = session_timeout_ms
        self.logger: Any = None
        self.metrics: Any = None
        self.tracer: Any = None
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._corr = itertools.count(1)
        self._io_lock = asyncio.Lock()
        self._connect_lock = asyncio.Lock()
        self._connected = False
        self._consumers: dict[tuple[str, str], _GroupConsumer] = {}
        self._topic_parts: dict[str, int] = {}   # publish routing cache
        self._rr = itertools.count()

    def use_logger(self, logger: Any) -> None:
        self.logger = logger

    def use_metrics(self, metrics: Any) -> None:
        self.metrics = metrics

    def use_tracer(self, tracer: Any) -> None:
        self.tracer = tracer

    # ------------------------------------------------------- connection
    async def connect(self) -> None:
        last: Exception | None = None
        for broker in self.brokers:
            host, _, port = broker.partition(":")
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    host, int(port or 9092))
                self._connected = True  # gofrlint: allow(lock-discipline) -- asyncio single-thread: flag flip is atomic between awaits; _connect_lock guards the redial sequence, not the bool
                if self.logger is not None:
                    self.logger.info(f"Kafka connected {broker}")
                return
            except OSError as exc:
                last = exc
        raise KafkaError(-1, f"no broker reachable: {last}")

    async def _ensure_connected(self) -> None:
        if self._connected:
            return
        async with self._connect_lock:
            if self._connected:      # another task already redialed
                return
            if self._writer is not None:
                try:
                    self._writer.close()
                except Exception:
                    pass
            # memberships died with the socket: reset IN PLACE so any
            # in-flight subscribe() loop holding a state object rejoins
            # the same _GroupConsumer instead of orphaning a ghost
            # member that nobody heartbeats
            for state in self._consumers.values():
                state.joined = False
                state.member_id = ""
                state.generation = -1
                state.partitions = []
            await self.connect()

    async def _call(self, api_key: int, body: bytes,
                    api_version: int = 0) -> _Reader:
        """One request/response round-trip (header v0, pipelined
        serially under a lock)."""
        await self._ensure_connected()
        corr = next(self._corr)
        header = (_i16(api_key) + _i16(api_version) + _i32(corr)
                  + _str(self.client_id))
        frame = header + body
        async with self._io_lock:
            assert self._writer is not None and self._reader is not None
            try:
                self._writer.write(_i32(len(frame)) + frame)
                await self._writer.drain()
                size = struct.unpack(">i", await
                                     self._reader.readexactly(4))[0]
                payload = await self._reader.readexactly(size)
            except (ConnectionError, asyncio.IncompleteReadError,
                    OSError) as exc:
                self._connected = False
                raise KafkaError(-1, f"connection lost: {exc}") from exc
        r = _Reader(payload)
        got = r.i32()
        if got != corr:
            self._connected = False  # gofrlint: allow(lock-discipline) -- asyncio single-thread: poison-the-connection flag flip, atomic between awaits
            raise KafkaError(-1, f"correlation mismatch {got} != {corr}")
        return r

    # ---------------------------------------------------------- publish
    async def publish(self, topic: str, value: bytes | str | dict,
                      key: str = "", metadata: dict | None = None) -> None:
        if isinstance(value, dict):
            value = json.dumps(value).encode()
        elif isinstance(value, str):
            value = value.encode()
        if not topic:
            raise KafkaError(-1, "topic name cannot be empty")
        start = time.perf_counter()
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_total_count",
                                           topic=topic)
        # route like the reference writer's balancer (kafka.go): keyed
        # messages hash to a stable partition, unkeyed round-robin
        n_parts = self._topic_parts.get(topic)
        if n_parts is None:
            parts = await self._partitions_for(topic)
            n_parts = self._topic_parts[topic] = max(1, len(parts))
        if key:
            pid = zlib.crc32(key.encode()) % n_parts
        else:
            pid = next(self._rr) % n_parts
        mset = _encode_message_set([(key.encode() if key else None, value)])
        part = _i32(pid) + _i32(len(mset)) + mset
        body = (_i16(1) + _i32(10000)            # acks=1, timeout
                + _array([_str(topic) + _array([part])]))
        r = await self._call(PRODUCE, body)
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                r.i64()
                if err:
                    raise KafkaError(err, f"produce {topic}")
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_success_count",
                                           topic=topic)
            self.metrics.record_histogram("app_pubsub_publish_latency",
                                          time.perf_counter() - start)

    # ------------------------------------------------------ group plumbing
    async def _partitions_for(self, topic: str) -> list[int]:
        r = await self._call(METADATA, _array([_str(topic)]))
        for _ in range(r.i32()):        # brokers
            r.i32(), r.string(), r.i32()
        parts: list[int] = []
        for _ in range(r.i32()):        # topics
            err = r.i16()
            name = r.string()
            n_parts = r.i32()
            for _ in range(n_parts):
                r.i16()
                pid = r.i32()
                r.i32()                 # leader
                for _ in range(r.i32()):
                    r.i32()             # replicas
                for _ in range(r.i32()):
                    r.i32()             # isr
                if name == topic and not err:
                    parts.append(pid)
        return sorted(parts)

    async def _join(self, topic: str, group: str,
                    state: _GroupConsumer) -> None:
        """JoinGroup -> (leader assigns) -> SyncGroup -> OffsetFetch."""
        meta = json.dumps({"topics": [topic]}).encode()
        body = (_str(group) + _i32(self.session_timeout_ms)
                + _str(state.member_id) + _str("consumer")
                + _array([_str("range") + _bytes(meta)]))
        r = await self._call(JOIN_GROUP, body)
        err = r.i16()
        if err == E_UNKNOWN_MEMBER:
            state.member_id = ""
            raise KafkaError(err, "rejoin")
        if err:
            raise KafkaError(err, "join")
        state.generation = r.i32()
        r.string()                              # protocol
        leader = r.string()
        state.member_id = r.string() or ""
        members = [(r.string() or "", r.bytes_() or b"")
                   for _ in range(r.i32())]

        assignments: list[bytes] = []
        if state.member_id == leader:
            # client-side assignment, exactly as real Kafka: the leader
            # partitions the topic round-robin over the member list
            parts = await self._partitions_for(topic)
            per: dict[str, list[int]] = {m: [] for m, _ in members}
            ids = [m for m, _ in members]
            for i, p in enumerate(parts):
                per[ids[i % len(ids)]].append(p)
            assignments = [
                _str(m) + _bytes(json.dumps({topic: per[m]}).encode())
                for m, _ in members]
        body = (_str(group) + _i32(state.generation) + _str(state.member_id)
                + _array(assignments))
        r = await self._call(SYNC_GROUP, body)
        err = r.i16()
        if err:
            raise KafkaError(err, "sync")
        assigned = json.loads((r.bytes_() or b"{}").decode() or "{}")
        state.partitions = assigned.get(topic, [])
        await self._fetch_offsets(topic, group, state)
        state.joined = True

    async def _fetch_offsets(self, topic: str, group: str,
                             state: _GroupConsumer) -> None:
        body = _str(group) + _array(
            [_str(topic) + _array([_i32(p) for p in state.partitions])])
        r = await self._call(OFFSET_FETCH, body)
        state.offsets = {}
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                pid = r.i32()
                off = r.i64()
                r.string()
                r.i16()
                if off < 0:  # no committed offset: start per policy
                    off = await self._list_offset(
                        topic, pid,
                        -2 if self.auto_offset == "earliest" else -1)
                state.offsets[pid] = off

    async def _list_offset(self, topic: str, partition: int,
                           when: int) -> int:
        """ListOffsets v0: when=-2 earliest, -1 latest."""
        part = _i32(partition) + _i64(when) + _i32(1)
        body = _i32(-1) + _array([_str(topic) + _array([part])])
        r = await self._call(LIST_OFFSETS, body)
        offset = 0
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                offs = [r.i64() for _ in range(r.i32())]
                if not err and offs:
                    offset = offs[0]
        return offset

    async def _heartbeat(self, group: str, state: _GroupConsumer) -> None:
        body = (_str(group) + _i32(state.generation)
                + _str(state.member_id))
        r = await self._call(HEARTBEAT, body)
        err = r.i16()
        if err in (E_REBALANCE_IN_PROGRESS, E_ILLEGAL_GENERATION,
                   E_UNKNOWN_MEMBER):
            state.joined = False          # rejoin on next subscribe
            if err == E_UNKNOWN_MEMBER:
                state.member_id = ""

    async def _fetch_into(self, topic: str, state: _GroupConsumer) -> None:
        if not state.partitions:
            await asyncio.sleep(self.fetch_max_wait_ms / 1000)
            return
        parts = [_i32(p) + _i64(state.offsets.get(p, 0)) + _i32(1 << 20)
                 for p in state.partitions]
        body = (_i32(-1) + _i32(self.fetch_max_wait_ms) + _i32(1)
                + _array([_str(topic) + _array(parts)]))
        r = await self._call(FETCH, body)
        for _ in range(r.i32()):
            name = r.string()
            for _ in range(r.i32()):
                pid = r.i32()
                err = r.i16()
                r.i64()                     # high watermark
                mset = r.bytes_() or b""
                if err:
                    continue
                for offset, key, value in _decode_message_set(mset):
                    if offset < state.offsets.get(pid, 0):
                        continue            # broker resent below our cursor
                    state.offsets[pid] = offset + 1
                    state.buffer.append((name, pid, offset, key, value))

    # -------------------------------------------------------- subscribe
    async def subscribe(self, topic: str, group: str = "") -> Message:
        group = group or self.group_id
        state = self._consumers.setdefault((topic, group), _GroupConsumer())
        while True:
            await self._ensure_connected()
            if not state.joined:
                try:
                    await self._join(topic, group, state)
                except KafkaError as exc:
                    if exc.code in (E_REBALANCE_IN_PROGRESS,
                                    E_UNKNOWN_MEMBER,
                                    E_ILLEGAL_GENERATION):
                        await asyncio.sleep(0.02)
                        continue
                    raise
            if state.buffer:
                name, pid, offset, key, value = state.buffer.popleft()
                if self.metrics is not None:
                    self.metrics.increment_counter(
                        "app_pubsub_subscribe_total_count", topic=topic)

                def committer(t=name, p=pid, o=offset, g=group,
                              s=state) -> None:
                    task = asyncio.ensure_future(self._commit(t, p, o, g, s))
                    # commit is fire-and-forget at the call site (the
                    # subscriber runtime commits after handler success);
                    # surface failures through the logger
                    task.add_done_callback(self._log_commit_errors)
                return Message(topic=name, value=value,
                               key=(key or b"").decode("utf-8", "replace"),
                               committer=committer)
            await self._heartbeat(group, state)
            if not state.joined:
                continue
            await self._fetch_into(topic, state)

    def _log_commit_errors(self, task: "asyncio.Task") -> None:
        exc = task.exception() if not task.cancelled() else None
        if exc is not None and self.logger is not None:
            self.logger.error(f"kafka offset commit failed: {exc!r}")

    async def _commit(self, topic: str, partition: int, offset: int,
                      group: str, state: _GroupConsumer) -> None:
        body = (_str(group) + _array(
            [_str(topic) + _array(
                [_i32(partition) + _i64(offset + 1) + _str("")])]))
        r = await self._call(OFFSET_COMMIT, body)
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                if err:
                    raise KafkaError(err, "offset commit")

    # ------------------------------------------------------------ admin
    async def create_topic_async(self, name: str,
                                 partitions: int = 1) -> None:
        spec = (_str(name) + _i32(partitions) + _i16(1)
                + _array([]) + _array([]))
        body = _array([spec]) + _i32(10000)
        r = await self._call(CREATE_TOPICS, body)
        for _ in range(r.i32()):
            r.string()
            r.i16()  # already-exists is fine

    def create_topic(self, name: str) -> None:
        asyncio.ensure_future(self.create_topic_async(name))

    def delete_topic(self, name: str) -> None:
        async def _delete() -> None:
            body = _array([_str(name)]) + _i32(10000)
            await self._call(DELETE_TOPICS, body)
        asyncio.ensure_future(_delete())

    def health_check(self) -> dict:
        return {"status": "UP" if self._connected else "DOWN",
                "backend": "kafka",
                "details": {"brokers": self.brokers,
                            "group": self.group_id}}

    async def close(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        self._connected = False  # gofrlint: allow(lock-discipline) -- asyncio single-thread: close() runs on the loop; no concurrent writer to race


# ------------------------------------------------------------ mini broker

class _Group:
    def __init__(self) -> None:
        self.generation = 0
        self.members: dict[str, bytes] = {}
        self.leader = ""
        self.assignments: dict[str, bytes] = {}
        self.offsets: dict[tuple[str, int], int] = {}
        #: set when the generation's leader has posted assignments;
        #: follower SyncGroups block on it, as on a real coordinator
        self.sync_event = asyncio.Event()

    def rebalance(self) -> None:
        self.generation += 1
        self.assignments.clear()
        self.sync_event = asyncio.Event()


class MiniKafkaBroker:
    """In-process single-node Kafka broker for tests/examples:
    partitioned append-only logs, v0 wire protocol for the API table in
    the module docstring, generation-checked consumer groups with
    client-side assignment, long-poll fetch."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 default_partitions: int = 1) -> None:
        self.host = host
        self.port = port
        self.default_partitions = default_partitions
        self._server: asyncio.AbstractServer | None = None
        #: topic -> list of partition logs, each [(key, value)]
        self.logs: dict[str, list[list[tuple[bytes | None, bytes]]]] = {}
        self.groups: dict[str, _Group] = {}
        self._member_ids = itertools.count(1)
        self._conn_ids = itertools.count(1)
        #: conn id -> {(group_id, member_id)}: members leave when their
        #: connection dies (the fast-test analog of session-timeout
        #: expiry on a real coordinator)
        self._conn_members: dict[int, set[tuple[str, str]]] = {}
        self._data_event = asyncio.Event()

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    def _topic(self, name: str) -> list[list[tuple[bytes | None, bytes]]]:
        if name not in self.logs:
            self.logs[name] = [[] for _ in range(self.default_partitions)]
        return self.logs[name]

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        conn_id = next(self._conn_ids)
        self._conn_members[conn_id] = set()
        try:
            while True:
                raw = await reader.readexactly(4)
                size = struct.unpack(">i", raw)[0]
                frame = _Reader(await reader.readexactly(size))
                api = frame.i16()
                frame.i16()                  # api_version (v0 assumed)
                corr = frame.i32()
                frame.string()               # client id
                body = await self._dispatch(api, frame, conn_id)
                resp = _i32(corr) + body
                writer.write(_i32(len(resp)) + resp)
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass
        finally:
            self._expire_conn(conn_id)
            try:
                writer.close()
            except Exception:
                pass

    def _expire_conn(self, conn_id: int) -> None:
        """Remove the connection's group members and rebalance the
        groups they leave behind."""
        for group_id, member_id in self._conn_members.pop(conn_id, ()):
            group = self.groups.get(group_id)
            if group is None or member_id not in group.members:
                continue
            del group.members[member_id]
            if group.leader == member_id:
                group.leader = next(iter(group.members), "")
            group.rebalance()

    async def _dispatch(self, api: int, r: _Reader, conn_id: int) -> bytes:
        handler = {
            PRODUCE: self._produce, FETCH: self._fetch,
            LIST_OFFSETS: self._list_offsets, METADATA: self._metadata,
            OFFSET_COMMIT: self._offset_commit,
            OFFSET_FETCH: self._offset_fetch,
            FIND_COORDINATOR: self._find_coordinator,
            JOIN_GROUP: self._join_group, HEARTBEAT: self._heartbeat,
            SYNC_GROUP: self._sync_group,
            CREATE_TOPICS: self._create_topics,
            DELETE_TOPICS: self._delete_topics,
        }.get(api)
        if handler is None:
            raise KafkaError(-1, f"unsupported api {api}")
        out = (handler(r, conn_id) if api in (JOIN_GROUP, SYNC_GROUP)
               else handler(r))
        if asyncio.iscoroutine(out):
            out = await out
        return out

    # ------------------------------------------------- produce / fetch
    def _produce(self, r: _Reader) -> bytes:
        r.i16()                              # acks
        r.i32()                              # timeout
        topics_out = []
        for _ in range(r.i32()):
            name = r.string() or ""
            parts_out = []
            for _ in range(r.i32()):
                pid = r.i32()
                mset = r.bytes_() or b""
                log = self._topic(name)
                if pid >= len(log):
                    parts_out.append(_i32(pid) + _i16(E_UNKNOWN_TOPIC)
                                     + _i64(-1))
                    continue
                base = len(log[pid])
                try:
                    entries = _decode_message_set(mset)
                except KafkaError:  # CRC mismatch: CORRUPT_MESSAGE
                    parts_out.append(_i32(pid) + _i16(2) + _i64(-1))
                    continue
                for _, key, value in entries:
                    log[pid].append((key, value))
                parts_out.append(_i32(pid) + _i16(0) + _i64(base))
            topics_out.append(_str(name) + _array(parts_out))
        self._data_event.set()
        self._data_event = asyncio.Event()   # wake current long-polls
        return _array(topics_out)

    async def _fetch(self, r: _Reader) -> bytes:
        r.i32()                              # replica id
        max_wait = r.i32()
        r.i32()                              # min bytes
        wants = []
        for _ in range(r.i32()):
            name = r.string() or ""
            for _ in range(r.i32()):
                wants.append((name, r.i32(), r.i64(), r.i32()))

        def build() -> tuple[bytes, bool]:
            by_topic: dict[str, list[bytes]] = {}
            any_data = False
            for name, pid, offset, _max in wants:
                log = self._topic(name)
                if pid >= len(log):
                    entry = _i32(pid) + _i16(E_UNKNOWN_TOPIC) + _i64(-1) \
                        + _bytes(b"")
                else:
                    entries = log[pid][offset:offset + 512]
                    if entries:
                        any_data = True
                    mset = _encode_message_set(entries, base_offset=offset)
                    entry = (_i32(pid) + _i16(0) + _i64(len(log[pid]))
                             + _bytes(mset))
                by_topic.setdefault(name, []).append(entry)
            body = _array([_str(n) + _array(p) for n, p in by_topic.items()])
            return body, any_data

        deadline = time.monotonic() + max_wait / 1000.0
        body, any_data = build()
        while not any_data and time.monotonic() < deadline:
            event = self._data_event
            try:
                await asyncio.wait_for(
                    event.wait(), max(0.0, deadline - time.monotonic()))
            except asyncio.TimeoutError:
                break
            body, any_data = build()
        return body

    def _list_offsets(self, r: _Reader) -> bytes:
        r.i32()                              # replica id
        topics_out = []
        for _ in range(r.i32()):
            name = r.string() or ""
            parts_out = []
            for _ in range(r.i32()):
                pid = r.i32()
                when = r.i64()
                r.i32()                      # max offsets
                log = self._topic(name)
                size = len(log[pid]) if pid < len(log) else 0
                offset = 0 if when == -2 else size
                parts_out.append(_i32(pid) + _i16(0)
                                 + _array([_i64(offset)]))
            topics_out.append(_str(name) + _array(parts_out))
        return _array(topics_out)

    def _metadata(self, r: _Reader) -> bytes:
        names = [r.string() or "" for _ in range(r.i32())]
        if not names:
            names = list(self.logs)
        brokers = _array([_i32(0) + _str(self.host) + _i32(self.port)])
        topics_out = []
        for name in names:
            log = self._topic(name)
            parts = [
                _i16(0) + _i32(pid) + _i32(0)
                + _array([_i32(0)]) + _array([_i32(0)])
                for pid in range(len(log))]
            topics_out.append(_i16(0) + _str(name) + _array(parts))
        return brokers + _array(topics_out)

    # ------------------------------------------------------ group APIs
    def _find_coordinator(self, r: _Reader) -> bytes:
        r.string()
        return _i16(0) + _i32(0) + _str(self.host) + _i32(self.port)

    def _join_group(self, r: _Reader, conn_id: int) -> bytes:
        group_id = r.string() or ""
        r.i32()                              # session timeout
        member_id = r.string() or ""
        r.string()                           # protocol type
        protocols = [(r.string() or "", r.bytes_() or b"")
                     for _ in range(r.i32())]
        group = self.groups.setdefault(group_id, _Group())
        if not member_id:
            member_id = f"member-{next(self._member_ids)}"
        if member_id not in group.members:
            group.rebalance()                # membership change
        group.members[member_id] = protocols[0][1] if protocols else b""
        self._conn_members.setdefault(conn_id, set()).add(
            (group_id, member_id))
        if not group.leader or group.leader not in group.members:
            group.leader = member_id
        members = _array([
            _str(m) + _bytes(meta) for m, meta in group.members.items()])
        return (_i16(0) + _i32(group.generation)
                + _str(protocols[0][0] if protocols else "range")
                + _str(group.leader) + _str(member_id) + members)

    async def _sync_group(self, r: _Reader, conn_id: int) -> bytes:
        group_id = r.string() or ""
        generation = r.i32()
        member_id = r.string() or ""
        group = self.groups.setdefault(group_id, _Group())
        if member_id not in group.members:
            return _i16(E_UNKNOWN_MEMBER) + _bytes(b"")
        if generation != group.generation:
            return _i16(E_ILLEGAL_GENERATION) + _bytes(b"")
        n_assignments = r.i32()
        for _ in range(n_assignments):
            m = r.string() or ""
            group.assignments[m] = r.bytes_() or b""
        if member_id == group.leader and n_assignments:
            group.sync_event.set()
        elif not group.sync_event.is_set():
            # follower synced before the leader: block until the
            # leader's assignments arrive (real-coordinator behavior)
            event, gen = group.sync_event, group.generation
            try:
                await asyncio.wait_for(event.wait(), timeout=5.0)
            except asyncio.TimeoutError:
                return _i16(E_REBALANCE_IN_PROGRESS) + _bytes(b"")
            if group.generation != gen:
                return _i16(E_REBALANCE_IN_PROGRESS) + _bytes(b"")
        return _i16(0) + _bytes(group.assignments.get(member_id, b""))

    def _heartbeat(self, r: _Reader) -> bytes:
        group_id = r.string() or ""
        generation = r.i32()
        member_id = r.string() or ""
        group = self.groups.setdefault(group_id, _Group())
        if member_id not in group.members:
            return _i16(E_UNKNOWN_MEMBER)
        if generation != group.generation:
            return _i16(E_REBALANCE_IN_PROGRESS)
        return _i16(0)

    def _offset_commit(self, r: _Reader) -> bytes:
        group_id = r.string() or ""
        group = self.groups.setdefault(group_id, _Group())
        topics_out = []
        for _ in range(r.i32()):
            name = r.string() or ""
            parts_out = []
            for _ in range(r.i32()):
                pid = r.i32()
                offset = r.i64()
                r.string()
                group.offsets[(name, pid)] = offset
                parts_out.append(_i32(pid) + _i16(0))
            topics_out.append(_str(name) + _array(parts_out))
        return _array(topics_out)

    def _offset_fetch(self, r: _Reader) -> bytes:
        group_id = r.string() or ""
        group = self.groups.setdefault(group_id, _Group())
        topics_out = []
        for _ in range(r.i32()):
            name = r.string() or ""
            parts_out = []
            for _ in range(r.i32()):
                pid = r.i32()
                offset = group.offsets.get((name, pid), -1)
                parts_out.append(_i32(pid) + _i64(offset) + _str("")
                                 + _i16(0))
            topics_out.append(_str(name) + _array(parts_out))
        return _array(topics_out)

    # ------------------------------------------------------------ admin
    def _create_topics(self, r: _Reader) -> bytes:
        out = []
        for _ in range(r.i32()):
            name = r.string() or ""
            n_parts = r.i32()
            r.i16()                          # replication factor
            for _ in range(r.i32()):         # manual assignments
                r.i32()
                for _ in range(r.i32()):
                    r.i32()
            for _ in range(r.i32()):         # configs
                r.string(), r.string()
            if name not in self.logs:
                self.logs[name] = [[] for _ in range(max(1, n_parts))]
            out.append(_str(name) + _i16(0))
        r.i32()                              # timeout
        return _array(out)

    def _delete_topics(self, r: _Reader) -> bytes:
        out = []
        for _ in range(r.i32()):
            name = r.string() or ""
            self.logs.pop(name, None)
            out.append(_str(name) + _i16(0))
        r.i32()
        return _array(out)

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 0.5)
            except asyncio.TimeoutError:
                pass
