from .message import Message
from .inmemory import InMemoryBroker
from .subscriber import SubscriptionManager

__all__ = ["Message", "InMemoryBroker", "SubscriptionManager"]
