"""Subscriber runtime: broker messages -> handler Contexts.

Mirrors reference pkg/gofr/subscriber.go: an event loop per topic that
polls the container's pub/sub client, wraps each message in a Context,
runs the handler with panic recovery, commits on success, and backs
off 2 seconds on broker errors (subscriber.go:27-107).
"""

from __future__ import annotations

import asyncio
from typing import Callable

from ..context import Context

ERROR_BACKOFF_S = 2.0


class SubscriptionManager:
    def __init__(self, container) -> None:
        self.container = container

    async def start_subscriber(self, topic: str, handler: Callable,
                               group: str | None = None) -> None:
        """Infinite consume loop for one topic (one asyncio task).

        ``group`` defaults to the configured consumer group
        (``CONSUMER_GROUP``/``KAFKA_CONSUMER_GROUP``), falling back to
        "default" — so apps with distinct configured groups never share
        offsets (reference kafka.go ConsumerGroupID semantics)."""
        if group is None:
            group = self._default_group()
        while True:
            try:
                await self.handle_one(topic, handler, group)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                self.container.logger.error(
                    f"subscriber {topic!r}: {exc!r}; retrying in "
                    f"{ERROR_BACKOFF_S}s")
                await asyncio.sleep(ERROR_BACKOFF_S)

    def _default_group(self) -> str:
        config = getattr(self.container, "config", None)
        if config is None:
            return "default"
        return config.get_or_default(
            "CONSUMER_GROUP",
            config.get_or_default("KAFKA_CONSUMER_GROUP", "default"))

    async def handle_one(self, topic: str, handler: Callable,
                         group: str | None = None) -> None:
        """Consume and handle exactly one message (test-friendly)."""
        if group is None:
            group = self._default_group()
        pubsub = self.container.pubsub
        if pubsub is None:
            raise RuntimeError("no pub/sub client configured")
        msg = await pubsub.subscribe(topic, group)
        ctx = Context(request=msg, container=self.container)
        metrics = self.container.metrics
        try:
            result = handler(ctx)
            if hasattr(result, "__await__"):
                await result
        except Exception as exc:  # handler panic: log, do NOT commit
            self.container.logger.error(
                f"handler for {topic!r} failed: {exc!r}")
            return
        msg.commit()  # at-least-once: commit only on success
        if metrics is not None:
            metrics.increment_counter(
                "app_pubsub_subscribe_success_count", topic=topic)
