from .llama import LlamaConfig, llama_decode_step, llama_init, llama_prefill

__all__ = ["LlamaConfig", "llama_decode_step", "llama_init", "llama_prefill"]
