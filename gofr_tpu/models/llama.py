"""Llama-3-family decoder — the flagship serving model.

Pure-functional: parameters are a pytree of arrays, the forward passes
are plain jittable functions. TPU-first structure:

- **lax.scan over layers** with stacked per-layer weights (leading
  ``L`` axis): one compiled layer body regardless of depth, which keeps
  XLA compile times flat for 32/80-layer configs and gives the pipeline
  parallel path its natural stage structure.
- bf16 params/activations, f32 norms/softmax/logits.
- GQA + RoPE (Llama-3 scaling), SwiGLU MLP, RMSNorm, optional tied
  embeddings.
- Prefill returns the per-layer K/V for cache insertion; decode takes
  cache [L, B, Smax, Hkv, hd] + per-sequence lengths and updates in
  place (donated by the engine under jit).

Capability reference: the serving targets of BASELINE.json (Llama-3-8B
`/chat` on v5e-8, 70B multi-host on v5p-64).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any

import jax
import jax.numpy as jnp

from ..ops.attention import attention, decode_attention
from ..ops.norms import rms_norm
from ..ops.quant import qgather, qmatmul, qmatmul_t
from ..ops.rope import apply_rope, rope_frequencies


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    rope_scaling: dict | None = None
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    # ---- presets -----------------------------------------------------
    @classmethod
    def tiny(cls) -> "LlamaConfig":
        """Test config: runs everywhere in milliseconds."""
        return cls(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                   n_kv_heads=2, ffn_dim=128, max_seq=128,
                   dtype=jnp.float32)

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()  # the defaults are the 8B shape

    @classmethod
    def llama3_70b(cls) -> "LlamaConfig":
        return cls(dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                   ffn_dim=28672)

    @classmethod
    def llama3_1b(cls) -> "LlamaConfig":
        """Llama-3.2-1B shape — the single-chip bench model."""
        return cls(vocab_size=128256, dim=2048, n_layers=16, n_heads=32,
                   n_kv_heads=8, ffn_dim=8192, tie_embeddings=True)

    def scaled(self, **kw) -> "LlamaConfig":
        return replace(self, **kw)


# ---------------------------------------------------------------- params

def llama_init(key: jax.Array, config: LlamaConfig) -> dict:
    """Random-init parameter pytree with stacked layer weights."""
    c = config
    hd = c.head_dim
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def norm_init(shape):
        return jnp.ones(shape, c.dtype)

    def dense_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(c.dtype)

    lk = jax.random.split(k_layers, 7)
    L = c.n_layers
    layers = {
        "attn_norm": norm_init((L, c.dim)),
        "wq": dense_init(lk[0], (L, c.dim, c.n_heads * hd), c.dim),
        "wk": dense_init(lk[1], (L, c.dim, c.n_kv_heads * hd), c.dim),
        "wv": dense_init(lk[2], (L, c.dim, c.n_kv_heads * hd), c.dim),
        "wo": dense_init(lk[3], (L, c.n_heads * hd, c.dim), c.n_heads * hd),
        "ffn_norm": norm_init((L, c.dim)),
        "w1": dense_init(lk[4], (L, c.dim, c.ffn_dim), c.dim),
        "w3": dense_init(lk[5], (L, c.dim, c.ffn_dim), c.dim),
        "w2": dense_init(lk[6], (L, c.ffn_dim, c.dim), c.ffn_dim),
    }
    params = {
        "embed": (jax.random.normal(k_embed, (c.vocab_size, c.dim), jnp.float32)
                  * 0.02).astype(c.dtype),
        "layers": layers,
        "final_norm": norm_init((c.dim,)),
    }
    if not c.tie_embeddings:
        params["lm_head"] = dense_init(k_head, (c.dim, c.vocab_size), c.dim)
    return params


def param_count(params: dict) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


# --------------------------------------------------------------- forward

def _attn_block(x, lp, c: LlamaConfig, inv_freq, positions, kv_lengths,
                implementation):
    """Self-attention over a full (prefill) block. Returns (out, k, v).
    Matrices route through ``qmatmul``: int8-quantized weights (see
    :mod:`..ops.quant`) dequantize inside the matmul read."""
    b, s, _ = x.shape
    hd = c.head_dim
    h = rms_norm(x, lp["attn_norm"], c.norm_eps)
    q = qmatmul(h, lp["wq"]).reshape(b, s, c.n_heads, hd)
    k = qmatmul(h, lp["wk"]).reshape(b, s, c.n_kv_heads, hd)
    v = qmatmul(h, lp["wv"]).reshape(b, s, c.n_kv_heads, hd)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    out = attention(q, k, v, causal=True, kv_lengths=kv_lengths,
                    implementation=implementation)
    out = qmatmul(out.reshape(b, s, c.n_heads * hd), lp["wo"])
    return out, k, v


def _mlp_block(x, lp, c: LlamaConfig):
    h = rms_norm(x, lp["ffn_norm"], c.norm_eps)
    gate = jax.nn.silu(qmatmul(h, lp["w1"]).astype(jnp.float32))
    return qmatmul((gate * qmatmul(h, lp["w3"]).astype(jnp.float32))
                   .astype(x.dtype), lp["w2"])


def _logits(params, c: LlamaConfig, x):
    # LM head runs in the weights' dtype (bf16 in serving; int8 when
    # quantized — half the HBM traffic again) with f32 accumulation:
    # the logits come out f32 for sampling either way.
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    if c.tie_embeddings:
        return qmatmul_t(x, params["embed"], out_dtype=jnp.float32)
    return qmatmul(x, params["lm_head"], out_dtype=jnp.float32)


def _backbone(params: dict, tokens: jnp.ndarray, c: LlamaConfig,
              kv_lengths, implementation, constrain
              ) -> tuple[jnp.ndarray, tuple[jnp.ndarray, jnp.ndarray]]:
    """Embedding + all transformer blocks; returns final hidden states
    [B, S, D] (pre-final-norm) and the stacked per-layer K/V."""
    b, s = tokens.shape
    inv_freq = rope_frequencies(c.head_dim, c.rope_theta, c.rope_scaling)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    x = qgather(params["embed"], tokens, c.dtype)
    if constrain is not None:
        x = constrain(x)

    def layer_fn(x, lp):
        attn_out, k, v = _attn_block(x, lp, c, inv_freq, positions,
                                     kv_lengths, implementation)
        x = x + attn_out
        x = x + _mlp_block(x, lp, c)
        if constrain is not None:
            x = constrain(x)
        return x, (k, v)

    return jax.lax.scan(layer_fn, x, params["layers"])


def llama_prefill(params: dict, tokens: jnp.ndarray, config: LlamaConfig, *,
                  kv_lengths: jnp.ndarray | None = None,
                  implementation: str = "auto",
                  constrain=None
                  ) -> tuple[jnp.ndarray, tuple[jnp.ndarray, jnp.ndarray]]:
    """Full-sequence forward.

    tokens [B, S] -> (logits [B, S, V], (k_cache, v_cache) each
    [L, B, S, Hkv, hd]). ``kv_lengths`` masks right-padded batches.
    ``constrain``: optional fn applied to residual activations — the
    parallel layer passes a ``with_sharding_constraint`` to pin
    Megatron-style sequence-parallel layouts between blocks.
    """
    x, (ks, vs) = _backbone(params, tokens, config, kv_lengths,
                            implementation, constrain)
    return _logits(params, config, x), (ks, vs)


def llama_prefill_last(params: dict, tokens: jnp.ndarray, config: LlamaConfig,
                       *, kv_lengths: jnp.ndarray,
                       implementation: str = "auto", constrain=None
                       ) -> tuple[jnp.ndarray, tuple[jnp.ndarray, jnp.ndarray]]:
    """Prefill for serving: logits only at each row's last prompt token.

    The LM head is the single largest matmul in a short-prompt prefill
    (S·D·V vs the backbone's ~S·12·D²); a serving prefill only ever
    samples from the final position, so gather the [B, D] hidden rows
    at ``kv_lengths - 1`` *before* the head. Returns
    (last_logits [B, V], (k_cache, v_cache) each [L, B, S, Hkv, hd]).
    """
    x, (ks, vs) = _backbone(params, tokens, config, kv_lengths,
                            implementation, constrain)
    last = jnp.take_along_axis(
        x, jnp.maximum(kv_lengths - 1, 0)[:, None, None], axis=1)[:, 0]
    return _logits(params, config, last), (ks, vs)


def llama_decode_step(params: dict, tokens: jnp.ndarray,
                      k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                      lengths: jnp.ndarray, config: LlamaConfig, *,
                      attn_window: int | None = None
                      ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One decode step for a batch of sequences.

    tokens [B] (the latest token per sequence); caches
    [L, B, Smax, Hkv, hd]; lengths [B] = current kv length per sequence
    (the new token is written at that position). Returns
    (logits [B, V], new_k_cache, new_v_cache). The engine donates the
    caches so XLA updates them in place.

    ``attn_window``: static row count attention reads per layer (the
    engine picks a bucket covering every live length this pass, see
    ``EngineConfig.decode_windows``). Decode attention's HBM traffic is
    then O(window), not O(max_seq) — the cache is still allocated and
    written at full size. Caller guarantees lengths + 1 <= window.
    """
    c = config
    b = tokens.shape[0]
    hd = c.head_dim
    inv_freq = rope_frequencies(c.head_dim, c.rope_theta, c.rope_scaling)
    positions = lengths[:, None]  # [B, 1] — absolute position of new token
    x = qgather(params["embed"], tokens, c.dtype)[:, None, :]  # [B, 1, D]
    batch_idx = jnp.arange(b)

    # caches ride the scan CARRY: each layer row-scatters its fresh
    # K/V straight into the full buffer and attention reads a dynamic
    # layer slice. Emitting per-layer caches as scan ys instead (the
    # r4 formulation) forced XLA to write every layer's FULL
    # [B, Smax, Hkv, hd] slice into a fresh stacked output each step —
    # a whole-cache copy per decode step on top of attention's reads
    # (measured 3x step time at max_seq=1024 on the CPU probe).
    def layer_fn(carry, scanned):
        x, kc_all, vc_all = carry
        lp, li = scanned
        h = rms_norm(x, lp["attn_norm"], c.norm_eps)
        q = qmatmul(h, lp["wq"]).reshape(b, 1, c.n_heads, hd)
        k = qmatmul(h, lp["wk"]).reshape(b, 1, c.n_kv_heads, hd)
        v = qmatmul(h, lp["wv"]).reshape(b, 1, c.n_kv_heads, hd)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        kc_all = kc_all.at[li, batch_idx, lengths].set(k[:, 0])
        vc_all = vc_all.at[li, batch_idx, lengths].set(v[:, 0])
        kc = jax.lax.dynamic_index_in_dim(kc_all, li, 0, keepdims=False)
        vc = jax.lax.dynamic_index_in_dim(vc_all, li, 0, keepdims=False)
        if attn_window is not None and attn_window < kc.shape[1]:
            kc = kc[:, :attn_window]
            vc = vc[:, :attn_window]
        out = decode_attention(q, kc, vc, lengths + 1)
        x = x + qmatmul(out.reshape(b, 1, c.n_heads * hd), lp["wo"])
        x = x + _mlp_block(x, lp, c)
        return (x, kc_all, vc_all), None

    (x, new_k, new_v), _ = jax.lax.scan(
        layer_fn, (x, k_cache, v_cache),
        (params["layers"], jnp.arange(c.n_layers)))
    logits = _logits(params, c, x)[:, 0]  # [B, V]
    return logits, new_k, new_v


def llama_decode_step_paged(params: dict, tokens: jnp.ndarray,
                            k_pool: jnp.ndarray, v_pool: jnp.ndarray,
                            tables: jnp.ndarray, lengths: jnp.ndarray,
                            config: LlamaConfig, *,
                            implementation: str = "auto"
                            ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One decode step straight against the paged KV pool.

    Unlike the engine's generic paged path (gather a dense view, run
    :func:`llama_decode_step`, scatter back — O(full cache) extra HBM
    traffic per pass), this writes each new K/V row through the block
    table and attends with the ragged paged kernel
    (:func:`..ops.paged_attention.paged_decode_attention`), so the pool
    is only ever touched in place. pools [L, Hkv, Np, pg, hd]
    (head-major — see ops/paged_kv.py); tables [B, Mp]; lengths [B] =
    rows already cached (the new token lands at that position).
    Returns (logits [B, V], new_k_pool, new_v_pool). Quantized pools
    (the ``{"q", "s"}`` pytree from ops/paged_kv.py) ride the same
    scan: writes quantize inside :func:`..ops.paged_kv.pool_write` and
    the ragged kernel dequantizes per page.
    """
    from ..ops.paged_attention import paged_decode_attention
    from ..ops.paged_kv import pool_layer, pool_shape, pool_write
    c = config
    b = tokens.shape[0]
    hd = c.head_dim
    n_pages, pg = pool_shape(k_pool)[2:4]
    inv_freq = rope_frequencies(c.head_dim, c.rope_theta, c.rope_scaling)
    positions = lengths[:, None]
    x = qgather(params["embed"], tokens, c.dtype)[:, None, :]  # [B, 1, D]
    # the new row's page id and in-page offset via the table; rows at
    # or past the allocation see the OOB id and drop on scatter
    pids = jnp.take_along_axis(
        tables, jnp.minimum(lengths // pg, tables.shape[1] - 1)[:, None],
        axis=1)[:, 0]
    pids = jnp.where(lengths < tables.shape[1] * pg, pids, n_pages)
    offs = lengths % pg

    # pools ride the scan CARRY (see llama_decode_step): the fresh row
    # scatters straight into the full pool — ys emission would copy
    # every layer's whole pool slice per step. Advanced-index note:
    # ``at[li, :, pids, offs]`` puts the broadcast [B] index result in
    # front of the sliced head axis, so the update value is k[:, 0]
    # ([B, Hkv, hd]) with no transpose.
    def layer_fn(carry, scanned):
        x, kp_all, vp_all = carry     # [L, Hkv, Np, pg, hd]
        lp, li = scanned
        h = rms_norm(x, lp["attn_norm"], c.norm_eps)
        q = qmatmul(h, lp["wq"]).reshape(b, 1, c.n_heads, hd)
        k = qmatmul(h, lp["wk"]).reshape(b, 1, c.n_kv_heads, hd)
        v = qmatmul(h, lp["wv"]).reshape(b, 1, c.n_kv_heads, hd)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        kp_all = pool_write(kp_all, li, pids, offs, k[:, 0])
        vp_all = pool_write(vp_all, li, pids, offs, v[:, 0])
        kp = pool_layer(kp_all, li)
        vp = pool_layer(vp_all, li)
        out = paged_decode_attention(q[:, 0], kp, vp, tables, lengths + 1,
                                     implementation=implementation)
        x = x + qmatmul(out.reshape(b, 1, c.n_heads * hd), lp["wo"])
        x = x + _mlp_block(x, lp, c)
        return (x, kp_all, vp_all), None

    (x, new_k, new_v), _ = jax.lax.scan(
        layer_fn, (x, k_pool, v_pool),
        (params["layers"], jnp.arange(c.n_layers)))
    logits = _logits(params, c, x)[:, 0]
    return logits, new_k, new_v


def llama_prefill_chunk(params: dict, tokens: jnp.ndarray,
                        k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                        offsets: jnp.ndarray, chunk_lengths: jnp.ndarray,
                        config: LlamaConfig, *,
                        implementation: str = "auto",
                        return_all_logits: bool = False,
                        tree_depths: jnp.ndarray | None = None,
                        tree_masks: jnp.ndarray | None = None
                        ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One chunk of a chunked prefill: process ``tokens`` [B, S] whose
    row b starts at absolute position ``offsets[b]``, attending to the
    cache rows written by earlier chunks plus intra-chunk causal, and
    writing this chunk's K/V into the caches at
    ``[offsets, offsets + chunk_lengths)``.

    This is how prompts longer than the widest prefill bucket run
    without truncation: the engine walks the prompt in bucket-width
    chunks (long-context obligation, SURVEY §5). Returns
    (last-position logits [B, V], new_k_cache, new_v_cache); caches
    are [L, B, Smax, Hkv, hd] and meant to be donated.

    ``tree_depths``/``tree_masks`` [B, S] (both or neither) switch the
    chunk into draft-tree verify mode: row i is tree NODE i (node 0 =
    root, topological order), RoPE runs at ``offsets + tree_depths``
    (siblings share a depth), K/V rows land at node index
    ``offsets + i`` (each node gets its own cache row — the engine
    compacts the accepted path afterwards), and attention masks
    in-chunk visibility by the packed ancestor bits instead of causal
    order. ``None`` (the default) traces the exact historical graph.
    """
    from ..ops.attention import attention, tree_attention
    c = config
    b, s = tokens.shape
    smax = k_cache.shape[2]
    hd = c.head_dim
    inv_freq = rope_frequencies(c.head_dim, c.rope_theta, c.rope_scaling)
    node_pos = offsets[:, None] + jnp.arange(s)[None, :]       # [B, S]
    positions = node_pos if tree_depths is None \
        else offsets[:, None] + tree_depths
    valid = jnp.arange(s)[None, :] < chunk_lengths[:, None]    # [B, S]
    # invalid rows scatter out of bounds and drop — padded tail rows
    # must never overwrite live cache
    write_pos = jnp.where(valid, node_pos, smax)
    batch_idx = jnp.arange(b)
    x = qgather(params["embed"], tokens, c.dtype)

    # caches ride the scan carry (see llama_decode_step): the chunk's
    # rows scatter straight into the full buffer instead of each layer
    # emitting its whole cache slice as a scan output
    def layer_fn(carry, scanned):
        x, kc_all, vc_all = carry
        lp, li = scanned
        h = rms_norm(x, lp["attn_norm"], c.norm_eps)
        q = qmatmul(h, lp["wq"]).reshape(b, s, c.n_heads, hd)
        k = qmatmul(h, lp["wk"]).reshape(b, s, c.n_kv_heads, hd)
        v = qmatmul(h, lp["wv"]).reshape(b, s, c.n_kv_heads, hd)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        kc_all = kc_all.at[li, batch_idx[:, None], write_pos].set(
            k.astype(kc_all.dtype), mode="drop")
        vc_all = vc_all.at[li, batch_idx[:, None], write_pos].set(
            v.astype(vc_all.dtype), mode="drop")
        kc = jax.lax.dynamic_index_in_dim(kc_all, li, 0, keepdims=False)
        vc = jax.lax.dynamic_index_in_dim(vc_all, li, 0, keepdims=False)
        # causal against the full history: query row s_i sees cache
        # positions <= offsets + s_i (earlier chunks + intra-chunk).
        # Dispatch follows the rest of the stack; q_offset != 0 routes
        # to the XLA path today, and a future history-aware kernel
        # picks it up here. Tree verify swaps the intra-chunk causal
        # mask for the packed ancestor bits.
        if tree_masks is None:
            out = attention(q, kc, vc, causal=True, q_offset=offsets,
                            implementation=implementation)
        else:
            out = tree_attention(q, kc, vc, history_lens=offsets,
                                 chunk_lens=chunk_lengths,
                                 tree_masks=tree_masks)
        x = x + qmatmul(out.reshape(b, s, c.n_heads * hd), lp["wo"])
        x = x + _mlp_block(x, lp, c)
        return (x, kc_all, vc_all), None

    (x, new_k, new_v), _ = jax.lax.scan(
        layer_fn, (x, k_cache, v_cache),
        (params["layers"], jnp.arange(c.n_layers)))
    if return_all_logits:
        # speculative verification wants every fed position's logits
        # (S is the small draft window there, so the [S, V] head is
        # cheap — unlike prompt prefill, where last-only matters)
        return _logits(params, c, x), new_k, new_v
    last = jnp.take_along_axis(
        x, jnp.maximum(chunk_lengths - 1, 0)[:, None, None], axis=1)[:, 0]
    return _logits(params, c, last), new_k, new_v


def llama_prefill_chunk_paged(params: dict, tokens: jnp.ndarray,
                              k_pool: jnp.ndarray, v_pool: jnp.ndarray,
                              tables: jnp.ndarray, offsets: jnp.ndarray,
                              chunk_lengths: jnp.ndarray,
                              config: LlamaConfig, *,
                              implementation: str = "auto",
                              return_all_logits: bool = False,
                              tree_depths: jnp.ndarray | None = None,
                              tree_masks: jnp.ndarray | None = None
                              ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One chunk of a chunked prefill straight against the paged pool.

    The generic paged chunk path gathers a dense per-slot view of the
    WHOLE pool allocation, runs :func:`llama_prefill_chunk` on it and
    scatters back — O(full-cache) HBM traffic per chunk, which
    dominates TTFT for long prompts. This variant writes each layer's
    chunk K/V through the block table (only the pages the chunk spans)
    and attends with the ragged chunk kernel
    (:func:`..ops.paged_attention.paged_chunk_attention`), so the pool
    is only ever touched in place — the prefill-side twin of
    :func:`llama_decode_step_paged`.

    tokens [B, S] start at absolute positions ``offsets`` per row;
    pools [L, Hkv, Np, pg, hd] (head-major); tables [B, Mp]. Rows past
    ``chunk_lengths[b]`` are padding: their writes drop (OOB page id)
    and their logits are garbage the caller discards. Returns
    (last-position logits [B, V] — or all positions [B, S, V] with
    ``return_all_logits`` for speculative verify — new_k_pool,
    new_v_pool); pools are meant to be donated.

    ``tree_depths``/``tree_masks`` [B, S] (both or neither) switch the
    chunk into draft-tree verify mode, exactly as in
    :func:`llama_prefill_chunk`: RoPE at ``offsets + tree_depths``,
    K/V rows at node index ``offsets + i``, attention through
    :func:`..ops.paged_attention.paged_tree_attention`'s packed
    ancestor bitmask. ``None`` traces the historical graph.
    """
    from ..ops.paged_attention import (paged_chunk_attention,
                                       paged_tree_attention)
    from ..ops.paged_kv import pool_layer, pool_shape, pool_write
    c = config
    b, s = tokens.shape
    hd = c.head_dim
    n_pages, pg = pool_shape(k_pool)[2:4]
    mp = tables.shape[1]
    inv_freq = rope_frequencies(c.head_dim, c.rope_theta, c.rope_scaling)
    node_pos = offsets[:, None] + jnp.arange(s)[None, :]       # [B, S]
    positions = node_pos if tree_depths is None \
        else offsets[:, None] + tree_depths
    valid = jnp.arange(s)[None, :] < chunk_lengths[:, None]    # [B, S]
    # page id + in-page offset per written position; padding rows and
    # positions past the table map to the OOB id and drop on scatter
    pids = jnp.take_along_axis(
        tables, jnp.clip(node_pos // pg, 0, mp - 1), axis=1)   # [B, S]
    pids = jnp.where(valid & (node_pos < mp * pg), pids, n_pages)
    offs = node_pos % pg
    x = qgather(params["embed"], tokens, c.dtype)

    # pools ride the scan carry (see llama_decode_step_paged); the
    # advanced-index write puts the broadcast [B, S] index result in
    # front of the sliced head axis, so the update value is the raw
    # [B, S, Hkv, hd] chunk K/V with no transpose
    def layer_fn(carry, scanned):
        x, kp_all, vp_all = carry     # [L, Hkv, Np, pg, hd]
        lp, li = scanned
        h = rms_norm(x, lp["attn_norm"], c.norm_eps)
        q = qmatmul(h, lp["wq"]).reshape(b, s, c.n_heads, hd)
        k = qmatmul(h, lp["wk"]).reshape(b, s, c.n_kv_heads, hd)
        v = qmatmul(h, lp["wv"]).reshape(b, s, c.n_kv_heads, hd)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        kp_all = pool_write(kp_all, li, pids, offs, k)
        vp_all = pool_write(vp_all, li, pids, offs, v)
        kp = pool_layer(kp_all, li)
        vp = pool_layer(vp_all, li)
        if tree_masks is None:
            out = paged_chunk_attention(q, kp, vp, tables, offsets,
                                        chunk_lengths,
                                        implementation=implementation)
        else:
            out = paged_tree_attention(q, kp, vp, tables, offsets,
                                       chunk_lengths, tree_masks,
                                       implementation=implementation)
        x = x + qmatmul(out.reshape(b, s, c.n_heads * hd), lp["wo"])
        x = x + _mlp_block(x, lp, c)
        return (x, kp_all, vp_all), None

    (x, new_k, new_v), _ = jax.lax.scan(
        layer_fn, (x, k_pool, v_pool),
        (params["layers"], jnp.arange(c.n_layers)))
    if return_all_logits:
        return _logits(params, c, x), new_k, new_v
    last = jnp.take_along_axis(
        x, jnp.maximum(chunk_lengths - 1, 0)[:, None, None], axis=1)[:, 0]
    return _logits(params, c, last), new_k, new_v


def make_empty_cache(config: LlamaConfig, batch: int,
                     max_seq: int | None = None) -> tuple[jnp.ndarray, jnp.ndarray]:
    c = config
    s = max_seq or c.max_seq
    shape = (c.n_layers, batch, s, c.n_kv_heads, c.head_dim)
    return jnp.zeros(shape, c.dtype), jnp.zeros(shape, c.dtype)
