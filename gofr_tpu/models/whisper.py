"""Whisper-family encoder-decoder for ASR (baseline config 4:
"Whisper-large ASR via Pub/Sub batch").

Pure-functional, same TPU-first structure as the Llama module:
stacked per-layer weights scanned with ``lax.scan`` (flat compile time
at any depth), bf16 matmuls with f32 norms/softmax, static shapes
end-to-end. The audio frontend (ops/audio.py) runs in the same program
so mel extraction happens on-device.

Architecture (Whisper v2/v3 shape): conv1d×2 downsampling + sinusoidal
positions -> pre-LN transformer encoder; decoder with causal
self-attention (KV cache), cross-attention over the encoder output
(K/V precomputed once per utterance), learned positions, tied output
embedding. Greedy transcription is a single ``lax.scan`` over decode
steps with per-sequence end-of-text masking — one compiled graph per
(batch, max_tokens) bucket, donated caches.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.attention import decode_attention, xla_attention
from ..ops.audio import log_mel_spectrogram
from ..ops.norms import layer_norm


@dataclass(frozen=True)
class WhisperConfig:
    vocab_size: int = 51866
    n_mels: int = 80
    audio_frames: int = 3000     # 30 s at 10 ms hop
    audio_ctx: int = 1500        # frames after conv stride-2
    text_ctx: int = 448
    dim: int = 1280
    n_heads: int = 20
    n_audio_layers: int = 32
    n_text_layers: int = 32
    sot_token: int = 50258       # <|startoftranscript|>
    eot_token: int = 50257       # <|endoftext|>
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    # ---- presets -----------------------------------------------------
    @classmethod
    def tiny_test(cls) -> "WhisperConfig":
        """Milliseconds-everywhere test shape."""
        return cls(vocab_size=128, n_mels=8, audio_frames=64, audio_ctx=32,
                   text_ctx=32, dim=32, n_heads=4, n_audio_layers=2,
                   n_text_layers=2, sot_token=1, eot_token=2,
                   dtype=jnp.float32)

    @classmethod
    def whisper_tiny(cls) -> "WhisperConfig":
        return cls(dim=384, n_heads=6, n_audio_layers=4, n_text_layers=4,
                   vocab_size=51865)

    @classmethod
    def whisper_base(cls) -> "WhisperConfig":
        return cls(dim=512, n_heads=8, n_audio_layers=6, n_text_layers=6,
                   vocab_size=51865)

    @classmethod
    def whisper_small(cls) -> "WhisperConfig":
        return cls(dim=768, n_heads=12, n_audio_layers=12, n_text_layers=12,
                   vocab_size=51865)

    @classmethod
    def whisper_large_v3(cls) -> "WhisperConfig":
        return cls(n_mels=128)   # large defaults; v3 uses 128 mels

    def scaled(self, **kw) -> "WhisperConfig":
        return replace(self, **kw)


# ---------------------------------------------------------------- params

def _sinusoids(length: int, channels: int) -> np.ndarray:
    """Whisper's encoder positional table (log-spaced sinusoids)."""
    log_timescale = np.log(10000.0) / (channels // 2 - 1)
    inv = np.exp(-log_timescale * np.arange(channels // 2))
    scaled = np.arange(length)[:, None] * inv[None, :]
    return np.concatenate([np.sin(scaled), np.cos(scaled)],
                          axis=1).astype(np.float32)


def _block_init(key, L: int, dim: int, n_heads: int, dtype,
                cross: bool) -> dict:
    """Stacked transformer-block weights; pre-LN, GELU MLP (4x)."""
    hd = dim
    keys = jax.random.split(key, 12)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    def zeros(shape):
        return jnp.zeros(shape, dtype)

    def ln(shape):
        return jnp.ones(shape, dtype)

    block = {
        "ln1_w": ln((L, dim)), "ln1_b": zeros((L, dim)),
        "wq": dense(keys[0], (L, dim, hd), dim), "bq": zeros((L, hd)),
        "wk": dense(keys[1], (L, dim, hd), dim),     # no k bias (Whisper)
        "wv": dense(keys[2], (L, dim, hd), dim), "bv": zeros((L, hd)),
        "wo": dense(keys[3], (L, hd, dim), hd), "bo": zeros((L, dim)),
        "ln_mlp_w": ln((L, dim)), "ln_mlp_b": zeros((L, dim)),
        "fc1": dense(keys[4], (L, dim, 4 * dim), dim),
        "fc1_b": zeros((L, 4 * dim)),
        "fc2": dense(keys[5], (L, 4 * dim, dim), 4 * dim),
        "fc2_b": zeros((L, dim)),
    }
    if cross:
        block.update({
            "lnx_w": ln((L, dim)), "lnx_b": zeros((L, dim)),
            "xwq": dense(keys[6], (L, dim, hd), dim), "xbq": zeros((L, hd)),
            "xwk": dense(keys[7], (L, dim, hd), dim),
            "xwv": dense(keys[8], (L, dim, hd), dim), "xbv": zeros((L, hd)),
            "xwo": dense(keys[9], (L, hd, dim), hd), "xbo": zeros((L, dim)),
        })
    return block


def whisper_init(key: jax.Array, config: WhisperConfig) -> dict:
    c = config
    ks = jax.random.split(key, 6)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(c.dtype)

    return {
        "conv1_w": dense(ks[0], (3, c.n_mels, c.dim), 3 * c.n_mels),
        "conv1_b": jnp.zeros((c.dim,), c.dtype),
        "conv2_w": dense(ks[1], (3, c.dim, c.dim), 3 * c.dim),
        "conv2_b": jnp.zeros((c.dim,), c.dtype),
        "enc_pos": jnp.asarray(_sinusoids(c.audio_ctx, c.dim), c.dtype),
        "enc_layers": _block_init(ks[2], c.n_audio_layers, c.dim,
                                  c.n_heads, c.dtype, cross=False),
        "enc_ln_w": jnp.ones((c.dim,), c.dtype),
        "enc_ln_b": jnp.zeros((c.dim,), c.dtype),
        "embed": (jax.random.normal(ks[3], (c.vocab_size, c.dim),
                                    jnp.float32) * 0.02).astype(c.dtype),
        "dec_pos": (jax.random.normal(ks[4], (c.text_ctx, c.dim),
                                      jnp.float32) * 0.01).astype(c.dtype),
        "dec_layers": _block_init(ks[5], c.n_text_layers, c.dim,
                                  c.n_heads, c.dtype, cross=True),
        "dec_ln_w": jnp.ones((c.dim,), c.dtype),
        "dec_ln_b": jnp.zeros((c.dim,), c.dtype),
    }


# ---------------------------------------------------------------- encoder

def _b(bias):
    # explicit [1, 1, D] lift of a bias vector onto [B, T, D]
    # activations: the test harness runs rank_promotion='raise'
    return bias.reshape(1, 1, -1)


def _heads(x, n_heads):
    b, s, d = x.shape
    return x.reshape(b, s, n_heads, d // n_heads)


def _merge(x):
    b, s, h, hd = x.shape
    return x.reshape(b, s, h * hd)


def _self_attn(x, lp, c: WhisperConfig, causal=False):
    q = _heads(x @ lp["wq"] + _b(lp["bq"]), c.n_heads)
    k = _heads(x @ lp["wk"], c.n_heads)
    v = _heads(x @ lp["wv"] + _b(lp["bv"]), c.n_heads)
    out = xla_attention(q, k, v, causal=causal)
    return _merge(out) @ lp["wo"] + _b(lp["bo"]), k, v


def whisper_encode(params: dict, mel: jnp.ndarray,
                   config: WhisperConfig) -> jnp.ndarray:
    """mel [B, frames, n_mels] -> encoder states [B, audio_ctx, dim]."""
    c = config
    x = mel.astype(c.dtype)
    dn = ("NWC", "WIO", "NWC")
    x = jax.nn.gelu(jax.lax.conv_general_dilated(
        x, params["conv1_w"], (1,), "SAME", dimension_numbers=dn)
        + _b(params["conv1_b"]))
    x = jax.nn.gelu(jax.lax.conv_general_dilated(
        x, params["conv2_w"], (2,), "SAME", dimension_numbers=dn)
        + _b(params["conv2_b"]))
    x = x + params["enc_pos"][None, :x.shape[1], :]

    def body(h, lp):
        a = layer_norm(h, lp["ln1_w"], lp["ln1_b"])
        attn_out, _, _ = _self_attn(a, lp, c, causal=False)
        h = h + attn_out
        m = layer_norm(h, lp["ln_mlp_w"], lp["ln_mlp_b"])
        h = h + (jax.nn.gelu(m @ lp["fc1"] + _b(lp["fc1_b"]))
                 @ lp["fc2"] + _b(lp["fc2_b"]))
        return h, None

    x, _ = jax.lax.scan(body, x, params["enc_layers"])
    return layer_norm(x, params["enc_ln_w"], params["enc_ln_b"])


# ---------------------------------------------------------------- decoder

def precompute_cross_kv(params: dict, enc: jnp.ndarray,
                        config: WhisperConfig) -> tuple:
    """Per-layer cross-attention K/V from the encoder output — computed
    once per utterance, reused by every decode step.
    Returns (k, v) each [L, B, audio_ctx, H, hd]."""
    c = config
    lp = params["dec_layers"]

    def per_layer(wk, wv, bv):
        k = _heads(enc @ wk, c.n_heads)
        v = _heads(enc @ wv + _b(bv), c.n_heads)
        return k, v

    return jax.vmap(per_layer)(lp["xwk"], lp["xwv"], lp["xbv"])


def _decoder_prefill(params: dict, tokens: jnp.ndarray, positions,
                     cross_k, cross_v, config: WhisperConfig):
    """Full causal prefill over the start-token prompt.

    tokens [B, S]; positions [S] absolute; cross_k/v [L,B,Sa,H,hd].
    Returns (hidden [B,S,dim], per-layer self K/V [L,B,S,H,hd]).
    """
    c = config
    x = params["embed"][tokens].astype(c.dtype) \
        + params["dec_pos"][positions][None, :, :].astype(c.dtype)

    def scan_body(h, xs):
        lp, xk, xv = xs
        a = layer_norm(h, lp["ln1_w"], lp["ln1_b"])
        q = _heads(a @ lp["wq"] + _b(lp["bq"]), c.n_heads)
        k = _heads(a @ lp["wk"], c.n_heads)
        v = _heads(a @ lp["wv"] + _b(lp["bv"]), c.n_heads)
        attn = xla_attention(q, k, v, causal=True)
        h = h + (_merge(attn) @ lp["wo"] + _b(lp["bo"]))

        xa = layer_norm(h, lp["lnx_w"], lp["lnx_b"])
        xq = _heads(xa @ lp["xwq"] + _b(lp["xbq"]), c.n_heads)
        xattn = xla_attention(xq, xk, xv, causal=False)
        h = h + (_merge(xattn) @ lp["xwo"] + _b(lp["xbo"]))

        m = layer_norm(h, lp["ln_mlp_w"], lp["ln_mlp_b"])
        h = h + (jax.nn.gelu(m @ lp["fc1"] + _b(lp["fc1_b"]))
                 @ lp["fc2"] + _b(lp["fc2_b"]))
        return h, (k, v)

    x, new_kv = jax.lax.scan(scan_body, x,
                             (params["dec_layers"], cross_k, cross_v))
    x = layer_norm(x, params["dec_ln_w"], params["dec_ln_b"])
    return x, new_kv


def _logits(params, hidden, config: WhisperConfig):
    return (hidden.astype(jnp.float32)
            @ params["embed"].T.astype(jnp.float32))


# --------------------------------------------------------- decode caching

def _decode_self_cache_update(cache_k, cache_v, new_k, new_v, lengths):
    """Insert step K/V [L,B,1,H,hd] at per-sequence positions."""
    rows = jnp.arange(cache_k.shape[2])[None, None, :]       # [1,1,Tmax]
    write = (rows == lengths[None, :, None])[..., None, None]
    cache_k = jnp.where(write, new_k.astype(cache_k.dtype), cache_k)
    cache_v = jnp.where(write, new_v.astype(cache_v.dtype), cache_v)
    return cache_k, cache_v


def transcribe_greedy(params: dict, mel: jnp.ndarray,
                      config: WhisperConfig, *,
                      max_tokens: int = 64) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Batched greedy ASR: mel [B, frames, n_mels] ->
    (tokens [B, max_tokens] int32, lengths [B] int32).

    One jittable graph: encode -> cross-K/V precompute -> SOT prefill ->
    ``lax.scan`` over decode steps with EOT freezing. Pad rows beyond a
    sequence's EOT hold the EOT token.
    """
    c = config
    b = mel.shape[0]
    enc = whisper_encode(params, mel, c)
    cross_k, cross_v = precompute_cross_kv(params, enc, c)

    sot = jnp.full((b, 1), c.sot_token, jnp.int32)
    hidden, first_kv = _decoder_prefill(
        params, sot, jnp.arange(1), cross_k, cross_v, c)
    first_logits = _logits(params, hidden[:, -1], c)

    L = c.n_text_layers
    t_max = max_tokens + 1
    cache_k = jnp.zeros((L, b, t_max, c.n_heads, c.head_dim), c.dtype)
    cache_v = jnp.zeros_like(cache_k)
    cache_k, cache_v = _decode_self_cache_update(
        cache_k, cache_v, first_kv[0], first_kv[1],
        jnp.zeros((b,), jnp.int32))

    first_tok = jnp.argmax(first_logits, axis=-1).astype(jnp.int32)
    done0 = first_tok == c.eot_token
    return _transcribe_loop(params, c, b, first_tok, done0, cache_k,
                            cache_v, cross_k, cross_v, max_tokens)


def _decoder_step_kv(params, tok, pos, cross_k, cross_v, c,
                     cache_k, cache_v, lengths):
    """One decode step that BOTH attends against and updates the cache.
    Returns (logits [B,V], cache_k, cache_v)."""
    x = params["embed"][tok[:, None]].astype(c.dtype) \
        + params["dec_pos"][pos][None, None, :].astype(c.dtype)

    lp = params["dec_layers"]
    b = tok.shape[0]
    batch_idx = jnp.arange(b)

    # caches ride the scan carry with a row scatter — the previous
    # formulation emitted them as scan ys after a full-cache
    # jnp.where select, i.e. a whole-cache read+write per layer per
    # step (see llama_decode_step for the measured cost)
    def scan_body(carry, xs):
        h, kc_all, vc_all = carry
        layer, xk, xv, li = xs
        a = layer_norm(h, layer["ln1_w"], layer["ln1_b"])
        q = _heads(a @ layer["wq"] + _b(layer["bq"]), c.n_heads)
        k = _heads(a @ layer["wk"], c.n_heads)
        v = _heads(a @ layer["wv"] + _b(layer["bv"]), c.n_heads)
        kc_all = kc_all.at[li, batch_idx, lengths].set(
            k[:, 0].astype(kc_all.dtype))
        vc_all = vc_all.at[li, batch_idx, lengths].set(
            v[:, 0].astype(vc_all.dtype))
        kc = jax.lax.dynamic_index_in_dim(kc_all, li, 0, keepdims=False)
        vc = jax.lax.dynamic_index_in_dim(vc_all, li, 0, keepdims=False)
        attn = decode_attention(q, kc, vc, lengths + 1)
        h = h + (_merge(attn) @ layer["wo"] + _b(layer["bo"]))

        xa = layer_norm(h, layer["lnx_w"], layer["lnx_b"])
        xq = _heads(xa @ layer["xwq"] + _b(layer["xbq"]), c.n_heads)
        xattn = xla_attention(xq, xk, xv, causal=False)
        h = h + (_merge(xattn) @ layer["xwo"] + _b(layer["xbo"]))

        m = layer_norm(h, layer["ln_mlp_w"], layer["ln_mlp_b"])
        h = h + (jax.nn.gelu(m @ layer["fc1"] + _b(layer["fc1_b"]))
                 @ layer["fc2"] + _b(layer["fc2_b"]))
        return (h, kc_all, vc_all), None

    (hidden, new_k, new_v), _ = jax.lax.scan(
        scan_body, (x, cache_k, cache_v),
        (lp, cross_k, cross_v, jnp.arange(c.n_text_layers)))
    hidden = layer_norm(hidden, params["dec_ln_w"], params["dec_ln_b"])
    logits = _logits(params, hidden[:, -1], c)
    return logits, new_k, new_v


def _transcribe_loop(params, c, b, first_tok, done0, cache_k, cache_v,
                     cross_k, cross_v, max_tokens):
    def step(carry, i):
        tok, done, ck, cv = carry
        lengths = jnp.broadcast_to(i + 1, (b,)).astype(jnp.int32)
        logits, ck, cv = _decoder_step_kv(
            params, tok, i + 1, cross_k, cross_v, c, ck, cv, lengths)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, c.eot_token, nxt)
        return (nxt, done | (nxt == c.eot_token), ck, cv), tok

    (_, done, _, _), toks = jax.lax.scan(
        step, (first_tok, done0, cache_k, cache_v),
        jnp.arange(max_tokens))
    tokens = jnp.moveaxis(toks, 0, 1)  # [B, max_tokens]
    lengths = jnp.sum(tokens != c.eot_token, axis=-1).astype(jnp.int32)
    return tokens, lengths


def transcribe_audio(params: dict, audio: jnp.ndarray,
                     config: WhisperConfig, *,
                     max_tokens: int = 64):
    """PCM [B, T] -> (tokens, lengths): mel frontend + greedy decode in
    one jittable graph (the ASR worker jits and buckets this)."""
    mel = log_mel_spectrogram(audio, n_mels=config.n_mels,
                              pad_to_frames=config.audio_frames)
    return transcribe_greedy(params, mel, config, max_tokens=max_tokens)


def param_count(params: dict) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
