"""Mixtral-style MoE decoder: Llama attention + sparse expert MLP.

The expert dimension is the natural expert-parallel (EP) axis: the
parallel layer shards ``w1/w3/w2`` over experts and turns the combine
into collectives, while this definition stays unchanged (see
gofr_tpu/parallel). Router logits are returned for the load-balancing
aux loss during training.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from ..ops.moe import moe_layer
from ..ops.norms import rms_norm
from ..ops.rope import apply_rope, rope_frequencies
from ..ops.attention import attention, decode_attention
from .llama import LlamaConfig


@dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    n_experts: int = 8
    top_k: int = 2

    @classmethod
    def tiny(cls) -> "MoEConfig":
        return cls(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                   n_kv_heads=2, ffn_dim=96, max_seq=128, n_experts=4,
                   top_k=2, dtype=jnp.float32)

    @classmethod
    def mixtral_8x7b(cls) -> "MoEConfig":
        return cls(vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
                   n_kv_heads=8, ffn_dim=14336, n_experts=8, top_k=2,
                   rope_theta=1e6)


def moe_init(key: jax.Array, config: MoEConfig) -> dict:
    c = config
    hd = c.head_dim
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(c.dtype)

    lk = jax.random.split(k_layers, 9)
    L, E = c.n_layers, c.n_experts
    layers = {
        "attn_norm": jnp.ones((L, c.dim), c.dtype),
        "wq": dense(lk[0], (L, c.dim, c.n_heads * hd), c.dim),
        "wk": dense(lk[1], (L, c.dim, c.n_kv_heads * hd), c.dim),
        "wv": dense(lk[2], (L, c.dim, c.n_kv_heads * hd), c.dim),
        "wo": dense(lk[3], (L, c.n_heads * hd, c.dim), c.n_heads * hd),
        "ffn_norm": jnp.ones((L, c.dim), c.dtype),
        "gate": dense(lk[4], (L, c.dim, E), c.dim),
        "w1": dense(lk[5], (L, E, c.dim, c.ffn_dim), c.dim),
        "w3": dense(lk[6], (L, E, c.dim, c.ffn_dim), c.dim),
        "w2": dense(lk[7], (L, E, c.ffn_dim, c.dim), c.ffn_dim),
    }
    params = {
        "embed": (jax.random.normal(k_embed, (c.vocab_size, c.dim), jnp.float32)
                  * 0.02).astype(c.dtype),
        "layers": layers,
        "final_norm": jnp.ones((c.dim,), c.dtype),
    }
    if not c.tie_embeddings:
        params["lm_head"] = dense(k_head, (c.dim, c.vocab_size), c.dim)
    return params


def _moe_mlp(x, lp, c: MoEConfig):
    b, s, d = x.shape
    h = rms_norm(x, lp["ffn_norm"], c.norm_eps)
    flat = h.reshape(b * s, d)
    out, router_logits = moe_layer(flat, lp["gate"], lp["w1"], lp["w3"],
                                   lp["w2"], num_selected=c.top_k)
    return out.reshape(b, s, d), router_logits.reshape(b, s, -1)


def _logits(params, c, x):
    # head in the weights' dtype with f32 accumulation (see
    # models/llama.py::_logits for the rationale)
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    head = params["embed"].T if c.tie_embeddings else params["lm_head"]
    return jnp.matmul(x.astype(head.dtype), head,
                      preferred_element_type=jnp.float32)


def _moe_backbone(params, tokens, c: MoEConfig, kv_lengths, implementation):
    """Embedding + all MoE blocks; final hidden [B, S, D] + caches +
    per-layer router logits."""
    b, s = tokens.shape
    hd = c.head_dim
    inv_freq = rope_frequencies(hd, c.rope_theta, c.rope_scaling)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    x = params["embed"][tokens]

    def layer_fn(x, lp):
        h = rms_norm(x, lp["attn_norm"], c.norm_eps)
        q = (h @ lp["wq"]).reshape(b, s, c.n_heads, hd)
        k = (h @ lp["wk"]).reshape(b, s, c.n_kv_heads, hd)
        v = (h @ lp["wv"]).reshape(b, s, c.n_kv_heads, hd)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        out = attention(q, k, v, causal=True, kv_lengths=kv_lengths,
                        implementation=implementation)
        x = x + (out.reshape(b, s, c.n_heads * hd) @ lp["wo"])
        mlp_out, router_logits = _moe_mlp(x, lp, c)
        return x + mlp_out, ((k, v), router_logits)

    x, ((ks, vs), router) = jax.lax.scan(layer_fn, x, params["layers"])
    return x, (ks, vs), router


def moe_prefill(params: dict, tokens: jnp.ndarray, config: MoEConfig, *,
                kv_lengths: jnp.ndarray | None = None,
                implementation: str = "auto"):
    """tokens [B,S] -> (logits, (k_cache, v_cache), router_logits)."""
    x, caches, router = _moe_backbone(params, tokens, config, kv_lengths,
                                      implementation)
    return _logits(params, config, x), caches, router


def moe_prefill_last(params: dict, tokens: jnp.ndarray, config: MoEConfig, *,
                     kv_lengths: jnp.ndarray, implementation: str = "auto"):
    """Serving prefill: logits only at each row's last prompt position
    (see models/llama.py::llama_prefill_last — the full [S, V] head is
    pure waste for positions never sampled)."""
    x, caches, router = _moe_backbone(params, tokens, config, kv_lengths,
                                      implementation)
    last = jnp.take_along_axis(
        x, jnp.maximum(kv_lengths - 1, 0)[:, None, None], axis=1)[:, 0]
    return _logits(params, config, last), caches, router


def moe_decode_step(params: dict, tokens: jnp.ndarray,
                    k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                    lengths: jnp.ndarray, config: MoEConfig, *,
                    attn_window: int | None = None):
    c = config
    b = tokens.shape[0]
    hd = c.head_dim
    inv_freq = rope_frequencies(hd, c.rope_theta, c.rope_scaling)
    positions = lengths[:, None]
    x = params["embed"][tokens][:, None, :]
    batch_idx = jnp.arange(b)

    # caches ride the scan carry — ys emission would copy each layer's
    # full [B, Smax, Hkv, hd] slice per step (see llama_decode_step)
    def layer_fn(carry, scanned):
        x, kc_all, vc_all = carry
        lp, li = scanned
        h = rms_norm(x, lp["attn_norm"], c.norm_eps)
        q = (h @ lp["wq"]).reshape(b, 1, c.n_heads, hd)
        k = (h @ lp["wk"]).reshape(b, 1, c.n_kv_heads, hd)
        v = (h @ lp["wv"]).reshape(b, 1, c.n_kv_heads, hd)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        kc_all = kc_all.at[li, batch_idx, lengths].set(k[:, 0])
        vc_all = vc_all.at[li, batch_idx, lengths].set(v[:, 0])
        kc = jax.lax.dynamic_index_in_dim(kc_all, li, 0, keepdims=False)
        vc = jax.lax.dynamic_index_in_dim(vc_all, li, 0, keepdims=False)
        if attn_window is not None and attn_window < kc.shape[1]:
            kc = kc[:, :attn_window]
            vc = vc[:, :attn_window]
        out = decode_attention(q, kc, vc, lengths + 1)
        x = x + (out.reshape(b, 1, c.n_heads * hd) @ lp["wo"])
        mlp_out, _ = _moe_mlp(x, lp, c)
        return (x + mlp_out, kc_all, vc_all), None

    (x, new_k, new_v), _ = jax.lax.scan(
        layer_fn, (x, k_cache, v_cache),
        (params["layers"], jnp.arange(c.n_layers)))
    return _logits(params, c, x)[:, 0], new_k, new_v
