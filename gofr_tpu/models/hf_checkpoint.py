"""Real-weight ingestion: safetensors checkpoints -> llama pytrees.

The reference framework's defining trait is speaking real external
formats over real protocols (its SQL driver talks the postgres wire,
reference pkg/gofr/datasource/sql/sql.go:74); for a model-serving
framework the analogous integration is the checkpoint on disk. This
module reads (and writes) the Hugging Face disk layout for the Llama
family with no third-party loader:

  * ``read_safetensors`` / ``write_safetensors`` — the safetensors
    container format from scratch (u64-LE header length, JSON header
    of ``{name: {dtype, shape, data_offsets}}``, raw little-endian
    tensor bytes), memory-mapped so a 16 GB checkpoint never
    double-buffers through Python;
  * ``load_llama_checkpoint`` — maps HF parameter names/layouts
    (``model.layers.{i}.self_attn.q_proj.weight`` stored ``[out, in]``)
    onto this repo's stacked ``[L, in, out]`` pytree
    (models/llama.py:83), reading ``config.json`` for the
    architecture and the ``model.safetensors.index.json`` weight map
    for sharded checkpoints, with optional int8
    quantize-on-load (ops/quant.py);
  * ``save_llama_checkpoint`` — the inverse, so pytrees round-trip to
    a directory any HF-format consumer can read.
"""

from __future__ import annotations

import json
import struct
from pathlib import Path
from typing import Any

import numpy as np

from .llama import LlamaConfig

# safetensors dtype tag -> numpy dtype. BF16 needs ml_dtypes (a jax
# dependency) — numpy has no native bfloat16.
_DTYPES: dict[str, Any] = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}


def _bf16():
    import ml_dtypes
    return ml_dtypes.bfloat16


def _np_dtype(tag: str):
    if tag == "BF16":
        return _bf16()
    try:
        return _DTYPES[tag]
    except KeyError:
        raise ValueError(f"unsupported safetensors dtype {tag!r}") from None


def _dtype_tag(dt: np.dtype) -> str:
    if dt == _bf16():
        return "BF16"
    for tag, npdt in _DTYPES.items():
        if dt == npdt:
            return tag
    raise ValueError(f"cannot store dtype {dt} in safetensors")


def read_safetensors(path: str | Path) -> dict[str, np.ndarray]:
    """Parse one .safetensors file into name -> memmap-backed array.

    Views are zero-copy slices of a single ``np.memmap``; slicing or
    ``np.asarray`` materialises only what the caller touches.
    """
    path = Path(path)
    with open(path, "rb") as f:
        (header_len,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(header_len))
    data = np.memmap(path, mode="r", offset=8 + header_len)
    out: dict[str, np.ndarray] = {}
    for name, spec in header.items():
        if name == "__metadata__":
            continue
        start, end = spec["data_offsets"]
        arr = data[start:end].view(_np_dtype(spec["dtype"]))
        out[name] = arr.reshape(spec["shape"])
    return out


def write_safetensors(path: str | Path, tensors: dict[str, np.ndarray],
                      metadata: dict[str, str] | None = None) -> None:
    """Write arrays as one .safetensors file (little-endian, C order)."""
    header: dict[str, Any] = {}
    if metadata:
        header["__metadata__"] = metadata
    offset = 0
    blobs: list[bytes] = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        if arr.dtype.byteorder == ">":
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        blob = arr.tobytes()
        header[name] = {"dtype": _dtype_tag(arr.dtype),
                        "shape": list(arr.shape),
                        "data_offsets": [offset, offset + len(blob)]}
        offset += len(blob)
        blobs.append(blob)
    head = json.dumps(header, separators=(",", ":")).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(head)))
        f.write(head)
        for blob in blobs:
            f.write(blob)


# ------------------------------------------------------------ llama map
#
# HF linear layers store [out_features, in_features]; this repo's
# matmuls run x @ w with stacked [L, in, out] weights — every
# projection transposes on the way through. The tiny-config CI
# round-trip would mask a wrong transpose only if the matrices were
# square; tiny is deliberately rectangular everywhere (64 x 128,
# 64 x 256).

_LAYER_MAP = (
    # (pytree key, HF suffix, transpose)
    ("attn_norm", "input_layernorm.weight", False),
    ("wq", "self_attn.q_proj.weight", True),
    ("wk", "self_attn.k_proj.weight", True),
    ("wv", "self_attn.v_proj.weight", True),
    ("wo", "self_attn.o_proj.weight", True),
    ("ffn_norm", "post_attention_layernorm.weight", False),
    ("w1", "mlp.gate_proj.weight", True),
    ("w3", "mlp.up_proj.weight", True),
    ("w2", "mlp.down_proj.weight", True),
)


_SERVING_DTYPES = {"float32": "float32", "fp32": "float32",
                   "bfloat16": "bfloat16", "bf16": "bfloat16",
                   "float16": "float16", "fp16": "float16"}


def resolve_serving_dtype(name: str):
    """Map a user-facing dtype name (``MODEL_DTYPE``) to a jnp float
    dtype, accepting the common short spellings. Rejects everything
    else up front: ``getattr(jnp, name)`` would happily resolve
    ``int8`` (which is NOT quantization — that's ``MODEL_QUANT``) and
    serve garbage with no error."""
    import jax.numpy as jnp
    canon = _SERVING_DTYPES.get(name.strip().lower())
    if canon is None:
        raise ValueError(
            f"MODEL_DTYPE={name!r}: expected one of "
            f"{sorted(set(_SERVING_DTYPES))} (for int8 weight-only "
            f"quantization use MODEL_QUANT=int8)")
    return getattr(jnp, canon)


def llama_config_from_hf(cfg: dict) -> LlamaConfig:
    """config.json -> LlamaConfig (HF "LlamaForCausalLM" schema)."""
    return LlamaConfig(
        vocab_size=cfg["vocab_size"],
        dim=cfg["hidden_size"],
        n_layers=cfg["num_hidden_layers"],
        n_heads=cfg["num_attention_heads"],
        n_kv_heads=cfg.get("num_key_value_heads",
                           cfg["num_attention_heads"]),
        ffn_dim=cfg["intermediate_size"],
        max_seq=cfg.get("max_position_embeddings", 8192),
        rope_theta=float(cfg.get("rope_theta", 500000.0)),
        rope_scaling=cfg.get("rope_scaling"),
        norm_eps=float(cfg.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(cfg.get("tie_word_embeddings", False)),
    )


def llama_config_to_hf(c: LlamaConfig) -> dict:
    out = {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "vocab_size": c.vocab_size,
        "hidden_size": c.dim,
        "num_hidden_layers": c.n_layers,
        "num_attention_heads": c.n_heads,
        "num_key_value_heads": c.n_kv_heads,
        "intermediate_size": c.ffn_dim,
        "max_position_embeddings": c.max_seq,
        "rope_theta": c.rope_theta,
        "rms_norm_eps": c.norm_eps,
        "tie_word_embeddings": c.tie_embeddings,
    }
    if c.rope_scaling:
        out["rope_scaling"] = c.rope_scaling
    return out


def _resolve_weight_files(directory: Path) -> dict[str, Path]:
    """name -> file, honoring the sharded-checkpoint index."""
    index = directory / "model.safetensors.index.json"
    if index.is_file():
        weight_map = json.loads(index.read_text())["weight_map"]
        return {name: directory / fname
                for name, fname in weight_map.items()}
    single = directory / "model.safetensors"
    if single.is_file():
        return {name: single for name in read_safetensors(single)}
    raise FileNotFoundError(
        f"no model.safetensors or model.safetensors.index.json under "
        f"{directory}")


def _tensor_reader(directory: Path):
    """name -> memmap-backed array across the checkpoint's files,
    with files opened lazily and shared by both model loaders."""
    files = _resolve_weight_files(directory)
    opened: dict[Path, dict[str, np.ndarray]] = {}

    def tensor(name: str) -> np.ndarray:
        try:
            path = files[name]
        except KeyError:
            raise KeyError(f"checkpoint is missing tensor {name!r}") \
                from None
        if path not in opened:
            opened[path] = read_safetensors(path)
        return opened[path][name]

    return tensor


def load_llama_checkpoint(directory: str | Path, *,
                          dtype: Any = None,
                          quantize: str | None = None,
                          max_seq: int | None = None,
                          ) -> tuple[dict, LlamaConfig]:
    """Load an HF-format Llama checkpoint directory into
    ``(params, LlamaConfig)`` ready for ``serving.glue.llama_engine``.

    ``dtype`` overrides the serving dtype (default: the config's,
    normally bfloat16); ``quantize="int8"``/``"int4"`` quantizes weight matrices
    on load so the full-precision pytree never resides in device
    memory; ``max_seq`` caps the KV capacity below the checkpoint's
    ``max_position_embeddings`` (a 128k cache would not fit one chip).
    """
    import jax.numpy as jnp

    directory = Path(directory)
    config = llama_config_from_hf(
        json.loads((directory / "config.json").read_text()))
    if max_seq is not None:
        # a cap, never a raise: positions past the trained context are
        # out-of-distribution RoPE the model has never seen
        config = config.scaled(max_seq=min(config.max_seq, max_seq))
    if dtype is not None:
        config = config.scaled(dtype=dtype)

    tensor = _tensor_reader(directory)

    if quantize not in (None, "int8", "int4"):
        raise ValueError(f"quantize must be None, 'int8' or 'int4', "
                         f"got {quantize!r}")
    if quantize is not None:
        from ..ops.quant import quantize_int4, quantize_int8
        qfn = quantize_int8 if quantize == "int8" else quantize_int4

    c = config
    # cast straight from the memmap into the serving dtype: a float32
    # detour would transiently double host RAM on a 16 GB checkpoint
    target = np.dtype(c.dtype)

    def to(a: np.ndarray, transpose: bool = False,
           quant_axis: int | None = None) -> Any:
        a = np.asarray(a).astype(target, copy=False)
        if transpose:
            a = a.T
        if quantize is not None and quant_axis is not None:
            # per-tensor quantize as each tensor lands on device: only
            # this one tensor is ever full-precision there, never the
            # whole tree (the point of quantize-on-LOAD)
            return qfn(jnp.asarray(a), axis=quant_axis)
        return jnp.asarray(a)

    def stack(key: str, suffix: str, transpose: bool) -> Any:
        rows = [np.asarray(tensor(f"model.layers.{i}.{suffix}"))
                .astype(target, copy=False)
                for i in range(c.n_layers)]
        if transpose:
            rows = [r.T for r in rows]
        stacked = np.stack(rows)  # the one full-size host copy
        # [L, in, out]: reduce the contraction axis (matches
        # ops.quant.quantize_llama_int8); norm gains stay exact
        quant_axis = None if key.endswith("_norm") else 1
        return to(stacked, quant_axis=quant_axis)

    params: dict = {
        # embed [V, D]: per-row scales serve gather AND the tied head
        "embed": to(tensor("model.embed_tokens.weight"), quant_axis=1),
        "layers": {key: stack(key, suffix, tr)
                   for key, suffix, tr in _LAYER_MAP},
        "final_norm": to(tensor("model.norm.weight")),
    }
    if not c.tie_embeddings:
        params["lm_head"] = to(tensor("lm_head.weight"), transpose=True,
                               quant_axis=0)
    return params, config


# ---------------------------------------------------------- whisper map
#
# HF "WhisperForConditionalGeneration" layout. Conv1d stores
# [out_channels, in_channels, kernel]; this repo's encoder convs are
# [kernel, in, out] (models/whisper.py:144) — axes reverse on the way
# through. Attention/MLP linears transpose like llama's. k_proj has no
# bias in every Whisper size; proj_out ties to the token embedding.

_WHISPER_BLOCK = (
    ("ln1_w", "self_attn_layer_norm.weight", False),
    ("ln1_b", "self_attn_layer_norm.bias", False),
    ("wq", "self_attn.q_proj.weight", True),
    ("bq", "self_attn.q_proj.bias", False),
    ("wk", "self_attn.k_proj.weight", True),
    ("wv", "self_attn.v_proj.weight", True),
    ("bv", "self_attn.v_proj.bias", False),
    ("wo", "self_attn.out_proj.weight", True),
    ("bo", "self_attn.out_proj.bias", False),
    ("ln_mlp_w", "final_layer_norm.weight", False),
    ("ln_mlp_b", "final_layer_norm.bias", False),
    ("fc1", "fc1.weight", True),
    ("fc1_b", "fc1.bias", False),
    ("fc2", "fc2.weight", True),
    ("fc2_b", "fc2.bias", False),
)
_WHISPER_CROSS = (
    ("lnx_w", "encoder_attn_layer_norm.weight", False),
    ("lnx_b", "encoder_attn_layer_norm.bias", False),
    ("xwq", "encoder_attn.q_proj.weight", True),
    ("xbq", "encoder_attn.q_proj.bias", False),
    ("xwk", "encoder_attn.k_proj.weight", True),
    ("xwv", "encoder_attn.v_proj.weight", True),
    ("xbv", "encoder_attn.v_proj.bias", False),
    ("xwo", "encoder_attn.out_proj.weight", True),
    ("xbo", "encoder_attn.out_proj.bias", False),
)


def whisper_config_from_hf(cfg: dict) -> "Any":
    from .whisper import WhisperConfig
    enc_heads = cfg.get("encoder_attention_heads", 8)
    dec_heads = cfg.get("decoder_attention_heads", enc_heads)
    if dec_heads != enc_heads:
        # the in-repo WhisperConfig models one head count (true for
        # every released Whisper size); a checkpoint that differs
        # would reshape q/k/v wrong and transcribe garbage silently
        raise ValueError(
            f"unsupported Whisper config: encoder_attention_heads="
            f"{enc_heads} != decoder_attention_heads={dec_heads}")
    return WhisperConfig(
        vocab_size=cfg["vocab_size"],
        n_mels=cfg.get("num_mel_bins", 80),
        dim=cfg["d_model"],
        n_heads=enc_heads,
        n_audio_layers=cfg["encoder_layers"],
        n_text_layers=cfg["decoder_layers"],
        audio_ctx=cfg.get("max_source_positions", 1500),
        audio_frames=2 * cfg.get("max_source_positions", 1500),
        text_ctx=cfg.get("max_target_positions", 448),
        sot_token=cfg.get("decoder_start_token_id", 50258),
        eot_token=cfg.get("eos_token_id", 50257),
    )


def whisper_config_to_hf(c: "Any") -> dict:
    return {
        "architectures": ["WhisperForConditionalGeneration"],
        "model_type": "whisper",
        "vocab_size": c.vocab_size,
        "num_mel_bins": c.n_mels,
        "d_model": c.dim,
        "encoder_attention_heads": c.n_heads,
        "decoder_attention_heads": c.n_heads,
        "encoder_layers": c.n_audio_layers,
        "decoder_layers": c.n_text_layers,
        "max_source_positions": c.audio_ctx,
        "max_target_positions": c.text_ctx,
        "decoder_start_token_id": c.sot_token,
        "eos_token_id": c.eot_token,
    }


def load_whisper_checkpoint(directory: str | Path, *,
                            dtype: Any = None) -> tuple[dict, "Any"]:
    """Load an HF-format Whisper checkpoint directory into
    ``(params, WhisperConfig)`` for ``models/whisper.py``'s
    transcription stack (the BASELINE Whisper-ASR config's
    real-weight path)."""
    import jax.numpy as jnp

    directory = Path(directory)
    config = whisper_config_from_hf(
        json.loads((directory / "config.json").read_text()))
    if dtype is not None:
        config = config.scaled(dtype=dtype)
    c = config
    target = np.dtype(c.dtype)
    tensor = _tensor_reader(directory)

    def to(name: str, transpose: bool = False) -> Any:
        a = np.asarray(tensor(name)).astype(target, copy=False)
        return jnp.asarray(a.T if transpose else a)

    def conv(name: str) -> Any:  # HF [out, in, k] -> ours [k, in, out]
        a = np.asarray(tensor(name)).astype(target, copy=False)
        return jnp.asarray(a.transpose(2, 1, 0))

    def stack(side: str, n_layers: int, entries) -> dict:
        out: dict = {}
        for key, suffix, transpose in entries:
            rows = [np.asarray(
                tensor(f"model.{side}.layers.{i}.{suffix}"))
                .astype(target, copy=False) for i in range(n_layers)]
            if transpose:
                rows = [r.T for r in rows]
            out[key] = jnp.asarray(np.stack(rows))
        return out

    params = {
        "conv1_w": conv("model.encoder.conv1.weight"),
        "conv1_b": to("model.encoder.conv1.bias"),
        "conv2_w": conv("model.encoder.conv2.weight"),
        "conv2_b": to("model.encoder.conv2.bias"),
        "enc_pos": to("model.encoder.embed_positions.weight"),
        "enc_layers": stack("encoder", c.n_audio_layers, _WHISPER_BLOCK),
        "enc_ln_w": to("model.encoder.layer_norm.weight"),
        "enc_ln_b": to("model.encoder.layer_norm.bias"),
        "embed": to("model.decoder.embed_tokens.weight"),
        "dec_pos": to("model.decoder.embed_positions.weight"),
        "dec_layers": stack("decoder", c.n_text_layers,
                            _WHISPER_BLOCK + _WHISPER_CROSS),
        "dec_ln_w": to("model.decoder.layer_norm.weight"),
        "dec_ln_b": to("model.decoder.layer_norm.bias"),
    }
    return params, config


def save_whisper_checkpoint(params: dict, config: "Any",
                            directory: str | Path) -> None:
    """Inverse of ``load_whisper_checkpoint`` (and its CI fixture
    generator)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    (directory / "config.json").write_text(
        json.dumps(whisper_config_to_hf(config), indent=1))
    bf16 = _bf16()

    def host(a: Any) -> np.ndarray:
        a = np.asarray(a)
        if a.dtype not in (np.float32, np.float16, bf16):
            a = a.astype(np.float32)
        return a

    tensors: dict[str, np.ndarray] = {
        "model.encoder.conv1.weight":
            host(params["conv1_w"]).transpose(2, 1, 0),
        "model.encoder.conv1.bias": host(params["conv1_b"]),
        "model.encoder.conv2.weight":
            host(params["conv2_w"]).transpose(2, 1, 0),
        "model.encoder.conv2.bias": host(params["conv2_b"]),
        "model.encoder.embed_positions.weight": host(params["enc_pos"]),
        "model.encoder.layer_norm.weight": host(params["enc_ln_w"]),
        "model.encoder.layer_norm.bias": host(params["enc_ln_b"]),
        "model.decoder.embed_tokens.weight": host(params["embed"]),
        "model.decoder.embed_positions.weight": host(params["dec_pos"]),
        "model.decoder.layer_norm.weight": host(params["dec_ln_w"]),
        "model.decoder.layer_norm.bias": host(params["dec_ln_b"]),
    }
    for side, n_layers, entries in (
            ("encoder", config.n_audio_layers, _WHISPER_BLOCK),
            ("decoder", config.n_text_layers,
             _WHISPER_BLOCK + _WHISPER_CROSS)):
        for key, suffix, transpose in entries:
            stacked = params[f"{'enc' if side == 'encoder' else 'dec'}"
                             f"_layers"][key]
            for i in range(n_layers):
                a = host(stacked[i])
                tensors[f"model.{side}.layers.{i}.{suffix}"] = \
                    a.T if transpose else a
    write_safetensors(directory / "model.safetensors", tensors,
                      metadata={"format": "pt"})


def save_llama_checkpoint(params: dict, config: LlamaConfig,
                          directory: str | Path) -> None:
    """Export a llama pytree as an HF-format checkpoint directory
    (config.json + model.safetensors) — the inverse of
    ``load_llama_checkpoint``, and the fixture generator for its CI."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    (directory / "config.json").write_text(
        json.dumps(llama_config_to_hf(config), indent=1))

    bf16 = _bf16()

    def host(a: Any, transpose: bool) -> np.ndarray:
        a = np.asarray(a)
        if a.dtype not in (np.float32, np.float16, bf16):
            a = a.astype(np.float32)
        return a.T if transpose else a

    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": host(params["embed"], False),
        "model.norm.weight": host(params["final_norm"], False),
    }
    for key, suffix, transpose in _LAYER_MAP:
        stacked = params["layers"][key]
        for i in range(config.n_layers):
            tensors[f"model.layers.{i}.{suffix}"] = host(
                stacked[i], transpose)
    if "lm_head" in params:
        tensors["lm_head.weight"] = host(params["lm_head"], True)
    write_safetensors(directory / "model.safetensors", tensors,
                      metadata={"format": "pt"})
