"""BERT-family encoder — the `/embed` endpoint model.

Bidirectional transformer encoder: learned position + segment
embeddings, post-LN blocks, GELU MLP, tanh pooler over [CLS].
Pure-functional with lax.scan over stacked layers like the Llama model.

Serves BASELINE.json config 2 (BERT-base /embed, single chip).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from ..ops.attention import xla_attention
from ..ops.norms import layer_norm


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    max_positions: int = 512
    type_vocab: int = 2
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def tiny(cls) -> "BertConfig":
        return cls(vocab_size=128, dim=32, n_layers=2, n_heads=2,
                   ffn_dim=64, max_positions=64, dtype=jnp.float32)

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()


def bert_init(key: jax.Array, config: BertConfig) -> dict:
    c = config
    ks = jax.random.split(key, 10)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(c.dtype)

    L = c.n_layers
    return {
        "word_embed": (jax.random.normal(ks[0], (c.vocab_size, c.dim),
                                         jnp.float32) * 0.02).astype(c.dtype),
        "pos_embed": (jax.random.normal(ks[1], (c.max_positions, c.dim),
                                        jnp.float32) * 0.02).astype(c.dtype),
        "type_embed": (jax.random.normal(ks[2], (c.type_vocab, c.dim),
                                         jnp.float32) * 0.02).astype(c.dtype),
        "embed_ln_w": jnp.ones((c.dim,), c.dtype),
        "embed_ln_b": jnp.zeros((c.dim,), c.dtype),
        "layers": {
            "wqkv": dense(ks[3], (L, c.dim, 3 * c.dim), c.dim),
            "wqkv_b": jnp.zeros((L, 3 * c.dim), c.dtype),
            "wo": dense(ks[4], (L, c.dim, c.dim), c.dim),
            "wo_b": jnp.zeros((L, c.dim), c.dtype),
            "ln1_w": jnp.ones((L, c.dim), c.dtype),
            "ln1_b": jnp.zeros((L, c.dim), c.dtype),
            "w1": dense(ks[5], (L, c.dim, c.ffn_dim), c.dim),
            "w1_b": jnp.zeros((L, c.ffn_dim), c.dtype),
            "w2": dense(ks[6], (L, c.ffn_dim, c.dim), c.ffn_dim),
            "w2_b": jnp.zeros((L, c.dim), c.dtype),
            "ln2_w": jnp.ones((L, c.dim), c.dtype),
            "ln2_b": jnp.zeros((L, c.dim), c.dtype),
        },
        "pooler_w": dense(ks[7], (c.dim, c.dim), c.dim),
        "pooler_b": jnp.zeros((c.dim,), c.dtype),
    }


def _b(bias):
    # explicit [1, 1, D] lift onto [B, S, D] activations: the test
    # harness runs jax_numpy_rank_promotion='raise'
    return bias.reshape(1, 1, -1)


def bert_encode(params: dict, tokens: jnp.ndarray, config: BertConfig, *,
                attention_mask: jnp.ndarray | None = None,
                token_types: jnp.ndarray | None = None
                ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """tokens [B, S] -> (hidden [B, S, D], pooled [B, D])."""
    c = config
    b, s = tokens.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), jnp.int32)
    if token_types is None:
        token_types = jnp.zeros((b, s), jnp.int32)

    x = (params["word_embed"][tokens]
         + params["pos_embed"][jnp.arange(s)][None]
         + params["type_embed"][token_types])
    x = layer_norm(x, params["embed_ln_w"], params["embed_ln_b"], c.norm_eps)

    lengths = attention_mask.sum(axis=-1).astype(jnp.int32)

    def layer_fn(x, lp):
        qkv = x @ lp["wqkv"] + _b(lp["wqkv_b"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, c.n_heads, c.head_dim)
        k = k.reshape(b, s, c.n_heads, c.head_dim)
        v = v.reshape(b, s, c.n_heads, c.head_dim)
        attn = xla_attention(q, k, v, causal=False, kv_lengths=lengths)
        attn = attn.reshape(b, s, c.dim) @ lp["wo"] + _b(lp["wo_b"])
        x = layer_norm(x + attn, lp["ln1_w"], lp["ln1_b"], c.norm_eps)
        h = jax.nn.gelu((x @ lp["w1"] + _b(lp["w1_b"])).astype(jnp.float32))
        h = h.astype(x.dtype) @ lp["w2"] + _b(lp["w2_b"])
        x = layer_norm(x + h, lp["ln2_w"], lp["ln2_b"], c.norm_eps)
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    pooled = jnp.tanh((x[:, 0] @ params["pooler_w"] + params["pooler_b"][None, :])
                      .astype(jnp.float32)).astype(c.dtype)
    return x, pooled


def mean_pool_embed(hidden: jnp.ndarray, attention_mask: jnp.ndarray
                    ) -> jnp.ndarray:
    """Masked mean pooling -> L2-normalized sentence embeddings [B, D]."""
    mask = attention_mask[..., None].astype(jnp.float32)
    h = hidden.astype(jnp.float32)
    summed = (h * mask).sum(axis=1)
    counts = jnp.maximum(mask.sum(axis=1), 1.0)
    emb = summed / counts
    return emb / jnp.maximum(jnp.linalg.norm(emb, axis=-1, keepdims=True), 1e-9)
