"""In-process cron scheduler.

Mirrors reference pkg/gofr/cron.go: 5-field (min hour dom mon dow) or
6-field (leading seconds) schedules parsed into match sets
(cron.go:16-25), a ticker loop that fires matching jobs each tick in
their own task with a fresh context and panic recovery (cron.go:69-73),
registered via ``app.add_cron_job`` (gofr.go:287).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Callable

from .context import Context


class CronParseError(ValueError):
    pass


# field bounds: sec min hour dom mon dow (5-field specs get sec=0 prepended)
_FIELD_RANGES = [(0, 59), (0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]


def _parse_field(spec: str, lo: int, hi: int) -> frozenset[int]:
    values: set[int] = set()
    for part in spec.split(","):
        part = part.strip()
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError as exc:
                raise CronParseError(f"bad step {step_s!r}") from exc
            if step < 1:
                raise CronParseError(f"bad step {step}")
        if part in ("*", ""):
            lo2, hi2 = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            try:
                lo2, hi2 = int(a), int(b)
            except ValueError as exc:
                raise CronParseError(f"bad range {part!r}") from exc
        else:
            try:
                lo2 = hi2 = int(part)
            except ValueError as exc:
                raise CronParseError(f"bad value {part!r}") from exc
        if lo2 < lo or hi2 > hi or lo2 > hi2:
            raise CronParseError(f"value {part!r} outside {lo}-{hi}")
        values.update(range(lo2, hi2 + 1, step))
    return frozenset(values)


@dataclass
class Schedule:
    seconds: frozenset[int]
    minutes: frozenset[int]
    hours: frozenset[int]
    days: frozenset[int]
    months: frozenset[int]
    weekdays: frozenset[int]

    @classmethod
    def parse(cls, spec: str) -> "Schedule":
        fields = spec.split()
        if len(fields) == 5:
            fields = ["0"] + fields  # fire at second 0 of matching minutes
        if len(fields) != 6:
            raise CronParseError(
                f"schedule needs 5 or 6 fields, got {len(fields)}: {spec!r}")
        parsed = [_parse_field(f, lo, hi)
                  for f, (lo, hi) in zip(fields, _FIELD_RANGES)]
        return cls(*parsed)

    def matches(self, t: time.struct_time) -> bool:
        return (t.tm_sec in self.seconds
                and t.tm_min in self.minutes
                and t.tm_hour in self.hours
                and t.tm_mday in self.days
                and t.tm_mon in self.months
                and t.tm_wday in self._py_weekdays())

    def _py_weekdays(self) -> frozenset[int]:
        # cron: 0=Sunday; python struct_time: 0=Monday
        return frozenset((d - 1) % 7 for d in self.weekdays)


@dataclass
class Job:
    name: str
    schedule: Schedule
    fn: Callable


class _TickRequest:
    """Context 'request' for cron jobs (implements the Request protocol)."""

    def __init__(self, name: str) -> None:
        self.name = name

    def param(self, key: str) -> str:
        return ""

    def params(self, key: str) -> list[str]:
        return []

    def path_param(self, key: str) -> str:
        return ""

    def bind(self, target=None):
        return None

    def host_name(self) -> str:
        return "cron"


class Cron:
    """1-second ticker; each matching job runs as its own task."""

    def __init__(self, container) -> None:
        self.container = container
        self.jobs: list[Job] = []
        self._tasks: set = set()

    def add(self, spec: str, name: str, fn: Callable) -> None:
        self.jobs.append(Job(name=name, schedule=Schedule.parse(spec), fn=fn))

    async def run(self) -> None:
        last_tick = int(time.time())
        while True:
            await asyncio.sleep(0.25)
            now = int(time.time())
            # fire each whole second exactly once, catching up if late
            for sec in range(last_tick + 1, now + 1):
                t = time.localtime(sec)
                for job in self.jobs:
                    if job.schedule.matches(t):
                        task = asyncio.ensure_future(self._run_job(job))
                        self._tasks.add(task)
                        task.add_done_callback(self._tasks.discard)
            last_tick = now

    async def _run_job(self, job: Job) -> None:
        ctx = Context(request=_TickRequest(job.name), container=self.container)
        try:
            result = job.fn(ctx)
            if hasattr(result, "__await__"):
                await result
        except Exception as exc:  # panic recovery per job
            self.container.logger.error(f"cron job {job.name!r} failed: {exc!r}")
