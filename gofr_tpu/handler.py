"""Handler execution: the HTTP hot path.

Mirrors reference pkg/gofr/handler.go:55-113: build a Context, run the
user handler under a request timeout with panic recovery, distinguish
timeout (408) from handler error, then render through the Responder.
Async-native: async handlers run on the loop; sync handlers are pushed
to a thread so they cannot stall the serving event loop (the goroutine
race of the reference mapped onto asyncio).
"""

from __future__ import annotations

import asyncio
import inspect
import json
import traceback
from typing import Any, Callable

from .container.container import Container
from .context import Context
from .http.errors import (
    ErrorInvalidRoute,
    ErrorMethodNotAllowed,
    ErrorPanicRecovery,
    ErrorRequestTimeout,
    status_and_level_for,
)
from .http.request import BindError, HTTPRequest
from .http.responder import Responder, ResponseData
from .http.router import Router

HandlerFunc = Callable[[Context], Any]

_FAVICON = bytes.fromhex(
    "89504e470d0a1a0a0000000d494844520000001000000010080600000028cf"
    "6282000000264944415478da63fcffff3f0335803851f9ff47cd1c3573d4cc"
    "51334733473523cd0400ba573b7e1c9b8e1a0000000049454e44ae426082")


async def run_handler(handler: HandlerFunc, ctx: Context,
                      timeout: float | None = None) -> Any:
    """Run a user handler (sync or async) with an optional timeout."""
    if inspect.iscoroutinefunction(handler):
        coro = handler(ctx)
    else:
        loop = asyncio.get_running_loop()
        # copy_context so contextvars (trace ids for logging) survive the
        # hop into the worker thread
        import contextvars
        cvs = contextvars.copy_context()
        coro = loop.run_in_executor(None, cvs.run, handler, ctx)
    if timeout is not None and timeout > 0:
        return await asyncio.wait_for(coro, timeout)
    return await coro


def build_core_handler(router: Router, container: Container,
                       request_timeout: float | None = None) -> Callable:
    """The innermost server handler: route -> context -> execute -> respond."""
    responder = Responder()

    async def core(request: HTTPRequest) -> ResponseData:
        matched = router.match(request.method, request.path)

        # static mounts serve paths no dynamic route claims
        # (reference gofr.go:314-339); dynamic routes win on overlap so a
        # '/' mount cannot shadow the API. A mount's own favicon.ico wins
        # over the built-in placeholder; a mount 404 for /favicon.ico
        # falls through to the placeholder.
        if matched is None:
            static = router.match_static(request.path)
            is_favicon = (request.path == "/favicon.ico"
                          and request.method in ("GET", "HEAD"))
            if static is not None and not (is_favicon and static[0] != "200"):
                status, content, ctype = static
                return ResponseData(status=int(status), body=content,
                                    content_type=ctype)
            if is_favicon:
                return ResponseData(status=200, body=_FAVICON,
                                    content_type="image/png")

        if matched is None:
            methods = router.registered_methods_for(request.path)
            if methods:  # path exists with other verbs -> 405
                err = ErrorMethodNotAllowed()
                response = responder.respond(None, err, request.method)
                response.headers["Allow"] = ", ".join(methods)
                return response
            # catch-all 404 listing registered routes (reference handler.go:137)
            err = ErrorInvalidRoute()
            response = responder.respond(None, err, request.method)
            body = json.loads(response.body)
            body["error"]["registered_routes"] = router.registered_paths()
            response.body = json.dumps(body).encode()
            return response

        route, path_params = matched
        request.set_path_params(path_params)
        # metrics middleware labels by route pattern, not raw path,
        # to keep label cardinality bounded
        request.matched_pattern = route.pattern
        ctx = Context(request=request, container=container)
        auth_info = getattr(request, "auth_info", None)
        if auth_info:  # set by auth middleware (reference context.go:121)
            ctx.set_auth_info(auth_info)

        try:
            result = await run_handler(route.handler, ctx, request_timeout)
            error = None
        except asyncio.TimeoutError:
            result, error = None, ErrorRequestTimeout()
        except asyncio.CancelledError:
            raise
        except BindError as exc:
            result, error = None, exc
        except Exception as exc:  # panic recovery (reference handler.go:141)
            result, error = None, exc
            if not hasattr(exc, "status_code"):
                container.logger.error(
                    f"panic in handler {request.method} {request.path}: {exc!r}",
                    stack=traceback.format_exc())
                error = ErrorPanicRecovery()

        if error is not None:
            _, level = status_and_level_for(error)
            ctx.logger.log_at(level, f"{request.method} {request.path}: {error}")
        return responder.respond(result, error, request.method)

    return core
