"""CQL native protocol v4: client against the mini server — real
frames over TCP, verified PlainText auth, typed row decode."""

import pytest

from gofr_tpu.datasource.cassandra_wire import (
    CassandraWire, CassandraWireError, MiniCassandraServer, cql_literal,
    expand_qmarks)


@pytest.fixture(scope="module")
def server():
    srv = MiniCassandraServer(keyspace="ks", user="cassandra",
                              password="cassandra")
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def db(server):
    c = CassandraWire(host="127.0.0.1", port=server.port, keyspace="ks",
                      username="cassandra", password="cassandra")
    c.connect()
    yield c
    c.close()


def test_query_roundtrip_with_typed_columns(db):
    db.exec("CREATE TABLE IF NOT EXISTS readings "
            "(id INTEGER, temp REAL, raw BLOB, label TEXT)")
    db.exec("DELETE FROM readings")
    db.exec("INSERT INTO readings VALUES (?, ?, ?, ?)",
            1, 21.5, b"\x01\x02", "lab")
    rows = db.query("SELECT id, temp, raw, label FROM readings")
    assert rows == [{"id": 1, "temp": 21.5, "raw": b"\x01\x02",
                     "label": "lab"}]
    # ints ride as bigint (8-byte), floats as double — both exact
    assert isinstance(rows[0]["id"], int)
    assert isinstance(rows[0]["temp"], float)


def test_null_values(db):
    db.exec("CREATE TABLE IF NOT EXISTS t_null (id INTEGER, v TEXT)")
    db.exec("DELETE FROM t_null")
    db.exec("INSERT INTO t_null VALUES (?, ?)", 1, None)
    assert db.query("SELECT v FROM t_null")[0]["v"] is None


def test_batch_executes_atomically(db):
    db.exec("CREATE TABLE IF NOT EXISTS t_batch (id INTEGER)")
    db.exec("DELETE FROM t_batch")
    db.new_batch("b1")
    db.batch_query("b1", "INSERT INTO t_batch VALUES (?)", 1)
    db.batch_query("b1", "INSERT INTO t_batch VALUES (?)", 2)
    db.execute_batch("b1")
    assert len(db.query("SELECT * FROM t_batch")) == 2
    # a failing statement rolls the whole batch back
    db.new_batch("b2")
    db.batch_query("b2", "INSERT INTO t_batch VALUES (?)", 3)
    db.batch_query("b2", "INSERT INTO no_such_table VALUES (1)")
    with pytest.raises(CassandraWireError):
        db.execute_batch("b2")
    assert len(db.query("SELECT * FROM t_batch")) == 2


def test_error_frame_carries_code_and_message(db):
    with pytest.raises(CassandraWireError) as exc:
        db.query("SELECT * FROM missing_table")
    assert "missing_table" in str(exc.value) or "no such table" \
        in str(exc.value)
    assert exc.value.code != 0
    # connection survives the error
    db.exec("CREATE TABLE IF NOT EXISTS t_ok (id INTEGER)")
    assert db.health_check()["status"] == "UP"


def test_wrong_password_rejected(server):
    bad = CassandraWire(host="127.0.0.1", port=server.port,
                        username="cassandra", password="WRONG")
    with pytest.raises(CassandraWireError, match="credentials"):
        bad.connect()


def test_no_auth_server_sends_ready():
    srv = MiniCassandraServer()
    srv.start()
    try:
        c = CassandraWire(host="127.0.0.1", port=srv.port)
        c.connect()
        assert c.health_check()["status"] == "UP"
        c.close()
    finally:
        srv.close()


def test_literals_and_qmark_expansion():
    assert cql_literal(None) == "NULL"
    assert cql_literal(True) == "true"
    assert cql_literal(b"\xbe\xef") == "0xbeef"
    assert cql_literal("o'brien") == "'o''brien'"
    assert expand_qmarks("SELECT 'a?b' WHERE x = ?", (1,)) \
        == "SELECT 'a?b' WHERE x = 1"
    with pytest.raises(CassandraWireError):
        expand_qmarks("SELECT ?", ())


def test_health_down_when_unreachable():
    c = CassandraWire(host="127.0.0.1", port=1)
    assert c.health_check()["status"] == "DOWN"
