"""SFTP over the from-spec SSH2 transport: full-stack wire tests —
curve25519 kex, aes128-ctr + hmac-sha2-256, password auth, channels,
SFTP v3 — against the mini SSH server."""

import io

import pytest

from gofr_tpu.datasource.sftp_wire import MiniSFTPServer, SFTPError, SFTPWire
from gofr_tpu.datasource.ssh_transport import SSHAuthError, SSHError


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    root = tmp_path_factory.mktemp("sftp_root")
    srv = MiniSFTPServer(root, users={"app": "s3cr3t"})
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def fs(server):
    client = SFTPWire(host="127.0.0.1", port=server.port,
                      username="app", password="s3cr3t",
                      expected_host_key=server.host_public_key())
    client.connect()
    yield client
    client.close()


def test_create_read_roundtrip(fs):
    fs.create("hello.txt", "hello over ssh\n")
    assert fs.read_text("hello.txt") == "hello over ssh\n"
    payload = bytes(range(256)) * 512  # 128 KB: multiple READ/WRITE chunks
    fs.create("blob.bin", payload)
    assert fs.read("blob.bin") == payload


def test_append_stat_exists(fs):
    fs.create("log.txt", "one\n")
    fs.append("log.txt", "two\n")
    assert fs.read_text("log.txt") == "one\ntwo\n"
    info = fs.stat("log.txt")
    assert info.size == 8 and not info.is_dir and info.mod_time > 0
    assert fs.exists("log.txt") is True
    assert fs.exists("nope.txt") is False


def test_mkdir_readdir_rename_remove(fs):
    fs.mkdir("data")
    fs.create("data/a.csv", "x,y\n1,2\n")
    fs.create("data/b.csv", "x,y\n3,4\n")
    names = [f.name for f in fs.read_dir("data")]
    assert names == ["a.csv", "b.csv"]
    root_entries = {f.name: f for f in fs.read_dir("/")}
    assert root_entries["data"].is_dir
    fs.rename("data/a.csv", "data/renamed.csv")
    assert fs.exists("data/renamed.csv") and not fs.exists("data/a.csv")
    rows = list(fs.read_rows("data/renamed.csv"))
    assert rows == [{"x": "1", "y": "2"}]
    fs.remove("data/renamed.csv")
    fs.remove("data/b.csv")
    fs.rmdir("data")
    assert not fs.exists("data")


def test_missing_file_errors(fs):
    with pytest.raises(SFTPError, match="no such file"):
        fs.read("missing.bin")
    with pytest.raises(SFTPError):
        fs.remove("missing.bin")
    with pytest.raises(SFTPError):
        fs.stat("missing.bin")


def test_path_jail(fs, server):
    fs.create("../escape.txt", "jailed")  # normalized inside the root
    assert (server.root / "escape.txt").exists()
    assert not (server.root.parent / "escape.txt").exists()
    fs.remove("escape.txt")


def test_wrong_password_rejected(server):
    bad = SFTPWire(host="127.0.0.1", port=server.port,
                   username="app", password="WRONG",
                   insecure_skip_host_key=True)
    with pytest.raises(SSHAuthError):
        bad.connect()


def test_host_key_pinning_detects_mitm(server):
    pinned = SFTPWire(host="127.0.0.1", port=server.port,
                      username="app", password="s3cr3t",
                      expected_host_key=b"\x00" * 32)
    with pytest.raises(SSHError, match="host key mismatch"):
        pinned.connect()


def test_no_host_key_policy_refused(server):
    """x/crypto/ssh-style contract: connecting without a pinned host
    key requires an explicit insecure opt-in."""
    lax = SFTPWire(host="127.0.0.1", port=server.port,
                   username="app", password="s3cr3t")
    with pytest.raises(SSHError, match="host key policy"):
        lax.connect()


def test_paramiko_style_aliases(fs):
    fs.putfo(io.BytesIO(b"injected"), "via_putfo.bin")
    buf = io.BytesIO()
    fs.getfo("via_putfo.bin", buf)
    assert buf.getvalue() == b"injected"
    assert "via_putfo.bin" in fs.listdir("/")
    fs.remove("via_putfo.bin")


def test_injected_into_existing_sftp_filesystem(server):
    """The previously injection-only SFTPFileSystem accepts this
    native client (ftp.py's paramiko-style contract)."""
    from gofr_tpu.datasource.ftp import SFTPFileSystem

    wire = SFTPWire(host="127.0.0.1", port=server.port,
                    username="app", password="s3cr3t",
                    insecure_skip_host_key=True)
    wire.connect()
    fs = SFTPFileSystem(client=wire)
    fs.connect()
    fs.create("nested.txt", "through the adapter")
    assert fs.read("nested.txt") == b"through the adapter"
    assert "nested.txt" in [f.name for f in fs.read_dir("/")]
    fs.remove("nested.txt")
    wire.close()


def test_health(fs):
    assert fs.health_check()["status"] == "UP"
    assert SFTPWire(host="127.0.0.1", port=1).health_check()["status"] \
        == "DOWN"
