"""Every example boots for real and answers over localhost — the
reference's per-example ``main_test.go`` pattern (SURVEY §4.3)."""

import asyncio
import importlib.util
import sys
import time
from pathlib import Path

import pytest

from gofr_tpu.config import DictConfig

from .apputil import AppRunner, grpc_channel

EXAMPLES = Path(__file__).parent.parent / "examples"


def load_example(name: str):
    """Import examples/<name>/main.py as a unique module."""
    path = EXAMPLES / name / "main.py"
    spec = importlib.util.spec_from_file_location(
        f"example_{name.replace('-', '_')}", path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


def cfg(**kw) -> DictConfig:
    return DictConfig({"HTTP_PORT": "0", "METRICS_PORT": "0",
                       "APP_NAME": "example", **kw})


def test_every_reference_example_has_a_counterpart():
    reference_examples = {
        "http-server", "http-server-using-redis", "sample-cmd",
        "using-add-filestore", "using-add-rest-handlers",
        "using-cron-jobs", "using-custom-metrics", "using-file-bind",
        "using-html-template", "using-http-auth-middleware",
        "using-http-service", "using-migrations", "using-publisher",
        "using-subscriber", "using-web-socket",
    }
    ours = {p.name for p in EXAMPLES.iterdir() if p.is_dir()}
    missing = reference_examples - ours
    assert not missing, f"examples missing vs reference: {missing}"
    assert "grpc-server" in ours      # reference examples/grpc analog
    assert {"model-serving", "asr-worker"} <= ours  # TPU-native


def test_http_server():
    mod = load_example("http-server")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        status, body = runner.get_json("/greet?name=tpu")
        assert (status, body["data"]) == (200, "Hello tpu!")
        status, body = runner.get_json("/users/1")
        assert body["data"]["name"] == "ada"
        status, _, data = runner.request("POST", "/users",
                                         {"name": "alan"})
        assert status == 201
        status, body = runner.get_json("/users/99")
        assert status == 404 and "error" in body


def test_http_server_using_redis():
    mod = load_example("http-server-using-redis")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        for _ in range(3):
            runner.request("POST", "/visit/home")
        status, body = runner.get_json("/visit/home")
        assert body["data"]["visits"] == 3


def test_sample_cmd(capsys):
    mod = load_example("sample-cmd")
    app = mod.build_app()
    assert app.run(["greet", "--name=tpu"]) == 0
    assert "hello tpu" in capsys.readouterr().out
    assert app.run(["greet", "--name=tpu", "--shout"]) == 0
    assert "HELLO TPU" in capsys.readouterr().out
    assert app.run(["version"]) == 0
    assert app.run(["nope"]) == 2  # unknown -> help + exit 2


def test_using_add_filestore():
    mod = load_example("using-add-filestore")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        runner.request("POST", "/notes/ideas", {"text": "pallas kernels"})
        status, body = runner.get_json("/notes/ideas")
        assert body["data"]["text"] == "pallas kernels"
        status, body = runner.get_json("/notes")
        assert "ideas.txt" in body["data"]


def test_using_add_rest_handlers():
    mod = load_example("using-add-rest-handlers")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        status, _, _ = runner.request(
            "POST", "/book", {"id": 1, "title": "scaling", "author": "jax"})
        assert status == 201
        status, body = runner.get_json("/book/1")
        assert body["data"]["title"] == "scaling"
        status, _, _ = runner.request("PUT", "/book/1",
                                      {"title": "scaling v2", "author": "jax"})
        assert status == 200
        status, body = runner.get_json("/book")
        assert len(body["data"]) == 1
        status, _, _ = runner.request("DELETE", "/book/1")
        assert status == 204


def test_using_cron_jobs():
    mod = load_example("using-cron-jobs")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        status, body = runner.get_json("/runs")
        assert status == 200
        assert "runs" in body["data"]  # job registered; fires on minute tick


def test_using_custom_metrics():
    mod = load_example("using-custom-metrics")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        runner.request("POST", "/order", {"amount": 42})
        status, _, data = runner.request("GET", "/metrics",
                                         port=runner.metrics_port)
        scrape = data.decode()
        assert "orders_created" in scrape
        assert "order_amount" in scrape
        assert "inventory_level" in scrape


def test_using_file_bind():
    mod = load_example("using-file-bind")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        boundary = "xyzBOUNDARY"
        body = (f"--{boundary}\r\n"
                'Content-Disposition: form-data; name="title"\r\n\r\n'
                "report\r\n"
                f"--{boundary}\r\n"
                'Content-Disposition: form-data; name="doc"; '
                'filename="r.txt"\r\n'
                "Content-Type: text/plain\r\n\r\n"
                "hello bytes\r\n"
                f"--{boundary}--\r\n")
        status, _, data = runner.request(
            "POST", "/upload", body,
            headers={"Content-Type":
                     f"multipart/form-data; boundary={boundary}"})
        assert status == 201
        import json
        out = json.loads(data)["data"]
        assert out["title"] == "report"
        assert out["doc"] == {"filename": "r.txt", "bytes": 11}


def test_using_html_template(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # templates materialize under tmp
    mod = load_example("using-html-template")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        status, _, data = runner.request("GET", "/hello?name=tpu")
        assert status == 200
        assert b"<h1>Hello tpu</h1>" in data


def test_using_http_auth_middleware():
    import base64
    mod = load_example("using-http-auth-middleware")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        status, _, _ = runner.request("GET", "/secret")
        assert status == 401
        creds = base64.b64encode(b"ada:lovelace").decode()
        status, _, data = runner.request(
            "GET", "/secret", headers={"Authorization": f"Basic {creds}"})
        assert status == 200
        # health stays open without credentials
        status, _, _ = runner.request("GET", "/.well-known/alive")
        assert status == 200


def test_using_http_service():
    mod = load_example("using-http-service")
    # a real downstream app
    from gofr_tpu.app import App
    downstream = App(config=cfg())

    @downstream.get("/items/{id}")
    def item(ctx):
        return {"id": ctx.path_param("id"), "price": 9.5}

    with AppRunner(app=downstream) as down:
        app = mod.build_app(cfg(),
                            downstream_url=f"http://127.0.0.1:{down.port}")
        with AppRunner(app=app) as runner:
            status, body = runner.get_json("/proxy/tpu")
            assert status == 200
            assert body["data"]["data"]["id"] == "tpu"


def test_using_migrations():
    mod = load_example("using-migrations")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        status, body = runner.get_json("/employees")
        assert [r["name"] for r in body["data"]] == ["ada", "grace"]
        # ledger recorded both versions
        rows = runner.app.container.sql.query(
            "SELECT version FROM gofr_migrations ORDER BY version")
        assert len(rows) == 2


def test_publisher_and_subscriber_pair():
    # apps run in separate event loops, so share a real broker over TCP
    # (the in-memory broker's queues are loop-bound)
    import threading
    ready = threading.Event()
    holder = {}

    def run_broker():
        async def main():
            from gofr_tpu.pubsub.nats import MiniNATSServer
            server = MiniNATSServer()
            await server.start()
            holder["port"] = server.port
            ready.set()
            await asyncio.Event().wait()
        asyncio.run(main())

    threading.Thread(target=run_broker, daemon=True).start()
    assert ready.wait(5)
    nats_cfg = {"PUBSUB_BACKEND": "NATS",
                "PUBSUB_BROKER": f"127.0.0.1:{holder['port']}"}

    pub_mod = load_example("using-publisher")
    sub_mod = load_example("using-subscriber")
    sub_app = sub_mod.build_app(cfg(**nats_cfg))
    pub_app = pub_mod.build_app(cfg(**nats_cfg))
    sub_mod.SEEN.clear()
    with AppRunner(app=sub_app):
        with AppRunner(app=pub_app) as pub:
            status, _, _ = pub.request("POST", "/publish/order",
                                       {"id": 7, "item": "tpu"})
            assert status == 201
            deadline = time.time() + 5
            while not sub_mod.SEEN and time.time() < deadline:
                time.sleep(0.02)
            assert sub_mod.SEEN == [{"id": 7, "item": "tpu"}]


def test_using_web_socket():
    mod = load_example("using-web-socket")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        from gofr_tpu.websocket import connect

        async def flow():
            conn = await connect(f"ws://127.0.0.1:{runner.port}/ws/echo")
            await conn.send("ping")
            reply = await conn.recv()
            await conn.close()
            return reply.text()
        reply = asyncio.run(flow())
        import json
        assert json.loads(reply) == {"echo": "ping"}


def test_grpc_server():
    mod = load_example("grpc-server")
    app = mod.build_app(cfg(GRPC_PORT="0"))
    with AppRunner(app=app) as runner:
        from gofr_tpu.grpc import GRPCClient

        async def flow():
            client = GRPCClient(f"127.0.0.1:{app.grpc_server.bound_port}")
            reply = await client.call("examples.Greeter", "SayHello",
                                      {"name": "tpu"})
            ticks = []
            async for item in client.stream(
                    "examples.Greeter", "Countdown", {"from": 2}):
                ticks.append(item["t_minus"])
            await client.close()
            return reply, ticks
        reply, ticks = asyncio.run(flow())
        assert reply["message"] == "Hello tpu!"
        assert ticks == [2, 1]


def test_grpc_protogen_example():
    """The protogen example: .proto → generated skeleton → served app,
    called through the generated client."""
    mod = load_example("grpc-protogen")
    app = mod.build_app(cfg(GRPC_PORT="0"))
    with AppRunner(app=app):
        import grpc

        import order_gofr

        async def flow():
            async with grpc_channel(
                    app.grpc_server.bound_port) as channel:
                client = order_gofr.OrderDeskClient(channel)
                ack = await client.Place(order_gofr.Order(
                    id="o-7", item="tpu", quantity=2))
                ack = ack.get("data", ack)
                statuses = []
                async for item in client.Track(
                        order_gofr.Order(id="o-7")):
                    statuses.append(item.get("data", item)["status"])
                return ack, statuses
        ack, statuses = asyncio.run(flow())
        assert ack["status"] == "ACCEPTED"
        assert statuses == ["ACCEPTED", "PACKED", "SHIPPED"]


def test_grpc_client_example():
    """The client example drives the server example end-to-end: HTTP
    in, gRPC out (unary + stream + health)."""
    server_mod = load_example("grpc-server")
    server_app = server_mod.build_app(cfg(GRPC_PORT="0"))
    with AppRunner(app=server_app):
        target = f"127.0.0.1:{server_app.grpc_server.bound_port}"
        client_mod = load_example("grpc-client")
        client_app = client_mod.build_app(cfg(), grpc_target=target)
        with AppRunner(app=client_app) as front:
            status, body = front.get_json("/hello?name=mesh")
            assert status == 200
            assert body["data"]["message"] == "Hello mesh!"
            status, body = front.get_json("/countdown?from=2")
            assert [m["t_minus"] for m in body["data"]["messages"]] \
                == [2, 1]
            status, body = front.get_json("/downstream-health")
            assert body["data"]["status"] == "SERVING"


def test_multi_host_serving_example():
    mod = load_example("multi-host-serving")
    app = mod.build_app(cfg())
    with AppRunner(app=app) as runner:
        w1 = mod.run_worker(f"http://127.0.0.1:{runner.port}", "h1")
        w2 = mod.run_worker(f"http://127.0.0.1:{runner.port}", "h2")
        try:
            status, body = runner.get_json("/control/topology")
            assert status == 200
            assert body["data"]["world_size"] == 2
            assert w1.assignment.rank == 0
            assert w2.assignment.rank == 1
        finally:
            w1.stop()
            w2.stop()


def test_model_serving():
    mod = load_example("model-serving")
    with AppRunner(app=mod.build_app(cfg())) as runner:
        status, _, data = runner.request(
            "POST", "/chat",
            {"prompt": "hi", "max_new_tokens": 4, "temperature": 0.0})
        assert status in (200, 201)
        import json
        out = json.loads(data)["data"]
        assert "text" in out or "tokens" in out
        # engine visible in health
        status, body = runner.get_json("/.well-known/health")
        assert "tpu" in body["data"]["checks"]


def test_model_serving_from_disk_checkpoint(tmp_path):
    """MODEL_PATH: the example boots from an on-disk HF-format
    checkpoint (weights + tokenizer.json) and serves /chat and /v1
    with the loaded weights (VERDICT r4 #3 done-bar)."""
    import json

    import jax

    from gofr_tpu.models.hf_checkpoint import save_llama_checkpoint
    from gofr_tpu.models.llama import LlamaConfig, llama_init

    cfg_t = LlamaConfig.tiny()
    save_llama_checkpoint(llama_init(jax.random.key(3), cfg_t), cfg_t,
                          tmp_path)
    from .test_hf_checkpoint import _mini_tokenizer_json
    _mini_tokenizer_json(tmp_path)

    mod = load_example("model-serving")
    app = mod.build_app(cfg(MODEL_PATH=str(tmp_path),
                            MODEL_MAX_SEQ="128"))
    with AppRunner(app=app) as runner:
        status, _, data = runner.request(
            "POST", "/chat",
            {"prompt": "the cat", "max_new_tokens": 4,
             "temperature": 0.0})
        assert status in (200, 201)
        out = json.loads(data)["data"]
        assert "text" in out or "tokens" in out
        # the OpenAI surface runs the HF tokenizer loaded from disk
        status, _, data = runner.request(
            "POST", "/v1/completions",
            {"model": tmp_path.name, "prompt": "the cat",
             "max_tokens": 4, "temperature": 0.0})
        assert status in (200, 201)
        body = json.loads(data)
        assert body["model"] == tmp_path.name
        assert body["choices"][0]["text"] is not None


def test_asr_worker():
    import numpy as np
    mod = load_example("asr-worker")
    app = mod.build_app(cfg())
    with AppRunner(app=app) as runner:
        tone = np.sin(np.linspace(0, 440, 4000)).astype(np.float32)
        status, _, data = runner.request("POST", "/transcribe",
                                         {"audio": tone.tolist()})
        assert status == 201
        import json
        assert "tokens" in json.loads(data)["data"]


def test_asr_worker_from_disk_checkpoint(tmp_path):
    """MODEL_PATH: the ASR worker transcribes with weights loaded
    from an on-disk HF-format Whisper checkpoint."""
    import json

    import jax
    import numpy as np

    from gofr_tpu.models.hf_checkpoint import save_whisper_checkpoint
    from gofr_tpu.models.whisper import WhisperConfig, whisper_init

    cfg_w = WhisperConfig.tiny_test()
    save_whisper_checkpoint(whisper_init(jax.random.key(2), cfg_w),
                            cfg_w, tmp_path)
    mod = load_example("asr-worker")
    app = mod.build_app(cfg(MODEL_PATH=str(tmp_path)))
    with AppRunner(app=app) as runner:
        tone = np.sin(np.linspace(0, 440, 4000)).astype(np.float32)
        status, _, data = runner.request("POST", "/transcribe",
                                         {"audio": tone.tolist()})
        assert status == 201
        assert "tokens" in json.loads(data)["data"]
