"""NATS and MQTT wire-protocol backends against their in-process mini
servers — the broker analog of the reference's miniredis-style tests
(SURVEY §4): the real client bytes go over a real TCP socket.
"""

import asyncio

import functools

import pytest

from gofr_tpu.config.env import DictConfig
from gofr_tpu.container.container import Container
from gofr_tpu.pubsub.mqtt import (MiniMQTTBroker, MQTTClient, encode_varint,
                                  topic_matches)
from gofr_tpu.pubsub.nats import MiniNATSServer, NATSClient, subject_matches


def async_test(fn):
    """No pytest-asyncio in the image; run coroutine tests via asyncio.run
    (the repo-wide pattern, cf. tests/test_pubsub.py)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))
    return wrapper


# ------------------------------------------------------------------ matching
@pytest.mark.parametrize("pattern,subject,ok", [
    ("orders.created", "orders.created", True),
    ("orders.*", "orders.created", True),
    ("orders.*", "orders.created.eu", False),
    ("orders.>", "orders.created.eu", True),
    (">", "anything.at.all", True),
    ("orders.created", "orders", False),
])
def test_nats_subject_matching(pattern, subject, ok):
    assert subject_matches(pattern, subject) is ok


@pytest.mark.parametrize("pattern,topic,ok", [
    ("a/b", "a/b", True),
    ("a/+", "a/b", True),
    ("a/+", "a/b/c", False),
    ("a/#", "a/b/c", True),
    ("#", "x/y", True),
    ("a/b", "a", False),
])
def test_mqtt_topic_matching(pattern, topic, ok):
    assert topic_matches(pattern, topic) is ok


def test_mqtt_varint():
    assert encode_varint(0) == b"\x00"
    assert encode_varint(127) == b"\x7f"
    assert encode_varint(128) == b"\x80\x01"
    assert encode_varint(16383) == b"\xff\x7f"


# ---------------------------------------------------------------------- NATS
class TestNATS:
    @async_test
    async def test_pub_sub_roundtrip(self):
        server = MiniNATSServer()
        await server.start()
        client = NATSClient(port=server.port)
        await client.connect()
        try:
            task = asyncio.ensure_future(client.subscribe("greetings", ""))
            await asyncio.sleep(0.05)  # let SUB reach the server
            await client.publish("greetings", {"hello": "tpu"})
            msg = await asyncio.wait_for(task, timeout=2)
            assert msg.topic == "greetings"
            assert msg.bind() == {"hello": "tpu"}
            msg.commit()  # no-op, must not raise
            assert client.health_check()["status"] == "UP"
        finally:
            await client.close()
            await server.close()

    @async_test
    async def test_queue_group_balances_and_plain_subs_fan_out(self):
        server = MiniNATSServer()
        await server.start()
        worker_a = NATSClient(port=server.port, name="a")
        worker_b = NATSClient(port=server.port, name="b")
        audit = NATSClient(port=server.port, name="audit")
        for c in (worker_a, worker_b, audit):
            await c.connect()
        try:
            ta = asyncio.ensure_future(worker_a.subscribe("jobs", "workers"))
            tb = asyncio.ensure_future(worker_b.subscribe("jobs", "workers"))
            taudit = asyncio.ensure_future(audit.subscribe("jobs", ""))
            await asyncio.sleep(0.05)
            await worker_a.publish("jobs", b"j1")
            await worker_a.publish("jobs", b"j2")
            # audit (plain sub) sees both; the queue group sees each once
            m1 = await asyncio.wait_for(taudit, 2)
            m2 = await asyncio.wait_for(audit.subscribe("jobs", ""), 2)
            assert {m1.value, m2.value} == {b"j1", b"j2"}
            group_msgs = await asyncio.wait_for(
                asyncio.gather(ta, tb), timeout=2)
            assert {m.value for m in group_msgs} == {b"j1", b"j2"}
        finally:
            for c in (worker_a, worker_b, audit):
                await c.close()
            await server.close()

    @async_test
    async def test_wildcard_subscription(self):
        server = MiniNATSServer()
        await server.start()
        client = NATSClient(port=server.port)
        await client.connect()
        try:
            task = asyncio.ensure_future(client.subscribe("orders.>", ""))
            await asyncio.sleep(0.05)
            await client.publish("orders.created.eu", b"x")
            msg = await asyncio.wait_for(task, 2)
            assert msg.topic == "orders.created.eu"
        finally:
            await client.close()
            await server.close()


    @async_test
    async def test_connection_loss_wakes_consumer_and_reconnects(self):
        from gofr_tpu.pubsub.nats import NATSError
        server = MiniNATSServer()
        await server.start()
        port = server.port
        client = NATSClient(port=port)
        await client.connect()
        task = asyncio.ensure_future(client.subscribe("t", ""))
        await asyncio.sleep(0.05)
        await server.close()  # broker dies while consumer is blocked
        with pytest.raises(NATSError):
            await asyncio.wait_for(task, timeout=3)  # wakes, no hang
        # broker comes back on the same port: client self-heals
        server2 = MiniNATSServer(port=port)
        await server2.start()
        try:
            task2 = asyncio.ensure_future(client.subscribe("t", ""))
            await asyncio.sleep(0.1)
            await client.publish("t", b"back")
            msg = await asyncio.wait_for(task2, timeout=3)
            assert msg.value == b"back"
        finally:
            await client.close()
            await server2.close()


# ---------------------------------------------------------------------- MQTT
class TestMQTT:
    @async_test
    async def test_pub_sub_qos1_roundtrip(self):
        broker = MiniMQTTBroker()
        await broker.start()
        client = MQTTClient(port=broker.port, qos=1)
        await client.connect()
        try:
            await client._ensure_sub("sensors/temp")
            await client.publish("sensors/temp", {"c": 21.5})
            msg = await asyncio.wait_for(
                client.subscribe("sensors/temp"), timeout=2)
            assert msg.bind() == {"c": 21.5}
            msg.commit()  # sends PUBACK for the inbound QoS1 message
            assert client.health_check()["status"] == "UP"
        finally:
            await client.close()
            await broker.close()

    @async_test
    async def test_wildcard_and_two_clients(self):
        broker = MiniMQTTBroker()
        await broker.start()
        alice = MQTTClient(port=broker.port, client_id="alice")
        bob = MQTTClient(port=broker.port, client_id="bob")
        await alice.connect()
        await bob.connect()
        try:
            await bob._ensure_sub("chat/+/msg")
            await alice.publish("chat/room1/msg", b"hi")
            msg = await asyncio.wait_for(bob.subscribe("chat/+/msg"), 2)
            assert msg.topic == "chat/room1/msg"
            assert msg.value == b"hi"
        finally:
            await alice.close()
            await bob.close()
            await broker.close()

    @async_test
    async def test_retained_message_replays_to_new_subscriber(self):
        broker = MiniMQTTBroker()
        await broker.start()
        publisher = MQTTClient(port=broker.port, client_id="p", retain=True)
        await publisher.connect()
        await publisher.publish("config/mode", b"serving")
        late = MQTTClient(port=broker.port, client_id="late")
        await late.connect()
        try:
            msg = await asyncio.wait_for(late.subscribe("config/#"), 2)
            assert msg.topic == "config/mode"
            assert msg.value == b"serving"
        finally:
            await publisher.close()
            await late.close()
            await broker.close()

    @async_test
    async def test_qos0_no_ack(self):
        broker = MiniMQTTBroker()
        await broker.start()
        client = MQTTClient(port=broker.port, qos=0)
        await client.connect()
        try:
            await client._ensure_sub("t")
            await client.publish("t", b"fire-and-forget")
            msg = await asyncio.wait_for(client.subscribe("t"), 2)
            assert msg.value == b"fire-and-forget"
        finally:
            await client.close()
            await broker.close()


# -------------------------------------------------------- container wiring
class TestBackendSelection:
    def test_env_selects_nats(self):
        c = Container.create(DictConfig({"PUBSUB_BACKEND": "NATS",
                                         "PUBSUB_BROKER": "10.0.0.9:5222"}))
        assert type(c.pubsub).__name__ == "NATSClient"
        assert (c.pubsub.host, c.pubsub.port) == ("10.0.0.9", 5222)
        assert c.pubsub in c._deferred_connects  # async connect deferred

    def test_broker_addr_tolerates_scheme_and_bare_host(self):
        c = Container.create(DictConfig({"PUBSUB_BACKEND": "NATS",
                                         "PUBSUB_BROKER": "nats://h1:9000"}))
        assert (c.pubsub.host, c.pubsub.port) == ("h1", 9000)
        c2 = Container.create(DictConfig({"PUBSUB_BACKEND": "NATS",
                                          "PUBSUB_BROKER": "justahost"}))
        assert (c2.pubsub.host, c2.pubsub.port) == ("justahost", 4222)

    def test_mqtt_qos_clamped_to_implemented_range(self):
        c = Container.create(DictConfig({"PUBSUB_BACKEND": "MQTT",
                                         "MQTT_QOS": "2"}))
        assert c.pubsub.qos == 1

    def test_env_selects_mqtt(self):
        c = Container.create(DictConfig({"PUBSUB_BACKEND": "MQTT",
                                         "MQTT_PORT": "2883",
                                         "MQTT_QOS": "0"}))
        assert type(c.pubsub).__name__ == "MQTTClient"
        assert c.pubsub.port == 2883
        assert c.pubsub.qos == 0

    def test_env_selects_memory(self):
        c = Container.create(DictConfig({"PUBSUB_BACKEND": "MEMORY"}))
        assert type(c.pubsub).__name__ == "InMemoryBroker"

    @async_test
    async def test_connect_async_failure_leaves_store_down(self):
        c = Container.create(DictConfig({"PUBSUB_BACKEND": "NATS",
                                         "PUBSUB_BROKER": "127.0.0.1:1"}))
        await c.connect_async()  # refused connection: logged, not raised
        assert c.pubsub.health_check()["status"] == "DOWN"
        assert c._deferred_connects == []


# --------------------------------------------- end-to-end subscriber runtime
@async_test
async def test_subscriber_runtime_over_nats():
    """App-style flow: SubscriptionManager pulls from a real NATS server
    and drives a handler with commit-on-success."""
    from gofr_tpu.container.mock import new_mock_container
    from gofr_tpu.pubsub.subscriber import SubscriptionManager

    server = MiniNATSServer()
    await server.start()
    container = new_mock_container()
    client = NATSClient(port=server.port)
    container.add_pubsub(client)
    await container.connect_async()

    received = asyncio.Event()
    seen = []

    async def handler(ctx):
        seen.append(ctx.bind())
        received.set()
        return None

    manager = SubscriptionManager(container)
    task = asyncio.ensure_future(manager.start_subscriber("events", handler))
    try:
        await asyncio.sleep(0.1)  # subscriber loop issues SUB
        await client.publish("events", {"kind": "ping"})
        await asyncio.wait_for(received.wait(), timeout=3)
        assert seen == [{"kind": "ping"}]
    finally:
        task.cancel()
        await client.close()
        await server.close()
