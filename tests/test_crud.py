"""Auto-CRUD handlers over a real server + sqlite."""

from __future__ import annotations

from dataclasses import dataclass

import pytest

from gofr_tpu.crud import scan_entity

from .apputil import AppRunner


@dataclass
class User:
    id: int
    name: str
    email: str = ""


@dataclass
class CustomNamed:
    uid: int
    label: str = ""

    @classmethod
    def table_name(cls) -> str:
        return "custom_tbl"

    @classmethod
    def rest_path(cls) -> str:
        return "custom"


class TestScanEntity:
    def test_first_field_is_pk(self):
        spec = scan_entity(User)
        assert spec.primary_key == "id"
        assert spec.table == "user"
        assert spec.path == "user"
        assert spec.fields == ["id", "name", "email"]

    def test_overrides(self):
        spec = scan_entity(CustomNamed)
        assert spec.table == "custom_tbl"
        assert spec.path == "custom"

    def test_rejects_non_dataclass(self):
        with pytest.raises(TypeError):
            scan_entity(dict)

    def test_rejects_bad_identifiers(self):
        @dataclass
        class Evil:
            pass
        Evil.table_name = classmethod(lambda cls: "users; DROP TABLE x")
        with pytest.raises(Exception):
            scan_entity(Evil)


def build(app):
    app.container.sql.exec(
        "CREATE TABLE user (id INTEGER PRIMARY KEY, name TEXT, email TEXT)")
    app.add_rest_handlers(User)


def crud_runner() -> AppRunner:
    return AppRunner(build=build,
                     config={"DB_DIALECT": "sqlite", "DB_NAME": ":memory:"})


class TestCRUD:
    def test_create_and_get(self):
        with crud_runner() as r:
            status, _, _ = r.request(
                "POST", "/user",
                body={"id": 1, "name": "ada", "email": "a@x.io"})
            assert status == 201
            status, body = r.get_json("/user/1")
            assert status == 200
            assert body["data"] == {"id": 1, "name": "ada",
                                    "email": "a@x.io"}

    def test_get_all(self):
        with crud_runner() as r:
            for i in (1, 2, 3):
                r.request("POST", "/user", body={"id": i, "name": f"u{i}"})
            status, body = r.get_json("/user")
            assert status == 200 and len(body["data"]) == 3

    def test_update(self):
        with crud_runner() as r:
            r.request("POST", "/user", body={"id": 1, "name": "ada"})
            status, _, _ = r.request(
                "PUT", "/user/1",
                body={"id": 1, "name": "lovelace", "email": "l@x.io"})
            assert status == 200
            _, body = r.get_json("/user/1")
            assert body["data"]["name"] == "lovelace"

    def test_delete(self):
        with crud_runner() as r:
            r.request("POST", "/user", body={"id": 1, "name": "ada"})
            status, _, _ = r.request("DELETE", "/user/1")
            assert status == 204
            status, _ = r.get_json("/user/1")
            assert status == 404

    def test_not_found_and_bad_body(self):
        with crud_runner() as r:
            status, _ = r.get_json("/user/99")
            assert status == 404
            status, _, _ = r.request("PUT", "/user/99",
                                     body={"id": 99, "name": "x"})
            assert status == 404
            status, _, _ = r.request("DELETE", "/user/99")
            assert status == 404
            status, _, _ = r.request("POST", "/user", body={"name": "no-pk"})
            assert status == 400  # missing required field id
