"""JetStream semantics over the mini server: persistence, durable pull
consumers, explicit acks, ack-wait redelivery (VERDICT missing #6 —
the reference NATS module's JetStream grade)."""

import asyncio
import functools

from gofr_tpu.config.env import DictConfig
from gofr_tpu.container.container import Container
from gofr_tpu.pubsub.jetstream import JetStreamClient, MiniJetStreamServer


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))
    return wrapper


@async_test
async def test_publish_gets_puback_and_persists():
    srv = MiniJetStreamServer()
    await srv.start()
    client = JetStreamClient(port=srv.port)
    try:
        await client.publish("orders", {"id": 1})
        await client.publish("orders", {"id": 2})
        assert len(srv.streams["orders"].messages) == 2  # persisted
    finally:
        await client.close()
        await srv.close()


@async_test
async def test_pull_consume_ack_ordering():
    srv = MiniJetStreamServer()
    await srv.start()
    client = JetStreamClient(port=srv.port)
    try:
        await client.publish("t", "a")
        await client.publish("t", "b")
        m1 = await asyncio.wait_for(client.subscribe("t", "workers"), 10)
        assert m1.value == b"a"
        m1.commit()
        m2 = await asyncio.wait_for(client.subscribe("t", "workers"), 10)
        assert m2.value == b"b"
        m2.commit()
        await asyncio.sleep(0.05)
        consumer = srv.consumers[("t", "workers")]
        assert not consumer.outstanding          # both acked
    finally:
        await client.close()
        await srv.close()


@async_test
async def test_unacked_redelivers_after_ack_wait():
    srv = MiniJetStreamServer()
    await srv.start()
    client = JetStreamClient(port=srv.port, ack_wait_s=0.3)
    try:
        await client.publish("t", "poison")
        m = await asyncio.wait_for(client.subscribe("t", "g"), 10)
        assert m.value == b"poison"              # delivered, NOT acked
        await asyncio.sleep(0.4)                 # ack-wait expires
        m2 = await asyncio.wait_for(client.subscribe("t", "g"), 10)
        assert m2.value == b"poison"             # redelivered
        m2.commit()
        await asyncio.sleep(0.05)
        assert not srv.consumers[("t", "g")].outstanding
    finally:
        await client.close()
        await srv.close()


@async_test
async def test_consumer_survives_client_restart():
    """Durability: a new client resumes the durable's cursor — acked
    messages never redeliver across restarts."""
    srv = MiniJetStreamServer()
    await srv.start()
    c1 = JetStreamClient(port=srv.port)
    await c1.publish("t", "one")
    await c1.publish("t", "two")
    m = await asyncio.wait_for(c1.subscribe("t", "d"), 10)
    assert m.value == b"one"
    m.commit()
    await asyncio.sleep(0.05)
    await c1.close()

    c2 = JetStreamClient(port=srv.port)
    try:
        m = await asyncio.wait_for(c2.subscribe("t", "d"), 10)
        assert m.value == b"two"
    finally:
        await c2.close()
        await srv.close()


@async_test
async def test_two_groups_each_get_every_message():
    srv = MiniJetStreamServer()
    await srv.start()
    client = JetStreamClient(port=srv.port)
    try:
        await client.publish("evt", "x")
        a = await asyncio.wait_for(client.subscribe("evt", "a"), 10)
        b = await asyncio.wait_for(client.subscribe("evt", "b"), 10)
        assert a.value == b"x" and b.value == b"x"
    finally:
        await client.close()
        await srv.close()


@async_test
async def test_container_wires_jetstream_backend():
    srv = MiniJetStreamServer()
    await srv.start()
    c = Container.create(DictConfig({
        "APP_NAME": "js", "PUBSUB_BACKEND": "JETSTREAM",
        "PUBSUB_BROKER": f"127.0.0.1:{srv.port}"}))
    try:
        assert isinstance(c.pubsub, JetStreamClient)
        await c.pubsub.publish("t", {"n": 1})
        msg = await asyncio.wait_for(c.pubsub.subscribe("t", "g"), 10)
        assert msg.bind() == {"n": 1}
        assert c.pubsub.health_check()["backend"] == "nats-jetstream"
    finally:
        await c.pubsub.close()
        await srv.close()


@async_test
async def test_dotted_subjects_work():
    """Idiomatic NATS subjects ('orders.created') must map to legal
    stream/durable names while the stream captures the dotted subject."""
    srv = MiniJetStreamServer()
    await srv.start()
    client = JetStreamClient(port=srv.port)
    try:
        await client.publish("orders.created", {"id": 9})
        m = await asyncio.wait_for(
            client.subscribe("orders.created", "eu.workers"), 10)
        assert m.bind() == {"id": 9}
        m.commit()
        assert "orders_created" in srv.streams
    finally:
        await client.close()
        await srv.close()


@async_test
async def test_subscribe_recovers_after_connection_drop():
    srv = MiniJetStreamServer()
    await srv.start()
    client = JetStreamClient(port=srv.port)
    try:
        await client.publish("t", "before")
        m = await asyncio.wait_for(client.subscribe("t", "g"), 10)
        assert m.value == b"before"
        m.commit()
        await asyncio.sleep(0.05)
        # server drops every connection; streams live server-side
        for w in list(srv._conns.values()):
            w.close()
        await asyncio.sleep(0.05)
        await client.publish("t", "after")
        m2 = await asyncio.wait_for(client.subscribe("t", "g"), 10)
        assert m2.value == b"after"
    finally:
        await client.close()
        await srv.close()
