"""Parallelism tests on the 8-device virtual CPU mesh.

The invariant everywhere: sharded execution computes the SAME numbers
as single-device execution (collectives change placement, not math).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.llama import LlamaConfig, llama_init, llama_prefill
from gofr_tpu.parallel.mesh import create_mesh, mesh_axes
from gofr_tpu.parallel.ring_attention import make_ring_attention
from gofr_tpu.parallel.sharding import llama_param_specs, shard_params
from gofr_tpu.parallel.train import (
    cross_entropy_loss,
    make_train_state,
    make_train_step,
)
from gofr_tpu.ops.attention import xla_attention

TINY = LlamaConfig(vocab_size=64, dim=32, n_layers=4, n_heads=4,
                   n_kv_heads=4, ffn_dim=64, max_seq=64, dtype=jnp.float32)


def make_batch(key, b=8, s=16):
    tokens = jax.random.randint(key, (b, s + 1), 0, TINY.vocab_size)
    return tokens[:, :-1], tokens[:, 1:], jnp.ones((b, s), jnp.int32)


def test_create_mesh_shapes():
    mesh = create_mesh({"dp": 2, "tp": 4})
    assert mesh_axes(mesh) == {"dp": 2, "tp": 4}
    mesh = create_mesh({"dp": 2, "tp": -1})
    assert mesh_axes(mesh)["tp"] == 4
    with pytest.raises(ValueError):
        create_mesh({"dp": 3, "tp": 4})


def test_sharded_forward_matches_unsharded():
    mesh = create_mesh({"dp": 2, "tp": 4})
    params = llama_init(jax.random.key(0), TINY)
    sharded = shard_params(params, mesh, llama_param_specs(mesh))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, TINY.vocab_size)
    ref_logits, _ = llama_prefill(params, tokens, TINY, implementation="xla")
    got_logits, _ = jax.jit(
        lambda p, t: llama_prefill(p, t, TINY, implementation="xla"))(
            sharded, tokens)
    np.testing.assert_allclose(np.asarray(got_logits), np.asarray(ref_logits),
                               rtol=1e-4, atol=1e-4)


def test_dense_train_step_dp_tp_sp():
    mesh = create_mesh({"dp": 2, "tp": 4})
    state, _ = make_train_state(jax.random.key(0), TINY, mesh)
    step = make_train_step(TINY, mesh, donate=False)
    tokens, targets, mask = make_batch(jax.random.key(1))

    # reference loss on unsharded params with identical init
    ref_params = llama_init(jax.random.key(0), TINY)
    ref_logits, _ = llama_prefill(ref_params, tokens, TINY, implementation="xla")
    ref_loss = cross_entropy_loss(ref_logits, targets, mask)

    state1, loss1 = step(state, tokens, targets, mask)
    assert abs(float(loss1) - float(ref_loss)) < 1e-3

    losses = [float(loss1)]
    for i in range(4):
        state1, loss = step(state1, tokens, targets, mask)
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # optimizing on a fixed batch must descend
    assert int(state1.step) == 5


def test_pipeline_train_step_matches_dense():
    from gofr_tpu.parallel.pipeline import make_pipeline_train_step

    mesh = create_mesh({"dp": 2, "pp": 4})
    state, _ = make_train_state(jax.random.key(0), TINY, mesh)
    step = make_pipeline_train_step(TINY, mesh, num_microbatches=4,
                                    donate=False)

    b, s, M = 8, 16, 4
    tokens, targets, mask = make_batch(jax.random.key(1), b=b, s=s)
    # reference loss (single device, no pipeline)
    ref_params = llama_init(jax.random.key(0), TINY)
    ref_logits, _ = llama_prefill(ref_params, tokens, TINY, implementation="xla")
    ref_loss = cross_entropy_loss(ref_logits, targets, mask)

    micro = lambda x: x.reshape(M, b // M, *x.shape[1:])
    state1, loss1 = step(state, micro(tokens), micro(targets), micro(mask))
    assert abs(float(loss1) - float(ref_loss)) < 1e-3

    losses = [float(loss1)]
    for _ in range(3):
        state1, loss = step(state1, micro(tokens), micro(targets), micro(mask))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_moe_ep_train_step():
    from gofr_tpu.models.moe import MoEConfig, moe_init, moe_prefill
    from gofr_tpu.parallel.sharding import moe_param_specs

    cfg = MoEConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                    n_kv_heads=4, ffn_dim=48, max_seq=64, n_experts=4,
                    top_k=2, dtype=jnp.float32)
    mesh = create_mesh({"dp": 2, "ep": 4})

    def fwd(params, tokens):
        logits, _, _ = moe_prefill(params, tokens, cfg, implementation="xla")
        return logits

    state, _ = make_train_state(jax.random.key(0), cfg, mesh,
                                init_fn=moe_init, specs_fn=moe_param_specs)
    step = make_train_step(cfg, mesh, forward_fn=fwd, donate=False)
    tokens, targets, mask = make_batch(jax.random.key(1))
    tokens = tokens % cfg.vocab_size

    # reference vs sharded first-step loss
    ref_params = moe_init(jax.random.key(0), cfg)
    ref_loss = cross_entropy_loss(fwd(ref_params, tokens), targets, mask)
    state1, loss1 = step(state, tokens, targets, mask)
    assert abs(float(loss1) - float(ref_loss)) < 1e-3

    state2, loss2 = step(state1, tokens, targets, mask)
    state3, loss3 = step(state2, tokens, targets, mask)
    assert float(loss3) < float(loss1)


def test_ring_attention_matches_reference():
    mesh = create_mesh({"sp": 8})
    ring = make_ring_attention(mesh, "sp")
    b, s, h, d = 2, 64, 2, 16
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))
    ref = xla_attention(q, k, v, causal=True)
    got = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_ring_attention_grad_flows():
    mesh = create_mesh({"sp": 4})
    ring = make_ring_attention(mesh, "sp")
    b, s, h, d = 1, 32, 2, 8
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))

    def f(q):
        return (ring(q, k, v) ** 2).sum()

    def f_ref(q):
        return (xla_attention(q, k, v, causal=True) ** 2).sum()

    g = jax.grad(f)(q)
    g_ref = jax.grad(f_ref)(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-3, atol=1e-3)
