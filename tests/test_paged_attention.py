"""Ragged paged decode-attention kernel: interpret-mode parity against
the dense reference, ragged lengths, OOB tables, GQA grouping."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.attention import decode_attention
from gofr_tpu.ops.paged_attention import (paged_decode_attention,
                                          paged_decode_attention_pallas,
                                          paged_decode_attention_xla)


def _random_paged_case(key, *, b=3, hq=4, hkv=2, hd=16, page=8,
                       max_pages=6, n_pages=32, lengths=(5, 17, 48)):
    """Build a pool + tables + the equivalent dense cache."""
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, hq, hd), jnp.float32)
    # head-major pool [Hkv, Np, pg, hd] (ops/paged_kv.py)
    k_pool = jax.random.normal(ks[1], (hkv, n_pages, page, hd), jnp.float32)
    v_pool = jax.random.normal(ks[2], (hkv, n_pages, page, hd), jnp.float32)
    rng = np.random.default_rng(0)
    tables = np.full((b, max_pages), n_pages, np.int32)  # OOB = unalloc
    for i, ln in enumerate(lengths):
        need = -(-ln // page)
        tables[i, :need] = rng.choice(n_pages, size=need, replace=False)
    tables = jnp.asarray(tables)
    lengths = jnp.asarray(list(lengths), jnp.int32)
    # dense equivalent: gather allocated pages (OOB clamps, rows masked)
    safe = jnp.minimum(tables, n_pages - 1)
    k_dense = k_pool[:, safe].transpose(1, 2, 3, 0, 4).reshape(
        b, max_pages * page, hkv, hd)
    v_dense = v_pool[:, safe].transpose(1, 2, 3, 0, 4).reshape(
        b, max_pages * page, hkv, hd)
    return q, k_pool, v_pool, tables, lengths, k_dense, v_dense


def test_interpret_matches_dense_reference():
    case = _random_paged_case(jax.random.key(0))
    q, k_pool, v_pool, tables, lengths, k_dense, v_dense = case
    want = decode_attention(q[:, None], k_dense, v_dense, lengths)[:, 0]
    got = paged_decode_attention_pallas(q, k_pool, v_pool, tables, lengths,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_xla_fallback_matches_dense_reference():
    case = _random_paged_case(jax.random.key(1), lengths=(1, 30, 41))
    q, k_pool, v_pool, tables, lengths, k_dense, v_dense = case
    want = decode_attention(q[:, None], k_dense, v_dense, lengths)[:, 0]
    got = paged_decode_attention_xla(q, k_pool, v_pool, tables, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ragged_lengths_ignore_unallocated_tail():
    """Rows past each slot's length must not contribute — poison the
    unallocated pages and the masked tail rows."""
    case = _random_paged_case(jax.random.key(2), lengths=(9, 9, 9))
    q, k_pool, v_pool, tables, lengths, k_dense, v_dense = case
    # poison every page NOT referenced by the first ceil(9/8)=2 entries
    used = set(np.asarray(tables)[:, :2].ravel().tolist())
    poison = np.asarray(k_pool).copy()
    for p in range(poison.shape[1]):
        if p not in used:
            poison[:, p] = 1e6
    got_clean = paged_decode_attention_pallas(
        q, k_pool, v_pool, tables, lengths, interpret=True)
    got_poisoned = paged_decode_attention_pallas(
        q, jnp.asarray(poison), v_pool, tables, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(got_poisoned),
                               np.asarray(got_clean), rtol=2e-5, atol=2e-5)


def test_single_chunk_and_multi_chunk_agree():
    """Slot long enough to span several 128-row chunks (page walk with
    double buffering) matches the reference."""
    case = _random_paged_case(jax.random.key(3), b=2, page=16,
                              max_pages=24, n_pages=64,
                              lengths=(300, 77))
    q, k_pool, v_pool, tables, lengths, k_dense, v_dense = case
    want = decode_attention(q[:, None], k_dense, v_dense, lengths)[:, 0]
    got = paged_decode_attention_pallas(q, k_pool, v_pool, tables, lengths,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_dispatch_auto_on_cpu_is_xla():
    case = _random_paged_case(jax.random.key(4))
    q, k_pool, v_pool, tables, lengths, k_dense, v_dense = case
    got = paged_decode_attention(q, k_pool, v_pool, tables, lengths,
                                 implementation="auto")
    want = decode_attention(q[:, None], k_dense, v_dense, lengths)[:, 0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_zero_length_slot_returns_zeros_not_nan():
    case = _random_paged_case(jax.random.key(5), lengths=(0, 8, 16))
    q, k_pool, v_pool, tables, lengths, *_ = case
    got = paged_decode_attention_pallas(q, k_pool, v_pool, tables, lengths,
                                        interpret=True)
    assert not np.isnan(np.asarray(got)).any()
    np.testing.assert_allclose(np.asarray(got[0]), 0.0, atol=1e-6)


# --------------------------------------------- Mosaic sublane alignment
#
# BENCH_r05's first real-TPU compile died in Mosaic: "Slice shape
# along dimension 2 must be aligned to tiling (8), but is 1" — a grid
# cell's q/out block carried fewer than 8 rows along the sublane dim
# (small GQA group x short q block). The wrappers now pad those blocks
# to the 8-row tile; these tests pin (a) the alignment arithmetic for
# every group/block_q the serving shapes can produce and (b) interpret
# -mode parity on the exact shapes that used to emit misaligned slices,
# so the regression is caught on CPU, not in the next TPU window.

def test_sublane_padding_always_tile_aligned():
    from gofr_tpu.ops.paged_attention import SUBLANE, _pad_group
    for group in range(1, 33):
        padded = _pad_group(group)
        assert padded >= group and padded % SUBLANE == 0, (group, padded)
        for block_q in (1, 2, 4, 8, 16, 32, 64, 128):
            rows = block_q * _pad_group(group, block_q)
            assert rows % SUBLANE == 0, (group, block_q, rows)
            assert _pad_group(group, block_q) >= group
    # no waste where none is needed: already-aligned shapes unchanged
    assert _pad_group(8) == 8
    assert _pad_group(4, 2) == 4
    assert _pad_group(1, 8) == 1


@pytest.mark.parametrize("hq,hkv", [(4, 4),    # MHA: group=1, the
                                               # "but is 1" failure
                                    (8, 2),    # group=4 (llama3-1b)
                                    (6, 2)])   # group=3: odd group
def test_decode_parity_with_sub_tile_group(hq, hkv):
    """Small-GQA-group decode blocks (sublane-padded) still match the
    dense reference bit-for-bit in interpret mode."""
    case = _random_paged_case(jax.random.key(7), hq=hq, hkv=hkv,
                              lengths=(5, 17, 48))
    q, k_pool, v_pool, tables, lengths, k_dense, v_dense = case
    want = decode_attention(q[:, None], k_dense, v_dense, lengths)[:, 0]
    got = paged_decode_attention_pallas(q, k_pool, v_pool, tables,
                                        lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_chunk_parity_with_sub_tile_rows():
    """Chunk blocks whose block_q x group < 8 (the spec-verify window
    shape: tiny Sq, small group) pad to the tile and stay correct."""
    from gofr_tpu.ops.attention import xla_attention
    from gofr_tpu.ops.paged_attention import paged_chunk_attention_pallas
    b, sq, hq, hkv, hd = 2, 5, 4, 4, 16     # group=1, block_q=1 -> 1 row
    page, max_pages, n_pages = 8, 6, 32
    ks = jax.random.split(jax.random.key(8), 3)
    q = jax.random.normal(ks[0], (b, sq, hq, hd), jnp.float32)
    k_pool = jax.random.normal(ks[1], (hkv, n_pages, page, hd),
                               jnp.float32)
    v_pool = jax.random.normal(ks[2], (hkv, n_pages, page, hd),
                               jnp.float32)
    rng = np.random.default_rng(3)
    history = np.asarray([11, 0], np.int32)
    chunk_lens = np.asarray([sq, 3], np.int32)
    tables = np.full((b, max_pages), n_pages, np.int32)
    for i in range(b):
        need = -(-int(history[i] + chunk_lens[i]) // page)
        tables[i, :need] = rng.choice(n_pages, size=need, replace=False)
    tables = jnp.asarray(tables)
    got = paged_chunk_attention_pallas(
        q, k_pool, v_pool, tables, jnp.asarray(history),
        jnp.asarray(chunk_lens), interpret=True)
    safe = jnp.minimum(tables, n_pages - 1)
    k_dense = k_pool[:, safe].transpose(1, 2, 3, 0, 4).reshape(
        b, max_pages * page, hkv, hd)
    v_dense = v_pool[:, safe].transpose(1, 2, 3, 0, 4).reshape(
        b, max_pages * page, hkv, hd)
    want = xla_attention(q, k_dense, v_dense, causal=True,
                         q_offset=jnp.asarray(history),
                         kv_lengths=jnp.asarray(history)
                         + jnp.asarray(chunk_lens))
    for i in range(b):
        n = int(chunk_lens[i])  # rows past chunk_len are padding
        np.testing.assert_allclose(np.asarray(got)[i, :n],
                                   np.asarray(want)[i, :n],
                                   rtol=2e-5, atol=2e-5)


def test_page_misalignment_raises_actionable_error():
    """A page size that cannot DMA into sublane-tiled VMEM must fail
    with a message naming the fix, not a Mosaic internal error (only
    on the compiled path — interpret mode has no tiling)."""
    case = _random_paged_case(jax.random.key(9), page=4, max_pages=12,
                              lengths=(5, 9, 3))
    q, k_pool, v_pool, tables, lengths, *_ = case
    with pytest.raises(ValueError, match="multiple of 8"):
        paged_decode_attention_pallas(q, k_pool, v_pool, tables,
                                      lengths, interpret=False)
    # interpret mode still accepts it (CPU tests use small pages)
    paged_decode_attention_pallas(q, k_pool, v_pool, tables, lengths,
                                  interpret=True)


# ---------------------------------------------------- quantized pools
#
# int8 KV pages (ops/paged_kv.py: {"q": int8, "s": f32 per-row}). The
# kernel DMAs the codes page plus its scale column and dequantizes
# in-register; the XLA fallback dequantizes its gathered view. Both
# paths therefore see the SAME f32 inputs, so kernel-vs-fallback
# parity is as tight as the unquantized case (2e-5, the repo's
# interpret-parity idiom) — while int8-vs-f32 is bounded by the
# quantization error itself (per element <= amax/254; observed worst
# case ~0.018 on N(0,1) pools, asserted at 0.05 = ~3x margin).

from gofr_tpu.ops.paged_attention import (paged_chunk_attention_pallas,
                                          paged_chunk_attention_xla)
from gofr_tpu.ops.paged_kv import quantize_pool


def _quant_decode_case(seed, *, page, hq, hkv, lengths=(5, 17, 0)):
    """Mid-page histories + a zero-length tail slot, quantized pools
    alongside their f32 source."""
    case = _random_paged_case(jax.random.key(seed), hq=hq, hkv=hkv,
                              page=page, max_pages=8, n_pages=32,
                              lengths=lengths)
    q, k_pool, v_pool, tables, lens, *_ = case
    return (q, k_pool, v_pool, quantize_pool(k_pool),
            quantize_pool(v_pool), tables, lens)


@pytest.mark.parametrize("page", [8, 16])
@pytest.mark.parametrize("hq,hkv", [(4, 4),   # GQA group 1
                                    (8, 2)])  # GQA group 4
def test_int8_decode_kernel_matches_int8_xla(page, hq, hkv):
    q, _, _, kq, vq, tables, lens = _quant_decode_case(
        41 + page, page=page, hq=hq, hkv=hkv)
    got = paged_decode_attention_pallas(q, kq, vq, tables, lens,
                                        interpret=True)
    want = paged_decode_attention_xla(q, kq, vq, tables, lens)
    # full-batch comparison: the fallback masks zero-length slots to
    # exact zeros, matching the kernel's denom-clamp contract
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    assert not np.isnan(np.asarray(got)).any()
    np.testing.assert_allclose(np.asarray(got)[2], 0.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(want)[2], 0.0, atol=1e-6)


@pytest.mark.parametrize("page", [8, 16])
def test_int8_decode_within_quant_bound_of_f32(page):
    q, k_pool, v_pool, kq, vq, tables, lens = _quant_decode_case(
        43 + page, page=page, hq=8, hkv=2)
    got = paged_decode_attention_pallas(q, kq, vq, tables, lens,
                                        interpret=True)
    want = paged_decode_attention_xla(q, k_pool, v_pool, tables, lens)
    np.testing.assert_allclose(np.asarray(got)[:2], np.asarray(want)[:2],
                               atol=0.05)


def _quant_chunk_case(seed, *, page, hq, hkv):
    """Chunk shapes: histories starting mid-page (3, 9) and a
    zero-length tail row."""
    b, sq, hd, max_pages, n_pages = 3, 5, 16, 8, 32
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, sq, hq, hd), jnp.float32)
    k_pool = jax.random.normal(ks[1], (hkv, n_pages, page, hd),
                               jnp.float32)
    v_pool = jax.random.normal(ks[2], (hkv, n_pages, page, hd),
                               jnp.float32)
    history = jnp.asarray([3, 9, 0], jnp.int32)
    chunk_lens = jnp.asarray([sq, 3, 0], jnp.int32)
    rng = np.random.default_rng(seed)
    tables = np.full((b, max_pages), n_pages, np.int32)
    for i in range(b):
        need = -(-int(history[i] + chunk_lens[i]) // page)
        if need:
            tables[i, :need] = rng.choice(n_pages, size=need,
                                          replace=False)
    return (q, k_pool, v_pool, quantize_pool(k_pool),
            quantize_pool(v_pool), jnp.asarray(tables), history,
            chunk_lens)


@pytest.mark.parametrize("page", [8, 16])
@pytest.mark.parametrize("hq,hkv", [(4, 4), (8, 2)])
def test_int8_chunk_kernel_matches_int8_xla(page, hq, hkv):
    (q, _, _, kq, vq, tables, history,
     chunk_lens) = _quant_chunk_case(47 + page + hq, page=page,
                                     hq=hq, hkv=hkv)
    got = paged_chunk_attention_pallas(q, kq, vq, tables, history,
                                       chunk_lens, interpret=True)
    want = paged_chunk_attention_xla(q, kq, vq, tables, history,
                                     chunk_lens)
    assert not np.isnan(np.asarray(got)).any()
    for i in range(3):
        n = int(chunk_lens[i])  # rows past chunk_len are padding
        np.testing.assert_allclose(np.asarray(got)[i, :n],
                                   np.asarray(want)[i, :n],
                                   rtol=2e-5, atol=2e-5)
    # the zero-length tail slot (history == chunk == 0) is exact zeros
    # on BOTH paths now — the fallback masks it like the kernel
    np.testing.assert_allclose(np.asarray(want)[2], 0.0, atol=1e-6)


def test_int8_chunk_within_quant_bound_of_f32():
    (q, k_pool, v_pool, kq, vq, tables, history,
     chunk_lens) = _quant_chunk_case(53, page=8, hq=8, hkv=2)
    got = paged_chunk_attention_pallas(q, kq, vq, tables, history,
                                       chunk_lens, interpret=True)
    want = paged_chunk_attention_xla(q, k_pool, v_pool, tables,
                                     history, chunk_lens)
    for i in range(3):
        n = int(chunk_lens[i])
        np.testing.assert_allclose(np.asarray(got)[i, :n],
                                   np.asarray(want)[i, :n], atol=0.05)


def test_int8_page_alignment_requires_32_rows():
    """int8 VMEM tiles are (32, 128): the compiled path must reject
    pages under 32 rows with the actionable error (a 16-row page is
    legal for f32's 8-row tiles), while interpret mode — no tiling —
    still accepts it so CPU tests can use small pages."""
    q, k_pool, v_pool, kq, vq, tables, lens = _quant_decode_case(
        59, page=16, hq=4, hkv=4)
    with pytest.raises(ValueError, match="multiple of 32"):
        paged_decode_attention_pallas(q, kq, vq, tables, lens,
                                      interpret=False)
    paged_decode_attention_pallas(q, kq, vq, tables, lens,
                                  interpret=True)


# ------------------------------------------------- engine-level parity

def test_paged_native_engine_matches_slot_engine():
    """The native paged decode path (row writes through the table +
    ragged kernel in interpret mode) must reproduce slot-layout greedy
    outputs exactly — same contract as the view path."""
    import time

    from gofr_tpu.serving.engine import EngineConfig, SamplingParams
    from gofr_tpu.serving.glue import demo_llama_engine

    def drain(reqs, timeout=180):
        deadline = time.time() + timeout
        while time.time() < deadline and any(
                r.finished_at is None and r.error is None for r in reqs):
            time.sleep(0.01)
        return reqs

    cfg = dict(max_batch=3, max_seq=128, seed=23)
    slot = demo_llama_engine(EngineConfig(**cfg))
    slot.start()
    want = [slot.submit([5 + i, 2, 9], SamplingParams(
        temperature=0.0, max_new_tokens=9)) for i in range(3)]
    drain(want)
    slot.stop()

    native = demo_llama_engine(EngineConfig(
        kv_layout="paged", page_size=16, paged_attention="interpret",
        **cfg))
    assert native._decode is not None
    native.start()
    got = [native.submit([5 + i, 2, 9], SamplingParams(
        temperature=0.0, max_new_tokens=9)) for i in range(3)]
    drain(got)
    native.stop()

    assert all(r.error is None for r in got)
    assert [r.generated for r in got] == [r.generated for r in want]


# ----------------------------------------- pipelined-prefill races

def _unstarted_paged_engine(**cfg):
    from gofr_tpu.serving.engine import EngineConfig
    from gofr_tpu.serving.glue import demo_llama_engine

    # pipeline_depth=1 forces the pipelined regime these races live
    # in: adaptive depth would collect prefills at admit time below
    # pipeline_min_slots and the dispatch->collect window would vanish
    base = dict(max_batch=2, max_seq=128, seed=31, kv_layout="paged",
                page_size=16, pipeline_depth=1)
    base.update(cfg)
    return demo_llama_engine(EngineConfig(**base))


def test_stale_prefill_result_discarded_after_preempt():
    """A batch prefill dispatched for request R must be discarded if R
    was preempted before its first token was collected — the recompute
    owns its own prefill (epoch protocol)."""
    from gofr_tpu.serving.engine import SamplingParams

    engine = _unstarted_paged_engine()
    req = engine.submit([5, 9, 2], SamplingParams(temperature=0.0,
                                                  max_new_tokens=6))
    # drive the engine internals directly (loop not started)
    engine._admit_batch([engine.waiting.pop_batch(1)[0]])
    assert engine._pending_prefills and req.pending_prefill
    slot = req.slot
    engine._preempt(slot)                  # evicted before collect
    assert not req.pending_prefill
    engine._collect_prefills()             # stale: must emit NOTHING
    assert req.generated == []
    assert req.finished_at is None         # still live, just requeued
    # the requeued life re-admits and produces its first token cleanly
    batch, engine._requeued = engine._requeued, []
    engine._requeued_set.clear()
    engine._admit_batch(batch)
    engine._collect_prefills()
    assert len(req.generated) == 1
    engine._shutdown_cleanup("test over")


def test_cancelled_pending_prefill_discarded():
    """Cancellation between prefill dispatch and collect retires the
    slot; the late first token must not land after the terminal None."""
    from gofr_tpu.serving.engine import SamplingParams

    engine = _unstarted_paged_engine()
    req = engine.submit([7, 7, 7], SamplingParams(temperature=0.0,
                                                  max_new_tokens=6))
    engine._admit_batch([engine.waiting.pop_batch(1)[0]])
    req.cancelled = True
    engine._retire_unservable()            # retires the pending slot
    assert req.finished_at is not None
    engine._collect_prefills()
    assert req.generated == []             # nothing after the None
    engine._shutdown_cleanup("test over")


def test_prefill_spans_do_not_double_count():
    """Two bucket groups dispatched back-to-back then collected
    together must accumulate a UNION of wall spans, not a 2x sum."""
    import time as _t

    from gofr_tpu.serving.engine import SamplingParams

    engine = _unstarted_paged_engine(max_batch=4)
    t0 = _t.perf_counter()
    for prompt in ([1] * 10, [2] * 40):    # two different buckets
        engine.submit(prompt, SamplingParams(temperature=0.0,
                                             max_new_tokens=4))
    engine._admit_batch(engine.waiting.pop_batch(4))
    assert len(engine._pending_prefills) == 2
    engine._collect_prefills()
    wall = _t.perf_counter() - t0
    assert engine.stats["prefill_s"] <= wall + 0.01
    engine._shutdown_cleanup("test over")


# ------------------------------------------------------ tree verify
#
# Multi-draft tree verify (ops/paged_attention.py paged_tree_attention):
# Sq tree nodes per slot attend the full history plus exactly their
# packed-ancestor in-tree rows. Parity cases mirror the serving shapes:
# branch counts 1/2/4, histories starting mid-page, a zero-length tail
# slot, GQA groups 1 and 4, f32/bf16/int8 pools. A chain-shaped tree
# must reduce bit-for-bit to the causal chunk kernel — speculation's
# greedy-identity contract rides on that.

from gofr_tpu.ops.attention import tree_attention
from gofr_tpu.ops.paged_attention import (paged_tree_attention,
                                          paged_tree_attention_pallas,
                                          paged_tree_attention_xla)
from gofr_tpu.serving.spec import build_draft_tree


def _branch_chains(branches):
    if branches == 1:
        return [[1, 2, 3, 4]]
    if branches == 2:
        return [[1, 2, 3], [1, 5], [6, 7]]  # shared prefix + fork
    return [[1, 2], [3, 4], [5, 6], [7, 8]]


def _tree_case(seed, *, branches, hq, hkv, page=8, dtype=jnp.float32):
    """3 slots: mid-page histories (3, 9) and a zero-length tail; the
    2nd slot verifies a topological PREFIX of the tree (shorter
    chunk), the 3rd is inactive."""
    tree = build_draft_tree(0, _branch_chains(branches))
    sq = tree.n_nodes
    b, hd, max_pages, n_pages = 3, 16, 8, 32
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, sq, hq, hd), jnp.float32)
    k_pool = jax.random.normal(ks[1], (hkv, n_pages, page, hd),
                               jnp.float32).astype(dtype)
    v_pool = jax.random.normal(ks[2], (hkv, n_pages, page, hd),
                               jnp.float32).astype(dtype)
    history = jnp.asarray([3, 9, 0], jnp.int32)
    chunk_lens = jnp.asarray([sq, min(sq, 3), 0], jnp.int32)
    masks = np.ones((b, sq), np.int32)
    masks[0] = tree.masks
    masks[1, :sq] = tree.masks  # prefix rows are the ones compared
    rng = np.random.default_rng(seed)
    tables = np.full((b, max_pages), n_pages, np.int32)
    for i in range(b):
        need = -(-int(history[i] + chunk_lens[i]) // page)
        if need:
            tables[i, :need] = rng.choice(n_pages, size=need,
                                          replace=False)
    return (q, k_pool, v_pool, jnp.asarray(tables), history,
            chunk_lens, jnp.asarray(masks))


@pytest.mark.parametrize("branches", [1, 2, 4])
@pytest.mark.parametrize("hq,hkv", [(4, 4),   # GQA group 1
                                    (8, 2)])  # GQA group 4
def test_tree_kernel_matches_xla(branches, hq, hkv):
    (q, k_pool, v_pool, tables, history, chunk_lens,
     masks) = _tree_case(61 + branches, branches=branches, hq=hq,
                         hkv=hkv)
    got = paged_tree_attention_pallas(q, k_pool, v_pool, tables,
                                      history, chunk_lens, masks,
                                      interpret=True)
    want = paged_tree_attention_xla(q, k_pool, v_pool, tables,
                                    history, chunk_lens, masks)
    assert not np.isnan(np.asarray(got)).any()
    for i in range(3):
        n = int(chunk_lens[i])  # rows past chunk_len are padding
        np.testing.assert_allclose(np.asarray(got)[i, :n],
                                   np.asarray(want)[i, :n],
                                   rtol=2e-5, atol=2e-5)
    # the zero-length tail slot returns exact zeros on both paths
    np.testing.assert_allclose(np.asarray(got)[2], 0.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(want)[2], 0.0, atol=1e-6)


def test_tree_chain_reduces_to_causal_chunk():
    """A chain-shaped tree's ancestor bitmask IS the causal window:
    the tree kernel must match the chunk kernel on it (speculation's
    greedy bit-identity rides this)."""
    (q, k_pool, v_pool, tables, history, chunk_lens,
     masks) = _tree_case(67, branches=1, hq=8, hkv=2)
    got = paged_tree_attention_pallas(q, k_pool, v_pool, tables,
                                      history, chunk_lens, masks,
                                      interpret=True)
    want = paged_chunk_attention_pallas(q, k_pool, v_pool, tables,
                                        history, chunk_lens,
                                        interpret=True)
    for i in range(3):
        n = int(chunk_lens[i])
        np.testing.assert_allclose(np.asarray(got)[i, :n],
                                   np.asarray(want)[i, :n],
                                   rtol=2e-5, atol=2e-5)


def test_tree_sibling_cannot_see_sibling():
    """Poisoning a sibling branch's pool rows must not change a node's
    output — only ancestors are visible in-tree."""
    (q, k_pool, v_pool, tables, history, chunk_lens,
     masks) = _tree_case(71, branches=2, hq=4, hkv=4)
    tree = build_draft_tree(0, _branch_chains(2))
    clean = paged_tree_attention_pallas(q, k_pool, v_pool, tables,
                                        history, chunk_lens, masks,
                                        interpret=True)
    # poison the LAST node's pool row for slot 0 (a leaf on the other
    # fork): nodes not descending from it must be unchanged
    leaf = tree.n_nodes - 1
    pos = int(history[0]) + leaf
    pid = int(tables[0, pos // k_pool.shape[2]])
    poisoned = np.asarray(k_pool).copy()
    poisoned[:, pid, pos % k_pool.shape[2]] = 1e6
    got = paged_tree_attention_pallas(q, jnp.asarray(poisoned), v_pool,
                                      tables, history, chunk_lens,
                                      masks, interpret=True)
    unaffected = [i for i in range(tree.n_nodes)
                  if not (tree.masks[i] >> leaf) & 1]
    np.testing.assert_allclose(np.asarray(got)[0, unaffected],
                               np.asarray(clean)[0, unaffected],
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("branches", [2, 4])
def test_int8_tree_kernel_matches_int8_xla(branches):
    (q, k_pool, v_pool, tables, history, chunk_lens,
     masks) = _tree_case(73 + branches, branches=branches, hq=8, hkv=2)
    kq, vq = quantize_pool(k_pool), quantize_pool(v_pool)
    got = paged_tree_attention_pallas(q, kq, vq, tables, history,
                                      chunk_lens, masks,
                                      interpret=True)
    want = paged_tree_attention_xla(q, kq, vq, tables, history,
                                    chunk_lens, masks)
    assert not np.isnan(np.asarray(got)).any()
    for i in range(3):
        n = int(chunk_lens[i])
        np.testing.assert_allclose(np.asarray(got)[i, :n],
                                   np.asarray(want)[i, :n],
                                   rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got)[2], 0.0, atol=1e-6)


def test_bf16_tree_pools_within_cast_bound():
    (q, k_pool, v_pool, tables, history, chunk_lens,
     masks) = _tree_case(79, branches=2, hq=4, hkv=4,
                         dtype=jnp.bfloat16)
    got = paged_tree_attention_pallas(q, k_pool, v_pool, tables,
                                      history, chunk_lens, masks,
                                      interpret=True)
    want = paged_tree_attention_xla(q, k_pool, v_pool, tables,
                                    history, chunk_lens, masks)
    for i in range(3):
        n = int(chunk_lens[i])
        np.testing.assert_allclose(
            np.asarray(got, np.float32)[i, :n],
            np.asarray(want, np.float32)[i, :n], atol=2e-2)


# ------------------------------------- len-0 slot kernel/XLA parity
#
# The Pallas kernels return exact zeros for zero-length slots (denom
# clamp + masked DMA); the _xla fallbacks used to let the dense
# softmax degrade to an unmasked average over garbage rows there,
# leaving the engine's discard of inactive-slot tokens load-bearing
# for correctness. All three fallbacks now zero len-0 rows, so which
# path served a pass can never leak into output bytes — the integrity
# plane's digest parity (serving/integrity.py) rides this. These pin
# exact (atol=0) zeros on BOTH paths for every kernel family.

def test_len0_slot_zeroed_on_both_paths_decode():
    case = _random_paged_case(jax.random.key(91), lengths=(0, 8, 16))
    q, k_pool, v_pool, tables, lengths, *_ = case
    kernel = paged_decode_attention_pallas(q, k_pool, v_pool, tables,
                                           lengths, interpret=True)
    fallback = paged_decode_attention_xla(q, k_pool, v_pool, tables,
                                          lengths)
    assert not np.isnan(np.asarray(fallback)).any()
    np.testing.assert_array_equal(np.asarray(kernel)[0],
                                  np.zeros_like(np.asarray(kernel)[0]))
    np.testing.assert_array_equal(np.asarray(fallback)[0],
                                  np.zeros_like(np.asarray(fallback)[0]))


def test_len0_slot_zeroed_on_both_paths_chunk():
    (q, k_pool, v_pool, _, _, tables, history,
     chunk_lens) = _quant_chunk_case(97, page=8, hq=4, hkv=4)
    kernel = paged_chunk_attention_pallas(q, k_pool, v_pool, tables,
                                          history, chunk_lens,
                                          interpret=True)
    fallback = paged_chunk_attention_xla(q, k_pool, v_pool, tables,
                                         history, chunk_lens)
    assert not np.isnan(np.asarray(fallback)).any()
    # slot 2 has history == chunk == 0: every row is dead padding
    np.testing.assert_array_equal(np.asarray(kernel)[2],
                                  np.zeros_like(np.asarray(kernel)[2]))
    np.testing.assert_array_equal(np.asarray(fallback)[2],
                                  np.zeros_like(np.asarray(fallback)[2]))


def test_len0_slot_zeroed_on_both_paths_tree():
    (q, k_pool, v_pool, tables, history, chunk_lens,
     masks) = _tree_case(101, branches=2, hq=4, hkv=4)
    kernel = paged_tree_attention_pallas(q, k_pool, v_pool, tables,
                                         history, chunk_lens, masks,
                                         interpret=True)
    fallback = paged_tree_attention_xla(q, k_pool, v_pool, tables,
                                        history, chunk_lens, masks)
    assert not np.isnan(np.asarray(fallback)).any()
    np.testing.assert_array_equal(np.asarray(kernel)[2],
                                  np.zeros_like(np.asarray(kernel)[2]))
    np.testing.assert_array_equal(np.asarray(fallback)[2],
                                  np.zeros_like(np.asarray(fallback)[2]))


def test_tree_dispatch_auto_on_cpu_matches_dense():
    (q, k_pool, v_pool, tables, history, chunk_lens,
     masks) = _tree_case(83, branches=2, hq=8, hkv=2)
    got = paged_tree_attention(q, k_pool, v_pool, tables, history,
                               chunk_lens, masks,
                               implementation="auto")
    safe = jnp.minimum(tables, k_pool.shape[1] - 1)
    k_dense = k_pool[:, safe].transpose(1, 2, 3, 0, 4).reshape(
        3, -1, k_pool.shape[0], k_pool.shape[3])
    v_dense = v_pool[:, safe].transpose(1, 2, 3, 0, 4).reshape(
        3, -1, v_pool.shape[0], v_pool.shape[3])
    want = tree_attention(q, k_dense, v_dense, history_lens=history,
                          chunk_lens=chunk_lens, tree_masks=masks)
    for i in range(3):
        n = int(chunk_lens[i])
        np.testing.assert_allclose(np.asarray(got)[i, :n],
                                   np.asarray(want)[i, :n],
                                   rtol=2e-5, atol=2e-5)
