"""Fleet front-door router tests: digest hashing + plan scoring,
session affinity, typed-retry failover against live upstreams, the
``/control/leave`` interaction (draining host stops receiving routes
immediately, in-flight streams finish, affinity entries drop), the
engine's prefix-digest export, and autoscale decisions — deterministic
clocks throughout, no sleeps around race windows.

The live-proxy tests boot a REAL leader app (``serve_fleet_leader``
with a ``RouterConfig``) in front of real worker apps whose handlers
are scripted (echo / stream / typed-503) — the full HTTP proxy path
without engine weight.
"""

import asyncio
import http.client
import json
import threading

import pytest

from gofr_tpu.http.responder import ResponseData
from gofr_tpu.serving.router import (Autoscaler, FleetRouter,
                                     RouterConfig, SessionAffinity,
                                     aligned_prefix_hashes, prefix_hash)

from .apputil import AppRunner


# ------------------------------------------------------- digest helpers
class TestDigestHelpers:
    def test_prefix_hash_is_stable_and_content_keyed(self):
        assert prefix_hash((1, 2, 3)) == prefix_hash([1, 2, 3])
        assert prefix_hash((1, 2, 3)) != prefix_hash((1, 2, 4))
        assert len(prefix_hash(range(100))) == 16

    def test_aligned_hashes_longest_first_and_leave_a_suffix(self):
        prompt = list(range(9))  # page 4: aligned prefixes 4 and 8
        got = aligned_prefix_hashes(prompt, 4, 64)
        assert [c for c, _ in got] == [8, 4]
        assert got[0][1] == prefix_hash(prompt[:8])
        # exactly page-aligned length: the full prompt may NOT be a
        # candidate (the engine always leaves >= 1 suffix token)
        got = aligned_prefix_hashes(list(range(8)), 4, 64)
        assert [c for c, _ in got] == [4]

    def test_max_pages_bounds_the_probe(self):
        got = aligned_prefix_hashes(list(range(100)), 4, 2)
        assert [c for c, _ in got] == [8, 4]

    def test_short_prompt_has_no_candidates(self):
        assert aligned_prefix_hashes([1, 2], 4, 64) == []


# ------------------------------------------------------ session affinity
class TestSessionAffinity:
    def test_lru_bound_evicts_oldest(self):
        aff = SessionAffinity(2)
        aff.put("a", "h1")
        aff.put("b", "h2")
        aff.get("a")          # touch: b becomes LRU
        aff.put("c", "h3")
        assert aff.get("a") == "h1"
        assert aff.get("b") is None
        assert aff.get("c") == "h3"

    def test_drop_host_sweeps_only_that_host(self):
        aff = SessionAffinity(8)
        for s, h in (("a", "h1"), ("b", "h2"), ("c", "h1")):
            aff.put(s, h)
        assert aff.drop_host("h1") == 2
        assert aff.get("a") is None and aff.get("c") is None
        assert aff.get("b") == "h2"

    def test_zero_size_disables(self):
        aff = SessionAffinity(0)
        aff.put("a", "h1")
        assert aff.get("a") is None


# ----------------------------------------------------------- plan scoring
class FakeLeader:
    """routing_view/evict surface of ControlPlaneLeader, no threads."""

    def __init__(self, members):
        self.members = members
        self.evict_listeners = []
        self.status_sources = {}
        self.evicted = []

    def routing_view(self):
        return [dict(m, summary=dict(m["summary"]))
                for m in self.members]

    def add_evict_listener(self, fn):
        self.evict_listeners.append(fn)

    def evict(self, host_id, reason="manual"):
        self.evicted.append((host_id, reason))
        self.members = [m for m in self.members
                        if m["host_id"] != host_id]
        for fn in self.evict_listeners:
            fn(host_id, reason)


def member(host, *, hashes=(), page=4, active=0, waiting=0,
           pass_p50=0.01, status="UP"):
    return {"host_id": host, "address": f"127.0.0.1:1{host[-1]}",
            "status": status,
            "summary": {"active_slots": active, "waiting": waiting,
                        "pass_p50_s": pass_p50,
                        "prefix_digest": {"page": page,
                                          "hashes": list(hashes)}}}


PROMPT = list(range(20))  # page 4: candidates 16, 12, 8, 4


class TestPlan:
    def test_longest_prefix_match_wins_over_load(self):
        owner = member("w1", hashes=[prefix_hash(PROMPT[:8])],
                       active=3, waiting=4)
        idle = member("w2")
        router = FleetRouter(FakeLeader([idle, owner]))
        plan = router.plan(PROMPT)
        assert [c["host_id"] for c in plan] == ["w1", "w2"]
        assert plan[0]["covered"] == 8

    def test_longer_coverage_beats_shorter(self):
        short = member("w1", hashes=[prefix_hash(PROMPT[:4])])
        long = member("w2", hashes=[prefix_hash(PROMPT[:16])])
        router = FleetRouter(FakeLeader([short, long]))
        plan = router.plan(PROMPT)
        assert plan[0]["host_id"] == "w2" and plan[0]["covered"] == 16

    def test_load_tiebreak_uses_depth_times_sec_per_token(self):
        # w1: 6 in flight at 10ms/token = 0.06; w2: 2 at 20ms = 0.04
        busy_fast = member("w1", active=4, waiting=2, pass_p50=0.01)
        calm_slow = member("w2", active=1, waiting=1, pass_p50=0.02)
        router = FleetRouter(FakeLeader([busy_fast, calm_slow]))
        assert router.plan(PROMPT)[0]["host_id"] == "w2"

    def test_affinity_moves_its_host_to_front(self):
        owner = member("w1", hashes=[prefix_hash(PROMPT[:8])])
        other = member("w2")
        router = FleetRouter(FakeLeader([owner, other]))
        router.affinity.put("s1", "w2")
        plan = router.plan(PROMPT, session="s1")
        assert plan[0]["host_id"] == "w2" and plan[0]["affinity"]
        assert plan[1]["host_id"] == "w1"

    def test_evict_drops_affinity_and_the_member(self):
        leader = FakeLeader([member("w1"), member("w2")])
        router = FleetRouter(leader)
        router.affinity.put("s1", "w1")
        leader.evict("w1", reason="leave")
        assert router.affinity.get("s1") is None
        assert [c["host_id"] for c in router.plan(PROMPT)] == ["w2"]

    def test_non_up_members_are_never_candidates(self):
        leader = FakeLeader([member("w1", status="DOWN"), member("w2")])
        router = FleetRouter(leader)
        assert [c["host_id"] for c in router.plan(PROMPT)] == ["w2"]

    def test_round_robin_rotates(self):
        leader = FakeLeader([member("w1"), member("w2")])
        router = FleetRouter(leader,
                             RouterConfig(policy="round_robin"))
        first = [router.plan(PROMPT)[0]["host_id"] for _ in range(4)]
        assert first == ["w1", "w2", "w1", "w2"]


# -------------------------------------------------- engine digest export
@pytest.fixture(scope="module")
def paged_engine():
    from gofr_tpu.serving.engine import EngineConfig
    from gofr_tpu.serving.glue import demo_llama_engine
    engine = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, kv_layout="paged", page_size=4,
        prefix_digest_hashes=2, seed=0))
    yield engine
    engine.stop()


class TestEngineDigest:
    def _pin(self, engine, key):
        engine._prefix_cache[tuple(key)] = []
        engine._prefix_digest_dirty = True

    def test_digest_reflects_cache_and_rides_fleet_summary(self,
                                                           paged_engine):
        e = paged_engine
        e._prefix_cache.clear()
        key = tuple(range(8))
        self._pin(e, key)
        e._refresh_prefix_digest()
        d = e.prefix_digest()
        assert d["page"] == 4 and d["entries"] == 1
        assert d["hashes"] == [prefix_hash(key)]
        assert e.recorder.fleet_summary()["prefix_digest"] == d

    def test_bound_keeps_the_newest_lru_entries(self, paged_engine):
        e = paged_engine
        e._prefix_cache.clear()
        keys = [tuple(range(n)) for n in (4, 8, 12)]
        for k in keys:
            self._pin(e, k)
        e._refresh_prefix_digest()
        d = e.prefix_digest()
        # prefix_digest_hashes=2: only the two newest keys are hashed,
        # but entries still reports the real cache size
        assert d["entries"] == 3
        assert d["hashes"] == [prefix_hash(k) for k in keys[-2:]]

    def test_clean_flag_skips_reassembly(self, paged_engine):
        e = paged_engine
        e._prefix_cache.clear()
        self._pin(e, range(4))
        e._refresh_prefix_digest()
        before = e.prefix_digest()
        e._prefix_cache[tuple(range(20, 28))] = []  # no dirty mark
        e._refresh_prefix_digest()
        assert e.prefix_digest() is before  # same object: no rebuild

    def test_reset_clears_and_marks_dirty(self, paged_engine):
        e = paged_engine
        self._pin(e, range(4))
        e._refresh_prefix_digest()
        e._reset_runtime_state()
        assert e._prefix_digest_dirty
        e._refresh_prefix_digest()
        assert e.prefix_digest()["hashes"] == []

    def test_digest_boundary_is_declared(self):
        from gofr_tpu.serving.engine import Engine
        reason = getattr(Engine._refresh_prefix_digest,
                         "__gofr_hot_path_boundary__", "")
        assert isinstance(reason, str) and reason.strip()


# ------------------------------------------------------------ autoscaler
class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


def load_view(*loads, occ=0.5):
    return [{"host_id": f"w{i}",
             "summary": {"active_slots": load, "waiting": 0,
                         "occupancy_mean": occ}}
            for i, load in enumerate(loads)]


class TestAutoscaler:
    def cfg(self, **kw):
        kw.setdefault("autoscale", True)
        kw.setdefault("setpoint_concurrency", 4)
        kw.setdefault("sustain_s", 10.0)
        kw.setdefault("cooldown_s", 30.0)
        return RouterConfig(**kw)

    def test_sustained_pressure_scales_up(self):
        clock = FakeClock()
        scaler = Autoscaler(self.cfg(), clock=clock)
        assert scaler.observe(load_view(6, 6)) is None  # arming tick
        clock.advance(9.9)
        assert scaler.observe(load_view(6, 6)) is None  # not sustained
        clock.advance(0.2)
        decision = scaler.observe(load_view(6, 6))
        assert decision and decision["action"] == "scale_up"

    def test_blip_rearms_the_sustain_window(self):
        clock = FakeClock()
        scaler = Autoscaler(self.cfg(), clock=clock)
        scaler.observe(load_view(6, 6))
        clock.advance(8)
        scaler.observe(load_view(1, 1))        # pressure lapsed
        clock.advance(4)
        assert scaler.observe(load_view(6, 6)) is None  # re-armed

    def test_sustained_idle_scales_down_least_loaded(self):
        clock = FakeClock()
        scaler = Autoscaler(self.cfg(), clock=clock)
        view = load_view(2, 1, occ=0.01)
        scaler.observe(view)
        clock.advance(11)
        decision = scaler.observe(view)
        assert decision["action"] == "scale_down"
        assert decision["victim"] == "w1"

    def test_single_host_never_scales_down(self):
        clock = FakeClock()
        scaler = Autoscaler(self.cfg(), clock=clock)
        scaler.observe(load_view(0, occ=0.0))
        clock.advance(60)
        assert scaler.observe(load_view(0, occ=0.0)) is None

    def test_cooldown_spaces_decisions(self):
        clock = FakeClock()
        scaler = Autoscaler(self.cfg(), clock=clock)
        scaler.observe(load_view(6, 6))
        clock.advance(11)
        assert scaler.observe(load_view(6, 6))["action"] == "scale_up"
        clock.advance(11)
        assert scaler.observe(load_view(6, 6)) is None  # cooling down
        clock.advance(31)
        assert scaler.observe(load_view(6, 6))["action"] == "scale_up"

    def test_act_mode_routes_scale_down_through_leader_evict(self):
        clock = FakeClock()
        leader = FakeLeader([member("w0"), member("w1")])
        router = FleetRouter(
            leader, self.cfg(autoscale_act=True, idle_occupancy=0.10),
            clock=clock)
        router.autoscaler.observe(load_view(1, 2, occ=0.01))
        clock.advance(11)
        decision = router.autoscaler.observe(load_view(1, 2, occ=0.01))
        assert decision["action"] == "scale_down"
        assert leader.evicted == [("w0", "scale_down")]

    def test_setpoint_file_read(self, tmp_path):
        path = tmp_path / "setpoint.json"
        path.write_text(json.dumps({"max_concurrency": 7, "qps": 3.2}))
        scaler = Autoscaler(self.cfg(setpoint_concurrency=0))
        scaler.load_setpoint_file(str(path))
        assert scaler.setpoint == 7
        scaler.load_setpoint_file(str(tmp_path / "missing.json"))
        assert scaler.setpoint == 7  # unreadable file keeps the old


# ------------------------------------------------------ live proxy tests
def build_worker(app):
    """A scripted worker: echo /chat (with the host name), a gated SSE
    stream, and typed-503 / bare-503 / 429 modes."""
    state = {"name": "?", "hits": 0, "mode": "ok",
             "started": threading.Event(),
             "release": threading.Event()}
    app._test_state = state

    @app.post("/chat")
    async def chat(ctx):
        state["hits"] += 1
        if state["mode"] == "draining":
            return ResponseData(
                status=503, headers={"Retry-After": "1"},
                body=json.dumps({"error": {
                    "message": "draining",
                    "details": {"code": "draining"}}}).encode())
        if state["mode"] == "plain_503":
            return ResponseData(status=503, body=json.dumps(
                {"error": {"message": "wedged"}}).encode())
        if state["mode"] == "rate_limited":
            return ResponseData(
                status=429, headers={"Retry-After": "2"},
                body=json.dumps({"error": {
                    "message": "slow down",
                    "details": {"code": "rate_limited"}}}).encode())
        body = ctx.bind() or {}
        if body.get("stream"):
            async def sse():
                state["started"].set()
                yield "data: first\n\n"
                while not state["release"].is_set():
                    await asyncio.sleep(0.005)
                yield "data: second\n\n"
                yield "data: [DONE]\n\n"
            return ResponseData(content_type="text/event-stream",
                                stream=sse())
        return {"host": state["name"],
                "echo": body.get("prompt", "")}


def build_leader(app):
    app._leader = app.serve_fleet_leader(
        router=RouterConfig(max_retries=2, affinity_size=16))


@pytest.fixture()
def fleet():
    with AppRunner(build=build_leader) as leader, \
            AppRunner(build=build_worker) as w1, \
            AppRunner(build=build_worker) as w2:
        w1.app._test_state["name"] = "w1"
        w2.app._test_state["name"] = "w2"
        control = leader.app._leader
        control.join("w1", f"127.0.0.1:{w1.port}", 1)
        control.join("w2", f"127.0.0.1:{w2.port}", 1)
        yield leader, w1, w2


def post_chat(runner, body, headers=None):
    hdrs = {"Content-Type": "application/json", **(headers or {})}
    return runner.request("POST", "/chat", body=json.dumps(body),
                          headers=hdrs)


class TestLiveProxy:
    def test_proxies_and_pins_session(self, fleet):
        leader, w1, w2 = fleet
        status, _, body = post_chat(
            leader, {"prompt": "hello", "session": "s1"})
        assert status == 201, body
        first_host = json.loads(body)["data"]["host"]
        runner = {"w1": w1, "w2": w2}[first_host]
        for _ in range(3):
            status, _, body = post_chat(
                leader, {"prompt": "again", "session": "s1"})
            assert status == 201
            assert json.loads(body)["data"]["host"] == first_host
        assert runner.app._test_state["hits"] == 4
        router = leader.app._leader.router
        state = router.debug_state()
        assert state["affinity"]["hits"] >= 3
        assert state["routed_total"] == 4

    def test_session_header_works_like_the_body_field(self, fleet):
        leader, w1, w2 = fleet
        status, _, body = post_chat(leader, {"prompt": "x"},
                                    headers={"X-Session-Id": "hdr"})
        assert status == 201
        host = json.loads(body)["data"]["host"]
        assert leader.app._leader.router.affinity.get("hdr") == host

    def test_typed_503_fails_over_to_the_survivor(self, fleet):
        leader, w1, w2 = fleet
        w1.app._test_state["mode"] = "draining"
        w2.app._test_state["mode"] = "draining"
        # pin the session to w1 so the draining host is first choice
        leader.app._leader.router.affinity.put("s", "w1")
        w2.app._test_state["mode"] = "ok"
        status, _, body = post_chat(
            leader, {"prompt": "failover", "session": "s"})
        assert status == 201, body
        assert json.loads(body)["data"]["host"] == "w2"
        assert w1.app._test_state["hits"] == 1  # refused once
        state = leader.app._leader.router.debug_state()
        assert state["retries"] >= 1
        # the session re-pins to the host that actually served
        assert leader.app._leader.router.affinity.get("s") == "w2"

    def test_429_mirrors_immediately_with_retry_after(self, fleet):
        leader, w1, w2 = fleet
        for w in (w1, w2):
            w.app._test_state["mode"] = "rate_limited"
        status, headers, body = post_chat(leader, {"prompt": "x"})
        assert status == 429
        assert headers.get("Retry-After") == "2"
        assert w1.app._test_state["hits"] \
            + w2.app._test_state["hits"] == 1  # no failover on 429

    def test_untyped_503_is_not_retried(self, fleet):
        leader, w1, w2 = fleet
        for w in (w1, w2):
            w.app._test_state["mode"] = "plain_503"
        status, _, _ = post_chat(leader, {"prompt": "x"})
        assert status == 503
        assert w1.app._test_state["hits"] \
            + w2.app._test_state["hits"] == 1

    def test_all_hosts_draining_mirrors_the_last_503(self, fleet):
        leader, w1, w2 = fleet
        for w in (w1, w2):
            w.app._test_state["mode"] = "draining"
        status, headers, body = post_chat(leader, {"prompt": "x"})
        assert status == 503
        assert headers.get("Retry-After") == "1"
        assert json.loads(body)["error"]["details"]["code"] == "draining"

    def test_leave_mid_stream_finishes_and_drops_routes(self, fleet):
        """Satellite: /control/leave x router. The in-flight stream
        runs to completion while the departed host stops receiving
        new routes the moment the leave lands — no sleeps, the gate
        is event-driven."""
        leader, w1, w2 = fleet
        leader.app._leader.router.affinity.put("s", "w1")
        result = {}

        def streaming_request():
            conn = http.client.HTTPConnection("127.0.0.1", leader.port,
                                              timeout=30)
            try:
                conn.request(
                    "POST", "/chat",
                    body=json.dumps({"prompt": "x", "stream": True,
                                     "session": "s"}),
                    headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                result["status"] = resp.status
                result["body"] = resp.read().decode()
            finally:
                conn.close()

        t = threading.Thread(target=streaming_request)
        t.start()
        assert w1.app._test_state["started"].wait(10), \
            "stream never reached w1"
        # leave lands while the stream is mid-flight
        status, _, _ = leader.request(
            "POST", "/control/leave",
            body=json.dumps({"host_id": "w1"}),
            headers={"Content-Type": "application/json"})
        assert status == 201
        # new routes skip w1 immediately — even for the pinned session
        assert leader.app._leader.router.affinity.get("s") is None
        s2, _, body2 = post_chat(leader,
                                 {"prompt": "after", "session": "s"})
        assert s2 == 201 and json.loads(body2)["data"]["host"] == "w2"
        hits_before = w1.app._test_state["hits"]
        # the in-flight stream still finishes with its terminal chunk
        w1.app._test_state["release"].set()
        t.join(10)
        assert not t.is_alive()
        assert result["status"] == 200
        assert result["body"].count("data:") == 3
        assert result["body"].rstrip().endswith("data: [DONE]")
        assert w1.app._test_state["hits"] == hits_before

    def test_client_abort_cancels_upstream_and_counts(self, fleet):
        """Satellite: client-abort propagation. The downstream client
        half-closes its socket mid-stream; the next chunk write fails,
        the router closes the proxied upstream instead of draining it,
        and ``app_router_client_aborts`` counts the abort. Event-gated
        and deadline-polled — no fixed sleeps."""
        leader, w1, w2 = fleet
        leader.app._leader.router.affinity.put("s", "w1")
        conn = http.client.HTTPConnection("127.0.0.1", leader.port,
                                          timeout=30)
        conn.request("POST", "/chat",
                     body=json.dumps({"prompt": "x", "stream": True,
                                      "session": "s"}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert w1.app._test_state["started"].wait(10)
        # the client walks away after the first chunk
        conn.sock.recv(1)  # ensure the first write landed
        conn.close()
        # unblock the worker: the router's NEXT chunk write hits the
        # dead client socket and must cancel the upstream
        w1.app._test_state["release"].set()
        router = leader.app._leader.router
        deadline = threading.Event()
        for _ in range(1000):
            if router.debug_state()["client_aborts"] >= 1:
                break
            deadline.wait(0.01)
        assert router.debug_state()["client_aborts"] == 1
        # the abort rode the metrics surface too
        status, _, text = leader.request("GET", "/metrics",
                                         port=leader.metrics_port)
        assert status == 200
        assert "app_router_client_aborts 1" in text.decode()
        # the fleet is healthy: the released slot serves new traffic
        s2, _, body2 = post_chat(leader, {"prompt": "after"})
        assert s2 == 201, body2

    def test_no_members_is_a_typed_503(self):
        with AppRunner(build=build_leader) as leader:
            status, _, body = post_chat(leader, {"prompt": "x"})
            assert status == 503, body

    def test_router_metrics_and_debug_fleet(self, fleet):
        leader, w1, w2 = fleet
        assert post_chat(leader, {"prompt": "x"})[0] == 201
        status, _, body = leader.request("GET", "/debug/fleet")
        assert status == 200
        doc = json.loads(body)["data"]
        assert doc["router"]["routed_total"] >= 1
        assert doc["router"]["policy"] == "prefix"
        status, _, text = leader.request("GET", "/metrics",
                                         port=leader.metrics_port)
        assert status == 200
        assert "app_router_routed" in text.decode()
        assert "app_router_cache_hit_ratio" in text.decode()


# ------------------------------------------------------- routing text
class TestRoutingText:
    def test_openai_chat_path_matches_the_worker_template(self):
        from gofr_tpu.serving.openai_compat import _render_messages
        messages = [{"role": "system", "content": "be terse"},
                    {"role": "user", "content": "hi"}]
        assert FleetRouter.routing_text(
            "/v1/chat/completions", {"messages": messages}) \
            == _render_messages(messages)

    def test_chat_path_joins_message_contents(self):
        body = {"messages": [{"content": "a"}, {"content": "b"}]}
        assert FleetRouter.routing_text("/chat", body) == "a\nb"
        assert FleetRouter.routing_text("/chat", {"prompt": "p"}) == "p"

    def test_malformed_bodies_route_by_load_alone(self):
        assert FleetRouter.routing_text("/chat", {}) == ""
        assert FleetRouter.routing_text(
            "/v1/chat/completions", {"messages": "nope"}) == ""
