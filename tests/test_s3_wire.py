"""S3 wire client: SigV4-signed REST against the verifying mini server
(reference datasource/file/s3's network-client role). The mini server
re-derives every signature, so these tests prove the signing chain."""

import pytest

from gofr_tpu.datasource.object_store import ObjectNotFound
from gofr_tpu.datasource.s3_wire import MiniS3Server, S3Error, S3Wire


@pytest.fixture()
def server():
    srv = MiniS3Server(access_key="AKID", secret_key="s3cr3t")
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def client(server):
    c = S3Wire(endpoint=f"127.0.0.1:{server.port}", bucket="data",
               access_key="AKID", secret_key="s3cr3t")
    c.connect()
    c.create_bucket()
    return c


def test_put_get_delete_roundtrip(client):
    client.put_object("reports/q1.txt", b"hello s3")
    assert client.get_object("reports/q1.txt") == b"hello s3"
    assert client.exists("reports/q1.txt")
    client.delete_object("reports/q1.txt")
    assert not client.exists("reports/q1.txt")
    with pytest.raises(ObjectNotFound):
        client.get_object("reports/q1.txt")


def test_list_objects_with_prefix(client):
    client.put_object("a/1", b"x")
    client.put_object("a/2", b"yy")
    client.put_object("b/3", b"zzz")
    keys = {o["Key"] for o in client.list_objects()}
    assert keys == {"a/1", "a/2", "b/3"}
    under_a = client.list_objects(prefix="a/")
    assert {o["Key"] for o in under_a} == {"a/1", "a/2"}
    assert {o["Size"] for o in under_a} == {1, 2}


def test_list_objects_follows_pagination(server, client):
    """The client must walk IsTruncated/NextContinuationToken to the
    end — real S3 truncates at max-keys (default 1000)."""
    for i in range(7):
        client.put_object(f"p/{i:02d}", b"v")
    pages = []
    orig = client._call

    def spy(method, path, query=None, body=b""):
        if query and query.get("list-type") == "2":
            # shrink the page size so truncation actually happens
            query = dict(query, **{"max-keys": "3"})
            pages.append(query.get("continuation-token", ""))
        return orig(method, path, query, body)

    client._call = spy
    try:
        keys = [o["Key"] for o in client.list_objects(prefix="p/")]
    finally:
        client._call = orig
    assert keys == [f"p/{i:02d}" for i in range(7)]
    assert len(pages) == 3  # 3+3+1 across three requests


def test_exists_true_false_and_error(server, client):
    client.put_object("here", b"x")
    assert client.exists("here") is True
    assert client.exists("absent") is False
    bad = S3Wire(endpoint=f"127.0.0.1:{server.port}", bucket="data",
                 access_key="AKID", secret_key="WRONG")
    with pytest.raises(S3Error, match="403"):
        bad.exists("here")  # auth trouble must not read as "absent"


def test_wrong_secret_is_rejected(server):
    bad = S3Wire(endpoint=f"127.0.0.1:{server.port}", bucket="data",
                 access_key="AKID", secret_key="WRONG")
    with pytest.raises(S3Error, match="403"):
        bad.put_object("k", b"v")


def test_wrong_access_key_is_rejected(server):
    bad = S3Wire(endpoint=f"127.0.0.1:{server.port}", bucket="data",
                 access_key="NOPE", secret_key="s3cr3t")
    with pytest.raises(S3Error, match="403"):
        bad.put_object("k", b"v")


def test_tampered_body_breaks_signature(server, client):
    """The payload hash is part of the signature: the server must
    reject a body that doesn't match the signed hash."""
    import urllib.request

    from gofr_tpu.datasource.s3_wire import sign_v4
    headers = sign_v4("PUT", "/data/k", {},
                      {"host": f"127.0.0.1:{server.port}"}, b"original",
                      access_key="AKID", secret_key="s3cr3t",
                      region="us-east-1")
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/data/k", data=b"TAMPERED",
        method="PUT", headers=headers)
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(req, timeout=10)
    assert exc_info.value.code == 403


def test_health_check(client, server):
    assert client.health_check()["status"] == "UP"
    server.close()
    assert client.health_check()["status"] == "DOWN"
