"""DynamoDB JSON-1.0 wire client (SigV4-signed) against the mini
server."""

import pytest

from gofr_tpu.datasource.dynamo_wire import (DynamoError, DynamoKV,
                                             MiniDynamoServer)
from gofr_tpu.datasource.kv import KeyNotFound


@pytest.fixture(scope="module")
def server():
    srv = MiniDynamoServer(access_key="AKID", secret_key="s3cr3t")
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def kv(server):
    client = DynamoKV(endpoint=f"127.0.0.1:{server.port}",
                      table="t", access_key="AKID", secret_key="s3cr3t")
    client.connect()
    return client


def test_kv_roundtrip(kv):
    kv.set("alpha", "1")
    kv.set("beta", "two")
    assert kv.get("alpha") == "1"
    kv.set("alpha", "updated")
    assert kv.get("alpha") == "updated"
    kv.delete("alpha")
    with pytest.raises(KeyNotFound):
        kv.get("alpha")
    kv.delete("alpha")  # idempotent, like the other KV backends
    kv.delete("beta")


def test_keys_follow_scan_pagination(kv, monkeypatch):
    for i in range(7):
        kv.set(f"p{i}", "x")
    monkeypatch.setattr("gofr_tpu.datasource.dynamo_wire._SCAN_PAGE", 3)
    assert kv.keys() == [f"p{i}" for i in range(7)]
    for i in range(7):
        kv.delete(f"p{i}")


def test_wrong_secret_rejected(server):
    bad = DynamoKV(endpoint=f"127.0.0.1:{server.port}", table="t",
                   access_key="AKID", secret_key="WRONG")
    with pytest.raises(DynamoError, match="403"):
        bad.set("k", "v")
    assert bad.health_check()["status"] == "DOWN"


def test_unicode_values(kv):
    kv.set("uni", "héllo ∆ 中文")
    assert kv.get("uni") == "héllo ∆ 中文"
    kv.delete("uni")


def test_health(kv):
    assert kv.health_check()["status"] == "UP"
    assert DynamoKV(endpoint="127.0.0.1:1",
                    table="t").health_check()["status"] == "DOWN"
