"""RESP2 network Redis client against the threaded mini server — real
protocol bytes over a real socket (miniredis pattern, SURVEY §4)."""

import pytest

from gofr_tpu.config.env import DictConfig
from gofr_tpu.datasource.redis import new_redis
from gofr_tpu.datasource.redis_wire import (
    MiniRedisServer,
    RedisWire,
    RESP2Error,
    encode_command,
)


@pytest.fixture()
def server():
    srv = MiniRedisServer()
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def client(server):
    c = RedisWire(host="127.0.0.1", port=server.port)
    c.connect()
    yield c
    c.close()


def test_encode_command_resp2_frame():
    assert encode_command("SET", "k", "v") == \
        b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n"


def test_strings_and_counters(client):
    assert client.ping()
    assert client.set("k", "hello")
    assert client.get("k") == "hello"
    assert client.get("missing") is None
    assert client.incr("n") == 1
    assert client.incr("n", 4) == 5
    assert client.decr("n") == 4
    assert client.delete("k", "n") == 2
    assert client.exists("k") == 0


def test_expiry_over_the_wire(client):
    client.set("tmp", "x", ex=100)
    assert 0 < client.ttl("tmp") <= 100
    assert client.expire("tmp", 50)
    assert client.ttl("tmp") <= 50
    assert client.ttl("nope") == -2


def test_hashes_lists_sets(client):
    client.hset("h", "a", "1")
    client.hset("h", "b", "2")
    assert client.hget("h", "a") == "1"
    assert client.hgetall("h") == {"a": "1", "b": "2"}
    assert client.hdel("h", "a") == 1

    client.rpush("l", "x", "y", "z")
    assert client.llen("l") == 3
    assert client.lrange("l", 0, -1) == ["x", "y", "z"]
    assert client.lpop("l") == "x"
    assert client.rpop("l") == "z"

    client.sadd("s", "a", "b")
    assert client.sismember("s", "a")
    assert client.smembers("s") == {"a", "b"}
    assert client.srem("s", "a") == 1


def test_keys_and_flush(client):
    client.set("user:1", "x")
    client.set("user:2", "y")
    client.set("other", "z")
    assert sorted(client.keys("user:*")) == ["user:1", "user:2"]
    assert client.flushdb()
    assert client.keys() == []


def test_server_error_is_raised_not_fatal(client):
    client.set("str", "x")
    with pytest.raises(RESP2Error):
        client.execute("HGET", "no")  # wrong arity -> -ERR reply
    # connection survives a server-side error
    assert client.get("str") == "x"


def test_wrongtype_error(client):
    client.set("str", "x")
    with pytest.raises(RESP2Error, match="WRONGTYPE"):
        client.hset("str", "f", "v")


def test_reconnects_after_server_restart(server, client):
    client.set("k", "1")
    server.close()
    with pytest.raises((RESP2Error, OSError)):
        client.get("k")
    # replacement server (fresh port — TIME_WAIT keeps the old one);
    # the client redials on next use
    srv2 = MiniRedisServer()
    srv2.start()
    client.port = srv2.port
    try:
        srv2.engine.set("k", "2")
        assert client.get("k") == "2"
    finally:
        srv2.close()


def test_health_check_up_down(server, client):
    assert client.health_check()["status"] == "UP"
    server.close()
    assert client.health_check()["status"] == "DOWN"


def test_new_redis_mode_switch(server):
    cfg = DictConfig({"REDIS_HOST": "127.0.0.1",
                      "REDIS_PORT": str(server.port),
                      "REDIS_MODE": "network"})
    r = new_redis(cfg)
    assert isinstance(r, RedisWire)
    assert r.set("via-env", "ok") and r.get("via-env") == "ok"
    r.close()

    from gofr_tpu.datasource.redis import Redis
    r2 = new_redis(DictConfig({"REDIS_HOST": "localhost"}))
    assert isinstance(r2, Redis)
