"""DBResolver: read/write routing, breakers, failover, primary pinning."""

from __future__ import annotations

import pytest

from gofr_tpu.datasource.dbresolver import (DBResolver, STRATEGY_RANDOM,
                                            primary_reads)
from gofr_tpu.datasource.sql import SQL, SQLError


def make_db(tag: str) -> SQL:
    db = SQL(database=":memory:")
    db.connect()
    db.exec("CREATE TABLE t (id INTEGER PRIMARY KEY, src TEXT)")
    db.exec("INSERT INTO t (src) VALUES (?)", tag)
    return db


class FailingDB:
    def __init__(self) -> None:
        self.calls = 0

    def query(self, *a):
        self.calls += 1
        raise SQLError("replica down")

    def use_logger(self, _):
        pass
    use_metrics = use_tracer = use_logger

    def connect(self):
        pass

    def close(self):
        pass

    def health_check(self):
        return {"status": "DOWN"}


def test_reads_round_robin_replicas_writes_hit_primary():
    primary, r1, r2 = make_db("p"), make_db("r1"), make_db("r2")
    res = DBResolver(primary, [r1, r2])
    seen = {res.query("SELECT src FROM t")[0]["src"] for _ in range(4)}
    assert seen == {"r1", "r2"}
    res.exec("INSERT INTO t (src) VALUES (?)", "w")
    assert len(primary.query("SELECT * FROM t")) == 2
    assert len(r1.query("SELECT * FROM t")) == 1
    assert res.stats["writes"] == 1
    assert res.stats["replica_reads"] == 4


def test_write_shaped_query_routes_to_primary():
    primary, r1 = make_db("p"), make_db("r1")
    res = DBResolver(primary, [r1])
    res.query("INSERT INTO t (src) VALUES ('via-query')")
    assert len(primary.query_row("SELECT COUNT(*) c FROM t").keys()) == 1
    assert len(primary.query("SELECT * FROM t")) == 2
    assert len(r1.query("SELECT * FROM t")) == 1


def test_primary_reads_context_pins():
    primary, r1 = make_db("p"), make_db("r1")
    res = DBResolver(primary, [r1])
    with primary_reads():
        assert res.query("SELECT src FROM t")[0]["src"] == "p"
    assert res.query("SELECT src FROM t")[0]["src"] == "r1"


def test_replica_failure_fails_over_and_breaker_opens():
    primary = make_db("p")
    bad = FailingDB()
    res = DBResolver(primary, [bad], breaker_threshold=2,
                     breaker_recovery=999)
    for _ in range(3):
        assert res.query("SELECT src FROM t")[0]["src"] == "p"
    # breaker opened after 2 failures; third read never touched the replica
    assert bad.calls == 2
    assert res.stats["replica_failovers"] == 3


def test_breaker_half_open_probe():
    primary = make_db("p")
    bad = FailingDB()
    res = DBResolver(primary, [bad], breaker_threshold=1,
                     breaker_recovery=0.0)
    res.query("SELECT src FROM t")
    res.query("SELECT src FROM t")
    # recovery=0 → half-open immediately, every read probes the replica
    assert bad.calls == 2


def test_select_and_tx_route_primary():
    from dataclasses import dataclass

    @dataclass
    class Row:
        id: int
        src: str

    primary, r1 = make_db("p"), make_db("r1")
    res = DBResolver(primary, [r1], strategy=STRATEGY_RANDOM)
    rows = res.select(Row, "SELECT * FROM t")
    assert rows[0].src in ("p", "r1")
    with pytest.raises(SQLError):
        res.select(dict, "SELECT * FROM t")
    with res.begin() as tx:
        tx.exec("INSERT INTO t (src) VALUES (?)", "tx")
    assert len(primary.query("SELECT * FROM t")) == 2


def test_health_degraded_on_sick_replica():
    res = DBResolver(make_db("p"), [FailingDB()])
    h = res.health_check()
    assert h["status"] == "DEGRADED"
    assert h["primary"]["status"] == "UP"
