"""CLI runtime: parsing, routing, binding, help, terminal widgets."""

from __future__ import annotations

import io
from dataclasses import dataclass

from gofr_tpu.cli import CMDApp, Out, parse_args
from gofr_tpu.cli.request import CMDRequest
from gofr_tpu.cli.terminal import ProgressBar
from gofr_tpu.config import DictConfig


def make_app() -> tuple[CMDApp, io.StringIO, io.StringIO]:
    app = CMDApp(config=DictConfig({"APP_NAME": "tool"}))
    stdout, stderr = io.StringIO(), io.StringIO()
    app.out = Out(stream=stdout, force_tty=False)
    app.err_out = Out(stream=stderr, force_tty=False)
    return app, stdout, stderr


class TestParseArgs:
    def test_forms(self):
        pos, flags = parse_args(["db", "migrate", "-n=5", "--env=prod",
                                 "-v", "--dry-run"])
        assert pos == ["db", "migrate"]
        assert flags["n"] == ["5"]
        assert flags["env"] == ["prod"]
        assert flags["v"] == ["true"]
        assert flags["dry-run"] == ["true"]

    def test_bare_flag_does_not_swallow_positional(self):
        # `tool greet --help extra`: help stays boolean, extra is a
        # stray arg — values require `=` (reference cmd.go:64-89)
        _, flags = parse_args(["greet", "--help", "extra"])
        assert flags["help"] == ["true"]
        assert flags["_args"] == ["extra"]

    def test_hyphenated_flags_bind_underscore_fields(self):
        request = CMDRequest(["migrate", "--dry-run"])
        assert request.bind()["dry_run"] == "true"

    def test_repeat_and_csv_params(self):
        request = CMDRequest(["x", "-t=a", "-t=b,c"])
        assert request.params("t") == ["a", "b", "c"]
        assert request.param("t") == "a"
        assert request.param("missing") == ""


@dataclass
class MigrateArgs:
    env: str
    n: int = 1
    dry_run: bool = False


class TestCMDApp:
    def test_routing_and_result_printing(self):
        app, stdout, _ = make_app()
        app.sub_command("greet", lambda ctx: f"hello {ctx.param('name')}")
        code = app.run(["greet", "-name=ada"])
        assert code == 0
        assert stdout.getvalue().strip() == "hello ada"

    def test_longest_prefix_wins(self):
        app, stdout, _ = make_app()
        app.sub_command("db", lambda ctx: "db root")
        app.sub_command("db migrate", lambda ctx: "migrating")
        assert app.run(["db", "migrate"]) == 0
        assert stdout.getvalue().strip() == "migrating"

    def test_dataclass_bind(self):
        app, stdout, _ = make_app()

        @app.sub_command("migrate")
        def migrate(ctx):
            args = ctx.bind(MigrateArgs)
            return {"env": args.env, "n": args.n, "dry": args.dry_run}
        assert app.run(["migrate", "--env=prod", "-n=3"]) == 0
        out = stdout.getvalue()
        assert '"env": "prod"' in out and '"n": 3' in out

    def test_dict_result_prints_json(self):
        app, stdout, _ = make_app()
        app.sub_command("info", lambda ctx: {"version": 1})
        app.run(["info"])
        assert '"version": 1' in stdout.getvalue()

    def test_error_goes_to_stderr_with_exit_code(self):
        app, stdout, stderr = make_app()

        def boom(ctx):
            raise ValueError("bad input")
        app.sub_command("boom", boom)
        code = app.run(["boom"])
        assert code == 1
        assert "bad input" in stderr.getvalue()
        assert stdout.getvalue() == ""

    def test_async_handler(self):
        app, stdout, _ = make_app()

        @app.sub_command("async")
        async def handler(ctx):
            return "done"
        assert app.run(["async"]) == 0
        assert "done" in stdout.getvalue()

    def test_help_listing(self):
        app, stdout, _ = make_app()
        app.sub_command("serve", lambda ctx: None,
                        description="start the server")
        app.sub_command("migrate", lambda ctx: None,
                        description="run migrations")
        assert app.run(["help"]) == 0
        out = stdout.getvalue()
        assert "serve" in out and "start the server" in out
        assert "migrate" in out and "run migrations" in out

    def test_help_flag_on_matched_subcommand(self):
        app, stdout, _ = make_app()
        ran = []
        app.sub_command("greet", lambda ctx: ran.append(1) or "hi",
                        description="say hello")
        assert app.run(["greet", "--help"]) == 0
        assert ran == []  # handler must NOT execute
        assert "say hello" in stdout.getvalue()

    def test_unknown_command_shows_help_exit_2(self):
        app, stdout, _ = make_app()
        app.sub_command("serve", lambda ctx: None, description="x")
        assert app.run(["nope"]) == 2
        assert "serve" in stdout.getvalue()

    def test_terminal_attached_to_context(self):
        app, stdout, _ = make_app()

        @app.sub_command("draw")
        def draw(ctx):
            ctx.terminal.print(ctx.terminal.green("ok"))
            return None
        assert app.run(["draw"]) == 0
        assert "ok" in stdout.getvalue()

    def test_container_reachable(self):
        app, stdout, _ = make_app()
        app.sub_command("name", lambda ctx: ctx.container.app_name)
        app.run(["name"])
        assert "tool" in stdout.getvalue()


class TestTerminal:
    def test_colors_only_on_tty(self):
        plain = Out(stream=io.StringIO(), force_tty=False)
        assert plain.green("x") == "x"
        tty = Out(stream=io.StringIO(), force_tty=True)
        assert tty.green("x") == "\x1b[32mx\x1b[0m"
        assert tty.bold("x") == "\x1b[1mx\x1b[0m"

    def test_progress_bar_tty_renders_bar(self):
        stream = io.StringIO()
        out = Out(stream=stream, force_tty=True)
        bar = ProgressBar(out, total=4, width=8)
        bar.increment()
        bar.set(4)
        text = stream.getvalue()
        assert "25%" in text and "100%" in text and "█" in text

    def test_progress_bar_plain_prints_milestones(self):
        stream = io.StringIO()
        out = Out(stream=stream, force_tty=False)
        bar = ProgressBar(out, total=10, width=8)
        for _ in range(10):
            bar.increment()
        text = stream.getvalue()
        assert "progress: 100%" in text
        assert "█" not in text

    def test_spinner_plain_mode(self):
        stream = io.StringIO()
        out = Out(stream=stream, force_tty=False)
        with out.spinner("working"):
            pass
        assert "working..." in stream.getvalue()

    def test_spinner_tty_animates(self):
        stream = io.StringIO()
        out = Out(stream=stream, force_tty=True)
        import time
        with out.spinner("load"):
            time.sleep(0.2)
        assert "load" in stream.getvalue()
