"""Config loader tests — precedence contract from reference godotenv.go:36-77."""

from gofr_tpu.config import DictConfig, EnvConfig, load_env_file


def test_parse_env_file(tmp_path):
    f = tmp_path / ".env"
    f.write_text(
        "# comment\n"
        "APP_NAME=myapp\n"
        "export HTTP_PORT=8000\n"
        'QUOTED="hello world"\n'
        "SINGLE='x # not comment'\n"
        "TRAILING=value # comment here\n"
        "EMPTY=\n"
        "noequals\n"
    )
    values = load_env_file(f)
    assert values["APP_NAME"] == "myapp"
    assert values["HTTP_PORT"] == "8000"
    assert values["QUOTED"] == "hello world"
    assert values["SINGLE"] == "x # not comment"
    assert values["TRAILING"] == "value"
    assert values["EMPTY"] == ""
    assert "noequals" not in values


def test_missing_file_is_empty(tmp_path):
    assert load_env_file(tmp_path / "nope.env") == {}


def test_precedence_os_env_wins(tmp_path):
    configs = tmp_path / "configs"
    configs.mkdir()
    (configs / ".env").write_text("A=base\nB=base\nC=base\n")
    (configs / ".staging.env").write_text("B=staging\nC=staging\n")
    cfg = EnvConfig(configs, environ={"APP_ENV": "staging", "C": "osenv"})
    assert cfg.get("A") == "base"
    assert cfg.get("B") == "staging"  # overlay wins over base
    assert cfg.get("C") == "osenv"    # OS env wins over everything
    assert cfg.get("D") is None


def test_app_env_from_file(tmp_path):
    configs = tmp_path / "configs"
    configs.mkdir()
    (configs / ".env").write_text("APP_ENV=dev\nX=1\n")
    (configs / ".dev.env").write_text("X=2\n")
    cfg = EnvConfig(configs, environ={})
    assert cfg.get("X") == "2"


def test_get_or_default_and_typed():
    cfg = DictConfig({"PORT": "9090", "RATIO": "0.5", "ON": "true", "BAD": "xyz"})
    assert cfg.get_or_default("PORT", "8000") == "9090"
    assert cfg.get_or_default("MISSING", "8000") == "8000"
    assert cfg.get_int("PORT", 1) == 9090
    assert cfg.get_int("BAD", 7) == 7
    assert cfg.get_float("RATIO", 1.0) == 0.5
    assert cfg.get_bool("ON") is True
    assert cfg.get_bool("MISSING", default=True) is True


def test_empty_value_falls_to_default():
    cfg = DictConfig({"E": ""})
    assert cfg.get_or_default("E", "d") == "d"
