"""ArangoDB HTTP wire client against the mini server."""

import pytest

from gofr_tpu.datasource.arango_wire import (ArangoWire, ArangoWireError,
                                             MiniArangoServer)
from gofr_tpu.datasource.graph import NodeNotFound


@pytest.fixture(scope="module")
def server():
    srv = MiniArangoServer(username="root", password="pw")
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def db(server):
    client = ArangoWire(endpoint=f"127.0.0.1:{server.port}",
                        username="root", password="pw")
    client.connect()
    return client


def test_document_crud(db):
    key = db.create_document("people", {"name": "ada", "age": 36})
    assert key
    doc = db.get_document("people", key)
    assert doc == {"name": "ada", "age": 36}
    db.update_document("people", key, {"name": "ada", "age": 37})
    assert db.get_document("people", key)["age"] == 37
    db.delete_document("people", key)
    with pytest.raises(NodeNotFound):
        db.get_document("people", key)
    with pytest.raises(NodeNotFound):
        db.delete_document("people", key)


def test_query_by_example(db):
    db.create_document("cities", {"name": "pisa", "country": "it"})
    db.create_document("cities", {"name": "rome", "country": "it"})
    db.create_document("cities", {"name": "lyon", "country": "fr"})
    rows = db.query("cities", {"country": "it"})
    assert {r["name"] for r in rows} == {"pisa", "rome"}
    assert all("_id" in r for r in rows)
    assert len(db.query("cities")) == 3


def test_edges_and_traversal(db):
    a = db.create_document("nodes", {"label": "a"})
    b = db.create_document("nodes", {"label": "b"})
    c = db.create_document("nodes", {"label": "c"})
    db.create_edge_document("links", f"nodes/{a}", f"nodes/{b}")
    db.create_edge_document("links", b, c)  # bare keys also accepted
    # traversal lists visited neighbors, excluding the start vertex
    one_hop = db.traversal(a, "links", depth=1)
    assert [d["label"] for d in one_hop] == ["b"]
    two_hops = db.traversal(a, "links", depth=2)
    assert [d["label"] for d in two_hops] == ["b", "c"]


def test_bad_credentials_are_401(server):
    bad = ArangoWire(endpoint=f"127.0.0.1:{server.port}",
                     username="root", password="WRONG")
    with pytest.raises(ArangoWireError, match="401"):
        bad.create_document("x", {})
    assert bad.health_check()["status"] == "DOWN"


def test_health(db):
    health = db.health_check()
    assert health["status"] == "UP"
    assert health["details"]["version"].startswith("3.11")
    assert ArangoWire(endpoint="127.0.0.1:1").health_check()["status"] \
        == "DOWN"
