"""Kafka wire-protocol backend against the in-process mini broker —
real Kafka v0 binary frames over a real TCP socket (the miniredis-style
pattern of tests/test_pubsub_backends.py, per SURVEY §4)."""

import asyncio
import functools

from gofr_tpu.config.env import DictConfig
from gofr_tpu.container.container import Container
from gofr_tpu.pubsub.kafka import (
    KafkaClient,
    MiniKafkaBroker,
    _decode_message_set,
    _encode_message_set,
)


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))
    return wrapper


# ------------------------------------------------------------- wire codecs

def test_message_set_roundtrip():
    entries = [(b"k1", b"v1"), (None, b"v2"), (b"", b"")]
    got = _decode_message_set(_encode_message_set(entries, base_offset=5))
    assert got == [(5, b"k1", b"v1"), (6, None, b"v2"), (7, b"", b"")]


def test_message_set_ignores_trailing_partial():
    full = _encode_message_set([(b"k", b"hello")])
    assert _decode_message_set(full + full[:7]) == [(0, b"k", b"hello")]


# ------------------------------------------------------------- end-to-end

@async_test
async def test_publish_subscribe_commit():
    broker = MiniKafkaBroker()
    await broker.start()
    client = KafkaClient(brokers=f"127.0.0.1:{broker.port}", group_id="g1")
    try:
        await client.publish("orders", {"id": 1}, key="k1")
        await client.publish("orders", {"id": 2})
        m1 = await client.subscribe("orders", "g1")
        m2 = await client.subscribe("orders", "g1")
        assert m1.bind() == {"id": 1} and m1.key == "k1"
        assert m2.bind() == {"id": 2}
        m1.commit()
        m2.commit()
        await asyncio.sleep(0.05)  # fire-and-forget commits land
        assert broker.groups["g1"].offsets[("orders", 0)] == 2
    finally:
        await client.close()
        await broker.close()


@async_test
async def test_committed_offset_survives_reconnect():
    """At-least-once: a new consumer in the same group resumes after
    the committed offset, not from the beginning."""
    broker = MiniKafkaBroker()
    await broker.start()
    addr = f"127.0.0.1:{broker.port}"
    c1 = KafkaClient(brokers=addr, group_id="g")
    await c1.publish("t", "a")
    await c1.publish("t", "b")
    m = await c1.subscribe("t", "g")
    assert m.value == b"a"
    m.commit()
    await asyncio.sleep(0.05)
    await c1.close()

    c2 = KafkaClient(brokers=addr, group_id="g")
    try:
        m = await c2.subscribe("t", "g")
        assert m.value == b"b"
    finally:
        await c2.close()
        await broker.close()


@async_test
async def test_uncommitted_message_redelivered():
    broker = MiniKafkaBroker()
    await broker.start()
    addr = f"127.0.0.1:{broker.port}"
    c1 = KafkaClient(brokers=addr, group_id="g")
    await c1.publish("t", "poison")
    m = await c1.subscribe("t", "g")
    assert m.value == b"poison"
    await c1.close()            # died without committing

    c2 = KafkaClient(brokers=addr, group_id="g")
    try:
        m = await c2.subscribe("t", "g")
        assert m.value == b"poison"
    finally:
        await c2.close()
        await broker.close()


@async_test
async def test_consumer_group_partitions_balance():
    """Two members of one group split a 2-partition topic: each
    message is consumed by exactly one member (reference
    kafka.go consumer-group semantics)."""
    broker = MiniKafkaBroker(default_partitions=2)
    await broker.start()
    addr = f"127.0.0.1:{broker.port}"
    pub = KafkaClient(brokers=addr)
    c1 = KafkaClient(brokers=addr, group_id="g")
    c2 = KafkaClient(brokers=addr, group_id="g")
    try:
        # join both members first (join order decides assignment)
        t1 = asyncio.ensure_future(c1.subscribe("evt", "g"))
        t2 = asyncio.ensure_future(c2.subscribe("evt", "g"))
        await asyncio.sleep(0.3)

        # unkeyed publishes round-robin across the two partitions
        await pub.publish("evt", "p0")
        await pub.publish("evt", "p1")

        got = {(await asyncio.wait_for(t1, 10)).value,
               (await asyncio.wait_for(t2, 10)).value}
        assert got == {b"p0", b"p1"}
    finally:
        await pub.close()
        await c1.close()
        await c2.close()
        await broker.close()


@async_test
async def test_rebalance_on_new_member():
    """A second member joining bumps the generation; the first member
    detects it via heartbeat and rejoins rather than erroring."""
    broker = MiniKafkaBroker(default_partitions=2)
    await broker.start()
    addr = f"127.0.0.1:{broker.port}"
    c1 = KafkaClient(brokers=addr, group_id="g")
    c2 = KafkaClient(brokers=addr, group_id="g")
    pub = KafkaClient(brokers=addr)
    try:
        t1 = asyncio.ensure_future(c1.subscribe("evt", "g"))
        await asyncio.sleep(0.2)          # c1 owns both partitions
        t2 = asyncio.ensure_future(c2.subscribe("evt", "g"))
        await asyncio.sleep(0.4)          # c1 must rejoin at generation+1

        await pub.publish("evt", "x")
        done, pending = await asyncio.wait({t1, t2}, timeout=10)
        assert done, "no member received the message after rebalance"
        assert {m.result().value for m in done} == {b"x"}
        for task in pending:
            task.cancel()
    finally:
        await pub.close()
        await c1.close()
        await c2.close()
        await broker.close()


@async_test
async def test_create_delete_topic_admin():
    broker = MiniKafkaBroker()
    await broker.start()
    client = KafkaClient(brokers=f"127.0.0.1:{broker.port}")
    try:
        await client.create_topic_async("adm", partitions=3)
        assert len(broker.logs["adm"]) == 3
        client.delete_topic("adm")
        await asyncio.sleep(0.05)
        assert "adm" not in broker.logs
        assert client.health_check()["status"] == "UP"
    finally:
        await client.close()
        await broker.close()


@async_test
async def test_container_wires_kafka_backend():
    broker = MiniKafkaBroker()
    await broker.start()
    config = DictConfig({
        "APP_NAME": "kafka-app",
        "PUBSUB_BACKEND": "KAFKA",
        "PUBSUB_BROKER": f"127.0.0.1:{broker.port}",
        "KAFKA_CONSUMER_GROUP": "workers",
    })
    c = Container.create(config)
    try:
        assert isinstance(c.pubsub, KafkaClient)
        assert c.pubsub.group_id == "workers"
        await c.pubsub.publish("t", {"ok": True})
        msg = await c.pubsub.subscribe("t", "workers")
        assert msg.bind() == {"ok": True}
    finally:
        await c.pubsub.close()
        await broker.close()


@async_test
async def test_keyed_publish_routes_stably():
    """Same key -> same partition (ordering per key), different keys
    spread (reference kafka.go writer balancer semantics)."""
    broker = MiniKafkaBroker(default_partitions=4)
    await broker.start()
    pub = KafkaClient(brokers=f"127.0.0.1:{broker.port}")
    try:
        await pub.create_topic_async("keyed", partitions=4)
        for _ in range(3):
            await pub.publish("keyed", "a", key="user-1")
        sizes = [len(p) for p in broker.logs["keyed"]]
        assert sorted(sizes) == [0, 0, 0, 3]   # all three on ONE partition
    finally:
        await pub.close()
        await broker.close()


def test_subscriber_group_defaults_from_config():
    from gofr_tpu.pubsub.subscriber import SubscriptionManager

    class FakeContainer:
        config = DictConfig({"KAFKA_CONSUMER_GROUP": "workers"})

    assert SubscriptionManager(FakeContainer())._default_group() == "workers"

    class Generic:
        config = DictConfig({"CONSUMER_GROUP": "generic"})

    assert SubscriptionManager(Generic())._default_group() == "generic"

    class Bare:
        config = DictConfig({})

    assert SubscriptionManager(Bare())._default_group() == "default"
