"""SurrealDB WebSocket JSON-RPC wire client against the mini server —
the framework's own WS runtime serving the RPC surface."""

import pytest

from gofr_tpu.datasource.surreal_wire import (MiniSurrealServer,
                                              SurrealWire, SurrealWireError)


@pytest.fixture(scope="module")
def server():
    srv = MiniSurrealServer(username="root", password="pw")
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def db(server):
    client = SurrealWire(endpoint=f"ws://127.0.0.1:{server.port}/rpc",
                         username="root", password="pw")
    client.connect()
    yield client
    client.close()


def test_create_select_update_delete(db):
    doc = db.create("person:ada", {"name": "ada", "year": 1815})
    assert doc["id"] == "person:ada"
    assert doc["name"] == "ada"
    got = db.select("person:ada")
    assert got[0]["year"] == 1815
    updated = db.update("person:ada", {"name": "ada", "year": 1816})
    assert updated["year"] == 1816
    db.delete("person:ada")
    with pytest.raises(SurrealWireError):
        db.select("person:ada")


def test_create_without_id_assigns_one(db):
    doc = db.create("event", {"kind": "deploy"})
    assert doc["id"].startswith("event:")
    db.delete(doc["id"])


def test_query_generates_surrealql_with_vars(db):
    db.create("city:pisa", {"name": "pisa", "country": "it"})
    db.create("city:rome", {"name": "rome", "country": "it"})
    db.create("city:lyon", {"name": "lyon", "country": "fr"})
    rows = db.query("city", {"country": "it"})
    assert {r["name"] for r in rows} == {"pisa", "rome"}
    assert len(db.query("city")) == 3
    for c in ("pisa", "rome", "lyon"):
        db.delete(f"city:{c}")


def test_signin_required(server):
    anon = SurrealWire(endpoint=f"ws://127.0.0.1:{server.port}/rpc",
                       username="", password="")
    anon.connect()  # no signin attempted
    try:
        with pytest.raises(SurrealWireError, match="not signed in"):
            anon.create("x:1", {"a": 1})
    finally:
        anon.close()


def test_bad_credentials_rejected(server):
    bad = SurrealWire(endpoint=f"ws://127.0.0.1:{server.port}/rpc",
                      username="root", password="WRONG")
    with pytest.raises(SurrealWireError, match="credentials"):
        bad.connect()
    bad.close()


def test_malformed_rpc_params_get_immediate_error(db):
    # one param where two are required: a JSON-RPC error, not a stall
    with pytest.raises(SurrealWireError, match="invalid params"):
        db._rpc("create", ["only-thing"])


def test_injection_shaped_field_name_rejected(db):
    with pytest.raises(SurrealWireError, match="invalid field"):
        db.query("t", {"x = 1 OR true; DROP": "v"})


def test_health(db):
    assert db.health_check()["status"] == "UP"
    loose = SurrealWire(endpoint="ws://127.0.0.1:1/rpc")
    assert loose.health_check()["status"] == "DOWN"
