"""OpenAI-compatible surface: chat/completions (unary + SSE chunks),
completions, models, stop sequences, error envelopes — through the
real HTTP stack."""

import json

import pytest

from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.openai_compat import (_cut_at_stop,
                                            install_openai_routes)
from gofr_tpu.serving.tokenizer import ByteTokenizer

from .apputil import AppRunner


@pytest.fixture(scope="module")
def oa_app():
    engine = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                            seed=41))
    engine.start()

    def build(app):
        install_openai_routes(app, engine, ByteTokenizer(),
                              model="tiny-llama")

    runner = AppRunner(build=build)
    with runner as app:
        yield app, engine
    engine.stop()


def _post(app, path, body):
    status, _, data = app.request("POST", path, body=body)
    return status, json.loads(data)


def test_models_list(oa_app):
    app, _ = oa_app
    status, body = app.get_json("/v1/models")
    assert status == 200
    assert body["object"] == "list"               # Raw: no envelope
    assert body["data"][0]["id"] == "tiny-llama"


def test_chat_completion_envelope(oa_app):
    app, _ = oa_app
    status, body = _post(app, "/v1/chat/completions", {
        "model": "tiny-llama", "temperature": 0.0, "max_tokens": 7,
        "messages": [{"role": "system", "content": "be brief"},
                     {"role": "user", "content": "hi"}]})
    assert status == 201
    out = body.get("data", body)
    assert out["object"] == "chat.completion"
    assert out["id"].startswith("chatcmpl-")
    choice = out["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert isinstance(choice["message"]["content"], str)
    assert choice["finish_reason"] == "length"   # ran to max_tokens
    assert out["usage"]["completion_tokens"] == 7
    assert out["usage"]["total_tokens"] == \
        out["usage"]["prompt_tokens"] + 7


def test_text_completion(oa_app):
    app, _ = oa_app
    status, body = _post(app, "/v1/completions", {
        "model": "tiny-llama", "prompt": "once upon",
        "temperature": 0.0, "max_tokens": 5})
    assert status == 201
    out = body.get("data", body)
    assert out["object"] == "text_completion"
    assert out["id"].startswith("cmpl-")
    assert isinstance(out["choices"][0]["text"], str)


def test_streaming_chunks(oa_app):
    import http.client

    app, _ = oa_app
    conn = http.client.HTTPConnection("127.0.0.1", app.port, timeout=60)
    conn.request("POST", "/v1/chat/completions", body=json.dumps({
        "model": "tiny-llama", "stream": True, "temperature": 0.0,
        "max_tokens": 6,
        "messages": [{"role": "user", "content": "go"}]}),
        headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read().decode()
    conn.close()
    events = [e[len("data: "):] for e in raw.split("\n\n")
              if e.startswith("data: ")]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    assert chunks[0]["choices"][0]["delta"] == {"role": "assistant"}
    text = "".join(c["choices"][0]["delta"].get("content", "")
                   for c in chunks)
    assert len(text) > 0
    assert chunks[-1]["choices"][0]["finish_reason"] == "length"
    # the streamed text equals the unary text for the same request
    status, body = _post(app, "/v1/chat/completions", {
        "model": "tiny-llama", "temperature": 0.0, "max_tokens": 6,
        "messages": [{"role": "user", "content": "go"}]})
    unary = body.get("data", body)["choices"][0]["message"]["content"]
    assert text == unary


def test_stop_sequences(oa_app):
    app, engine = oa_app
    # discover the deterministic output, then stop on a piece of it
    status, body = _post(app, "/v1/completions", {
        "prompt": "stop test", "temperature": 0.0, "max_tokens": 10})
    full = body.get("data", body)["choices"][0]["text"]
    assert len(full) >= 3
    marker = full[1:3]
    status, body = _post(app, "/v1/completions", {
        "prompt": "stop test", "temperature": 0.0, "max_tokens": 10,
        "stop": [marker]})
    out = body.get("data", body)
    assert out["choices"][0]["text"] == full.split(marker)[0]
    assert out["choices"][0]["finish_reason"] == "stop"


def test_error_envelopes(oa_app):
    app, _ = oa_app
    status, body = _post(app, "/v1/chat/completions", {"messages": []})
    assert status == 400
    assert "messages" in body["error"]["message"] \
        or body["error"]["details"]["param"] == "messages"
    status, body = _post(app, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "x"}], "n": 3})
    assert status == 400
    status, body = _post(app, "/v1/completions", {
        "prompt": "x", "stop": ["a", "b", "c", "d", "e"]})
    assert status == 400


def test_cut_at_stop_picks_earliest():
    assert _cut_at_stop("abcdef", ["de", "bc"]) == ("a", True)
    assert _cut_at_stop("abcdef", ["zz"]) == ("abcdef", False)


def test_content_parts_and_null_optionals(oa_app):
    """OpenAI SDK shapes: content-parts arrays render their text; an
    explicit JSON null optional means 'use the default'."""
    app, _ = oa_app
    status, body = _post(app, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": "hel"},
            {"type": "text", "text": "lo"}]}],
        "temperature": None, "max_tokens": 4, "n": None})
    assert status == 201, body
    out = body.get("data", body)
    assert out["usage"]["completion_tokens"] == 4
    # non-text parts are rejected, not repr-mangled into the prompt
    status, body = _post(app, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": [
            {"type": "image_url", "image_url": {"url": "x"}}]}]})
    assert status == 400
    # bad n is a 400, not a 500
    status, body = _post(app, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "x"}], "n": "abc"})
    assert status == 400


def test_unary_stop_cancels_generation(oa_app):
    """A stop hit mid-drain cancels the engine request instead of
    letting it burn the rest of its token budget."""
    app, engine = oa_app
    status, body = _post(app, "/v1/completions", {
        "prompt": "cancel probe", "temperature": 0.0, "max_tokens": 10})
    full = body.get("data", body)["choices"][0]["text"]
    marker = full[1:3]
    status, body = _post(app, "/v1/completions", {
        "prompt": "cancel probe", "temperature": 0.0, "max_tokens": 90,
        "stop": [marker]})
    out = body.get("data", body)
    assert out["choices"][0]["finish_reason"] == "stop"
    assert out["choices"][0]["text"] == full.split(marker)[0]
    # far fewer than 90 tokens were actually generated
    assert out["usage"]["completion_tokens"] < 20
