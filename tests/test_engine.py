"""Continuous-batching engine tests (tiny model, CPU)."""

import threading
import time

import pytest

from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def engine():
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128))
    eng.start()
    yield eng
    eng.stop()


def test_single_generation(engine):
    req = engine.submit_sync([1, 2, 3],
                             SamplingParams(temperature=0.0, max_new_tokens=8))
    assert len(req.generated) == 8
    assert req.error is None
    assert req.ttft_ms is not None and req.ttft_ms >= 0
    assert req.finished_at is not None


def test_greedy_determinism(engine):
    a = engine.submit_sync([5, 6, 7],
                           SamplingParams(temperature=0.0, max_new_tokens=10))
    b = engine.submit_sync([5, 6, 7],
                           SamplingParams(temperature=0.0, max_new_tokens=10))
    assert a.generated == b.generated


def test_concurrent_requests_all_complete(engine):
    reqs = []
    for i in range(8):  # 2x the slot count -> queueing must work
        reqs.append(engine.submit(
            [1 + i, 2, 3],
            SamplingParams(temperature=0.0, max_new_tokens=6)))
    deadline = time.time() + 60
    while time.time() < deadline:
        if all(r.finished_at is not None for r in reqs):
            break
        time.sleep(0.01)
    assert all(r.finished_at is not None for r in reqs)
    assert all(len(r.generated) == 6 for r in reqs)


def test_batched_identical_to_solo(engine):
    """Continuous batching must not change greedy outputs."""
    solo = engine.submit_sync([9, 8, 7],
                              SamplingParams(temperature=0.0, max_new_tokens=6))
    others = [engine.submit([3 + i, 1, 4],
                            SamplingParams(temperature=0.7, max_new_tokens=12))
              for i in range(3)]
    batched = engine.submit_sync([9, 8, 7],
                                 SamplingParams(temperature=0.0, max_new_tokens=6))
    deadline = time.time() + 60
    while time.time() < deadline and any(r.finished_at is None for r in others):
        time.sleep(0.01)
    assert solo.generated == batched.generated


def test_long_prompt_truncated(engine):
    req = engine.submit_sync(list(range(1, 200)) * 2,
                             SamplingParams(temperature=0.0, max_new_tokens=4))
    assert req.error is None
    assert len(req.generated) == 4


def test_health_check(engine):
    health = engine.health_check()
    assert health["status"] == "UP"
    assert health["total_generated"] > 0


def test_max_seq_stops_generation(engine):
    # prompt near the cap: generation must stop at max_seq, not crash
    req = engine.submit_sync(list(range(1, 120)),
                             SamplingParams(temperature=0.0, max_new_tokens=50))
    assert req.error is None
    assert 0 < len(req.generated) <= 50


def test_stochastic_sampling_varies(engine):
    outs = set()
    for i in range(4):
        req = engine.submit_sync([1, 2],
                                 SamplingParams(temperature=5.0, top_p=1.0,
                                                max_new_tokens=8))
        outs.add(tuple(req.generated))
    assert len(outs) > 1  # very high temperature -> variety


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "hello TPU — ünïcode ✓"
    ids = tok.encode(text)
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == text


def test_submit_from_thread_without_loop(engine):
    result = {}

    def worker():
        req = engine.submit_sync([2, 4, 6],
                                 SamplingParams(temperature=0.0,
                                                max_new_tokens=3))
        result["tokens"] = req.generated

    t = threading.Thread(target=worker)
    t.start()
    t.join(60)
    assert len(result["tokens"]) == 3


def test_top_k_one_equals_greedy(engine):
    """top_k=1 restricts sampling to the argmax even at temperature>0,
    so it must reproduce the greedy continuation."""
    prompt = list(range(1, 9))
    greedy = engine.submit_sync(
        prompt, SamplingParams(temperature=0.0, max_new_tokens=8))
    k1 = engine.submit_sync(
        prompt, SamplingParams(temperature=1.0, top_k=1, max_new_tokens=8))
    assert k1.generated == greedy.generated


def test_sample_batch_top_k_masks_rows():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from gofr_tpu.serving.engine import _sample_batch
    logits = jnp.asarray([[0.0, 5.0, 4.0, 1.0],
                          [0.0, 5.0, 4.0, 1.0]])
    temps = jnp.asarray([1.0, 1.0], jnp.float32)
    top_ps = jnp.asarray([1.0, 1.0], jnp.float32)
    top_ks = jnp.asarray([1, 0], jnp.int32)  # row0 k=1, row1 unrestricted
    seen0 = set()
    seen1 = set()
    for i in range(32):
        out = np.asarray(_sample_batch(logits, jax.random.key(i),
                                       temps, top_ps, top_ks))
        seen0.add(int(out[0]))
        seen1.add(int(out[1]))
    assert seen0 == {1}          # k=1: always the argmax
    assert len(seen1) > 1        # unrestricted row actually samples


def test_crash_containment():
    """A throwing hot loop must fail every stream and flip health DOWN
    — never hang submitters (reference panic-recovery stance,
    /root/reference/pkg/gofr/handler.go:141)."""
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64))

    def boom(*a, **kw):
        raise RuntimeError("injected decode failure")

    eng._decode = boom
    eng.start()
    reqs = [eng.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                 max_new_tokens=8))
            for _ in range(4)]
    deadline = time.time() + 30
    while time.time() < deadline:
        if all(r.finished_at is not None for r in reqs):
            break
        time.sleep(0.01)
    assert all(r.finished_at is not None for r in reqs)
    assert all(r.error and "injected decode failure" in r.error for r in reqs)
    health = eng.health_check()
    assert health["status"] == "DOWN"
    assert "injected decode failure" in health["error"]
    eng.stop()


def test_stop_retires_active_slots():
    """stop() must terminate streams still holding a slot — no stream
    may hang after shutdown."""
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=128))
    eng.start()
    # long generation that cannot finish before stop()
    req = eng.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                               max_new_tokens=100))
    deadline = time.time() + 30
    while time.time() < deadline and req.first_token_at is None:
        time.sleep(0.01)
    assert req.first_token_at is not None
    eng.stop()
    assert req.finished_at is not None
    assert req.error == "engine stopped"


def test_seeded_engines_reproduce_streams():
    """Same seed => identical stochastic generations; different seed
    => (overwhelmingly) different."""
    sp = SamplingParams(temperature=1.0, max_new_tokens=12)

    def run(seed):
        eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64,
                                             seed=seed))
        eng.start()
        out = eng.submit_sync([1, 2, 3], sp).generated
        eng.stop()
        return out

    assert run(7) == run(7)
    assert run(7) != run(1234)


def test_top_p_applied_after_top_k_renormalisation():
    """With top_k=2 and top_p=0.6 the top-p mass must be computed on
    the top-k-renormalised distribution: the two survivors split the
    mass ~50/50, so the nucleus keeps both; pre-top-k (the old bug)
    the first token already holds >0.6 of the full mass and the second
    could never be drawn."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from gofr_tpu.serving.engine import _sample_batch
    # token0 and token1 nearly tied, the rest far behind
    logits = jnp.asarray([[5.0, 4.9, -10.0, -10.0]])
    temps = jnp.asarray([1.0], jnp.float32)
    top_ps = jnp.asarray([0.6], jnp.float32)
    top_ks = jnp.asarray([2], jnp.int32)
    seen = set()
    for i in range(64):
        out = np.asarray(_sample_batch(logits, jax.random.key(i),
                                       temps, top_ps, top_ks))
        seen.add(int(out[0]))
    assert seen == {0, 1}


def test_prefill_batches_admit_together():
    """A burst larger than prefill_batch still completes, with groups
    admitted batch-at-a-time."""
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=64))
    eng.config.prefill_batch = 2
    eng.start()
    reqs = [eng.submit([i + 1, 2, 3], SamplingParams(temperature=0.0,
                                                     max_new_tokens=5))
            for i in range(6)]
    deadline = time.time() + 60
    while time.time() < deadline:
        if all(r.finished_at is not None for r in reqs):
            break
        time.sleep(0.01)
    assert all(r.error is None for r in reqs)
    assert all(len(r.generated) == 5 for r in reqs)
    eng.stop()


def test_moe_engine_generates():
    """The MoE glue path must serve end to end (tiny config, greedy)."""
    import jax
    from gofr_tpu.models.moe import MoEConfig, moe_init
    from gofr_tpu.serving.glue import moe_engine
    c = MoEConfig.tiny()
    params = moe_init(jax.random.key(0), c)
    eng = moe_engine(params, c, EngineConfig(max_batch=2, max_seq=64, seed=3),
                     implementation="xla")
    eng.start()
    req = eng.submit_sync([1, 2, 3], SamplingParams(temperature=0.0,
                                                    max_new_tokens=6))
    eng.stop()
    assert req.error is None
    assert len(req.generated) == 6


def test_engine_warmup_precompiles_and_serves():
    """warmup() before start() must leave the engine fully functional
    and identical in output to an unwarmed engine."""
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64, seed=5))
    eng.warmup(prompt_lens=(3,))
    eng.start()
    warm = eng.submit_sync([1, 2, 3], SamplingParams(temperature=0.0,
                                                     max_new_tokens=6))
    eng.stop()
    ref = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64, seed=5))
    ref.start()
    cold = ref.submit_sync([1, 2, 3], SamplingParams(temperature=0.0,
                                                     max_new_tokens=6))
    ref.stop()
    assert warm.error is None and warm.generated == cold.generated


def test_engine_exports_saturation_gauges():
    from gofr_tpu.metrics.registry import Manager
    from gofr_tpu.serving.glue import demo_llama_engine
    from gofr_tpu.serving.engine import EngineConfig, SamplingParams

    metrics = Manager()
    engine = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64,
                                            seed=1), metrics=metrics)
    engine.start()
    try:
        req = engine.submit_sync([1, 2, 3], SamplingParams(
            temperature=0.0, max_new_tokens=4))
        assert req.error is None
    finally:
        engine.stop()
    scrape = metrics.render_prometheus()
    assert "app_engine_active_slots" in scrape
    assert "app_engine_waiting" in scrape


def test_stalled_engine_reports_degraded():
    """A wedged device call (the failure mode a hung TPU tunnel
    produces) must flip health to DEGRADED while work is in flight —
    exceptions go DOWN via _crash; a hang has no exception."""
    import threading
    import time as _time

    from gofr_tpu.serving.glue import demo_llama_engine
    from gofr_tpu.serving.engine import EngineConfig, SamplingParams

    engine = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64,
                                            stall_threshold_s=0.2,
                                            seed=1))
    release = threading.Event()
    original = engine._decode

    def wedged(*args, **kw):
        release.wait(30)  # simulate a hung device call
        return original(*args, **kw)

    engine._decode = wedged
    engine.start()
    try:
        req = engine.submit(list(range(40)), SamplingParams(
            temperature=0.0, max_new_tokens=8))
        deadline = _time.time() + 10
        while _time.time() < deadline:
            if engine.health_check()["status"] == "DEGRADED":
                break
            _time.sleep(0.05)
        health = engine.health_check()
        assert health["status"] == "DEGRADED", health
        assert health["stalled_for_s"] >= 0.2
        release.set()  # device "recovers": request completes, health UP
        deadline = _time.time() + 30
        while _time.time() < deadline and req.finished_at is None \
                and req.error is None:
            _time.sleep(0.05)
        assert req.error is None and len(req.generated) == 8
        assert engine.health_check()["status"] == "UP"
    finally:
        release.set()
        engine.stop()


def test_decode_windows_match_full_attention():
    """Windowed decode attention (reads O(window) rows, not O(max_seq))
    must be greedily identical to the full graph, including prompts
    whose lengths cross a window boundary mid-generation."""
    import time as _t

    from gofr_tpu.serving.glue import demo_llama_engine

    def run(**extra):
        eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=256,
                                             seed=13, **extra))
        eng.start()
        # 10-token prompt + 40 generated: passes need 18, 26, 34, ...
        # rows (len + K, K=8) — the 32-window graph runs the early
        # passes, then selection hands the SAME donated caches to the
        # 64 graph and finally the full graph as lengths cross each
        # boundary (the riskiest path: variant switches mid-request)
        reqs = [eng.submit(list(range(2, 12)), SamplingParams(
            temperature=0.0, max_new_tokens=40)) for _ in range(3)]
        deadline = _t.time() + 120
        while _t.time() < deadline and any(
                r.finished_at is None and r.error is None for r in reqs):
            _t.sleep(0.01)
        eng.stop()
        assert all(r.error is None for r in reqs), [r.error for r in reqs]
        assert all(len(r.generated) == 40 for r in reqs)
        return [r.generated for r in reqs]

    want = run()
    got = run(decode_windows=(32, 64))
    assert got == want


def test_moe_decode_windows_match_full_attention():
    """MoE windowed decode must match the full graph greedily across a
    window boundary (same contract as the llama test — the signature
    probe now enables windows for moe_engine too)."""
    import time as _t

    import jax
    from gofr_tpu.models.moe import MoEConfig, moe_init
    from gofr_tpu.serving.glue import moe_engine

    c = MoEConfig.tiny()
    params = moe_init(jax.random.key(0), c)

    def run(**extra):
        eng = moe_engine(params, c,
                         EngineConfig(max_batch=2, max_seq=128, seed=7,
                                      **extra),
                         implementation="xla")
        eng.start()
        reqs = [eng.submit([4 + i, 2, 9], SamplingParams(
            temperature=0.0, max_new_tokens=40)) for i in range(2)]
        deadline = _t.time() + 120
        while _t.time() < deadline and any(
                r.finished_at is None and r.error is None for r in reqs):
            _t.sleep(0.01)
        eng.stop()
        assert all(r.error is None for r in reqs), [r.error for r in reqs]
        assert all(len(r.generated) == 40 for r in reqs)
        return [r.generated for r in reqs]

    want = run()
    got = run(decode_windows=(16, 32))
    assert got == want
