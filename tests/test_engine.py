"""Continuous-batching engine tests (tiny model, CPU)."""

import threading
import time

import pytest

from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def engine():
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128))
    eng.start()
    yield eng
    eng.stop()


def test_single_generation(engine):
    req = engine.submit_sync([1, 2, 3],
                             SamplingParams(temperature=0.0, max_new_tokens=8))
    assert len(req.generated) == 8
    assert req.error is None
    assert req.ttft_ms is not None and req.ttft_ms >= 0
    assert req.finished_at is not None


def test_greedy_determinism(engine):
    a = engine.submit_sync([5, 6, 7],
                           SamplingParams(temperature=0.0, max_new_tokens=10))
    b = engine.submit_sync([5, 6, 7],
                           SamplingParams(temperature=0.0, max_new_tokens=10))
    assert a.generated == b.generated


def test_concurrent_requests_all_complete(engine):
    reqs = []
    for i in range(8):  # 2x the slot count -> queueing must work
        reqs.append(engine.submit(
            [1 + i, 2, 3],
            SamplingParams(temperature=0.0, max_new_tokens=6)))
    deadline = time.time() + 60
    while time.time() < deadline:
        if all(r.finished_at is not None for r in reqs):
            break
        time.sleep(0.01)
    assert all(r.finished_at is not None for r in reqs)
    assert all(len(r.generated) == 6 for r in reqs)


def test_batched_identical_to_solo(engine):
    """Continuous batching must not change greedy outputs."""
    solo = engine.submit_sync([9, 8, 7],
                              SamplingParams(temperature=0.0, max_new_tokens=6))
    others = [engine.submit([3 + i, 1, 4],
                            SamplingParams(temperature=0.7, max_new_tokens=12))
              for i in range(3)]
    batched = engine.submit_sync([9, 8, 7],
                                 SamplingParams(temperature=0.0, max_new_tokens=6))
    deadline = time.time() + 60
    while time.time() < deadline and any(r.finished_at is None for r in others):
        time.sleep(0.01)
    assert solo.generated == batched.generated


def test_long_prompt_truncated(engine):
    req = engine.submit_sync(list(range(1, 200)) * 2,
                             SamplingParams(temperature=0.0, max_new_tokens=4))
    assert req.error is None
    assert len(req.generated) == 4


def test_health_check(engine):
    health = engine.health_check()
    assert health["status"] == "UP"
    assert health["total_generated"] > 0


def test_max_seq_stops_generation(engine):
    # prompt near the cap: generation must stop at max_seq, not crash
    req = engine.submit_sync(list(range(1, 120)),
                             SamplingParams(temperature=0.0, max_new_tokens=50))
    assert req.error is None
    assert 0 < len(req.generated) <= 50


def test_stochastic_sampling_varies(engine):
    outs = set()
    for i in range(4):
        req = engine.submit_sync([1, 2],
                                 SamplingParams(temperature=5.0, top_p=1.0,
                                                max_new_tokens=8))
        outs.add(tuple(req.generated))
    assert len(outs) > 1  # very high temperature -> variety


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "hello TPU — ünïcode ✓"
    ids = tok.encode(text)
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == text


def test_submit_from_thread_without_loop(engine):
    result = {}

    def worker():
        req = engine.submit_sync([2, 4, 6],
                                 SamplingParams(temperature=0.0,
                                                max_new_tokens=3))
        result["tokens"] = req.generated

    t = threading.Thread(target=worker)
    t.start()
    t.join(60)
    assert len(result["tokens"]) == 3


def test_top_k_one_equals_greedy(engine):
    """top_k=1 restricts sampling to the argmax even at temperature>0,
    so it must reproduce the greedy continuation."""
    prompt = list(range(1, 9))
    greedy = engine.submit_sync(
        prompt, SamplingParams(temperature=0.0, max_new_tokens=8))
    k1 = engine.submit_sync(
        prompt, SamplingParams(temperature=1.0, top_k=1, max_new_tokens=8))
    assert k1.generated == greedy.generated


def test_sample_batch_top_k_masks_rows():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from gofr_tpu.serving.engine import _sample_batch
    logits = jnp.asarray([[0.0, 5.0, 4.0, 1.0],
                          [0.0, 5.0, 4.0, 1.0]])
    temps = jnp.asarray([1.0, 1.0], jnp.float32)
    top_ps = jnp.asarray([1.0, 1.0], jnp.float32)
    top_ks = jnp.asarray([1, 0], jnp.int32)  # row0 k=1, row1 unrestricted
    seen0 = set()
    seen1 = set()
    for i in range(32):
        out = np.asarray(_sample_batch(logits, jax.random.key(i),
                                       temps, top_ps, top_ks))
        seen0.add(int(out[0]))
        seen1.add(int(out[1]))
    assert seen0 == {1}          # k=1: always the argmax
    assert len(seen1) > 1        # unrestricted row actually samples
