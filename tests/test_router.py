"""Router tests — pattern matching, params, 405 detection, static serving."""

from gofr_tpu.http.router import Router


def handler(ctx):
    return "ok"


def test_exact_match():
    r = Router()
    r.add("GET", "/greet", handler)
    matched = r.match("GET", "/greet")
    assert matched is not None
    route, params = matched
    assert route.pattern == "/greet"
    assert params == {}


def test_path_params():
    r = Router()
    r.add("GET", "/users/{id}/posts/{post_id}", handler)
    route, params = r.match("GET", "/users/42/posts/7")
    assert params == {"id": "42", "post_id": "7"}


def test_no_match_wrong_method_lists_allowed():
    r = Router()
    r.add("GET", "/thing", handler)
    r.add("PUT", "/thing", handler)
    assert r.match("POST", "/thing") is None
    assert r.registered_methods_for("/thing") == ["GET", "PUT"]


def test_trailing_slash_equivalence():
    r = Router()
    r.add("GET", "/a/b", handler)
    assert r.match("GET", "/a/b/") is not None


def test_segment_count_must_match():
    r = Router()
    r.add("GET", "/a/{x}", handler)
    assert r.match("GET", "/a") is None
    assert r.match("GET", "/a/b/c") is None


def test_static_serving_and_traversal_guard(tmp_path):
    site = tmp_path / "static"
    site.mkdir()
    (site / "index.html").write_text("<h1>home</h1>")
    (site / "app.js").write_text("console.log(1)")
    (site / ".env").write_text("SECRET=x")
    (tmp_path / "outside.txt").write_text("secret")

    r = Router()
    r.add_static("/static", str(site))

    status, content, ctype = r.match_static("/static/index.html")
    assert status == "200" and b"home" in content and ctype == "text/html"

    status, _, _ = r.match_static("/static/app.js")
    assert status == "200"

    # directory -> index.html
    status, content, _ = r.match_static("/static")
    assert status == "200" and b"home" in content

    # restricted file
    status, _, _ = r.match_static("/static/.env")
    assert status == "404"

    # traversal attempt
    status, _, _ = r.match_static("/static/../outside.txt")
    assert status == "404"

    # miss entirely different prefix
    assert r.match_static("/other/file") is None


def test_static_404_fallback_page(tmp_path):
    site = tmp_path / "s"
    site.mkdir()
    (site / "404.html").write_text("custom missing page")
    r = Router()
    r.add_static("/s", str(site))
    status, content, ctype = r.match_static("/s/nope.txt")
    assert status == "404" and b"custom missing" in content and "html" in ctype


def test_restricted_directory_contents_blocked(tmp_path):
    site = tmp_path / "s"
    (site / ".git").mkdir(parents=True)
    (site / ".git" / "config").write_text("[remote] url=secret")
    r = Router()
    r.add_static("/s", str(site))
    status, content, _ = r.match_static("/s/.git/config")
    assert status == "404" and b"secret" not in content
