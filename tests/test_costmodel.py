"""Unit tests for the pass-cost observatory (serving/costmodel.py):
the per-signature CostModel's EWMA/baseline/drift state machine, the
AutoProfiler's single-flight/debounce/auto-stop guards, the hardened
ProfilerCapture (watchdog + force-stop recovery), the cost_skew fault
site, and the replay cost-divergence advisory. Everything here is
clock-free or injected-clock — determinism is the contract."""

import threading
import time

import pytest

from gofr_tpu.serving.costmodel import AutoProfiler, CostModel
from gofr_tpu.serving.faults import FaultPlan
from gofr_tpu.serving.replay import cost_divergence


# --------------------------------------------------------------- fakes
class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeCapture:
    """Stands in for ProfilerCapture: records start/stop calls."""

    def __init__(self):
        self.starts: list = []
        self.stops = 0
        self.refuse = False
        self.stop_result = {"ok": True, "duration_s": 0.5}

    def start(self, trace_dir=None, *, max_capture_s=None):
        if self.refuse:
            return {"ok": False, "error": "refused"}
        self.starts.append(max_capture_s)
        return {"ok": True, "dir": f"/fake/capture-{len(self.starts)}"}

    def stop(self, force=False):
        self.stops += 1
        return dict(self.stop_result)


def feed(model, n, dur, sig="decode/0", kind="decode", **kw):
    out = []
    for _ in range(n):
        out.append(model.observe(kind, sig, dur, **kw))
    return out


# ----------------------------------------------------------- CostModel
class TestCostModel:
    def test_steady_costs_seal_a_baseline_and_never_drift(self):
        m = CostModel(baseline_passes=8)
        drifts = feed(m, 50, 0.01, tokens=4)
        assert all(d is None for d in drifts)
        sig = m.state()["signatures"]["decode/0"]
        assert sig["baseline_s"] == pytest.approx(0.01)
        assert sig["n"] == 50 and not sig["drifting"]
        assert m.drift_episodes == 0

    def test_identical_feeds_are_deterministic(self):
        a, b = CostModel(baseline_passes=4), CostModel(baseline_passes=4)
        seq = [0.01, 0.012, 0.009, 0.011, 0.05, 0.08, 0.02]
        ra = [a.observe("decode", "decode/0", d, tokens=2) for d in seq]
        rb = [b.observe("decode", "decode/0", d, tokens=2) for d in seq]
        assert ra == rb
        assert a.state() == b.state()

    def test_drift_fires_exactly_once_per_episode(self):
        m = CostModel(baseline_passes=4, drift_ratio=2.0, drift_sigma=6.0)
        assert all(d is None for d in feed(m, 4, 0.01))
        drifts = [d for d in feed(m, 12, 0.1) if d is not None]
        assert len(drifts) == 1
        d = drifts[0]
        assert d["kind"] == "decode" and d["signature"] == "decode/0"
        assert d["ratio"] > 2.0
        assert d["ewma_s"] > d["baseline_s"]
        assert m.drift_episodes == 1
        assert m.state()["signatures"]["decode/0"]["drifting"]

    def test_hysteresis_ends_the_episode_and_allows_a_second(self):
        m = CostModel(baseline_passes=4, drift_ratio=2.0, drift_sigma=0.0)
        feed(m, 4, 0.01)
        assert any(feed(m, 10, 0.1))          # episode 1 opens
        # recovery: EWMA decays back under the midpoint (1.5x base)
        feed(m, 40, 0.01)
        assert not m.state()["signatures"]["decode/0"]["drifting"]
        # a fresh excursion opens a SECOND episode, exactly once
        drifts = [d for d in feed(m, 12, 0.1) if d is not None]
        assert len(drifts) == 1
        assert m.drift_episodes == 2

    def test_conservation_separates_synthetic_inflation(self):
        m = CostModel(baseline_passes=4)
        real = [0.01, 0.02, 0.015, 0.01]
        for dur in real:
            m.observe("decode", "decode/0", dur, skew_s=0.5)
        # total includes the injected skew; synthetic names it, so
        # total - synthetic conserves against the real busy seconds
        assert m.synthetic_s == pytest.approx(2.0)
        assert m.total_s - m.synthetic_s == pytest.approx(sum(real))

    def test_overflow_still_accumulates_totals(self):
        m = CostModel(max_signatures=2)
        m.observe("decode", "decode/0", 0.01)
        m.observe("decode", "decode/1", 0.01)
        m.observe("decode", "decode/2", 0.01)  # overflows the table
        assert m.overflow == 1
        assert len(m.state()["signatures"]) == 2
        assert m.total_s == pytest.approx(0.03)

    def test_disabled_model_is_inert(self):
        m = CostModel(False)
        assert m.observe("decode", "decode/0", 0.01) is None
        assert m.total_s == 0.0 and m.table() is None
        assert m.state()["enabled"] is False

    def test_table_and_by_kind_price_tokens(self):
        m = CostModel()
        feed(m, 10, 0.01, tokens=100)
        feed(m, 10, 0.02, sig="prefill/8/1", kind="prefill",
             tokens=1000, rows=8)
        tab = m.table()
        assert tab["decode/0"]["mean_s"] == pytest.approx(0.01)
        assert tab["decode/0"]["us_per_token"] == pytest.approx(100.0)
        assert tab["prefill/8/1"]["kind"] == "prefill"
        by = m.by_kind()
        assert by["decode"] == pytest.approx(100.0)
        assert by["prefill"] == pytest.approx(20.0)
        st = m.state()["signatures"]["prefill/8/1"]
        assert st["us_per_row"] == pytest.approx(0.2 / 80 * 1e6)

    def test_reset_forgets_everything(self):
        m = CostModel(baseline_passes=2)
        feed(m, 10, 0.01)
        m.reset()
        assert m.table() is None and m.total_s == 0.0
        assert m.state()["signatures"] == {}


# -------------------------------------------------------- AutoProfiler
class TestAutoProfiler:
    def make(self, **kw):
        cap, clock = FakeCapture(), FakeClock()
        kw.setdefault("passes", 3)
        kw.setdefault("max_capture_s", 10.0)
        kw.setdefault("debounce_s", 60.0)
        return AutoProfiler(cap, clock=clock, **kw), cap, clock

    def test_arm_and_pass_budget_auto_stop(self):
        prof, cap, _ = self.make()
        res = prof.arm("cost_drift", "pass cost drift: decode/0")
        assert res and res["dir"] == "/fake/capture-1"
        assert cap.starts == [10.0]  # bounded start, not unbounded
        for _ in range(3):
            prof.note_pass()
        assert cap.stops == 1 and prof.captures == 1
        art = prof.last_artifact
        assert art["ok"] and art["reason"] == "cost_drift"
        assert art["dir"] == "/fake/capture-1" and art["passes"] == 3
        assert prof.state()["armed"] is None

    def test_single_flight_refuses_a_second_arm(self):
        prof, cap, _ = self.make()
        assert prof.arm("cost_drift") is not None
        assert prof.arm("fast_burn") is None
        assert prof.suppressed == 1 and len(cap.starts) == 1

    def test_debounce_gates_back_to_back_captures(self):
        prof, cap, clock = self.make()
        prof.arm("cost_drift")
        for _ in range(3):
            prof.note_pass()
        assert prof.arm("cost_drift") is None  # clock has not moved
        assert prof.debounced == 1
        clock.advance(61.0)
        assert prof.arm("cost_drift") is not None
        assert len(cap.starts) == 2

    def test_max_capture_s_stops_at_the_next_collect(self):
        prof, cap, clock = self.make(passes=1000)
        prof.arm("goodput_floor")
        clock.advance(11.0)  # past max_capture_s
        prof.note_pass()
        assert cap.stops == 1 and prof.last_artifact["ok"]

    def test_kill_switch_suppresses_arms(self, monkeypatch):
        prof, cap, _ = self.make()
        monkeypatch.setenv("GOFR_AUTOPROF", "0")
        assert prof.arm("cost_drift") is None
        assert prof.suppressed == 1 and not cap.starts
        assert prof.state()["kill_switch"]
        monkeypatch.setenv("GOFR_AUTOPROF", "1")
        assert prof.arm("cost_drift") is not None

    def test_no_capture_means_disabled(self):
        prof = AutoProfiler(None)
        assert not prof.enabled and prof.arm("cost_drift") is None
        prof.note_pass()  # idle tick is a no-op, not an error

    def test_refused_start_suppresses(self):
        prof, cap, _ = self.make()
        cap.refuse = True
        assert prof.arm("cost_drift") is None and prof.suppressed == 1

    def test_capture_watchdog_winning_the_stop_is_still_ok(self):
        # ProfilerCapture's own max_capture_s timer may stop the trace
        # before the pass budget runs out; the artifact was written, so
        # the "no capture running" stop must not mark it failed
        prof, cap, _ = self.make()
        cap.stop_result = {"ok": False, "error": "no capture running"}
        prof.arm("cost_drift")
        for _ in range(3):
            prof.note_pass()
        assert prof.last_artifact["ok"]


# ----------------------------------------------- ProfilerCapture hardening
@pytest.fixture
def fake_profiler(monkeypatch):
    calls = {"start": 0, "stop": 0, "raise_on_stop": False}

    def fake_start(path):
        calls["start"] += 1

    def fake_stop():
        calls["stop"] += 1
        if calls["raise_on_stop"]:
            raise RuntimeError("No profile started")

    import jax
    monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake_stop)
    return calls


class TestProfilerCaptureHardening:
    def test_stop_without_capture_reports_cleanly(self, tmp_path,
                                                  fake_profiler):
        from gofr_tpu.serving.observability import ProfilerCapture
        cap = ProfilerCapture(base_dir=str(tmp_path))
        res = cap.stop()
        assert not res["ok"] and "no capture running" in res["error"]
        assert fake_profiler["stop"] == 0

    def test_force_stop_recovers_a_leaked_capture(self, tmp_path,
                                                  fake_profiler):
        # local state says idle, but JAX kept tracing (a crashed client
        # never called stop): force must stop the underlying trace
        from gofr_tpu.serving.observability import ProfilerCapture
        cap = ProfilerCapture(base_dir=str(tmp_path))
        res = cap.stop(force=True)
        assert res["ok"] and res["recovered"] and res["dir"] is None
        assert fake_profiler["stop"] == 1
        # the next start works again
        assert cap.start()["ok"]

    def test_force_stop_swallows_the_stop_error(self, tmp_path,
                                                fake_profiler):
        from gofr_tpu.serving.observability import ProfilerCapture
        cap = ProfilerCapture(base_dir=str(tmp_path))
        assert cap.start()["ok"]
        fake_profiler["raise_on_stop"] = True
        res = cap.stop(force=True)
        assert res["ok"] and res["recovered"]
        assert not cap.status()["running"]

    def test_plain_stop_still_surfaces_the_error(self, tmp_path,
                                                 fake_profiler):
        from gofr_tpu.serving.observability import ProfilerCapture
        cap = ProfilerCapture(base_dir=str(tmp_path))
        assert cap.start()["ok"]
        fake_profiler["raise_on_stop"] = True
        res = cap.stop()
        assert not res["ok"] and "RuntimeError" in res["error"]

    def test_max_capture_s_watchdog_auto_stops(self, tmp_path,
                                               fake_profiler):
        from gofr_tpu.serving.observability import ProfilerCapture
        cap = ProfilerCapture(base_dir=str(tmp_path))
        assert cap.start(max_capture_s=0.05)["ok"]
        deadline = time.time() + 5.0
        while cap.status()["running"] and time.time() < deadline:
            time.sleep(0.01)
        assert not cap.status()["running"]
        assert cap.status()["auto_stops"] == 1
        assert fake_profiler["stop"] == 1

    def test_manual_stop_cancels_the_watchdog(self, tmp_path,
                                              fake_profiler):
        from gofr_tpu.serving.observability import ProfilerCapture
        cap = ProfilerCapture(base_dir=str(tmp_path), max_capture_s=0.05)
        assert cap.start()["ok"]
        assert cap.stop()["ok"]
        time.sleep(0.15)  # the expired timer must not double-stop
        assert cap.status()["auto_stops"] == 0
        assert fake_profiler["stop"] == 1


# ------------------------------------------------------ cost_skew fault
class TestCostSkewFault:
    def test_parse_and_payload(self):
        plan = FaultPlan.parse(
            "cost_skew:at=7,times=0,seconds=0.5,request=decode/0")
        assert plan.enabled
        assert plan.payload("cost_skew") == pytest.approx(0.5)
        assert plan.payload("pass_stall") == 0.0

    def test_signature_scoped_deterministic_trigger(self):
        plan = FaultPlan.parse(
            "cost_skew:at=3,times=2,seconds=0.1,request=decode/0")
        # other signatures never count toward the trigger
        assert not any(plan.trip("cost_skew", "prefill/8/1")
                       for _ in range(10))
        hits = [plan.trip("cost_skew", "decode/0") for _ in range(6)]
        assert hits == [False, False, True, True, False, False]


# --------------------------------------------- replay cost divergence
class TestCostDivergence:
    REC = {"decode/0": {"kind": "decode", "n": 50, "mean_s": 0.010},
           "prefill/8/1": {"kind": "prefill", "n": 9, "mean_s": 0.040}}

    def test_flags_only_the_regressed_signature(self):
        rep = {"decode/0": {"kind": "decode", "n": 50, "mean_s": 0.030},
               "prefill/8/1": {"kind": "prefill", "n": 9,
                               "mean_s": 0.041}}
        out = cost_divergence(self.REC, rep)
        assert [d["signature"] for d in out] == ["decode/0"]
        assert out[0]["ratio"] == pytest.approx(3.0)
        assert out[0]["kind"] == "decode"

    def test_floor_suppresses_microsecond_jitter(self):
        rec = {"decode/0": {"kind": "decode", "n": 5, "mean_s": 0.0001}}
        rep = {"decode/0": {"kind": "decode", "n": 5, "mean_s": 0.0004}}
        assert cost_divergence(rec, rep) == []

    def test_missing_tables_are_silent(self):
        assert cost_divergence(None, self.REC) == []
        assert cost_divergence(self.REC, None) == []
        assert cost_divergence(self.REC, {}) == []
