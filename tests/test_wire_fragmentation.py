"""TCP fragmentation torture: a byte-dribbling proxy sits between each
binary-protocol client and its mini server, forwarding one byte at a
time in each direction. Framing code that assumes recv() returns whole
packets breaks instantly under this; the exact-read loops must not.
"""

import socket
import socketserver
import threading


class DribbleProxy:
    """Forwards every byte individually, both directions."""

    def __init__(self, upstream_host: str, upstream_port: int) -> None:
        self.upstream = (upstream_host, upstream_port)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    up = socket.create_connection(outer.upstream,
                                                  timeout=30)
                except OSError:
                    return
                stop = threading.Event()

                def pump(src: socket.socket, dst: socket.socket) -> None:
                    try:
                        while not stop.is_set():
                            data = src.recv(4096)
                            if not data:
                                break
                            for i in range(len(data)):  # the torture
                                dst.sendall(data[i:i + 1])
                    except OSError:
                        pass
                    finally:
                        stop.set()
                        for s in (src, dst):
                            try:
                                s.shutdown(socket.SHUT_RDWR)
                            except OSError:
                                pass

                t = threading.Thread(target=pump,
                                     args=(up, self.request), daemon=True)
                t.start()
                pump(self.request, up)
                t.join(5)

        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = TCP(("127.0.0.1", 0), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def test_postgres_survives_byte_dribble():
    from gofr_tpu.datasource.postgres_wire import (MiniPostgresServer,
                                                   PostgresWire)
    srv = MiniPostgresServer(user="u", password="p", auth="scram-sha-256")
    srv.start()
    proxy = DribbleProxy("127.0.0.1", srv.port)
    try:
        db = PostgresWire(host="127.0.0.1", port=proxy.port,
                          user="u", password="p")
        db.connect()  # SCRAM handshake over 1-byte fragments
        db.exec("CREATE TABLE t (a INTEGER, b TEXT)")
        db.exec("INSERT INTO t VALUES ($1, $2)", 1, "x" * 500)
        row = db.query_row("SELECT a, b FROM t")
        assert row["a"] == 1 and len(row["b"]) == 500
        db.close()
    finally:
        proxy.close()
        srv.close()


def test_mysql_survives_byte_dribble():
    from gofr_tpu.datasource.mysql_wire import MiniMySQLServer, MySQLWire
    srv = MiniMySQLServer(user="u", password="p")
    srv.start()
    proxy = DribbleProxy("127.0.0.1", srv.port)
    try:
        db = MySQLWire(host="127.0.0.1", port=proxy.port,
                       user="u", password="p")
        db.connect()  # challenge-response auth over fragments
        db.exec("CREATE TABLE t (a INTEGER, b TEXT)")
        db.exec("INSERT INTO t VALUES (?, ?)", 7, "y" * 300)
        row = db.query_row("SELECT a, b FROM t")
        assert row["a"] == 7 and len(row["b"]) == 300
        db.close()
    finally:
        proxy.close()
        srv.close()


def test_cassandra_survives_byte_dribble():
    from gofr_tpu.datasource.cassandra_wire import (CassandraWire,
                                                    MiniCassandraServer)
    srv = MiniCassandraServer(user="u", password="p")
    srv.start()
    proxy = DribbleProxy("127.0.0.1", srv.port)
    try:
        db = CassandraWire(host="127.0.0.1", port=proxy.port,
                           username="u", password="p")
        db.connect()  # SASL over 9-byte frames over fragments
        db.exec("CREATE TABLE t (a INTEGER, b TEXT)")
        db.exec("INSERT INTO t VALUES (?, ?)", 3, "z" * 200)
        row = db.query("SELECT a, b FROM t")[0]
        assert row["a"] == 3 and len(row["b"]) == 200
        db.close()
    finally:
        proxy.close()
        srv.close()


def test_couchbase_kv_survives_byte_dribble():
    from gofr_tpu.datasource.couchbase_wire import (CouchbaseWire,
                                                    MiniCouchbaseServer)
    srv = MiniCouchbaseServer(username="u", password="p")
    srv.start()
    proxy = DribbleProxy("127.0.0.1", srv.kv_port)
    try:
        cb = CouchbaseWire(host="127.0.0.1", kv_port=proxy.port,
                           query_endpoint=f"127.0.0.1:{srv.query_port}",
                           username="u", password="p")
        cb.connect()  # SASL PLAIN over 24-byte headers over fragments
        cb.upsert("b", "k", {"payload": "w" * 400})
        assert len(cb.get("b", "k")["payload"]) == 400
        cb.close()
    finally:
        proxy.close()
        srv.close()


def test_redis_survives_byte_dribble():
    from gofr_tpu.datasource.redis_wire import MiniRedisServer, RedisWire
    srv = MiniRedisServer()
    srv.start()
    proxy = DribbleProxy("127.0.0.1", srv.port)
    try:
        r = RedisWire(host="127.0.0.1", port=proxy.port)
        r.connect()
        r.set("k", "v" * 1000)
        assert r.get("k") == "v" * 1000
        r.close()
    finally:
        proxy.close()
        srv.close()


def test_sftp_over_ssh_survives_byte_dribble(tmp_path):
    """The whole SSH2 stack — version exchange, curve25519 kex,
    encrypted/MACed packets, auth, channel, SFTP — over 1-byte
    fragments."""
    from gofr_tpu.datasource.sftp_wire import MiniSFTPServer, SFTPWire
    srv = MiniSFTPServer(tmp_path / "root", users={"u": "p"})
    srv.start()
    proxy = DribbleProxy("127.0.0.1", srv.port)
    try:
        fs = SFTPWire(host="127.0.0.1", port=proxy.port,
                      username="u", password="p",
                      expected_host_key=srv.host_public_key())
        fs.connect()
        fs.create("frag.bin", b"\x01\x02" * 256)
        assert fs.read("frag.bin") == b"\x01\x02" * 256
        fs.close()
    finally:
        proxy.close()
        srv.close()
