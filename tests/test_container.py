"""DI container unit tests: env-driven create, provider wiring,
health aggregation, generated adders, mock container.

(reference container/container.go:77-177, health.go:8-98,
mock_container.go:93)
"""

from gofr_tpu.config import DictConfig
from gofr_tpu.container.container import Container
from gofr_tpu.container.mock import MockContainer


def test_create_wires_sql_and_defaults_from_env():
    c = Container.create(DictConfig({
        "APP_NAME": "svc", "APP_VERSION": "1.2.3",
        "DB_DIALECT": "sqlite", "DB_NAME": ":memory:"}))
    assert c.app_name == "svc" and c.app_version == "1.2.3"
    assert c.sql is not None
    assert c.sql.query_row("SELECT 1 AS one")["one"] == 1
    assert c.pubsub is None  # not configured stays None


def test_unconfigured_create_still_boots():
    c = Container.create(DictConfig({}))
    assert c.sql is None
    health = c.health()
    assert health["status"] in ("UP", "DEGRADED")
    assert health["details"]["name"] == "gofr-app"


def test_provider_pattern_wires_logger_metrics_tracer():
    c = Container.create(DictConfig({}))
    seen = {}

    class Store:
        def use_logger(self, logger):
            seen["logger"] = logger

        def use_metrics(self, metrics):
            seen["metrics"] = metrics

        def use_tracer(self, tracer):
            seen["tracer"] = tracer

        def connect(self):
            seen["connected"] = True

        def health_check(self):
            return {"status": "UP"}

    c.add_mongo(Store())
    assert seen == {"logger": c.logger, "metrics": c.metrics,
                    "tracer": c.tracer, "connected": True}
    assert c.mongo is not None


def test_generated_adders_cover_every_breadth_slot():
    from gofr_tpu.container.container import _BREADTH_SLOTS
    c = Container.create(DictConfig({}))
    for slot in _BREADTH_SLOTS:
        assert callable(getattr(c, f"add_{slot}")), slot
        assert hasattr(c, slot)


def test_health_aggregates_down_slot_to_degraded():
    c = Container.create(DictConfig({}))

    class Sick:
        def connect(self):
            pass

        def health_check(self):
            return {"status": "DOWN", "error": "gone"}

    c.add_cassandra(Sick())
    health = c.health()
    assert health["status"] == "DEGRADED"
    assert health["checks"]["cassandra"]["status"] == "DOWN"


def test_health_includes_extra_health_checks():
    c = Container.create(DictConfig({}))

    class Extra:
        def health_check(self):
            return {"status": "DEGRADED", "details": {"n": 2}}

    c.register_health_check("control_plane", Extra())
    health = c.health()
    assert health["checks"]["control_plane"]["status"] == "DEGRADED"
    assert health["status"] == "DEGRADED"


def test_health_check_exception_reads_as_down():
    c = Container.create(DictConfig({}))

    class Broken:
        def connect(self):
            pass

        def health_check(self):
            raise RuntimeError("probe exploded")

    c.add_solr(Broken())
    health = c.health()
    assert health["checks"]["solr"]["status"] == "DOWN"
    assert health["status"] == "DEGRADED"


def test_mock_container_records_calls_and_results():
    mock = MockContainer()
    mock.mock("sql").expect("query_row", result={"n": 7})
    assert mock.sql.query_row("SELECT n FROM t WHERE id = ?", 1) \
        == {"n": 7}
    calls = mock.mock("sql").calls_to("query_row")
    assert calls == [(("SELECT n FROM t WHERE id = ?", 1), {})]


def test_models_registry():
    c = Container.create(DictConfig({}))

    class Engine:
        pass

    engine = Engine()
    c.add_model("chat", engine)
    assert c.get_model("chat") is engine
    assert c.get_model("absent") is None
