"""gRPC reflection (GRPC_ENABLE_REFLECTION gate, reference
grpc.go:130-134) and the streaming chat service (BASELINE config 3's
gRPC surface)."""

from __future__ import annotations

import asyncio
import json

import grpc as grpc_lib

from gofr_tpu.grpc.reflection import (
    decode_reflection_request,
    encode_list_services_response,
)
from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.grpc_chat import make_chat_service
from gofr_tpu.serving.tokenizer import ByteTokenizer
from gofr_tpu.grpc.health import _decode_varint

from .apputil import AppRunner, grpc_channel


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def _reflection_request_list_services() -> bytes:
    # field 7 (list_services), wire type 2, empty string
    return bytes([7 << 3 | 2, 0])


def _parse_list_services(data: bytes) -> list[str]:
    """Walk ServerReflectionResponse -> list_services_response(6) ->
    service(1) -> name(1)."""
    names = []
    pos = 0
    while pos < len(data):
        tag, pos = _decode_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if wire != 2:
            _, pos = _decode_varint(data, pos)
            continue
        length, pos = _decode_varint(data, pos)
        payload = data[pos:pos + length]
        pos += length
        if field == 6:  # ListServiceResponse
            spos = 0
            while spos < len(payload):
                stag, spos = _decode_varint(payload, spos)
                slen, spos = _decode_varint(payload, spos)
                svc = payload[spos:spos + slen]
                spos += slen
                if stag >> 3 == 1:
                    npos = 0
                    ntag, npos = _decode_varint(svc, npos)
                    nlen, npos = _decode_varint(svc, npos)
                    names.append(svc[npos:npos + nlen].decode())
    return names


def test_reflection_codec_roundtrip():
    req = _reflection_request_list_services()
    which, original, arg = decode_reflection_request(req)
    assert which == "list_services" and original == req
    resp = encode_list_services_response(req, ["a.B", "c.D"])
    assert _parse_list_services(resp) == ["a.B", "c.D"]


def _build_chat(app):
    engine = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64,
                                            seed=3))
    engine.start()
    app._test_engine = engine
    app.register_grpc_service(make_chat_service(engine, ByteTokenizer()))


def test_reflection_lists_services_over_the_wire():
    cfg = {"GRPC_PORT": "0", "GRPC_ENABLE_REFLECTION": "true"}
    with AppRunner(build=_build_chat, config=cfg) as r:
        port = r.app.grpc_server.bound_port

        async def go():
            channel = grpc_channel(port)
            for svc in ("grpc.reflection.v1alpha.ServerReflection",
                        "grpc.reflection.v1.ServerReflection"):
                method = channel.stream_stream(
                    f"/{svc}/ServerReflectionInfo",
                    request_serializer=lambda b: b,
                    response_deserializer=lambda b: b)
                call = method(iter([_reflection_request_list_services()]))
                names = []
                async for raw in call:
                    names = _parse_list_services(raw)
                    break
                assert "gofr.serving.Chat" in names
                assert "grpc.health.v1.Health" in names
                assert svc in names
            await channel.close()
        run(go())
    r.app._test_engine.stop()


def test_reflection_disabled_by_default():
    with AppRunner(build=_build_chat, config={"GRPC_PORT": "0"}) as r:
        port = r.app.grpc_server.bound_port

        async def go():
            channel = grpc_channel(port)
            method = channel.stream_stream(
                "/grpc.reflection.v1alpha.ServerReflection"
                "/ServerReflectionInfo",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b)
            # UNAVAILABLE is a transient connect failure under a loaded
            # suite — retry; the assertion is about the terminal code
            for attempt in range(5):
                call = method(iter([_reflection_request_list_services()]))
                try:
                    async for _ in call:
                        raise AssertionError(
                            "reflection answered while off")
                except grpc_lib.aio.AioRpcError as exc:
                    if (exc.code() == grpc_lib.StatusCode.UNAVAILABLE
                            and attempt < 4):
                        await asyncio.sleep(0.3)
                        continue
                    assert exc.code() \
                        == grpc_lib.StatusCode.UNIMPLEMENTED, exc.code()
                break
            await channel.close()
        run(go())
    r.app._test_engine.stop()


def test_grpc_chat_streaming_tokens():
    with AppRunner(build=_build_chat, config={"GRPC_PORT": "0"}) as r:
        port = r.app.grpc_server.bound_port

        async def go():
            channel = grpc_channel(port)
            method = channel.unary_stream(
                "/gofr.serving.Chat/Stream",
                request_serializer=lambda o: json.dumps(o).encode(),
                response_deserializer=lambda b: json.loads(b))
            events = [e async for e in method(
                {"prompt": "stream me", "max_tokens": 6,
                 "temperature": 0.0})]
            tokens = [e for e in events if "token" in e]
            assert len(tokens) == 6
            assert events[-1]["done"] is True
            assert events[-1]["usage"]["completion_tokens"] == 6
            await channel.close()
        run(go())
    r.app._test_engine.stop()


def test_grpc_chat_unary_complete_matches_stream():
    with AppRunner(build=_build_chat, config={"GRPC_PORT": "0"}) as r:
        port = r.app.grpc_server.bound_port

        async def go():
            channel = grpc_channel(port)
            unary = channel.unary_unary(
                "/gofr.serving.Chat/Complete",
                request_serializer=lambda o: json.dumps(o).encode(),
                response_deserializer=lambda b: json.loads(b))
            streaming = channel.unary_stream(
                "/gofr.serving.Chat/Stream",
                request_serializer=lambda o: json.dumps(o).encode(),
                response_deserializer=lambda b: json.loads(b))
            req = {"prompt": "same greedy", "max_tokens": 5,
                   "temperature": 0.0}
            whole = await unary(req)
            streamed = [e["token"] async for e in streaming(req)
                        if "token" in e]
            assert whole["tokens"] == streamed
            assert whole["usage"]["completion_tokens"] == 5
            await channel.close()
        run(go())
    r.app._test_engine.stop()


def test_grpc_stream_client_cancel_cancels_request():
    """Cancelling a gRPC stream mid-generation must retire the engine
    request promptly — same contract as the HTTP SSE disconnect."""
    import time as _time

    with AppRunner(build=_build_chat, config={"GRPC_PORT": "0"}) as r:
        port = r.app.grpc_server.bound_port
        engine = r.app._test_engine

        async def go():
            channel = grpc_channel(port)
            method = channel.unary_stream(
                "/gofr.serving.Chat/Stream",
                request_serializer=lambda o: json.dumps(o).encode(),
                response_deserializer=lambda b: json.loads(b))
            call = method({"prompt": "abandon me", "max_tokens": 4096,
                           "temperature": 0.0})
            got = 0
            async for event in call:
                if "token" in event:
                    got += 1
                if got >= 2:  # generation is live — walk away
                    break
            abandoned = next(
                (req for req in engine.active
                 if req is not None
                 and req.params.max_new_tokens == 4096), None)
            call.cancel()
            await channel.close()
            return abandoned

        abandoned = run(go())
        assert abandoned is not None
        # the engine free-runs between the client walking away and the
        # server event loop delivering the cancel (a loaded suite can
        # stretch that lag arbitrarily), so anchor the overshoot bound
        # at the moment the ENGINE sees the flag, not at the client
        # call: after req.cancelled is True, at most the in-flight
        # pass plus one more can land before the retire sweep
        deadline = _time.time() + 30
        while _time.time() < deadline and not abandoned.cancelled:
            _time.sleep(0.01)
        assert abandoned.cancelled
        n_at_flag = len(abandoned.generated)
        while _time.time() < deadline and abandoned.finished_at is None:
            _time.sleep(0.05)
        assert abandoned.finished_at is not None
        K = engine.config.decode_steps_per_pass
        assert len(abandoned.generated) <= n_at_flag + 2 * K, (
            len(abandoned.generated), n_at_flag)
    r.app._test_engine.stop()
