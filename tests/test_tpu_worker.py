"""TPU worker result semantics: ``ok`` must mean a real measurement.

A job that prints an error payload and exits 0 (bench.py's containment
path does exactly that) used to be recorded as a success; ``ok`` now
requires rc == 0 AND a parsed, non-error JSON payload."""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_worker():
    spec = importlib.util.spec_from_file_location(
        "tpu_worker", os.path.join(REPO, "scripts", "tpu_worker.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ok_requires_parsed_non_error_payload():
    w = _load_worker()
    good = '# init stuff\n{"metric": "chat_req_per_s", "value": 23.6}\n'
    err0 = ('{"metric": "chat_req_per_s", "value": 0.0, '
            '"error": "tpu: backend probe failed"}\n')
    assert w._job_ok(0, good) == (True, "")
    # error payload + rc 0: the failure mode this fix exists for
    ok, why = w._job_ok(0, err0)
    assert not ok and "error" in why
    # no payload at all
    ok, why = w._job_ok(0, "warmup compile 12.3s\nall done\n")
    assert not ok and "payload" in why
    # non-zero rc always fails, payload or not
    ok, why = w._job_ok(1, good)
    assert not ok and "rc=1" in why
    # timeout path records rc None
    ok, why = w._job_ok(None, good)
    assert not ok


def test_parse_payload_variants():
    w = _load_worker()
    # last JSON line wins; BENCH_JSON prefix is stripped
    out = ('{"old": 1}\n'
           'BENCH_JSON {"metric": "x", "value": 2.0}\n'
           '# trailing comment\n')
    assert w._parse_payload(out) == {"metric": "x", "value": 2.0}
    assert w._parse_payload("") is None
    assert w._parse_payload("{not json}") is None
    # non-dict JSON lines are skipped
    assert w._parse_payload("[1, 2, 3]") is None
