"""True multi-process multi-host harness (SURVEY §4, VERDICT r3 #5):
N real OS processes join the control plane over HTTP, get contiguous
ranks, call ``jax.distributed.initialize`` with the leader-issued
assignment, run one cross-process check, and the eviction/rejoin path
is driven by killing a live worker process."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from gofr_tpu.serving.control_plane import ControlPlaneLeader

from .apputil import AppRunner

pytestmark = pytest.mark.slow


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(leader_url: str, host_id: str, mode: str,
           expect_world: int = 2) -> subprocess.Popen:
    env = dict(os.environ)
    # one device per process (the suite's 8-device flag would blow the
    # global mesh past tiny-model head counts) — but replace ONLY the
    # device-count flag, keep any other XLA_FLAGS the developer set
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    env.update({"GOFR_LEADER_URL": leader_url, "GOFR_HOST_ID": host_id,
                "GOFR_MODE": mode, "GOFR_EXPECT_WORLD": str(expect_world),
                "JAX_PLATFORMS": "cpu", "GOFR_TELEMETRY": "false",
                "XLA_FLAGS": " ".join(
                    kept + ["--xla_force_host_platform_device_count=1"])})
    script = os.path.join(os.path.dirname(__file__),
                          "multihost_worker.py")
    return subprocess.Popen([sys.executable, script], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _events(stdout: str) -> list[dict]:
    out = []
    for line in stdout.splitlines():
        if line.startswith("EV "):
            out.append(json.loads(line[3:]))
    return out


def test_two_processes_rank_up_and_initialize_jax():
    """join → ranks → jax.distributed.initialize across 2 OS processes
    → both see the global 2-process world → one collective."""
    coord = f"127.0.0.1:{_free_port()}"
    leader = ControlPlaneLeader(coordinator=coord,
                                heartbeat_interval_s=0.5)
    with AppRunner(build=lambda app: leader.install(app)) as runner:
        url = f"http://127.0.0.1:{runner.port}"
        procs = [_spawn(url, f"host-{i}", "jax") for i in range(2)]
        outs = []
        for p in procs:
            try:
                stdout, stderr = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                p.kill()
                stdout, stderr = p.communicate()
            outs.append((p.returncode, stdout, stderr))

        evs = [_events(o[1]) for o in outs]
        for rc, stdout, stderr in outs:
            assert rc == 0, f"worker failed rc={rc}:\n{stdout}\n{stderr}"
        inits = [next(e for e in es if e["event"] == "initialized")
                 for es in evs]
        # leader-issued ranks are the jax process ids, contiguous
        assert sorted(i["rank"] for i in inits) == [0, 1]
        for init in inits:
            assert init["process_index"] == init["rank"]
            assert init["process_count"] == 2
            assert init["global_devices"] >= 2  # sees the OTHER host
            assert init["global_devices"] > init["local_devices"]
            if init.get("collective") is not None:
                assert init["collective"] == [0, 1]
        # the settled assignments agreed on the coordinator
        settled = [next(e for e in es if e["event"] == "settled")
                   for es in evs]
        assert {s["coordinator"] for s in settled} == {coord}


def test_kill_worker_evict_rejoin_regenerates_ranks():
    """A killed worker process misses heartbeats, is evicted (generation
    bump), the survivor's assignment re-ranks, and a fresh process
    rejoins to restore the world — the elastic-restart lifecycle."""
    leader = ControlPlaneLeader(coordinator="127.0.0.1:0",
                                heartbeat_interval_s=0.3,
                                eviction_misses=3)
    with AppRunner(build=lambda app: leader.install(app)) as runner:
        url = f"http://127.0.0.1:{runner.port}"
        a = _spawn(url, "host-a", "plain")
        b = _spawn(url, "host-b", "plain")
        try:
            deadline = time.time() + 30
            while time.time() < deadline \
                    and leader.topology()["world_size"] != 2:
                time.sleep(0.1)
            assert leader.topology()["world_size"] == 2
            gen_before = leader.generation

            b.send_signal(signal.SIGKILL)      # the host dies hard
            deadline = time.time() + 30
            while time.time() < deadline \
                    and leader.topology()["world_size"] != 1:
                time.sleep(0.1)
            topo = leader.topology()
            assert topo["world_size"] == 1     # evicted
            assert leader.generation > gen_before
            assert topo["members"]["host-a"]["rank"] == 0  # re-ranked

            c = _spawn(url, "host-c", "plain") # elastic rejoin
            try:
                deadline = time.time() + 30
                while time.time() < deadline \
                        and leader.topology()["world_size"] != 2:
                    time.sleep(0.1)
                topo = leader.topology()
                assert topo["world_size"] == 2
                assert sorted(m["rank"] for m in
                              topo["members"].values()) == [0, 1]
            finally:
                c.kill()
                c.communicate(timeout=10)
        finally:
            for p in (a, b):
                if p.poll() is None:
                    p.kill()
                p.communicate(timeout=10)


def test_tensor_parallel_decode_across_processes():
    """The distributed-serving hand-off end to end: leader-issued ranks
    -> jax.distributed.initialize -> ONE tp-sharded llama decode as an
    SPMD program spanning both OS processes, reproducing the
    single-device greedy tokens. (Equality holds because the tiny
    model's logit gaps dwarf tp's reduction-reorder noise; if a future
    platform flips a near-tie, compare logits with a tolerance instead
    of blaming the sharding.)"""
    # local single-device reference (separate process world untouched);
    # the scenario constants are SHARED with the worker (TP_PROMPT...)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gofr_tpu.models.llama import (LlamaConfig, llama_decode_step,
                                       llama_init, llama_prefill_last,
                                       make_empty_cache)

    from .multihost_worker import TP_MAX_SEQ, TP_PROMPT, TP_STEPS

    config = LlamaConfig.tiny()
    params = llama_init(jax.random.key(0), config)
    n = len(TP_PROMPT)
    prompt = jnp.asarray([TP_PROMPT], jnp.int32)
    lengths = jnp.asarray([n], jnp.int32)
    logits, (k, v) = llama_prefill_last(params, prompt, config,
                                        kv_lengths=lengths,
                                        implementation="xla")
    k0, v0 = make_empty_cache(config, 1, max_seq=TP_MAX_SEQ)
    k = k0.at[:, :, :n].set(k)
    v = v0.at[:, :, :n].set(v)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    want = [int(np.asarray(tok)[0])]
    for step in range(TP_STEPS - 1):
        logits, k, v = llama_decode_step(params, tok, k, v,
                                         lengths + step, config)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        want.append(int(np.asarray(tok)[0]))

    coord = f"127.0.0.1:{_free_port()}"
    leader = ControlPlaneLeader(coordinator=coord,
                                heartbeat_interval_s=0.5)
    with AppRunner(build=lambda app: leader.install(app)) as runner:
        url = f"http://127.0.0.1:{runner.port}"
        procs = [_spawn(url, f"tp-{i}", "jax_tp") for i in range(2)]
        outs = []
        for p in procs:
            try:
                stdout, stderr = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                p.kill()
                stdout, stderr = p.communicate()
            outs.append((p.returncode, stdout, stderr))
    for rc, stdout, stderr in outs:
        assert rc == 0, f"worker failed rc={rc}:\n{stdout}\n{stderr}"
    token_lists = []
    for _rc, stdout, _stderr in outs:
        ev = next(e for e in _events(stdout) if e["event"] == "tp_tokens")
        token_lists.append(ev["tokens"])
    # both processes computed the same replicated logits, and the
    # greedy tokens match the single-device reference
    assert token_lists[0] == token_lists[1] == want, \
        (token_lists, want)
