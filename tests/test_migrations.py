"""Migrations: ledger, ordering, transactional rollback, multi-store."""

from __future__ import annotations

import pytest

from gofr_tpu.config import DictConfig
from gofr_tpu.container.container import Container
from gofr_tpu.datasource.kv import InMemoryKV
from gofr_tpu.datasource.redis import Redis
from gofr_tpu.datasource.sql import SQL
from gofr_tpu.migrations import Migrate, MigrationError, run


def make_container(*, sql=True, redis=False, kv=False) -> Container:
    c = Container(config=DictConfig({}))
    if sql:
        store = SQL()
        store.connect()
        c.sql = store
    if redis:
        c.redis = Redis()
        c.redis.connect()
    if kv:
        c.kv = InMemoryKV()
        c.kv.connect()
    return c


def create_users(ds):
    ds.sql.exec("CREATE TABLE users (id INTEGER PRIMARY KEY, name TEXT)")


def seed_users(ds):
    ds.sql.exec("INSERT INTO users (id, name) VALUES (1, 'ada')")


class TestMigrationRun:
    def test_applies_in_version_order_and_records_ledger(self):
        c = make_container()
        applied = run(c, {
            20240102: Migrate(up=seed_users),
            20240101: Migrate(up=create_users),
        })
        assert applied == [20240101, 20240102]
        rows = c.sql.query("SELECT version FROM gofr_migrations ORDER BY version")
        assert [r["version"] for r in rows] == [20240101, 20240102]
        assert c.sql.query_row("SELECT name FROM users")["name"] == "ada"

    def test_rerun_is_idempotent(self):
        c = make_container()
        migrations = {1: Migrate(up=create_users), 2: Migrate(up=seed_users)}
        assert run(c, migrations) == [1, 2]
        assert run(c, migrations) == []  # nothing new
        migrations[3] = Migrate(
            up=lambda ds: ds.sql.exec(
                "INSERT INTO users (id, name) VALUES (2, 'lin')"))
        assert run(c, migrations) == [3]
        assert len(c.sql.query("SELECT * FROM users")) == 2

    def test_failure_rolls_back_sql_and_ledger(self):
        c = make_container()
        run(c, {1: Migrate(up=create_users)})

        def bad(ds):
            ds.sql.exec("INSERT INTO users (id, name) VALUES (9, 'ghost')")
            raise RuntimeError("migration exploded")
        with pytest.raises(RuntimeError, match="exploded"):
            run(c, {1: Migrate(up=create_users), 2: Migrate(up=bad)})
        # neither the row nor the ledger entry survived
        assert c.sql.query("SELECT * FROM users") == []
        versions = [r["version"] for r in
                    c.sql.query("SELECT version FROM gofr_migrations")]
        assert versions == [1]
        # and a later fixed run applies cleanly
        assert run(c, {1: Migrate(up=create_users),
                       2: Migrate(up=seed_users)}) == [2]

    def test_ddl_also_rolls_back(self):
        """CREATE TABLE inside a failing migration must not survive
        (sqlite legacy mode would auto-commit DDL and wedge reruns)."""
        c = make_container()

        def bad_ddl(ds):
            ds.sql.exec("CREATE TABLE half_done (id INTEGER)")
            raise RuntimeError("died after DDL")
        with pytest.raises(RuntimeError):
            run(c, {1: Migrate(up=bad_ddl)})
        row = c.sql.query_row(
            "SELECT name FROM sqlite_master WHERE name='half_done'")
        assert row is None
        # rerun with a fixed migration succeeds (no 'already exists')
        assert run(c, {1: Migrate(up=create_users)}) == [1]

    def test_select_works_inside_migration(self):
        from dataclasses import dataclass

        @dataclass
        class User:
            id: int
            name: str

        c = make_container()
        got = []

        def read_back(ds):
            ds.sql.exec("INSERT INTO users VALUES (1, 'ada')")
            got.extend(ds.sql.select(User, "SELECT id, name FROM users"))
        run(c, {1: Migrate(up=create_users), 2: Migrate(up=read_back)})
        assert got == [User(id=1, name="ada")]

    def test_kv_and_redis_ledgers(self):
        c = make_container(sql=False, redis=True, kv=True)
        ran = []
        applied = run(c, {
            1: Migrate(up=lambda ds: ds.kv.set("schema", "v1")),
            2: Migrate(up=lambda ds: ran.append(2)),
        })
        assert applied == [1, 2]
        assert c.kv.get("schema") == "v1"
        # both stores recorded both versions
        assert run(c, {1: Migrate(up=lambda ds: ran.append("again")),
                       2: Migrate(up=lambda ds: ran.append("again"))}) == []
        assert "again" not in ran

    def test_validation(self):
        c = make_container()
        with pytest.raises(MigrationError, match="invalid migration version"):
            run(c, {0: Migrate(up=create_users)})
        with pytest.raises(MigrationError, match="no callable"):
            run(c, {1: object()})

    def test_no_datasource_errors(self):
        c = make_container(sql=False)
        with pytest.raises(MigrationError, match="no datasource"):
            run(c, {1: Migrate(up=create_users)})

    def test_pubsub_topic_migration(self):
        from gofr_tpu.pubsub.inmemory import InMemoryBroker
        c = make_container()
        c.pubsub = InMemoryBroker()
        run(c, {1: Migrate(up=lambda ds: ds.pubsub.create_topic("orders"))})
        assert "orders" in c.pubsub.topics


class TestAppMigrate:
    def test_app_facade(self):
        from gofr_tpu.app import App
        app = App(config=DictConfig({"DB_DIALECT": "sqlite",
                                     "DB_NAME": ":memory:"}))
        assert app.container.sql is not None
        app.migrate({1: Migrate(up=create_users)})
        assert app.container.sql.query("SELECT * FROM users") == []
