"""Boot a real App on ephemeral ports in a background thread for tests.

The analog of the reference's ``testutil.NewServerConfigs`` pattern
(pkg/gofr/testutil/port.go:51-71): tests exercise the actual server
over localhost.
"""

from __future__ import annotations

import asyncio
import http.client
import json
import threading
import time

from gofr_tpu.config import DictConfig


def grpc_channel(port: int):
    """aio channel with a LOCAL subchannel pool. grpc's default global
    pool shares live TCP subchannels across channels keyed by target,
    so when the kernel recycles an ephemeral port across two test
    servers in one process, a fresh channel can ride the dead server's
    cached connection — observed as spurious UNAVAILABLE/INTERNAL on
    the first RPC under a loaded suite."""
    import grpc
    return grpc.aio.insecure_channel(
        f"127.0.0.1:{port}",
        options=(("grpc.use_local_subchannel_pool", 1),))


class AppRunner:
    def __init__(self, app=None, config: dict | None = None, build=None):
        from gofr_tpu.app import App
        cfg = {"HTTP_PORT": "0", "METRICS_PORT": "0", "APP_NAME": "test-app"}
        cfg.update(config or {})
        self.app = app if app is not None else App(config=DictConfig(cfg))
        self._build = build  # callback(app) to register routes
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()
        self._error: BaseException | None = None

    def __enter__(self) -> "AppRunner":
        if self._build is not None:
            self._build(self.app)

        def runner() -> None:
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def main():
                try:
                    await self.app.start()
                finally:
                    self._started.set()
                await self.app._stop_event.wait()

            try:
                self._loop.run_until_complete(main())
            except Exception as exc:
                self._error = exc
                self._started.set()
            finally:
                self._loop.close()

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()
        if not self._started.wait(10):
            raise TimeoutError("app did not start")
        if self._error is not None:
            raise self._error
        time.sleep(0.01)
        return self

    def __exit__(self, *exc) -> None:
        if self._loop is not None and self._loop.is_running():
            asyncio.run_coroutine_threadsafe(self.app.stop(), self._loop).result(10)
        if self._thread is not None:
            self._thread.join(10)

    @property
    def port(self) -> int:
        return self.app.http_server.bound_port

    @property
    def metrics_port(self) -> int:
        return self.app.metrics_server.bound_port

    # -- tiny sync client
    def request(self, method: str, path: str, body: bytes | str | dict | None = None,
                headers: dict | None = None, port: int | None = None,
                timeout: float = 60):
        # 60 s default: generation endpoints compile on first hit and
        # the suite shares cores with benches/background work — a 10 s
        # cap flaked under load (r5, test_model_serving_from_disk_
        # checkpoint) while meaning nothing about correctness
        conn = http.client.HTTPConnection("127.0.0.1", port or self.port,
                                          timeout=timeout)
        headers = dict(headers or {})
        if isinstance(body, dict):
            body = json.dumps(body)
            headers.setdefault("Content-Type", "application/json")
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

    def get_json(self, path: str, **kw):
        status, headers, data = self.request("GET", path, **kw)
        return status, json.loads(data) if data else None
