"""Tracer tests — span lifecycle, propagation, sampling, log correlation."""

import pytest

from gofr_tpu.logging import MockLogger
from gofr_tpu.tracing import (
    InMemoryExporter, Tracer, extract_traceparent, format_traceparent,
)


@pytest.fixture(autouse=True)
def _reset_trace_contextvars():
    """The cross-thread test ends its span on ANOTHER thread, where the
    contextvar token can't reset the main thread's context — without
    this cleanup the span (and its trace ids) stay active on the main
    thread and corrupt any log-asserting test that runs later in the
    suite (the tier-1 runner executes files alphabetically)."""
    yield
    from gofr_tpu.logging.logger import _trace_ctx
    from gofr_tpu.tracing.tracer import _current_span
    _current_span.set(None)
    _trace_ctx.set(None)


def test_span_lifecycle_and_export():
    exp = InMemoryExporter()
    tracer = Tracer(exporter=exp)
    with tracer.start_span("GET /x") as span:
        span.set_attribute("http.status", 200)
    assert len(exp.spans) == 1
    s = exp.spans[0]
    assert s.name == "GET /x"
    assert s.end_time is not None
    assert s.attributes["http.status"] == 200
    assert len(s.trace_id) == 32 and len(s.span_id) == 16


def test_child_span_shares_trace():
    exp = InMemoryExporter()
    tracer = Tracer(exporter=exp)
    with tracer.start_span("parent") as parent:
        with tracer.start_span("child") as child:
            assert child.trace_id == parent.trace_id
            assert child.parent_id == parent.span_id
    assert [s.name for s in exp.spans] == ["child", "parent"]


def test_traceparent_roundtrip():
    header = format_traceparent("ab" * 16, "cd" * 8)
    parsed = extract_traceparent(header)
    assert parsed == ("ab" * 16, "cd" * 8)
    assert extract_traceparent("garbage") is None
    assert extract_traceparent(None) is None
    assert extract_traceparent("00-" + "0" * 32 + "-" + "1" * 16 + "-01") is None


def test_remote_parent_continues_trace():
    exp = InMemoryExporter()
    tracer = Tracer(exporter=exp)
    header = format_traceparent("12" * 16, "34" * 8)
    with tracer.start_span("srv", traceparent=header) as span:
        assert span.trace_id == "12" * 16
        assert span.parent_id == "34" * 8


def test_sampling_zero_exports_nothing():
    exp = InMemoryExporter()
    tracer = Tracer(exporter=exp, ratio=0.0)
    with tracer.start_span("dropped"):
        pass
    assert exp.spans == []


def test_inject_headers():
    tracer = Tracer(exporter=InMemoryExporter())
    with tracer.start_span("client") as span:
        headers = tracer.inject_headers({})
        assert headers["traceparent"] == format_traceparent(span.trace_id, span.span_id)
    assert tracer.inject_headers({}) == {}


def test_span_correlates_logs():
    tracer = Tracer(exporter=InMemoryExporter())
    log = MockLogger()
    with tracer.start_span("op") as span:
        log.info("inside")
    rec = log.lines[0]
    assert rec["trace_id"] == span.trace_id
    assert rec["span_id"] == span.span_id


def test_error_status_on_exception():
    exp = InMemoryExporter()
    tracer = Tracer(exporter=exp)
    try:
        with tracer.start_span("boom"):
            raise ValueError("bad")
    except ValueError:
        pass
    assert exp.spans[0].status.startswith("ERROR")


def test_upstream_sampled_flag_honored():
    exp = InMemoryExporter()
    tracer = Tracer(exporter=exp, ratio=0.0)  # local ratio would drop
    with tracer.start_span("s", traceparent="00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"):
        pass
    assert len(exp.spans) == 1  # upstream said sampled -> we keep it
    with tracer.start_span("t", traceparent="00-" + "ab" * 16 + "-" + "cd" * 8 + "-00"):
        pass
    assert len(exp.spans) == 1  # upstream said not sampled -> dropped


def test_end_from_other_thread_still_exports():
    import threading
    exp = InMemoryExporter()
    tracer = Tracer(exporter=exp)
    span = tracer.start_span("cross-thread")
    t = threading.Thread(target=span.end)
    t.start()
    t.join()
    assert len(exp.spans) == 1
