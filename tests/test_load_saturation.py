"""Saturation load test: 64+ concurrent requests through the full HTTP
layer on the tiny model (VERDICT r2 item 7 — the regression net under
the bench's throughput/TTFT claims).

Asserts: every request completes, the TTFT histogram populates, and
admission is fair (no request's TTFT is pathologically starved relative
to the pack). Marked ``slow``; CI can deselect with ``-m 'not slow'``.
"""

import json
import threading
import time

import pytest

from .apputil import AppRunner
from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.handlers import make_chat_handler
from gofr_tpu.serving.tokenizer import ByteTokenizer

N_REQUESTS = 64
GEN_TOKENS = 8


@pytest.mark.slow
def test_64_concurrent_chats_saturate_and_complete():
    from gofr_tpu.metrics.registry import Manager
    metrics = Manager()
    metrics.new_histogram("app_chat_ttft_seconds", "ttft",
                          buckets=(0.1, 0.5, 1.0, 5.0, 30.0))
    metrics.new_histogram("app_tpu_execute_seconds", "device pass")
    engine = demo_llama_engine(
        EngineConfig(max_batch=8, max_seq=128, seed=1), metrics=metrics)
    engine.start()

    results: list[dict] = []
    errors: list[str] = []
    lock = threading.Lock()

    with AppRunner() as runner:
        runner.app.post("/chat", make_chat_handler(engine, ByteTokenizer()))

        def one(i: int) -> None:
            try:
                status, _, data = runner.request(
                    "POST", "/chat",
                    body={"prompt": f"load test request {i}",
                          "max_tokens": GEN_TOKENS, "temperature": 0.0},
                    # saturation is the POINT: under a loaded suite the
                    # tail request legitimately waits out the queue
                    timeout=180)
                payload = json.loads(data)
                with lock:
                    if status != 201:
                        errors.append(f"req {i}: status {status}")
                    else:
                        results.append(payload["data"])
            except Exception as exc:
                with lock:
                    errors.append(f"req {i}: {exc!r}")

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(N_REQUESTS)]
        start = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        wall = time.time() - start

    engine.stop()

    # 1) everyone completes, with the full token budget
    assert not errors, errors[:5]
    assert len(results) == N_REQUESTS
    assert all(r["usage"]["completion_tokens"] == GEN_TOKENS
               for r in results)

    # 2) the TTFT histogram populated once per request
    scrape = metrics.render_prometheus()
    ttft_count = next(
        line for line in scrape.splitlines()
        if line.startswith("app_chat_ttft_seconds_count"))
    assert int(float(ttft_count.split()[-1])) == N_REQUESTS

    # 3) fairness: with FIFO admission the TTFT distribution is a
    # staircase — the slowest request waits its queue turn, nothing
    # more. Anchor the bound to the MEDIAN (robust to a loaded CI
    # machine; anchoring to the fastest request flakes under
    # contention): a starved request would sit orders of magnitude
    # beyond the pack.
    ttfts = sorted(r["usage"]["ttft_ms"] for r in results)
    median = max(ttfts[len(ttfts) // 2], 1.0)
    assert ttfts[-1] <= max(median * 25, 30_000), (
        f"slowest TTFT {ttfts[-1]:.0f}ms vs median {median:.0f}ms")

    # sanity: saturated throughput is positive and finite
    assert wall < 300
