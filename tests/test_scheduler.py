"""Scheduler tests: the admission/fair-share/rate-limit/shed unit
contract over serving/scheduler.py, SLOTracker out-of-order feeds, the
engine's starvation-preemption hook, and the REPLAY EVIDENCE for the
policy itself — a two-tenant contention workload replayed through a
FIFO engine and a fair-share engine, asserting the victim tenant's
fast burn rate is strictly lower under fair-share while the aggregate
goodput ratio degrades by at most 5%.
"""

import queue
import time
from types import SimpleNamespace

import pytest

from gofr_tpu.serving.observability import (SLOConfig, SLOTracker,
                                            WORKLOAD_FORMAT,
                                            WORKLOAD_VERSION)
from gofr_tpu.serving.scheduler import (BACKGROUND, INTERACTIVE,
                                        QUEUE_FULL, RATE_LIMITED, SHED,
                                        RateLimit, SchedReject,
                                        Scheduler, SchedulerConfig,
                                        retry_after_header)


def req(tenant=None, lane=INTERACTIVE, n_prompt=4, max_new=8,
        submitted_at=None):
    return SimpleNamespace(
        tenant=tenant, lane=lane, prompt_tokens=list(range(n_prompt)),
        params=SimpleNamespace(max_new_tokens=max_new),
        submitted_at=time.time() if submitted_at is None
        else submitted_at,
        reject=None)


def drain(sched, n=64):
    out = []
    while len(out) < n:
        batch = sched.pop_batch(1, first_wait_s=0.0)
        if not batch:
            break
        out.extend(batch)
    return out


class FakeLedger:
    """rollup() shaped like UsageLedger's windowed form."""

    def __init__(self, device_s):
        self.device_s = device_s

    def rollup(self, tenant=None, window_s=None):
        return {"window": "5m", "partial": False,
                "tenants": {name: {"device_s": s, "prompt_tokens": 100,
                                   "completion_tokens": 100}
                            for name, s in self.device_s.items()}}


class FakeSLO:
    def __init__(self, burn=0.0, threshold=14.4):
        self.burn = burn
        self.threshold = threshold
        self.config = SimpleNamespace(availability=0.999)

    def state(self):
        return {"fast_burn": {"burn_rate": self.burn,
                              "threshold": self.threshold,
                              "tripped": self.burn >= self.threshold}}


class FakeLogger:
    def __init__(self):
        self.warns = []

    def warn(self, msg, **kw):
        self.warns.append((msg, kw))


def force_slo_recheck(sched):
    """Defeat the 0.25s fast-burn read throttle between puts."""
    sched._slo_checked = float("-inf")


# ------------------------------------------------------- admission unit
class TestAdmission:
    def test_unknown_policy_raises(self):
        with pytest.raises(ValueError):
            Scheduler(SchedulerConfig(policy="lifo"))
        sched = Scheduler()
        with pytest.raises(ValueError):
            sched.reconfigure(SchedulerConfig(policy="lifo"))

    def test_single_tenant_is_strict_fifo(self):
        # one tenant = one sub-queue: fair-share must be bit-identical
        # to the old queue's arrival order
        sched = Scheduler(SchedulerConfig(policy="fair"))
        items = [req(tenant="a") for _ in range(5)]
        for it in items:
            assert sched.put(it)
        assert drain(sched) == items

    def test_fifo_policy_is_global_arrival_order(self):
        sched = Scheduler(SchedulerConfig(policy="fifo"))
        items = [req(tenant=t) for t in
                 ("a", "b", "a", "c", "b", "a")]
        for it in items:
            assert sched.put(it)
        assert drain(sched) == items

    def test_queue_full_typed_reject(self):
        sched = Scheduler(SchedulerConfig(), capacity=2)
        assert sched.put(req(tenant="a"))
        assert sched.put(req(tenant="a"))
        third = req(tenant="a")
        assert not sched.put(third)
        rej = third.reject
        assert isinstance(rej, SchedReject)
        assert rej.code == QUEUE_FULL and rej.tenant == "a"
        assert rej.retry_after_s == sched.config.retry_after_s
        assert sched.counters["rejected"][QUEUE_FULL] == 1
        # already-admitted work re-entering is exempt from the bound
        victim = drain(sched, 1)[0]
        assert sched.put(req(tenant="a"))  # refill to capacity
        sched.readmit(victim)
        assert sched.qsize() == 3  # over the bound, by design
        assert sched.counters["readmitted"] == 1

    def test_readmit_enters_at_the_head(self):
        sched = Scheduler(SchedulerConfig())
        a, b, c = (req(tenant="t", lane=BACKGROUND) for _ in range(3))
        for it in (a, b, c):
            assert sched.put(it)
        assert drain(sched, 1) == [a]
        sched.readmit(a)  # preemption victim: back to the head
        assert drain(sched) == [a, b, c]

    def test_close_contract(self):
        sched = Scheduler(SchedulerConfig())
        sched.close()
        it = req()
        assert not sched.put(it)
        # closed queues stamp nothing: the engine's "not accepting
        # requests" failure stands
        assert it.reject is None
        assert sched.pop_batch(4, first_wait_s=0.0) is None

    def test_get_nowait_and_qsize(self):
        sched = Scheduler(SchedulerConfig())
        with pytest.raises(queue.Empty):
            sched.get_nowait()
        it = req()
        sched.put(it)
        assert sched.qsize() == 1
        assert sched.get_nowait() is it
        assert sched.qsize() == 0


# ----------------------------------------------------------- rate limit
class TestRateLimits:
    def test_rps_bucket_rejects_with_retry_after(self):
        sched = Scheduler(SchedulerConfig(
            rate_limits={"a": RateLimit(rps=1.0, burst=1.0)}))
        assert sched.put(req(tenant="a"))
        second = req(tenant="a")
        assert not sched.put(second)
        rej = second.reject
        assert rej.code == RATE_LIMITED and rej.tenant == "a"
        assert rej.retry_after_s > 0
        hdr = retry_after_header(rej)
        assert int(hdr["Retry-After"]) >= 1
        # another tenant has its own bucket
        assert sched.put(req(tenant="b"))
        assert sched.counters["rejected"][RATE_LIMITED] == 1

    def test_prompt_token_bucket(self):
        sched = Scheduler(SchedulerConfig(
            rate_limits={"a": RateLimit(prompt_tps=10.0,
                                        prompt_burst=10.0)}))
        assert sched.put(req(tenant="a", n_prompt=8))
        big = req(tenant="a", n_prompt=8)  # bucket holds only 2 more
        assert not sched.put(big)
        assert big.reject.code == RATE_LIMITED

    def test_wildcard_limit_applies_to_unlisted_tenants(self):
        sched = Scheduler(SchedulerConfig(
            rate_limits={"*": RateLimit(rps=1.0, burst=1.0)}))
        assert sched.put(req(tenant="anyone"))
        blocked = req(tenant="anyone")
        assert not sched.put(blocked)
        assert blocked.reject.code == RATE_LIMITED

    def test_readmit_bypasses_buckets(self):
        sched = Scheduler(SchedulerConfig(
            rate_limits={"a": RateLimit(rps=1.0, burst=1.0)}))
        first = req(tenant="a")
        assert sched.put(first)
        drain(sched, 1)
        sched.readmit(first)  # its admission was already paid
        assert sched.qsize() == 1


# ------------------------------------------------------ fairness / lanes
class TestFairShareAndLanes:
    def test_interactive_lane_dequeues_first(self):
        sched = Scheduler(SchedulerConfig())
        bg = [req(tenant="t", lane=BACKGROUND) for _ in range(2)]
        for it in bg:
            sched.put(it)
        fg = req(tenant="t")
        sched.put(fg)
        assert drain(sched) == [fg] + bg

    def test_background_tenants_mapping(self):
        sched = Scheduler(SchedulerConfig(background_tenants=("bulk",)))
        it = req(tenant="bulk")
        sched.put(it)
        assert it.lane == BACKGROUND
        # explicit background submission wins over the default too
        it2 = req(tenant="chat", lane=BACKGROUND)
        sched.put(it2)
        assert it2.lane == BACKGROUND

    def test_ledger_share_starves_the_hog(self):
        # hot tenant owns nearly all windowed device time: the victim's
        # later arrival must still dequeue first
        sched = Scheduler(SchedulerConfig(),
                          ledger=FakeLedger({"hot": 10.0,
                                             "victim": 0.1}))
        hot = [req(tenant="hot") for _ in range(3)]
        for it in hot:
            sched.put(it)
        cold = req(tenant="victim")
        sched.put(cold)
        order = drain(sched)
        assert order[0] is cold

    def test_weights_scale_entitlement(self):
        # same measured share, but tenant "paid" carries weight 10:
        # its weighted share is lower, so it dequeues first
        sched = Scheduler(SchedulerConfig(weights={"paid": 10.0}),
                          ledger=FakeLedger({"free": 1.0, "paid": 1.0}))
        free = req(tenant="free")
        sched.put(free)
        paid = req(tenant="paid")
        sched.put(paid)
        assert drain(sched)[0] is paid

    def test_inflight_debt_interleaves_before_ledger_catches_up(self):
        # zero ledger shares (cold start): after dequeuing one hot
        # request the hot tenant carries in-flight debt, so the next
        # pick is the victim even though it arrived last
        sched = Scheduler(SchedulerConfig())
        hot = [req(tenant="hot") for _ in range(4)]
        for it in hot:
            sched.put(it)
        cold = req(tenant="victim")
        sched.put(cold)
        first = drain(sched, 1)[0]
        assert first is hot[0]  # tie on zero shares: arrival order
        assert drain(sched, 1)[0] is cold

    def test_reconfigure_rebuckets_and_preserves_burn(self):
        sched = Scheduler(SchedulerConfig())
        sched.note_retire("bulk", good=False)
        queued = req(tenant="bulk")
        sched.put(queued)
        assert queued.lane == INTERACTIVE
        sched.reconfigure(SchedulerConfig(background_tenants=("bulk",)))
        assert queued.lane == BACKGROUND
        st = sched.state()
        assert st["tenants"]["bulk"]["queued"][BACKGROUND] == 1
        assert st["tenants"]["bulk"]["burn"]["bad"] == 1
        assert drain(sched) == [queued]


# ------------------------------------------------------------- shedding
class TestShedding:
    def make(self, slo, **cfg):
        logger = FakeLogger()
        sched = Scheduler(
            SchedulerConfig(**cfg),
            ledger=FakeLedger({"hot": 20.0, "victim": 1.0}),
            slo_source=lambda: slo, logger=logger)
        return sched, logger

    def test_episode_sheds_background_first_with_hysteresis(self):
        slo = FakeSLO(burn=20.0)
        sched, logger = self.make(slo)
        bg = req(tenant="victim", lane=BACKGROUND)
        assert not sched.put(bg)
        assert bg.reject.code == SHED
        assert sched.counters["shed_episodes"] == 1
        assert len(logger.warns) == 1  # WARN once per episode
        # interactive traffic from the under-share tenant still flows
        assert sched.put(req(tenant="victim"))

        # burn falls below the trip point but above the exit ratio:
        # hysteresis keeps the episode open (no re-admit flapping)
        slo.burn = 10.0  # threshold 14.4, exit at 7.2
        force_slo_recheck(sched)
        still = req(tenant="victim", lane=BACKGROUND)
        assert not sched.put(still)
        assert len(logger.warns) == 1  # same episode, no second WARN

        # full recovery ends the episode; background flows again
        slo.burn = 5.0
        force_slo_recheck(sched)
        assert sched.put(req(tenant="victim", lane=BACKGROUND))

        # a fresh trip is a NEW episode: counted and warned again
        slo.burn = 20.0
        force_slo_recheck(sched)
        again = req(tenant="victim", lane=BACKGROUND)
        assert not sched.put(again)
        assert sched.counters["shed_episodes"] == 2
        assert len(logger.warns) == 2

    def test_over_share_interactive_sheds_under_share_survives(self):
        sched, _ = self.make(FakeSLO(burn=20.0), shed_overshare=1.5)
        hog = req(tenant="hot")  # 20/21 of the window: over-share
        assert not sched.put(hog)
        assert hog.reject.code == SHED
        assert sched.put(req(tenant="victim"))

    def test_shed_disabled_is_inert(self):
        sched, logger = self.make(FakeSLO(burn=100.0), shed=False)
        assert sched.put(req(tenant="victim", lane=BACKGROUND))
        assert sched.counters["shed_episodes"] == 0
        assert not logger.warns


# ------------------------------------------------- starvation decision
class TestStarvation:
    def test_decision_is_rate_capped_and_counted_separately(self):
        sched = Scheduler(SchedulerConfig(starvation_s=0.01,
                                          preempt_min_interval_s=30.0))
        old = req(tenant="a", submitted_at=time.time() - 5.0)
        sched.put(old)
        assert sched.starving_interactive()
        # the DECISION armed the rate cap — a victimless attempt must
        # not re-fire every engine pass
        assert not sched.starving_interactive()
        assert sched.counters["preemptions"] == 0
        sched.note_preempted()  # the engine actually preempted
        assert sched.counters["preemptions"] == 1

    def test_fifo_and_disabled_never_starve(self):
        for cfg in (SchedulerConfig(policy="fifo", starvation_s=0.01),
                    SchedulerConfig(starvation_s=0.0)):
            sched = Scheduler(cfg)
            sched.put(req(tenant="a",
                          submitted_at=time.time() - 5.0))
            assert not sched.starving_interactive()


# ------------------------------------------------------- state contract
class TestState:
    def test_state_shape(self):
        sched = Scheduler(
            SchedulerConfig(rate_limits={"a": RateLimit(rps=5.0)}),
            ledger=FakeLedger({"a": 3.0, "b": 1.0}))
        sched.put(req(tenant="a"))
        sched.put(req(tenant="b", lane=BACKGROUND))
        sched.note_retire("a", good=False)
        st = sched.state()
        assert st["policy"] == "fair"
        assert st["lanes"] == {INTERACTIVE: 1, BACKGROUND: 1}
        assert st["depth"] == 2
        a = st["tenants"]["a"]
        assert a["queued"][INTERACTIVE] == 1
        assert 0.0 < a["device_share"] < 1.0
        assert a["burn"]["bad"] == 1 and a["burn"]["burn_rate"] > 0
        assert "rps_bucket_level" in a
        assert st["shedding"]["enabled"] and not st["shedding"]["active"]
        assert st["counters"]["admitted"] == 2

    def test_tenant_burn_evicts_outside_window(self):
        sched = Scheduler(SchedulerConfig(burn_window_s=10.0))
        sched.note_retire("a", good=False, t=time.time() - 60.0)
        sched.note_retire("a", good=True)
        burn = sched.state()["tenants"]["a"]["burn"]
        assert burn == {"total": 1, "bad": 0, "burn_rate": 0.0}

    def test_retry_after_header_rounds_up_with_floor(self):
        assert retry_after_header(
            SchedReject("shed", "a", 0.2))["Retry-After"] == "1"
        assert retry_after_header(
            SchedReject("rate_limited", "a", 2.3))["Retry-After"] == "3"


# ------------------------------------------- SLOTracker out-of-order t
class TestSLOTrackerOutOfOrder:
    """record(t=...) feeds are clamped to the newest seen timestamp so
    the per-window deques stay sorted and eviction stays exact —
    replay feeds and multi-source clocks deliver out-of-order times."""

    def make(self):
        return SLOTracker(SLOConfig(windows=(10.0, 100.0),
                                    fast_burn=0.0))

    def test_late_old_timestamp_cannot_hide_behind_a_newer_one(self):
        # state() evicts against the wall clock, so anchor there
        base = time.time()
        tr = self.make()
        tr.record(False, t=base)
        tr.record(False, t=base - 50.0)  # clamped up to base
        win = tr.state()["windows"]["10s"]
        assert (win["total"], win["bad"]) == (2, 2)
        # a record past the window end evicts BOTH together — an
        # unclamped base-50 entry sitting behind base would make the
        # head-pop eviction stop early and overcount forever
        tr.record(True, t=base + 11.0)
        win = tr._state_locked(base + 11.0)["windows"]["10s"]
        assert (win["total"], win["bad"]) == (1, 0)

    def test_out_of_order_feed_matches_sorted_feed(self):
        # the invariant in one line: counts equal a tracker fed the
        # same outcomes with the clamped (sorted) timestamps
        base = time.time()
        shuffled = [(False, base + 3.0), (True, base + 1.0),
                    (False, base + 2.5), (True, base + 4.0),
                    (False, base + 1.2)]
        a, b = self.make(), self.make()
        for good, t in shuffled:
            a.record(good, t=t)
        clamped, hi = [], float("-inf")
        for good, t in shuffled:
            hi = max(hi, t)
            clamped.append((good, hi))
        for good, t in clamped:
            b.record(good, t=t)
        assert a.state()["windows"] == b.state()["windows"]

    def test_high_water_mark_tracks_the_max(self):
        base = time.time()
        tr = self.make()
        for dt in (5.0, 3.0, 9.0, 1.0):
            tr.record(True, t=base + dt)
        assert tr._last_t == base + 9.0


# --------------------------------------------------- engine integration
@pytest.fixture(scope="module")
def glue():
    jax = pytest.importorskip("jax")
    del jax
    from gofr_tpu.serving import glue as g
    return g


def _finish(reqs, timeout=120.0):
    deadline = time.time() + timeout
    while any(r.finished_at is None and r.error is None for r in reqs):
        if time.time() > deadline:
            raise TimeoutError("requests did not finish")
        time.sleep(0.005)
    return reqs


class TestEngineIntegration:
    def test_starvation_preempts_background_for_interactive(self, glue):
        from gofr_tpu.serving.engine import EngineConfig, SamplingParams
        cfg = EngineConfig(
            max_batch=1, max_seq=128, seed=7,
            scheduler=SchedulerConfig(starvation_s=0.05,
                                      preempt_min_interval_s=0.0))
        eng = glue.demo_llama_engine(cfg)
        eng.start()
        try:
            bg = eng.submit([1, 2, 3, 4],
                            SamplingParams(max_new_tokens=96,
                                           temperature=0.0),
                            tenant="bulk", lane=BACKGROUND)
            deadline = time.time() + 30.0
            while bg.slot < 0:  # wait until it holds the only slot
                assert time.time() < deadline, bg.error
                time.sleep(0.002)
            fg = eng.submit([5, 6, 7],
                            SamplingParams(max_new_tokens=4,
                                           temperature=0.0),
                            tenant="chat")
            _finish([bg, fg])
            assert fg.error is None and bg.error is None
            assert eng.waiting.counters["preemptions"] >= 1
            # the victim was recomputed, not lost
            assert len(bg.generated) == 96
            assert len(fg.generated) == 4
            # the interactive request did not wait for the 96-token
            # background request to finish first
            assert fg.finished_at < bg.finished_at
        finally:
            eng.stop()


# ------------------------------------------------------ replay evidence
def contention_workload():
    """Synthetic two-tenant contention capture: the hot tenant floods
    8 long requests, then the victim submits 3 short ones. Greedy,
    versioned, replayable — the records carry no completions (status
    absent), so replay measures scheduling, not token identity."""
    records = []
    t = 0.0
    for i in range(8):
        records.append({"t": t, "tenant": "team-hot",
                        "prompt_tokens": [1 + i, 2, 3, 4, 5, 6],
                        "params": {"temperature": 0.0,
                                   "max_new_tokens": 24}})
        t += 0.001
    for i in range(3):
        records.append({"t": t, "tenant": "team-victim",
                        "prompt_tokens": [9 + i, 8, 7],
                        "params": {"temperature": 0.0,
                                   "max_new_tokens": 4}})
        t += 0.001
    return {"header": {"format": WORKLOAD_FORMAT,
                       "version": WORKLOAD_VERSION, "engine_seed": 3},
            "records": records}


def tenant_e2es(eng, tenant):
    return [ev["e2e_s"] for ev in eng.usage_ledger._events
            if ev["tenant"] == tenant and ev["status"] == "ok"]


def burn_rate(e2es, threshold_s, availability=0.999):
    """The SLO fast-burn arithmetic over one tenant's replayed
    latencies: error rate over the window divided by the budget."""
    bad = sum(1 for v in e2es if v > threshold_s)
    return (bad / len(e2es)) / (1.0 - availability)


class TestFairShareReplayEvidence:
    """The acceptance evidence for this PR, as a test: the SAME
    contention workload replayed under FIFO and under fair-share. The
    victim tenant's burn rate must be STRICTLY lower under fair-share,
    and the aggregate goodput ratio must degrade by at most 5% — the
    policy buys isolation with queueing order, not with device waste.
    """

    def replay(self, glue, policy):
        from gofr_tpu.serving.engine import (EngineConfig,
                                             SamplingParams)
        from gofr_tpu.serving.replay import replay_workload
        workload = contention_workload()
        cfg = EngineConfig(max_batch=1, max_seq=128,
                           seed=workload["header"]["engine_seed"],
                           scheduler=SchedulerConfig(policy=policy))
        eng = glue.demo_llama_engine(cfg)
        try:
            # warm the jit caches first: otherwise compile time lands
            # in the first request's e2e and drowns the queueing
            # signal the comparison measures
            eng.start()
            _finish([eng.submit([1, 2, 3, 4, 5, 6],
                                SamplingParams(max_new_tokens=24,
                                               temperature=0.0),
                                tenant="warmup"),
                     eng.submit([1, 2, 3],
                                SamplingParams(max_new_tokens=4,
                                               temperature=0.0),
                                tenant="warmup")])
            report = replay_workload(eng, workload, speed=1000.0,
                                     timeout_s=120.0)
        finally:
            eng.stop()
        return eng, report

    def test_victim_burn_lower_goodput_within_5pct(self, glue):
        fifo_eng, fifo_rep = self.replay(glue, "fifo")
        fair_eng, fair_rep = self.replay(glue, "fair")
        assert fifo_rep["replay_errors"] == 0
        assert fair_rep["replay_errors"] == 0

        fifo_victim = tenant_e2es(fifo_eng, "team-victim")
        fair_victim = tenant_e2es(fair_eng, "team-victim")
        assert len(fifo_victim) == len(fair_victim) == 3

        # under FIFO the victim queues behind the hot tenant's entire
        # flood; under fair-share the DRR debt interleaves it after a
        # single hot request. Judge both runs against the same
        # threshold: half the BEST e2e the victim saw under FIFO.
        threshold = 0.5 * min(fifo_victim)
        fifo_burn = burn_rate(fifo_victim, threshold)
        fair_burn = burn_rate(fair_victim, threshold)
        assert fifo_burn > 0  # the contention is real
        assert fair_burn < fifo_burn  # strictly lower, the tentpole
        # and the isolation is mechanical, not marginal: the victim's
        # worst wait under fair-share beats its best wait under FIFO
        assert max(fair_victim) < min(fifo_victim)

        # aggregate efficiency: fairness reorders the queue, it must
        # not burn device time — goodput ratio within 5% of FIFO
        fifo_ratio = fifo_rep["replayed_goodput"]["goodput_ratio"]
        fair_ratio = fair_rep["replayed_goodput"]["goodput_ratio"]
        assert fair_ratio >= 0.95 * fifo_ratio, (fifo_ratio, fair_ratio)

        # the hot tenant still gets all its work done
        assert len(tenant_e2es(fair_eng, "team-hot")) == 8
