"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding/parallelism tests
run against ``--xla_force_host_platform_device_count=8`` CPU devices, the
standard JAX pattern for testing Mesh/pjit code paths.  Must run before
jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("GOFR_TELEMETRY", "false")
