"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding/parallelism tests
run against ``--xla_force_host_platform_device_count=8`` CPU devices,
the standard JAX pattern for testing Mesh/pjit code paths.

The environment ships with the 'axon' TPU plugin, which wins over the
``JAX_PLATFORMS`` env var alone — ``jax.config.update`` is what
actually pins the backend. A developer explicitly exporting
``JAX_PLATFORMS`` to something other than the ambient 'axon' keeps
their choice.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("GOFR_TELEMETRY", "false")

# default to cpu unless the developer explicitly exported something else;
# the config.update must run unconditionally because the env var alone
# does not override the axon plugin
_platform = os.environ.get("JAX_PLATFORMS", "axon")
if _platform == "axon":
    _platform = "cpu"
os.environ["JAX_PLATFORMS"] = _platform
import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)

# Silent rank promotion ((B,) op (B, N) broadcasting by accident) is a
# classic source of wrong-but-plausible numerics in ops/models — make
# it a hard error under test. Production code is unaffected; this is a
# test-harness invariant, the static sibling of gofrlint's rules.
jax.config.update("jax_numpy_rank_promotion", "raise")

# Opt-in NaN tripwire: GOFR_DEBUG_NANS=1 makes every jitted op re-run
# eagerly and raise at the op that produced a NaN (jax_debug_nans) —
# too slow for CI default, invaluable when hunting a numeric bug.
if os.environ.get("GOFR_DEBUG_NANS", "").lower() in ("1", "true", "yes"):
    jax.config.update("jax_debug_nans", True)
