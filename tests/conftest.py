"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding/parallelism tests
run against ``--xla_force_host_platform_device_count=8`` CPU devices,
the standard JAX pattern for testing Mesh/pjit code paths.

The environment ships with the 'axon' TPU plugin, which wins over the
``JAX_PLATFORMS`` env var alone — ``jax.config.update`` is what
actually pins the backend. A developer explicitly exporting
``JAX_PLATFORMS`` to something other than the ambient 'axon' keeps
their choice.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("GOFR_TELEMETRY", "false")

# default to cpu unless the developer explicitly exported something else;
# the config.update must run unconditionally because the env var alone
# does not override the axon plugin
_platform = os.environ.get("JAX_PLATFORMS", "axon")
if _platform == "axon":
    _platform = "cpu"
os.environ["JAX_PLATFORMS"] = _platform
import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)
