"""Worker-process entry for the multi-process multi-host test.

Runs as a REAL OS process (``python tests/multihost_worker.py``): joins
the control-plane leader over HTTP, and depending on GOFR_MODE either

- ``jax``: waits for the expected world size, calls
  ``jax.distributed.initialize(**assignment.jax_initialize_args())``
  (the SURVEY §4 hand-off this harness exists to prove), verifies the
  global process/device view, attempts one cross-process collective,
  prints evidence as JSON lines, and exits; or
- ``plain``: joins and heartbeats forever (the test kills it to drive
  eviction), printing every assignment change.

Configuration via env: GOFR_LEADER_URL, GOFR_HOST_ID, GOFR_MODE,
GOFR_EXPECT_WORLD.
"""

import json
import os
import sys
import time


def emit(**kw):
    print("EV " + json.dumps(kw), flush=True)


def main() -> None:
    leader_url = os.environ["GOFR_LEADER_URL"]
    host_id = os.environ["GOFR_HOST_ID"]
    mode = os.environ.get("GOFR_MODE", "plain")
    expect_world = int(os.environ.get("GOFR_EXPECT_WORLD", "2"))

    if mode == "jax":
        import jax
        jax.config.update("jax_platforms", "cpu")

    from gofr_tpu.serving.control_plane import WorkerAgent

    changes = []
    agent = WorkerAgent(leader_url, host_id=host_id,
                        address=f"proc:{os.getpid()}", n_devices=1,
                        heartbeat_interval_s=0.3,
                        on_assignment=lambda a: changes.append(a))
    assignment = agent.join()
    emit(event="joined", **assignment.to_dict())

    if mode == "plain":
        agent.start()
        while True:                    # killed by the test
            time.sleep(0.2)
            if len(changes) > 1:
                emit(event="assignment_changed",
                     **changes[-1].to_dict())
                changes = changes[:1]

    # jax mode: wait until the whole group has joined, refresh the
    # assignment at the settled generation, then hand off to the SPMD
    # runtime exactly the way a serving host would
    deadline = time.time() + 60
    while time.time() < deadline:
        assignment, _changed = agent.heartbeat_sync()
        if assignment.world_size == expect_world:
            break
        time.sleep(0.2)
    else:
        emit(event="error", error="group never reached expected size")
        sys.exit(2)
    emit(event="settled", **assignment.to_dict())

    import jax
    jax.distributed.initialize(**assignment.jax_initialize_args())
    import numpy as np

    evidence = {
        "event": "initialized",
        "rank": assignment.rank,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
    }
    try:
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(
            np.asarray([assignment.rank], np.int32))
        evidence["collective"] = sorted(
            int(x) for x in np.asarray(gathered).ravel())
    except Exception as exc:  # CPU cross-process collectives optional
        evidence["collective"] = None
        evidence["collective_error"] = f"{type(exc).__name__}: {exc}"
    emit(**evidence)
    jax.distributed.shutdown()
    sys.exit(0)


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
