"""Worker-process entry for the multi-process multi-host test.

Runs as a REAL OS process (``python tests/multihost_worker.py``): joins
the control-plane leader over HTTP, and depending on GOFR_MODE either

- ``jax``: waits for the expected world size, calls
  ``jax.distributed.initialize(**assignment.jax_initialize_args())``
  (the SURVEY §4 hand-off this harness exists to prove), verifies the
  global process/device view, attempts one cross-process collective,
  prints evidence as JSON lines, and exits;
- ``jax_tp``: everything ``jax`` does, then runs a tensor-parallel
  tiny-llama greedy decode as ONE SPMD program spanning the whole
  2-process mesh (collectives cross the OS-process boundary) and
  emits the tokens — the scenario constants (``TP_PROMPT`` etc.) are
  shared with the test's single-device reference; or
- ``plain``: joins and heartbeats forever (the test kills it to drive
  eviction), printing every assignment change.

Configuration via env: GOFR_LEADER_URL, GOFR_HOST_ID, GOFR_MODE,
GOFR_EXPECT_WORLD.
"""

import json
import os
import sys
import time


def emit(**kw):
    print("EV " + json.dumps(kw), flush=True)


#: the jax_tp decode scenario — ONE definition for the worker and the
#: test's single-device reference, so they cannot drift apart
TP_PROMPT = [5, 9, 2, 7]
TP_STEPS = 6
TP_MAX_SEQ = 32


def main() -> None:
    leader_url = os.environ["GOFR_LEADER_URL"]
    host_id = os.environ["GOFR_HOST_ID"]
    mode = os.environ.get("GOFR_MODE", "plain")
    expect_world = int(os.environ.get("GOFR_EXPECT_WORLD", "2"))

    if mode in ("jax", "jax_tp"):
        import jax
        jax.config.update("jax_platforms", "cpu")

    from gofr_tpu.serving.control_plane import WorkerAgent

    changes = []
    agent = WorkerAgent(leader_url, host_id=host_id,
                        address=f"proc:{os.getpid()}", n_devices=1,
                        heartbeat_interval_s=0.3,
                        on_assignment=lambda a: changes.append(a))
    assignment = agent.join()
    emit(event="joined", **assignment.to_dict())

    if mode == "plain":
        agent.start()
        while True:                    # killed by the test
            time.sleep(0.2)
            if len(changes) > 1:
                emit(event="assignment_changed",
                     **changes[-1].to_dict())
                changes = changes[:1]

    # jax mode: wait until the whole group has joined, refresh the
    # assignment at the settled generation, then hand off to the SPMD
    # runtime exactly the way a serving host would
    deadline = time.time() + 60
    while time.time() < deadline:
        assignment, _changed = agent.heartbeat_sync()
        if assignment.world_size == expect_world:
            break
        time.sleep(0.2)
    else:
        emit(event="error", error="group never reached expected size")
        sys.exit(2)
    emit(event="settled", **assignment.to_dict())

    import jax
    jax.distributed.initialize(**assignment.jax_initialize_args())
    import numpy as np

    evidence = {
        "event": "initialized",
        "rank": assignment.rank,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
    }
    try:
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(
            np.asarray([assignment.rank], np.int32))
        evidence["collective"] = sorted(
            int(x) for x in np.asarray(gathered).ravel())
    except Exception as exc:  # CPU cross-process collectives optional
        evidence["collective"] = None
        evidence["collective_error"] = f"{type(exc).__name__}: {exc}"
    emit(**evidence)

    if mode == "jax_tp":
        # the full hand-off: tensor-parallel llama decode as ONE SPMD
        # program spanning both OS processes — every matmul's
        # collectives cross the process boundary
        emit(event="tp_tokens", tokens=_tp_decode(jax))
    jax.distributed.shutdown()
    sys.exit(0)


def _tp_decode(jax) -> list[int]:
    """Greedy-decode a few tokens with the tiny llama tp-sharded over
    every device of the 2-process mesh; returns the token ids (each
    process computes the replicated logits, so both emit the same)."""
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gofr_tpu.models.llama import (LlamaConfig, llama_decode_step,
                                       llama_init, llama_prefill_last,
                                       make_empty_cache)
    from gofr_tpu.parallel.mesh import create_mesh
    from gofr_tpu.parallel.sharding import llama_param_specs, shard_params

    config = LlamaConfig.tiny()
    mesh = create_mesh({"tp": len(jax.devices())})
    # identical seed in every process -> globally consistent host
    # arrays; device_put slices out each process's addressable shards
    params = shard_params(llama_init(jax.random.key(0), config),
                          mesh, llama_param_specs(mesh))
    replicated = NamedSharding(mesh, P())
    kv_sh = NamedSharding(mesh, P(None, None, None, "tp", None))

    n = len(TP_PROMPT)
    prompt = jnp.asarray([TP_PROMPT], jnp.int32)
    lengths = jnp.asarray([n], jnp.int32)

    prefill = jax.jit(
        lambda p, t, ln: llama_prefill_last(p, t, config, kv_lengths=ln,
                                            implementation="xla"),
        out_shardings=(replicated, (kv_sh, kv_sh)))
    decode = jax.jit(
        lambda p, tok, kc, vc, ln: llama_decode_step(
            p, tok, kc, vc, ln, config),
        out_shardings=(replicated, kv_sh, kv_sh))

    k0, v0 = make_empty_cache(config, 1, max_seq=TP_MAX_SEQ)
    logits, (k, v) = prefill(
        params, jax.device_put(prompt, replicated),
        jax.device_put(lengths, replicated))
    # grow the prompt KV into a max_seq cache for decode
    k = jax.device_put(k0, kv_sh).at[:, :, :n].set(k)
    v = jax.device_put(v0, kv_sh).at[:, :, :n].set(v)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = [int(np.asarray(tok)[0])]
    for step in range(TP_STEPS - 1):
        logits, k, v = decode(params, tok, k, v, lengths + step)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        tokens.append(int(np.asarray(tok)[0]))
    return tokens


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
