"""Native C++ runtime layer: build system, BPE encoder, batch queue.

Parity-style tests: the native BPE must produce exactly the pure-Python
fallback's tokenization, and the native queue must behave like the
Python fallback — both are exercised with the same assertions.
"""

import threading
import time

import pytest

from gofr_tpu.native import available, compiler
from gofr_tpu.native.batch_queue import (PyRequestQueue, RequestQueue,
                                         new_request_queue)
from gofr_tpu.serving.tokenizer import BPETokenizer

HAVE_CC = compiler() is not None
needs_cc = pytest.mark.skipif(not HAVE_CC, reason="no C++ compiler")


def _ranks() -> dict[bytes, int]:
    """Byte vocabulary + some merges, tiktoken-style ascending ranks."""
    ranks = {bytes([i]): i for i in range(256)}
    nxt = 256
    for merge in [b"th", b"he", b"in", b"er", b"the", b" t", b" the",
                  b"to", b"ke", b"en", b"tok", b"token", b"iz", b"ize"]:
        ranks[merge] = nxt
        nxt += 1
    return ranks


@needs_cc
class TestNativeBPE:
    def test_builds(self):
        assert available("bpe")

    def test_matches_python_fallback_exactly(self):
        tok = BPETokenizer(_ranks())
        assert tok._native is not None, "native path should have loaded"
        texts = ["the tokenizer tokenizes the token",
                 "hello world", "", "a", "  ", "thththththth",
                 "ünïcödé — emoji 🎉 bytes", "x" * 500]
        for text in texts:
            data = text.encode("utf-8")
            assert tok._native.encode(data) == tok._bpe_merge(data), text

    def test_parity_fuzz(self):
        """Random byte soup over the merge alphabet — catches stale-
        heap-entry divergence the curated texts missed."""
        import random
        rng = random.Random(7)
        tok = BPETokenizer(_ranks())
        for trial in range(150):
            data = bytes(rng.choices(b"thein erko z the token",
                                     k=rng.randint(0, 300)))
            assert tok._native.encode(data) == tok._bpe_merge(data), \
                (trial, data)

    def test_roundtrip_through_tokenizer(self):
        tok = BPETokenizer(_ranks())
        text = "the token in the tokenizer"
        ids = tok.encode(text, bos=False)
        assert tok.decode(ids) == text
        # merges actually happened (fewer tokens than bytes)
        assert len(ids) < len(text.encode())

    def test_long_text_fast(self):
        tok = BPETokenizer(_ranks())
        text = "the tokenizer tokenizes the token " * 2000  # ~68KB
        start = time.perf_counter()
        ids = tok._native.encode(text.encode())
        elapsed = time.perf_counter() - start
        assert tok.decode(ids) == text
        assert elapsed < 2.0  # heap merge, not O(n^2)


@needs_cc
class TestNativeQueueBuilds:
    def test_new_request_queue_is_native(self):
        q = new_request_queue()
        assert isinstance(q, RequestQueue)


@pytest.mark.parametrize("make", [
    pytest.param(lambda: RequestQueue(), id="native",
                 marks=needs_cc),
    pytest.param(lambda: PyRequestQueue(), id="python"),
])
class TestRequestQueueSemantics:
    def test_put_pop_order(self, make):
        q = make()
        for i in range(5):
            assert q.put(f"r{i}")
        assert q.qsize() == 5
        batch = q.pop_batch(3, first_wait_s=0.1)
        assert batch == ["r0", "r1", "r2"]
        assert q.pop_batch(10, first_wait_s=0.1) == ["r3", "r4"]

    def test_timeout_returns_empty(self, make):
        q = make()
        start = time.perf_counter()
        assert q.pop_batch(4, first_wait_s=0.05) == []
        assert time.perf_counter() - start < 1.0

    def test_close_returns_none_after_drain(self, make):
        q = make()
        q.put("last")
        q.close()
        assert q.pop_batch(4, first_wait_s=0.05) == ["last"]
        assert q.pop_batch(4, first_wait_s=0.05) is None

    def test_blocking_pop_wakes_on_push(self, make):
        q = make()
        got = []

        def consumer():
            got.extend(q.pop_batch(4, first_wait_s=5.0) or [])

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        q.put("wake")
        t.join(timeout=5)
        assert not t.is_alive()
        assert got == ["wake"]

    def test_drain_window_coalesces_stragglers(self, make):
        q = make()
        q.put("a")

        def late_producer():
            time.sleep(0.03)
            q.put("b")

        t = threading.Thread(target=late_producer)
        t.start()
        batch = q.pop_batch(4, first_wait_s=0.5, drain_wait_s=0.3)
        t.join()
        assert batch == ["a", "b"]  # straggler joined the same batch

    def test_get_nowait_compat(self, make):
        import queue as queue_mod
        q = make()
        q.put("x")
        assert q.get_nowait() == "x"
        with pytest.raises(queue_mod.Empty):
            q.get_nowait()

    def test_many_producers_one_consumer(self, make):
        q = make()
        n_producers, per = 8, 50

        def producer(base):
            for i in range(per):
                q.put(base + i)

        threads = [threading.Thread(target=producer, args=(k * 1000,))
                   for k in range(n_producers)]
        for t in threads:
            t.start()
        seen = []
        deadline = time.time() + 10
        while len(seen) < n_producers * per and time.time() < deadline:
            seen.extend(q.pop_batch(64, first_wait_s=0.5) or [])
        for t in threads:
            t.join()
        assert len(seen) == n_producers * per
        assert len(set(seen)) == n_producers * per  # no dups, no losses


def test_engine_uses_request_queue():
    """The serving engine's admission queue is the native-or-fallback
    request queue (compatible with its queue.Queue-era API)."""
    from gofr_tpu.serving.glue import demo_llama_engine
    engine = demo_llama_engine()
    assert hasattr(engine.waiting, "pop_batch")
    assert engine.waiting.qsize() == 0
