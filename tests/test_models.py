"""Model tests: prefill/decode consistency, masking, shapes."""

import jax
import jax.numpy as jnp
import numpy as np

from gofr_tpu.models.bert import BertConfig, bert_encode, bert_init, mean_pool_embed
from gofr_tpu.models.llama import (
    LlamaConfig,
    llama_decode_step,
    llama_init,
    llama_prefill,
    make_empty_cache,
    param_count,
)
from gofr_tpu.models.moe import MoEConfig, moe_decode_step, moe_init, moe_prefill


def test_llama_prefill_shapes():
    c = LlamaConfig.tiny()
    params = llama_init(jax.random.key(0), c)
    tokens = jax.random.randint(jax.random.key(1), (2, 10), 0, c.vocab_size)
    logits, (k, v) = llama_prefill(params, tokens, c, implementation="xla")
    assert logits.shape == (2, 10, c.vocab_size)
    assert k.shape == (c.n_layers, 2, 10, c.n_kv_heads, c.head_dim)
    assert v.shape == k.shape
    assert bool(jnp.isfinite(logits).all())


def test_llama_decode_matches_prefill():
    """Teacher-forced prefill logits == step-by-step decode logits."""
    c = LlamaConfig.tiny()
    params = llama_init(jax.random.key(0), c)
    b, s = 2, 12
    tokens = jax.random.randint(jax.random.key(1), (b, s), 0, c.vocab_size)

    full_logits, _ = llama_prefill(params, tokens, c, implementation="xla")

    # prefill the first 4 tokens, then decode the rest one at a time
    prefix = 4
    _, (k, v) = llama_prefill(params, tokens[:, :prefix], c, implementation="xla")
    k_cache, v_cache = make_empty_cache(c, b, max_seq=s + 4)
    k_cache = k_cache.at[:, :, :prefix].set(k)
    v_cache = v_cache.at[:, :, :prefix].set(v)

    lengths = jnp.full((b,), prefix, jnp.int32)
    for t in range(prefix, s):
        logits, k_cache, v_cache = llama_decode_step(
            params, tokens[:, t], k_cache, v_cache, lengths, c)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full_logits[:, t]),
            rtol=2e-4, atol=2e-4)
        lengths = lengths + 1


def test_llama_padded_batch_masking():
    """Padding tokens beyond kv_lengths must not change real rows."""
    c = LlamaConfig.tiny()
    params = llama_init(jax.random.key(0), c)
    tokens = jax.random.randint(jax.random.key(1), (1, 6), 0, c.vocab_size)
    padded = jnp.pad(tokens, ((0, 0), (0, 4)), constant_values=7)
    lengths = jnp.array([6], jnp.int32)
    logits_plain, _ = llama_prefill(params, tokens, c, implementation="xla")
    logits_padded, _ = llama_prefill(params, padded, c,
                                     kv_lengths=lengths, implementation="xla")
    np.testing.assert_allclose(np.asarray(logits_padded[:, :6]),
                               np.asarray(logits_plain), rtol=1e-4, atol=1e-4)


def test_llama_param_counts_match_architecture():
    c = LlamaConfig.llama3_8b()
    hd = c.head_dim
    expected = (
        c.vocab_size * c.dim                       # embed
        + c.n_layers * (
            2 * c.dim                              # norms
            + c.dim * c.n_heads * hd               # wq
            + 2 * c.dim * c.n_kv_heads * hd        # wk, wv
            + c.n_heads * hd * c.dim               # wo
            + 3 * c.dim * c.ffn_dim)               # w1, w3, w2
        + c.dim                                    # final norm
        + c.dim * c.vocab_size)                    # lm head
    # ~8.03B for the 8B config
    assert abs(expected - 8.03e9) / 8.03e9 < 0.01
    tiny = LlamaConfig.tiny()
    params = llama_init(jax.random.key(0), tiny)
    assert param_count(params) > 0


def test_bert_encode_and_pooling():
    c = BertConfig.tiny()
    params = bert_init(jax.random.key(0), c)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, c.vocab_size)
    mask = jnp.ones((2, 16), jnp.int32).at[1, 8:].set(0)
    hidden, pooled = bert_encode(params, tokens, c, attention_mask=mask)
    assert hidden.shape == (2, 16, c.dim)
    assert pooled.shape == (2, c.dim)
    emb = mean_pool_embed(hidden, mask)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(emb), axis=-1),
                               1.0, rtol=1e-5)


def test_bert_mask_blocks_padding_influence():
    c = BertConfig.tiny()
    params = bert_init(jax.random.key(0), c)
    tokens = jax.random.randint(jax.random.key(1), (1, 8), 0, c.vocab_size)
    mask = jnp.ones((1, 8), jnp.int32)
    hidden_a, _ = bert_encode(params, tokens, c, attention_mask=mask)
    # change tokens beyond the mask; valid positions must be unaffected
    padded_tokens = jnp.pad(tokens, ((0, 0), (0, 4)), constant_values=3)
    padded_mask = jnp.pad(mask, ((0, 0), (0, 4)))
    hidden_b, _ = bert_encode(params, padded_tokens, c,
                              attention_mask=padded_mask)
    np.testing.assert_allclose(np.asarray(hidden_b[:, :8]),
                               np.asarray(hidden_a), rtol=1e-4, atol=1e-4)


def test_moe_prefill_decode_consistency():
    c = MoEConfig.tiny()
    params = moe_init(jax.random.key(0), c)
    b, s = 1, 8
    tokens = jax.random.randint(jax.random.key(1), (b, s), 0, c.vocab_size)
    full_logits, (k, v), router = moe_prefill(params, tokens, c,
                                              implementation="xla")
    assert router.shape == (c.n_layers, b, s, c.n_experts)

    prefix = 3
    _, (kp, vp), _ = moe_prefill(params, tokens[:, :prefix], c,
                                 implementation="xla")
    smax = s + 2
    kc = jnp.zeros((c.n_layers, b, smax, c.n_kv_heads, c.head_dim), c.dtype)
    vc = jnp.zeros_like(kc)
    kc = kc.at[:, :, :prefix].set(kp)
    vc = vc.at[:, :, :prefix].set(vp)
    lengths = jnp.full((b,), prefix, jnp.int32)
    for t in range(prefix, s):
        logits, kc, vc = moe_decode_step(params, tokens[:, t], kc, vc,
                                         lengths, c)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full_logits[:, t]),
                                   rtol=2e-4, atol=2e-4)
        lengths = lengths + 1
