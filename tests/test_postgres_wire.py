"""PostgreSQL v3 wire protocol: client against the mini server.

Real protocol bytes over a real TCP socket — startup, MD5 and
SCRAM-SHA-256 auth exchanges verified for real, simple + extended
query cycles, transactions, and error recovery.
"""

from dataclasses import dataclass

import pytest

from gofr_tpu.datasource.postgres_wire import (
    MiniPostgresServer, PostgresError, PostgresWire)


@pytest.fixture(scope="module")
def server():
    srv = MiniPostgresServer(user="app", password="s3cr3t", auth="md5")
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def db(server):
    c = PostgresWire(host="127.0.0.1", port=server.port,
                     user="app", password="s3cr3t", database="appdb")
    c.connect()
    yield c
    c.close()


def test_startup_and_parameter_status(db):
    assert db.server_params["server_version"].startswith("16")


def test_simple_query_roundtrip(db):
    db.exec("CREATE TABLE IF NOT EXISTS t_simple (id INTEGER, name TEXT)")
    db.exec("DELETE FROM t_simple")
    db.exec("INSERT INTO t_simple VALUES (1, 'ada'), (2, 'grace')")
    rows = db.query("SELECT id, name FROM t_simple ORDER BY id")
    assert [(r["id"], r["name"]) for r in rows] == [(1, "ada"), (2, "grace")]
    assert db.query_row("SELECT name FROM t_simple WHERE id = 2")["name"] \
        == "grace"


def test_extended_query_with_dollar_params(db):
    db.exec("CREATE TABLE IF NOT EXISTS t_ext "
            "(id INTEGER, score REAL, blob BLOB, note TEXT)")
    db.exec("DELETE FROM t_ext")
    res = db.exec("INSERT INTO t_ext VALUES ($1, $2, $3, $4)",
                  7, 2.5, b"\x00\xff", "hi there")
    assert res.rowcount == 1
    row = db.query_row("SELECT * FROM t_ext WHERE id = $1", 7)
    assert row["score"] == 2.5
    assert row["blob"] == b"\x00\xff"
    assert row["note"] == "hi there"
    # NULL params travel as -1 length
    db.exec("INSERT INTO t_ext VALUES ($1, $2, $3, $4)", 8, None, None, None)
    row = db.query_row("SELECT score, note FROM t_ext WHERE id = $1", 8)
    assert row["score"] is None and row["note"] is None


def test_out_of_range_param_is_protocol_error(db):
    """$N beyond the bound count is an ErrorResponse, not a torn
    connection."""
    with pytest.raises(PostgresError):
        db.query("SELECT $2 AS x", 1)
    assert db.query_row("SELECT 3 AS ok")["ok"] == 3  # stream intact


def test_param_reuse_order(db):
    """$N placeholders bind by number, not appearance order."""
    row = db.query_row("SELECT $2 AS a, $1 AS b, $2 AS c", 10, 20)
    assert (row["a"], row["b"], row["c"]) == (20, 10, 20)


def test_exec_rowcount_tags(db):
    db.exec("CREATE TABLE IF NOT EXISTS t_tags (id INTEGER)")
    db.exec("DELETE FROM t_tags")
    assert db.exec("INSERT INTO t_tags VALUES (1), (2), (3)").rowcount == 3
    assert db.exec("UPDATE t_tags SET id = id + 10").rowcount == 3
    assert db.exec("DELETE FROM t_tags WHERE id > 11").rowcount == 2


def test_transaction_commit_and_rollback(db):
    db.exec("CREATE TABLE IF NOT EXISTS t_tx (id INTEGER)")
    db.exec("DELETE FROM t_tx")
    with db.begin() as tx:
        tx.exec("INSERT INTO t_tx VALUES ($1)", 1)
    assert len(db.query("SELECT * FROM t_tx")) == 1
    with pytest.raises(RuntimeError):
        with db.begin() as tx:
            tx.exec("INSERT INTO t_tx VALUES ($1)", 2)
            raise RuntimeError("boom")
    assert len(db.query("SELECT * FROM t_tx")) == 1  # rolled back


def test_error_response_and_recovery(db):
    with pytest.raises(PostgresError) as exc:
        db.query("SELECT * FROM no_such_table")
    assert exc.value.sqlstate
    # the connection survives an error cycle
    assert db.query_row("SELECT 1 AS one")["one"] == 1
    # extended-cycle error also recovers (server skips to Sync)
    with pytest.raises(PostgresError):
        db.query("SELECT * FROM no_such_table WHERE id = $1", 1)
    assert db.query_row("SELECT 2 AS two")["two"] == 2


def test_select_orm_lite(db):
    @dataclass
    class Person:
        id: int
        name: str

    db.exec("CREATE TABLE IF NOT EXISTS people (id INTEGER, name TEXT)")
    db.exec("DELETE FROM people")
    db.exec("INSERT INTO people VALUES ($1, $2)", 1, "ada")
    people = db.select(Person, "SELECT id, name FROM people")
    assert people == [Person(1, "ada")]


def test_md5_wrong_password_rejected(server):
    bad = PostgresWire(host="127.0.0.1", port=server.port,
                       user="app", password="WRONG")
    with pytest.raises(PostgresError, match="authentication"):
        bad.connect()


def test_unknown_user_rejected(server):
    bad = PostgresWire(host="127.0.0.1", port=server.port,
                       user="nobody", password="s3cr3t")
    with pytest.raises(PostgresError):
        bad.connect()


def test_cleartext_auth():
    srv = MiniPostgresServer(user="u", password="pw", auth="password")
    srv.start()
    try:
        c = PostgresWire(host="127.0.0.1", port=srv.port,
                         user="u", password="pw")
        c.connect()
        assert c.query_row("SELECT 1 AS x")["x"] == 1
        c.close()
    finally:
        srv.close()


def test_scram_sha256_auth_and_mutual_verification():
    srv = MiniPostgresServer(user="u", password="pw", auth="scram-sha-256")
    srv.start()
    try:
        c = PostgresWire(host="127.0.0.1", port=srv.port,
                         user="u", password="pw")
        c.connect()  # raises if the server's signature fails to verify
        assert c.query_row("SELECT 42 AS v")["v"] == 42
        c.close()
        bad = PostgresWire(host="127.0.0.1", port=srv.port,
                           user="u", password="nope")
        with pytest.raises(PostgresError, match="authentication"):
            bad.connect()
    finally:
        srv.close()


def test_env_driven_container_swap(server):
    """DB_DIALECT=postgres + DB_HOST dials the wire client through the
    same new_sql entry the container uses (reference sql.go:74)."""
    from gofr_tpu.config.env import DictConfig
    from gofr_tpu.datasource.sql import new_sql

    cfg = DictConfig({"DB_DIALECT": "postgres",
                     "DB_HOST": "127.0.0.1",
                     "DB_PORT": str(server.port),
                     "DB_USER": "app", "DB_PASSWORD": "s3cr3t",
                     "DB_NAME": "appdb"})
    db = new_sql(cfg)
    assert isinstance(db, PostgresWire)
    assert db.query_row("SELECT 5 AS five")["five"] == 5
    assert db.health_check()["status"] == "UP"
    db.close()


def test_dollar_inside_string_literal_is_not_a_param(db):
    db.exec("CREATE TABLE IF NOT EXISTS t_lit (note TEXT)")
    db.exec("DELETE FROM t_lit")
    db.exec("INSERT INTO t_lit VALUES ('costs $15')")
    assert db.query_row("SELECT note FROM t_lit")["note"] == "costs $15"


def test_null_in_first_row_keeps_numeric_oid(db):
    db.exec("CREATE TABLE IF NOT EXISTS t_null (score REAL)")
    db.exec("DELETE FROM t_null")
    db.exec("INSERT INTO t_null VALUES (NULL), (2.5)")
    rows = db.query("SELECT score FROM t_null ORDER BY score")
    assert rows[0]["score"] is None
    assert rows[1]["score"] == 2.5  # float, not the string "2.5"


def test_transactions_are_per_connection(server):
    """Client A's open BEGIN must not swallow client B's insert —
    postgres transactions are per-connection."""
    a = PostgresWire(host="127.0.0.1", port=server.port,
                     user="app", password="s3cr3t")
    b = PostgresWire(host="127.0.0.1", port=server.port,
                     user="app", password="s3cr3t")
    a.connect()
    b.connect()
    try:
        a.exec("CREATE TABLE IF NOT EXISTS t_iso (id INTEGER)")
        a.exec("DELETE FROM t_iso")
        a.exec("BEGIN")
        a.exec("INSERT INTO t_iso VALUES (1)")
        import threading
        done = threading.Event()

        def other():
            b.exec("INSERT INTO t_iso VALUES (2)")  # blocks until A ends
            done.set()

        t = threading.Thread(target=other, daemon=True)
        t.start()
        a.exec("ROLLBACK")  # A's insert is discarded...
        assert done.wait(10)
        t.join(10)
        rows = a.query("SELECT id FROM t_iso")
        # ...while B's, committed after A released, survives
        assert [r["id"] for r in rows] == [2]
    finally:
        a.close()
        b.close()


def test_health_check(db):
    assert db.health_check()["status"] == "UP"
    loose = PostgresWire(host="127.0.0.1", port=1, user="x")
    assert loose.health_check()["status"] == "DOWN"
