"""Mongo wire client: BSON codec + OP_MSG over a real socket against
the mini server (reference datasource/mongo's network-client role)."""

import datetime

import pytest

from gofr_tpu.datasource.mongo_wire import (
    MiniMongoServer,
    MongoWire,
    MongoWireError,
    ObjectId,
    decode_bson,
    decode_op_msg,
    encode_bson,
    encode_op_msg,
)


# ------------------------------------------------------------------ codec

def test_bson_roundtrip_all_types():
    oid = ObjectId()
    doc = {
        "str": "héllo",
        "int32": 42,
        "int64": 1 << 40,
        "neg": -7,
        "float": 3.5,
        "bool_t": True,
        "bool_f": False,
        "null": None,
        "binary": b"\x00\x01\xff",
        "oid": oid,
        "when": datetime.datetime(2026, 7, 30, 12, 0,
                                  tzinfo=datetime.timezone.utc),
        "nested": {"a": [1, "two", {"three": 3}]},
    }
    got, pos = decode_bson(encode_bson(doc))
    assert pos == len(encode_bson(doc))
    assert got == doc


def test_object_ids_unique_and_stable():
    a, b = ObjectId(), ObjectId()
    assert a != b
    assert len(a.raw) == 12
    assert ObjectId(a.raw) == a
    assert str(a) == a.raw.hex()


def test_op_msg_roundtrip():
    frame = encode_op_msg(7, {"ping": 1, "$db": "x"})
    request_id, response_to, body = decode_op_msg(frame)
    assert request_id == 7 and response_to == 0
    assert body == {"ping": 1, "$db": "x"}


# ------------------------------------------------------------- end-to-end

@pytest.fixture()
def server():
    srv = MiniMongoServer()
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def client(server):
    c = MongoWire(host="127.0.0.1", port=server.port, database="testdb")
    c.connect()
    yield c
    c.close()


def test_insert_find_roundtrip(client):
    oid = client.insert_one("users", {"name": "ada", "age": 36})
    assert isinstance(oid, ObjectId)
    rows = client.find("users", {"name": "ada"})
    assert len(rows) == 1
    assert rows[0]["age"] == 36
    assert rows[0]["_id"] == oid
    assert client.find_one("users", {"name": "nobody"}) is None


def test_filters_update_delete_count(client):
    client.insert_many("n", [{"v": i} for i in range(10)])
    assert client.count_documents("n") == 10
    assert len(client.find("n", {"v": {"$gte": 5}})) == 5
    assert client.update_many("n", {"v": {"$lt": 3}}, {"flag": True}) == 3
    assert client.count_documents("n", {"flag": True}) == 3
    assert client.delete_many("n", {"v": {"$gte": 8}}) == 2
    assert client.count_documents("n") == 8
    client.drop("n")
    assert client.count_documents("n") == 0


def test_find_by_object_id(client):
    oid = client.insert_one("docs", {"body": "x"})
    got = client.find_one("docs", {"_id": oid})
    assert got is not None and got["body"] == "x"


def test_duplicate_id_errors_but_connection_survives(client):
    oid = client.insert_one("dup", {"a": 1})
    with pytest.raises(MongoWireError, match="duplicate"):
        client.command({"insert": "dup",
                        "documents": [{"_id": oid, "a": 2}]})
    assert client.count_documents("dup") == 1  # still usable


def test_health_check_up_down(server, client):
    assert client.health_check()["status"] == "UP"
    server.close()
    assert client.health_check()["status"] == "DOWN"


def test_write_errors_raise(client, monkeypatch):
    """ok:1 + writeErrors (how real servers report failed writes) must
    raise, not silently succeed."""
    real = MiniMongoServer._execute

    def with_write_error(self, body):
        if "insert" in body:
            return {"ok": 1.0, "n": 0,
                    "writeErrors": [{"index": 0, "code": 11000,
                                     "errmsg": "E11000 duplicate key"}]}
        return real(self, body)

    monkeypatch.setattr(MiniMongoServer, "_execute", with_write_error)
    with pytest.raises(MongoWireError, match="duplicate key"):
        client.insert_one("w", {"a": 1})
