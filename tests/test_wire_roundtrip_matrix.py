"""Cross-cutting wire-client matrix: hostile strings round-trip
byte-for-byte through every SQL-ish wire client (their literal
escaping is the attack surface), and every instrumented client records
into its latency histogram.
"""

import random

import pytest

NASTY = [
    "plain",
    "o'brien",
    'double "quoted"',
    "back\\slash",
    "semi; DROP TABLE x; --",
    "newline\nand\rreturn",
    "tab\tand null-ish \\0",
    "unicode ∆ 中文 émoji 🙂",
    "$1 $2 ? ?? '?' {} %s",
    "  leading and trailing  ",
    "quote at end'",
    "'", "''", '"', "`", "",
]


def _random_nasty(rng: random.Random, n: int) -> list[str]:
    alphabet = "ab'\"\\\n\r\t;?$%{}()`∆é 中"
    return ["".join(rng.choice(alphabet) for _ in range(rng.randint(1, 30)))
            for _ in range(n)]


ALL = NASTY + _random_nasty(random.Random(11), 40)


def _roundtrip(db, values):
    db.exec("CREATE TABLE fuzz (i INTEGER, v TEXT)")
    for i, value in enumerate(values):
        db.exec("INSERT INTO fuzz VALUES (?, ?)", i, value)
    rows = db.query("SELECT i, v FROM fuzz ORDER BY i")
    got = [r["v"] for r in rows]
    assert got == values, [
        (want, have) for want, have in zip(values, got) if want != have]


def test_postgres_roundtrip_matrix():
    from gofr_tpu.datasource.postgres_wire import (MiniPostgresServer,
                                                   PostgresWire)
    srv = MiniPostgresServer(auth="trust")
    srv.start()
    try:
        db = PostgresWire(host="127.0.0.1", port=srv.port,
                          user="postgres")
        db.connect()
        db.exec("CREATE TABLE fuzz (i INTEGER, v TEXT)")
        for i, value in enumerate(ALL):
            db.exec("INSERT INTO fuzz VALUES ($1, $2)", i, value)
        got = [r["v"] for r in db.query("SELECT v FROM fuzz ORDER BY i")]
        assert got == ALL
        db.close()
    finally:
        srv.close()


def test_mysql_roundtrip_matrix():
    from gofr_tpu.datasource.mysql_wire import MiniMySQLServer, MySQLWire
    srv = MiniMySQLServer(user="u", password="p")
    srv.start()
    try:
        db = MySQLWire(host="127.0.0.1", port=srv.port, user="u",
                       password="p")
        db.connect()
        _roundtrip(db, ALL)
        db.close()
    finally:
        srv.close()


def test_cassandra_roundtrip_matrix():
    from gofr_tpu.datasource.cassandra_wire import (CassandraWire,
                                                    MiniCassandraServer)
    srv = MiniCassandraServer()
    srv.start()
    try:
        db = CassandraWire(host="127.0.0.1", port=srv.port)
        db.connect()
        _roundtrip(db, ALL)
        db.close()
    finally:
        srv.close()


def test_clickhouse_roundtrip_matrix():
    from gofr_tpu.datasource.clickhouse_wire import (ClickhouseWire,
                                                     MiniClickhouseServer)
    srv = MiniClickhouseServer()
    srv.start()
    try:
        db = ClickhouseWire(endpoint=f"127.0.0.1:{srv.port}")
        _roundtrip(db, ALL)
    finally:
        srv.close()


@pytest.mark.parametrize("which", ["redis", "mongo", "dynamo"])
def test_kv_document_roundtrip_matrix(which):
    if which == "redis":
        from gofr_tpu.datasource.redis_wire import (MiniRedisServer,
                                                    RedisWire)
        srv = MiniRedisServer()
        srv.start()
        client = RedisWire(host="127.0.0.1", port=srv.port)
        client.connect()
        try:
            for i, value in enumerate(ALL):
                client.set(f"k{i}", value)
            for i, value in enumerate(ALL):
                assert client.get(f"k{i}") == value
        finally:
            client.close()
            srv.close()
    elif which == "mongo":
        from gofr_tpu.datasource.mongo_wire import (MiniMongoServer,
                                                    MongoWire)
        srv = MiniMongoServer()
        srv.start()
        client = MongoWire(host="127.0.0.1", port=srv.port)
        client.connect()
        try:
            for i, value in enumerate(ALL):
                client.insert_one("fuzz", {"i": i, "v": value})
            for i, value in enumerate(ALL):
                assert client.find_one("fuzz", {"i": i})["v"] == value
        finally:
            client.close()
            srv.close()
    else:
        from gofr_tpu.datasource.dynamo_wire import (DynamoKV,
                                                     MiniDynamoServer)
        srv = MiniDynamoServer()
        srv.start()
        kv = DynamoKV(endpoint=f"127.0.0.1:{srv.port}", table="t",
                      access_key="test", secret_key="secret")
        try:
            for i, value in enumerate(ALL):
                kv.set(f"k{i}", value)
            for i, value in enumerate(ALL):
                assert kv.get(f"k{i}") == value
        finally:
            srv.close()


def test_every_instrumented_wire_client_records_metrics():
    """One op through each HTTP-ish wire client with a Manager attached
    must populate that client's own histogram."""
    from gofr_tpu.metrics.registry import Manager

    from gofr_tpu.datasource.es_wire import ElasticsearchWire, MiniESServer
    from gofr_tpu.datasource.solr_wire import MiniSolrServer, SolrWire
    from gofr_tpu.datasource.opentsdb_wire import (MiniOpenTSDBServer,
                                                   OpenTSDBWire)
    from gofr_tpu.datasource.arango_wire import ArangoWire, MiniArangoServer

    cases = []
    es_srv = MiniESServer()
    es_srv.start()
    cases.append((ElasticsearchWire(endpoint=f"127.0.0.1:{es_srv.port}"),
                  lambda c: c.index("i", "1", {"a": 1}),
                  "app_elasticsearch_stats", es_srv))
    solr_srv = MiniSolrServer()
    solr_srv.start()
    cases.append((SolrWire(endpoint=f"127.0.0.1:{solr_srv.port}"),
                  lambda c: c.add("c", [{"id": "1"}]),
                  "app_solr_stats", solr_srv))
    tsdb_srv = MiniOpenTSDBServer()
    tsdb_srv.start()
    cases.append((OpenTSDBWire(endpoint=f"127.0.0.1:{tsdb_srv.port}"),
                  lambda c: c.put_data_points(
                      [{"metric": "m", "timestamp": 1, "value": 1.0}]),
                  "app_opentsdb_stats", tsdb_srv))
    arango_srv = MiniArangoServer()
    arango_srv.start()
    cases.append((ArangoWire(endpoint=f"127.0.0.1:{arango_srv.port}"),
                  lambda c: c.create_document("c", {"a": 1}),
                  "app_arangodb_stats", arango_srv))

    try:
        for client, op, metric, _srv in cases:
            manager = Manager()
            client.use_metrics(manager)
            op(client)
            scrape = manager.render_prometheus()
            assert f"{metric}_count" in scrape, metric
    finally:
        for _, _, _, srv in cases:
            srv.close()
