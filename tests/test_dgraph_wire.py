"""Dgraph HTTP wire client against the mini server — the client emits
real DQL; the server parses exactly that subset."""

import pytest

from gofr_tpu.datasource.dgraph_wire import (DgraphWire, DgraphWireError,
                                             MiniDgraphServer,
                                             build_query_dql)


@pytest.fixture(scope="module")
def server():
    srv = MiniDgraphServer()
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def db(server):
    client = DgraphWire(endpoint=f"127.0.0.1:{server.port}")
    client.connect()
    return client


def test_dql_generation():
    assert build_query_dql({}) \
        == "{ q(func: has(dgraph.type)) { uid expand(_all_) } }"
    assert build_query_dql({"name": "ada"}) \
        == '{ q(func: eq(name, "ada")) { uid expand(_all_) } }'
    dql = build_query_dql({"name": 'a"b', "age": 36}, expand="friend")
    assert dql == ('{ q(func: eq(age, 36)) @filter(eq(name, "a\\"b"))'
                   " { uid expand(_all_) friend { uid expand(_all_) } } }")


def test_mutate_and_query(db):
    uids = db.mutate([{"uid": "_:a", "name": "ada", "age": 36},
                      {"uid": "_:g", "name": "grace", "age": 30}])
    assert set(uids) == {"a", "g"}
    rows = db.query({"name": "ada"})
    assert len(rows) == 1 and rows[0]["age"] == 36
    assert rows[0]["uid"]


def test_query_with_filter_and_expand(db):
    db.mutate({"name": "linus", "knows": [{"name": "andrew"}]})
    rows = db.query({"name": "linus"}, expand="knows")
    assert rows and rows[0]["knows"][0]["name"] == "andrew"


def test_numeric_and_bool_predicates(db):
    db.mutate({"name": "flagged", "active": True, "rank": 2.5})
    rows = db.query({"active": True, "rank": 2.5})
    assert any(r["name"] == "flagged" for r in rows)


def test_alter_and_errors(db):
    db.alter("name: string @index(term) .")
    # by-hand DQL outside the supported subset: dgraph-style in-body error
    status, data = db._call(
        "/query", b"{ q(func: regexp(name, /a/)) { uid } }",
        "application/dql")
    assert status == 200 and data.get("errors")
    with pytest.raises(DgraphWireError):
        DgraphWire._check(status, data, "query")


def test_values_containing_and_or_parens(db):
    """Quoted values with \" AND \" or \")\" survive generation AND
    mini-server parsing (review regression)."""
    db.mutate({"name": "rock AND roll (live)", "n": 1})
    rows = db.query({"name": "rock AND roll (live)", "n": 1})
    assert rows and rows[0]["n"] == 1


def test_injection_shaped_predicate_rejected(db):
    with pytest.raises(DgraphWireError, match="invalid predicate"):
        db.query({'name) { uid } } { q2(func: has(x)': "v"})


def test_health(db):
    assert db.health_check()["status"] == "UP"
    assert DgraphWire(endpoint="127.0.0.1:1").health_check()["status"] \
        == "DOWN"
