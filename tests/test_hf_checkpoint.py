"""Real-weight ingestion: safetensors parsing, HF name/layout mapping,
golden logits through a loaded checkpoint, tokenizer.json ingestion
(VERDICT r4 missing #1)."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gofr_tpu.models.hf_checkpoint import (
    load_llama_checkpoint,
    read_safetensors,
    save_llama_checkpoint,
    write_safetensors,
)
from gofr_tpu.models.llama import (
    LlamaConfig,
    llama_init,
    llama_prefill_last,
)
from gofr_tpu.serving.tokenizer import BPETokenizer


# ----------------------------------------------------- container format

def test_safetensors_roundtrip_dtypes(tmp_path):
    import ml_dtypes
    tensors = {
        "a": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b": np.arange(6, dtype=np.int64).reshape(2, 3),
        "c": np.linspace(-1, 1, 8).astype(ml_dtypes.bfloat16),
        "d": np.array([True, False]),
    }
    path = tmp_path / "t.safetensors"
    write_safetensors(path, tensors, metadata={"format": "pt"})
    back = read_safetensors(path)
    assert set(back) == set(tensors)
    for name, want in tensors.items():
        got = back[name]
        assert got.dtype == want.dtype and got.shape == want.shape
        assert np.array_equal(np.asarray(got), want), name


def test_safetensors_header_is_standard(tmp_path):
    """The header must be the documented layout — a foreign reader
    (e.g. HF safetensors) should accept files we write."""
    import struct
    path = tmp_path / "t.safetensors"
    write_safetensors(path, {"x": np.zeros((2, 2), np.float32)})
    raw = path.read_bytes()
    (hlen,) = struct.unpack("<Q", raw[:8])
    header = json.loads(raw[8:8 + hlen])
    assert header["x"] == {"dtype": "F32", "shape": [2, 2],
                           "data_offsets": [0, 16]}
    assert len(raw) == 8 + hlen + 16


# ------------------------------------------------------ llama pytree map

@pytest.fixture(scope="module")
def tiny_checkpoint(tmp_path_factory):
    """A tiny HF-format checkpoint on disk, from known params."""
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.key(7), cfg)
    directory = tmp_path_factory.mktemp("ckpt")
    save_llama_checkpoint(params, cfg, directory)
    return params, cfg, directory


def test_checkpoint_writes_hf_names(tiny_checkpoint):
    _, cfg, directory = tiny_checkpoint
    names = set(read_safetensors(directory / "model.safetensors"))
    assert "model.embed_tokens.weight" in names
    assert "model.norm.weight" in names
    assert "model.layers.0.self_attn.q_proj.weight" in names
    assert f"model.layers.{cfg.n_layers - 1}.mlp.down_proj.weight" in names
    # HF layout is [out_features, in_features]
    tensors = read_safetensors(directory / "model.safetensors")
    assert tensors["model.layers.0.self_attn.k_proj.weight"].shape == \
        (cfg.n_kv_heads * cfg.head_dim, cfg.dim)
    assert tensors["model.layers.0.mlp.gate_proj.weight"].shape == \
        (cfg.ffn_dim, cfg.dim)
    hf_cfg = json.loads((directory / "config.json").read_text())
    assert hf_cfg["hidden_size"] == cfg.dim
    assert hf_cfg["num_key_value_heads"] == cfg.n_kv_heads


def test_load_roundtrips_params_exactly(tiny_checkpoint):
    params, cfg, directory = tiny_checkpoint
    loaded, lcfg = load_llama_checkpoint(directory, dtype=jnp.float32)
    assert lcfg.dim == cfg.dim and lcfg.n_layers == cfg.n_layers
    assert lcfg.tie_embeddings == cfg.tie_embeddings
    flat_want = jax.tree_util.tree_leaves_with_path(params)
    flat_got = dict(jax.tree_util.tree_leaves_with_path(loaded))
    assert len(flat_want) == len(flat_got)
    for path, want in flat_want:
        got = flat_got[path]
        assert got.shape == want.shape, path
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=0, err_msg=str(path))


def test_golden_logits_through_loaded_checkpoint(tiny_checkpoint):
    """Forward pass on loaded weights must equal the source params'
    forward pass bit-for-bit (same dtype, same graph)."""
    params, cfg, directory = tiny_checkpoint
    loaded, _ = load_llama_checkpoint(directory, dtype=jnp.float32)
    tokens = jnp.array([[3, 1, 4, 1, 5, 9, 2, 6]], jnp.int32)
    lengths = jnp.array([8], jnp.int32)
    want, _ = llama_prefill_last(params, tokens, cfg, kv_lengths=lengths)
    got, _ = llama_prefill_last(loaded, tokens, cfg, kv_lengths=lengths)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_load_sharded_index(tiny_checkpoint, tmp_path):
    """model.safetensors.index.json + split shard files load the same."""
    params, cfg, src = tiny_checkpoint
    tensors = dict(read_safetensors(src / "model.safetensors"))
    names = sorted(tensors)
    half = len(names) // 2
    shards = {"model-00001-of-00002.safetensors": names[:half],
              "model-00002-of-00002.safetensors": names[half:]}
    weight_map = {}
    for fname, members in shards.items():
        write_safetensors(tmp_path / fname,
                          {n: tensors[n] for n in members})
        weight_map.update({n: fname for n in members})
    (tmp_path / "model.safetensors.index.json").write_text(
        json.dumps({"weight_map": weight_map}))
    (tmp_path / "config.json").write_text(
        (src / "config.json").read_text())
    loaded, _ = load_llama_checkpoint(tmp_path, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(loaded["embed"]),
                                  np.asarray(params["embed"]))
    np.testing.assert_array_equal(
        np.asarray(loaded["layers"]["w2"]),
        np.asarray(params["layers"]["w2"]))


def test_quantize_on_load(tiny_checkpoint):
    _, cfg, directory = tiny_checkpoint
    loaded, _ = load_llama_checkpoint(directory, quantize="int8")
    from gofr_tpu.ops.quant import is_quantized
    assert is_quantized(loaded["layers"]["wq"])
    assert not is_quantized(loaded["final_norm"])


def test_missing_tensor_is_a_clear_error(tmp_path, tiny_checkpoint):
    _, cfg, src = tiny_checkpoint
    tensors = dict(read_safetensors(src / "model.safetensors"))
    tensors.pop("model.layers.1.mlp.up_proj.weight")
    write_safetensors(tmp_path / "model.safetensors", tensors)
    (tmp_path / "config.json").write_text(
        (src / "config.json").read_text())
    with pytest.raises(KeyError, match="up_proj"):
        load_llama_checkpoint(tmp_path)


def test_loaded_checkpoint_serves(tiny_checkpoint):
    """The whole point: an on-disk checkpoint serves end to end."""
    from gofr_tpu.serving.engine import EngineConfig, SamplingParams
    from gofr_tpu.serving.glue import llama_engine

    params, cfg, directory = tiny_checkpoint
    loaded, lcfg = load_llama_checkpoint(directory, dtype=jnp.float32)
    engine = llama_engine(loaded, lcfg,
                          EngineConfig(max_batch=2, max_seq=64, seed=0))
    engine.start()
    try:
        req = engine.submit_sync(
            [5, 6, 7], SamplingParams(temperature=0.0, max_new_tokens=6))
        assert req.error is None and len(req.generated) == 6
        # greedy tokens from the SOURCE params must match exactly
        ref = llama_engine(params, cfg,
                           EngineConfig(max_batch=2, max_seq=64, seed=0))
        ref.start()
        try:
            want = ref.submit_sync(
                [5, 6, 7],
                SamplingParams(temperature=0.0, max_new_tokens=6))
            assert req.generated == want.generated
        finally:
            ref.stop()
    finally:
        engine.stop()


# -------------------------------------------------------------- whisper

def test_whisper_checkpoint_roundtrip(tmp_path):
    """Save a tiny Whisper as HF format, load it back, transcribe —
    params exact, greedy transcription identical (the ASR flagship's
    real-weight path)."""
    from gofr_tpu.models.hf_checkpoint import (
        load_whisper_checkpoint,
        save_whisper_checkpoint,
    )
    from gofr_tpu.models.whisper import (
        WhisperConfig,
        transcribe_audio,
        whisper_init,
    )

    cfg = WhisperConfig.tiny_test()
    params = whisper_init(jax.random.key(5), cfg)
    save_whisper_checkpoint(params, cfg, tmp_path)

    # the on-disk layout is HF's: conv [out, in, k], linears [out, in]
    tensors = read_safetensors(tmp_path / "model.safetensors")
    assert tensors["model.encoder.conv1.weight"].shape == \
        (cfg.dim, cfg.n_mels, 3)
    assert tensors["model.decoder.layers.0.fc1.weight"].shape == \
        (4 * cfg.dim, cfg.dim)
    assert "model.decoder.layers.0.encoder_attn.q_proj.weight" in tensors
    assert "model.encoder.layers.0.encoder_attn.q_proj.weight" \
        not in tensors  # cross-attention is decoder-only

    loaded, lcfg = load_whisper_checkpoint(tmp_path, dtype=jnp.float32)
    assert lcfg.dim == cfg.dim and lcfg.n_mels == cfg.n_mels
    flat_want = dict(jax.tree_util.tree_leaves_with_path(params))
    flat_got = dict(jax.tree_util.tree_leaves_with_path(loaded))
    assert set(flat_want) == set(flat_got)
    for path, want in flat_want.items():
        np.testing.assert_array_equal(
            np.asarray(flat_got[path]), np.asarray(want),
            err_msg=str(path))

    audio = np.sin(np.linspace(0, 55, 1600)).astype(np.float32)[None]
    want_toks, want_lens = transcribe_audio(
        params, jnp.asarray(audio), cfg, max_tokens=8)
    got_toks, got_lens = transcribe_audio(
        loaded, jnp.asarray(audio), lcfg, max_tokens=8)
    assert np.array_equal(np.asarray(want_toks), np.asarray(got_toks))
    assert np.array_equal(np.asarray(want_lens), np.asarray(got_lens))


def test_whisper_missing_tensor_is_clear(tmp_path):
    from gofr_tpu.models.hf_checkpoint import (
        load_whisper_checkpoint,
        save_whisper_checkpoint,
    )
    from gofr_tpu.models.whisper import WhisperConfig, whisper_init

    cfg = WhisperConfig.tiny_test()
    save_whisper_checkpoint(whisper_init(jax.random.key(1), cfg), cfg,
                            tmp_path)
    tensors = dict(read_safetensors(tmp_path / "model.safetensors"))
    tensors.pop("model.decoder.layers.1.encoder_attn.v_proj.bias")
    write_safetensors(tmp_path / "model.safetensors", tensors)
    with pytest.raises(KeyError, match="encoder_attn.v_proj.bias"):
        load_whisper_checkpoint(tmp_path)


# ------------------------------------------------------- tokenizer.json

def _mini_tokenizer_json(tmp_path):
    """A handcrafted byte-level BPE tokenizer.json: bytes for ascii,
    merges building ' the' the way GPT-2-family files do."""
    table_inv = {}  # byte -> unicode char used in the json
    from gofr_tpu.serving.tokenizer import _byte_level_table
    for ch, b in _byte_level_table().items():
        table_inv[b] = ch

    def enc(s: str) -> str:
        return "".join(table_inv[b] for b in s.encode())

    vocab = {}
    for b in range(256):
        vocab[table_inv[b]] = b
    nxt = 256
    for piece in ("th", "the", enc(" t"), enc(" th"), enc(" the"),
                  "he", "at", "cat"):
        if piece not in vocab:
            vocab[piece] = nxt
            nxt += 1
    merges = ["t h", "th e", f"{enc(' ')} t", f"{enc(' t')} h",
              f"{enc(' th')} e", "h e", "a t", "c at"]
    spec = {
        "model": {"type": "BPE", "vocab": vocab, "merges": merges},
        "added_tokens": [
            {"id": 300, "content": "<|begin_of_text|>"},
            {"id": 301, "content": "<|end_of_text|>"},
        ],
    }
    path = tmp_path / "tokenizer.json"
    path.write_text(json.dumps(spec))
    return path


def test_hf_tokenizer_loads_and_encodes(tmp_path):
    tok = BPETokenizer.from_hf_json(_mini_tokenizer_json(tmp_path))
    assert tok.bos_id == 300 and tok.eos_id == 301
    ids = tok.encode("the cat", bos=False)
    # "the" merges fully; " cat" pretokenizes to " cat" whose bytes
    # merge to " c"?? no — ' ' has no merge with 'c', so ' ' 'cat'
    assert ids[0] == tok.ranks[b"the"]
    assert tok.decode(ids) == "the cat"


def test_hf_tokenizer_merge_priority_not_id_order(tmp_path):
    """'at' (id 262) merges before 'cat' exists; priorities come from
    the merges list, not vocab ids."""
    tok = BPETokenizer.from_hf_json(_mini_tokenizer_json(tmp_path))
    ids = tok.encode("cat", bos=False)
    assert ids == [tok.ranks[b"cat"]]


def test_hf_tokenizer_pretokenizer_keeps_spaces_lossless(tmp_path):
    tok = BPETokenizer.from_hf_json(_mini_tokenizer_json(tmp_path))
    for text in ("the the", " the\n\nthe", "a  b   c", "don't"):
        assert tok.decode(tok.encode(text, bos=False)) == text


def test_hf_tokenizer_roundtrips_unicode(tmp_path):
    tok = BPETokenizer.from_hf_json(_mini_tokenizer_json(tmp_path))
    text = "héllo wörld ☃"
    assert tok.decode(tok.encode(text, bos=False)) == text


def test_hf_tokenizer_native_matches_python(tmp_path):
    """The C++ fast path (merge table + piece boundaries in one
    bounded call) must produce exactly the pure-Python per-piece
    ids."""
    tok = BPETokenizer.from_hf_json(_mini_tokenizer_json(tmp_path))
    if tok._native is None:
        pytest.skip("no C++ toolchain")
    texts = ["the cat", "the the the", " the\n\nthe at cat",
             "a  b   c", "don't", "héllo wörld ☃", "", "   ",
             "that that", "cat" * 50]
    tok_py = BPETokenizer(tok.ranks, tok.specials,
                          merge_ranks=tok.merge_ranks,
                          pretokenize=True)
    tok_py._native = None
    for text in texts:
        assert tok.encode(text, bos=False) \
            == tok_py.encode(text, bos=False), text


def test_native_boundaries_forbid_cross_piece_merges(tmp_path):
    """'that' would merge 'at' across ' t|hat'-style splits if
    boundaries were ignored; the boundary array must pin piece
    edges."""
    tok = BPETokenizer.from_hf_json(_mini_tokenizer_json(tmp_path))
    if tok._native is None:
        pytest.skip("no C++ toolchain")
    # "c at" pretokenizes to ["c", " at"]: the 'c'+'a' pair may not
    # merge into "cat" across the boundary
    ids = tok.encode("c at", bos=False)
    assert tok.ranks[b"cat"] not in ids
    assert tok.decode(ids) == "c at"
