"""Oracle wire client: TNS framing (CONNECT/RESEND/ACCEPT/REFUSE,
markers), O5LOGON-style auth, statements with :n binds, transactions,
ORA-coded errors — against the mini Oracle server."""

from dataclasses import dataclass

import pytest

from gofr_tpu.datasource.oracle_wire import (MiniOracleServer, OracleError,
                                             OracleWire)


@pytest.fixture(scope="module")
def server():
    srv = MiniOracleServer(service_name="FREEPDB1",
                           users={"app": "tiger"})
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def db(server):
    wire = OracleWire(port=server.port, service_name="FREEPDB1",
                      username="app", password="tiger")
    wire.connect()
    yield wire
    wire.close()


def test_connect_ping_dual(db):
    db.ping()
    row = db.query_row("SELECT 1 AS N FROM DUAL")
    assert row["N"] == "1"


def test_ddl_dml_binds_roundtrip(db):
    db.exec("CREATE TABLE IF NOT EXISTS emp (id INTEGER, name TEXT)")
    db.exec("DELETE FROM emp")
    assert db.exec("INSERT INTO emp (id, name) VALUES (:1, :2)",
                   1, "scott") == 1
    db.exec("INSERT INTO emp (id, name) VALUES (:1, :2)", 2, "king")
    rows = db.query("SELECT id, name FROM emp WHERE id > :1 "
                    "ORDER BY id", 0)
    assert [(r["ID"], r["NAME"]) for r in rows] == [("1", "scott"),
                                                    ("2", "king")]


def test_select_into_dataclass(db):
    @dataclass
    class Emp:
        id: str
        name: str

    db.exec("CREATE TABLE IF NOT EXISTS emp2 (id INTEGER, name TEXT)")
    db.exec("INSERT INTO emp2 (id, name) VALUES (:1, :2)", 7, "adams")
    got = db.select(Emp, "SELECT id, name FROM emp2 WHERE id = :1", 7)
    assert got == [Emp(id="7", name="adams")]


def test_transaction_commit_and_rollback(db):
    db.exec("CREATE TABLE IF NOT EXISTS acct (id INTEGER, bal INTEGER)")
    db.exec("DELETE FROM acct")
    with db.begin() as tx:
        tx.exec("INSERT INTO acct (id, bal) VALUES (:1, :2)", 1, 100)
    assert db.query_row("SELECT COUNT(*) AS C FROM acct")["C"] == "1"
    with pytest.raises(RuntimeError):
        with db.begin() as tx:
            tx.exec("INSERT INTO acct (id, bal) VALUES (:1, :2)", 2, 200)
            raise RuntimeError("boom")
    assert db.query_row("SELECT COUNT(*) AS C FROM acct")["C"] == "1"


def test_sql_error_is_ora_coded_after_break_marker(db):
    with pytest.raises(OracleError) as e:
        db.query("SELECT * FROM no_such_table_anywhere")
    assert e.value.code == 900          # ORA-00900 invalid SQL statement
    db.ping()                           # marker/reset left session usable


def test_wrong_password_ora_01017(server):
    bad = OracleWire(port=server.port, username="app", password="WRONG")
    with pytest.raises(OracleError) as e:
        bad.connect()
    assert e.value.code == 1017


def test_unknown_service_refused(server):
    lost = OracleWire(port=server.port, service_name="NOPE",
                      username="app", password="tiger")
    with pytest.raises(OracleError) as e:
        lost.connect()
    assert "12514" in str(e.value)


def test_null_values(db):
    db.exec("CREATE TABLE IF NOT EXISTS nt (id INTEGER, v TEXT)")
    db.exec("INSERT INTO nt (id, v) VALUES (:1, :2)", 1, None)
    row = db.query_row("SELECT v FROM nt WHERE id = :1", 1)
    assert row["V"] is None


def test_health_check(db, server):
    assert db.health_check()["status"] == "UP"
    assert OracleWire(port=1, timeout_s=0.5).health_check()["status"] \
        == "DOWN"


def test_survives_byte_dribble(server):
    """Full TNS stack (CONNECT/RESEND/ACCEPT, auth, DATA frames) over
    1-byte fragments."""
    from .test_wire_fragmentation import DribbleProxy

    proxy = DribbleProxy("127.0.0.1", server.port)
    try:
        wire = OracleWire(port=proxy.port, username="app",
                          password="tiger", timeout_s=60)
        wire.connect()
        wire.exec("CREATE TABLE IF NOT EXISTS frag (x INTEGER)")
        wire.exec("INSERT INTO frag (x) VALUES (:1)", 42)
        assert wire.query_row("SELECT x FROM frag")["X"] == "42"
        wire.close()
    finally:
        proxy.close()


def test_env_driven_selection(server):
    """DB_DIALECT=oracle + DB_HOST dials the TNS wire client through
    the same env path postgres/mysql use (reference sql.go:74)."""
    from gofr_tpu.config.env import DictConfig
    from gofr_tpu.datasource.sql import new_sql

    db = new_sql(DictConfig({
        "DB_DIALECT": "oracle", "DB_HOST": "127.0.0.1",
        "DB_PORT": str(server.port), "DB_NAME": "FREEPDB1",
        "DB_USER": "app", "DB_PASSWORD": "tiger"}))
    assert isinstance(db, OracleWire)
    assert db.query_row("SELECT 1 AS ONE FROM DUAL")["ONE"] == "1"
    db.close()


def test_env_selection_degrades_gracefully():
    """Misconfiguration degrades (None + log), never crashes boot."""
    from gofr_tpu.config.env import DictConfig
    from gofr_tpu.datasource.sql import new_sql

    # oracle without DB_HOST: explicit message, not "unsupported dialect"
    assert new_sql(DictConfig({"DB_DIALECT": "oracle"})) is None
    # malformed port: degrade like the postgres/mysql path
    assert new_sql(DictConfig({"DB_DIALECT": "oracle",
                               "DB_HOST": "127.0.0.1",
                               "DB_PORT": "1521x"})) is None


def test_auto_crud_over_oracle(server):
    """add_rest_handlers works with the Oracle wire client as the
    container's sql slot: :n placeholders, uppercase column mapping."""
    import json as _json

    from gofr_tpu.config.env import DictConfig
    from tests.apputil import AppRunner

    cfg = {"APP_NAME": "crud-ora", "HTTP_PORT": "0", "METRICS_PORT": "0",
           "GOFR_TELEMETRY": "false", "DB_DIALECT": "oracle",
           "DB_HOST": "127.0.0.1", "DB_PORT": str(server.port),
           "DB_NAME": "FREEPDB1", "DB_USER": "app",
           "DB_PASSWORD": "tiger"}

    from dataclasses import dataclass

    @dataclass
    class Book:
        id: int
        title: str

    with AppRunner(config=cfg) as runner:
        runner.app.container.sql.exec(
            "CREATE TABLE IF NOT EXISTS book (id INTEGER, title TEXT)")
        runner.app.container.sql.exec("DELETE FROM book")
        from gofr_tpu.crud import add_rest_handlers
        add_rest_handlers(runner.app, Book)
        status, _, data = runner.request(
            "POST", "/book", body={"id": 1, "title": "TNS"})
        assert status == 201, data
        status, _, data = runner.request("GET", "/book")
        assert status == 200
        rows = _json.loads(data)["data"]
        assert rows == [{"id": "1", "title": "TNS"}] or \
            rows == [{"id": 1, "title": "TNS"}], rows
