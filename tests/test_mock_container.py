"""Mock-container depth: gomock-style expectations with argument
matching and unmet-expectation failure, plus the sqlmock-style SQL
double (reference container/mock_container.go:93,
container/sql_mock.go:12; VERDICT r4 #8)."""

from __future__ import annotations

import pytest

from gofr_tpu.container.mock import (
    CallRecorder,
    ExpectationError,
    MockContainer,
    SQLMock,
)


class TestCallRecorderExpectations:
    def test_expectation_matches_args_and_returns(self):
        rec = CallRecorder("redis")
        rec.expect_call("get").with_args("k1").returns("v1")
        rec.expect_call("get").with_args("k2").returns("v2")
        assert rec.get("k2") == "v2"
        assert rec.get("k1") == "v1"
        rec.verify()

    def test_unexpected_args_fail_immediately(self):
        rec = CallRecorder("redis")
        rec.expect_call("get").with_args("k1").returns("v1")
        with pytest.raises(ExpectationError, match="matching no open"):
            rec.get("other")

    def test_times_enforced_at_verify(self):
        rec = CallRecorder("svc")
        rec.expect_call("ping").times(2)
        rec.ping()
        with pytest.raises(ExpectationError, match="exactly 2x"):
            rec.verify()
        rec.ping()
        rec.verify()

    def test_times_cap_rejects_extra_calls(self):
        rec = CallRecorder("svc")
        rec.expect_call("ping").times(1)
        rec.ping()
        with pytest.raises(ExpectationError):
            rec.ping()

    def test_raises_expectation(self):
        rec = CallRecorder("kv")
        rec.expect_call("set").raises(RuntimeError("down"))
        with pytest.raises(RuntimeError, match="down"):
            rec.set("a", "b")

    def test_loose_mode_still_works_without_declarations(self):
        rec = CallRecorder("legacy")
        rec.expect("keys", ["a"])
        assert rec.keys() == ["a"]
        assert rec.calls_to("keys") == [((), {})]
        rec.verify()  # nothing declared, nothing unmet

    def test_at_least_once_default(self):
        rec = CallRecorder("svc")
        rec.expect_call("flush")
        with pytest.raises(ExpectationError, match="at least once"):
            rec.verify()


class TestSQLMock:
    def test_query_rows_and_ordering(self):
        m = SQLMock()
        m.expect_query(r"SELECT \* FROM users").returns(
            [{"id": 1, "name": "ada"}])
        m.expect_exec(r"DELETE FROM users").with_args(1).affects(1)
        assert m.query("SELECT * FROM users") == [{"id": 1, "name": "ada"}]
        cur = m.exec("DELETE FROM users WHERE id = ?", 1)
        assert cur.rowcount == 1  # cursor-shaped, like the real store
        m.verify()

    def test_affects_zero_drives_not_found_paths(self):
        m = SQLMock()
        m.expect_exec(r"UPDATE users").affects(0)
        cur = m.exec("UPDATE users SET name = ? WHERE id = ?", "x", 99)
        assert getattr(cur, "rowcount", 1) == 0  # crud's 404 check
        m.verify()

    def test_out_of_order_fails(self):
        m = SQLMock()
        m.expect_query(r"SELECT a").returns([])
        m.expect_query(r"SELECT b").returns([])
        with pytest.raises(ExpectationError, match="unexpected"):
            m.query("SELECT b FROM t")

    def test_unordered_mode(self):
        m = SQLMock(ordered=False)
        m.expect_query(r"SELECT a").returns([{"a": 1}])
        m.expect_query(r"SELECT b").returns([{"b": 2}])
        assert m.query("SELECT b FROM t") == [{"b": 2}]
        assert m.query("SELECT a FROM t") == [{"a": 1}]
        m.verify()

    def test_arg_mismatch_fails(self):
        m = SQLMock()
        m.expect_exec(r"UPDATE").with_args("ada", 1).affects(1)
        with pytest.raises(ExpectationError):
            m.exec("UPDATE users SET name = ? WHERE id = ?", "lin", 1)

    def test_unmet_statement_fails_verify(self):
        m = SQLMock()
        m.expect_exec(r"INSERT INTO audit").affects(1)
        with pytest.raises(ExpectationError, match="never issued"):
            m.verify()

    def test_canned_error(self):
        m = SQLMock()
        m.expect_query(r"SELECT").raises(RuntimeError("db on fire"))
        with pytest.raises(RuntimeError, match="on fire"):
            m.query_row("SELECT 1")

    def test_transaction_shares_expectations(self):
        m = SQLMock()
        m.expect_exec(r"INSERT INTO t").affects(1)
        with m.begin() as tx:
            tx.exec("INSERT INTO t (x) VALUES (?)", 5)
        m.verify()

    def test_select_binds_dataclasses(self):
        import dataclasses

        @dataclasses.dataclass
        class User:
            id: int
            name: str

        m = SQLMock()
        m.expect_query(r"SELECT").returns([{"id": 3, "name": "lin"}])
        assert m.select(User, "SELECT * FROM users") == [User(3, "lin")]


class TestMockContainerIntegration:
    def test_mock_sql_installs_and_verifies(self):
        c = MockContainer()
        sql = c.mock_sql()
        sql.expect_query(r"SELECT 1").returns([{"one": 1}])
        assert c.sql.query("SELECT 1") == [{"one": 1}]
        c.verify()

    def test_container_verify_covers_every_mock(self):
        c = MockContainer()
        redis = c.mock("redis")
        redis.expect_call("get").with_args("x").returns("y")
        with pytest.raises(ExpectationError, match="redis"):
            c.verify()

    def test_context_manager_verifies_on_clean_exit(self):
        with pytest.raises(ExpectationError):
            with MockContainer() as c:
                c.mock_sql().expect_exec(r"INSERT").affects(1)
                # exits cleanly without issuing the INSERT -> fails

    def test_context_manager_does_not_mask_test_failure(self):
        with pytest.raises(ValueError, match="real failure"):
            with MockContainer() as c:
                c.mock_sql().expect_exec(r"INSERT").affects(1)
                raise ValueError("real failure")

    def test_handler_against_sqlmock(self):
        """A handler using container.sql runs hermetically against
        declared statements — no sqlite behind it."""
        from gofr_tpu.context import Context

        def handler(ctx: Context):
            row = ctx.sql.query_row(
                "SELECT name FROM users WHERE id = ?", 7)
            return {"hello": row["name"]}

        c = MockContainer()
        sql = c.mock_sql()
        sql.expect_query(r"SELECT name FROM users").with_args(7) \
            .returns([{"name": "ada"}])
        ctx = Context(request=None, container=c)
        assert handler(ctx) == {"hello": "ada"}
        c.verify()
