"""End-to-end app tests over real localhost sockets."""

import json
import time

from gofr_tpu.http import ErrorEntityNotFound
from gofr_tpu.http.response import Stream

from .apputil import AppRunner


def build_routes(app):
    @app.get("/greet")
    def greet(ctx):
        name = ctx.param("name") or "world"
        return f"hello {name}"

    @app.post("/users")
    def create_user(ctx):
        data = ctx.bind()
        return {"created": data["name"]}

    @app.get("/users/{id}")
    def get_user(ctx):
        uid = ctx.path_param("id")
        if uid == "404":
            raise ErrorEntityNotFound("id", uid)
        return {"id": uid}

    @app.delete("/users/{id}")
    def delete_user(ctx):
        return None

    @app.get("/boom")
    def boom(ctx):
        raise RuntimeError("kaboom")

    @app.get("/stream")
    async def stream(ctx):
        async def gen():
            for i in range(3):
                yield f"tok{i} "
        return Stream(gen(), content_type="text/plain")

    @app.get("/async")
    async def async_handler(ctx):
        return {"mode": "async"}


def test_full_request_cycle():
    with AppRunner(build=build_routes) as app:
        # GET with query param
        status, body = app.get_json("/greet?name=tpu")
        assert status == 200 and body == {"data": "hello tpu"}

        # POST -> 201
        status, headers, data = app.request("POST", "/users", {"name": "ada"})
        assert status == 201
        assert json.loads(data) == {"data": {"created": "ada"}}

        # path params
        status, body = app.get_json("/users/42")
        assert status == 200 and body == {"data": {"id": "42"}}

        # typed error -> 404 envelope
        status, body = app.get_json("/users/404")
        assert status == 404 and "No entity found" in body["error"]["message"]

        # DELETE -> 204 no body
        status, _, data = app.request("DELETE", "/users/1")
        assert status == 204 and data == b""

        # panic recovery -> 500 with generic message (no leak)
        status, body = app.get_json("/boom")
        assert status == 500
        assert body["error"]["message"] == "internal server error"

        # async handler
        status, body = app.get_json("/async")
        assert status == 200 and body == {"data": {"mode": "async"}}


def test_default_routes_and_errors():
    with AppRunner(build=build_routes) as app:
        # health + alive
        status, body = app.get_json("/.well-known/health")
        assert status == 200 and body["data"]["status"] == "UP"
        status, body = app.get_json("/.well-known/alive")
        assert status == 200 and body["data"] == {"status": "UP"}

        # favicon
        status, headers, data = app.request("GET", "/favicon.ico")
        assert status == 200 and data[:4] == b"\x89PNG"

        # 404 with registered routes listed
        status, body = app.get_json("/nope")
        assert status == 404
        assert "/greet" in body["error"]["registered_routes"]

        # 405 with Allow header
        status, headers, _ = app.request("PUT", "/greet")
        assert status == 405
        assert "GET" in headers.get("Allow", "")

        # CORS headers present
        status, headers, _ = app.request("OPTIONS", "/greet")
        assert status == 200
        assert headers.get("Access-Control-Allow-Origin") == "*"


def test_streaming_response():
    with AppRunner(build=build_routes) as app:
        status, headers, data = app.request("GET", "/stream")
        assert status == 200
        assert data == b"tok0 tok1 tok2 "
        assert headers.get("Transfer-Encoding") == "chunked"


def test_metrics_server_scrape():
    with AppRunner(build=build_routes) as app:
        app.get_json("/greet")
        status, headers, data = app.request("GET", "/metrics", port=app.metrics_port)
        assert status == 200
        text = data.decode()
        assert "app_http_response_count" in text
        assert 'path="/greet"' in text
        assert "app_info" in text


def test_request_log_has_trace_and_status(capsys=None):
    with AppRunner(build=build_routes) as app:
        # remote traceparent accepted
        status, _, _ = app.request(
            "GET", "/greet",
            headers={"traceparent": "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"})
        assert status == 200


def test_malformed_request_line():
    import socket
    with AppRunner(build=build_routes) as app:
        s = socket.create_connection(("127.0.0.1", app.port), timeout=5)
        s.sendall(b"GARBAGE\r\n\r\n")
        data = s.recv(65536)
        assert b"400" in data.split(b"\r\n")[0]
        s.close()


def test_keep_alive_two_requests_one_connection():
    import socket
    with AppRunner(build=build_routes) as app:
        s = socket.create_connection(("127.0.0.1", app.port), timeout=5)
        req = b"GET /greet HTTP/1.1\r\nHost: x\r\n\r\n"
        s.sendall(req)
        first = s.recv(65536)
        assert b"200 OK" in first
        s.sendall(req)
        second = s.recv(65536)
        assert b"200 OK" in second
        s.close()


def test_request_timeout():
    import time as time_mod

    def build(app):
        @app.get("/slow")
        def slow(ctx):
            time_mod.sleep(2)
            return "done"

    with AppRunner(config={"REQUEST_TIMEOUT": "0.2"}, build=build) as app:
        status, body = app.get_json("/slow")
        assert status == 408
        assert "timed out" in body["error"]["message"]


def test_metrics_label_uses_route_pattern_not_raw_path():
    with AppRunner(build=build_routes) as app:
        app.get_json("/users/1")
        app.get_json("/users/2")
        app.get_json("/definitely/not/registered")
        status, _, data = app.request("GET", "/metrics", port=app.metrics_port)
        text = data.decode()
        assert 'path="/users/{id}"' in text
        assert 'path="/users/1"' not in text
        assert 'path="<unmatched>"' in text


def test_static_mount_does_not_shadow_dynamic_routes(tmp_path_factory):
    site = tmp_path_factory.mktemp("public")
    (site / "page.html").write_text("<p>static</p>")

    def build(app):
        app.add_static_files("/", str(site))

        @app.get("/api/users")
        def users(ctx):
            return ["ada"]

    with AppRunner(build=build) as app:
        status, body = app.get_json("/api/users")
        assert status == 200 and body == {"data": ["ada"]}
        status, _, data = app.request("GET", "/page.html")
        assert status == 200 and b"static" in data


def test_stream_failure_truncates_without_terminator():
    import socket

    def build(app):
        @app.get("/failing-stream")
        async def failing(ctx):
            async def gen():
                yield "tok0 "
                yield "tok1 "
                raise RuntimeError("device lost")
            return Stream(gen(), content_type="text/plain")

    with AppRunner(build=build) as app:
        s = socket.create_connection(("127.0.0.1", app.port), timeout=5)
        s.sendall(b"GET /failing-stream HTTP/1.1\r\nHost: x\r\n\r\n")
        received = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            received = received + chunk
        s.close()
        assert b"tok0" in received
        assert not received.endswith(b"0\r\n\r\n")  # no clean terminator


def test_sync_handler_logs_carry_trace_id():
    from gofr_tpu.logging import MockLogger

    def build(app):
        mock_log = MockLogger()
        app.logger = mock_log
        app.container.logger = mock_log

        @app.get("/traced")
        def traced(ctx):
            ctx.logger.info("from inside sync handler")
            return "ok"

    with AppRunner(build=build) as app:
        tp = "00-" + "ef" * 16 + "-" + "12" * 8 + "-01"
        app.request("GET", "/traced", headers={"traceparent": tp})
        lines = [l for l in app.app.logger.lines
                 if l.get("message") == "from inside sync handler"]
        assert lines and lines[0]["trace_id"] == "ef" * 16


def test_malformed_timeout_config_still_boots():
    with AppRunner(config={"REQUEST_TIMEOUT": "30s"}, build=build_routes) as app:
        status, _ = app.get_json("/greet")
        assert status == 200


def test_on_start_hook_partial_and_failure():
    import functools
    seen = []

    def setup(tag, container):
        seen.append((tag, container is not None))

    def build(app):
        app.on_start(functools.partial(setup, "db"))

    with AppRunner(build=build) as app:
        assert seen == [("db", True)]


def test_head_request_served_by_get_route():
    with AppRunner(build=build_routes) as app:
        status, headers, data = app.request("HEAD", "/greet")
        assert status == 200
        assert data == b""
        assert int(headers.get("Content-Length", -1)) > 0


def test_graceful_stop_via_signal_handler_path():
    """_signal_stop must complete shutdown (not cancel itself)."""
    import asyncio

    with AppRunner(build=build_routes) as app:
        loop = app._loop

        def trigger():
            app.app._signal_stop()

        loop.call_soon_threadsafe(trigger)
        deadline = time.time() + 10
        while time.time() < deadline and not app.app._stop_event.is_set():
            time.sleep(0.05)
        assert app.app._stop_event.is_set()


def test_static_mount_favicon_wins_over_builtin(tmp_path_factory):
    site = tmp_path_factory.mktemp("fav")
    (site / "favicon.ico").write_bytes(b"REAL-ICON-BYTES")

    def build(app):
        app.add_static_files("/", str(site))

    with AppRunner(build=build) as app:
        status, _, data = app.request("GET", "/favicon.ico")
        assert status == 200 and data == b"REAL-ICON-BYTES"

    # and without a mount, the builtin placeholder serves
    with AppRunner(build=build_routes) as app:
        status, _, data = app.request("GET", "/favicon.ico")
        assert status == 200 and data[:4] == b"\x89PNG"


def test_occupied_port_fails_with_named_guidance():
    """Port-occupancy guard (reference gofr.go:119-130): boot on a
    taken port names the port and the env key, not a raw bind error."""
    import asyncio
    import socket

    import pytest

    from gofr_tpu.app import App
    from gofr_tpu.config import DictConfig

    blocker = socket.socket()
    blocker.bind(("0.0.0.0", 0))  # wildcard: clashes on every platform
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        app = App(config=DictConfig({"HTTP_PORT": str(port),
                                     "METRICS_PORT": "0",
                                     "APP_NAME": "clash"}))
        with pytest.raises(RuntimeError, match=f"{port}.*HTTP_PORT"):
            asyncio.run(app.start())
    finally:
        blocker.close()
