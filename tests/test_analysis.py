"""gofrlint unit tests: per-rule fixtures (flagged + clean twins),
suppression parsing, the CLI contract, and the meta-test pinning the
static metric extraction to the dynamic registry-coverage scan on the
live repo."""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from gofr_tpu.analysis import run_analysis
from gofr_tpu.analysis.rules import metric_hygiene

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "analysis_fixtures"


def lint(*names, rules=None):
    findings, _ = run_analysis([FIXTURES / n for n in names],
                               rules=rules, root=REPO)
    return findings


def violations(findings, rule=None):
    out = [f for f in findings if not f.suppressed]
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


# ------------------------------------------------------------ hot path
class TestHotPathPurity:
    def test_bad_fixture_flags_every_seeded_violation(self):
        got = violations(lint("hot_path_bad.py"), "hot-path-purity")
        lines = {f.line for f in got}
        # the nine direct violations in dispatch() ...
        assert {14, 15, 16, 17, 18, 19, 20, 21, 22} <= lines
        # ... and the closure-reached one in the undecorated helper
        assert 32 in lines

    def test_closure_finding_names_the_root_chain(self):
        got = violations(lint("hot_path_bad.py"), "hot-path-purity")
        via = [f for f in got if f.line == 32]
        assert via and "Engine.step" in via[0].message

    def test_clean_twin_is_silent(self):
        assert violations(lint("hot_path_good.py"), "hot-path-purity") == []

    def test_boundary_stops_traversal_but_cold_code_is_ignored(self):
        # _retire (boundary) and cold_path (unreachable) both contain
        # would-be violations; neither may fire
        got = lint("hot_path_good.py")
        assert violations(got, "hot-path-purity") == []


# ---------------------------------------------------- scheduler contract
class TestSchedulerHotPathContract:
    """The serving/scheduler.py contract, lint-enforced: admission/
    retire bookkeeping (clocks, metrics, logging, burn-rate reads) is
    legal ONLY behind @hot_path_boundary entry points — inline in a
    hot root, or in an undecorated helper the closure reaches, it
    must flag."""

    def test_inline_scheduler_bookkeeping_flags(self):
        got = violations(lint("sched_bad.py"), "hot-path-purity")
        lines = {f.line for f in got}
        # the three direct violations in admit_pass() ...
        assert {15, 16, 17} <= lines
        # ... and the closure-reached fair-share helper
        assert {23, 24} <= lines

    def test_boundary_entry_points_are_clean(self):
        assert violations(lint("sched_good.py"), "hot-path-purity") == []

    def test_live_scheduler_entry_points_declare_boundaries(self):
        # the real module, not a fixture: the entry points that touch
        # admission/retire paths carry the boundary annotation with a
        # non-empty reason, so the contract survives refactors
        from gofr_tpu.serving.scheduler import Scheduler
        for entry in (Scheduler.put, Scheduler.note_retire):
            reason = getattr(entry, "__gofr_hot_path_boundary__", "")
            assert isinstance(reason, str) and reason.strip(), entry

    def test_live_repo_hot_closure_excludes_scheduler(self):
        # with the scheduler ON by default, the engine's hot closure
        # must not grow into scheduler.py (the zero-hot-path invariant)
        from gofr_tpu.analysis.callgraph import CallGraph
        from gofr_tpu.analysis.core import load_project
        project = load_project([REPO / "gofr_tpu" / "serving"], root=REPO)
        closure = CallGraph(project).hot_closure()
        offenders = [str(k) for k in closure
                     if k.module.endswith("scheduler.py")]
        assert not offenders, offenders


# ---------------------------------------------------- fault-site contract
class TestFaultInjectionSites:
    """The serving/faults.py contract, lint-enforced: chaos compiled
    into the hot loop is legal ONLY as a guarded call into a
    @hot_path_boundary trip — inlined clocks/metrics/logging flag."""

    def test_inline_chaos_flags(self):
        got = violations(lint("faults_bad.py"), "hot-path-purity")
        lines = {f.line for f in got}
        assert {14, 15, 16} <= lines          # inline trigger + telemetry
        assert 22 in lines                    # closure-reached helper

    def test_boundary_guarded_sites_are_clean(self):
        assert violations(lint("faults_good.py"), "hot-path-purity") == []

    def test_live_trip_declares_a_boundary(self):
        # the real module, not a fixture: FaultPlan.trip must keep its
        # boundary (with a reason) or every compiled-in site would
        # drag sleeps and counters into the engine's hot closure
        from gofr_tpu.serving.faults import FaultPlan
        reason = getattr(FaultPlan.trip, "__gofr_hot_path_boundary__", "")
        assert isinstance(reason, str) and reason.strip()


# ----------------------------------------------- event-ledger contract
class TestEventLedgerContract:
    """The serving/events.py contract, lint-enforced: flight-recorder
    emission is legal ONLY through the @hot_path_boundary
    ``EventLedger.emit`` — inline ring appends, wall-clock stamps or
    counters in a hot root (or a closure-reached helper) must flag."""

    def test_inline_event_recording_flags(self):
        got = violations(lint("events_bad.py"), "hot-path-purity")
        lines = {f.line for f in got}
        assert {14, 15, 16} <= lines          # inline stamp + telemetry
        assert 21 in lines                    # closure-reached helper

    def test_boundary_emission_is_clean(self):
        assert violations(lint("events_good.py"), "hot-path-purity") == []

    def test_live_emit_declares_a_boundary(self):
        # the real module, not a fixture: EventLedger.emit must keep
        # its boundary (with a reason) or every emission site would
        # drag clocks, locks and counters into the hot closure
        from gofr_tpu.serving.events import EventLedger
        reason = getattr(EventLedger.emit,
                         "__gofr_hot_path_boundary__", "")
        assert isinstance(reason, str) and reason.strip()

    def test_live_repo_hot_closure_excludes_events(self):
        # with the ledger wired on by default, the engine's hot
        # closure must not grow into events.py: emission is only
        # reachable through already-declared boundary sites
        from gofr_tpu.analysis.callgraph import CallGraph
        from gofr_tpu.analysis.core import load_project
        project = load_project([REPO / "gofr_tpu" / "serving"], root=REPO)
        closure = CallGraph(project).hot_closure()
        offenders = [str(k) for k in closure
                     if k.module.endswith("events.py")]
        assert not offenders, offenders


# -------------------------------------------------- cost-model contract
class TestCostModelContract:
    """The serving/costmodel.py contract, lint-enforced: pass-cost
    accounting is legal ONLY through @hot_path_boundary folds
    (``CostModel.observe`` / ``Engine._note_pass_cost``) — inline EWMA
    updates, wall-clock reads or drift counters in a hot root (or a
    closure-reached helper) must flag."""

    def test_inline_cost_accounting_flags(self):
        got = violations(lint("costmodel_bad.py"), "hot-path-purity")
        lines = {f.line for f in got}
        assert {14, 15, 16} <= lines          # inline price + telemetry
        assert 21 in lines                    # closure-reached helper

    def test_boundary_fold_is_clean(self):
        assert violations(lint("costmodel_good.py"),
                          "hot-path-purity") == []

    def test_live_folds_declare_boundaries(self):
        # the real modules, not fixtures: both the model's fold and
        # the engine's per-pass feed must keep their boundaries (with
        # reasons) or every collect site would drag the EWMA math,
        # drift counters and WARNs into the hot closure
        from gofr_tpu.serving.costmodel import CostModel
        from gofr_tpu.serving.engine import Engine
        for entry in (CostModel.observe, Engine._note_pass_cost):
            reason = getattr(entry, "__gofr_hot_path_boundary__", "")
            assert isinstance(reason, str) and reason.strip(), entry

    def test_live_repo_hot_closure_excludes_costmodel(self):
        # with the cost model ON by default, the engine's hot closure
        # must not grow into costmodel.py: observation is only
        # reachable through already-declared boundary sites
        from gofr_tpu.analysis.callgraph import CallGraph
        from gofr_tpu.analysis.core import load_project
        project = load_project([REPO / "gofr_tpu" / "serving"], root=REPO)
        closure = CallGraph(project).hot_closure()
        offenders = [str(k) for k in closure
                     if k.module.endswith("costmodel.py")]
        assert not offenders, offenders


# -------------------------------------------------- integrity contract
class TestIntegrityContract:
    """The serving/integrity.py contract, lint-enforced: output
    fingerprinting is legal ONLY through @hot_path_boundary folds
    (``IntegrityPlane.fold`` / ``Engine._note_integrity``) — inline
    digest downloads, mismatch counters or WARNs in a hot root (or a
    closure-reached helper) must flag."""

    def test_inline_fingerprinting_flags(self):
        got = violations(lint("integrity_bad.py"), "hot-path-purity")
        lines = {f.line for f in got}
        assert {14, 18, 19} <= lines          # download + telemetry
        assert 24 in lines                    # closure-reached helper

    def test_boundary_fold_is_clean(self):
        assert violations(lint("integrity_good.py"),
                          "hot-path-purity") == []

    def test_live_folds_declare_boundaries(self):
        # the real modules, not fixtures: both the plane's fold and
        # the engine's per-request feed must keep their boundaries
        # (with reasons) or every retire site would drag the digest,
        # probe pricing and mismatch telemetry into the hot closure
        from gofr_tpu.serving.engine import Engine
        from gofr_tpu.serving.integrity import IntegrityPlane
        for entry in (IntegrityPlane.fold, Engine._note_integrity):
            reason = getattr(entry, "__gofr_hot_path_boundary__", "")
            assert isinstance(reason, str) and reason.strip(), entry

    def test_live_repo_hot_closure_excludes_integrity(self):
        # with the plane ON by default, the engine's hot closure must
        # not grow into integrity.py: folding is only reachable
        # through already-declared boundary sites
        from gofr_tpu.analysis.callgraph import CallGraph
        from gofr_tpu.analysis.core import load_project
        project = load_project([REPO / "gofr_tpu" / "serving"], root=REPO)
        closure = CallGraph(project).hot_closure()
        offenders = [str(k) for k in closure
                     if k.module.endswith("integrity.py")]
        assert not offenders, offenders


# ------------------------------------------------ speculation contract
class TestSpeculationContract:
    """The drafting/controller contract, lint-enforced: n-gram index
    maintenance, controller pricing and the verify collect's device
    reads are legal ONLY behind the engine's @hot_path_boundary entry
    points (``_draft_proposals``, ``_spec_pass``) — inline in a hot
    root, or in an undecorated helper the closure reaches, they must
    flag."""

    def test_inline_drafting_flags(self):
        got = violations(lint("spec_bad.py"), "hot-path-purity")
        lines = {f.line for f in got}
        assert {14, 15, 16} <= lines    # clock + counter + log inline
        assert {23, 24} <= lines        # closure-reached draft helper

    def test_boundary_drafting_is_clean(self):
        assert violations(lint("spec_good.py"), "hot-path-purity") == []

    def test_live_spec_entry_points_declare_boundaries(self):
        # the real module, not a fixture: drafting and the verify
        # collect must keep their boundaries (with reasons) or the
        # n-gram index, controller EWMAs and accept/path downloads
        # would drag host syncs into the engine's hot closure
        from gofr_tpu.serving.engine import Engine
        for entry in (Engine._draft_proposals, Engine._spec_pass):
            reason = getattr(entry, "__gofr_hot_path_boundary__", "")
            assert isinstance(reason, str) and reason.strip(), entry

    def test_live_repo_hot_closure_excludes_spec(self):
        # the drafting/controller module stays out of the hot closure:
        # it is only reachable through the declared boundary sites
        from gofr_tpu.analysis.callgraph import CallGraph
        from gofr_tpu.analysis.core import load_project
        project = load_project([REPO / "gofr_tpu" / "serving"],
                               root=REPO)
        closure = CallGraph(project).hot_closure()
        offenders = [str(k) for k in closure
                     if k.module.endswith("spec.py")]
        assert not offenders, offenders


# ----------------------------------------------------- router contract
class TestRouterContract:
    """The serving/router.py contract, lint-enforced: the async proxy
    path must never block the event loop (every stream the leader
    proxies rides it), and prefix-digest assembly is legal ONLY behind
    a declared @hot_path_boundary — inline in a hot root or in a
    closure-reached helper it must flag."""

    def test_blocking_proxy_path_flags(self):
        got = violations(lint("router_bad.py"), "blocking-in-async")
        # sleep, sync HTTP probe, setpoint-file read — all inline in
        # the async proxy
        assert {f.line for f in got} == {16, 17, 18}

    def test_inline_digest_assembly_flags(self):
        got = violations(lint("router_bad.py"), "hot-path-purity")
        lines = {f.line for f in got}
        assert {29, 30} <= lines        # clock + gauge in the hot root
        assert 37 in lines              # closure-reached digest helper

    def test_clean_twin_is_silent_on_both_rules(self):
        got = lint("router_good.py")
        assert violations(got, "blocking-in-async") == []
        assert violations(got, "hot-path-purity") == []

    def test_live_digest_refresh_declares_a_boundary(self):
        # the real module, not a fixture: the engine's digest refresh
        # runs off the gauge pass inside the hot loop, so losing its
        # boundary would drag hashing into the hot closure
        from gofr_tpu.serving.engine import Engine
        reason = getattr(Engine._refresh_prefix_digest,
                         "__gofr_hot_path_boundary__", "")
        assert isinstance(reason, str) and reason.strip()

    def test_live_proxy_path_is_async_clean(self):
        # the real router module must pass the blocking-in-async rule
        # it exists to model
        findings, _ = run_analysis(
            [REPO / "gofr_tpu" / "serving" / "router.py"], root=REPO)
        assert [f for f in findings
                if not f.suppressed
                and f.rule == "blocking-in-async"] == []


# ---------------------------------------------------------------- locks
class TestLockDiscipline:
    def test_bad_fixture(self):
        got = violations(lint("locks_bad.py"), "lock-discipline")
        assert {f.line for f in got} == {17, 20, 23}
        assert any("_items" in f.message for f in got)
        assert any("_count" in f.message for f in got)

    def test_clean_twin(self):
        assert violations(lint("locks_good.py"), "lock-discipline") == []


# ------------------------------------------------------------- election
class TestElectionContract:
    """Leader-HA determinism contract (docs/operations.md "Losing the
    leader"): lease state (epoch/active) mutates only under the lock,
    and election/fencing decisions are pure functions of counts and
    epochs — no wall clock, no RNG — so every failover drill
    reproduces under bisect."""

    #: the election/fencing decision functions in the live module
    ELECTION_FNS = ("ensure_active", "_fence", "_choose_candidate",
                    "_adopt_epoch")

    def test_bad_fixture_lease_races_are_flagged(self):
        got = violations(lint("election_bad.py"), "lock-discipline")
        assert {f.line for f in got} == {20, 21}
        assert any("active" in f.message for f in got)
        assert any("epoch" in f.message for f in got)

    def test_bad_fixture_election_reads_clock_and_rng(self):
        # what the contract bans, demonstrated: the bad twin's choose()
        # references time and random
        names = self._referenced_modules(
            FIXTURES / "election_bad.py", ("choose",))
        assert {"time", "random"} <= names

    def test_clean_twin_is_silent_and_pure(self):
        assert violations(lint("election_good.py"),
                          "lock-discipline") == []
        names = self._referenced_modules(
            FIXTURES / "election_good.py", ("choose",))
        assert not names & {"time", "random"}

    def test_live_election_functions_are_clock_and_rng_free(self):
        src = REPO / "gofr_tpu" / "serving" / "control_plane.py"
        names = self._referenced_modules(src, self.ELECTION_FNS)
        assert not names & {"time", "random"}, (
            f"election/fencing logic reads a clock or RNG: {names}")

    def test_live_module_lints_clean(self):
        src = REPO / "gofr_tpu" / "serving" / "control_plane.py"
        findings, _ = run_analysis([src], root=REPO)
        assert violations(findings, "lock-discipline") == []

    @staticmethod
    def _referenced_modules(path, fn_names):
        """Module names used as ``mod.attr(...)`` inside the named
        functions of ``path`` (any nesting depth)."""
        import ast
        tree = ast.parse(path.read_text())
        out: set = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in fn_names:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Attribute) \
                            and isinstance(sub.value, ast.Name):
                        out.add(sub.value.id)
        return out


# ---------------------------------------------------------------- async
class TestBlockingInAsync:
    def test_bad_fixture(self):
        got = violations(lint("async_bad.py"), "blocking-in-async")
        assert {f.line for f in got} == {9, 10, 11, 12, 13}

    def test_clean_twin(self):
        assert violations(lint("async_good.py"), "blocking-in-async") == []


# -------------------------------------------------------------- metrics
class TestMetricHygiene:
    def test_bad_fixture(self):
        got = violations(lint("metrics_bad.py"), "metric-hygiene")
        msgs = {f.line: f.message for f in got}
        assert "app_orphan_total" in msgs[6]      # orphan registration
        assert "app_never_registered" in msgs[13]
        assert "not a string literal" in msgs[14]
        assert len(got) == 3

    def test_clean_twin_including_loop_unroll(self):
        assert violations(lint("metrics_good.py"), "metric-hygiene") == []

    def test_cross_file_resolution(self):
        # registration in one file, write in the other: both clean when
        # linted together
        got = violations(lint("metrics_good.py", "metrics_bad.py"),
                         "metric-hygiene")
        # bad file's findings survive; good file contributes none
        assert all(f.path.endswith("metrics_bad.py") for f in got)


# ------------------------------------------------------------ recompile
class TestRecompileHazard:
    def test_bad_fixture(self):
        got = violations(lint("recompile_bad.py"), "recompile-hazard")
        assert {f.line for f in got} == {17, 18, 19, 29}

    def test_clean_twin(self):
        assert violations(lint("recompile_good.py"), "recompile-hazard") == []


# ------------------------------------------------------------- kv quant
class TestKvQuantBoundary:
    """Quantize-on-write contract (ops/paged_kv.py): the jitted
    scatters own the pool representation — hot closures pass raw rows
    and never cast or host-read the pool."""

    def test_bad_fixture_flags_every_seeded_violation(self):
        got = violations(lint("kvquant_bad.py"), "kv-quant-boundary")
        assert {f.line for f in got} == {11, 14, 22, 24, 29, 30, 31}
        assert any("quantizes/casts on write" in f.message for f in got)
        assert any("host-side readback" in f.message for f in got)

    def test_clean_twin_is_silent(self):
        assert violations(lint("kvquant_good.py"),
                          "kv-quant-boundary") == []

    def test_live_serving_and_models_respect_the_boundary(self):
        """The contract test the rule exists for: the LIVE engine/glue/
        model hot closures quantize inside the jitted scatters — no
        caller-side .astype at a scatter boundary, no host-side pool
        dequant crept back in."""
        findings, _ = run_analysis(
            [REPO / "gofr_tpu" / "serving", REPO / "gofr_tpu" / "models",
             REPO / "gofr_tpu" / "ops"], root=REPO)
        assert [f for f in findings if not f.suppressed
                and f.rule == "kv-quant-boundary"] == []


# ---------------------------------------------------------- suppression
class TestSuppressions:
    def test_missing_reason_is_an_error(self):
        got = lint("suppression_bad.py")
        bad = violations(got, "bad-suppression")
        assert any("missing its mandatory" in f.message and f.line == 9
                   for f in bad)

    def test_reasonless_allow_does_not_suppress(self):
        got = lint("suppression_bad.py")
        assert any(f.line == 9 for f in
                   violations(got, "hot-path-purity"))

    def test_stale_allow_is_an_error(self):
        got = lint("suppression_bad.py")
        assert any(f.line == 12 and "suppresses nothing" in f.message
                   for f in violations(got, "bad-suppression"))

    def test_typoed_rule_neither_suppresses_nor_passes(self):
        got = lint("suppression_bad.py")
        assert any(f.line == 17 for f in violations(got, "hot-path-purity"))
        assert any(f.line == 17 for f in violations(got, "bad-suppression"))

    def test_valid_allow_suppresses_and_keeps_reason(self):
        got = lint("suppression_good.py")
        assert violations(got) == []
        sup = [f for f in got if f.suppressed]
        assert sup and all(f.allow_reason for f in sup)

    def test_one_allow_may_cover_multiple_rules(self):
        got = lint("suppression_good.py")
        rules = {f.rule for f in got if f.suppressed and f.line == 14}
        assert "hot-path-purity" in rules


# ------------------------------------------------------------------ CLI
class TestCLI:
    def run_cli(self, *args):
        return subprocess.run(
            [sys.executable, str(REPO / "scripts" / "lint.py"), *args],
            capture_output=True, text=True, cwd=REPO)

    def test_bad_fixture_exits_nonzero_with_file_line(self):
        r = self.run_cli(str(FIXTURES / "async_bad.py"))
        assert r.returncode == 1
        assert re.search(r"async_bad\.py:9:\d+: \[blocking-in-async\]",
                         r.stdout)

    def test_json_format_is_machine_readable(self):
        r = self.run_cli("--format=json", str(FIXTURES / "async_bad.py"))
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert doc["counts"]["blocking-in-async"] == 5
        assert all({"rule", "path", "line", "col", "message"}
                   <= set(v) for v in doc["violations"])

    def test_clean_fixture_exits_zero(self):
        r = self.run_cli(str(FIXTURES / "async_good.py"))
        assert r.returncode == 0, r.stdout + r.stderr

    def test_self_test_passes(self):
        r = self.run_cli("--self-test")
        assert r.returncode == 0, r.stdout + r.stderr

    def test_unknown_rule_is_usage_error(self):
        r = self.run_cli("--rule", "no-such-rule", ".")
        assert r.returncode == 2

    def test_repo_lints_clean(self):
        # the acceptance gate itself: the live tree must stay clean
        r = self.run_cli("gofr_tpu/", "scripts/", "bench.py")
        assert r.returncode == 0, r.stdout + r.stderr


# ------------------------------------------------------------- meta-test
class TestStaticDynamicAgreement:
    """gofrlint's static metric extraction and the dynamic
    registry-coverage test (test_observability.py) must agree on the
    live repo — if they drift, one of them has a blind spot."""

    def test_static_extraction_covers_the_dynamic_scan(self):
        from gofr_tpu.analysis.core import load_project
        from .test_observability import _WRITE_RE, SERVING_DIR

        regex_names = set()
        for path in SERVING_DIR.glob("*.py"):
            regex_names.update(_WRITE_RE.findall(path.read_text()))

        project = load_project([SERVING_DIR], root=REPO)
        static_names = metric_hygiene.written_names(project)

        # everything the regex sees, the AST walk must see ...
        assert regex_names <= static_names, (
            f"static extraction missed: {sorted(regex_names - static_names)}")
        # ... and anything extra the AST walk finds (multi-line calls,
        # loop-unrolled names the regex can't follow) must still be a
        # registered metric, or the dynamic test has a blind spot
        extra = static_names - regex_names
        whole_tree = load_project([REPO / "gofr_tpu"], root=REPO)
        registered = metric_hygiene.registered_names(whole_tree)
        assert extra <= registered, (
            f"statically-found writes the dynamic test cannot see AND "
            f"nobody registers: {sorted(extra - registered)}")

    def test_every_serving_write_is_statically_registered(self):
        """The static twin of the dynamic coverage test's main assert."""
        from gofr_tpu.analysis.core import load_project
        serving = load_project([REPO / "gofr_tpu" / "serving"], root=REPO)
        whole_tree = load_project([REPO / "gofr_tpu"], root=REPO)
        written = metric_hygiene.written_names(serving)
        registered = metric_hygiene.registered_names(whole_tree)
        assert written, "no writes found — the extraction broke"
        missing = sorted(n for n in written if n not in registered)
        assert not missing, f"written in serving/ but never registered: {missing}"
