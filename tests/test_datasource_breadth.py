"""Breadth datasource families: document, columnar, graph, time-series.

Mirrors the reference's per-module driver tests (datasource/mongo,
datasource/cassandra, ... *_test.go): each store's native surface is
exercised against the embedded engine, plus container registration and
health aggregation.
"""

import pytest

from gofr_tpu.container.container import Container
from gofr_tpu.container.mock import new_mock_container
from gofr_tpu.datasource.columnar import (BatchNotInitialised, Cassandra,
                                          Clickhouse, Oracle, ScyllaDB)
from gofr_tpu.datasource.document import (Couchbase, DocumentNotFound,
                                          Elasticsearch, Mongo, Solr)
from gofr_tpu.datasource.graph import (ArangoDB, Dgraph, GraphError,
                                       NodeNotFound, SurrealDB)
from gofr_tpu.datasource.timeseries import (InfluxDB, OpenTSDB,
                                            TimeseriesError)


# ---------------------------------------------------------------- document
class TestMongo:
    def test_crud_roundtrip(self):
        m = Mongo()
        m.connect()
        m.insert_one("users", {"name": "ada", "age": 36})
        m.insert_one("users", {"name": "grace", "age": 45})
        assert m.count_documents("users") == 2
        hits = m.find("users", {"age": {"$gt": 40}})
        assert [h["name"] for h in hits] == ["grace"]
        assert m.find_one("users", {"name": "ada"})["age"] == 36
        assert m.update_many("users", {"name": "ada"},
                             {"$set": {"age": 37}}) == 1
        assert m.find_one("users", {"name": "ada"})["age"] == 37
        assert m.delete_many("users", {"age": {"$lt": 40}}) == 1
        assert m.count_documents("users") == 1

    def test_filter_operators(self):
        m = Mongo()
        m.insert_many("n", [{"v": i} for i in range(5)])
        assert len(m.find("n", {"v": {"$gte": 2, "$lte": 3}})) == 2
        assert len(m.find("n", {"v": {"$ne": 0}})) == 4
        assert len(m.find("n", {"v": {"$in": [1, 4, 9]}})) == 2

    def test_health_and_metrics(self):
        c = new_mock_container()
        m = c.add_mongo(Mongo())
        m.insert_one("t", {"x": 1})
        assert c.health()["checks"]["mongo"]["status"] == "UP"
        assert c.metrics.get_histogram_count("app_mongo_stats", type="insert") == 1


class TestElasticsearch:
    def test_index_search_ranking(self):
        es = Elasticsearch()
        es.index("docs", 1, {"title": "tpu systolic matmul"})
        es.index("docs", 2, {"title": "hbm bandwidth tpu"})
        es.index("docs", 3, {"title": "unrelated prose"})
        out = es.search("docs", {"match": {"title": "tpu matmul"}})
        assert out["hits"]["total"]["value"] == 2
        assert out["hits"]["hits"][0]["_id"] == 1  # 2-token overlap first

    def test_term_get_delete_bulk(self):
        es = Elasticsearch()
        assert es.bulk("i", [(n, {"k": n % 2}) for n in range(4)]) == 4
        assert es.search("i", {"term": {"k": 0}})["hits"]["total"]["value"] == 2
        assert es.get("i", 3)["k"] == 1
        es.delete("i", 3)
        with pytest.raises(DocumentNotFound):
            es.get("i", 3)


class TestSolrCouchbase:
    def test_solr_add_search(self):
        s = Solr()
        s.add("books", [{"id": "b1", "title": "jax on tpu"},
                        {"id": "b2", "title": "go services"}])
        assert s.search("books", "title:jax on tpu")["response"]["numFound"] == 1
        assert s.search("books", "*:*")["response"]["numFound"] == 2
        s.delete("books", "b1")
        assert s.search("books", "*:*")["response"]["numFound"] == 1

    def test_couchbase_bucket_ops(self):
        cb = Couchbase()
        cb.upsert("main", "u:1", {"name": "ada"})
        cb.insert("main", "u:2", {"name": "grace"})
        assert cb.get("main", "u:1")["name"] == "ada"
        assert len(cb.query("main")) == 2
        cb.remove("main", "u:1")
        with pytest.raises(DocumentNotFound):
            cb.remove("main", "u:1")


# ---------------------------------------------------------------- columnar
@pytest.mark.parametrize("cls", [Cassandra, ScyllaDB, Clickhouse, Oracle])
def test_cql_family_statements(cls):
    store = cls()
    store.connect()
    store.exec("CREATE TABLE t (id INTEGER PRIMARY KEY, name TEXT)")
    store.exec("INSERT INTO t (id, name) VALUES (?, ?)", 1, "ada")
    store.exec("INSERT INTO t (id, name) VALUES (?, ?)", 2, "grace")
    rows = store.query("SELECT * FROM t ORDER BY id")
    assert [r["name"] for r in rows] == ["ada", "grace"]
    assert store.health_check()["status"] == "UP"
    store.close()
    assert store.health_check()["status"] == "DOWN"


def test_cassandra_batch_atomicity():
    c = Cassandra()
    c.connect()
    c.exec("CREATE TABLE t (id INTEGER PRIMARY KEY)")
    c.new_batch("b1")
    c.batch_query("b1", "INSERT INTO t (id) VALUES (?)", 1)
    c.batch_query("b1", "INSERT INTO t (id) VALUES (?)", 2)
    c.execute_batch("b1")
    assert len(c.query("SELECT * FROM t")) == 2
    # failing batch rolls back entirely
    c.new_batch("b2")
    c.batch_query("b2", "INSERT INTO t (id) VALUES (?)", 3)
    c.batch_query("b2", "INSERT INTO t (id) VALUES (?)", 1)  # dup PK
    with pytest.raises(Exception):
        c.execute_batch("b2")
    assert len(c.query("SELECT * FROM t")) == 2
    with pytest.raises(BatchNotInitialised):
        c.batch_query("nope", "SELECT 1")


def test_cql_strips_cql_only_clauses():
    c = ScyllaDB()
    c.connect()
    c.exec("CREATE TABLE t (id INTEGER)")
    c.exec("INSERT INTO t (id) VALUES (?) USING TTL 60", 1)
    assert c.query("SELECT * FROM t ALLOW FILTERING") == [{"id": 1}]


def test_oracle_tx_commit_rollback():
    o = Oracle()
    o.connect()
    o.exec("CREATE TABLE m (v INTEGER)")
    tx = o.begin()
    tx.exec("INSERT INTO m (v) VALUES (?)", 1)
    tx.rollback()
    assert o.select("SELECT * FROM m") == []
    tx = o.begin()
    tx.exec("INSERT INTO m (v) VALUES (?)", 2)
    tx.commit()
    assert o.select("SELECT * FROM m") == [{"v": 2}]


# ------------------------------------------------------------------- graph
class TestDgraph:
    def test_mutate_query_expand(self):
        d = Dgraph()
        d.connect()
        uids = d.mutate({"uid": "_:ada", "name": "ada",
                         "follows": [{"name": "grace"}, {"name": "alan"}]})
        assert "ada" in uids
        hits = d.query({"name": "ada"}, expand="follows")
        assert len(hits) == 1
        assert {f["name"] for f in hits[0]["follows"]} == {"grace", "alan"}
        d.alter("name: string @index(term) .")
        assert d.schema

    def test_edge_to_missing_node(self):
        d = Dgraph()
        with pytest.raises(NodeNotFound):
            d.engine.add_edge("knows", "0xdead", "0xbeef")


class TestArango:
    def test_documents_and_traversal(self):
        a = ArangoDB()
        a.connect()
        i1 = a.create_document("people", {"name": "ada"})
        i2 = a.create_document("people", {"name": "grace"})
        i3 = a.create_document("people", {"name": "alan"})
        a.create_edge_document("knows", i1, i2)
        a.create_edge_document("knows", i2, i3)
        assert a.get_document("people", i1)["name"] == "ada"
        a.update_document("people", i1, {"name": "ada lovelace"})
        two_hops = a.traversal(i1, "knows", depth=2)
        assert [d["name"] for d in two_hops] == ["grace", "alan"]
        a.delete_document("people", i3)
        assert len(a.query("people")) == 2


class TestSurreal:
    def test_record_id_crud(self):
        s = SurrealDB()
        s.connect()
        created = s.create("user:ada", {"age": 36})
        assert created["id"] == "user:ada"
        s.create("user", {"age": 45})  # engine-assigned id
        assert len(s.select("user")) == 2
        assert s.select("user:ada")[0]["age"] == 36
        assert s.update("user:ada", {"age": 37})["age"] == 37
        with pytest.raises(GraphError):
            s.update("user", {})
        s.delete("user:ada")
        assert len(s.query("user")) == 1


# ------------------------------------------------------------- time-series
class TestOpenTSDB:
    def test_put_query_aggregate(self):
        t = OpenTSDB()
        t.connect()
        t.put_data_points([
            {"metric": "sys.cpu", "timestamp": 100, "value": 10,
             "tags": {"host": "a"}},
            {"metric": "sys.cpu", "timestamp": 200, "value": 30,
             "tags": {"host": "a"}},
            {"metric": "sys.cpu", "timestamp": 300, "value": 50,
             "tags": {"host": "b"}},
        ])
        out = t.query("sys.cpu", "avg", start=100, end=250)
        assert out["value"] == 20
        assert t.query("sys.cpu", "max")["value"] == 50
        only_a = t.query("sys.cpu", "sum", tags={"host": "a"})
        assert only_a["value"] == 40
        with pytest.raises(TimeseriesError):
            t.engine.aggregate("sys.cpu", "median")

    def test_annotations(self):
        t = OpenTSDB()
        t.put_annotation({"startTime": 150, "description": "deploy"})
        assert t.query_annotations(100, 200)[0]["description"] == "deploy"
        assert t.query_annotations(300, 400) == []


class TestInfluxDB:
    def test_buckets_and_points(self):
        i = InfluxDB()
        i.connect()
        i.create_bucket("metrics")
        i.write_point("metrics", "temp", 1.0, {"c": 21.0}, {"room": "lab"})
        i.write_point("metrics", "temp", 2.0, {"c": 23.0}, {"room": "lab"})
        pts = i.query("metrics", "temp", "c")
        assert pts == [(1.0, 21.0), (2.0, 23.0)]
        assert i.aggregate("metrics", "temp", "c", "avg") == 22.0
        assert i.health_check()["details"]["buckets"] == 1
        i.delete_bucket("metrics")
        assert i.list_buckets() == []


# ----------------------------------------------- container + context wiring
def test_container_holds_every_breadth_slot():
    c = Container()
    stores = {
        "mongo": Mongo(), "elasticsearch": Elasticsearch(), "solr": Solr(),
        "couchbase": Couchbase(), "cassandra": Cassandra(),
        "scylladb": ScyllaDB(), "clickhouse": Clickhouse(),
        "oracle": Oracle(), "dgraph": Dgraph(), "arangodb": ArangoDB(),
        "surrealdb": SurrealDB(), "opentsdb": OpenTSDB(),
        "influxdb": InfluxDB(),
    }
    for name, store in stores.items():
        added = getattr(c, f"add_{name}")(store)
        assert added is store
        assert store.logger is c.logger  # provider wiring ran
    checks = c.health()["checks"]
    for name in stores:
        assert checks[name]["status"] == "UP", name


def test_context_resolves_breadth_slots():
    from gofr_tpu.context import Context
    c = new_mock_container()
    c.add_dgraph(Dgraph())
    ctx = Context(request=None, container=c)
    assert ctx.dgraph is c.dgraph
    with pytest.raises(AttributeError):
        ctx.no_such_store


def test_mock_container_can_mock_breadth_slot():
    c = new_mock_container()
    rec = c.mock("cassandra")
    rec.expect("query", [{"id": 7}])
    assert c.cassandra.query("SELECT ...") == [{"id": 7}]
    assert rec.calls_to("query")
