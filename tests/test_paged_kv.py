"""Paged KV primitives + the engine's paged mode."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.paged_kv import (gather_view, scatter_chunk,
                                   scatter_decode, scatter_prefill)

L, NP, PG, H, D = 2, 6, 4, 2, 3   # layers, pages, page size, heads, head dim


def _pool(fill=0.0):
    return jnp.full((L, H, NP, PG, D), fill, jnp.float32)


def test_scatter_prefill_then_gather_roundtrip():
    pool = _pool()
    # one row owning pages [2, 0], prompt length 6 (spans both pages)
    tables = jnp.asarray([[2, 0, NP]], jnp.int32)           # Mp = 3
    slab = jnp.arange(L * 1 * 8 * H * D, dtype=jnp.float32).reshape(
        L, 1, 8, H, D)                                       # S = 8 > 6: padded
    pool = scatter_prefill(pool, tables, slab)
    view = gather_view(pool, tables)
    np.testing.assert_array_equal(np.asarray(view[:, :, :8]),
                                  np.asarray(slab))


def test_scatter_prefill_drops_unallocated_padding():
    pool = _pool(-1.0)
    tables = jnp.asarray([[1, NP, NP]], jnp.int32)          # only page 1
    slab = jnp.ones((L, 1, 8, H, D), jnp.float32)           # rows 4..7 OOB
    pool = scatter_prefill(pool, tables, slab)
    got = np.asarray(pool)
    assert (got[:, :, 1] == 1.0).all()                         # page 1 written
    mask = np.ones(NP, bool)
    mask[1] = False
    assert (got[:, :, mask] == -1.0).all()                     # others untouched


def test_scatter_prefill_dummy_row_dropped():
    pool = _pool(-1.0)
    tables = jnp.asarray([[NP, NP, NP]], jnp.int32)         # dummy row
    slab = jnp.ones((L, 1, 4, H, D), jnp.float32)
    pool = scatter_prefill(pool, tables, slab)
    assert (np.asarray(pool) == -1.0).all()


def test_scatter_chunk_writes_only_chunk_rows():
    pool = _pool(-1.0)
    tables = jnp.asarray([[3, 1, NP]], jnp.int32)
    # chunk of 3 rows starting at logical position 3: spans the page
    # boundary (page 3 offset 3, then page 1 offsets 0-1)
    slab = jnp.zeros((L, 1, 8, H, D), jnp.float32)
    slab = slab.at[:, 0, 0].set(7.0).at[:, 0, 1].set(8.0) \
        .at[:, 0, 2].set(9.0)
    pool = scatter_chunk(pool, tables, slab, jnp.asarray([3]),
                         jnp.asarray([3]))
    got = np.asarray(pool)
    assert (got[:, :, 3, 3] == 7.0).all()
    assert (got[:, :, 1, 0] == 8.0).all()
    assert (got[:, :, 1, 1] == 9.0).all()
    # rows 3..7 of the slab are past chunk_len: dropped, not written
    written = np.zeros_like(got, bool)
    written[:, :, 3, 3] = written[:, :, 1, 0] = written[:, :, 1, 1] = True
    assert (got[~written] == -1.0).all()


def test_scatter_chunk_matches_prefill_on_prompt_rows():
    """With offset 0 and chunk_len = prompt length, scatter_chunk and
    scatter_prefill agree on every prompt row; only the padding rows
    within the last allocated page differ (chunk drops them)."""
    tables = jnp.asarray([[2, 0, NP]], jnp.int32)
    slab = jnp.arange(L * 1 * 8 * H * D, dtype=jnp.float32).reshape(
        L, 1, 8, H, D)
    a = scatter_prefill(_pool(), tables, slab)
    b = scatter_chunk(_pool(), tables, slab, jnp.asarray([0]),
                      jnp.asarray([6]))
    view_a = gather_view(a, tables)
    view_b = gather_view(b, tables)
    np.testing.assert_array_equal(np.asarray(view_a[:, :, :6]),
                                  np.asarray(view_b[:, :, :6]))
    # rows 6,7 were pad rows: prefill wrote them, chunk dropped them
    assert (np.asarray(view_b[:, :, 6:8]) == 0.0).all()
    assert not (np.asarray(view_a[:, :, 6:8]) == 0.0).all()


def test_scatter_chunk_dummy_row_dropped():
    pool = _pool(-1.0)
    tables = jnp.asarray([[NP, NP, NP]], jnp.int32)
    slab = jnp.ones((L, 1, 4, H, D), jnp.float32)
    pool = scatter_chunk(pool, tables, slab, jnp.asarray([0]),
                         jnp.asarray([4]))
    assert (np.asarray(pool) == -1.0).all()


def test_scatter_chunk_past_table_end_drops():
    pool = _pool(-1.0)
    tables = jnp.asarray([[0, 1, 2]], jnp.int32)   # 12 logical rows
    slab = jnp.zeros((L, 1, 4, H, D), jnp.float32)
    pool = scatter_chunk(pool, tables, slab, jnp.asarray([11]),
                         jnp.asarray([4]))
    got = np.asarray(pool)
    # position 11 lands (page 2, offset 3); 12..14 drop
    assert (got[:, :, 2, 3] == 0.0).all()
    untouched = np.full_like(got, -1.0)
    untouched[:, :, 2, 3] = 0.0
    np.testing.assert_array_equal(got, untouched)


def test_scatter_decode_writes_k_rows():
    pool = _pool()
    tables = jnp.asarray([[3, 1, NP]], jnp.int32)
    view = jnp.zeros((L, 1, 12, H, D), jnp.float32)
    # pass appended K=2 rows at logical positions 3, 4 (page boundary!)
    view = view.at[:, 0, 3].set(7.0)
    view = view.at[:, 0, 4].set(8.0)
    pool = scatter_decode(pool, tables, view, jnp.asarray([3]), 2)
    got = np.asarray(pool)
    assert (got[:, :, 3, 3] == 7.0).all()   # logical 3 -> page 3, offset 3
    assert (got[:, :, 1, 0] == 8.0).all()   # logical 4 -> page 1, offset 0
    assert got.sum() == (7.0 + 8.0) * L * H * D


def test_scatter_decode_past_view_end_drops():
    pool = _pool(-1.0)
    tables = jnp.asarray([[0, 1, 2]], jnp.int32)
    view = jnp.zeros((L, 1, 12, H, D), jnp.float32)
    pool = scatter_decode(pool, tables, view, jnp.asarray([11]), 2)
    got = np.asarray(pool)
    # position 11 lands (page 2, offset 3); position 12 is dropped
    assert (got[:, :, 2, 3] == 0.0).all()
    untouched = np.full_like(got, -1.0)
    untouched[:, :, 2, 3] = 0.0
    np.testing.assert_array_equal(got, untouched)


# ---------------------------------------------------------------- engine

from gofr_tpu.serving.engine import EngineConfig, SamplingParams  # noqa: E402
from gofr_tpu.serving.glue import demo_llama_engine  # noqa: E402


def _drain(reqs, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.01)
    return reqs


def test_paged_engine_matches_slot_engine():
    cfg = dict(max_batch=4, max_seq=128, seed=17)
    slot = demo_llama_engine(EngineConfig(**cfg))
    slot.start()
    want = [slot.submit([3 + i, 1, 4], SamplingParams(
        temperature=0.0, max_new_tokens=10)) for i in range(4)]
    _drain(want)
    slot.stop()

    paged = demo_llama_engine(EngineConfig(kv_layout="paged", page_size=16,
                                           **cfg))
    paged.start()
    got = [paged.submit([3 + i, 1, 4], SamplingParams(
        temperature=0.0, max_new_tokens=10)) for i in range(4)]
    _drain(got)
    paged.stop()

    assert [r.generated for r in got] == [r.generated for r in want]
    assert all(r.error is None for r in got)


def test_paged_overcommit_beyond_contiguous_capacity():
    """Total logical capacity (max_batch * max_seq = 4*128 rows) does
    not fit the pool (12 pages * 16 = 192 rows), but short requests do:
    the engine must serve more concurrent requests than the contiguous
    layout could hold in the same memory."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=128, seed=2,
        kv_layout="paged", page_size=16, kv_pages=12))
    eng.start()
    reqs = [eng.submit([1 + i, 2, 3], SamplingParams(
        temperature=0.0, max_new_tokens=8)) for i in range(8)]
    _drain(reqs)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    assert all(len(r.generated) == 8 for r in reqs)


def test_paged_preemption_recomputes_and_completes():
    """Pool too small for all admitted requests to run to their full
    length: the engine preempts (freeing pages, recomputing later) and
    every request still finishes with exactly its token budget."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=128, seed=8,
        kv_layout="paged", page_size=16, kv_pages=8))  # 128 rows total
    eng.start()
    reqs = [eng.submit(list(range(1, 30)), SamplingParams(
        temperature=0.0, max_new_tokens=24)) for _ in range(4)]
    _drain(reqs)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    assert all(len(r.generated) == 24 for r in reqs)


def test_paged_greedy_unaffected_by_preemption():
    """Preemption-by-recompute must not change greedy outputs."""
    roomy = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, seed=4, kv_layout="paged", page_size=16))
    roomy.start()
    want = roomy.submit_sync(list(range(1, 20)), SamplingParams(
        temperature=0.0, max_new_tokens=16)).generated
    roomy.stop()

    tight = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, seed=4,
        kv_layout="paged", page_size=16, kv_pages=5))
    tight.start()
    got = [tight.submit(list(range(1, 20)), SamplingParams(
        temperature=0.0, max_new_tokens=16)) for _ in range(2)]
    _drain(got)
    tight.stop()
    assert all(r.error is None for r in got)
    assert all(r.generated == want for r in got)


def test_recovered_pool_keeps_head_major_layout():
    """_recover_lost_cache must rebuild the pool in the SAME head-major
    [L, Hkv, Np, pg, hd] layout the init path allocates (a recovery
    that reverts to the dense-cache axis order silently corrupts every
    subsequent scatter/gather)."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, seed=5, kv_layout="paged", page_size=16))
    shape_before = eng.k_cache.shape
    eng.k_cache.delete()
    eng.v_cache.delete()
    eng._recover_lost_cache(RuntimeError("induced"))
    assert eng.k_cache.shape == shape_before
    assert eng.v_cache.shape == shape_before
    # and the engine still serves after recovery
    eng.start()
    reqs = [eng.submit([3, 1, 4], SamplingParams(
        temperature=0.0, max_new_tokens=6)) for _ in range(2)]
    _drain(reqs)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    assert all(len(r.generated) == 6 for r in reqs)


def test_paged_view_decode_windows_match():
    """Windowed paged-view decode (gathers only the table columns
    covering the window) must match the unwindowed paged engine
    greedily across a window boundary."""
    def run(**extra):
        eng = demo_llama_engine(EngineConfig(
            max_batch=2, max_seq=128, seed=21, kv_layout="paged",
            page_size=16, **extra))
        eng.start()
        reqs = [eng.submit(list(range(2, 12)), SamplingParams(
            temperature=0.0, max_new_tokens=40)) for _ in range(2)]
        _drain(reqs)
        eng.stop()
        assert all(r.error is None for r in reqs), [r.error for r in reqs]
        assert all(len(r.generated) == 40 for r in reqs)
        return [r.generated for r in reqs]

    want = run()
    got = run(decode_windows=(32, 64))
    assert got == want
