"""Paged KV primitives + the engine's paged mode."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.paged_kv import (gather_view, scatter_chunk,
                                   scatter_decode, scatter_prefill)

L, NP, PG, H, D = 2, 6, 4, 2, 3   # layers, pages, page size, heads, head dim


def _pool(fill=0.0):
    return jnp.full((L, H, NP, PG, D), fill, jnp.float32)


def test_scatter_prefill_then_gather_roundtrip():
    pool = _pool()
    # one row owning pages [2, 0], prompt length 6 (spans both pages)
    tables = jnp.asarray([[2, 0, NP]], jnp.int32)           # Mp = 3
    slab = jnp.arange(L * 1 * 8 * H * D, dtype=jnp.float32).reshape(
        L, 1, 8, H, D)                                       # S = 8 > 6: padded
    pool = scatter_prefill(pool, tables, slab)
    view = gather_view(pool, tables)
    np.testing.assert_array_equal(np.asarray(view[:, :, :8]),
                                  np.asarray(slab))


def test_scatter_prefill_drops_unallocated_padding():
    pool = _pool(-1.0)
    tables = jnp.asarray([[1, NP, NP]], jnp.int32)          # only page 1
    slab = jnp.ones((L, 1, 8, H, D), jnp.float32)           # rows 4..7 OOB
    pool = scatter_prefill(pool, tables, slab)
    got = np.asarray(pool)
    assert (got[:, :, 1] == 1.0).all()                         # page 1 written
    mask = np.ones(NP, bool)
    mask[1] = False
    assert (got[:, :, mask] == -1.0).all()                     # others untouched


def test_scatter_prefill_dummy_row_dropped():
    pool = _pool(-1.0)
    tables = jnp.asarray([[NP, NP, NP]], jnp.int32)         # dummy row
    slab = jnp.ones((L, 1, 4, H, D), jnp.float32)
    pool = scatter_prefill(pool, tables, slab)
    assert (np.asarray(pool) == -1.0).all()


def test_scatter_chunk_writes_only_chunk_rows():
    pool = _pool(-1.0)
    tables = jnp.asarray([[3, 1, NP]], jnp.int32)
    # chunk of 3 rows starting at logical position 3: spans the page
    # boundary (page 3 offset 3, then page 1 offsets 0-1)
    slab = jnp.zeros((L, 1, 8, H, D), jnp.float32)
    slab = slab.at[:, 0, 0].set(7.0).at[:, 0, 1].set(8.0) \
        .at[:, 0, 2].set(9.0)
    pool = scatter_chunk(pool, tables, slab, jnp.asarray([3]),
                         jnp.asarray([3]))
    got = np.asarray(pool)
    assert (got[:, :, 3, 3] == 7.0).all()
    assert (got[:, :, 1, 0] == 8.0).all()
    assert (got[:, :, 1, 1] == 9.0).all()
    # rows 3..7 of the slab are past chunk_len: dropped, not written
    written = np.zeros_like(got, bool)
    written[:, :, 3, 3] = written[:, :, 1, 0] = written[:, :, 1, 1] = True
    assert (got[~written] == -1.0).all()


def test_scatter_chunk_matches_prefill_on_prompt_rows():
    """With offset 0 and chunk_len = prompt length, scatter_chunk and
    scatter_prefill agree on every prompt row; only the padding rows
    within the last allocated page differ (chunk drops them)."""
    tables = jnp.asarray([[2, 0, NP]], jnp.int32)
    slab = jnp.arange(L * 1 * 8 * H * D, dtype=jnp.float32).reshape(
        L, 1, 8, H, D)
    a = scatter_prefill(_pool(), tables, slab)
    b = scatter_chunk(_pool(), tables, slab, jnp.asarray([0]),
                      jnp.asarray([6]))
    view_a = gather_view(a, tables)
    view_b = gather_view(b, tables)
    np.testing.assert_array_equal(np.asarray(view_a[:, :, :6]),
                                  np.asarray(view_b[:, :, :6]))
    # rows 6,7 were pad rows: prefill wrote them, chunk dropped them
    assert (np.asarray(view_b[:, :, 6:8]) == 0.0).all()
    assert not (np.asarray(view_a[:, :, 6:8]) == 0.0).all()


def test_scatter_chunk_dummy_row_dropped():
    pool = _pool(-1.0)
    tables = jnp.asarray([[NP, NP, NP]], jnp.int32)
    slab = jnp.ones((L, 1, 4, H, D), jnp.float32)
    pool = scatter_chunk(pool, tables, slab, jnp.asarray([0]),
                         jnp.asarray([4]))
    assert (np.asarray(pool) == -1.0).all()


def test_scatter_chunk_past_table_end_drops():
    pool = _pool(-1.0)
    tables = jnp.asarray([[0, 1, 2]], jnp.int32)   # 12 logical rows
    slab = jnp.zeros((L, 1, 4, H, D), jnp.float32)
    pool = scatter_chunk(pool, tables, slab, jnp.asarray([11]),
                         jnp.asarray([4]))
    got = np.asarray(pool)
    # position 11 lands (page 2, offset 3); 12..14 drop
    assert (got[:, :, 2, 3] == 0.0).all()
    untouched = np.full_like(got, -1.0)
    untouched[:, :, 2, 3] = 0.0
    np.testing.assert_array_equal(got, untouched)


def test_scatter_decode_writes_k_rows():
    pool = _pool()
    tables = jnp.asarray([[3, 1, NP]], jnp.int32)
    view = jnp.zeros((L, 1, 12, H, D), jnp.float32)
    # pass appended K=2 rows at logical positions 3, 4 (page boundary!)
    view = view.at[:, 0, 3].set(7.0)
    view = view.at[:, 0, 4].set(8.0)
    pool = scatter_decode(pool, tables, view, jnp.asarray([3]), 2)
    got = np.asarray(pool)
    assert (got[:, :, 3, 3] == 7.0).all()   # logical 3 -> page 3, offset 3
    assert (got[:, :, 1, 0] == 8.0).all()   # logical 4 -> page 1, offset 0
    assert got.sum() == (7.0 + 8.0) * L * H * D


def test_scatter_decode_past_view_end_drops():
    pool = _pool(-1.0)
    tables = jnp.asarray([[0, 1, 2]], jnp.int32)
    view = jnp.zeros((L, 1, 12, H, D), jnp.float32)
    pool = scatter_decode(pool, tables, view, jnp.asarray([11]), 2)
    got = np.asarray(pool)
    # position 11 lands (page 2, offset 3); position 12 is dropped
    assert (got[:, :, 2, 3] == 0.0).all()
    untouched = np.full_like(got, -1.0)
    untouched[:, :, 2, 3] = 0.0
    np.testing.assert_array_equal(got, untouched)


# ---------------------------------------------------- quantized pools

from gofr_tpu.ops.paged_kv import (dequantize_rows, is_quantized_pool,  # noqa: E402
                                   pool_row_bytes, quantize_pool,
                                   quantize_rows)


def _qpool():
    return quantize_pool(_pool())


def test_quantized_roundtrip_within_quant_bound():
    """scatter (quantize-on-write) then gather (dequantize) reproduces
    the written rows within the symmetric-int8 bound: per element the
    error is at most scale/2 = amax/254."""
    pool = _qpool()
    tables = jnp.asarray([[2, 0, NP]], jnp.int32)
    slab = jax.random.normal(jax.random.key(0), (L, 1, 8, H, D),
                             jnp.float32)
    pool = scatter_prefill(pool, tables, slab)
    assert is_quantized_pool(pool)
    view = gather_view(pool, tables, dtype=jnp.float32)
    err = np.abs(np.asarray(view[:, :, :8]) - np.asarray(slab))
    bound = np.max(np.abs(np.asarray(slab)), axis=-1,
                   keepdims=True) / 254 + 1e-6
    assert (err <= bound).all()


def test_quantized_decode_append_preserves_earlier_rows():
    """Per-row scales are load-bearing: appending one decode row to a
    partially filled page must leave every earlier row's codes AND
    scale bit-identical (a page-wide amax would re-quantize them)."""
    pool = _qpool()
    tables = jnp.asarray([[3, NP, NP]], jnp.int32)
    slab = jax.random.normal(jax.random.key(1), (L, 1, 4, H, D),
                             jnp.float32) * 5.0
    pool = scatter_prefill(pool, tables, slab[:, :, :3])  # rows 0..2
    before_q = np.asarray(pool["q"][:, :, 3, :3]).copy()
    before_s = np.asarray(pool["s"][:, :, 3, :3]).copy()
    # append logical row 3 (offset 3 of page 3) with a much larger amax
    view = jnp.zeros((L, 1, 12, H, D), jnp.float32)
    view = view.at[:, 0, 3].set(100.0)
    pool = scatter_decode(pool, tables, view, jnp.asarray([3]), 1)
    np.testing.assert_array_equal(np.asarray(pool["q"][:, :, 3, :3]),
                                  before_q)
    np.testing.assert_array_equal(np.asarray(pool["s"][:, :, 3, :3]),
                                  before_s)
    got = dequantize_rows(pool["q"][:, :, 3, 3], pool["s"][:, :, 3, 3])
    np.testing.assert_allclose(np.asarray(got), 100.0, rtol=1e-2)


def test_quantized_view_roundtrip_is_idempotent():
    """The view fallback round-trips untouched rows (gather ->
    dequantize -> requantize -> scatter). Requantizing dequantized
    values must reproduce the exact codes and scale: each written row
    has an element at |q| = 127, so the amax — and everything derived
    from it — is reconstructed bit-for-bit. Zero rows hit the scale
    floor and stay exactly zero."""
    rows = jnp.concatenate([
        jax.random.normal(jax.random.key(2), (6, D), jnp.float32),
        jnp.zeros((2, D), jnp.float32)])
    q1, s1 = quantize_rows(rows)
    q2, s2 = quantize_rows(dequantize_rows(q1, s1))
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))


def test_quantized_scatter_drops_like_plain():
    """OOB table entries drop on BOTH leaves — dummy rows must not
    corrupt codes or scales."""
    pool = _qpool()
    q0 = np.asarray(pool["q"]).copy()
    s0 = np.asarray(pool["s"]).copy()
    tables = jnp.asarray([[NP, NP, NP]], jnp.int32)
    slab = jnp.ones((L, 1, 4, H, D), jnp.float32)
    pool = scatter_chunk(pool, tables, slab, jnp.asarray([0]),
                         jnp.asarray([4]))
    np.testing.assert_array_equal(np.asarray(pool["q"]), q0)
    np.testing.assert_array_equal(np.asarray(pool["s"]), s0)


def test_quantized_row_bytes_accounting():
    """int8 rows cost hd + 4 bytes per (layer, head) vs 4*hd for the
    f32 source pool — the engine's byte-budget sizing leans on this."""
    plain, quant = _pool(), _qpool()
    assert pool_row_bytes(plain) == L * H * D * 4
    assert pool_row_bytes(quant) == L * H * (D + 4)


# ---------------------------------------------------------------- engine

from gofr_tpu.serving.engine import EngineConfig, SamplingParams  # noqa: E402
from gofr_tpu.serving.glue import demo_llama_engine  # noqa: E402


def _drain(reqs, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.01)
    return reqs


def test_paged_engine_matches_slot_engine():
    cfg = dict(max_batch=4, max_seq=128, seed=17)
    slot = demo_llama_engine(EngineConfig(**cfg))
    slot.start()
    want = [slot.submit([3 + i, 1, 4], SamplingParams(
        temperature=0.0, max_new_tokens=10)) for i in range(4)]
    _drain(want)
    slot.stop()

    paged = demo_llama_engine(EngineConfig(kv_layout="paged", page_size=16,
                                           **cfg))
    paged.start()
    got = [paged.submit([3 + i, 1, 4], SamplingParams(
        temperature=0.0, max_new_tokens=10)) for i in range(4)]
    _drain(got)
    paged.stop()

    assert [r.generated for r in got] == [r.generated for r in want]
    assert all(r.error is None for r in got)


def test_paged_overcommit_beyond_contiguous_capacity():
    """Total logical capacity (max_batch * max_seq = 4*128 rows) does
    not fit the pool (12 pages * 16 = 192 rows), but short requests do:
    the engine must serve more concurrent requests than the contiguous
    layout could hold in the same memory."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=128, seed=2,
        kv_layout="paged", page_size=16, kv_pages=12))
    eng.start()
    reqs = [eng.submit([1 + i, 2, 3], SamplingParams(
        temperature=0.0, max_new_tokens=8)) for i in range(8)]
    _drain(reqs)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    assert all(len(r.generated) == 8 for r in reqs)


def test_paged_preemption_recomputes_and_completes():
    """Pool too small for all admitted requests to run to their full
    length: the engine preempts (freeing pages, recomputing later) and
    every request still finishes with exactly its token budget."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=128, seed=8,
        kv_layout="paged", page_size=16, kv_pages=8))  # 128 rows total
    eng.start()
    reqs = [eng.submit(list(range(1, 30)), SamplingParams(
        temperature=0.0, max_new_tokens=24)) for _ in range(4)]
    _drain(reqs)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    assert all(len(r.generated) == 24 for r in reqs)


def test_paged_greedy_unaffected_by_preemption():
    """Preemption-by-recompute must not change greedy outputs."""
    roomy = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, seed=4, kv_layout="paged", page_size=16))
    roomy.start()
    want = roomy.submit_sync(list(range(1, 20)), SamplingParams(
        temperature=0.0, max_new_tokens=16)).generated
    roomy.stop()

    tight = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, seed=4,
        kv_layout="paged", page_size=16, kv_pages=5))
    tight.start()
    got = [tight.submit(list(range(1, 20)), SamplingParams(
        temperature=0.0, max_new_tokens=16)) for _ in range(2)]
    _drain(got)
    tight.stop()
    assert all(r.error is None for r in got)
    assert all(r.generated == want for r in got)


def test_recovered_pool_keeps_head_major_layout():
    """_recover_lost_cache must rebuild the pool in the SAME head-major
    [L, Hkv, Np, pg, hd] layout the init path allocates (a recovery
    that reverts to the dense-cache axis order silently corrupts every
    subsequent scatter/gather)."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, seed=5, kv_layout="paged", page_size=16))
    shape_before = eng.k_cache.shape
    eng.k_cache.delete()
    eng.v_cache.delete()
    eng._recover_lost_cache(RuntimeError("induced"))
    assert eng.k_cache.shape == shape_before
    assert eng.v_cache.shape == shape_before
    # and the engine still serves after recovery
    eng.start()
    reqs = [eng.submit([3, 1, 4], SamplingParams(
        temperature=0.0, max_new_tokens=6)) for _ in range(2)]
    _drain(reqs)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    assert all(len(r.generated) == 6 for r in reqs)


def test_kv_dtype_validation():
    """Engine construction (where every config knob is validated)
    rejects unknown kv_dtypes and int8/byte-budgets on the slot
    layout — both only mean something for paged pools."""
    with pytest.raises(ValueError, match="kv_dtype"):
        demo_llama_engine(EngineConfig(kv_dtype="fp8"))
    with pytest.raises(ValueError, match="kv_layout='paged'"):
        demo_llama_engine(EngineConfig(kv_dtype="int8"))  # slot layout
    with pytest.raises(ValueError, match="kv_pool_bytes"):
        demo_llama_engine(EngineConfig(kv_pool_bytes=1 << 20))


def test_int8_view_and_native_paths_agree_exactly():
    """The int8 view fallback (gather + dense decode + scatter) and the
    int8 native path (pool_write + ragged XLA fallback) see the SAME
    dequantized rows, so greedy outputs must agree token-for-token —
    this pins the two quantized implementations against each other the
    way the bf16 paths are pinned against the slot engine."""
    def run(**extra):
        eng = demo_llama_engine(EngineConfig(
            max_batch=2, max_seq=128, seed=13, kv_layout="paged",
            page_size=16, kv_dtype="int8", **extra))
        eng.start()
        reqs = [eng.submit(list(range(2, 9)), SamplingParams(
            temperature=0.0, max_new_tokens=12)) for _ in range(2)]
        _drain(reqs)
        eng.stop()
        assert all(r.error is None for r in reqs), [r.error for r in reqs]
        return [r.generated for r in reqs]

    view = run()                               # auto on CPU -> view
    native = run(paged_attention="xla")
    assert view == native
    assert all(len(t) == 12 for t in view)


def test_int8_engine_greedy_close_to_bf16():
    """End-to-end accuracy bound: int8 KV shifts logits by the quant
    error, which a tiny random model (near-uniform logits) amplifies —
    real checkpoints have far larger logit margins. The documented
    tolerance is therefore token-LEVEL, not bitwise: at least half the
    greedy tokens must agree with the f32-KV engine's, and both runs
    must complete error-free."""
    def run(dt):
        eng = demo_llama_engine(EngineConfig(
            max_batch=2, max_seq=128, seed=19, kv_layout="paged",
            page_size=16, kv_dtype=dt))
        eng.start()
        reqs = [eng.submit([3, 1, 4, 1, 5], SamplingParams(
            temperature=0.0, max_new_tokens=12)) for _ in range(2)]
        _drain(reqs)
        eng.stop()
        assert all(r.error is None for r in reqs), [r.error for r in reqs]
        return [r.generated for r in reqs]

    want, got = run("bf16"), run("int8")
    agree = sum(a == b for w, g in zip(want, got)
                for a, b in zip(w, g))
    total = sum(len(w) for w in want)
    assert agree >= total // 2, (want, got)


def test_int8_pool_doubles_pages_at_same_byte_budget():
    """Capacity is the point: at one fixed kv_pool_bytes budget the
    int8 pool must hold >= 1.8x the pages of the bf16 pool. Uses
    head_dim=64 (ratio 2*hd/(hd+4) = 1.88); the tiny config's hd=16
    would overstate the win (its f32 pools give 3.2x)."""
    import jax as _jax

    from gofr_tpu.models.llama import LlamaConfig, llama_init
    from gofr_tpu.serving.glue import llama_engine

    c = LlamaConfig(vocab_size=64, dim=256, n_layers=2, n_heads=4,
                    n_kv_heads=2, ffn_dim=64, max_seq=256,
                    dtype=jnp.bfloat16)
    assert c.head_dim == 64
    params = llama_init(_jax.random.key(0), c)
    budget = 1 << 20

    def pages(dt):
        eng = llama_engine(params, c, EngineConfig(
            max_batch=2, max_seq=256, kv_layout="paged", page_size=32,
            kv_dtype=dt, kv_pool_bytes=budget), implementation="xla")
        return eng._n_pages, eng._kv_bytes_total

    bf16_pages, bf16_bytes = pages("bf16")
    int8_pages, int8_bytes = pages("int8")
    assert int8_pages >= 1.8 * bf16_pages, (int8_pages, bf16_pages)
    # both pools actually fit the budget they were sized against
    assert bf16_bytes <= budget and int8_bytes <= budget


def test_recovered_pool_stays_quantized():
    """_recover_lost_cache must rebuild the int8 pool in the SAME
    quantized representation (a plain-array rebuild would break every
    compiled graph's pytree signature)."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, seed=5, kv_layout="paged",
        page_size=16, kv_dtype="int8"))
    shape_before = eng.k_cache["q"].shape
    eng.k_cache["q"].delete()
    assert eng._kv_lost()                      # pytree-aware probe
    eng._recover_lost_cache(RuntimeError("induced"))
    assert eng.k_cache["q"].shape == shape_before
    assert eng.k_cache["s"].shape == shape_before[:-1] + (1,)
    eng.start()
    reqs = [eng.submit([3, 1, 4], SamplingParams(
        temperature=0.0, max_new_tokens=6)) for _ in range(2)]
    _drain(reqs)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    assert all(len(r.generated) == 6 for r in reqs)


def test_paged_view_decode_windows_match():
    """Windowed paged-view decode (gathers only the table columns
    covering the window) must match the unwindowed paged engine
    greedily across a window boundary."""
    def run(**extra):
        eng = demo_llama_engine(EngineConfig(
            max_batch=2, max_seq=128, seed=21, kv_layout="paged",
            page_size=16, **extra))
        eng.start()
        reqs = [eng.submit(list(range(2, 12)), SamplingParams(
            temperature=0.0, max_new_tokens=40)) for _ in range(2)]
        _drain(reqs)
        eng.stop()
        assert all(r.error is None for r in reqs), [r.error for r in reqs]
        assert all(len(r.generated) == 40 for r in reqs)
        return [r.generated for r in reqs]

    want = run()
    got = run(decode_windows=(32, 64))
    assert got == want
