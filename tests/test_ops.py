"""Ops tests on the virtual CPU backend (pallas in interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops import (
    apply_rope,
    attention,
    decode_attention,
    layer_norm,
    moe_layer,
    rms_norm,
    rope_frequencies,
    sample_tokens,
    top_k_routing,
)
from gofr_tpu.ops.attention import xla_attention


def test_rms_norm_matches_reference():
    x = jax.random.normal(jax.random.key(0), (2, 5, 64))
    w = jax.random.normal(jax.random.key(1), (64,)) * 0.1 + 1.0
    got = rms_norm(x, w)
    # pure-numpy reference: mixing the jax x into numpy ops would hit
    # the harness's jax_numpy_rank_promotion='raise'
    xn = np.asarray(x)
    expected = xn / np.sqrt((xn ** 2).mean(-1, keepdims=True) + 1e-6) * np.asarray(w)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5)


def test_layer_norm_zero_mean_unit_var():
    x = jax.random.normal(jax.random.key(0), (3, 7, 32)) * 5 + 3
    out = layer_norm(x, jnp.ones(32), jnp.zeros(32))
    np.testing.assert_allclose(np.asarray(out).mean(-1), 0.0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out).std(-1), 1.0, atol=1e-3)


def test_rope_preserves_norm_and_relative_property():
    d = 64
    inv = rope_frequencies(d, theta=10000.0)
    x = jax.random.normal(jax.random.key(0), (1, 6, 2, d))
    pos = jnp.arange(6)[None, :]
    rotated = apply_rope(x, pos, inv)
    # rotation preserves vector norms
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(rotated), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)
    # dot products depend only on relative distance
    q = jax.random.normal(jax.random.key(1), (1, 1, 1, d))
    k = jax.random.normal(jax.random.key(2), (1, 1, 1, d))
    def dot_at(pq, pk):
        rq = apply_rope(q, jnp.array([[pq]]), inv)
        rk = apply_rope(k, jnp.array([[pk]]), inv)
        return float(jnp.sum(rq * rk))
    assert dot_at(3, 1) == pytest.approx(dot_at(12, 10), rel=1e-4)
    assert dot_at(0, 0) == pytest.approx(dot_at(9, 9), rel=1e-4)


def test_rope_llama3_scaling_changes_low_freqs_only():
    d = 128
    base = rope_frequencies(d)
    scaled = rope_frequencies(d, scaling={"factor": 8, "low_freq_factor": 1,
                                          "high_freq_factor": 4,
                                          "original_max_position": 8192})
    base, scaled = np.asarray(base), np.asarray(scaled)
    assert np.allclose(scaled[:8], base[:8])        # high freq intact
    assert np.allclose(scaled[-8:], base[-8:] / 8)  # low freq slowed 8x


def test_xla_attention_causal_masking():
    b, s, h, d = 1, 8, 2, 16
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))
    out_full = xla_attention(q, k, v, causal=True)
    # changing future kv must not affect past outputs
    k2 = k.at[:, -1].set(99.0)
    v2 = v.at[:, -1].set(99.0)
    out_mod = xla_attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(np.asarray(out_full[:, :-1]),
                               np.asarray(out_mod[:, :-1]), rtol=1e-5)


def test_gqa_matches_repeated_heads():
    b, s, d = 2, 8, 16
    hq, hkv = 8, 2
    q = jax.random.normal(jax.random.key(0), (b, s, hq, d))
    k = jax.random.normal(jax.random.key(1), (b, s, hkv, d))
    v = jax.random.normal(jax.random.key(2), (b, s, hkv, d))
    out = xla_attention(q, k, v, causal=True)
    k_rep = jnp.repeat(k, hq // hkv, axis=2)
    v_rep = jnp.repeat(v, hq // hkv, axis=2)
    out_rep = xla_attention(q, k_rep, v_rep, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_rep), rtol=1e-5)


def test_flash_attention_matches_xla():
    b, s, hq, hkv, d = 2, 256, 4, 2, 128
    q = jax.random.normal(jax.random.key(0), (b, s, hq, d), dtype=jnp.float32)
    k = jax.random.normal(jax.random.key(1), (b, s, hkv, d), dtype=jnp.float32)
    v = jax.random.normal(jax.random.key(2), (b, s, hkv, d), dtype=jnp.float32)
    ref = xla_attention(q, k, v, causal=True)
    got = attention(q, k, v, causal=True, implementation="interpret",
                    block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=1e-2)


def test_flash_attention_respects_kv_lengths():
    b, s, h, d = 2, 128, 2, 128
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))
    lengths = jnp.array([128, 64], dtype=jnp.int32)
    ref = xla_attention(q, k, v, causal=True, kv_lengths=lengths)
    got = attention(q, k, v, causal=True, kv_lengths=lengths,
                    implementation="interpret", block_q=64, block_k=64)
    # rows beyond a sequence's length are padding; compare valid region
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref[0]),
                               rtol=2e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(got[1, :64]), np.asarray(ref[1, :64]),
                               rtol=2e-2, atol=1e-2)


def test_flash_attention_non_multiple_seq_len():
    b, s, h, d = 1, 100, 2, 128  # not a multiple of block sizes
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))
    ref = xla_attention(q, k, v, causal=True)
    got = attention(q, k, v, causal=True, implementation="interpret",
                    block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=1e-2)


def test_decode_attention_matches_full_attention_last_row():
    b, smax, hq, hkv, d = 2, 32, 4, 2, 16
    cur_lens = jnp.array([10, 20], dtype=jnp.int32)
    k_cache = jax.random.normal(jax.random.key(1), (b, smax, hkv, d))
    v_cache = jax.random.normal(jax.random.key(2), (b, smax, hkv, d))
    q = jax.random.normal(jax.random.key(0), (b, 1, hq, d))
    got = decode_attention(q, k_cache, v_cache, cur_lens)
    for i, ln in enumerate([10, 20]):
        ref = xla_attention(q[i:i+1], k_cache[i:i+1, :ln], v_cache[i:i+1, :ln],
                            causal=False)
        np.testing.assert_allclose(np.asarray(got[i]), np.asarray(ref[0]),
                                   rtol=1e-4, atol=1e-5)


def test_chunked_prefill_q_offset():
    b, s, h, d = 1, 16, 2, 16
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))
    full = xla_attention(q, k, v, causal=True)
    # second half of q attending to full kv with offset
    part = xla_attention(q[:, 8:], k, v, causal=True, q_offset=8)
    np.testing.assert_allclose(np.asarray(full[:, 8:]), np.asarray(part),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------- sampling

def test_greedy_sampling():
    logits = jnp.array([[0.1, 5.0, 0.2], [3.0, 0.0, 0.1]])
    out = sample_tokens(logits, jax.random.key(0), temperature=0.0)
    assert out.tolist() == [1, 0]


def test_top_k_restricts_support():
    logits = jnp.array([[10.0, 9.0, 1.0, 0.0, -5.0]])
    draws = [int(sample_tokens(logits, jax.random.key(i), temperature=2.0,
                               top_k=2)[0]) for i in range(50)]
    assert set(draws) <= {0, 1}
    assert len(set(draws)) == 2  # both top-2 seen at high temperature


def test_top_p_keeps_at_least_one():
    logits = jnp.array([[0.0, 0.0, 0.0, 20.0]])
    draws = {int(sample_tokens(logits, jax.random.key(i), temperature=1.0,
                               top_p=0.01)[0]) for i in range(20)}
    assert draws == {3}


def test_sampling_follows_distribution():
    logits = jnp.log(jnp.array([[0.7, 0.2, 0.1]]))
    counts = np.zeros(3)
    for i in range(300):
        counts[int(sample_tokens(logits, jax.random.key(i))[0])] += 1
    assert counts[0] > counts[1] > counts[2]


# -------------------------------------------------------------------- moe

def test_top_k_routing_weights_sum_to_one():
    logits = jax.random.normal(jax.random.key(0), (10, 8))
    weights, indices = top_k_routing(logits, 2)
    np.testing.assert_allclose(np.asarray(weights.sum(-1)), 1.0, rtol=1e-5)
    assert indices.shape == (10, 2)
    assert len(set(np.asarray(indices).flatten().tolist())) <= 8


def test_moe_layer_single_expert_equals_dense_mlp():
    t, dm, f = 6, 16, 32
    x = jax.random.normal(jax.random.key(0), (t, dm))
    gate_w = jnp.zeros((dm, 1))
    w1 = jax.random.normal(jax.random.key(1), (1, dm, f)) * 0.1
    w3 = jax.random.normal(jax.random.key(2), (1, dm, f)) * 0.1
    w2 = jax.random.normal(jax.random.key(3), (1, f, dm)) * 0.1
    out, _ = moe_layer(x, gate_w, w1, w3, w2, num_selected=1)
    expected = (jax.nn.silu(x @ w1[0]) * (x @ w3[0])) @ w2[0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-4, atol=1e-5)


def test_moe_routes_to_distinct_experts():
    t, dm, f, e = 32, 8, 16, 4
    x = jax.random.normal(jax.random.key(0), (t, dm))
    gate_w = jax.random.normal(jax.random.key(1), (dm, e))
    w1 = jax.random.normal(jax.random.key(2), (e, dm, f)) * 0.1
    w3 = jax.random.normal(jax.random.key(3), (e, dm, f)) * 0.1
    w2 = jax.random.normal(jax.random.key(4), (e, f, dm)) * 0.1
    out, router_logits = moe_layer(x, gate_w, w1, w3, w2, num_selected=2)
    assert out.shape == (t, dm)
    assert router_logits.shape == (t, e)
    _, idx = top_k_routing(router_logits, 2)
    assert len(set(np.asarray(idx).flatten().tolist())) > 1
