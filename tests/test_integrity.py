"""Output-integrity observatory (serving/integrity.py): digest
folding at the retire boundary, golden canary probes priced in the
goodput ledger, mismatch-episode hysteresis, and the leader's fleet
divergence vote with router quarantine."""

import time

import pytest

from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.integrity import (DIGEST_VERSION, GoldenSet,
                                        IntegrityPlane, request_digest)


def _drain(reqs, timeout=180):
    deadline = time.time() + timeout
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.01)
    return reqs


def _greedy(max_new_tokens=8):
    return SamplingParams(temperature=0.0, max_new_tokens=max_new_tokens)


# ------------------------------------------------------ the fingerprint

class _Params:
    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


def test_request_digest_deterministic_and_sensitive():
    p = _Params(temperature=0.0, top_p=1.0, top_k=0, max_new_tokens=8)
    a = request_digest([1, 2, 3], p, [9, 8, 7])
    assert a == request_digest([1, 2, 3], p, [9, 8, 7])
    # one emitted token flips the fingerprint
    assert a != request_digest([1, 2, 3], p, [9, 8, 6])
    # prompt and params are folded too
    assert a != request_digest([1, 2, 4], p, [9, 8, 7])
    hot = _Params(temperature=0.7, top_p=1.0, top_k=0, max_new_tokens=8)
    assert a != request_digest([1, 2, 3], hot, [9, 8, 7])
    # ... but a cosmetic float round-trip (JSON replay) lands in the
    # same 1e-4 quantization bucket
    jittered = _Params(temperature=1e-9, top_p=1.0 - 1e-9, top_k=0,
                       max_new_tokens=8)
    assert a == request_digest([1, 2, 3], jittered, [9, 8, 7])


def test_digest_identical_across_kv_layouts():
    """Slot and paged layouts produce bit-identical greedy tokens
    (test_paged_attention pins that) — the fingerprint must agree
    too, or a mixed-layout fleet would vote against itself."""
    prompts = [[5 + i, 2, 9] for i in range(2)]
    digests = {}
    for name, extra in (
            ("slot", {}),
            ("paged", dict(kv_layout="paged", page_size=16,
                           paged_attention="interpret"))):
        engine = demo_llama_engine(EngineConfig(
            max_batch=2, max_seq=128, seed=23, **extra))
        engine.start()
        reqs = [engine.submit(p, _greedy()) for p in prompts]
        _drain(reqs)
        engine.stop()
        assert all(r.error is None for r in reqs)
        digests[name] = [r.digest for r in reqs]
        assert all(digests[name])
    assert digests["slot"] == digests["paged"]


def test_digest_deterministic_on_int8_pool():
    """The int8 page pool legitimately shifts numerics vs bf16 — the
    contract is run-to-run determinism (same host, same config, same
    digest), which is what the golden probes lean on."""
    engine = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, seed=23, kv_layout="paged",
        page_size=32, kv_dtype="int8", paged_attention="interpret"))
    engine.start()
    first, second = _drain([engine.submit([5, 2, 9], _greedy()),
                            engine.submit([5, 2, 9], _greedy())])
    engine.stop()
    assert first.error is None and second.error is None
    assert first.digest and first.digest == second.digest


def test_greedy_bit_identity_with_plane_on():
    """The plane is pure host arithmetic at the retire boundary:
    switching it off must not change one emitted token."""
    prompts = [[7, 3, 1], [4, 4, 2]]
    outs = {}
    for flag in (True, False):
        engine = demo_llama_engine(EngineConfig(
            max_batch=2, max_seq=128, seed=29, integrity=flag))
        engine.start()
        reqs = [engine.submit(p, _greedy()) for p in prompts]
        _drain(reqs)
        engine.stop()
        assert all(r.error is None for r in reqs)
        outs[flag] = [r.generated for r in reqs]
        # the digest is stamped exactly when the plane is on
        assert all(bool(r.digest) == flag for r in reqs)
    assert outs[True] == outs[False]


# ------------------------------------------------------- golden corpus

def _capture_golden(tmp_path, *, seed=23, n=3):
    """Run greedy traffic with workload capture on and seal a golden
    set from the records — the operator's sealing flow."""
    engine = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, seed=seed,
        workload_capture=True))
    engine.start()
    reqs = [engine.submit([5 + i, 2, 9], _greedy(6)) for i in range(n)]
    _drain(reqs)
    records = engine.workload.snapshot()["records"]
    engine.stop()
    golden = GoldenSet.seal(records)
    assert len(golden) == n
    path = str(tmp_path / "golden.jsonl")
    golden.save(path)
    return path, golden, [r.digest for r in reqs]


def test_golden_seal_load_roundtrip_and_loud_failures(tmp_path):
    path, golden, digests = _capture_golden(tmp_path)
    loaded = GoldenSet.load(path)
    assert [e.to_dict() for e in loaded.entries] == \
        [e.to_dict() for e in golden.entries]
    assert sorted(e.digest for e in loaded.entries) == sorted(digests)
    # wrong header contracts fail loudly: probing against the wrong
    # corpus would alarm on every probe, or on none
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"format": "not-golden", "version": 1}\n')
    with pytest.raises(ValueError, match="format"):
        GoldenSet.load(str(bad))
    bad.write_text('{"format": "gofr-golden", "version": 1, '
                   f'"digest_version": {DIGEST_VERSION + 1}}}\n')
    with pytest.raises(ValueError, match="digest_version"):
        GoldenSet.load(str(bad))


def test_probe_pricing_conserves_goodput(tmp_path):
    """Golden probes run on the background lane, their device time
    re-prices to the integrity_probe waste cause, and the goodput
    conservation identity stays exact with the cadence live."""
    path, _, _ = _capture_golden(tmp_path)
    engine = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, seed=23,
        integrity_golden_path=path, integrity_probe_passes=2,
        workload_capture=True))
    engine.start()
    _drain([engine.submit([5, 2, 9], _greedy(6))])
    deadline = time.time() + 60
    while time.time() < deadline and \
            engine.integrity.probes["run"] < 2:
        time.sleep(0.02)
    state = engine.integrity_state()
    goodput = engine.goodput.state()
    records = engine.workload.snapshot()["records"]
    engine.stop()
    assert state["probes"]["run"] >= 2
    assert state["probes"]["mismatch"] == 0 and not state["episode"]
    assert state["probe_device_s"] > 0.0
    assert goodput["waste_s"]["integrity_probe"] > 0.0
    assert goodput["conservation_error_s"] == 0.0
    # canaries are synthetic traffic: never captured as workload
    assert all(r.get("tenant") != "_integrity" for r in records)


# -------------------------------------------- mismatch-episode hysteresis

class _FakeReq:
    def __init__(self, *, probe=None, expected=None, generated=(9,)):
        self.prompt_tokens = [1, 2]
        self.params = _Params(temperature=0.0, top_p=1.0, top_k=0,
                              max_new_tokens=4)
        self.generated = list(generated)
        self.probe = probe
        self.probe_expected = expected
        self.error = None
        self.cancelled = False


def test_mismatch_episode_fires_once_then_rearms():
    plane = IntegrityPlane(True, rearm_probes=2)
    good = request_digest([1, 2], _FakeReq().params, [9])

    def probe(generated):
        return plane.fold(_FakeReq(probe="g000", expected=good,
                                   generated=generated))

    assert probe([9]) is None and plane.probes["ok"] == 1
    # first mismatch opens the episode: exactly one alarm record
    rec = probe([8])
    assert rec and rec["episode"] == 1 and rec["expected"] == good
    # further mismatches inside the episode stay silent
    assert probe([8]) is None and probe([7]) is None
    assert plane.probes["mismatch"] == 3 and plane.episodes == 1
    # one clean probe is not enough to re-arm (hysteresis) ...
    assert probe([9]) is None and plane.episode
    # ... two consecutive clean probes close the episode ...
    assert probe([9]) is None and not plane.episode
    # ... and the NEXT mismatch alarms again as a fresh episode
    rec = probe([8])
    assert rec and rec["episode"] == 2


def test_failed_probe_is_not_judged():
    plane = IntegrityPlane(True)
    req = _FakeReq(probe="g000", expected="feed", generated=[])
    req.error = "queue_full"
    assert plane.fold(req) is None
    assert plane.probes == {"run": 0, "ok": 0, "mismatch": 0,
                            "error": 1}
    assert not plane.episode


# ----------------------------------------- fleet divergence + quarantine

def _leader(**kw):
    from gofr_tpu.serving.control_plane import (ControlPlaneLeader,
                                                FleetConfig)
    fleet = FleetConfig(**kw) if kw else None
    return ControlPlaneLeader(coordinator="10.0.0.1:8476", fleet=fleet)


def _beat(leader, host, digests, seq, *, busy_s=10.0):
    """One heartbeat carrying an integrity digest block; busy_s lets a
    test make one host's traffic mix look much heavier."""
    leader.heartbeat(host, leader.generation, summary={
        "busy_s": busy_s, "useful_s": busy_s * 0.9,
        "waste_s": {"padding": busy_s * 0.1},
        "integrity": {"digest_version": 1, "seq": seq,
                      "probe_digests": dict(digests),
                      "probe_ok": True}})


def test_vote_names_outlier_and_spares_heavier_mix_host():
    leader = _leader()
    for h in ("a", "b", "c"):
        leader.join(h, f"http://{h}:1", 4)
    # host b carries 10x the traffic of its siblings — load must not
    # look like divergence; host c disagrees on g000's digest
    _beat(leader, "a", {"g000": "aaaa", "g001": "cccc"}, 1)
    _beat(leader, "b", {"g000": "aaaa", "g001": "cccc"}, 1,
          busy_s=100.0)
    _beat(leader, "c", {"g000": "ffff", "g001": "cccc"}, 1)
    vote = leader._vote_integrity()
    assert vote["votes"]["g000"]["majority"] == "aaaa"
    assert sorted(vote["quarantined"]) == ["c"]
    assert vote["quarantined"]["c"]["golden_id"] == "g000"
    assert vote["quarantined"]["c"]["digest"] == "ffff"
    statuses = {m["host_id"]: m["status"]
                for m in leader.routing_view()}
    assert statuses == {"a": "UP", "b": "UP", "c": "QUARANTINED"}
    assert leader.fleet_status()["hosts"]["c"]["status"] == "QUARANTINED"
    # exactly ONE divergence event + incident for the whole episode,
    # however many heartbeats repeat the same bad digest
    _beat(leader, "c", {"g000": "ffff", "g001": "cccc"}, 1)
    divergences = leader.events.snapshot(
        kind="fleet.integrity_divergence")
    assert len(divergences) == 1
    assert divergences[0]["attrs"]["outlier"] == "c"
    assert divergences[0]["attrs"]["majority"] == "aaaa"
    assert len([b for b in leader.incidents.list()
                if b["reason"] == "integrity_divergence"]) == 1


def test_no_vote_below_quorum_or_without_strict_majority():
    leader = _leader()
    for h in ("a", "b"):
        leader.join(h, f"http://{h}:1", 4)
    _beat(leader, "a", {"g000": "aaaa"}, 1)
    _beat(leader, "b", {"g000": "ffff"}, 1)
    # two hosts disagreeing is a tie, not a verdict
    vote = leader._vote_integrity()
    assert vote["votes"] == {} and vote["quarantined"] == {}
    # a 2-2 split above quorum records the split, never guesses
    # (quorum=4 so no intermediate 3-ballot majority forms while the
    # heartbeats trickle in)
    leader = _leader(integrity_quorum=4)
    for h in ("a", "b", "c", "d"):
        leader.join(h, f"http://{h}:1", 4)
    _beat(leader, "a", {"g000": "aaaa"}, 2)
    _beat(leader, "b", {"g000": "ffff"}, 2)
    _beat(leader, "c", {"g000": "aaaa"}, 2)
    _beat(leader, "d", {"g000": "ffff"}, 2)
    vote = leader._vote_integrity()
    assert vote["votes"]["g000"]["majority"] is None
    assert vote["quarantined"] == {}


def test_quarantine_rejoins_after_seq_advanced_clean_probes():
    leader = _leader(integrity_clean_probes=2)
    for h in ("a", "b", "c"):
        leader.join(h, f"http://{h}:1", 4)
    _beat(leader, "a", {"g000": "aaaa"}, 1)
    _beat(leader, "b", {"g000": "aaaa"}, 1)
    _beat(leader, "c", {"g000": "ffff"}, 1)
    assert "c" in leader._vote_integrity()["quarantined"]
    # clean digest but the SAME probe seq: a repeated heartbeat is not
    # new evidence, the streak counts probes
    _beat(leader, "c", {"g000": "aaaa"}, 1)
    assert "c" in leader._vote_integrity()["quarantined"]
    _beat(leader, "c", {"g000": "aaaa"}, 2)
    assert "c" in leader._vote_integrity()["quarantined"]
    _beat(leader, "c", {"g000": "aaaa"}, 3)
    vote = leader._vote_integrity()
    assert vote["quarantined"] == {}
    assert {m["host_id"]: m["status"] for m in leader.routing_view()} \
        == {"a": "UP", "b": "UP", "c": "UP"}
    actions = [e["attrs"]["action"] for e in
               leader.events.snapshot(kind="fleet.quarantine")]
    assert actions == ["quarantine", "rejoin"]


def test_router_drops_quarantined_host_and_sweeps_affinity():
    from gofr_tpu.serving.router import FleetRouter, RouterConfig

    leader = _leader()
    for h in ("a", "b", "c"):
        leader.join(h, f"http://{h}:1", 4)
    router = FleetRouter(leader, RouterConfig(affinity_size=8))
    router.affinity.put("sess-1", "c")
    assert {m["host_id"] for m in router._members()} == {"a", "b", "c"}
    _beat(leader, "a", {"g000": "aaaa"}, 1)
    _beat(leader, "b", {"g000": "aaaa"}, 1)
    _beat(leader, "c", {"g000": "ffff"}, 1)
    # quarantined: routed share goes to zero on the next plan and the
    # pinned session must re-plan onto a healthy sibling
    assert {m["host_id"] for m in router._members()} == {"a", "b"}
    assert router.affinity.get("sess-1") is None
    assert router.debug_state()["quarantines"] == {"quarantine": 1}
    _beat(leader, "c", {"g000": "aaaa"}, 2)
    _beat(leader, "c", {"g000": "aaaa"}, 3)
    assert {m["host_id"] for m in router._members()} == {"a", "b", "c"}
    assert router.debug_state()["quarantines"] == \
        {"quarantine": 1, "rejoin": 1}


# ------------------------------------------------ fault-driven divergence

def test_logit_corrupt_diverges_digest_without_crashing():
    """The deterministic corruption drill: exact invocation window,
    stream lengths preserved, nothing crashes — only bytes (and so
    the fingerprint) change."""
    engine = demo_llama_engine(EngineConfig(
        max_batch=1, max_seq=128, seed=23,
        faults="logit_corrupt:at=1"))
    engine.start()
    # at=1 fires on the first emitted token only: request 1 is
    # corrupted, request 2 (same prompt) is the clean reference
    dirty = _drain([engine.submit([5, 2, 9], _greedy(6))])[0]
    clean = _drain([engine.submit([5, 2, 9], _greedy(6))])[0]
    engine.stop()
    assert dirty.error is None and clean.error is None
    assert len(dirty.generated) == len(clean.generated)
    assert dirty.generated != clean.generated
    diff = [i for i, (d, c) in enumerate(
        zip(dirty.generated, clean.generated)) if d != c]
    assert diff[0] == 0  # the corrupted emit is the faulted one
    assert dirty.digest != clean.digest
