"""Request abstraction tests — params, JSON/form/multipart binding."""

from dataclasses import dataclass, field

import pytest

from gofr_tpu.http.request import BindError, HTTPRequest


def make(method="GET", target="/", headers=None, body=b""):
    return HTTPRequest(method, target, headers or {}, body)


def test_query_params():
    r = make(target="/search?q=llama&tag=a&tag=b&csv=x,y,z&empty=")
    assert r.param("q") == "llama"
    assert r.param("missing") == ""
    assert r.params("tag") == ["a", "b"]
    assert r.params("csv") == ["x", "y", "z"]
    assert r.param("empty") == ""


def test_path_params_and_host():
    r = make(target="/users/1", headers={"Host": "api.local:8000"})
    r.set_path_params({"id": "1"})
    assert r.path_param("id") == "1"
    assert r.path_param("nope") == ""
    assert r.host_name() == "api.local:8000"


def test_bind_json_to_dict():
    r = make("POST", "/x", {"Content-Type": "application/json"},
             b'{"name": "ada", "age": 37}')
    assert r.bind() == {"name": "ada", "age": 37}


@dataclass
class Person:
    name: str
    age: int
    tags: list[str] = field(default_factory=list)
    active: bool = True


def test_bind_json_to_dataclass_with_coercion():
    r = make("POST", "/x", {"Content-Type": "application/json"},
             b'{"name": "ada", "age": "37", "tags": ["x"], "active": "false", "extra": 1}')
    p = r.bind(Person)
    assert p == Person(name="ada", age=37, tags=["x"], active=False)


def test_bind_missing_required_field():
    r = make("POST", "/x", {"Content-Type": "application/json"}, b'{"age": 1}')
    with pytest.raises(BindError, match="name"):
        r.bind(Person)


def test_bind_invalid_json():
    r = make("POST", "/x", {"Content-Type": "application/json"}, b"{nope")
    with pytest.raises(BindError, match="invalid JSON"):
        r.bind()


def test_bind_form_urlencoded():
    r = make("POST", "/x", {"Content-Type": "application/x-www-form-urlencoded"},
             b"name=ada&age=37")
    p = r.bind(Person)
    assert p.name == "ada" and p.age == 37


def test_bind_multipart():
    boundary = "XBOUND"
    body = (
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="name"\r\n\r\n'
        "ada\r\n"
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="doc"; filename="a.txt"\r\n'
        "Content-Type: text/plain\r\n\r\n"
        "file-bytes-here\r\n"
        f"--{boundary}--\r\n"
    ).encode()
    r = make("POST", "/up",
             {"Content-Type": f"multipart/form-data; boundary={boundary}"}, body)
    data = r.bind()
    assert data["name"] == "ada"
    assert data["doc"]["filename"] == "a.txt"
    assert data["doc"]["content"] == b"file-bytes-here"
    assert data["doc"]["content_type"] == "text/plain"


def test_bind_binary_and_text():
    r = make("POST", "/x", {"Content-Type": "application/octet-stream"}, b"\x01\x02")
    assert r.bind() == b"\x01\x02"
    r2 = make("POST", "/x", {"Content-Type": "text/plain"}, b"hello")
    assert r2.bind() == "hello"


def test_nested_dataclass_bind():
    @dataclass
    class Address:
        city: str

    @dataclass
    class User:
        name: str
        address: Address

    r = make("POST", "/x", {"Content-Type": "application/json"},
             b'{"name": "a", "address": {"city": "zurich"}}')
    u = r.bind(User)
    assert u.address.city == "zurich"
