"""Device-resident decode state + fused multi-pass decode.

The decode hot path keeps per-slot scheduler state (lengths, sampling
params, active mask, page tables) as persistent DEVICE arrays that are
re-uploaded only on admission/retirement/preemption events; lengths and
the sampling-rng counter advance on-device inside the decode graph.
These tests pin the contract:

  * steady-state dispatches perform ZERO host->device transfers
    (enforced with ``jax.transfer_guard_host_to_device``);
  * scheduler events trigger exactly one resync;
  * ``decode_passes_per_dispatch`` (M) is a pure throughput knob —
    greedy outputs are bit-identical to the single-pass path on both
    KV layouts, in fewer dispatches.
"""

import time

import jax
import pytest

from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine


def _admit(eng, prompts, **sp):
    """Drive the engine WITHOUT its thread: pop + admit on this thread
    so the test controls exactly when decode passes run."""
    params = SamplingParams(**sp)
    reqs = [eng.submit(p, params) for p in prompts]
    batch = eng.waiting.pop_batch(len(reqs), first_wait_s=0.5)
    assert batch and len(batch) == len(reqs)
    eng._admit_batch(batch)
    eng._collect_prefills()
    return reqs


def _run_threaded(eng, prompts, n):
    eng.start()
    sp = SamplingParams(temperature=0.0, max_new_tokens=n)
    reqs = [eng.submit(p, sp) for p in prompts]
    deadline = time.time() + 120
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    assert all(len(r.generated) == n for r in reqs)
    return [r.generated for r in reqs]


def test_steady_state_decode_uploads_nothing():
    """Consecutive decode passes with no admission/retirement events
    must not upload ANY scheduler state — the graph runs entirely on
    device-resident arrays (tokens feed back on device, lengths and
    the rng counter advance in-graph)."""
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=256,
                                         seed=0))
    reqs = _admit(eng, [[1 + i, 2, 3] for i in range(3)],
                  temperature=0.0, max_new_tokens=200)
    # two unguarded passes: the first uploads the freshly admitted
    # state, the second re-uploads once as the fresh rows flip to
    # device-side token feedback (use_prev) — then steady state
    eng._decode_step()
    eng._drain_pending()
    eng._decode_step()
    eng._drain_pending()
    transfers = eng.stats["h2d_transfers"]
    syncs = eng.stats["sched_syncs"]
    with jax.transfer_guard_host_to_device("disallow"):
        for _ in range(3):
            eng._decode_step()
            eng._drain_pending()
    assert eng.stats["h2d_transfers"] == transfers
    assert eng.stats["sched_syncs"] == syncs
    K = eng.config.decode_steps_per_pass
    assert all(len(r.generated) == 1 + 5 * K for r in reqs)


def test_admission_event_triggers_exactly_one_resync():
    """A scheduler event (new admission) costs one state upload, then
    the path returns to zero-transfer steady state."""
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=256,
                                         seed=1))
    _admit(eng, [[7, 8, 9]], temperature=0.0, max_new_tokens=200)
    for _ in range(3):
        eng._decode_step()
        eng._drain_pending()
    syncs = eng.stats["sched_syncs"]
    _admit(eng, [[4, 5, 6]], temperature=0.0, max_new_tokens=200)
    eng._decode_step()          # admission -> resync
    eng._drain_pending()
    eng._decode_step()          # fresh row flips to use_prev -> resync
    eng._drain_pending()
    assert eng.stats["sched_syncs"] == syncs + 2
    with jax.transfer_guard_host_to_device("disallow"):
        eng._decode_step()      # steady again
        eng._drain_pending()
    assert eng.stats["sched_syncs"] == syncs + 2


def test_dispatch_and_collect_spans_accounted():
    """The per-pass host-side phase accounting must populate — the
    bench uses it to prove dispatch overhead fell."""
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64,
                                         seed=2))
    eng.start()
    req = eng.submit_sync([1, 2, 3], SamplingParams(
        temperature=0.0, max_new_tokens=12))
    eng.stop()
    assert req.error is None
    assert eng.stats["decode_passes"] >= 1
    assert eng.stats["dispatch_s"] > 0.0
    assert eng.stats["collect_s"] >= 0.0
    assert eng.stats["sched_syncs"] >= 1
    assert eng.stats["h2d_transfers"] >= 7


@pytest.mark.parametrize("layout_kw", [
    {},
    {"kv_layout": "paged", "page_size": 16, "paged_attention": "view"},
])
def test_multi_pass_decode_greedy_identical(layout_kw):
    """decode_passes_per_dispatch is a pure dispatch-overhead knob:
    K x M fused steps must reproduce the single-pass token streams
    bit for bit (both KV layouts), in fewer dispatches."""
    prompts = [[5 + i, 2, 9] for i in range(3)]
    n = 32

    def build(m):
        return demo_llama_engine(EngineConfig(
            max_batch=4, max_seq=128, seed=11,
            decode_passes_per_dispatch=m, **layout_kw))

    single = build(1)
    want = _run_threaded(single, prompts, n)
    fused = build(4)
    got = _run_threaded(fused, prompts, n)
    assert got == want
    assert fused.stats["decode_passes"] < single.stats["decode_passes"]


def test_multi_pass_respects_max_seq_ceiling():
    """A fused pass crossing the cache ceiling emits only the valid
    prefix and retires the slot — no overrun, no hang."""
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64,
                                         seed=3,
                                         decode_passes_per_dispatch=4))
    eng.start()
    req = eng.submit_sync(list(range(1, 40)), SamplingParams(
        temperature=0.0, max_new_tokens=100))
    eng.stop()
    assert req.error is None
    assert 0 < len(req.generated) <= 100
