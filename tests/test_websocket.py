"""Websocket layer: handshake, echo, bind, streaming, manager, auth."""

from __future__ import annotations

import asyncio
import base64
from dataclasses import dataclass

import pytest

from gofr_tpu.websocket import WSHandshakeError, connect
from gofr_tpu.websocket.service import WSService

from .apputil import AppRunner


@dataclass
class ChatMessage:
    user: str
    text: str


def build_echo(app):
    @app.websocket("/ws/echo")
    def echo(ctx):
        return {"echo": ctx.bind(str)}

    @app.websocket("/ws/chat/{room}")
    def chat(ctx):
        msg = ctx.bind(ChatMessage)
        return {"room": ctx.path_param("room"), "from": msg.user,
                "text": msg.text.upper()}

    @app.websocket("/ws/stream")
    async def stream(ctx):
        n = int(ctx.bind(str))
        for i in range(n):
            await ctx.write_message_to_socket({"token": i})
        return {"done": n}

    @app.websocket("/ws/boom")
    def boom(ctx):
        raise ValueError("handler exploded")


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 15))


class TestWebSocket:
    def test_echo_roundtrip(self):
        with AppRunner(build=build_echo) as r:
            async def go():
                conn = await connect(f"ws://127.0.0.1:{r.port}/ws/echo")
                await conn.send("hello")
                reply = await conn.recv()
                assert reply is not None
                import json
                assert json.loads(reply.text()) == {"echo": "hello"}
                await conn.close()
            run(go())

    def test_dataclass_bind_and_path_params(self):
        with AppRunner(build=build_echo) as r:
            async def go():
                conn = await connect(f"ws://127.0.0.1:{r.port}/ws/chat/tpu")
                await conn.send({"user": "ada", "text": "hi"})
                import json
                reply = json.loads((await conn.recv()).text())
                assert reply == {"room": "tpu", "from": "ada", "text": "HI"}
                await conn.close()
            run(go())

    def test_streaming_write_message_to_socket(self):
        with AppRunner(build=build_echo) as r:
            async def go():
                conn = await connect(f"ws://127.0.0.1:{r.port}/ws/stream")
                await conn.send("3")
                import json
                got = [json.loads((await conn.recv()).text())
                       for _ in range(4)]
                assert got == [{"token": 0}, {"token": 1}, {"token": 2},
                               {"done": 3}]
                await conn.close()
            run(go())

    def test_handler_error_keeps_connection(self):
        with AppRunner(build=build_echo) as r:
            async def go():
                conn = await connect(f"ws://127.0.0.1:{r.port}/ws/boom")
                await conn.send("x")
                import json
                reply = json.loads((await conn.recv()).text())
                # internal details are masked (HTTP panic-recovery policy)
                assert reply == {"error": "internal server error"}
                # connection survives; next message also answered
                await conn.send("y")
                assert (await conn.recv()) is not None
                await conn.close()
            run(go())

    def test_ping_pong_and_large_message(self):
        with AppRunner(build=build_echo) as r:
            async def go():
                conn = await connect(f"ws://127.0.0.1:{r.port}/ws/echo")
                await conn.ping(b"hb")  # pong handled inside recv
                big = "x" * 70000  # forces 16-bit extended length
                await conn.send(big)
                import json
                reply = json.loads((await conn.recv()).text())
                assert reply["echo"] == big
                await conn.close()
            run(go())

    def test_plain_http_get_is_426(self):
        with AppRunner(build=build_echo) as r:
            status, _, _ = r.request("GET", "/ws/echo")
            assert status == 426

    def test_unknown_ws_path_rejected(self):
        with AppRunner(build=build_echo) as r:
            async def go():
                with pytest.raises(WSHandshakeError):
                    await connect(f"ws://127.0.0.1:{r.port}/ws/nope")
            run(go())


class TestManagerBroadcast:
    def test_broadcast_reaches_all(self):
        received = asyncio.Event()

        def build(app):
            build_echo(app)

            @app.get("/announce")
            async def announce(ctx):
                n = await ctx.ws_manager.broadcast({"announcement": "hi"})
                return {"sent": n}
        with AppRunner(build=build) as r:
            async def go():
                a = await connect(f"ws://127.0.0.1:{r.port}/ws/echo")
                b = await connect(f"ws://127.0.0.1:{r.port}/ws/echo")
                await asyncio.sleep(0.05)  # let server register both
                status, body = r.get_json("/announce")
                assert status == 200 and body["data"]["sent"] == 2
                import json
                assert json.loads((await a.recv()).text()) == \
                    {"announcement": "hi"}
                assert json.loads((await b.recv()).text()) == \
                    {"announcement": "hi"}
                await a.close()
                await b.close()
            run(go())


class TestWebSocketAuth:
    def _build(self, app):
        app.enable_basic_auth(alice="pw")
        build_echo(app)

    def test_handshake_requires_auth(self):
        with AppRunner(build=self._build) as r:
            async def go():
                with pytest.raises(WSHandshakeError, match="401"):
                    await connect(f"ws://127.0.0.1:{r.port}/ws/echo")
            run(go())

    def test_handshake_with_credentials(self):
        with AppRunner(build=self._build) as r:
            token = base64.b64encode(b"alice:pw").decode()
            async def go():
                conn = await connect(
                    f"ws://127.0.0.1:{r.port}/ws/echo",
                    headers={"Authorization": f"Basic {token}"})
                await conn.send("hi")
                assert (await conn.recv()) is not None
                await conn.close()
            run(go())


class TestUserMiddlewareGuardsUpgrade:
    def test_user_middleware_runs_before_handshake(self):
        """The upgrade is innermost: custom middleware can veto it."""
        def build(app):
            build_echo(app)

            def deny_mw(next_handler):
                async def wrapped(request):
                    if request.header("x-tenant") != "good":
                        from gofr_tpu.http.responder import ResponseData
                        return ResponseData(status=403, body=b"denied")
                    return await next_handler(request)
                return wrapped
            app.use_middleware(deny_mw)
        with AppRunner(build=build) as r:
            async def go():
                with pytest.raises(WSHandshakeError, match="403"):
                    await connect(f"ws://127.0.0.1:{r.port}/ws/echo")
                conn = await connect(f"ws://127.0.0.1:{r.port}/ws/echo",
                                     headers={"X-Tenant": "good"})
                await conn.send("hi")
                assert (await conn.recv()) is not None
                await conn.close()
            run(go())


class TestWSService:
    def test_outbound_service_send_and_receive(self):
        inbound: list[str] = []

        def build(app):
            build_echo(app)
        with AppRunner(build=build) as r:
            async def go():
                got = asyncio.Event()

                def on_message(msg):
                    inbound.append(msg.text())
                    got.set()
                service = WSService("peer",
                                    f"ws://127.0.0.1:{r.port}/ws/echo",
                                    retry_interval=0.2,
                                    on_message=on_message)
                await service.start()
                assert await service.wait_connected(10)
                await service.send("ping")
                await asyncio.wait_for(got.wait(), 10)
                assert "ping" in inbound[0]
                await service.stop()
            run(go())

    def test_service_reports_disconnected(self):
        async def go():
            service = WSService("down", "ws://127.0.0.1:9/ws", retry_interval=5)
            with pytest.raises(ConnectionError):
                await service.send("x")
        run(go())
