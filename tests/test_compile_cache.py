"""Persistent XLA compile-cache plumbing (``gofr_tpu.config.env``).

One shared config path (``GOFR_COMPILE_CACHE_DIR`` -> default under
``~/.cache``) resolves the ``jax_compilation_cache_dir`` for the
engine, bench children and every TPU job, so warmup compiles amortize
across processes instead of being re-paid per child."""

import os
import subprocess
import sys

from gofr_tpu.config.env import (COMPILE_CACHE_ENV, DictConfig,
                                 default_compile_cache_dir,
                                 enable_compile_cache,
                                 resolve_compile_cache_dir)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_resolve_precedence_and_off(monkeypatch, tmp_path):
    monkeypatch.delenv(COMPILE_CACHE_ENV, raising=False)
    assert resolve_compile_cache_dir() == default_compile_cache_dir()
    monkeypatch.setenv(COMPILE_CACHE_ENV, str(tmp_path))
    assert resolve_compile_cache_dir() == str(tmp_path)
    for off in ("off", "none", "0", "FALSE"):
        monkeypatch.setenv(COMPILE_CACHE_ENV, off)
        assert resolve_compile_cache_dir() is None
    # a Config layer wins over the OS environment fallback
    cfg = DictConfig({COMPILE_CACHE_ENV: "/somewhere/else"})
    assert resolve_compile_cache_dir(cfg) == "/somewhere/else"


def test_enable_points_jax_at_directory(tmp_path):
    import jax
    target = str(tmp_path / "cache")
    try:
        assert enable_compile_cache(target) == target
        assert jax.config.jax_compilation_cache_dir == target
        assert os.path.isdir(target)
        assert enable_compile_cache(None) is None  # disabled = no-op
        assert jax.config.jax_compilation_cache_dir == target
    finally:
        # restore the shared default so later engines in this process
        # aren't pinned to the tmpdir
        enable_compile_cache("auto")


def test_engine_config_field_applies_cache_dir(tmp_path):
    import jax

    from gofr_tpu.serving.engine import EngineConfig
    from gofr_tpu.serving.glue import demo_llama_engine
    target = str(tmp_path / "engine-cache")
    try:
        demo_llama_engine(EngineConfig(max_batch=2, max_seq=64,
                                       compile_cache_dir=target))
        assert jax.config.jax_compilation_cache_dir == target
    finally:
        enable_compile_cache("auto")


_CHILD = """
import os
import jax
import jax.numpy as jnp
from gofr_tpu.config.env import enable_compile_cache
path = enable_compile_cache()
assert path == os.environ["GOFR_COMPILE_CACHE_DIR"], path
f = jax.jit(lambda x: (x @ x + jnp.float32(3)).sum())
f(jnp.ones((32, 32), jnp.float32)).block_until_ready()
print("CACHE_FILES",
      len([n for n in os.listdir(path) if n.endswith("-cache")]))
"""


def test_children_share_cache_across_processes(tmp_path):
    """Two child processes compiling the same graph: the first
    populates the shared directory, the second gets pure cache hits
    (no new entries) — the amortization the TPU jobs rely on."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env[COMPILE_CACHE_ENV] = str(tmp_path)

    def run():
        p = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                           capture_output=True, text=True,
                           timeout=180, cwd=REPO)
        assert p.returncode == 0, p.stderr[-2000:]
        return int(p.stdout.strip().rsplit(" ", 1)[-1])

    first = run()
    assert first > 0, "first child compiled nothing into the cache"
    second = run()
    assert second == first, (first, second)
