"""Workload capture + deterministic replay (serving/observability.py
WorkloadRecorder + serving/replay.py).

The contract under test: capture adds ZERO perturbation to the hot
path (transfer-guard + greedy bit-identity hold with capture ON), the
captured JSONL round-trips through the replay driver, and greedy
replay through a fresh engine with the same model/config/seed is
**bit-identical** to the recorded completions — with divergences
detected, located (first divergent token) and counted when it is not.
"""

import json
import time

import jax
import pytest

from gofr_tpu.container.container import Container
from gofr_tpu.metrics.registry import Manager as MetricsManager
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.observability import (WORKLOAD_VERSION,
                                            WorkloadRecorder)
from gofr_tpu.serving.replay import (load_workload, parse_workload,
                                     replay_workload)
from gofr_tpu.serving.tokenizer import ByteTokenizer

from .apputil import AppRunner


class _FakeReq:
    def __init__(self, i, generated=(1, 2, 3)):
        self.prompt_tokens = [10 + i, 5, 7]
        self.params = SamplingParams(temperature=0.0, max_new_tokens=8)
        self.submitted_at = 100.0 + i
        self.first_token_at = 100.5 + i
        self.finished_at = 101.0 + i
        self.generated = list(generated)
        self.tenant = f"t{i % 2}"
        self.error = None
        self.cancelled = False

    @property
    def ttft_ms(self):
        return (self.first_token_at - self.submitted_at) * 1000.0


def _run(eng, prompts, n, *, tenants=None, timeout=120):
    eng.start()
    sp = SamplingParams(temperature=0.0, max_new_tokens=n)
    reqs = [eng.submit(p, sp,
                       tenant=tenants[i] if tenants else None)
            for i, p in enumerate(prompts)]
    deadline = time.time() + timeout
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return reqs


# ---------------------------------------------------------- recorder unit
def test_recorder_ring_bounds_under_overflow():
    rec = WorkloadRecorder(4, engine_seed=3)
    rec.start()
    for i in range(10):
        rec.record(_FakeReq(i))
    snap = rec.snapshot()
    assert len(snap["records"]) == 4                    # ring bounded
    assert snap["header"]["recorded"] == 10
    assert snap["header"]["dropped"] == 6
    assert [r["prompt_tokens"][0] for r in snap["records"]] == \
        [16, 17, 18, 19]                                # oldest dropped
    assert rec.snapshot(2)["records"][-1]["prompt_tokens"][0] == 19
    # size 0 disables entirely; start() is a no-op
    off = WorkloadRecorder(0)
    off.start()
    off.record(_FakeReq(0))
    assert off.snapshot()["records"] == [] and not off.capturing


def test_recorder_not_capturing_until_started_and_start_clears():
    rec = WorkloadRecorder(8, engine_seed=1)
    rec.record(_FakeReq(0))
    assert rec.snapshot()["records"] == []              # disarmed
    rec.start()
    rec.record(_FakeReq(1))
    assert len(rec.snapshot()["records"]) == 1
    rec.stop()
    rec.record(_FakeReq(2))
    assert len(rec.snapshot()["records"]) == 1          # disarmed again
    rec.start()                                         # fresh capture
    assert rec.snapshot()["records"] == []


def test_redaction_never_emits_raw_tokens():
    rec = WorkloadRecorder(8, redact=True, engine_seed=1)
    rec.start()
    req = _FakeReq(0, generated=(42, 43, 44))
    rec.record(req)
    text = rec.to_jsonl()
    header, record = [json.loads(ln) for ln in text.splitlines()]
    assert header["redacted"] is True
    assert "prompt_tokens" not in record
    assert "completion_tokens" not in record
    assert record["prompt_len"] == 3 and record["completion_len"] == 3
    assert len(record["prompt_hash"]) == 24
    # no raw id sequence anywhere in the serialized file
    assert "42" not in json.dumps(record.get("prompt_hash", "")) or True
    for needle in ("[10, 5, 7]", "[42, 43, 44]", '"42,'):
        assert needle not in text
    # identical token streams collide (what divergence checks need);
    # different streams don't
    rec.record(_FakeReq(0, generated=(42, 43, 44)))
    rec.record(_FakeReq(0, generated=(42, 43, 99)))
    recs = rec.snapshot()["records"]
    assert recs[0]["completion_hash"] == recs[1]["completion_hash"]
    assert recs[0]["completion_hash"] != recs[2]["completion_hash"]


def test_workload_format_validation():
    with pytest.raises(ValueError, match="empty"):
        parse_workload("")
    with pytest.raises(ValueError, match="not a gofr-workload"):
        parse_workload('{"format": "something-else"}')
    with pytest.raises(ValueError, match="version"):
        parse_workload(json.dumps(
            {"format": "gofr-workload", "version": WORKLOAD_VERSION + 1}))
    with pytest.raises(ValueError, match="not JSON"):
        parse_workload('{"format": "gofr-workload", "version": %d}\n'
                       "garbage" % WORKLOAD_VERSION)
    ok = parse_workload(json.dumps(
        {"format": "gofr-workload", "version": WORKLOAD_VERSION})
        + '\n{"t": 1.0}')
    assert len(ok["records"]) == 1


def test_replay_refuses_redacted_workloads():
    workload = {"header": {"redacted": True}, "records": []}
    with pytest.raises(ValueError, match="redacted"):
        replay_workload(object(), workload)


# ----------------------------------------- zero-perturbation with capture
def test_steady_state_zero_h2d_with_capture_on():
    """The transfer-guard contract with workload capture armed:
    steady-state decode still uploads nothing."""
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=256,
                                         seed=0, workload_capture=True))
    assert eng.workload.capturing
    params = SamplingParams(temperature=0.0, max_new_tokens=200)
    reqs = [eng.submit([1 + i, 2, 3], params, tenant=f"t{i}")
            for i in range(3)]
    batch = eng.waiting.pop_batch(len(reqs), first_wait_s=0.5)
    assert batch and len(batch) == len(reqs)
    eng._admit_batch(batch)
    eng._collect_prefills()
    for _ in range(2):  # admission upload, then the use_prev flip
        eng._decode_step()
        eng._drain_pending()
    transfers = eng.stats["h2d_transfers"]
    with jax.transfer_guard_host_to_device("disallow"):
        for _ in range(3):
            eng._decode_step()
            eng._drain_pending()
    assert eng.stats["h2d_transfers"] == transfers


@pytest.mark.parametrize("layout_kw", [
    {},
    {"kv_layout": "paged", "page_size": 16, "paged_attention": "view"},
])
def test_greedy_bit_identical_with_capture_on(layout_kw):
    """Capture ON changes no generated token, and the captured
    completions ARE the emitted streams (both KV layouts)."""
    prompts = [[5 + i, 2, 9] for i in range(3)]

    def cfg(**kw):
        return EngineConfig(max_batch=4, max_seq=128, seed=11,
                            **layout_kw, **kw)

    bare = demo_llama_engine(cfg())
    want = [r.generated for r in _run(bare, prompts, 16)]

    cap = demo_llama_engine(cfg(workload_capture=True))
    got = _run(cap, prompts, 16,
               tenants=[f"tenant-{i}" for i in range(3)])
    assert [r.generated for r in got] == want
    records = cap.workload.snapshot()["records"]
    assert len(records) == 3
    by_prompt = {tuple(r["prompt_tokens"]): r for r in records}
    for req in got:
        rec = by_prompt[tuple(req.prompt_tokens)]
        assert rec["completion_tokens"] == req.generated
        assert rec["status"] == "ok"
        assert rec["seed"] == 11 and rec["ttft_ms"] is not None


# ------------------------------------------------------------ replay e2e
def _capture_workload(seed=17, n_reqs=5, gen=12):
    cfg = EngineConfig(max_batch=4, max_seq=128, seed=seed,
                       workload_capture=True)
    eng = demo_llama_engine(cfg)
    prompts = [[3 + i, 8, 1, 9] for i in range(n_reqs)]
    _run(eng, prompts, gen,
         tenants=[f"team-{i % 2}" for i in range(n_reqs)])
    return eng.workload.to_jsonl(), cfg


def test_capture_then_replay_is_bit_identical(tmp_path):
    text, cfg = _capture_workload()
    path = tmp_path / "w.jsonl"
    path.write_text(text)
    workload = load_workload(str(path))
    assert workload["header"]["engine_seed"] == 17

    fresh = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=128,
        seed=workload["header"]["engine_seed"]))
    try:
        report = replay_workload(fresh, workload, speed=1000.0,
                                 timeout_s=120.0)
    finally:
        fresh.stop()
    assert report["compared"] == 5
    assert report["divergent"] == 0
    assert report["bit_identical"] is True
    assert report["replay_errors"] == 0
    # tenants rode the replay into the fresh engine's accounting
    assert set(fresh.usage_ledger.rollup()["tenants"]) == \
        {"team-0", "team-1"}
    # both latency views populated
    assert report["recorded_latency"]["p50_ttft_ms"] is not None
    assert report["replayed_latency"]["p50_ttft_ms"] is not None


def test_replay_detects_and_locates_divergence(tmp_path):
    text, _ = _capture_workload(seed=19, n_reqs=3, gen=10)
    workload = parse_workload(text)
    # tamper: flip the 4th token of one recorded completion
    victim = workload["records"][1]
    victim["completion_tokens"] = list(victim["completion_tokens"])
    victim["completion_tokens"][3] ^= 1
    fresh = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                           seed=19))
    m = MetricsManager()
    fresh.attach_metrics(m)
    try:
        report = replay_workload(fresh, workload, speed=1000.0)
    finally:
        fresh.stop()
    assert report["divergent"] == 1
    assert report["bit_identical"] is False
    div = report["divergences"][0]
    assert div["kind"] == "token"
    assert div["first_divergent_token"] == 3
    assert m.get("app_replay_divergence").get() == 1.0


def test_replay_closed_loop_mode():
    text, _ = _capture_workload(seed=23, n_reqs=4, gen=8)
    workload = parse_workload(text)
    fresh = demo_llama_engine(EngineConfig(max_batch=2, max_seq=128,
                                           seed=23))
    try:
        report = replay_workload(fresh, workload, closed_loop=2,
                                 timeout_s=120.0)
    finally:
        fresh.stop()
    assert report["mode"] == "closed-loop-2"
    assert report["divergent"] == 0 and report["compared"] == 4


# --------------------------------------------------------- HTTP surface
@pytest.fixture(scope="module")
def workload_app():
    engine = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                            seed=0))

    def build(app):
        app.enable_api_key_auth(key_names={"alpha-key": "team-alpha",
                                           "beta-key": "team-beta"})
        app.serve_model("llm", engine, ByteTokenizer())

    with AppRunner(build=build) as app:
        yield app


AUTH = {"X-Api-Key": "alpha-key"}


def _chat(app, key, prompt, n=4):
    status, _, data = app.request(
        "POST", "/chat",
        {"prompt": prompt, "max_tokens": n, "temperature": 0.0},
        headers={"X-Api-Key": key})
    assert status == 201, (status, data[:200])
    return json.loads(data)["data"]


def test_workload_endpoints_e2e(workload_app):
    app = workload_app
    # arm capture, drive traffic from two tenants, stop, download
    status, _, data = app.request("POST", "/debug/workload/start",
                                  headers=AUTH)
    assert status in (200, 201), (status, data[:200])
    _chat(app, "alpha-key", "workload alpha one")
    _chat(app, "beta-key", "workload beta one")
    status, _, data = app.request("POST", "/debug/workload/stop",
                                  headers=AUTH)
    assert status in (200, 201), status
    assert json.loads(data)["data"]["workload"]["records"] == 2

    status, headers, data = app.request("GET", "/debug/workload",
                                        headers=AUTH)
    assert status == 200, status
    assert "application/jsonl" in headers.get("Content-Type", "")
    lines = [json.loads(ln) for ln in data.decode().splitlines()]
    header, records = lines[0], lines[1:]
    assert header["format"] == "gofr-workload"
    assert header["version"] == WORKLOAD_VERSION
    assert len(records) == 2
    assert {r["tenant"] for r in records} == {"team-alpha", "team-beta"}
    assert all(r["status"] == "ok" and r["completion_tokens"]
               for r in records)

    # ?n= keeps the last n records
    status, _, data = app.request("GET", "/debug/workload?n=1",
                                  headers=AUTH)
    assert len(data.decode().strip().splitlines()) == 2  # header + 1

    # the downloaded file replays through the driver end to end
    workload = parse_workload(data.decode())
    assert len(workload["records"]) == 1


def test_workload_endpoint_input_hardening(workload_app):
    app = workload_app
    # garbage n -> 400 on BOTH debug surfaces
    for path in ("/debug/workload?n=zzz", "/debug/engine?n=zzz",
                 "/debug/workload?n=1.5", "/debug/engine?n=%20"):
        status, _, data = app.request("GET", path, headers=AUTH)
        assert status == 400, (path, status, data[:200])
    # negative and absurd values clamp instead of erroring
    for path in ("/debug/workload?n=-5", "/debug/engine?n=-1",
                 "/debug/workload?n=999999999999",
                 "/debug/engine?n=999999999999"):
        status, _, _ = app.request("GET", path, headers=AUTH)
        assert status == 200, (path, status)
    # unknown model -> 404
    status, _, _ = app.request("GET", "/debug/workload?model=nope",
                               headers=AUTH)
    assert status == 404
    status, _, _ = app.request("POST", "/debug/workload/start",
                               body={"redact": True},
                               headers={**AUTH,
                                        "Content-Type":
                                        "application/json"})
    assert status in (200, 201)
    # leave capture disarmed for other tests
    app.request("POST", "/debug/workload/stop", headers=AUTH)


def test_workload_endpoints_respect_app_auth(workload_app):
    app = workload_app
    for method, path in (("GET", "/debug/workload"),
                         ("POST", "/debug/workload/start"),
                         ("POST", "/debug/workload/stop"),
                         ("GET", "/debug/engine")):
        status, _, _ = app.request(method, path)
        assert status == 401, (method, path, status)
