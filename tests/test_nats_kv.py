"""NATS-KV over JetStream buckets (reference datasource/kv-store/nats):
set = stream capture, get = direct last_by_subj, delete = KV-Operation
DEL tombstone via HPUB — real bytes against the mini JetStream server."""

import asyncio
import threading

import pytest

from gofr_tpu.datasource.kv import KeyNotFound, KVError
from gofr_tpu.datasource.nats_kv import NATSKV
from gofr_tpu.pubsub.jetstream import MiniJetStreamServer


@pytest.fixture(scope="module")
def server():
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    srv = MiniJetStreamServer()
    asyncio.run_coroutine_threadsafe(srv.start(), loop).result(10)
    yield srv
    asyncio.run_coroutine_threadsafe(srv.close(), loop).result(10)
    loop.call_soon_threadsafe(loop.stop)
    thread.join(5)


@pytest.fixture()
def kv(server):
    store = NATSKV(port=server.port, bucket="app")
    store.connect()
    yield store
    store.close()


def test_set_get_roundtrip(kv):
    kv.set("greeting", "hello")
    assert kv.get("greeting") == "hello"
    kv.set("greeting", "hello again")        # last write wins
    assert kv.get("greeting") == "hello again"


def test_missing_key(kv):
    with pytest.raises(KeyNotFound):
        kv.get("never-written")


def test_delete_writes_tombstone(kv, server):
    kv.set("doomed", "v")
    assert kv.get("doomed") == "v"
    kv.delete("doomed")
    with pytest.raises(KeyNotFound):
        kv.get("doomed")
    # the tombstone is a real message with the KV-Operation header —
    # deletion without destroying history (nats KV semantics)
    subject, payload, hdrs = server.streams["KV_app"].messages[-1]
    assert subject == "$KV.app.doomed"
    assert payload == b""
    assert b"KV-Operation: DEL" in hdrs
    # and the key is writable again afterwards
    kv.set("doomed", "reborn")
    assert kv.get("doomed") == "reborn"


def test_dotted_keys_are_distinct(kv):
    kv.set("cfg.db.host", "a")
    kv.set("cfg.db.port", "b")
    assert kv.get("cfg.db.host") == "a"
    assert kv.get("cfg.db.port") == "b"


def test_invalid_names_rejected(server):
    with pytest.raises(KVError):
        NATSKV(port=server.port, bucket="has.dot")
    store = NATSKV(port=server.port, bucket="ok")
    store.connect()
    try:
        for bad in ("", "a b", "star*", ".leading", "trailing."):
            with pytest.raises(KVError):
                store.set(bad, "x")
    finally:
        store.close()


def test_buckets_are_isolated(server):
    a = NATSKV(port=server.port, bucket="tenant_a")
    b = NATSKV(port=server.port, bucket="tenant_b")
    a.connect()
    b.connect()
    try:
        a.set("k", "from-a")
        b.set("k", "from-b")
        assert a.get("k") == "from-a"
        assert b.get("k") == "from-b"
    finally:
        a.close()
        b.close()


def test_health_and_container_wiring(server):
    from gofr_tpu.config.env import DictConfig
    from gofr_tpu.container.container import Container

    container = Container(DictConfig({"APP_NAME": "kvtest"}))
    store = container.add_kv_store(NATSKV(port=server.port, bucket="health"))
    store.connect()
    try:
        store.set("k", "v")
        assert store.get("k") == "v"
        assert store.health_check()["status"] == "UP"
        assert container.kv is store
    finally:
        store.close()
    assert store.health_check()["status"] == "DOWN"
