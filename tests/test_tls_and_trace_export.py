"""TLS serving (CERT_FILE/KEY_FILE) and network trace export
(OTLP/zipkin) — VERDICT r2 item 8, matching reference
http_server.go:82 and otel.go:131-151."""

import datetime
import json
import ssl
import threading
import time
import urllib.request

import pytest

from gofr_tpu.config.env import DictConfig
from gofr_tpu.container.container import Container
from gofr_tpu.tracing.export import OTLPHTTPExporter, ZipkinExporter
from gofr_tpu.tracing.tracer import Tracer

from .apputil import AppRunner


# ----------------------------------------------------------------- helpers

def _self_signed_cert(tmp_path):
    """Generate a throwaway self-signed cert/key (pure stdlib is not
    enough — use the cryptography package if present, else skip)."""
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID
    except ImportError:
        pytest.skip("cryptography package not available")
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName("localhost")]), critical=False)
            .sign(key, hashes.SHA256()))
    cert_file = tmp_path / "cert.pem"
    key_file = tmp_path / "key.pem"
    cert_file.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_file.write_bytes(key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption()))
    return str(cert_file), str(key_file)


class _CollectorHandler:
    """Tiny HTTP sink standing in for an OTLP/zipkin collector."""

    def __init__(self):
        import http.server
        import socketserver
        received = self.received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                size = int(self.headers.get("Content-Length", 0))
                received.append((self.path,
                                 json.loads(self.rfile.read(size))))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        self.server = socketserver.TCPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


# --------------------------------------------------------------------- TLS

def test_tls_serving_end_to_end(tmp_path):
    cert_file, key_file = _self_signed_cert(tmp_path)
    with AppRunner(config={"CERT_FILE": cert_file,
                           "KEY_FILE": key_file}) as runner:
        runner.app.get("/hello", lambda ctx: {"ok": True})
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        resp = urllib.request.urlopen(
            f"https://localhost:{runner.port}/hello", context=ctx,
            timeout=10)
        body = json.load(resp)
        assert body["data"] == {"ok": True}
        # plaintext against the TLS port must fail, not fall through
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://localhost:{runner.port}/hello", timeout=5)


def test_invalid_tls_config_fails_startup(tmp_path):
    """A bad cert must fail boot, never silently serve cleartext on a
    port clients expect to be HTTPS (ListenAndServeTLS semantics)."""
    import asyncio

    from gofr_tpu.app import App
    from gofr_tpu.config.env import DictConfig

    bad = tmp_path / "nope.pem"
    app = App(config=DictConfig({"APP_NAME": "tls-bad", "HTTP_PORT": "0",
                                 "METRICS_PORT": "0",
                                 "GOFR_TELEMETRY": "false",
                                 "CERT_FILE": str(bad),
                                 "KEY_FILE": str(bad)}))
    app.get("/hello", lambda ctx: "hi")
    with pytest.raises(RuntimeError, match="CERT_FILE"):
        asyncio.run(app.start())


# ------------------------------------------------------------ trace export

def _wait_for(predicate, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return False


def test_otlp_exporter_posts_spans():
    collector = _CollectorHandler()
    exporter = OTLPHTTPExporter(f"http://127.0.0.1:{collector.port}",
                                service_name="svc",
                                flush_interval_s=0.1)
    tracer = Tracer(service_name="svc", exporter=exporter)
    try:
        with tracer.start_span("GET /users") as span:
            span.set_attribute("http.status", 200)
        assert _wait_for(lambda: collector.received)
        path, payload = collector.received[0]
        assert path == "/v1/traces"
        rs = payload["resourceSpans"][0]
        attrs = rs["resource"]["attributes"]
        assert {"key": "service.name",
                "value": {"stringValue": "svc"}} in attrs
        span_json = rs["scopeSpans"][0]["spans"][0]
        assert span_json["name"] == "GET /users"
        assert len(span_json["traceId"]) == 32
        assert len(span_json["spanId"]) == 16
        assert int(span_json["endTimeUnixNano"]) >= \
            int(span_json["startTimeUnixNano"])
    finally:
        exporter.close()
        collector.close()


def test_zipkin_exporter_posts_spans():
    collector = _CollectorHandler()
    exporter = ZipkinExporter(f"http://127.0.0.1:{collector.port}",
                              service_name="svc", flush_interval_s=0.1)
    tracer = Tracer(service_name="svc", exporter=exporter)
    try:
        with tracer.start_span("work"):
            pass
        assert _wait_for(lambda: collector.received)
        path, payload = collector.received[0]
        assert path == "/api/v2/spans"
        assert payload[0]["name"] == "work"
        assert payload[0]["localEndpoint"] == {"serviceName": "svc"}
        assert payload[0]["duration"] >= 1
    finally:
        exporter.close()
        collector.close()


def test_exporter_survives_dead_collector():
    exporter = OTLPHTTPExporter("http://127.0.0.1:1",  # nothing listens
                                flush_interval_s=0.05, timeout_s=0.2)
    tracer = Tracer(service_name="svc", exporter=exporter)
    with tracer.start_span("doomed"):
        pass
    assert _wait_for(lambda: exporter.dropped >= 1)
    exporter.close()


def test_container_wires_network_exporters():
    c = Container.create(DictConfig({
        "APP_NAME": "traced", "TRACE_EXPORTER": "otlp",
        "TRACER_URL": "http://127.0.0.1:4318"}))
    assert isinstance(c.tracer.exporter, OTLPHTTPExporter)
    c.tracer.exporter.close()

    c = Container.create(DictConfig({
        "APP_NAME": "traced", "TRACE_EXPORTER": "zipkin",
        "TRACER_HOST": "tempo.internal"}))
    assert isinstance(c.tracer.exporter, ZipkinExporter)
    assert c.tracer.exporter.endpoint == "http://tempo.internal:9411"
    c.tracer.exporter.close()
