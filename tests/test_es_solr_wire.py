"""Elasticsearch and Solr HTTP wire clients against their mini servers."""

import pytest

from gofr_tpu.datasource.document import DocumentNotFound
from gofr_tpu.datasource.es_wire import (ElasticsearchWire, ESWireError,
                                         MiniESServer)
from gofr_tpu.datasource.solr_wire import MiniSolrServer, SolrWire


@pytest.fixture(scope="module")
def es():
    srv = MiniESServer()
    srv.start()
    client = ElasticsearchWire(endpoint=f"127.0.0.1:{srv.port}")
    client.connect()
    yield client
    srv.close()


@pytest.fixture(scope="module")
def solr():
    srv = MiniSolrServer()
    srv.start()
    client = SolrWire(endpoint=f"127.0.0.1:{srv.port}")
    client.connect()
    yield client
    srv.close()


# ---------------------------------------------------------------- ES

def test_es_index_get_delete(es):
    es.index("articles", "a1", {"title": "Ring attention on TPU"})
    doc = es.get("articles", "a1")
    assert doc["title"] == "Ring attention on TPU"
    assert doc["_id"] == "a1"
    es.delete("articles", "a1")
    with pytest.raises(DocumentNotFound):
        es.get("articles", "a1")
    with pytest.raises(DocumentNotFound):
        es.delete("articles", "a1")


def test_es_match_search_ranks_by_overlap(es):
    es.index("posts", "1", {"body": "sharding large language models"})
    es.index("posts", "2", {"body": "sharding models over device mesh"})
    es.index("posts", "3", {"body": "cooking pasta"})
    result = es.search("posts", {"match": {"body": "sharding models"}})
    hits = result["hits"]["hits"]
    assert [h["_id"] for h in hits[:2]] == ["1", "2"] or \
        [h["_id"] for h in hits[:2]] == ["2", "1"]
    assert all(h["_id"] != "3" for h in hits)
    assert result["hits"]["total"]["value"] == 2


def test_es_term_and_match_all(es):
    es.index("users", "u1", {"role": "admin"})
    es.index("users", "u2", {"role": "dev"})
    term = es.search("users", {"term": {"role": "admin"}})
    assert [h["_id"] for h in term["hits"]["hits"]] == ["u1"]
    everything = es.search("users", {"match_all": {}})
    assert everything["hits"]["total"]["value"] == 2


def test_es_bulk(es):
    n = es.bulk("logs", [(str(i), {"n": i}) for i in range(5)])
    assert n == 5
    assert es.get("logs", "3")["n"] == 3


def test_es_unsupported_query_is_an_error(es):
    with pytest.raises(ESWireError):
        es.search("posts", {"fuzzy": {"body": "x"}})


def test_es_health(es):
    assert es.health_check()["status"] == "UP"
    down = ElasticsearchWire(endpoint="127.0.0.1:1")
    assert down.health_check()["status"] == "DOWN"


# ---------------------------------------------------------------- Solr

def test_solr_add_and_select(solr):
    solr.add("books", [{"id": "b1", "title": "Systems on TPU"},
                       {"id": "b2", "title": "Cooking for devs"}])
    result = solr.search("books", "title:Systems on TPU")
    assert result["response"]["numFound"] == 1
    everything = solr.search("books", "*:*")
    assert everything["response"]["numFound"] == 2


def test_solr_bare_text_search(solr):
    solr.add("notes", [{"id": "n1", "text": "mesh sharding plan"},
                       {"id": "n2", "text": "grocery list"}])
    result = solr.search("notes", "sharding")
    assert [d["id"] for d in result["response"]["docs"]] == ["n1"]


def test_solr_delete(solr):
    solr.add("tmp", [{"id": "t1", "v": 1}])
    assert solr.search("tmp", "*:*")["response"]["numFound"] == 1
    solr.delete("tmp", "t1")
    assert solr.search("tmp", "*:*")["response"]["numFound"] == 0


def test_solr_health(solr):
    health = solr.health_check()
    assert health["status"] == "UP"
    assert health["details"]["solr_version"].startswith("9")
    down = SolrWire(endpoint="127.0.0.1:1")
    assert down.health_check()["status"] == "DOWN"
