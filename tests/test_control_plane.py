"""Multi-host serving control plane over real HTTP: join/heartbeat/
topology routes on a framework App, worker agents on the service
client, failure detection, elastic rank reassignment."""

import time

import pytest

from gofr_tpu.serving.control_plane import (ControlPlaneLeader,
                                            ShardAssignment, WorkerAgent)

from .apputil import AppRunner


def make_leader(**kw):
    leader = ControlPlaneLeader(coordinator="10.0.0.1:8476", **kw)

    def build(app):
        leader.install(app)
    return leader, build


def agent(runner, host_id, **kw):
    return WorkerAgent(f"http://127.0.0.1:{runner.port}",
                       host_id=host_id, n_devices=4,
                       heartbeat_interval_s=0.1, **kw)


def test_join_assigns_contiguous_ranks_sorted_by_host_id():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        c = agent(runner, "host-c")
        a = agent(runner, "host-a")
        b = agent(runner, "host-b")
        c.join()
        a.join()
        b.join()
        # ranks follow sorted host ids, regardless of join order
        assert (a.assignment.rank, b.assignment.rank) == (0, 1)
        assert b.assignment.world_size == 3
        # earlier joiners see their new rank at the next heartbeat
        c._heartbeat_once()
        assert c.assignment.rank == 2
        assert c.assignment.world_size == 3
        assert leader.generation == 3  # one bump per join


def test_assignment_feeds_jax_distributed():
    assignment = ShardAssignment(host_id="h", rank=1, world_size=4,
                                 n_devices=4, generation=7,
                                 coordinator="10.0.0.1:8476")
    assert assignment.jax_initialize_args() == {
        "coordinator_address": "10.0.0.1:8476",
        "num_processes": 4, "process_id": 1}


def test_generation_change_invokes_on_assignment():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        seen = []
        a = agent(runner, "a", on_assignment=lambda s: seen.append(
            (s.generation, s.rank, s.world_size)))
        a.join()
        assert seen == [(1, 0, 1)]
        b = agent(runner, "b")
        b.join()
        a._heartbeat_once()      # same assignment, new generation
        assert seen[-1] == (2, 0, 2)
        a._heartbeat_once()      # no change: callback not re-invoked
        assert len(seen) == 2


def test_dead_host_is_evicted_and_ranks_close_up():
    leader, build = make_leader(heartbeat_interval_s=0.1,
                                eviction_misses=2)
    with AppRunner(build=build) as runner:
        a = agent(runner, "a")
        b = agent(runner, "b")
        a.start()                # heartbeats on a thread
        b.join()                 # joins but never heartbeats: "dies"
        deadline = time.time() + 5
        while time.time() < deadline:
            if leader.topology()["world_size"] == 1 \
                    and a.assignment.world_size == 1:
                break
            time.sleep(0.05)
        a.stop()
        topo = leader.topology()
        assert topo["world_size"] == 1 and "a" in topo["members"]
        assert a.assignment.rank == 0 and a.assignment.world_size == 1


def test_evicted_worker_rejoins_on_heartbeat():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        a = agent(runner, "a")
        a.join()
        leader.evict("a")
        generation = leader.generation
        a._heartbeat_once()      # 409 -> automatic rejoin
        assert leader.topology()["world_size"] == 1
        assert a.assignment.generation == generation + 1


def test_health_gossip_aggregates_to_leader():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        healthy = agent(runner, "good",
                        health_source=lambda: {"status": "UP"})
        sick = agent(runner, "bad",
                     health_source=lambda: {"status": "DOWN",
                                            "error": "HBM ECC"})
        healthy.join()
        sick.join()
        healthy._heartbeat_once()
        sick._heartbeat_once()
        topo = leader.topology()
        assert topo["members"]["bad"]["health"]["error"] == "HBM ECC"
        health = leader.health_check()
        assert health["status"] == "DEGRADED"
        assert health["details"]["degraded_hosts"] == ["bad"]


def test_leader_health_rides_the_app_health_endpoint():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        sick = agent(runner, "bad",
                     health_source=lambda: {"status": "DOWN"})
        sick.join()
        status, body = runner.get_json("/.well-known/health")
        checks = body["data"]["checks"]
        assert checks["control_plane"]["status"] == "DEGRADED"
        assert body["data"]["status"] == "DEGRADED"


def test_worker_survives_leader_down_at_start():
    """start() before the leader exists must retry, not die."""
    worker = WorkerAgent("http://127.0.0.1:1", host_id="early",
                         heartbeat_interval_s=0.1)
    worker.start()                      # leader unreachable: no raise
    try:
        assert worker.assignment is None
        leader, build = make_leader()
        with AppRunner(build=build) as runner:
            # point the (already running) agent at the live leader
            from gofr_tpu.service import new_http_service
            worker._service = new_http_service(
                f"http://127.0.0.1:{runner.port}")
            deadline = time.time() + 5
            while time.time() < deadline and worker.assignment is None:
                time.sleep(0.05)
            assert worker.assignment is not None
            assert worker.assignment.rank == 0
    finally:
        worker.stop()


def test_topology_route_over_http():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        agent(runner, "x").join()
        status, body = runner.get_json("/control/topology")
        assert status == 200
        topo = body["data"]
        assert topo["world_size"] == 1
        assert topo["members"]["x"]["rank"] == 0
