"""Multi-host serving control plane over real HTTP: join/heartbeat/
topology routes on a framework App, worker agents on the service
client, failure detection, elastic rank reassignment."""

import time

import pytest

from gofr_tpu.serving.control_plane import (ControlPlaneLeader,
                                            ShardAssignment, WorkerAgent)

from .apputil import AppRunner


def make_leader(**kw):
    leader = ControlPlaneLeader(coordinator="10.0.0.1:8476", **kw)

    def build(app):
        leader.install(app)
    return leader, build


def agent(runner, host_id, **kw):
    return WorkerAgent(f"http://127.0.0.1:{runner.port}",
                       host_id=host_id, n_devices=4,
                       heartbeat_interval_s=0.1, **kw)


def test_join_assigns_contiguous_ranks_sorted_by_host_id():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        c = agent(runner, "host-c")
        a = agent(runner, "host-a")
        b = agent(runner, "host-b")
        c.join()
        a.join()
        b.join()
        # ranks follow sorted host ids, regardless of join order
        assert (a.assignment.rank, b.assignment.rank) == (0, 1)
        assert b.assignment.world_size == 3
        # earlier joiners see their new rank at the next heartbeat
        c._heartbeat_once()
        assert c.assignment.rank == 2
        assert c.assignment.world_size == 3
        assert leader.generation == 3  # one bump per join


def test_assignment_feeds_jax_distributed():
    assignment = ShardAssignment(host_id="h", rank=1, world_size=4,
                                 n_devices=4, generation=7,
                                 coordinator="10.0.0.1:8476")
    assert assignment.jax_initialize_args() == {
        "coordinator_address": "10.0.0.1:8476",
        "num_processes": 4, "process_id": 1}


def test_generation_change_invokes_on_assignment():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        seen = []
        a = agent(runner, "a", on_assignment=lambda s: seen.append(
            (s.generation, s.rank, s.world_size)))
        a.join()
        assert seen == [(1, 0, 1)]
        b = agent(runner, "b")
        b.join()
        a._heartbeat_once()      # same assignment, new generation
        assert seen[-1] == (2, 0, 2)
        a._heartbeat_once()      # no change: callback not re-invoked
        assert len(seen) == 2


def test_dead_host_is_evicted_and_ranks_close_up():
    leader, build = make_leader(heartbeat_interval_s=0.1,
                                eviction_misses=2)
    with AppRunner(build=build) as runner:
        a = agent(runner, "a")
        b = agent(runner, "b")
        a.start()                # heartbeats on a thread
        b.join()                 # joins but never heartbeats: "dies"
        deadline = time.time() + 5
        while time.time() < deadline:
            if leader.topology()["world_size"] == 1 \
                    and a.assignment.world_size == 1:
                break
            time.sleep(0.05)
        a.stop()
        topo = leader.topology()
        assert topo["world_size"] == 1 and "a" in topo["members"]
        assert a.assignment.rank == 0 and a.assignment.world_size == 1


def test_evicted_worker_rejoins_on_heartbeat():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        a = agent(runner, "a")
        a.join()
        leader.evict("a")
        generation = leader.generation
        a._heartbeat_once()      # 409 -> automatic rejoin
        assert leader.topology()["world_size"] == 1
        assert a.assignment.generation == generation + 1


def test_health_gossip_aggregates_to_leader():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        healthy = agent(runner, "good",
                        health_source=lambda: {"status": "UP"})
        sick = agent(runner, "bad",
                     health_source=lambda: {"status": "DOWN",
                                            "error": "HBM ECC"})
        healthy.join()
        sick.join()
        healthy._heartbeat_once()
        sick._heartbeat_once()
        topo = leader.topology()
        assert topo["members"]["bad"]["health"]["error"] == "HBM ECC"
        health = leader.health_check()
        assert health["status"] == "DEGRADED"
        assert health["details"]["degraded_hosts"] == ["bad"]


def test_leader_health_rides_the_app_health_endpoint():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        sick = agent(runner, "bad",
                     health_source=lambda: {"status": "DOWN"})
        sick.join()
        status, body = runner.get_json("/.well-known/health")
        checks = body["data"]["checks"]
        assert checks["control_plane"]["status"] == "DEGRADED"
        assert body["data"]["status"] == "DEGRADED"


def test_worker_survives_leader_down_at_start():
    """start() before the leader exists must retry, not die."""
    worker = WorkerAgent("http://127.0.0.1:1", host_id="early",
                         heartbeat_interval_s=0.1)
    worker.start()                      # leader unreachable: no raise
    try:
        assert worker.assignment is None
        leader, build = make_leader()
        with AppRunner(build=build) as runner:
            # point the (already running) agent at the live leader
            from gofr_tpu.service import new_http_service
            worker._service = new_http_service(
                f"http://127.0.0.1:{runner.port}")
            deadline = time.time() + 5
            while time.time() < deadline and worker.assignment is None:
                time.sleep(0.05)
            assert worker.assignment is not None
            assert worker.assignment.rank == 0
    finally:
        worker.stop()


def test_topology_route_over_http():
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        agent(runner, "x").join()
        status, body = runner.get_json("/control/topology")
        assert status == 200
        topo = body["data"]
        assert topo["world_size"] == 1
        assert topo["members"]["x"]["rank"] == 0


# ------------------------------------------- failure-path transitions
# (fleet metrics/generation counters asserted through each one)

def _fleet_gauge(leader, name, **labels):
    return leader.metrics.get(name).get(**labels)


def test_stale_generation_rejoin_moves_generation_counters():
    """An evicted host's next heartbeat is a 409 -> automatic rejoin;
    the generation gauge tracks every bump (evict + rejoin) and the
    eviction counter records the reason."""
    leader, build = make_leader()
    with AppRunner(build=build) as runner:
        a = agent(runner, "a")
        b = agent(runner, "b")
        a.join()
        b.join()
        assert _fleet_gauge(leader, "app_fleet_generation") == 2.0
        assert _fleet_gauge(leader, "app_fleet_world_size") == 2.0
        leader.evict("a", reason="manual")
        assert _fleet_gauge(leader, "app_fleet_generation") == 3.0
        assert _fleet_gauge(leader, "app_fleet_world_size") == 1.0
        assert _fleet_gauge(leader, "app_fleet_evictions",
                            reason="manual") == 1.0
        a._heartbeat_once()      # 409 -> rejoin with a fresh assignment
        assert a.assignment is not None
        assert a.assignment.generation == 4
        assert _fleet_gauge(leader, "app_fleet_generation") == 4.0
        assert _fleet_gauge(leader, "app_fleet_world_size") == 2.0
        # a worker heartbeating with a STALE generation number (but
        # still a member) is told changed=True, no eviction involved
        b.assignment.generation = 1
        b._heartbeat_once()
        assert b.assignment.generation == 4


def test_eviction_then_regeneration_reranks_and_counts():
    """Heartbeat-timeout eviction (the sweeper path): the survivor
    re-ranks, and the eviction counter carries reason=heartbeat_timeout
    — distinct from degraded/manual evictions."""
    leader, build = make_leader(heartbeat_interval_s=0.1,
                                eviction_misses=2)
    with AppRunner(build=build) as runner:
        live = agent(runner, "live")
        dead = agent(runner, "dead")
        live.start()
        dead.join()              # joins, never heartbeats again
        deadline = time.time() + 5
        while time.time() < deadline:
            if leader.topology()["world_size"] == 1 \
                    and live.assignment.world_size == 1:
                break
            time.sleep(0.05)
        live.stop()
        assert leader.topology()["world_size"] == 1
        assert live.assignment.rank == 0
        assert _fleet_gauge(leader, "app_fleet_evictions",
                            reason="heartbeat_timeout") == 1.0
        assert _fleet_gauge(leader, "app_fleet_world_size") == 1.0
        assert _fleet_gauge(leader, "app_fleet_generation") \
            == leader.generation


def test_degraded_heartbeat_evicts_via_control_route():
    """A heartbeat gossiping DEGRADED (the stall-watchdog escalation)
    is evicted immediately over the HTTP route; DOWN keeps gossiping
    (a dead engine stays visible, only a wedged one is cut)."""
    from gofr_tpu.serving.control_plane import FleetConfig
    leader, build = make_leader(fleet=FleetConfig(evict_degraded=True))
    with AppRunner(build=build) as runner:
        state = {"status": "UP"}
        w = agent(runner, "w", health_source=lambda: dict(state))
        other = agent(runner, "other")
        w.join()
        other.join()
        generation = leader.generation
        state["status"] = "DEGRADED"
        state["stalled_for_s"] = 42.0
        w._heartbeat_once()
        assert w.assignment is None
        assert leader.generation == generation + 1
        assert leader.topology()["world_size"] == 1
        assert _fleet_gauge(leader, "app_fleet_evictions",
                            reason="degraded") == 1.0
        other._heartbeat_once()
        assert other.assignment.rank == 0
        # DOWN gossip does NOT evict (observability, not amputation)
        state["status"] = "DOWN"
        del state["stalled_for_s"]
        w.join()                 # operator-forced rejoin works
        w._heartbeat_once()
        assert w.assignment is not None
        assert leader.topology()["world_size"] == 2
        assert leader.health_check()["status"] == "DEGRADED"
