"""Prefix caching on the paged KV layout: page-aligned prompt prefixes
are retained at retire, attached by reference to later requests with
the same prefix (the system-prompt pattern), and only the suffix is
computed — with greedy outputs identical to the uncached path."""

import time

import numpy as np

from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine

SYSTEM = list(np.random.RandomState(3).randint(3, 200, size=33))
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=4)


def _cfg(**kw):
    base = dict(max_batch=2, max_seq=128, prefill_buckets=(8,),
                kv_layout="paged", page_size=8, seed=5)
    base.update(kw)
    return EngineConfig(**base)


def _run(engine, prompt, params=GREEDY):
    req = engine.submit_sync(prompt, params)
    assert req.error is None, req.error
    return list(req.generated)


def test_hit_reuses_pages_and_matches_uncached():
    engine = demo_llama_engine(_cfg())
    engine.start()
    try:
        first = _run(engine, SYSTEM + [7, 8, 9])
        assert engine.stats["prefix_hits"] == 0
        free_before = len(engine._free_pages)
        second = _run(engine, SYSTEM + [7, 8, 9])
        assert engine.stats["prefix_hits"] == 1
        assert second == first  # greedy determinism across the cache
        # a different suffix under the same system prompt also hits
        third = _run(engine, SYSTEM + [50, 60])
        assert engine.stats["prefix_hits"] == 2
        assert len(engine._free_pages) <= free_before + 2
    finally:
        engine.stop()

    # ground truth: an engine with the cache disabled
    plain = demo_llama_engine(_cfg(prefix_cache=False))
    plain.start()
    try:
        assert _run(plain, SYSTEM + [7, 8, 9]) == first
        assert plain.stats["prefix_hits"] == 0
    finally:
        plain.stop()


def test_cache_entries_evict_under_pool_pressure():
    # pool of 16 pages (128 rows); budget defaults to 4 pages
    engine = demo_llama_engine(_cfg(kv_pages=16))
    engine.start()
    try:
        _run(engine, SYSTEM + [1])            # registers a 4-page prefix
        assert engine._cached_pages >= 1
        # a giant request needs nearly the whole pool: cached entries
        # must evict rather than starve it
        big = list(np.random.RandomState(8).randint(3, 200, size=110))
        out = _run(engine, big)
        assert len(out) == 4
    finally:
        engine.stop()


def test_shared_pages_survive_one_sharers_retirement():
    """Two hits on the same prefix, interleaved retirement: refcounts
    must keep the pages valid for the second request and the cache."""
    engine = demo_llama_engine(_cfg())
    engine.start()
    try:
        baseline = _run(engine, SYSTEM + [7])
        a = engine.submit(SYSTEM + [7],
                          SamplingParams(temperature=0.0,
                                         max_new_tokens=24))
        b = engine.submit(SYSTEM + [7],
                          SamplingParams(temperature=0.0,
                                         max_new_tokens=2))
        deadline = time.time() + 60
        while time.time() < deadline and not all(
                r.finished_at is not None or r.error for r in (a, b)):
            time.sleep(0.01)
        assert a.error is None and b.error is None
        assert len(a.generated) == 24 and len(b.generated) == 2
        assert a.generated[:4] == baseline  # same prefix KV, same tokens
        # allocator sanity: no page double-freed or leaked
        refs = engine._page_refs
        held = sum(int(engine._slot_pages[i])
                   for i in range(engine.config.max_batch))
        assert held == 0
        assert int(refs.sum()) == engine._cached_pages
        assert len(engine._free_pages) \
            == engine._n_pages - engine._cached_pages
    finally:
        engine.stop()


def test_long_prompt_hit_skips_shared_chunks():
    """Prefix reuse composes with the long-prompt walk: the second
    request's walk starts at the shared boundary (fewer prefill calls)
    and still matches the first run's tokens."""
    engine = demo_llama_engine(_cfg())
    engine.start()
    try:
        long_prompt = SYSTEM + list(range(40))   # 73 tokens, > pool bucket
        first = _run(engine, long_prompt)
        calls_after_first = engine.stats["prefill_calls"]
        second = _run(engine, long_prompt)
        suffix_calls = engine.stats["prefill_calls"] - calls_after_first
        assert second == first
        assert engine.stats["prefix_hits"] >= 1
        # first run walked ceil(73/8)=10 chunks; the hit walks the
        # 9-token suffix: at most 3 calls
        assert suffix_calls <= 3, suffix_calls
    finally:
        engine.stop()


def test_attach_then_pool_exceed_does_not_corrupt_cache():
    """A cache hit whose full prompt can never fit the pool must fail
    WITHOUT leaking the attached shared pages into the slot (review
    regression: the next occupant would have scatter-written over the
    cached prefix KV)."""
    engine = demo_llama_engine(_cfg(kv_pages=8))  # 64-row pool
    engine.start()
    try:
        short = SYSTEM[:17]                 # registers a 2-page prefix
        baseline = _run(engine, short + [7])
        assert engine._cached_pages >= 1
        # same prefix, but a prompt the pool can never hold
        doomed = engine.submit_sync(
            short + list(range(80)),
            SamplingParams(temperature=0.0, max_new_tokens=2))
        assert doomed.error is not None and "kv pool" in doomed.error
        # the cached prefix must still be intact and reusable
        again = _run(engine, short + [7])
        assert again == baseline
        refs = engine._page_refs
        assert len(engine._free_pages) \
            == engine._n_pages - int((refs > 0).sum())
    finally:
        engine.stop()


def test_allocator_invariants_under_random_churn():
    """Hundreds of randomized submits — shared prefixes, long prompts,
    cancellations, pool pressure with preemption and cache eviction —
    then drain: every request resolves, and the refcount ledger
    balances exactly (free + referenced == pool; references == cache
    pins when idle)."""
    rng = np.random.RandomState(42)
    engine = demo_llama_engine(_cfg(max_batch=3, kv_pages=24,
                                    prefill_chunks_per_pass=1))
    engine.start()
    reqs = []
    try:
        prefixes = [list(rng.randint(3, 200, size=n)) for n in (17, 33)]
        for i in range(60):
            kind = rng.randint(4)
            if kind == 0:      # shared-prefix request
                prompt = prefixes[rng.randint(2)] \
                    + list(rng.randint(3, 200, size=rng.randint(1, 6)))
            elif kind == 1:    # long prompt (chunk walk)
                prompt = list(rng.randint(3, 200,
                                          size=rng.randint(40, 90)))
            else:              # short unique prompt
                prompt = list(rng.randint(3, 200,
                                          size=rng.randint(2, 12)))
            req = engine.submit(prompt, SamplingParams(
                temperature=0.0,
                max_new_tokens=int(rng.randint(1, 6))))
            reqs.append(req)
            if rng.rand() < 0.2:
                engine.cancel(req)
            if rng.rand() < 0.3:
                time.sleep(0.01)

        deadline = time.time() + 240
        while time.time() < deadline and any(
                r.finished_at is None and r.error is None for r in reqs):
            time.sleep(0.02)
        unresolved = [r for r in reqs
                      if r.finished_at is None and r.error is None]
        assert not unresolved, f"{len(unresolved)} requests never resolved"

        refs = engine._page_refs
        assert all(r is None for r in engine.active)
        assert int(engine._slot_pages.sum()) == 0
        assert len(engine._free_pages) \
            == engine._n_pages - int((refs > 0).sum())
        # at quiescence, the only references are the cache's pins
        cache_refs = sum(len(p) for p in engine._prefix_cache.values())
        assert int(refs.sum()) == cache_refs
        # no page is both free and referenced
        assert all(refs[p] == 0 for p in engine._free_pages)
    finally:
        engine.stop()
