"""Logger tests — level gating, JSON shape, trace injection, live level change."""

import io
import json

from gofr_tpu.logging import (
    DEBUG, ERROR, INFO, WARN,
    ContextLogger, Logger, MockLogger, level_from_string,
)
from gofr_tpu.logging.logger import reset_trace_context, set_trace_context


def test_level_gating():
    log = MockLogger(level=WARN)
    log.debug("d")
    log.info("i")
    log.warn("w")
    log.error("e")
    levels = [l["level"] for l in log.lines]
    assert levels == ["WARN", "ERROR"]


def test_json_shape_and_fields():
    log = MockLogger(level=DEBUG)
    log.info("hello", component="http", port=8000)
    rec = log.lines[0]
    assert rec["message"] == "hello"
    assert rec["component"] == "http"
    assert rec["port"] == 8000
    assert rec["time"].endswith("Z")


def test_percent_formatting():
    log = MockLogger()
    log.info("listening on %s:%d", "0.0.0.0", 8000)
    assert log.lines[0]["message"] == "listening on 0.0.0.0:8000"


def test_trace_context_injection():
    log = MockLogger()
    token = set_trace_context("a" * 32, "b" * 16)
    try:
        log.info("traced")
    finally:
        reset_trace_context(token)
    log.info("untraced")
    assert log.lines[0]["trace_id"] == "a" * 32
    assert log.lines[0]["span_id"] == "b" * 16
    assert "trace_id" not in log.lines[1]


def test_change_level_live_and_context_logger():
    base = MockLogger(level=INFO)
    ctx_log = ContextLogger(base)
    ctx_log.debug("hidden")
    base.change_level(DEBUG)
    ctx_log.debug("shown")
    assert [l["message"] for l in base.lines] == ["shown"]


def test_level_from_string():
    assert level_from_string("debug") == DEBUG
    assert level_from_string("ERROR") == ERROR
    assert level_from_string("bogus") == INFO


def test_pretty_mode_renders_colored_line():
    buf = io.StringIO()
    log = Logger(level=INFO, out=buf, err=buf, pretty=True)
    log.warn("careful")
    text = buf.getvalue()
    assert "WARN" in text and "careful" in text and "\x1b[" in text


def test_structured_message_dict():
    log = MockLogger()
    log.info({"event": "boot", "ok": True})
    assert log.lines[0]["message"] == {"event": "boot", "ok": True}


def test_thread_safety_no_interleaving():
    import threading
    log = MockLogger()

    def spam(i):
        for _ in range(50):
            log.info(f"msg-{i}")

    threads = [threading.Thread(target=spam, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(log.lines) == 200
    for rec in log.lines:
        json.dumps(rec)  # every line is valid standalone JSON


def test_fatal_exits():
    import pytest
    log = MockLogger()
    with pytest.raises(SystemExit):
        log.fatal("dead")
    assert log.lines[0]["level"] == "FATAL"
