"""Chunked prefill: prompts longer than the widest prefill bucket run
in bucket-width chunks against the growing cache — no truncation, and
greedy outputs identical to a single wide prefill."""

import numpy as np

from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine

PROMPT = list(np.random.RandomState(5).randint(3, 200, size=30))


def _generate(engine, prompt, n=6):
    engine.start()
    try:
        req = engine.submit_sync(prompt,
                                 SamplingParams(temperature=0.0,
                                                max_new_tokens=n))
        assert req.error is None, req.error
        return list(req.generated), len(req.prompt_tokens)
    finally:
        engine.stop()


def test_long_prompt_is_not_truncated_and_matches_wide_prefill():
    # narrow buckets: the 30-token prompt takes 4 chunks of 8
    chunked = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     seed=7))
    toks_chunked, kept_chunked = _generate(chunked, PROMPT)
    assert kept_chunked == len(PROMPT)  # nothing clamped

    # one wide bucket: the same prompt prefills in a single call
    wide = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(32,),
                     seed=7))
    toks_wide, kept_wide = _generate(wide, PROMPT)
    assert kept_wide == len(PROMPT)

    # same model weights (same init seed), greedy: identical output
    assert toks_chunked == toks_wide


def test_chunked_head_of_prompt_matters():
    """Truncation would drop the prompt head; chunked prefill must
    see it — two prompts differing only in their first token generate
    differently (greedy, tiny random model: near-certain)."""
    engine_a = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     seed=7))
    toks_a, _ = _generate(engine_a, PROMPT)
    engine_b = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     seed=7))
    changed = [(PROMPT[0] + 1) % 200] + PROMPT[1:]
    toks_b, _ = _generate(engine_b, changed)
    assert toks_a != toks_b


def test_chunked_interleaves_with_bucketed_admission():
    """Short and long prompts admitted together: both complete, the
    long one unclamped."""
    engine = demo_llama_engine(
        EngineConfig(max_batch=4, max_seq=128, prefill_buckets=(8,),
                     seed=3))
    engine.start()
    try:
        long_req = engine.submit(PROMPT, SamplingParams(
            temperature=0.0, max_new_tokens=4))
        short_req = engine.submit([5, 6, 7], SamplingParams(
            temperature=0.0, max_new_tokens=4))
        import time
        deadline = time.time() + 60
        while time.time() < deadline:
            if all(r.finished_at is not None or r.error
                   for r in (long_req, short_req)):
                break
            time.sleep(0.01)
        assert long_req.error is None and short_req.error is None
        assert len(long_req.generated) == 4
        assert len(short_req.generated) == 4
        assert len(long_req.prompt_tokens) == len(PROMPT)
    finally:
        engine.stop()


def test_paged_layout_keeps_the_clamp():
    """The paged pool has no chunked path (yet): long prompts clamp to
    the widest bucket, exactly the pre-chunking behavior — no crash,
    honest truncation."""
    engine = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     kv_layout="paged", seed=7))
    toks, kept = _generate(engine, PROMPT)
    assert kept == 8  # clamped to the widest bucket
    assert len(toks) == 6


def test_cancel_mid_chunk_walk_frees_the_slot():
    """A client that vanishes while its long prompt is mid-walk must
    release the reserved slot (the walk spans several engine passes
    with prefill_chunks_per_pass=1)."""
    import time

    engine = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     prefill_chunks_per_pass=1, seed=2))
    engine.start()
    try:
        req = engine.submit(PROMPT, SamplingParams(temperature=0.0,
                                                   max_new_tokens=50))
        engine.cancel(req)      # racing the walk is the point
        deadline = time.time() + 30
        while time.time() < deadline and req.finished_at is None:
            time.sleep(0.01)
        assert req.finished_at is not None
        deadline = time.time() + 10
        while time.time() < deadline and any(
                r is not None for r in engine.active):
            time.sleep(0.01)
        assert all(r is None for r in engine.active)
        # the engine still serves
        follow = engine.submit_sync([1, 2, 3], SamplingParams(
            temperature=0.0, max_new_tokens=3))
        assert follow.error is None and len(follow.generated) == 3
    finally:
        engine.stop()
