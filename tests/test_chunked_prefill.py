"""Chunked prefill: prompts longer than the widest prefill bucket run
in bucket-width chunks against the growing cache — no truncation, and
greedy outputs identical to a single wide prefill."""

import numpy as np

from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine

PROMPT = list(np.random.RandomState(5).randint(3, 200, size=30))


def _generate(engine, prompt, n=6):
    engine.start()
    try:
        req = engine.submit_sync(prompt,
                                 SamplingParams(temperature=0.0,
                                                max_new_tokens=n))
        assert req.error is None, req.error
        return list(req.generated), len(req.prompt_tokens)
    finally:
        engine.stop()


def test_long_prompt_is_not_truncated_and_matches_wide_prefill():
    # narrow buckets: the 30-token prompt takes 4 chunks of 8
    chunked = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     seed=7))
    toks_chunked, kept_chunked = _generate(chunked, PROMPT)
    assert kept_chunked == len(PROMPT)  # nothing clamped

    # one wide bucket: the same prompt prefills in a single call
    wide = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(32,),
                     seed=7))
    toks_wide, kept_wide = _generate(wide, PROMPT)
    assert kept_wide == len(PROMPT)

    # same model weights (same init seed), greedy: identical output
    assert toks_chunked == toks_wide


def test_chunked_head_of_prompt_matters():
    """Truncation would drop the prompt head; chunked prefill must
    see it — two prompts differing only in their first token generate
    differently (greedy, tiny random model: near-certain)."""
    engine_a = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     seed=7))
    toks_a, _ = _generate(engine_a, PROMPT)
    engine_b = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     seed=7))
    changed = [(PROMPT[0] + 1) % 200] + PROMPT[1:]
    toks_b, _ = _generate(engine_b, changed)
    assert toks_a != toks_b


def test_chunked_interleaves_with_bucketed_admission():
    """Short and long prompts admitted together: both complete, the
    long one unclamped."""
    engine = demo_llama_engine(
        EngineConfig(max_batch=4, max_seq=128, prefill_buckets=(8,),
                     seed=3))
    engine.start()
    try:
        long_req = engine.submit(PROMPT, SamplingParams(
            temperature=0.0, max_new_tokens=4))
        short_req = engine.submit([5, 6, 7], SamplingParams(
            temperature=0.0, max_new_tokens=4))
        import time
        deadline = time.time() + 60
        while time.time() < deadline:
            if all(r.finished_at is not None or r.error
                   for r in (long_req, short_req)):
                break
            time.sleep(0.01)
        assert long_req.error is None and short_req.error is None
        assert len(long_req.generated) == 4
        assert len(short_req.generated) == 4
        assert len(long_req.prompt_tokens) == len(PROMPT)
    finally:
        engine.stop()


def test_paged_layout_chunks_and_matches_slot_layout():
    """The paged pool walks long prompts too (gather view → chunk →
    scatter back): unclamped, and greedy-identical to the slot
    layout."""
    paged = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     kv_layout="paged", seed=7))
    toks_paged, kept = _generate(paged, PROMPT)
    assert kept == len(PROMPT)  # nothing clamped

    slot = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     seed=7))
    toks_slot, _ = _generate(slot, PROMPT)
    assert toks_paged == toks_slot


def test_cancel_mid_chunk_walk_frees_the_slot():
    """A client that vanishes while its long prompt is mid-walk must
    release the reserved slot (the walk spans several engine passes
    with prefill_chunks_per_pass=1)."""
    import time

    engine = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     prefill_chunks_per_pass=1, seed=2))
    engine.start()
    try:
        req = engine.submit(PROMPT, SamplingParams(temperature=0.0,
                                                   max_new_tokens=50))
        engine.cancel(req)      # racing the walk is the point
        deadline = time.time() + 30
        while time.time() < deadline and req.finished_at is None:
            time.sleep(0.01)
        assert req.finished_at is not None
        deadline = time.time() + 10
        while time.time() < deadline and any(
                r is not None for r in engine.active):
            time.sleep(0.01)
        assert all(r is None for r in engine.active)
        # the engine still serves
        follow = engine.submit_sync([1, 2, 3], SamplingParams(
            temperature=0.0, max_new_tokens=3))
        assert follow.error is None and len(follow.generated) == 3
    finally:
        engine.stop()


def test_paged_prompt_exceeding_pool_fails_cleanly():
    """A prompt that can never fit the page pool fails with a clear
    error instead of walking forever or crashing the loop."""
    engine = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     kv_layout="paged", kv_pages=4, page_size=8,
                     seed=1))
    engine.start()
    try:
        req = engine.submit_sync(PROMPT, SamplingParams(
            temperature=0.0, max_new_tokens=4))
        assert req.error is not None and "kv pool" in req.error
        # a fitting prompt still serves
        ok = engine.submit_sync([1, 2, 3], SamplingParams(
            temperature=0.0, max_new_tokens=3))
        assert ok.error is None and len(ok.generated) == 3
    finally:
        engine.stop()


def test_two_long_prompts_contend_for_the_pool():
    """Pool smaller than both walks: preemption-by-recompute plus the
    requeue machinery must land BOTH requests with exact token
    budgets (regression: double-requeue once emitted a bogus extra
    token; slot-holding walks once deadlocked the requeue drain)."""
    import time

    engine = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, prefill_buckets=(8,),
        kv_layout="paged", kv_pages=20, page_size=8,
        prefill_chunks_per_pass=1, seed=4))
    engine.start()
    try:
        a = engine.submit(list(range(3, 90)), SamplingParams(
            temperature=0.0, max_new_tokens=4))
        b = engine.submit(list(range(90, 175)), SamplingParams(
            temperature=0.0, max_new_tokens=4))
        deadline = time.time() + 240
        while time.time() < deadline:
            if all(r.finished_at is not None or r.error for r in (a, b)):
                break
            time.sleep(0.02)
        assert a.error is None and b.error is None, (a.error, b.error)
        assert len(a.generated) == 4, len(a.generated)
        assert len(b.generated) == 4, len(b.generated)
    finally:
        engine.stop()


def test_warmup_chunked_compiles_both_layouts():
    for layout in ("slot", "paged"):
        engine = demo_llama_engine(
            EngineConfig(max_batch=2, max_seq=64, prefill_buckets=(8,),
                         kv_layout=layout, seed=1))
        engine.warmup(prompt_lens=(8,), chunked=True)  # must not crash
        toks, _ = _generate(engine, list(range(3, 30)), n=3)
        assert len(toks) == 3


def test_walker_does_not_starve_waiting_admission():
    """A mid-walk long prompt holds one slot; a short prompt must be
    admitted into the OTHER free slot while the walk is still going."""
    import time

    engine = demo_llama_engine(
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     prefill_chunks_per_pass=1, seed=6))
    engine.start()
    try:
        long_req = engine.submit(PROMPT, SamplingParams(
            temperature=0.0, max_new_tokens=4))
        short_req = engine.submit([9, 9, 9], SamplingParams(
            temperature=0.0, max_new_tokens=2))
        deadline = time.time() + 60
        while time.time() < deadline:
            if all(r.finished_at is not None or r.error
                   for r in (long_req, short_req)):
                break
            time.sleep(0.01)
        assert short_req.error is None and len(short_req.generated) == 2
        assert long_req.error is None and len(long_req.generated) == 4
    finally:
        engine.stop()


def test_paged_windowed_chunk_walk_matches_full():
    """The windowed chunk-walk variant (gathers only the table columns
    the largest configured window covers) must reproduce the full
    graph's greedy output for long paged prompts — including walks
    whose history outgrows the window and falls back mid-walk."""
    base = dict(max_batch=2, max_seq=256, prefill_buckets=(16,), seed=7,
                kv_layout="paged", page_size=16)
    long_prompt = PROMPT + PROMPT  # 60 tokens -> 4 chunk passes

    full = demo_llama_engine(EngineConfig(**base))
    want, kept = _generate(full, long_prompt)
    assert kept == len(long_prompt)

    # window 48: the walk starts windowed (offsets 0,16,32 need <=48
    # rows), outgrows it at offset 48, and falls back to full
    windowed = demo_llama_engine(EngineConfig(decode_windows=(48,),
                                              **base))
    got, _ = _generate(windowed, long_prompt)
    assert got == want
