"""protogen (.proto → service skeleton, the gofr-cli analog): parse,
generate, import, implement, serve, call with the generated client, and
reflection answering file_containing_symbol with protoc descriptors."""

import asyncio
import importlib.util
import sys
import textwrap

import pytest

from gofr_tpu.grpc.protogen import generate, parse_proto

from .apputil import grpc_channel

PROTO = textwrap.dedent("""\
    syntax = "proto3";

    package demo.greeter;

    // a message with a few shapes
    message HelloRequest {
      string name = 1;
      int32 times = 2;
      repeated string tags = 3;
    }

    message HelloReply {
      string message = 1;
      bool ok = 2;
    }

    service Greeter {
      rpc SayHello (HelloRequest) returns (HelloReply);
      rpc StreamHello (HelloRequest) returns (stream HelloReply);
    }
""")


@pytest.fixture(scope="module")
def generated(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("protogen")
    proto = tmp / "greeter.proto"
    proto.write_text(PROTO)
    out = tmp / "greeter_gofr.py"
    out.write_text(generate(proto))
    spec = importlib.util.spec_from_file_location("greeter_gofr", out)
    module = importlib.util.module_from_spec(spec)
    sys.modules["greeter_gofr"] = module
    spec.loader.exec_module(module)
    yield module
    sys.modules.pop("greeter_gofr", None)


def test_parse_proto_shapes():
    pf = parse_proto(PROTO)
    assert pf.package == "demo.greeter"
    assert [m.name for m in pf.messages] == ["HelloRequest", "HelloReply"]
    req = pf.messages[0]
    assert [(f.name, f.type, f.repeated) for f in req.fields] == [
        ("name", "string", False), ("times", "int32", False),
        ("tags", "string", True)]
    svc = pf.services[0]
    assert svc.name == "Greeter"
    assert [(r.name, r.server_stream) for r in svc.rpcs] == [
        ("SayHello", False), ("StreamHello", True)]


def test_generated_module_shape(generated):
    m = generated
    assert m.GreeterBase.name == "demo.greeter.Greeter"
    req = m.HelloRequest(name="x")
    assert req.times == 0 and req.tags == []
    assert m.HelloRequest.from_dict({"name": "y", "junk": 1}).name == "y"
    # skeleton methods are registered rpcs but unimplemented
    specs = {s.name: s.kind for s in m.GreeterBase.rpc_specs()}
    assert specs == {"SayHello": "unary", "StreamHello": "server_stream"}
    # protoc is in the image: descriptors must have been compiled in
    assert m.FILE_DESCRIPTOR_SET


def test_serve_and_call_with_generated_client(generated):
    """Subclass the skeleton, serve it on the framework's gRPC server,
    call both RPCs through the generated client."""
    import grpc

    from gofr_tpu.config.env import DictConfig
    from gofr_tpu.container.container import Container
    from gofr_tpu.grpc.server import GRPCServer

    m = generated

    class Greeter(m.GreeterBase):
        async def SayHello(self, ctx, request):
            req = m.HelloRequest.from_dict(request)
            return {"message": f"hello {req.name}", "ok": True}

        async def StreamHello(self, ctx, request):
            req = m.HelloRequest.from_dict(request)
            for i in range(max(1, req.times)):
                yield {"message": f"hello {req.name} #{i}", "ok": True}

    async def scenario():
        container = Container(DictConfig({
            "APP_NAME": "protogen-test",
            "GRPC_ENABLE_REFLECTION": "true"}))
        server = GRPCServer(container, port=0)
        server.register(Greeter())
        server.register_descriptors(m.FILE_DESCRIPTOR_SET)
        await server.start()
        try:
            async with grpc_channel(server.bound_port) as channel:
                client = m.GreeterClient(channel)
                reply = await client.SayHello(
                    m.HelloRequest(name="world"))
                assert reply["data"]["message"] == "hello world" \
                    if "data" in reply else \
                    reply["message"] == "hello world"
                got = []
                async for item in client.StreamHello(
                        m.HelloRequest(name="s", times=3)):
                    got.append(item)
                texts = [(e.get("data") or e)["message"] if "data" in e
                         else e["message"] for e in got]
                assert len(got) == 3 and texts[0] == "hello s #0"

                # reflection: symbol lookup returns real descriptors
                from gofr_tpu.grpc.health import (_decode_varint,
                                                  _encode_varint)
                stub = channel.stream_stream(
                    "/grpc.reflection.v1.ServerReflection"
                    "/ServerReflectionInfo",
                    request_serializer=lambda b: b,
                    response_deserializer=lambda b: b)

                sym = b"demo.greeter.Greeter"
                req = (_encode_varint((4 << 3) | 2)
                       + _encode_varint(len(sym)) + sym)

                async def one():
                    yield req
                async for resp in stub(one()):
                    # field 4 = file_descriptor_response present
                    pos, found = 0, False
                    while pos < len(resp):
                        tag, pos = _decode_varint(resp, pos)
                        if tag & 7 == 2:
                            ln, pos = _decode_varint(resp, pos)
                            if tag >> 3 == 4:
                                found = True
                                assert ln > 0
                            pos += ln
                        else:
                            _, pos = _decode_varint(resp, pos)
                    assert found, "no FileDescriptorResponse"
                    break
        finally:
            await server.shutdown()

    asyncio.run(scenario())


def test_descriptor_registry_nested_symbols(tmp_path):
    """file_containing_symbol resolves nested messages and enums, not
    just top-level names (grpcurl describe pkg.Outer.Inner)."""
    import subprocess
    import shutil

    from gofr_tpu.grpc.reflection import DescriptorRegistry

    proto = tmp_path / "nested.proto"
    proto.write_text(textwrap.dedent("""\
        syntax = "proto3";
        package deep.pkg;
        message Outer {
          message Inner { string v = 1; }
          enum Mode { OFF = 0; ON = 1; }
          Inner inner = 1;
        }
    """))
    protoc = shutil.which("protoc")
    assert protoc, "protoc expected in the image"
    out = tmp_path / "fds.bin"
    subprocess.run([protoc, f"-I{tmp_path}", str(proto),
                    f"--descriptor_set_out={out}"], check=True)
    reg = DescriptorRegistry()
    reg.add_serialized_set(out.read_bytes())
    for symbol in ("deep.pkg.Outer", "deep.pkg.Outer.Inner",
                   "deep.pkg.Outer.Mode"):
        assert reg.file_containing_symbol(symbol), symbol
    assert reg.file_containing_symbol("deep.pkg.Nope") is None
    assert reg.file_by_filename("nested.proto")
