"""Whisper-family ASR: audio frontend, model forward, greedy decode,
batched worker over pub/sub (baseline config 4)."""

import asyncio
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gofr_tpu.models.whisper import (WhisperConfig, param_count,
                                     precompute_cross_kv, transcribe_audio,
                                     transcribe_greedy, whisper_encode,
                                     whisper_init)
from gofr_tpu.ops.audio import log_mel_spectrogram, mel_filterbank
from gofr_tpu.serving.asr import (ASRConfig, ASRWorker, Transcriber,
                                  decode_audio_payload, make_asr_handler)


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))
    return wrapper


CFG = WhisperConfig.tiny_test()
PARAMS = whisper_init(jax.random.key(0), CFG)


# ------------------------------------------------------------------- audio
class TestAudioFrontend:
    def test_mel_filterbank_shape_and_coverage(self):
        bank = mel_filterbank(80)
        assert bank.shape == (201, 80)
        # every filter has mass; interior bins covered
        assert (bank.sum(axis=0) > 0).all()

    def test_log_mel_shapes_and_range(self):
        t = 16000  # 1 s
        audio = jnp.asarray(np.sin(np.linspace(0, 440 * 2 * np.pi, t)),
                            jnp.float32)
        mel = log_mel_spectrogram(audio, n_mels=80)
        assert mel.ndim == 3 and mel.shape[0] == 1 and mel.shape[2] == 80
        assert bool(jnp.isfinite(mel).all())
        # whisper scaling keeps values in roughly [-1, 1.5]
        assert float(mel.max()) < 2.0

    def test_pad_to_frames_static_shape(self):
        audio = jnp.zeros((2, 8000), jnp.float32)
        mel = log_mel_spectrogram(audio, n_mels=8, pad_to_frames=64)
        assert mel.shape == (2, 64, 8)

    def test_jittable(self):
        fn = jax.jit(lambda a: log_mel_spectrogram(a, n_mels=8,
                                                   pad_to_frames=64))
        out = fn(jnp.zeros((1, 4000), jnp.float32))
        assert out.shape == (1, 64, 8)


# ------------------------------------------------------------------- model
class TestWhisperModel:
    def test_param_tree_and_count(self):
        assert param_count(PARAMS) > 0
        assert PARAMS["enc_layers"]["wq"].shape[0] == CFG.n_audio_layers
        assert PARAMS["dec_layers"]["xwk"].shape[0] == CFG.n_text_layers

    def test_encode_shape(self):
        mel = jnp.zeros((2, CFG.audio_frames, CFG.n_mels), jnp.float32)
        enc = whisper_encode(PARAMS, mel, CFG)
        assert enc.shape == (2, CFG.audio_ctx, CFG.dim)
        assert bool(jnp.isfinite(enc).all())

    def test_cross_kv_shapes(self):
        mel = jnp.zeros((2, CFG.audio_frames, CFG.n_mels), jnp.float32)
        enc = whisper_encode(PARAMS, mel, CFG)
        ck, cv = precompute_cross_kv(PARAMS, enc, CFG)
        assert ck.shape == (CFG.n_text_layers, 2, CFG.audio_ctx,
                            CFG.n_heads, CFG.head_dim)
        assert cv.shape == ck.shape

    def test_greedy_transcribe_shapes_and_determinism(self):
        mel = jax.random.normal(jax.random.key(1),
                                (2, CFG.audio_frames, CFG.n_mels))
        t1, l1 = transcribe_greedy(PARAMS, mel, CFG, max_tokens=8)
        t2, l2 = transcribe_greedy(PARAMS, mel, CFG, max_tokens=8)
        assert t1.shape == (2, 8)
        assert np.array_equal(np.asarray(t1), np.asarray(t2))
        assert np.array_equal(np.asarray(l1), np.asarray(l2))
        assert (np.asarray(l1) <= 8).all()

    def test_transcribe_audio_end_to_end_jits(self):
        fn = jax.jit(lambda p, a: transcribe_audio(p, a, CFG, max_tokens=4))
        audio = jnp.zeros((1, 6400), jnp.float32)  # pads to audio_frames
        tokens, lengths = fn(PARAMS, audio)
        assert tokens.shape == (1, 4)
        assert int(lengths[0]) <= 4

    def test_eot_freezes_sequence(self):
        # rows past a sequence's EOT must all be EOT
        mel = jax.random.normal(jax.random.key(2),
                                (4, CFG.audio_frames, CFG.n_mels))
        tokens, lengths = transcribe_greedy(PARAMS, mel, CFG, max_tokens=8)
        tokens = np.asarray(tokens)
        for row, n in zip(tokens, np.asarray(lengths)):
            eots = row == CFG.eot_token
            if eots.any():
                first = int(np.argmax(eots))
                assert eots[first:].all()

    def test_presets(self):
        assert WhisperConfig.whisper_large_v3().n_mels == 128
        assert WhisperConfig.whisper_tiny().dim == 384


# ----------------------------------------------------------------- serving
def _tone(freq=220.0, seconds=0.25):
    t = np.arange(int(16000 * seconds)) / 16000
    return np.sin(2 * np.pi * freq * t).astype(np.float32)


class TestTranscriber:
    def test_bucketing_and_results(self):
        tr = Transcriber(PARAMS, CFG, ASRConfig(max_batch=4, max_tokens=4,
                                                sample_buckets=(8000, 16000)))
        out = tr.transcribe_batch([_tone(), _tone(440.0)])
        assert len(out) == 2
        assert out[0]["batch"] == 2
        assert out[0]["samples"] == 8000
        assert all(r["n_tokens"] <= 4 for r in out)
        assert tr.executions == 1

    def test_payload_decoding(self):
        import base64
        pcm = _tone()
        assert np.allclose(decode_audio_payload({"audio": pcm.tolist()}), pcm)
        b64 = base64.b64encode(pcm.tobytes()).decode()
        assert np.allclose(decode_audio_payload({"audio_b64": b64}), pcm)
        with pytest.raises(ValueError):
            decode_audio_payload({"nope": 1})

    def test_http_handler(self):
        tr = Transcriber(PARAMS, CFG, ASRConfig(max_batch=1, max_tokens=4,
                                                sample_buckets=(8000,)))

        class Ctx:
            def bind(self):
                return {"audio": _tone().tolist()}
        result = make_asr_handler(tr)(Ctx())
        assert "tokens" in result and result["n_tokens"] <= 4


class TestASRWorker:
    @async_test
    async def test_batch_consume_publish_commit(self):
        from gofr_tpu.pubsub.inmemory import InMemoryBroker
        broker = InMemoryBroker()
        tr = Transcriber(PARAMS, CFG, ASRConfig(max_batch=4, max_tokens=4,
                                                sample_buckets=(8000,)))
        worker = ASRWorker(tr, broker)
        for i in range(3):
            await broker.publish("asr.requests",
                                 {"request_id": f"r{i}",
                                  "audio": _tone(200.0 + i).tolist()})
        handled = await worker.run_once()
        assert handled == 3
        assert tr.executions == 1  # one device batch for all three
        results = [await broker.subscribe("asr.results") for _ in range(3)]
        ids = {r.bind()["request_id"] for r in results}
        assert ids == {"r0", "r1", "r2"}
        # everything committed: no redelivery pending
        assert broker.redeliver_uncommitted("asr.requests", "asr-workers") == 0

    @async_test
    async def test_poison_message_dropped(self):
        from gofr_tpu.pubsub.inmemory import InMemoryBroker
        broker = InMemoryBroker()
        tr = Transcriber(PARAMS, CFG, ASRConfig(max_batch=2, max_tokens=4,
                                                sample_buckets=(8000,)))
        worker = ASRWorker(tr, broker)
        await broker.publish("asr.requests", {"garbage": True})
        await broker.publish("asr.requests",
                             {"request_id": "ok", "audio": _tone().tolist()})
        handled = await worker.run_once()
        assert handled == 1
        result = await broker.subscribe("asr.results")
        assert result.bind()["request_id"] == "ok"
        assert broker.redeliver_uncommitted("asr.requests", "asr-workers") == 0
