"""Metrics manager tests — registration, writes, Prometheus exposition."""

import pytest

from gofr_tpu.metrics import Manager, MetricsError


def test_counter_flow():
    m = Manager()
    m.new_counter("app_requests", "total requests")
    m.increment_counter("app_requests", path="/a", method="GET")
    m.increment_counter("app_requests", path="/a", method="GET")
    m.increment_counter("app_requests", path="/b", method="POST")
    c = m.get("app_requests")
    assert c.get(path="/a", method="GET") == 2
    assert c.get(path="/b", method="POST") == 1


def test_duplicate_registration_rejected():
    m = Manager()
    m.new_counter("x", "d")
    with pytest.raises(MetricsError):
        m.new_counter("x", "again")


def test_up_down_and_gauge():
    m = Manager()
    m.new_up_down_counter("inflight", "in-flight requests")
    m.delta_up_down_counter("inflight", +1)
    m.delta_up_down_counter("inflight", +1)
    m.delta_up_down_counter("inflight", -1)
    assert m.get("inflight").get() == 1
    m.new_gauge("temp", "temperature")
    m.set_gauge("temp", 42.5, zone="a")
    assert m.get("temp").get(zone="a") == 42.5


def test_histogram_buckets_and_render():
    m = Manager()
    m.new_histogram("lat", "latency", buckets=[0.1, 1.0, 10.0])
    for v in (0.05, 0.5, 5.0, 50.0):
        m.record_histogram("lat", v, path="/x")
    h = m.get("lat")
    assert h.get_count(path="/x") == 4
    assert h.get_sum(path="/x") == pytest.approx(55.55)
    text = m.render_prometheus()
    assert 'lat_bucket{le="0.1",path="/x"} 1' in text
    assert 'lat_bucket{le="1",path="/x"} 2' in text
    assert 'lat_bucket{le="10",path="/x"} 3' in text
    assert 'lat_bucket{le="+Inf",path="/x"} 4' in text
    assert 'lat_count{path="/x"} 4' in text


def test_prometheus_text_format():
    m = Manager()
    m.new_counter("hits", "hit count")
    m.increment_counter("hits", route='/a"b')
    text = m.render_prometheus()
    assert "# HELP hits hit count" in text
    assert "# TYPE hits counter" in text
    assert 'hits{route="/a\\"b"} 1' in text


def test_unknown_metric_write_is_noop():
    m = Manager()
    m.increment_counter("ghost")  # must not raise
    m.record_histogram("ghost", 1.0)
    m.set_gauge("ghost", 1.0)


def test_wrong_kind_write_is_noop():
    m = Manager()
    m.new_counter("c", "d")
    m.set_gauge("c", 5.0)  # counter written as gauge -> rejected
    assert m.get("c").get() == 0.0


def test_unwritten_metric_renders_no_phantom_series():
    m = Manager()
    m.new_counter("quiet", "never written")
    text = m.render_prometheus()
    assert "# TYPE quiet counter" in text
    assert "\nquiet 0" not in text
