"""Mesh-sharded serving: the engine on a tp/dp mesh must generate the
same tokens as a single-device engine (BASELINE config 5's CPU-mesh
analog — a model too big for one chip is served by passing ``mesh=``).
"""

import time

import jax
import pytest

from gofr_tpu.models.llama import LlamaConfig, llama_init
from gofr_tpu.parallel.mesh import create_mesh
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import llama_engine

TINY = LlamaConfig.tiny()


def _generate(mesh):
    params = llama_init(jax.random.key(0), TINY)
    eng = llama_engine(
        params, TINY,
        EngineConfig(max_batch=4, max_seq=128, seed=11),
        mesh=mesh, implementation="xla")
    eng.start()
    try:
        outs = []
        reqs = [eng.submit([3 + i, 1, 4, 1, 5],
                           SamplingParams(temperature=0.0, max_new_tokens=8))
                for i in range(6)]
        deadline = time.time() + 120
        while time.time() < deadline and any(
                r.finished_at is None and r.error is None for r in reqs):
            time.sleep(0.01)
        for r in reqs:
            assert r.error is None, r.error
            outs.append(r.generated)
        return outs
    finally:
        eng.stop()


@pytest.fixture(scope="module")
def single_device_outputs():
    return _generate(None)


def test_tp_sharded_decode_matches_single_device(single_device_outputs):
    mesh = create_mesh({"tp": 2}, jax.devices()[:2])
    assert _generate(mesh) == single_device_outputs


def test_wider_tp_matches_single_device(single_device_outputs):
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    # tiny config has 2 kv heads; tp=2 shards them, wider tp shards
    # the q-head/ffn dims via the same specs
    mesh = create_mesh({"tp": 2, "dp": 4}, jax.devices())
    assert _generate(mesh) == single_device_outputs


def test_sharded_params_actually_sharded():
    mesh = create_mesh({"tp": 2}, jax.devices()[:2])
    params = llama_init(jax.random.key(0), TINY)
    eng = llama_engine(params, TINY,
                       EngineConfig(max_batch=2, max_seq=64),
                       mesh=mesh, implementation="xla")
    wq = eng.params["layers"]["wq"]
    # column-parallel: output dim split over tp=2
    shard_shapes = {s.data.shape for s in wq.addressable_shards}
    assert shard_shapes == {(TINY.n_layers, TINY.dim,
                             TINY.n_heads * TINY.head_dim // 2)}
    kc = eng.k_cache
    # kv heads split over tp=2
    assert {s.data.shape for s in kc.addressable_shards} == {
        (TINY.n_layers, 2, 64, TINY.n_kv_heads // 2, TINY.head_dim)}


def _generate_long(mesh):
    import numpy as np
    prompt = list(np.random.RandomState(9).randint(3, 200, size=40))
    params = llama_init(jax.random.key(0), TINY)
    eng = llama_engine(
        params, TINY,
        EngineConfig(max_batch=2, max_seq=128, prefill_buckets=(8,),
                     seed=11),
        mesh=mesh, implementation="xla")
    eng.start()
    try:
        req = eng.submit(prompt, SamplingParams(temperature=0.0,
                                                max_new_tokens=6))
        deadline = time.time() + 180
        while time.time() < deadline and req.finished_at is None \
                and req.error is None:
            time.sleep(0.01)
        assert req.error is None, req.error
        assert len(req.prompt_tokens) == 40  # chunked, not clamped
        return list(req.generated)
    finally:
        eng.stop()


def test_chunked_prefill_sharded_matches_single_device():
    """A long prompt walking in chunks on a tp-sharded engine must
    produce the single-device tokens — the chunk graph's cache slicing
    and scatters compose with the mesh sharding."""
    single = _generate_long(None)
    sharded = _generate_long(create_mesh({"tp": 2}, jax.devices()[:2]))
    assert sharded == single


def _generate_modern(mesh):
    """The production engine shape, all features on at once: paged KV
    (gather/scatter view path under a mesh), prefix cache, chunked
    prefill, speculative decode, pipelined dispatch."""
    params = llama_init(jax.random.key(0), TINY)
    eng = llama_engine(
        params, TINY,
        EngineConfig(max_batch=4, max_seq=128, prefill_buckets=(16, 32),
                     seed=11, kv_layout="paged", page_size=16,
                     prefix_cache=True, speculative=True, spec_draft=3,
                     # drafting is consulted only at pass boundaries
                     # (the matched tail ends at the boundary token):
                     # short passes + 1-gram lookup make engagement
                     # deterministic within the tiny token budget
                     spec_ngram=1, decode_steps_per_pass=2,
                     pipeline_depth=1),
        mesh=mesh, implementation="xla")
    eng.start()
    try:
        outs = []
        system = list(range(40, 40 + 32))  # two full pages: cacheable
        # long prompt (chunk walk), two prefix-sharers (second hits
        # the cache), and a repetitive prompt generated long enough
        # that the greedy loop repeats its own n-grams (drafts fire)
        prompts = [(list(range(3, 3 + 48)), 10),
                   (system + [7, 8, 9], 10),
                   (system + [9, 8, 7], 10),
                   ([5, 6] * 5, 24)]
        for prompt, gen in prompts:  # sequential: prefix registration
            req = eng.submit(prompt, SamplingParams(  # is retire-time
                temperature=0.0, max_new_tokens=gen))
            deadline = time.time() + 180
            while time.time() < deadline and req.finished_at is None \
                    and req.error is None:
                time.sleep(0.01)
            assert req.error is None, req.error
            assert req.finished_at is not None, "timed out"
            outs.append(list(req.generated))
        stats = dict(eng.stats)
        return outs, stats
    finally:
        eng.stop()


def _generate_int8(mesh):
    params = llama_init(jax.random.key(0), TINY)
    eng = llama_engine(params, TINY,
                       EngineConfig(max_batch=4, max_seq=128, seed=11),
                       mesh=mesh, implementation="xla",
                       quantize="int8")
    eng.start()
    try:
        reqs = [eng.submit([3 + i, 1, 4, 1, 5],
                           SamplingParams(temperature=0.0,
                                          max_new_tokens=8))
                for i in range(4)]
        deadline = time.time() + 120
        while time.time() < deadline and any(
                r.finished_at is None and r.error is None for r in reqs):
            time.sleep(0.01)
        assert all(r.error is None for r in reqs)
        return [r.generated for r in reqs]
    finally:
        eng.stop()


def test_int8_sharded_matches_int8_single_device():
    """Weight-only int8 composes with tp sharding: the {'q','s'}
    leaves shard like their bf16 matrix (scales keep the output axis,
    reduction axis unsharded) and greedy outputs are identical to
    single-device int8."""
    single = _generate_int8(None)
    sharded = _generate_int8(create_mesh({"tp": 2}, jax.devices()[:2]))
    assert sharded == single


def test_int8_sharded_params_actually_sharded():
    mesh = create_mesh({"tp": 2}, jax.devices()[:2])
    params = llama_init(jax.random.key(0), TINY)
    eng = llama_engine(params, TINY,
                       EngineConfig(max_batch=2, max_seq=64),
                       mesh=mesh, implementation="xla", quantize="int8")
    wq = eng.params["layers"]["wq"]
    out_dim = TINY.n_heads * TINY.head_dim
    assert {s.data.shape for s in wq["q"].addressable_shards} == \
        {(TINY.n_layers, TINY.dim, out_dim // 2)}
    # scales: per-output-channel, sharded with the output axis
    assert {s.data.shape for s in wq["s"].addressable_shards} == \
        {(TINY.n_layers, 1, out_dim // 2)}
    # engine never started: nothing to stop


def test_modern_engine_sharded_matches_single_device():
    """Greedy equivalence for the full modern feature set — paged KV,
    prefix cache, chunked prefill, speculative decode, pipelining —
    between single-device and tp-sharded engines, with the features
    proven to actually engage (VERDICT r4 #4)."""
    single, sstats = _generate_modern(None)
    sharded, mstats = _generate_modern(
        create_mesh({"tp": 2}, jax.devices()[:2]))
    assert sharded == single
    for stats in (sstats, mstats):
        assert stats["prefix_hits"] >= 1, stats
        assert stats["spec_passes"] >= 1, stats
