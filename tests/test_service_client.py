"""Inter-service HTTP client tests against a real in-process server."""

import asyncio
import base64
import json

import pytest

from gofr_tpu.http import ErrorServiceUnavailable  # noqa: F401  (import check)
from gofr_tpu.service.client import (
    APIKeyAuth,
    BasicAuth,
    CircuitBreaker,
    CircuitOpenError,
    CustomHeaders,
    HTTPService,
    RateLimit,
    RateLimitedError,
    Retry,
    ServiceError,
)

from .apputil import AppRunner


def build_upstream(app):
    state = {"fail_next": 0, "hits": 0}
    app._test_state = state

    @app.get("/ok")
    def ok(ctx):
        return {"msg": "hi", "auth": ctx.header("Authorization"),
                "apikey": ctx.header("X-Api-Key"),
                "custom": ctx.header("X-Custom"),
                "traceparent": ctx.header("traceparent")}

    @app.get("/flaky")
    def flaky(ctx):
        state["hits"] += 1
        if state["fail_next"] > 0:
            state["fail_next"] -= 1
            raise RuntimeError("boom")
        return {"hits": state["hits"]}

    @app.post("/echo")
    def echo(ctx):
        return ctx.bind()


@pytest.fixture(scope="module")
def upstream():
    with AppRunner(build=build_upstream) as app:
        yield app


def call(service, method="get", path="/ok", **kw):
    return asyncio.run(getattr(service, method)(path, **kw))


def test_basic_request_and_json(upstream):
    svc = HTTPService(f"http://127.0.0.1:{upstream.port}")
    resp = call(svc)
    assert resp.ok and resp.json()["data"]["msg"] == "hi"


def test_post_json_body(upstream):
    svc = HTTPService(f"http://127.0.0.1:{upstream.port}")
    resp = call(svc, "post", "/echo", json={"a": [1, 2]})
    assert resp.status == 201
    assert resp.json()["data"] == {"a": [1, 2]}


def test_query_params(upstream):
    svc = HTTPService(f"http://127.0.0.1:{upstream.port}")
    resp = call(svc, "get", "/ok", params={"x": "1 2"})
    assert resp.ok


def test_auth_options(upstream):
    svc = HTTPService(f"http://127.0.0.1:{upstream.port}",
                      BasicAuth("user", "pass"),
                      APIKeyAuth("secret-key"),
                      CustomHeaders({"X-Custom": "v"}))
    data = call(svc).json()["data"]
    expected = base64.b64encode(b"user:pass").decode()
    assert data["auth"] == f"Basic {expected}"
    assert data["apikey"] == "secret-key"
    assert data["custom"] == "v"


def test_trace_propagation(upstream):
    from gofr_tpu.tracing import InMemoryExporter, Tracer
    tracer = Tracer(exporter=InMemoryExporter())

    async def flow():
        svc = HTTPService(f"http://127.0.0.1:{upstream.port}", tracer=tracer)
        with tracer.start_span("client-op") as span:
            resp = await svc.get("/ok")
            return span.trace_id, resp.json()["data"]["traceparent"]

    trace_id, header = asyncio.run(flow())
    assert trace_id in header


def test_retry_recovers_from_5xx(upstream):
    upstream.app._test_state["fail_next"] = 2
    svc = HTTPService(f"http://127.0.0.1:{upstream.port}",
                      Retry(max_retries=3, backoff_s=0.01))
    resp = call(svc, "get", "/flaky")
    assert resp.ok


def test_retry_gives_up_on_connection_refused():
    svc = HTTPService("http://127.0.0.1:1", Retry(max_retries=1, backoff_s=0.01),
                      timeout=0.5)
    with pytest.raises(ServiceError, match="attempts"):
        call(svc)


def test_rate_limit(upstream):
    svc = HTTPService(f"http://127.0.0.1:{upstream.port}",
                      RateLimit(rate=0.001, burst=2))
    assert call(svc).ok
    assert call(svc).ok
    with pytest.raises(RateLimitedError):
        call(svc)


def test_circuit_breaker_opens_and_recovers(upstream):
    cb = CircuitBreaker(threshold=2, interval_s=0.05)
    svc = HTTPService(f"http://127.0.0.1:{upstream.port}", cb)

    async def flow():
        upstream.app._test_state["fail_next"] = 10
        for _ in range(2):
            resp = await svc.get("/flaky")
            assert resp.status == 500
        assert cb.is_open
        with pytest.raises(CircuitOpenError):
            await svc.get("/flaky")
        # upstream recovers; health probe closes the breaker
        upstream.app._test_state["fail_next"] = 0
        for _ in range(40):
            if not cb.is_open:
                break
            await asyncio.sleep(0.05)
        assert not cb.is_open
        resp = await svc.get("/flaky")
        assert resp.ok

    asyncio.run(flow())


def test_health_check(upstream):
    svc = HTTPService(f"http://127.0.0.1:{upstream.port}")
    assert asyncio.run(svc.health_check()) == {"status": "UP"}
    dead = HTTPService("http://127.0.0.1:1", timeout=0.5)
    assert asyncio.run(dead.health_check())["status"] == "DOWN"


def test_container_service_registration(upstream):
    from gofr_tpu.container.container import Container
    c = Container()
    c.register_service("billing",
                       HTTPService(f"http://127.0.0.1:{upstream.port}"))
    health = c.health()
    assert health["checks"]["service:billing"]["status"] == "UP"


def test_circuit_breaker_lazy_half_open_across_loops(upstream):
    """Short-lived loops (asyncio.run per call) must not strand the
    circuit open: one trial request per interval passes half-open."""
    cb = CircuitBreaker(threshold=2, interval_s=0.05)
    svc = HTTPService(f"http://127.0.0.1:{upstream.port}", cb)
    upstream.app._test_state["fail_next"] = 10
    for _ in range(2):
        assert call(svc, "get", "/flaky").status == 500  # separate loops
    assert cb.is_open
    upstream.app._test_state["fail_next"] = 0
    import time as time_mod
    time_mod.sleep(0.06)
    resp = call(svc, "get", "/flaky")  # half-open trial, new loop
    assert resp.ok and not cb.is_open


# ---------------------------------------------------------- retry-after
class TestRetryAfter:
    """Retry honors a server-stated Retry-After on 429/503 (seconds
    and HTTP-date forms) instead of its own exponential backoff."""

    def test_parse_delta_seconds(self):
        from gofr_tpu.service.client import parse_retry_after
        assert parse_retry_after("7") == 7.0
        assert parse_retry_after(" 2.5 ") == 2.5
        assert parse_retry_after("-3") == 0.0

    def test_parse_http_date(self):
        import time as time_mod
        from email.utils import formatdate
        from gofr_tpu.service.client import parse_retry_after
        wait = parse_retry_after(
            formatdate(time_mod.time() + 10, usegmt=True))
        assert wait is not None and 7.0 < wait <= 10.5
        # a date already in the past floors at zero, never negative
        past = parse_retry_after(
            formatdate(time_mod.time() - 60, usegmt=True))
        assert past == 0.0

    def test_parse_garbage_is_none(self):
        from gofr_tpu.service.client import parse_retry_after
        assert parse_retry_after("") is None
        assert parse_retry_after("soon") is None
        assert parse_retry_after("Wed, 99 Foo") is None

    def _run(self, retry, responses, slept):
        """Drive Retry.around against a scripted upstream, recording
        every sleep instead of waiting it out."""

        class FakeResp:
            def __init__(self, status, headers=None):
                self.status = status
                self.headers = headers or {}

        script = list(responses)

        async def fake_call(method, path, headers, body):
            return FakeResp(*script.pop(0))

        async def fake_sleep(s):
            slept.append(s)

        real_sleep = asyncio.sleep
        asyncio.sleep = fake_sleep
        try:
            return asyncio.run(
                retry.around(fake_call, "GET", "/x", {}, None))
        finally:
            asyncio.sleep = real_sleep

    def test_503_waits_what_the_server_asked(self):
        slept = []
        resp = self._run(Retry(max_retries=2, backoff_s=0.01),
                         [(503, {"retry-after": "4"}), (200,)], slept)
        assert resp.status == 200
        assert slept == [4.0]

    def test_429_retries_only_with_the_header(self):
        slept = []
        resp = self._run(Retry(max_retries=2, backoff_s=0.01),
                         [(429, {"retry-after": "1"}), (200,)], slept)
        assert resp.status == 200 and slept == [1.0]
        # a bare 429 is a quota answer, not a transient: no retry
        slept = []
        resp = self._run(Retry(max_retries=2, backoff_s=0.01),
                         [(429,), (200,)], slept)
        assert resp.status == 429 and slept == []

    def test_wait_is_capped(self):
        slept = []
        resp = self._run(
            Retry(max_retries=1, backoff_s=0.01, max_retry_after_s=5.0),
            [(503, {"retry-after": "3600"}), (200,)], slept)
        assert resp.status == 200
        assert slept == [5.0]

    def test_unparseable_header_falls_back_to_backoff(self):
        slept = []
        resp = self._run(Retry(max_retries=1, backoff_s=0.25),
                         [(503, {"retry-after": "later"}), (200,)], slept)
        assert resp.status == 200
        assert slept == [0.25]

    def test_honor_disabled_uses_backoff(self):
        slept = []
        resp = self._run(
            Retry(max_retries=1, backoff_s=0.5, honor_retry_after=False),
            [(503, {"retry-after": "9"}), (200,)], slept)
        assert resp.status == 200
        assert slept == [0.5]
