"""InfluxDB HTTP/line-protocol client against the mini server — real
wire bytes over a real socket (reference datasource/influxdb's
network-client role)."""

import pytest

from gofr_tpu.datasource.influx_wire import (
    InfluxWire,
    MiniInfluxServer,
    decode_line,
    encode_line,
)
from gofr_tpu.datasource.timeseries import TimeseriesError


# --------------------------------------------------------- line protocol

def test_line_protocol_roundtrip():
    line = encode_line("cpu", {"usage": 42.5}, {"host": "a b", "dc": "eu"},
                       ts=1700000000.123)
    measurement, tags, fields, ts = decode_line(line)
    assert measurement == "cpu"
    assert tags == {"host": "a b", "dc": "eu"}
    assert fields == {"usage": 42.5}
    assert ts == pytest.approx(1700000000.123, abs=1e-6)


def test_line_protocol_escaping():
    line = encode_line("my measure", {"v": 1.0}, {"k=1": "x,y"})
    measurement, tags, fields, _ = decode_line(line)
    assert measurement == "my measure"
    assert tags == {"k=1": "x,y"}


def test_line_requires_fields():
    with pytest.raises(TimeseriesError):
        encode_line("m", {})


# ------------------------------------------------------------- end-to-end

@pytest.fixture()
def server():
    srv = MiniInfluxServer()
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def client(server):
    c = InfluxWire(url=f"127.0.0.1:{server.port}")
    c.connect()
    yield c
    c.close()


def test_write_query_roundtrip(client):
    client.create_bucket("metrics")
    client.write_point("metrics", "cpu", 100.0, {"usage": 0.5},
                       {"host": "a"})
    client.write_point("metrics", "cpu", 200.0, {"usage": 0.9},
                       {"host": "b"})
    points = client.query("metrics", "cpu", "usage")
    assert points == [(100.0, 0.5), (200.0, 0.9)]
    # range + tag filters ride the InfluxQL WHERE clause
    assert client.query("metrics", "cpu", "usage", start=150.0) == \
        [(200.0, 0.9)]
    assert client.query("metrics", "cpu", "usage",
                        tags={"host": "a"}) == [(100.0, 0.5)]


def test_aggregates(client):
    client.create_bucket("m")
    for i, v in enumerate([1.0, 2.0, 3.0]):
        client.write_point("m", "t", float(i), {"v": v})
    assert client.aggregate("m", "t", "v", "sum") == 6.0
    assert client.aggregate("m", "t", "v", "avg") == 2.0
    assert client.aggregate("m", "t", "v", "max") == 3.0
    assert client.aggregate("m", "t", "v", "count") == 3
    assert client.aggregate("m", "t", "v", "avg", start=1.0) == 2.5
    assert client.aggregate("m", "nothing", "v", "avg") is None


def test_bucket_admin(client):
    client.create_bucket("a")
    client.create_bucket("b")
    assert client.list_buckets() == ["a", "b"]
    client.delete_bucket("a")
    assert client.list_buckets() == ["b"]


def test_health_check(client, server):
    assert client.health_check()["status"] == "UP"
    server.close()
    assert client.health_check()["status"] == "DOWN"


def test_quoted_tag_values_roundtrip(client):
    client.create_bucket("q")
    client.write_point("q", "t", 1.0, {"v": 5.0}, {"host": "o'brien"})
    assert client.query("q", "t", "v", tags={"host": "o'brien"}) == \
        [(1.0, 5.0)]


def test_invalid_identifier_rejected(client):
    with pytest.raises(TimeseriesError, match="invalid identifier"):
        client.query("b", 'x" OR 1=1', "v")
