"""Google Pub/Sub REST backend against the in-process emulator, and
the Event Hubs adapter over the Kafka endpoint (reference
datasource/pubsub/google + eventhub modules)."""

import asyncio
import functools

from gofr_tpu.config.env import DictConfig
from gofr_tpu.container.container import Container
from gofr_tpu.pubsub.eventhub import EventHubClient
from gofr_tpu.pubsub.google import GooglePubSubClient, MiniPubSubEmulator
from gofr_tpu.pubsub.kafka import MiniKafkaBroker


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))
    return wrapper


@async_test
async def test_publish_pull_ack_roundtrip():
    emu = MiniPubSubEmulator()
    await emu.start()
    client = GooglePubSubClient(f"127.0.0.1:{emu.port}", project="p")
    try:
        # real Pub/Sub delivers only to subscriptions that exist at
        # publish time; apps create them at boot (the subscriber
        # runtime pulls from startup), tests do it explicitly
        await client._ensure_subscription("orders", "g-orders")
        await client.publish("orders", {"id": 7}, key="k",
                             metadata={"source": "web"})
        msg = await asyncio.wait_for(client.subscribe("orders", "g"), 10)
        assert msg.bind() == {"id": 7}
        assert msg.key == "k"
        assert msg.metadata["source"] == "web"
        msg.commit()
        await asyncio.sleep(0.05)
        assert not emu.subs["g-orders"]["outstanding"]
    finally:
        await client.close()
        await emu.close()


@async_test
async def test_groups_fan_out_but_compete_within():
    """Each group (subscription) sees every message once; consumers in
    one group compete."""
    emu = MiniPubSubEmulator()
    await emu.start()
    client = GooglePubSubClient(f"127.0.0.1:{emu.port}")
    try:
        # create both subscriptions BEFORE publishing (pub/sub fan-out
        # starts at subscription creation, as in the real service)
        await client._ensure_subscription("evt", "a-evt")
        await client._ensure_subscription("evt", "b-evt")
        await client.publish("evt", "x")
        m1 = await asyncio.wait_for(client.subscribe("evt", "a"), 10)
        m2 = await asyncio.wait_for(client.subscribe("evt", "b"), 10)
        assert m1.value == b"x" and m2.value == b"x"
    finally:
        await client.close()
        await emu.close()


@async_test
async def test_unacked_message_redelivers_after_deadline():
    emu = MiniPubSubEmulator()
    await emu.start()
    client = GooglePubSubClient(f"127.0.0.1:{emu.port}", ack_deadline_s=1)
    try:
        await client._ensure_subscription("t", "g-t")
        await client.publish("t", "poison")
        m = await asyncio.wait_for(client.subscribe("t", "g"), 10)
        assert m.value == b"poison"   # received but NOT acked
        await asyncio.sleep(1.1)      # deadline passes
        m2 = await asyncio.wait_for(client.subscribe("t", "g"), 10)
        assert m2.value == b"poison"
        m2.commit()
    finally:
        await client.close()
        await emu.close()


@async_test
async def test_container_wires_google_backend():
    emu = MiniPubSubEmulator()
    await emu.start()
    c = Container.create(DictConfig({
        "APP_NAME": "gp", "PUBSUB_BACKEND": "GOOGLE",
        "PUBSUB_BROKER": f"127.0.0.1:{emu.port}",
        "GOOGLE_PROJECT_ID": "proj-x"}))
    try:
        assert isinstance(c.pubsub, GooglePubSubClient)
        assert c.pubsub.project == "proj-x"
        await c.pubsub._ensure_subscription("t", "w-t")
        await c.pubsub.publish("t", {"ok": 1})
        msg = await asyncio.wait_for(c.pubsub.subscribe("t", "w"), 10)
        assert msg.bind() == {"ok": 1}
        assert c.pubsub.health_check()["status"] == "UP"
    finally:
        await c.pubsub.close()
        await emu.close()


@async_test
async def test_eventhub_adapter_over_kafka_endpoint():
    broker = MiniKafkaBroker()
    await broker.start()
    client = EventHubClient(namespace=f"127.0.0.1:{broker.port}",
                            eventhub="telemetry", consumer_group="$Default")
    try:
        await client.publish(value={"reading": 42})  # default hub
        msg = await asyncio.wait_for(client.subscribe(), 15)
        assert msg.topic == "telemetry"
        assert msg.bind() == {"reading": 42}
        health = client.health_check()
        assert health["backend"] == "eventhub"
        assert health["details"]["eventhub"] == "telemetry"
    finally:
        await client.close()
        await broker.close()


@async_test
async def test_container_wires_eventhub_backend():
    broker = MiniKafkaBroker()
    await broker.start()
    c = Container.create(DictConfig({
        "APP_NAME": "eh", "PUBSUB_BACKEND": "EVENTHUB",
        "PUBSUB_BROKER": f"127.0.0.1:{broker.port}",
        "EVENTHUB_NAME": "ingest"}))
    try:
        assert isinstance(c.pubsub, EventHubClient)
        await c.pubsub.publish(value="ping")
        msg = await asyncio.wait_for(c.pubsub.subscribe(), 15)
        assert msg.value == b"ping" and msg.topic == "ingest"
    finally:
        await c.pubsub.close()
        await broker.close()
