"""Leader high availability: deterministic election via lease-with-
epoch, worker-driven failover on missed acks, epoch fencing of revived
stale leaders, stateless state rebuild on takeover, and the
``GET /control/leader`` discovery contract — all over real HTTP."""

import threading
import time

import pytest

from gofr_tpu.serving.control_plane import (ControlPlaneLeader,
                                            FleetConfig, NotLeader,
                                            StaleLeader, WorkerAgent)
from gofr_tpu.serving.faults import FaultPlan
from gofr_tpu.service import probe_leader, resolve_leader

from .apputil import AppRunner


def make_leader(rank=0, candidates=(), **kw):
    fleet = FleetConfig(leader_candidates=tuple(candidates))
    leader = ControlPlaneLeader(coordinator="10.0.0.1:8476",
                                rank=rank, fleet=fleet,
                                host_id=f"leader-{rank}", **kw)

    def build(app):
        leader.install(app)
    return leader, build


def agent(port, host_id, **kw):
    kw.setdefault("heartbeat_interval_s", 0.05)
    return WorkerAgent(f"http://127.0.0.1:{port}", host_id=host_id,
                       n_devices=2, **kw)


def ha_agent(ports, host_id, **kw):
    candidates = tuple(f"http://127.0.0.1:{p}" for p in ports)
    kw.setdefault("fleet", FleetConfig(
        leader_candidates=candidates, missed_acks_before_failover=1))
    return agent(ports[0], host_id, **kw)


# ------------------------------------------------------------ election
class TestLeaseWithEpoch:
    def test_rank0_boots_active_standby_boots_fenced(self):
        active = ControlPlaneLeader(rank=0)
        standby = ControlPlaneLeader(rank=1)
        assert (active.active, active.epoch) == (True, 1)
        assert (standby.active, standby.epoch) == (False, 0)
        # a standby refuses non-takeover control writes, typed
        with pytest.raises(NotLeader):
            standby.join("w1", "127.0.0.1:1", 1)
        with pytest.raises(NotLeader):
            standby.heartbeat("w1", -1)

    def test_takeover_activates_above_all_observed_epochs(self):
        standby = ControlPlaneLeader(rank=1)
        assert standby.ensure_active(worker_epoch=1)
        assert standby.active and standby.epoch == 2
        # a later takeover with stale evidence does not re-bump
        assert not standby.ensure_active(worker_epoch=0)
        assert standby.epoch == 2

    def test_takeover_join_route_activates_and_rebuilds(self):
        leader, build = make_leader(rank=1)
        with AppRunner(build=build) as runner:
            w = agent(runner.port, "w1")
            w.epoch = 1           # learned from the dead leader
            with pytest.raises(RuntimeError):
                w.join()          # non-takeover: typed not_leader
            w.join(takeover=True)
            assert leader.active and leader.epoch == 2
            assert w.epoch == 2   # worker adopts the new epoch
            assert "w1" in leader.topology()["members"]
            assert leader.leadership()["converging"] is False

    def test_revived_stale_leader_is_fenced_then_reelected_higher(self):
        leader, build = make_leader(rank=0)
        with AppRunner(build=build) as runner:
            w = agent(runner.port, "w1")
            w.join()
            # a newer leader was elected elsewhere: worker knows epoch 3
            w.epoch = 3
            w._heartbeat_once()   # 409 stale_leader -> fence -> walk
            assert w.failovers.get("stale_leader") == 1
            state = leader.leadership()
            assert state["stale_rejects"] >= 1
            # sole candidate: the write was REFUSED (fence), then the
            # walk deterministically re-elected the leader strictly
            # above every observed epoch — stale state can never win
            assert state["active"] is True and state["epoch"] == 4
            assert w.epoch == 4

    def test_fence_raises_stale_leader_directly(self):
        leader = ControlPlaneLeader(rank=0)
        with pytest.raises(StaleLeader):
            leader.heartbeat("w1", -1, epoch=9)
        assert leader.active is False

    def test_choose_candidate_is_a_pure_rank_epoch_decision(self):
        choose = WorkerAgent._choose_candidate
        a = {"rank": 0, "url": "a", "active": True, "epoch": 1}
        b = {"rank": 1, "url": "b", "active": True, "epoch": 2}
        s = {"rank": 2, "url": "c", "active": False, "epoch": 0}
        # highest epoch wins among actives
        assert choose([a, b, s], 1) == ("b", False)
        # an active below the known epoch is a revived stale leader:
        # never adopted as-is — the lowest-ranked live candidate is
        # re-elected by takeover (which bumps past the known epoch)
        assert choose([a, s], 2) == ("a", True)
        # nothing reachable -> no decision
        assert choose([], 0) is None
        # ties break to the lowest rank, deterministically
        b_same = dict(b, epoch=1)
        assert choose([b_same, a], 1) == ("a", False)


# ------------------------------------------------------------ failover
class TestWorkerFailover:
    def test_missed_acks_trigger_takeover_of_next_candidate(self):
        leader0, build0 = make_leader(rank=0)
        leader1, build1 = make_leader(rank=1)
        with AppRunner(build=build0) as r0, \
                AppRunner(build=build1) as r1:
            w = ha_agent((r0.port, r1.port), "w1",
                         summary_source=lambda: {
                             "active_slots": 0, "waiting": 0,
                             "prefix_hashes": [7, 8]})
            w.join()
            assert w.epoch == 1
            # leader0 dies: every control RPC (probes too) -> 503
            leader0.faults = FaultPlan.parse("leader_down:times=0")
            w._heartbeat_once()   # miss -> walk -> takeover leader1
            assert w.failovers.get("missed_acks") == 1
            assert leader1.active and leader1.epoch == 2
            assert w.epoch == 2
            # stateless rebuild: the immediate post-join heartbeat
            # already shipped the routing digest to the new leader
            view = leader1.routing_view()
            assert [m["host_id"] for m in view] == ["w1"]
            assert leader1.leadership()["converging"] is False

    def test_partitioned_host_alone_elects_the_standby(self):
        leader0, build0 = make_leader(
            rank=0, faults="leader_partition:request=w1,times=0")
        leader1, build1 = make_leader(rank=1)
        with AppRunner(build=build0) as r0, \
                AppRunner(build=build1) as r1:
            w2 = ha_agent((r0.port, r1.port), "w2")
            w2.join()
            w1 = ha_agent((r0.port, r1.port), "w1")
            # w1 cannot even join leader0: the run-loop path walks the
            # candidates; probes see leader0 active, but its join is
            # refused -> strike it -> takeover-join the standby
            assert w1._locate_leader()
            assert leader1.active and "w1" in leader1.topology()["members"]
            # the partition is asymmetric: w2 still heartbeats leader0
            w2._heartbeat_once()
            assert w2.failovers == {}

    def test_stale_epoch_replay_is_rejected_and_rejoined(self):
        leader, build = make_leader(rank=0)
        with AppRunner(build=build) as runner:
            w = agent(runner.port, "w1")
            w.join()
            leader.faults = FaultPlan.parse("stale_epoch_replay:at=1")
            w._heartbeat_once()   # ack carries epoch-1: fenced
            assert w.failovers.get("stale_leader") == 1
            # the walk re-joined the (still healthy) leader and the
            # follow-up heartbeat saw the true epoch again
            assert w.epoch == leader.epoch == 1
            assert "w1" in leader.topology()["members"]

    def test_ack_drop_counts_as_a_missed_ack(self):
        leader, build = make_leader(rank=0)
        with AppRunner(build=build) as runner:
            w = agent(runner.port, "w1",
                      faults="ack_drop:at=1,times=2")
            w.join()
            w._heartbeat_once()
            w._heartbeat_once()
            # single-candidate fleet: misses accumulate, no walk
            assert w._missed_acks == 2
            assert w.failovers == {}

    def test_single_candidate_worker_keeps_pre_ha_behavior(self):
        leader, build = make_leader(rank=0)
        with AppRunner(build=build) as runner:
            w = agent(runner.port, "w1")
            w.join()
            assert w.candidates == (f"http://127.0.0.1:{runner.port}",)
            assert w.missed_acks_before_failover == 3


# ----------------------------------------------------------- discovery
class TestDiscovery:
    def test_control_leader_route_and_probe(self):
        leader, build = make_leader(
            rank=0, candidates=("http://a:1", "http://b:2"))
        with AppRunner(build=build) as runner:
            info = probe_leader(f"http://127.0.0.1:{runner.port}")
            assert info["active"] is True
            assert info["epoch"] == 1
            assert info["rank"] == 0
            assert info["candidates"] == ["http://a:1", "http://b:2"]
            assert "heartbeat_interval_s" in info
        # dead candidate: a None, never an exception
        assert probe_leader(f"http://127.0.0.1:{runner.port}",
                            timeout_s=0.2) is None

    def test_resolve_leader_prefers_highest_epoch_active(self):
        leader0, build0 = make_leader(rank=0)
        leader1, build1 = make_leader(rank=1)
        with AppRunner(build=build0) as r0, \
                AppRunner(build=build1) as r1:
            urls = (f"http://127.0.0.1:{r0.port}",
                    f"http://127.0.0.1:{r1.port}")
            got = resolve_leader(urls)
            assert (got["rank"], got["epoch"]) == (0, 1)
            # takeover elsewhere: the standby now out-ranks by epoch
            leader1.ensure_active(worker_epoch=1)
            got = resolve_leader(urls)
            assert (got["rank"], got["epoch"]) == (1, 2)
            # fencing rule: an active below epoch_at_least is skipped
            got = resolve_leader(urls[:1], epoch_at_least=2)
            assert got is None


# ------------------------------------------------- leave during takeover
class TestLeaveDuringTakeover:
    def test_leave_retries_against_new_leader_and_sticks(self):
        leader0, build0 = make_leader(rank=0)
        leader1, build1 = make_leader(rank=1)
        with AppRunner(build=build0) as r0, \
                AppRunner(build=build1) as r1:
            x = ha_agent((r0.port, r1.port), "x")
            y = ha_agent((r0.port, r1.port), "y")
            x.join()
            y.join()
            # leader0 dies; x starts deregistering INTO the takeover
            # window while y drives the election
            leader0.faults = FaultPlan.parse("leader_down:times=0")
            done: list = []
            t = threading.Thread(
                target=lambda: done.append(x.deregister(rounds=40)))
            t.start()
            y._heartbeat_once()      # miss -> walk -> leader1 active
            t.join(timeout=10)
            assert done == [True]    # the leave landed post-election
            hosts = leader1.topology()["members"]
            assert "x" not in hosts and "y" in hosts
            # a stale heartbeat can never re-adopt the departed host
            x._heartbeat_once()
            assert "x" not in leader1.topology()["members"]

    def test_heartbeat_rejoin_is_suppressed_while_leaving(self):
        leader, build = make_leader(rank=0)
        with AppRunner(build=build) as runner:
            w = agent(runner.port, "w1")
            w.join()
            assert w.deregister() is True
            assert "w1" not in leader.topology()["members"]
            # the leader answers this unknown host with rejoin; the
            # leaving guard must ignore it
            w._heartbeat_once()
            assert "w1" not in leader.topology()["members"]


# ------------------------------------------------------- data-plane gate
class TestRouterGate:
    def _post_chat(self, port):
        import http.client
        import json
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        try:
            conn.request("POST", "/chat",
                         body=json.dumps({"prompt": "hi"}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, dict(resp.getheaders()), \
                json.loads(resp.read().decode() or "{}")
        finally:
            conn.close()

    def test_standby_router_serves_typed_not_leader(self):
        from gofr_tpu.serving.router import RouterConfig
        holder: dict = {}

        def build(app):
            holder["leader"] = app.serve_fleet_leader(
                rank=1, router=RouterConfig(max_retries=0))
        with AppRunner(build=build) as runner:
            status, _, doc = self._post_chat(runner.port)
            assert status == 503
            details = doc["error"]["details"]
            assert details["code"] == "not_leader"

    def test_converging_takeover_serves_retryable_503(self):
        from gofr_tpu.serving.router import RouterConfig
        holder: dict = {}

        def build(app):
            holder["leader"] = app.serve_fleet_leader(
                rank=1, router=RouterConfig(max_retries=0))
        with AppRunner(build=build) as runner:
            holder["leader"].ensure_active(worker_epoch=1)
            status, headers, doc = self._post_chat(runner.port)
            assert status == 503
            details = doc["error"]["details"]
            assert details["code"] == "leader_takeover"
            lowered = {k.lower(): v for k, v in headers.items()}
            assert int(lowered["retry-after"]) >= 1
            # first member join ends the convergence window
            holder["leader"].join("w1", "127.0.0.1:1", 1)
            assert holder["leader"].leadership()["converging"] is False
