"""Deterministic fault injection, crash recovery, and the restartable
engine lifecycle (serving/faults.py + the Engine supervisor).

The chaos contract under test: a plan fires at exact invocation counts
(never wall clock, never RNG); a crashed loop restarts within the
``RestartPolicy`` budget; requests that never emitted a token replay
bit-identically; mid-stream requests fail with a typed *retryable*
reject (no duplicate-token risk); and a stopped engine ``start()``s
again on its resident weights and compile cache."""

import threading
import time

import pytest

from gofr_tpu.serving.engine import (EngineConfig, GenRequest,
                                     RestartPolicy, SamplingParams)
from gofr_tpu.serving.faults import (NO_FAULTS, FaultPlan, FaultSpec,
                                     InjectedFault, plan_from_env,
                                     resolve_plan)
from gofr_tpu.serving.glue import demo_llama_engine

GREEDY = SamplingParams(temperature=0.0, max_new_tokens=6)


def wait_all(reqs, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(r.finished_at is not None or r.error is not None
               for r in reqs):
            return True
        time.sleep(0.01)
    return False


# ------------------------------------------------------------ the plan
class TestFaultPlan:
    def test_parse_full_syntax(self):
        plan = FaultPlan.parse(
            "pass_stall:at=5,seconds=2.5;heartbeat_drop:at=2,times=4;"
            "page_exhaustion:request=tenant-a")
        sites = [s.site for s in plan.specs]
        assert sites == ["pass_stall", "heartbeat_drop", "page_exhaustion"]
        stall, drop, pool = plan.specs
        assert (stall.at, stall.seconds) == (5, 2.5)
        assert (drop.at, drop.times) == (2, 4)
        assert pool.request == "tenant-a"
        # unparameterised defaults: fire once, on the first invocation
        spec = FaultPlan.parse("pass_raise").specs[0]
        assert (spec.at, spec.times) == (1, 1)

    def test_blank_parses_to_the_disabled_singleton(self):
        # identity matters: every call site guards with `is not NO_FAULTS`
        assert FaultPlan.parse("") is NO_FAULTS
        assert FaultPlan.parse("  ") is NO_FAULTS
        assert resolve_plan(FaultPlan(())) is NO_FAULTS

    def test_bad_plans_fail_loudly(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            FaultPlan.parse("meteor_strike")
        with pytest.raises(ValueError, match="key=value"):
            FaultPlan.parse("pass_raise:after=3")
        with pytest.raises(ValueError, match="at >= 1"):
            FaultPlan.parse("pass_raise:at=0")
        with pytest.raises(TypeError):
            resolve_plan(42)

    def test_unknown_site_error_names_token_and_valid_sites(self):
        # a typo'd GOFR_FAULTS silently arming nothing would make a
        # chaos drill vacuously green — the message must hand the
        # operator the bad token AND the menu
        with pytest.raises(ValueError) as err:
            FaultPlan.parse("pass_raise:at=2;leeder_down")
        msg = str(err.value)
        assert "'leeder_down'" in msg
        assert "leader_down" in msg          # the valid-site list
        assert "pass_raise" in msg

    def test_stray_semicolon_is_rejected(self):
        with pytest.raises(ValueError, match="stray ';'"):
            FaultPlan.parse("pass_raise:at=2;")
        with pytest.raises(ValueError, match="stray ';'"):
            FaultPlan.parse(";pass_raise")
        with pytest.raises(ValueError, match="stray ';'"):
            FaultPlan.parse("pass_raise;;heartbeat_drop")

    def test_missing_site_name_is_rejected(self):
        with pytest.raises(ValueError, match="missing site name"):
            FaultPlan.parse(":at=2")

    def test_bad_pair_errors_name_the_offending_token(self):
        # not key=value at all
        with pytest.raises(ValueError, match=r"'at'.*key=value"):
            FaultPlan.parse("pass_raise:at")
        # unknown key, named in the clause
        with pytest.raises(ValueError, match=r"'when=3'"):
            FaultPlan.parse("pass_raise:when=3")
        # non-numeric payloads name the value they choked on
        with pytest.raises(ValueError, match=r"integer.*'soon'"):
            FaultPlan.parse("pass_raise:at=soon")
        with pytest.raises(ValueError, match=r"number.*'fast'"):
            FaultPlan.parse("pass_stall:seconds=fast")

    def test_env_resolution(self, monkeypatch):
        monkeypatch.setenv("GOFR_FAULTS", "pass_raise:at=7")
        plan = plan_from_env()
        assert plan.specs[0].at == 7
        assert resolve_plan(None).specs[0].at == 7
        monkeypatch.delenv("GOFR_FAULTS")
        assert resolve_plan(None) is NO_FAULTS

    def test_trip_fires_by_invocation_count_only(self):
        plan = FaultPlan([FaultSpec(site="pass_raise", at=3, times=2)])
        assert plan.trip("pass_raise") is False       # invocation 1
        assert plan.trip("pass_raise") is False       # invocation 2
        for _ in range(2):                            # 3 and 4: armed
            with pytest.raises(InjectedFault, match="pass_raise"):
                plan.trip("pass_raise")
        assert plan.trip("pass_raise") is False       # 5: window closed
        assert plan.fired == {"pass_raise": 2}
        plan.reset()                                  # rewind: same movie
        assert plan.trip("pass_raise") is False
        assert plan.fired == {}

    def test_times_zero_fires_forever(self):
        plan = FaultPlan([FaultSpec(site="heartbeat_drop", at=2, times=0)])
        got = [plan.trip("heartbeat_drop") for _ in range(5)]
        assert got == [False, True, True, True, True]

    def test_request_tag_gates_the_counter(self):
        # untagged invocations must not advance a tagged spec's trigger
        plan = FaultPlan([FaultSpec(site="page_exhaustion", at=2,
                                    request="tenant-a")])
        assert plan.trip("page_exhaustion") is False              # untagged
        assert plan.trip("page_exhaustion",
                         request_id="tenant-b") is False          # other tag
        assert plan.trip("page_exhaustion",
                         request_id="tenant-a") is False          # count 1
        assert plan.trip("page_exhaustion",
                         request_id="tenant-a") is True           # count 2
        assert plan.trip("page_exhaustion") is False


def test_restart_policy_backoff_is_exponential_and_capped():
    policy = RestartPolicy(backoff_s=0.1, backoff_mult=2.0,
                           max_backoff_s=0.5)
    assert [policy.backoff_for(n) for n in (1, 2, 3, 4, 5)] \
        == [0.1, 0.2, 0.4, 0.5, 0.5]


# -------------------------------------------------- engine fault sites
def test_page_exhaustion_is_a_typed_503_not_a_crash():
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, faults="page_exhaustion:at=1"))
    eng.start()
    try:
        hit = eng.submit_sync([1, 2, 3], GREEDY)
        assert hit.error and "kv page pool exhausted" in hit.error
        assert hit.reject is not None
        assert hit.reject.code == "kv_exhausted"
        assert hit.reject.retry_after_s > 0
        # the engine did NOT crash: the next submit serves normally
        ok = eng.submit_sync([1, 2, 3], GREEDY)
        assert ok.error is None and len(ok.generated) == 6
        assert eng.health_check()["status"] == "UP"
    finally:
        eng.stop()


def test_pass_raise_restarts_within_budget_and_replays_bit_identical():
    """The headline chaos invariant: with a crash injected mid-traffic,
    every request either completes bit-identically to the fault-free
    run or fails with the typed retryable ``engine_restart`` reject —
    and a client-side retry of those lands bit-identically too."""
    prompts = [[1 + i, 2, 3] for i in range(4)]
    ref = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64, seed=11))
    ref.start()
    want = [ref.submit_sync(p, GREEDY).generated for p in prompts]
    ref.stop()

    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, seed=11, faults="pass_raise:at=2",
        restart_policy=RestartPolicy(max_restarts=3, backoff_s=0.01)))
    eng.start()
    try:
        reqs = [eng.submit(p, GREEDY) for p in prompts]
        assert wait_all(reqs)
        for prompt, req, expect in zip(prompts, reqs, want):
            if req.error is not None:
                # mid-stream at the crash: must be the typed reject
                assert req.reject is not None \
                    and req.reject.code == "engine_restart", req.error
                req = eng.submit(prompt, GREEDY)
                assert wait_all([req]) and req.error is None
            assert req.generated == expect
        health = eng.health_check()
        assert health["status"] == "UP"
        assert health["restarts"] == 1
        assert "injected fault: pass_raise" in health["last_crash"]
    finally:
        eng.stop()


def test_restart_budget_exhaustion_is_terminal():
    # every pass raises: the supervisor burns its budget, then _crash
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, faults="pass_raise:times=0",
        restart_policy=RestartPolicy(max_restarts=2, backoff_s=0.01)))
    eng.start()
    try:
        req = eng.submit([1, 2, 3], GREEDY)
        assert wait_all([req], timeout=30)
        deadline = time.time() + 10
        while time.time() < deadline \
                and eng.health_check()["status"] != "DOWN":
            time.sleep(0.01)
        health = eng.health_check()
        assert health["status"] == "DOWN"
        assert health["restarts"] == 2
        assert "injected fault" in health["error"]
    finally:
        eng.stop()


def test_nan_logits_rejects_mid_stream_as_retryable():
    """The fault fires at decode *collect* — tokens already emitted —
    so recovery must take the typed-reject branch, never silently
    replay (the no-duplicate-token invariant)."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, faults="nan_logits:at=3",
        restart_policy=RestartPolicy(max_restarts=3, backoff_s=0.01)))
    eng.start()
    try:
        req = eng.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                   max_new_tokens=20))
        assert wait_all([req])
        assert req.error is not None
        assert req.reject is not None
        assert req.reject.code == "engine_restart"
        assert "retry" in req.reject.message
        # partial output stopped mid-stream; the engine itself healed
        assert 0 < len(req.generated) < 20
        ok = eng.submit_sync([1, 2, 3], GREEDY)
        assert ok.error is None and len(ok.generated) == 6
    finally:
        eng.stop()


def test_recover_salvage_rules_whitebox():
    """The discriminator, pinned: ``first_token_at is None`` goes to
    the recovery buffer flagged ``recovered`` (re-prefill priced as
    preempt_recompute); anything mid-stream gets the typed reject."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64,
        restart_policy=RestartPolicy(max_restarts=1, backoff_s=0.01)))
    fresh = GenRequest(prompt_tokens=[1, 2, 3], params=GREEDY)
    fresh.slot = 0
    mid = GenRequest(prompt_tokens=[4, 5, 6], params=GREEDY)
    mid.slot = 1
    mid.first_token_at = time.time()
    mid.generated.append(42)
    eng.active[0], eng.active[1] = fresh, mid
    eng._running = True          # supervisor only runs on a live engine
    try:
        assert eng._recover(RuntimeError("boom")) is True
    finally:
        eng._running = False
    assert fresh in eng._requeued and fresh.recovered
    assert fresh.slot == -1 and fresh.error is None
    assert mid.error is not None and mid.reject.code == "engine_restart"
    assert eng._restarts == 1 and "boom" in eng._last_crash
    # budget exhausted -> terminal
    eng._running = True
    eng.active[0] = None
    try:
        assert eng._recover(RuntimeError("again")) is False
    finally:
        eng._running = False


# ------------------------------------------------ restartable lifecycle
@pytest.mark.parametrize("layout", [
    {"kv_layout": "slot"},
    {"kv_layout": "paged", "page_size": 16},
], ids=["slot", "paged"])
def test_stop_start_stop_cycle_serves_identically(layout):
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64,
                                         seed=3, **layout))
    eng.start()
    first = eng.submit_sync([1, 2, 3], GREEDY)
    assert first.error is None
    eng.stop()
    # the stopped window: submissions get the typed engine_down 503
    down = eng.submit([1, 2, 3], GREEDY)
    assert down.error is not None
    assert down.reject is not None and down.reject.code == "engine_down"
    # restart in place: resident weights + compile cache, clean KV
    eng.start()
    second = eng.submit_sync([1, 2, 3], GREEDY)
    assert second.error is None
    assert second.generated == first.generated
    eng.stop()
    assert eng.health_check()["status"] == "DOWN"


def test_concurrent_stop_callers_are_safe():
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64))
    eng.start()
    req = eng.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                               max_new_tokens=100))
    while req.first_token_at is None and req.error is None:
        time.sleep(0.01)
    errors = []

    def stopper():
        try:
            eng.stop()
        except Exception as exc:  # pragma: no cover - the failure mode
            errors.append(exc)

    threads = [threading.Thread(target=stopper) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors
    assert req.finished_at is not None and req.error == "engine stopped"
    # and the pile-up did not wedge the lifecycle: restart still works
    eng.start()
    ok = eng.submit_sync([1, 2, 3], GREEDY)
    assert ok.error is None
    eng.stop()


def test_drain_completes_inflight_and_refuses_new():
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=128))
    eng.start()
    inflight = eng.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                    max_new_tokens=40))
    while inflight.first_token_at is None and inflight.error is None:
        time.sleep(0.01)
    result = {}

    def drainer():
        result["drained"] = eng.drain(timeout_s=60.0)

    t = threading.Thread(target=drainer)
    t.start()
    # inside the drain window: new work is refused with a typed 503
    deadline = time.time() + 5
    refused = None
    while time.time() < deadline and not eng._draining:
        time.sleep(0.002)
    if eng._draining:  # the in-flight request is still running
        refused = eng.submit([7, 8, 9], GREEDY)
    t.join(90)
    assert result["drained"] is True
    assert inflight.error is None and len(inflight.generated) == 40
    if refused is not None:
        assert refused.reject is not None
        assert refused.reject.code == "draining"
    # drained engines restart like stopped ones
    eng.start()
    ok = eng.submit_sync([1, 2, 3], GREEDY)
    assert ok.error is None
    eng.stop()


def test_timed_out_stop_counts_stranded_slots():
    """pass_stall wedges the loop past stop()'s join budget: the timed
    -out path must count the stranded slots into health_check and keep
    the thread handle so start() refuses until the pass retires."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, faults="pass_stall:at=2,seconds=1.5"))
    # queue the request BEFORE start: pass 1 admits it, pass 2 stalls
    req = eng.submit([1, 2, 3], GREEDY)
    eng.start()
    deadline = time.time() + 10
    while time.time() < deadline \
            and not any(r is not None for r in eng.active):
        time.sleep(0.01)
    assert any(r is not None for r in eng.active)
    eng.stop(join_timeout_s=0.1)          # far below the 1.5s stall
    health = eng.health_check()
    assert health["stranded_slots"] == 1
    # start() during the wedged pass must refuse, not corrupt caches
    with pytest.raises(RuntimeError, match="still in a device call"):
        eng.start()
    # the pass completes; the thread retires the stream itself
    deadline = time.time() + 30
    while time.time() < deadline and eng._thread.is_alive():
        time.sleep(0.05)
    assert not eng._thread.is_alive()
    assert req.finished_at is not None
    # and now the engine restarts cleanly, stranded count cleared
    eng.start()
    ok = eng.submit_sync([1, 2, 3], GREEDY)
    assert ok.error is None
    assert "stranded_slots" not in eng.health_check()
    eng.stop()


def test_restart_counters_reach_the_registry():
    from gofr_tpu.metrics.registry import Manager
    metrics = Manager()
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, faults="pass_raise:at=2",
        restart_policy=RestartPolicy(max_restarts=2, backoff_s=0.01)),
        metrics=metrics)
    eng.start()
    try:
        reqs = [eng.submit([1 + i, 2, 3], GREEDY) for i in range(3)]
        assert wait_all(reqs)
        deadline = time.time() + 10
        while time.time() < deadline \
                and metrics.get("app_engine_restarts").get() < 1.0:
            time.sleep(0.01)
        assert metrics.get("app_engine_restarts").get() == 1.0
        scrape = metrics.render_prometheus()
        assert "app_engine_requests_recovered" in scrape
    finally:
        eng.stop()


def test_sigterm_drain_completes_inflight_requests():
    """The app's signal path must DRAIN served engines — the in-flight
    stream finishes (no "engine stopped" cut-off) before the hard-stop
    hooks run — and still complete shutdown."""
    from .apputil import AppRunner
    from gofr_tpu.serving.tokenizer import ByteTokenizer
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=128))

    def build(app):
        app.serve_model("llm", eng, ByteTokenizer())

    with AppRunner(build=build) as runner:
        req = eng.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                   max_new_tokens=40))
        while req.first_token_at is None and req.error is None:
            time.sleep(0.01)
        runner._loop.call_soon_threadsafe(runner.app._signal_stop)
        deadline = time.time() + 60
        while time.time() < deadline \
                and not runner.app._stop_event.is_set():
            time.sleep(0.05)
        assert runner.app._stop_event.is_set()
        assert req.error is None and len(req.generated) == 40
        assert not eng._running


# --------------------------------------------- control-plane fault sites
def _leader(**kw):
    from gofr_tpu.serving.control_plane import ControlPlaneLeader
    leader = ControlPlaneLeader(coordinator="10.0.0.1:8476", **kw)

    def build(app):
        leader.install(app)
    return leader, build


def _agent(runner, host_id, **kw):
    from gofr_tpu.serving.control_plane import WorkerAgent
    return WorkerAgent(f"http://127.0.0.1:{runner.port}",
                       host_id=host_id, n_devices=4,
                       heartbeat_interval_s=0.05, **kw)


def test_join_retries_back_off_with_jitter(monkeypatch):
    """With the leader refusing every join, retry delays must grow
    exponentially from the heartbeat interval to the cap, jittered —
    never a fixed-cadence thundering herd."""
    import time as real_time

    from gofr_tpu.serving import control_plane

    class FakeTime:
        def __init__(self):
            self.delays = []

        def sleep(self, d):
            self.delays.append(d)
            real_time.sleep(0.001)  # yield without waiting the delay out

        def __getattr__(self, name):
            return getattr(real_time, name)

    fake = FakeTime()
    monkeypatch.setattr(control_plane, "time", fake)
    plan = FaultPlan.parse("join_refused:times=0")  # refuse forever
    agent = control_plane.WorkerAgent(
        "http://127.0.0.1:1", host_id="unwanted",
        heartbeat_interval_s=0.1, join_backoff_max_s=0.8, faults=plan)
    agent.start()
    try:
        deadline = real_time.time() + 10
        while real_time.time() < deadline \
                and plan.fired.get("join_refused", 0) < 8:
            real_time.sleep(0.01)
        assert plan.fired.get("join_refused", 0) >= 8
    finally:
        agent.stop()
    delays = fake.delays
    # first retry: one heartbeat interval, jittered x0.5-1.5
    assert 0.05 <= delays[0] <= 0.15
    # the ramp reached well past the base (0.15 is the base ceiling)
    assert max(delays) >= 0.4
    # and respected cap x max-jitter
    assert max(delays) <= 0.8 * 1.5 + 1e-9
    assert agent.assignment is None


def test_join_refused_then_recovers():
    """A leader refusing the first joins (rolling restart) is survived:
    the retry loop lands the join once the refusal window closes."""
    from .apputil import AppRunner
    leader, build = _leader()
    with AppRunner(build=build) as runner:
        plan = FaultPlan.parse("join_refused:times=2")
        agent = _agent(runner, "w", faults=plan)
        agent.start()      # initial join trips 1; loop retries 2, 3...
        try:
            deadline = time.time() + 10
            while time.time() < deadline and agent.assignment is None:
                time.sleep(0.02)
            assert agent.assignment is not None
            assert plan.fired["join_refused"] == 2
            assert leader.topology()["world_size"] == 1
        finally:
            agent.stop()


def test_heartbeat_drop_leads_to_timeout_eviction():
    """Dropping every heartbeat (lossy control network) must look to
    the leader exactly like a dead host: sweeper eviction with
    reason=heartbeat_timeout."""
    from .apputil import AppRunner
    leader, build = _leader(heartbeat_interval_s=0.1, eviction_misses=2)
    with AppRunner(build=build) as runner:
        agent = _agent(runner, "mute",
                       faults="heartbeat_drop:times=0")
        agent.start()
        try:
            deadline = time.time() + 10
            while time.time() < deadline \
                    and leader.topology()["world_size"] != 0:
                time.sleep(0.05)
            assert leader.topology()["world_size"] == 0
            assert leader.metrics.get("app_fleet_evictions").get(
                reason="heartbeat_timeout") == 1.0
        finally:
            agent.stop()


def test_deregister_leaves_immediately_and_suppresses_rejoin():
    """The SIGTERM drain path: deregister() tells the leader NOW (no
    heartbeat-silence wait), survivors re-rank, and the agent's own
    retry loop must not quietly rejoin afterwards."""
    from .apputil import AppRunner
    leader, build = _leader()
    with AppRunner(build=build) as runner:
        leaving = _agent(runner, "leaving")
        staying = _agent(runner, "staying")
        leaving.start()
        staying.start()
        try:
            deadline = time.time() + 10
            while time.time() < deadline and (
                    leaving.assignment is None
                    or staying.assignment is None):
                time.sleep(0.02)
            assert leader.topology()["world_size"] == 2
            leaving.deregister()
            topo = leader.topology()
            assert topo["world_size"] == 1
            assert "leaving" not in topo["members"]
            assert leader.metrics.get("app_fleet_evictions").get(
                reason="leave") == 1.0
            # several heartbeat intervals later: still out (no rejoin)
            time.sleep(0.4)
            assert leaving.assignment is None
            assert leader.topology()["world_size"] == 1
        finally:
            leaving.stop()
            staying.stop()


