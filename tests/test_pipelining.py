"""Adaptive decode-pipelining policy (VERDICT r4 #1).

The decode pipeline (keep one dispatched pass in flight, collect it
after the next dispatch) only pays at saturation: below
``pipeline_min_slots`` actively-decoding slots the one-wasted-pass-per-
retirement and the one-pass token lag cost more than the host/device
overlap buys.  These tests pin the policy at both ends by observing the
in-flight queue depth at collect time:

  * ``len(engine._pending) >= 2`` at a collect means a second pass was
    dispatched while the first was still uncollected — overlap engaged;
  * always ``== 1`` means every pass was collected before the next
    dispatch — depth 0, serialised.
"""

import time

from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine


def _observe_depths(eng):
    """Record len(_pending) at every _decode_collect entry."""
    seen = []
    orig = eng._decode_collect

    def spy():
        seen.append(len(eng._pending))
        return orig()

    eng._decode_collect = spy
    return seen


def _run(eng, n_reqs, gen_len):
    eng.start()
    sp = SamplingParams(temperature=0.0, max_new_tokens=gen_len)
    reqs = [eng.submit([1 + i, 2, 3], sp) for i in range(n_reqs)]
    deadline = time.time() + 120
    while time.time() < deadline:
        if all(r.finished_at is not None or r.error is not None
               for r in reqs):
            break
        time.sleep(0.005)
    eng.stop()
    assert all(r.error is None for r in reqs)
    assert all(len(r.generated) == gen_len for r in reqs)
    return reqs


def test_pipeline_engages_at_saturation():
    """16 decoding slots >= pipeline_min_slots: passes must overlap."""
    eng = demo_llama_engine(EngineConfig(max_batch=16, max_seq=128,
                                         seed=0))
    depths = _observe_depths(eng)
    _run(eng, n_reqs=16, gen_len=24)  # 3 decode passes each at K=8
    assert depths, "no decode passes collected"
    assert max(depths) >= 2, (
        f"pipeline never engaged at max_batch=16: collect-time depths "
        f"{depths}")


def test_pipeline_depth_zero_below_threshold():
    """4 slots < pipeline_min_slots: every pass collects before the
    next dispatch (the r4 tiny-config regression mode)."""
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                         seed=0))
    depths = _observe_depths(eng)
    _run(eng, n_reqs=8, gen_len=24)
    assert depths, "no decode passes collected"
    assert max(depths) == 1, (
        f"pipelined below the slot threshold: collect-time depths "
        f"{depths}")


def test_pipeline_depth_override_forces_overlap():
    """Explicit pipeline_depth=1 engages regardless of batch size."""
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                         seed=0, pipeline_depth=1))
    depths = _observe_depths(eng)
    _run(eng, n_reqs=4, gen_len=24)
    assert depths and max(depths) >= 2


def test_greedy_output_identical_across_depths():
    """The pipeline is a scheduling detail: token streams must not
    depend on it."""
    outs = []
    for depth in (0, 1, None):
        eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                             seed=0,
                                             pipeline_depth=depth))
        reqs = _run(eng, n_reqs=4, gen_len=16)
        outs.append([r.generated for r in reqs])
    assert outs[0] == outs[1] == outs[2]
