"""Cron schedule parsing + scheduler loop tests."""

import asyncio
import time

import pytest

from gofr_tpu.container.mock import MockContainer
from gofr_tpu.cron import Cron, CronParseError, Schedule


def t(sec=0, minute=0, hour=0, day=1, month=1, weekday_py=0):
    # build struct_time-like via time.struct_time
    return time.struct_time((2026, month, day, hour, minute, sec, weekday_py, 1, -1))


def test_parse_five_field_wildcard():
    s = Schedule.parse("* * * * *")
    assert s.matches(t(sec=0, minute=30, hour=12))
    assert not s.matches(t(sec=5, minute=30))  # seconds default to 0


def test_parse_six_field_seconds():
    s = Schedule.parse("*/15 * * * * *")
    assert s.matches(t(sec=0)) and s.matches(t(sec=45))
    assert not s.matches(t(sec=7))


def test_parse_ranges_lists_steps():
    s = Schedule.parse("0-10/5 9,17 * * *")
    assert s.matches(t(minute=0, hour=9))
    assert s.matches(t(minute=5, hour=17))
    assert s.matches(t(minute=10, hour=9))
    assert not s.matches(t(minute=3, hour=9))
    assert not s.matches(t(minute=0, hour=12))


def test_weekday_convention():
    # cron 0 = Sunday; python tm_wday 6 = Sunday
    s = Schedule.parse("0 0 * * 0")
    assert s.matches(t(weekday_py=6))
    assert not s.matches(t(weekday_py=0))  # Monday


def test_parse_errors():
    with pytest.raises(CronParseError):
        Schedule.parse("* * *")
    with pytest.raises(CronParseError):
        Schedule.parse("61 * * * *")
    with pytest.raises(CronParseError):
        Schedule.parse("a * * * *")
    with pytest.raises(CronParseError):
        Schedule.parse("*/0 * * * *")


def test_cron_fires_matching_jobs():
    container = MockContainer()
    cron = Cron(container)
    fired = []
    cron.add("* * * * * *", "tick", lambda ctx: fired.append(time.time()))
    failing = []

    def bad(ctx):
        failing.append(1)
        raise RuntimeError("job blew up")
    cron.add("* * * * * *", "bad", bad)

    async def run():
        task = asyncio.ensure_future(cron.run())
        await asyncio.sleep(2.3)
        task.cancel()

    asyncio.run(run())
    assert len(fired) >= 2  # every-second job fired each tick
    assert len(failing) >= 2
    # panic recovery logged, loop survived
    assert any("bad" in str(l.get("message", ""))
               for l in container.log_lines)
