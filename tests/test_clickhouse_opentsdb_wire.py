"""ClickHouse HTTP-interface and OpenTSDB REST wire clients against
their mini servers."""

import pytest

from gofr_tpu.datasource.clickhouse_wire import (
    ClickhouseWire, ClickhouseWireError, MiniClickhouseServer,
    expand_placeholders)
from gofr_tpu.datasource.opentsdb_wire import (
    MiniOpenTSDBServer, OpenTSDBWire, OpenTSDBWireError)


@pytest.fixture(scope="module")
def ch():
    srv = MiniClickhouseServer()
    srv.start()
    client = ClickhouseWire(endpoint=f"127.0.0.1:{srv.port}")
    client.connect()
    yield client
    srv.close()


@pytest.fixture(scope="module")
def tsdb():
    srv = MiniOpenTSDBServer()
    srv.start()
    client = OpenTSDBWire(endpoint=f"127.0.0.1:{srv.port}")
    client.connect()
    yield client
    srv.close()


# ------------------------------------------------------------ clickhouse

def test_ch_roundtrip_jsoneachrow(ch):
    ch.exec("CREATE TABLE events (id INTEGER, kind TEXT, val REAL)")
    ch.exec("INSERT INTO events VALUES (?, ?, ?)", 1, "click", 0.5)
    ch.async_insert("INSERT INTO events VALUES (?, ?, ?)", 2, "view", 1.5)
    rows = ch.select("SELECT id, kind, val FROM events ORDER BY id")
    assert rows == [{"id": 1, "kind": "click", "val": 0.5},
                    {"id": 2, "kind": "view", "val": 1.5}]


def test_ch_placeholder_escaping(ch):
    ch.exec("CREATE TABLE quotes (s TEXT)")
    tricky = "O'Brien said \\ 'hi'"
    ch.exec("INSERT INTO quotes VALUES (?)", tricky)
    assert ch.select("SELECT s FROM quotes")[0]["s"] == tricky


def test_ch_placeholder_inside_literal_not_expanded():
    assert expand_placeholders("SELECT 'a?b', ?", (1,)) \
        == "SELECT 'a?b', 1"
    with pytest.raises(ClickhouseWireError):
        expand_placeholders("SELECT ?", ())
    with pytest.raises(ClickhouseWireError):
        expand_placeholders("SELECT 1", (5,))


def test_ch_null_and_bool_literals(ch):
    ch.exec("CREATE TABLE flags (a INTEGER, b INTEGER)")
    ch.exec("INSERT INTO flags VALUES (?, ?)", None, True)
    row = ch.select("SELECT a, b FROM flags")[0]
    assert row["a"] is None and row["b"] == 1


def test_ch_format_word_in_identifier_still_gets_json(ch):
    ch.exec("CREATE TABLE fmt (format_version INTEGER)")
    ch.exec("INSERT INTO fmt VALUES (?)", 3)
    # 'format' inside an identifier must not suppress the FORMAT clause
    assert ch.select("SELECT format_version FROM fmt") \
        == [{"format_version": 3}]


def test_ch_error_surfaces(ch):
    with pytest.raises(ClickhouseWireError, match="DB::Exception"):
        ch.select("SELECT * FROM nonexistent_table")


def test_ch_health(ch):
    assert ch.health_check()["status"] == "UP"
    assert ClickhouseWire(endpoint="127.0.0.1:1").health_check()["status"] \
        == "DOWN"


# ------------------------------------------------------------- opentsdb

def test_tsdb_put_and_query(tsdb):
    n = tsdb.put_data_points([
        {"metric": "sys.cpu", "timestamp": 100, "value": 1.0,
         "tags": {"host": "a"}},
        {"metric": "sys.cpu", "timestamp": 160, "value": 3.0,
         "tags": {"host": "b"}},
    ])
    assert n == 2
    result = tsdb.query("sys.cpu", aggregator="sum")
    assert result["dps"] == {"100": 1.0, "160": 3.0}
    assert result["value"] == 4.0


def test_tsdb_query_with_tags_and_range(tsdb):
    tsdb.put_data_points([
        {"metric": "sys.mem", "timestamp": 10, "value": 5.0,
         "tags": {"host": "a"}},
        {"metric": "sys.mem", "timestamp": 20, "value": 7.0,
         "tags": {"host": "b"}},
    ])
    only_a = tsdb.query("sys.mem", aggregator="max", tags={"host": "a"})
    assert only_a["dps"] == {"10": 5.0}
    ranged = tsdb.query("sys.mem", start=15, end=25)
    assert ranged["dps"] == {"20": 7.0}


def test_tsdb_annotations(tsdb):
    tsdb.put_annotation({"startTime": 50, "description": "deploy v2"})
    tsdb.put_annotation({"startTime": 500, "description": "deploy v3"})
    found = tsdb.query_annotations(0, 100)
    assert [a["description"] for a in found] == ["deploy v2"]


def test_tsdb_bad_point_is_an_error(tsdb):
    with pytest.raises(OpenTSDBWireError):
        tsdb.put_data_points([{"metric": "x"}])  # no timestamp/value


def test_tsdb_health(tsdb):
    health = tsdb.health_check()
    assert health["status"] == "UP"
    assert health["details"]["version"].startswith("2.4")
    assert OpenTSDBWire(endpoint="127.0.0.1:1").health_check()["status"] \
        == "DOWN"
