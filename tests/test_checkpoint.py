"""Checkpoint/resume: roundtrip fidelity, bf16, atomicity, GC,
sharded restore onto a mesh, train-state resume."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gofr_tpu.checkpoint import Checkpointer, CheckpointError
from gofr_tpu.models.llama import LlamaConfig, llama_init


def tree_equal(a, b):
    leaves_a = jax.tree.leaves(a)
    leaves_b = jax.tree.leaves(b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(leaves_a, leaves_b))


class TestRoundtrip:
    def test_param_tree_roundtrip(self, tmp_path):
        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.key(0), cfg)
        ckpt = Checkpointer(tmp_path)
        ckpt.save(100, params, metadata={"config": "tiny"})
        restored = ckpt.restore(like=params)
        assert tree_equal(params, restored)
        assert ckpt.restore_metadata()["config"] == "tiny"

    def test_bf16_leaves_roundtrip(self, tmp_path):
        tree = {"w": jnp.asarray([[1.5, -2.25]], jnp.bfloat16),
                "b": jnp.asarray([3], jnp.int32)}
        ckpt = Checkpointer(tmp_path)
        ckpt.save(1, tree)
        restored = ckpt.restore(like=tree)
        assert restored["w"].dtype == jnp.bfloat16
        assert tree_equal(tree, restored)

    def test_flat_restore_without_like(self, tmp_path):
        ckpt = Checkpointer(tmp_path)
        ckpt.save(5, {"a": jnp.ones((2,)), "nest": {"b": jnp.zeros((3,))}})
        flat = ckpt.restore()
        assert set(flat) == {"['a']", "['nest']['b']"}

    def test_structure_mismatch_raises(self, tmp_path):
        ckpt = Checkpointer(tmp_path)
        ckpt.save(1, {"a": jnp.ones((2,))})
        with pytest.raises(CheckpointError, match="structure mismatch"):
            ckpt.restore(like={"a": jnp.ones((2,)), "b": jnp.ones((2,))})


class TestVersioning:
    def test_latest_and_explicit_steps(self, tmp_path):
        ckpt = Checkpointer(tmp_path)
        for step in (10, 20, 30):
            ckpt.save(step, {"v": jnp.asarray([step])})
        assert ckpt.latest_step() == 30
        assert int(ckpt.restore(step=20)["['v']"][0]) == 20
        assert int(ckpt.restore()["['v']"][0]) == 30

    def test_keep_budget_gc(self, tmp_path):
        ckpt = Checkpointer(tmp_path, keep=2)
        for step in range(5):
            ckpt.save(step, {"v": jnp.asarray([step])})
        assert ckpt.steps() == [3, 4]

    def test_duplicate_step_rejected(self, tmp_path):
        ckpt = Checkpointer(tmp_path)
        ckpt.save(1, {"v": jnp.ones(1)})
        with pytest.raises(CheckpointError, match="already saved"):
            ckpt.save(1, {"v": jnp.ones(1)})

    def test_no_checkpoint_raises(self, tmp_path):
        with pytest.raises(CheckpointError, match="no checkpoints"):
            Checkpointer(tmp_path).restore()

    def test_half_written_temp_is_invisible(self, tmp_path):
        ckpt = Checkpointer(tmp_path)
        ckpt.save(1, {"v": jnp.ones(1)})
        # a crashed save leaves only a temp dir — never a listed step
        (tmp_path / ".tmp_save_dead").mkdir()
        (tmp_path / "step_9").mkdir()  # no manifest -> incomplete
        assert ckpt.steps() == [1]


class TestShardedRestore:
    def test_restore_onto_mesh(self, tmp_path):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devices = np.array(jax.devices()[:8]).reshape(8)
        mesh = Mesh(devices, ("tp",))
        tree = {"wq": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                "norm": jnp.ones((8,), jnp.float32)}
        ckpt = Checkpointer(tmp_path)
        ckpt.save(1, tree)

        def sharding_for(key):
            if "wq" in key:
                return NamedSharding(mesh, P("tp", None))
            return NamedSharding(mesh, P())

        restored = ckpt.restore(like=tree, sharding_fn=sharding_for)
        assert tree_equal(tree, restored)
        # the leaf really is sharded over the mesh axis
        shard_shapes = {s.data.shape for s in restored["wq"].addressable_shards}
        assert shard_shapes == {(1, 8)}


class TestTrainResume:
    def test_train_state_resume_matches_uninterrupted(self, tmp_path):
        """Save at step 2, restore, continue 2 more steps — identical to
        4 uninterrupted steps (bitwise, CPU determinism)."""
        from gofr_tpu.parallel.mesh import create_mesh
        from gofr_tpu.parallel.train import make_train_state, make_train_step
        cfg = LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                          n_kv_heads=2, ffn_dim=64, max_seq=32,
                          dtype=jnp.float32)
        mesh = create_mesh({"dp": 1, "tp": 1}, jax.devices()[:1])
        step_fn = make_train_step(cfg, mesh)

        def batch(i):
            toks = jax.random.randint(jax.random.key(i), (2, 17), 0, 64)
            return toks[:, :-1], toks[:, 1:], jnp.ones((2, 16), jnp.int32)

        state, _ = make_train_state(jax.random.key(0), cfg, mesh)
        for i in range(4):
            state, loss_ref = step_fn(state, *batch(i))

        state2, _ = make_train_state(jax.random.key(0), cfg, mesh)
        ckpt = Checkpointer(tmp_path)
        for i in range(2):
            state2, _ = step_fn(state2, *batch(i))
        ckpt.save(2, state2)
        resumed = ckpt.restore(like=state2)
        for i in range(2, 4):
            resumed, loss_resumed = step_fn(resumed, *batch(i))
        assert float(loss_ref) == float(loss_resumed)
        assert tree_equal(jax.tree.leaves(state), jax.tree.leaves(resumed))


def test_warm_start_hook(tmp_path):
    import asyncio
    from gofr_tpu.app import App
    from gofr_tpu.checkpoint import warm_start
    from gofr_tpu.config.env import DictConfig
    from gofr_tpu.serving.engine import EngineConfig
    from gofr_tpu.serving.glue import llama_engine

    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.key(3), cfg)
    Checkpointer(tmp_path).save(7, params)

    app = App(config=DictConfig({"HTTP_PORT": "0", "METRICS_PORT": "0"}))
    built = {}

    def build(restored):
        assert tree_equal(params, restored)
        engine = llama_engine(restored, cfg,
                              EngineConfig(max_batch=2, max_seq=64,
                                           prefill_buckets=(16,)))
        built["engine"] = engine
        return engine

    warm_start(app, "llama", tmp_path, build)

    async def boot():
        await app.start()
        await app.stop()
    asyncio.run(boot())
    assert built["engine"] is app.container.get_model("llama")
    assert "llama" in app.container.tpu.engines
