"""Couchbase (memcached binary KV + N1QL HTTP) and ScyllaDB (CQL)
wire clients against their mini servers."""

import pytest

from gofr_tpu.datasource.cassandra_wire import (MiniCassandraServer,
                                                ScyllaWire)
from gofr_tpu.datasource.couchbase_wire import (CouchbaseWire,
                                                CouchbaseWireError,
                                                MiniCouchbaseServer)
from gofr_tpu.datasource.document import DocumentError, DocumentNotFound


@pytest.fixture(scope="module")
def server():
    srv = MiniCouchbaseServer(username="app", password="pw")
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def cb(server):
    client = CouchbaseWire(
        host="127.0.0.1", kv_port=server.kv_port,
        query_endpoint=f"127.0.0.1:{server.query_port}",
        username="app", password="pw")
    client.connect()
    yield client
    client.close()


def test_kv_roundtrip_over_binary_protocol(cb):
    cb.upsert("profiles", "u1", {"name": "ada", "score": 9})
    assert cb.get("profiles", "u1") == {"name": "ada", "score": 9}
    cb.upsert("profiles", "u1", {"name": "ada", "score": 10})
    assert cb.get("profiles", "u1")["score"] == 10
    cb.remove("profiles", "u1")
    with pytest.raises(DocumentNotFound):
        cb.get("profiles", "u1")
    with pytest.raises(DocumentNotFound):
        cb.remove("profiles", "u1")


def test_insert_conflicts_on_existing_key(cb):
    cb.upsert("tickets", "t1", {"state": "open"})
    with pytest.raises(DocumentError, match="duplicate"):
        cb.insert("tickets", "t1", {"state": "new"})
    cb.insert("tickets", "t2", {"state": "new"})
    assert cb.get("tickets", "t2")["state"] == "new"


def test_n1ql_query_with_named_args(cb):
    cb.upsert("fleet", "a", {"kind": "v5e", "up": True})
    cb.upsert("fleet", "b", {"kind": "v5p", "up": True})
    cb.upsert("fleet", "c", {"kind": "v5e", "up": False})
    rows = cb.query("fleet", {"kind": "v5e", "up": True})
    assert len(rows) == 1 and rows[0]["up"] is True
    assert len(cb.query("fleet")) == 3


def test_injection_shaped_identifiers_rejected(cb):
    with pytest.raises(CouchbaseWireError, match="invalid field"):
        cb.query("fleet", {'x` = "" OR 1=1 OR `y': "v"})
    with pytest.raises(CouchbaseWireError, match="invalid bucket"):
        cb.query("b` d; DROP `x", {})


def test_wrong_password_rejected(server):
    bad = CouchbaseWire(host="127.0.0.1", kv_port=server.kv_port,
                        username="app", password="WRONG")
    with pytest.raises(CouchbaseWireError, match="SASL"):
        bad.connect()


def test_health(cb):
    health = cb.health_check()
    assert health["status"] == "UP"
    assert "PLAIN" in health["details"]["mechs"]


def test_scylla_speaks_cql(tmp_path):
    srv = MiniCassandraServer()
    srv.start()
    try:
        db = ScyllaWire(host="127.0.0.1", port=srv.port)
        db.connect()
        db.exec("CREATE TABLE heat (id INTEGER, c REAL)")
        db.exec("INSERT INTO heat VALUES (?, ?)", 1, 42.0)
        assert db.query("SELECT c FROM heat")[0]["c"] == 42.0
        assert db.metric == "app_scylladb_stats"
        db.close()
    finally:
        srv.close()
