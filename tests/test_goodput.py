"""Goodput observatory: device-time waste attribution with a hard
conservation invariant (useful + sum(waste causes) == busy, per pass
kind and cumulatively), memory watermarks (monotone non-decreasing
within a run), the post-warmup recompile sentinel (fires exactly once
per novel shape, silent on warm shapes), per-tenant waste columns in
the usage ledger, fleet-summary waste fields, and the replay
efficiency-divergence report.

The zero-hot-path invariant itself (transfer guard + greedy
bit-identity with the meter ON) is pinned by test_observability.py —
the meter defaults on, so those tests already run with it.
"""

import json
import math
import time

import pytest

from gofr_tpu.metrics.registry import Manager as MetricsManager
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.observability import (GoodputMeter,
                                            RecompileSentinel,
                                            UsageLedger,
                                            WatermarkTracker)
from gofr_tpu.serving.replay import (efficiency_divergence,
                                     parse_workload, replay_workload)


def _drive(eng, prompts, n, *, tenants=None, timeout=120):
    """Submit + drain on an already-started engine (engines are not
    restartable: tests needing several waves share one session)."""
    sp = SamplingParams(temperature=0.0, max_new_tokens=n)
    reqs = [eng.submit(p, sp,
                       tenant=tenants[i] if tenants else None)
            for i, p in enumerate(prompts)]
    deadline = time.time() + timeout
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return reqs


def _run(eng, prompts, n, *, tenants=None, timeout=120):
    eng.start()
    try:
        return _drive(eng, prompts, n, tenants=tenants,
                      timeout=timeout)
    finally:
        eng.stop()


def _assert_conserved(meter: GoodputMeter) -> None:
    """THE invariant: every accounted busy second is classified."""
    assert meter.busy_s > 0
    total = meter.useful_s + sum(meter.waste_s.values())
    assert math.isclose(total, meter.busy_s, rel_tol=1e-9,
                        abs_tol=1e-9), (total, meter.busy_s)
    for kind, sub in meter.by_kind.items():
        ktotal = sub["useful_s"] + sum(sub[c] for c in meter.CAUSES)
        assert math.isclose(ktotal, sub["busy_s"], rel_tol=1e-9,
                            abs_tol=1e-9), (kind, sub)


# ---------------------------------------------------------- meter unit
def test_meter_decode_padding_split():
    m = GoodputMeter()
    m.add_decode(1.0, 3, 4)
    assert m.useful_s == pytest.approx(0.75)
    assert m.waste_s["padding"] == pytest.approx(0.25)
    _assert_conserved(m)


def test_meter_prefill_recompute_split():
    m = GoodputMeter()
    # group of 4 padded rows: 2 fresh, 1 recompute, 1 dummy pad
    m.add_prefill("prefill", 2.0, 4, 2, 1)
    assert m.useful_s == pytest.approx(1.0)
    assert m.waste_s["preempt_recompute"] == pytest.approx(0.5)
    assert m.waste_s["padding"] == pytest.approx(0.5)
    _assert_conserved(m)


def test_meter_spec_rejected_split():
    m = GoodputMeter()
    # batch 2, one row drafted 4 accepted 1 (bonus always emits), one
    # row with no drafts (pure decode step: fully useful)
    m.add_spec(1.0, 2, [(4, 1), (0, 0)])
    share = 0.5
    assert m.waste_s["spec_rejected"] == pytest.approx(share * 3 / 5)
    assert m.useful_s == pytest.approx(share * 2 / 5 + share)
    assert m.waste_s["padding"] == pytest.approx(0.0)
    _assert_conserved(m)


def test_meter_bubble_requires_backlog():
    m = GoodputMeter()
    m.note_pass_end(10.0, backlog=False)
    m.note_dispatch(10.5)
    assert m.waste_s["bubble"] == 0.0
    m.note_pass_end(11.0, backlog=True)
    m.note_dispatch(11.25)
    assert m.waste_s["bubble"] == pytest.approx(0.25)
    assert m.busy_s == pytest.approx(0.25)
    # the gap is consumed: a second dispatch opens no new bubble
    m.note_dispatch(12.0)
    assert m.waste_s["bubble"] == pytest.approx(0.25)


def test_meter_disabled_accounts_nothing():
    m = GoodputMeter(enabled=False)
    m.add_decode(1.0, 1, 4)
    m.note_pass_end(1.0, True)
    m.note_dispatch(2.0)
    assert m.busy_s == 0.0 and m.summary().get("goodput_ratio") is None


def test_sentinel_fires_once_and_only_after_seal():
    s = RecompileSentinel()
    assert not s.dispatch(("decode", 0))  # pre-seal: cold compile
    s.observe(("prefill", 64, 1))
    s.seal()
    assert not s.dispatch(("decode", 0))       # seen pre-seal
    assert not s.dispatch(("prefill", 64, 1))  # observed in warmup
    assert s.dispatch(("prefill", 128, 1))     # novel: fires
    assert not s.dispatch(("prefill", 128, 1))  # now warm: silent
    assert s.recompiles == 1
    assert s.state()["signatures"] == ["prefill/128/1"]
    off = RecompileSentinel(enabled=False)
    off.seal()
    assert not off.dispatch(("x",)) and off.recompiles == 0


def test_watermark_tracker_monotone():
    wm = WatermarkTracker()
    assert wm.update("kv_pages", 4.0)
    assert not wm.update("kv_pages", 3.0)  # below the mark: ignored
    assert wm.get("kv_pages") == 4.0
    assert wm.update("kv_pages", 9.0)
    state = wm.state()
    assert state["kv_pages"]["value"] == 9.0
    assert "t" in state["kv_pages"]


# ----------------------------------------------- engine: conservation
def test_decode_conservation_and_padding():
    """Plain decode run on a half-empty batch: the invariant holds and
    the empty slots' device time shows up as padding waste."""
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                         seed=3))
    _run(eng, [[1, 2, 3], [4, 5, 6]], 16)
    _assert_conserved(eng.goodput)
    assert eng.goodput.by_kind["decode"]["busy_s"] > 0
    assert eng.goodput.waste_s["padding"] > 0  # 2 of 4 slots empty
    ratio = eng.goodput.summary()["goodput_ratio"]
    assert 0.0 < ratio <= 1.0


def test_chunk_prefill_conservation():
    """A prompt longer than the widest bucket walks the chunked path;
    its passes are classified and conserved too."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=256, seed=5, prefill_buckets=(16,)))
    _run(eng, [list(range(1, 50))], 8)
    _assert_conserved(eng.goodput)
    assert eng.goodput.by_kind["prefill_chunk"]["busy_s"] > 0


def test_preemption_waste_attributed():
    """Pool pressure forces preemption-by-recompute: the re-prefilled
    device time lands in waste_s['preempt_recompute'], on the
    preempted request's waste_recompute_s, and in its tenant's ledger
    column — conservation still exact."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=128, seed=8,
        kv_layout="paged", page_size=16, kv_pages=8))
    prompts = [list(range(1, 30))] * 4
    reqs = _run(eng, prompts, 24,
                tenants=["acme", "acme", "globex", "globex"])
    assert eng.stats["preemptions"] > 0, "scenario never preempted"
    _assert_conserved(eng.goodput)
    assert eng.goodput.waste_s["preempt_recompute"] > 0
    assert sum(r.waste_recompute_s for r in reqs) > 0
    usage = eng.usage_ledger.rollup()
    total_waste = sum(t["waste_recompute_s"]
                      for t in usage["tenants"].values())
    # rollup rounds each column to 6 decimals — compare at that grain
    assert total_waste == pytest.approx(
        sum(r.waste_recompute_s for r in reqs), abs=1e-5)


def test_spec_verify_conservation():
    """Speculative decoding: verify passes are classified (useful +
    spec_rejected + padding) and conserve."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=256, seed=5, speculative=True,
        spec_ngram=1, decode_steps_per_pass=2))
    pattern = [7, 11, 13, 7, 11, 13, 7, 11]
    _run(eng, [pattern], 24)
    assert eng.stats["spec_passes"] > 0
    _assert_conserved(eng.goodput)
    sub = eng.goodput.by_kind["spec_verify"]
    assert sub["busy_s"] > 0 and sub["useful_s"] > 0


def test_bubble_recorded_under_load():
    """Sequential single-slot decode leaves host gaps between passes
    while the request is mid-generation — the bubble cause must be
    populated (it is the dispatch-overhead number the observatory
    exists to expose)."""
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=128,
                                         seed=2))
    _run(eng, [[1, 2, 3]], 32)
    _assert_conserved(eng.goodput)
    assert eng.goodput.waste_s["bubble"] > 0


# --------------------------------------------------- engine: sentinel
def test_engine_recompile_sentinel_fires_once_on_novel_shape():
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=256,
                                         seed=1))
    eng.warmup(prompt_lens=(32,))
    assert eng.sentinel.sealed
    # warm shape: a prompt inside the warmed 32-bucket stays silent
    _run(eng, [[1, 2, 3]], 4)
    assert eng.stats["recompiles"] == 0

    # novel shape: a prompt in an unwarmed bucket fires exactly once
    eng2 = demo_llama_engine(EngineConfig(max_batch=2, max_seq=256,
                                          seed=1))
    eng2.warmup(prompt_lens=(32,))

    class SpyLogger:
        def __init__(self):
            self.warns = []

        def warn(self, msg, **kw):
            self.warns.append((str(msg), kw))

        def error(self, msg, **kw):
            pass

        def info(self, msg, **kw):
            pass

    eng2.logger = spy = SpyLogger()
    eng2.start()
    try:
        _drive(eng2, [list(range(1, 60))], 4)  # bucket 64: not warmed
        assert eng2.stats["recompiles"] == 1
        fired = [kw for msg, kw in spy.warns if "recompile" in msg]
        assert len(fired) == 1 \
            and "prefill/64" in fired[0]["signature"]
        # same novel shape again: warm now, stays silent
        _drive(eng2, [list(range(1, 60))], 4)
        assert eng2.stats["recompiles"] == 1
        assert eng2.sentinel.state()["recompiles"] == 1
    finally:
        eng2.stop()


def test_unwarmed_engine_never_seals():
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=128,
                                         seed=0))
    _run(eng, [[1, 2, 3]], 4)
    assert not eng.sentinel.sealed
    assert eng.stats["recompiles"] == 0


# ------------------------------------------------- engine: watermarks
def test_engine_watermarks_monotone_within_run():
    m = MetricsManager()
    eng = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=128, seed=0, kv_layout="paged",
        page_size=16, prefix_cache=True))
    eng.attach_metrics(m)
    eng.start()
    try:
        _drive(eng, [[2, 3, 5], [7, 11, 13]], 12)
        first = eng.efficiency_state()["watermarks"]
        assert first["kv_pages"]["value"] > 0
        assert first["host_rss_bytes"]["value"] > 0
        _drive(eng, [list(range(1, 40))], 12)
        second = eng.efficiency_state()["watermarks"]
        for name, mark in first.items():
            assert second[name]["value"] >= mark["value"], (name,
                                                            first,
                                                            second)
        time.sleep(0.3)
        eng._update_gauges()  # past the throttle window
        # the published gauges mirror the marks
        assert m.get("app_engine_kv_pages_watermark").get() \
            == second["kv_pages"]["value"]
    finally:
        eng.stop()


def test_slot_layout_records_kv_rows_watermark():
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=128,
                                         seed=0))
    _run(eng, [[1, 2, 3]], 8)
    marks = eng.efficiency_state()["watermarks"]
    assert marks["kv_rows"]["value"] > 0
    assert "kv_pages" not in marks


# ---------------------------------------------------- metrics surface
def test_waste_counters_and_ratio_published():
    m = MetricsManager()
    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                         seed=3))
    eng.attach_metrics(m)
    eng.start()
    sp = SamplingParams(temperature=0.0, max_new_tokens=24)
    reqs = [eng.submit([1 + i, 2, 3], sp) for i in range(2)]
    deadline = time.time() + 60
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    time.sleep(0.3)  # one throttled gauge refresh past the retires
    eng._update_gauges()
    eng.stop()
    ratio = m.get("app_engine_goodput_ratio").get()
    assert 0.0 < ratio <= 1.0
    waste = m.get("app_engine_waste_seconds")
    published = sum(waste.get(cause=c) for c in GoodputMeter.CAUSES)
    # deltas lag the meter by at most one throttle window: published
    # totals can never exceed the busy time they conserve against
    assert 0.0 < published <= eng.goodput.busy_s + 1e-9


def test_ledger_waste_columns_in_rollup():
    ledger = UsageLedger()
    ledger.record(tenant="acme", status="ok", prompt_tokens=10,
                  completion_tokens=5, device_s=1.0,
                  waste_recompute_s=0.25, waste_spec_s=0.1)
    ledger.record(tenant="acme", status="ok", prompt_tokens=10,
                  completion_tokens=5, device_s=0.5,
                  waste_recompute_s=0.05)
    tot = ledger.rollup()["tenants"]["acme"]
    assert tot["waste_recompute_s"] == pytest.approx(0.3)
    assert tot["waste_spec_s"] == pytest.approx(0.1)
    windowed = ledger.rollup(window_s=3600)["tenants"]["acme"]
    assert windowed["waste_recompute_s"] == pytest.approx(0.3)


def test_fleet_summary_carries_goodput_fields():
    eng = demo_llama_engine(EngineConfig(max_batch=2, max_seq=128,
                                         seed=0))
    _run(eng, [[1, 2, 3]], 8)
    summary = eng.recorder.fleet_summary()
    assert 0.0 < summary["goodput_ratio"] <= 1.0
    assert summary["busy_s"] > 0
    assert set(GoodputMeter.CAUSES) == set(summary["waste_s"])


def test_leader_names_straggler_waste_cause():
    """The straggler WARN and /debug/fleet digest carry the slow
    host's dominant waste cause from its heartbeat summary."""
    from gofr_tpu.serving.control_plane import ControlPlaneLeader

    class SpyLogger:
        def __init__(self):
            self.warns = []

        def warn(self, msg, **kw):
            self.warns.append((str(msg), kw))

        def info(self, msg, **kw):
            pass

        def error(self, msg, **kw):
            pass

    leader = ControlPlaneLeader(logger=(spy := SpyLogger()))
    # three hosts: with only two, max/median can never clear the 2x
    # straggler threshold (the median of two IS their mean)
    for host in ("fast-a", "fast-b", "slow"):
        leader.join(host, f"{host}:1", 1)
    for host in ("fast-a", "fast-b"):
        leader.heartbeat(host, leader.generation, {"status": "UP"},
                         {"pass_p50_s": 0.01, "pass_p95_s": 0.01,
                          "busy_s": 10.0, "useful_s": 9.0,
                          "waste_s": {"padding": 0.5, "bubble": 0.5}})
    leader.heartbeat(
        "slow", leader.generation, {"status": "UP"},
        {"pass_p50_s": 0.5, "pass_p95_s": 0.5,
         "busy_s": 10.0, "useful_s": 4.0,
         "waste_s": {"padding": 1.0, "preempt_recompute": 5.0}})
    digest = leader._recompute_skew()
    assert digest["stragglers"] == ["slow"]
    assert digest["straggler_causes"]["slow"] == "preempt_recompute"
    fleet_gp = digest["goodput"]
    assert fleet_gp["busy_s"] == pytest.approx(30.0)
    assert fleet_gp["goodput_ratio"] == pytest.approx(22.0 / 30.0)
    named = [kw for msg, kw in spy.warns if "straggler" in msg]
    assert named and named[0]["dominant_waste"] == "preempt_recompute"


# -------------------------------------------------- replay divergence
def test_efficiency_divergence_rule():
    rec = {"busy_s": 10.0, "waste_s": {"padding": 1.0,
                                       "preempt_recompute": 0.5}}
    bad = {"busy_s": 10.0, "waste_s": {"padding": 1.1,
                                       "preempt_recompute": 2.0}}
    out = efficiency_divergence(rec, bad)
    assert [d["cause"] for d in out] == ["preempt_recompute"]
    assert out[0]["recorded_share"] == pytest.approx(0.05)
    assert out[0]["replayed_share"] == pytest.approx(0.2)
    assert efficiency_divergence(rec, rec) == []
    assert efficiency_divergence(None, bad) == []
    assert efficiency_divergence(rec, {"busy_s": 0.0}) == []


def test_capture_header_and_replay_report_carry_goodput(tmp_path):
    cfg = dict(max_batch=4, max_seq=128, seed=17,
               workload_capture=True)
    eng = demo_llama_engine(EngineConfig(**cfg))
    _run(eng, [[3 + i, 5, 9] for i in range(3)], 10)
    text = eng.workload.to_jsonl()
    header = json.loads(text.splitlines()[0])
    assert header["goodput"]["busy_s"] > 0
    assert "waste_s" in header["goodput"]

    workload = parse_workload(text)
    replayer = demo_llama_engine(
        EngineConfig(max_batch=4, max_seq=128, seed=17))
    try:
        report = replay_workload(replayer, workload, closed_loop=3,
                                 timeout_s=120)
    finally:
        replayer.stop()
    assert report["bit_identical"], report["divergences"]
    assert report["recorded_goodput"]["busy_s"] > 0
    assert report["replayed_goodput"]["busy_s"] > 0
    assert isinstance(report["efficiency_divergence"], list)


# ------------------------------------------------- capacity estimator
def test_capacity_pick_max_sustainable():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "capacity", os.path.join(os.path.dirname(__file__), "..",
                                 "scripts", "capacity.py"))
    capacity = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(capacity)
    levels = [{"concurrency": 1, "qps": 10, "tripped": False},
              {"concurrency": 2, "qps": 18, "tripped": False},
              {"concurrency": 4, "qps": 19, "tripped": True},
              {"concurrency": 8, "qps": 12, "tripped": False}]
    best = capacity.pick_max_sustainable(levels)
    assert best["concurrency"] == 2  # nothing past the first trip
    assert capacity.pick_max_sustainable(
        [{"concurrency": 1, "qps": 1, "tripped": True}]) is None


def test_capacity_sweep_reports_goodput_curve():
    """Two lenient-SLO levels over a tiny captured workload: each
    level carries qps + goodput + burn state, and the sweep names the
    max sustainable level."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "capacity", os.path.join(os.path.dirname(__file__), "..",
                                 "scripts", "capacity.py"))
    capacity = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(capacity)
    from gofr_tpu.serving.observability import SLOConfig

    cap = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                         seed=9, workload_capture=True))
    _run(cap, [[2 + i, 4, 6] for i in range(4)], 8)
    workload = parse_workload(cap.workload.to_jsonl())

    eng = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                         seed=9))
    try:
        result = capacity.sweep(
            eng, workload, [1, 2],
            SLOConfig(ttft_s=60.0, tpot_s=60.0, e2e_s=120.0),
            timeout_s=120, log=lambda _m: None)
    finally:
        eng.stop()
    assert [e["concurrency"] for e in result["levels"]] == [1, 2]
    for entry in result["levels"]:
        assert entry["qps"] > 0
        assert 0.0 < entry["goodput_ratio"] <= 1.0
        assert not entry["tripped"]
    assert result["max_sustainable_concurrency"] == 2
    assert result["tripped_at"] is None
