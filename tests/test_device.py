"""TPU device registry: enumeration, caching, health, metrics, and the
dead-tunnel timeout path."""

import time

from gofr_tpu.container.mock import new_mock_container
from gofr_tpu.device import DeviceRegistry


def test_enumerates_devices():
    reg = DeviceRegistry()
    devices = reg.devices()
    assert len(devices) >= 1  # virtual cpu mesh from conftest
    d = devices[0]
    assert {"id", "platform", "kind", "process_index"} <= set(d)
    assert reg.device_count() == len(devices)


def test_cache_ttl_avoids_reprobe():
    reg = DeviceRegistry(cache_ttl_s=60)
    reg.devices()
    probes = {"n": 0}
    original = DeviceRegistry._probe

    def counting():
        probes["n"] += 1
        return original()
    reg._probe = counting
    reg.devices()
    assert probes["n"] == 0  # served from cache
    reg.devices(refresh=True)
    assert probes["n"] == 1


def test_health_up_with_engines():
    reg = DeviceRegistry()

    class FakeEngine:
        def health_check(self):
            return {"status": "UP", "steps": 7}
    reg.register_engine("llama", FakeEngine())
    health = reg.health_check()
    assert health["status"] == "UP"
    assert health["details"]["device_count"] >= 1
    assert health["details"]["engines"]["llama"]["steps"] == 7


def test_dead_backend_times_out_and_reports_down():
    reg = DeviceRegistry(probe_timeout_s=0.2, cache_ttl_s=0)

    def hang():
        time.sleep(5)
        return []
    reg._probe = hang
    start = time.time()
    assert reg.devices() == []
    assert time.time() - start < 2.0  # bounded, no hang
    health = reg.health_check()
    assert health["status"] == "DOWN"
    assert "exceeded" in health["details"]["error"]


def test_stale_cache_degrades_instead_of_down():
    reg = DeviceRegistry(cache_ttl_s=0)
    devices = reg.devices()
    assert devices  # real probe worked

    def boom():
        raise ConnectionError("tunnel gone")
    reg._probe = boom
    still = reg.devices()
    assert still == devices  # stale cache served
    assert reg.health_check()["status"] == "DEGRADED"


def test_publish_metrics_sets_gauges():
    c = new_mock_container()
    reg = DeviceRegistry(metrics=c.metrics)
    reg.publish_metrics()
    gauge = c.metrics.get("app_tpu_device_count")
    assert gauge is not None
    # cpu devices may not expose memory_stats; the count gauge must exist
    rendered = c.metrics.render_prometheus()
    assert "app_tpu_device_count" in rendered


def test_serve_model_attaches_registry():
    from gofr_tpu.app import App
    from gofr_tpu.config.env import DictConfig
    from gofr_tpu.serving.glue import demo_llama_engine

    app = App(config=DictConfig({"HTTP_PORT": "0", "METRICS_PORT": "0"}))
    app.serve_model("llama", demo_llama_engine(), chat_path=None)
    assert type(app.container.tpu).__name__ == "DeviceRegistry"
    assert "llama" in app.container.tpu.engines
    health = app.container.health()
    assert "tpu" in health["checks"]
