"""Responder policy tests — the status-code contract from responder.go."""

import json

from gofr_tpu.http import (
    ErrorEntityNotFound,
    ErrorInvalidParam,
    File,
    Partial,
    Raw,
    Redirect,
    Response,
)
from gofr_tpu.http.responder import Responder

r = Responder()


def body(resp):
    return json.loads(resp.body)


def test_get_success_envelope():
    resp = r.respond({"x": 1}, None, "GET")
    assert resp.status == 200
    assert body(resp) == {"data": {"x": 1}}


def test_post_created():
    assert r.respond("made", None, "POST").status == 201


def test_delete_no_content():
    resp = r.respond(None, None, "DELETE")
    assert resp.status == 204
    assert resp.body == b""


def test_error_statuses():
    resp = r.respond(None, ErrorEntityNotFound("id", "9"), "GET")
    assert resp.status == 404
    assert "No entity found with id: 9" in body(resp)["error"]["message"]
    assert r.respond(None, ErrorInvalidParam("age"), "GET").status == 400


def test_unknown_exception_is_500():
    resp = r.respond(None, RuntimeError("boom"), "GET")
    assert resp.status == 500
    assert body(resp)["error"]["message"] == "boom"


def test_partial_content():
    resp = r.respond(Partial(data=[1, 2], error=RuntimeError("replica down")), None, "GET")
    assert resp.status == 206
    b = body(resp)
    assert b["data"] == [1, 2]
    assert "replica down" in b["error"]["message"]


def test_redirect_by_method():
    assert r.respond(Redirect("/new"), None, "GET").status == 302
    assert r.respond(Redirect("/new"), None, "POST").status == 303
    assert r.respond(Redirect("/new"), None, "GET").headers["Location"] == "/new"


def test_file_and_raw():
    resp = r.respond(File(b"PDFDATA", "application/pdf"), None, "GET")
    assert resp.body == b"PDFDATA" and resp.content_type == "application/pdf"
    raw = r.respond(Raw([1, 2, 3]), None, "GET")
    assert json.loads(raw.body) == [1, 2, 3]  # no envelope


def test_response_with_metadata_and_headers():
    resp = r.respond(Response(data={"a": 1}, metadata={"page": 2},
                              headers={"X-Custom": "v"}), None, "GET")
    b = body(resp)
    assert b == {"data": {"a": 1}, "metadata": {"page": 2}}
    assert resp.headers["X-Custom"] == "v"


def test_xml_response():
    from gofr_tpu.http import XML
    resp = r.respond(XML({"name": "a<b", "tags": ["x", "y"]}, root="doc"),
                     None, "GET")
    assert resp.status == 200
    assert resp.content_type.startswith("application/xml")
    assert resp.body == (b'<?xml version="1.0" encoding="UTF-8"?>'
                         b"<doc><name>a&lt;b</name>"
                         b"<tags><item>x</item><item>y</item></tags></doc>")
    assert r.respond(XML({}), None, "POST").status == 201


def test_custom_error_status_code_attr():
    class TeapotError(Exception):
        status_code = 418

    assert r.respond(None, TeapotError("short"), "GET").status == 418
