"""gRPC transport: unary/streaming RPCs, health, observability, errors."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

import grpc as grpc_lib
import pytest

from gofr_tpu.grpc import (
    GRPCClient,
    GRPCService,
    bidi_stream_rpc,
    client_stream_rpc,
    rpc,
    server_stream_rpc,
)

from .apputil import AppRunner, grpc_channel


@dataclass
class Greeting:
    name: str
    excited: bool = False


class GreeterService(GRPCService):
    name = "gofr.test.Greeter"

    @rpc
    def SayHello(self, ctx, request):
        greeting = ctx.bind(Greeting)
        suffix = "!" if greeting.excited else "."
        return {"message": f"hello {greeting.name}{suffix}"}

    @rpc
    def WhoAmI(self, ctx, request):
        # container injection: config reachable from the service handler
        return {"app": self.container.app_name,
                "metadata_probe": ctx.param("x-probe")}

    @rpc
    def Boom(self, ctx, request):
        raise RuntimeError("kaboom")

    @server_stream_rpc
    async def CountTo(self, ctx, request):
        for i in range(int(request["n"])):
            yield {"i": i}

    @client_stream_rpc
    async def Sum(self, ctx, request_iterator):
        total = 0
        async for item in request_iterator:
            total += item["x"]
        return {"total": total}

    @bidi_stream_rpc
    async def EchoAll(self, ctx, request_iterator):
        async for item in request_iterator:
            yield {"echo": item}


def build(app):
    app.register_grpc_service(GreeterService())


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 20))


def grpc_runner() -> AppRunner:
    return AppRunner(build=build, config={"GRPC_PORT": "0"})


class TestGRPC:
    def test_unary_and_dataclass_bind(self):
        with grpc_runner() as r:
            port = r.app.grpc_server.bound_port

            async def go():
                client = GRPCClient(f"127.0.0.1:{port}")
                reply = await client.call("gofr.test.Greeter", "SayHello",
                                          {"name": "ada", "excited": True})
                assert reply == {"message": "hello ada!"}
                await client.close()
            run(go())

    def test_container_injection_and_metadata(self):
        with grpc_runner() as r:
            port = r.app.grpc_server.bound_port

            async def go():
                channel = grpc_channel(port)
                method = channel.unary_unary(
                    "/gofr.test.Greeter/WhoAmI",
                    request_serializer=lambda o: b"{}",
                    response_deserializer=lambda b: __import__("json").loads(b))
                reply = await method({}, metadata=(("x-probe", "42"),))
                assert reply["app"] == "test-app"
                assert reply["metadata_probe"] == "42"
                await channel.close()
            run(go())

    def test_server_streaming(self):
        with grpc_runner() as r:
            port = r.app.grpc_server.bound_port

            async def go():
                client = GRPCClient(f"127.0.0.1:{port}")
                got = [item async for item in
                       client.stream("gofr.test.Greeter", "CountTo", {"n": 4})]
                assert got == [{"i": 0}, {"i": 1}, {"i": 2}, {"i": 3}]
                await client.close()
            run(go())

    def test_client_stream_and_bidi(self):
        with grpc_runner() as r:
            port = r.app.grpc_server.bound_port

            async def go():
                import json
                channel = grpc_channel(port)
                sum_rpc = channel.stream_unary(
                    "/gofr.test.Greeter/Sum",
                    request_serializer=lambda o: json.dumps(o).encode(),
                    response_deserializer=lambda b: json.loads(b))

                async def gen():
                    for x in (1, 2, 3):
                        yield {"x": x}
                reply = await sum_rpc(gen())
                assert reply == {"total": 6}

                bidi = channel.stream_stream(
                    "/gofr.test.Greeter/EchoAll",
                    request_serializer=lambda o: json.dumps(o).encode(),
                    response_deserializer=lambda b: json.loads(b))
                call = bidi(gen())
                got = [item async for item in call]
                assert [g["echo"]["x"] for g in got] == [1, 2, 3]
                await channel.close()
            run(go())

    def test_handler_error_maps_to_internal(self):
        with grpc_runner() as r:
            port = r.app.grpc_server.bound_port

            async def go():
                client = GRPCClient(f"127.0.0.1:{port}")
                with pytest.raises(grpc_lib.aio.AioRpcError) as err:
                    await client.call("gofr.test.Greeter", "Boom", {})
                assert err.value.code() == grpc_lib.StatusCode.INTERNAL
                assert "kaboom" in err.value.details()
                await client.close()
            run(go())

    def test_unknown_method_unimplemented(self):
        with grpc_runner() as r:
            port = r.app.grpc_server.bound_port

            async def go():
                client = GRPCClient(f"127.0.0.1:{port}")
                with pytest.raises(grpc_lib.aio.AioRpcError) as err:
                    await client.call("gofr.test.Greeter", "Nope", {})
                assert err.value.code() == grpc_lib.StatusCode.UNIMPLEMENTED
                await client.close()
            run(go())

    def test_standard_health_protocol(self):
        with grpc_runner() as r:
            port = r.app.grpc_server.bound_port

            async def go():
                client = GRPCClient(f"127.0.0.1:{port}")
                assert await client.health_check() == "SERVING"
                assert await client.health_check("gofr.test.Greeter") == \
                    "SERVING"
                assert await client.health_check("no.such.Service") == \
                    "SERVICE_UNKNOWN"
                await client.close()
            run(go())

    def test_metrics_recorded(self):
        with grpc_runner() as r:
            port = r.app.grpc_server.bound_port

            async def go():
                client = GRPCClient(f"127.0.0.1:{port}")
                await client.call("gofr.test.Greeter", "SayHello",
                                  {"name": "x"})
                await client.close()
            run(go())
            status, _, data = r.request("GET", "/metrics",
                                        port=r.metrics_port)
            assert status == 200
            text = data.decode()
            assert "app_grpc_server_duration" in text
            assert "SayHello" in text
