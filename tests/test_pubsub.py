"""Pub/sub broker + subscriber runtime tests."""

import asyncio

from gofr_tpu.container.mock import MockContainer
from gofr_tpu.pubsub.inmemory import InMemoryBroker, partition_for
from gofr_tpu.pubsub.subscriber import SubscriptionManager


def run(coro):
    return asyncio.run(coro)


def test_publish_subscribe_roundtrip():
    async def flow():
        broker = InMemoryBroker()
        await broker.publish("orders", {"id": 1, "amount": 9.5})
        msg = await broker.subscribe("orders")
        assert msg.topic == "orders"
        assert msg.bind() == {"id": 1, "amount": 9.5}
        msg.commit()
        assert msg.committed
    run(flow())


def test_consumer_groups_each_get_copy():
    async def flow():
        broker = InMemoryBroker()
        broker.create_topic("t")
        # pre-register both groups by subscribing concurrently
        async def consume(group):
            return await broker.subscribe("t", group)
        t1 = asyncio.ensure_future(consume("g1"))
        t2 = asyncio.ensure_future(consume("g2"))
        await asyncio.sleep(0.01)
        await broker.publish("t", b"payload")
        m1, m2 = await asyncio.gather(t1, t2)
        assert m1.value == m2.value == b"payload"
    run(flow())


def test_uncommitted_redelivery():
    async def flow():
        broker = InMemoryBroker()
        await broker.publish("jobs", b"work-1")
        msg = await broker.subscribe("jobs")
        assert not msg.committed
        # simulate crash: never commit; requeue pending
        n = broker.redeliver_uncommitted("jobs")
        assert n == 1
        again = await broker.subscribe("jobs")
        assert again.value == b"work-1"
        again.commit()
        assert broker.redeliver_uncommitted("jobs") == 0
    run(flow())


def test_subscriber_runtime_commit_on_success_only():
    async def flow():
        container = MockContainer()
        broker = InMemoryBroker(metrics=container.metrics)
        container.pubsub = broker
        manager = SubscriptionManager(container)

        seen = []
        calls = {"n": 0}

        def handler(ctx):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("first attempt fails")
            seen.append(ctx.bind())

        await broker.publish("audio", {"file": "a.wav"})
        await manager.handle_one("audio", handler)      # fails -> no commit
        assert broker.redeliver_uncommitted("audio") == 1
        await manager.handle_one("audio", handler)      # succeeds -> commit
        assert seen == [{"file": "a.wav"}]
        assert broker.redeliver_uncommitted("audio") == 0
        # metrics counted both deliveries, one success
        total = container.metrics.get("app_pubsub_subscribe_total_count")
        success = container.metrics.get("app_pubsub_subscribe_success_count")
        assert total.get(topic="audio") == 2
        assert success.get(topic="audio") == 1
    run(flow())


def test_message_implements_request_protocol():
    async def flow():
        broker = InMemoryBroker()
        await broker.publish("t", b"\x00binary", key="k1",
                             metadata={"source": "cam-1"})
        msg = await broker.subscribe("t")
        assert msg.param("source") == "cam-1"
        assert msg.path_param("topic") == "t"
        assert msg.host_name() == "t"
        assert msg.bind() == b"\x00binary"  # non-json stays raw
    run(flow())


def test_partition_for_stable_and_bounded():
    parts = {partition_for(f"key-{i}", 8) for i in range(100)}
    assert parts <= set(range(8))
    assert len(parts) > 3  # spreads
    assert partition_for("abc", 8) == partition_for("abc", 8)
    assert partition_for("x", 1) == 0


def test_app_level_subscription():
    """app.subscribe drives handlers from broker messages end-to-end."""
    from gofr_tpu.app import App
    from gofr_tpu.config import DictConfig
    import threading
    import time as time_mod

    app = App(config=DictConfig({"HTTP_PORT": "0", "METRICS_PORT": "0"}))
    broker = InMemoryBroker()
    app.container.pubsub = broker
    received = []

    @app.subscribe("events")
    def on_event(ctx):
        received.append(ctx.bind())

    stop = {}

    def runner():
        async def main():
            await app.start()
            await broker.publish("events", {"n": 1})
            await broker.publish("events", {"n": 2})
            for _ in range(100):
                if len(received) >= 2:
                    break
                await asyncio.sleep(0.02)
            await app.stop()
        asyncio.run(main())

    t = threading.Thread(target=runner)
    t.start()
    t.join(20)
    assert received == [{"n": 1}, {"n": 2}]


def test_backlog_replayed_to_late_group():
    async def flow():
        broker = InMemoryBroker()
        await broker.publish("t", b"m1")   # nobody listening yet
        await broker.publish("t", b"m2")
        msg = await broker.subscribe("t", "late-group")
        assert msg.value == b"m1"
        msg2 = await broker.subscribe("t", "late-group")
        assert msg2.value == b"m2"
        # a second late group also sees the retained messages
        other = await broker.subscribe("t", "other-group")
        assert other.value == b"m1"
    run(flow())
