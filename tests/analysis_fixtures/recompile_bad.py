"""recompile-hazard: violations — per-request values into static args."""
import functools

import jax


def forward(tokens, width):
    return tokens


_jitted = jax.jit(forward, static_argnums=(1,))
_named = jax.jit(forward, static_argnames=("width",))
_partial = functools.partial(jax.jit, static_argnums=(1,))(forward)


def serve(req):
    out = _jitted(req.tokens, len(req.prompt_tokens))   # L17: tainted position
    out = _named(req.tokens, width=req.width)           # L18: tainted kwarg
    out = _partial(req.tokens, len(req.tokens))         # L19: tainted via partial
    return out


class Engine:
    def build(self):
        self._fwd = jax.jit(forward, static_argnums=(1,))

    def step(self, request):
        # attribute-held wrapper, len() is taint-transparent
        return self._fwd(request.tokens, len(request.tokens))   # L29
