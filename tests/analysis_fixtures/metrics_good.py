"""metric-hygiene: clean twin — literal pairs, plus the registration-loop
idiom the analyzer unrolls statically."""

_GAUGES = (
    ("app_fixture_occupancy", "slots in use"),
    ("app_fixture_queue_depth", "requests waiting"),
)


def setup(metrics):
    metrics.new_counter("app_fixture_requests", "requests served")
    for name, desc in _GAUGES:
        metrics.new_gauge(name, desc)
    for name, desc in (
        ("app_fixture_ttft_seconds", "time to first token"),
    ):
        metrics.new_histogram(name, desc)


def serve(metrics):
    metrics.increment_counter("app_fixture_requests")
    metrics.set_gauge("app_fixture_occupancy", 3.0)
    metrics.set_gauge("app_fixture_queue_depth", 0.0)
    metrics.record_histogram("app_fixture_ttft_seconds", 0.03)
