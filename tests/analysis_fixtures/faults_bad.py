"""hot-path-purity: fault injection inlined in the hot loop — the
anti-pattern serving/faults.py exists to prevent. Lines matter —
test_analysis.py pins them."""
import time

from gofr_tpu.analysis import hot_path


class Engine:
    @hot_path
    def step(self, batch):
        # ad-hoc chaos: trigger state off the wall clock, telemetry
        # written from the dispatch path
        if time.time() > self.fault_deadline:                   # L14
            self.metrics.increment_counter("app_faults_fired")  # L15
            self.logger.warn("injected fault firing")           # L16
            raise RuntimeError("injected fault")
        return self._advance(batch)

    def _advance(self, batch):
        # undecorated helper on the closure: its clock read flags too
        return batch, time.time()                               # L22
