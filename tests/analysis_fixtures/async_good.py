"""blocking-in-async: clean twin."""
import asyncio
import time


async def agent_tick(client):
    await asyncio.sleep(0.5)          # the async way
    t = time.perf_counter()           # timers are fine
    await client.get("/health")       # async HTTP client

    def offload():
        # nested SYNC def: runs in an executor, allowed to block
        time.sleep(0.1)
        with open("/tmp/state.json") as f:
            return f.read()

    return await asyncio.to_thread(offload), t


def plain_sync():
    # not async: blocking is its job
    time.sleep(0.01)
    with open("/tmp/state.json") as f:
        return f.read()
