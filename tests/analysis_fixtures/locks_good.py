"""lock-discipline: clean twin — locked writes, _locked helpers, and
attributes that were never lock-protected to begin with."""
import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._count = 0
        self._name = "pool"     # never touched under a lock anywhere

    def put(self, x):
        with self._lock:
            self._items.append(x)
            self._count += 1

    def drain(self):
        with self._lock:
            self._evict_locked()

    def _evict_locked(self):
        # *_locked naming convention: caller holds the lock
        self._items.clear()
        self._count = 0

    def rename(self, name):
        # _name has no locked mutation anywhere -> not in the lockset
        self._name = name


class Unlocked:
    # a class with no lock at all is never flagged
    def __init__(self):
        self.state = 0

    def bump(self):
        self.state += 1
