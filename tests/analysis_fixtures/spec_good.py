"""spec drafting/controller contract: the clean twin — none of this
may be flagged."""
import time

from gofr_tpu.analysis import hot_path, hot_path_boundary


class Engine:
    @hot_path
    def decode_pass(self, state):
        # the hot loop only DECIDES to speculate; everything hosty
        # lives behind the drafting boundary, where the walk stops
        return self._draft_proposals(state)

    @hot_path_boundary(
        "drafting policy is host work priced against the multi-token "
        "verify pass it gates, not paid per decode pass")
    def _draft_proposals(self, state):
        self.metrics.add_counter("app_engine_spec_drafted", 1.0)
        self.logger.info("drafting")
        return time.time()
