"""spec drafting/controller contract: violations. Lines matter —
test_analysis.py pins them."""
import time

import numpy as np

from gofr_tpu.analysis import hot_path


class Engine:
    @hot_path
    def decode_pass(self, state, logits):
        drafts = self._draft(state)           # closure reaches _draft
        t0 = time.time()                      # L14: wall clock inline
        self.metrics.add_counter("app_engine_spec_drafted", 1.0)  # L15
        self.logger.info("drafted")           # L16: logging inline
        return drafts, t0

    def _draft(self, state):
        # undecorated drafting helper reached from the hot root: the
        # per-pass context rescan's device read and the controller's
        # wall-clock pricing must flag
        host = np.asarray(state)              # L23: d2h sync
        started = time.time()                 # L24: wall clock
        return list(host), started
