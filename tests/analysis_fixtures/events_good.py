"""hot-path-purity: the clean twin — a ring-buffered EventLedger whose
``emit`` is a @hot_path_boundary (the serving/events.py pattern): the
purity walk stops at the ledger, so state transitions recorded from
boundary code never drag clocks or counters into the hot closure.
None of this may be flagged."""
import time

from gofr_tpu.analysis import hot_path, hot_path_boundary


class EventLedger:
    @hot_path_boundary("event emission: the ring append, wall-clock "
                       "stamp and counters are host-side bookkeeping "
                       "— the purity walk stops here by design")
    def emit(self, kind, **attrs):
        # inside the boundary anything goes — this models
        # serving/events.py EventLedger.emit
        event = {"ts": time.time(), "kind": kind, "attrs": attrs}
        self.ring.append(event)
        self.metrics.increment_counter("app_events_total", kind=kind)
        return event


NO_EVENTS = EventLedger()


class Engine:
    @hot_path
    def step(self, batch):
        # the recorded transition: one boundary call, nothing inline
        if self.events is not NO_EVENTS:
            self.events.emit("engine.step")
        return self._advance(batch)

    def _advance(self, batch):
        return batch
