"""hot-path-purity: the clean scheduler twin — admission and retire
decisions exit the hot closure through @hot_path_boundary entry
points (the serving/scheduler.py contract). None of this may flag."""
import time

from gofr_tpu.analysis import hot_path, hot_path_boundary


class Engine:
    @hot_path
    def admit_pass(self, batch):
        # the hot root only touches the boundary entry points — the
        # walk stops there, exactly like the engine loop calling the
        # real Scheduler's pop_batch/starvation hook
        taken = self._sched_pop(len(batch))
        self._sched_retire(taken)
        return taken

    @hot_path_boundary(
        "admission boundary: lock-guarded host bookkeeping off the decode graph")
    def _sched_pop(self, n):
        # inside the boundary the scheduler may consult clocks, burn
        # rates and metrics — that is the point of the boundary
        self.metrics.set_gauge("app_sched_lane_depth", float(n))
        return time.time()

    @hot_path_boundary(
        "retire boundary: per-tenant burn bookkeeping fed at request retire")
    def _sched_retire(self, t):
        self.metrics.increment_counter("app_sched_rejections")
        self.logger.warn("shed episode", t=t)

    def reconfigure(self):
        # undecorated and unreachable from any hot root (an app-thread
        # config swap): not scanned
        self.logger.info("scheduler reconfigured")
        return time.time()
