"""metric-hygiene: violations (unregistered write, orphan registration,
dynamic name)."""


def setup(metrics):
    metrics.new_counter("app_orphan_total",         # L5: registered, never written
                        "no write anywhere")
    metrics.new_gauge("app_used_gauge", "written below")


def serve(metrics, name):
    metrics.set_gauge("app_used_gauge", 1.0)
    metrics.increment_counter("app_never_registered")   # L12: write w/o registration
    metrics.record_histogram(name, 0.5)                 # L13: dynamic name
