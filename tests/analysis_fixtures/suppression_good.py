"""Valid suppressions: reasoned allow on the finding line silences it."""
import time

from gofr_tpu.analysis import hot_path


@hot_path
def dispatch():
    return time.time()  # gofrlint: allow(hot-path-purity) -- fixture: wall clock here is the test's point


@hot_path
def dispatch_multi(metrics):
    metrics.set_gauge("app_fixture_g", time.time())  # gofrlint: allow(hot-path-purity, metric-hygiene) -- fixture: one allow may cover several rules
