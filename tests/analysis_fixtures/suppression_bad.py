"""bad-suppression: a reason-less allow, a stale allow, a typo'd rule."""
import time

from gofr_tpu.analysis import hot_path


@hot_path
def dispatch():
    return time.time()  # gofrlint: allow(hot-path-purity)

# stale — nothing on this line violates anything
x = 1  # gofrlint: allow(lock-discipline) -- guards a finding that is not here


@hot_path
def dispatch2():
    return time.time()  # gofrlint: allow(hot-path-purty) -- typo'd rule id covers nothing
