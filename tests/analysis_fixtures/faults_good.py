"""hot-path-purity: the clean twin — deterministic fault sites behind
the NO_FAULTS identity guard and a @hot_path_boundary trip (the
serving/faults.py pattern). None of this may be flagged."""
import time

from gofr_tpu.analysis import hot_path, hot_path_boundary


class FaultPlan:
    @hot_path_boundary("fault injection: when a plan is armed, firing "
                       "the fault IS the point — the disabled default "
                       "never reaches this method")
    def trip(self, site):
        # inside the boundary anything goes — this models FaultPlan.trip
        self.fired[site] = self.fired.get(site, 0) + 1
        self.logger.warn("injected fault firing", site=site)
        time.sleep(self.seconds)
        return True


NO_FAULTS = FaultPlan()


class Engine:
    @hot_path
    def step(self, batch):
        # the compiled-in site: one identity comparison when disabled
        if self.faults is not NO_FAULTS:
            self.faults.trip("pass_raise")
        return self._advance(batch)

    def _advance(self, batch):
        return batch
