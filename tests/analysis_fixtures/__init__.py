# Fixture snippets for gofrlint's unit tests (tests/test_analysis.py).
# These files are PARSED by the analyzer, never imported or executed —
# each <rule>_bad.py seeds known violations at known lines, each
# <rule>_good.py is the clean twin. Not linted by CI's repo run
# (scripts/lint.py gofr_tpu/ scripts/ bench.py excludes tests/).
