"""hot-path-purity: the clean twin — an IntegrityPlane whose ``fold``
is a @hot_path_boundary (the serving/integrity.py pattern): the digest
runs over token ids the collect already emitted as host ints, and the
mismatch counter/WARN live inside the boundary. None of this may be
flagged."""

from gofr_tpu.analysis import hot_path, hot_path_boundary


class IntegrityPlane:
    @hot_path_boundary("digest fold at the retire boundary: one "
                       "blake2b over already-emitted host token ids "
                       "plus probe bookkeeping — once per request, "
                       "never per pass; the purity walk stops here")
    def fold(self, req):
        # inside the boundary anything goes — this models
        # serving/integrity.py IntegrityPlane.fold
        digest = self.fingerprint(req.prompt_tokens, req.generated)
        req.digest = digest
        if req.probe and digest != req.probe_expected:
            self.metrics.increment_counter(
                "app_engine_integrity_failures", kind="probe_mismatch")
            self.logger.warn("golden probe digest mismatch",
                             golden=req.probe)
        return digest


DISABLED = IntegrityPlane()


class Engine:
    @hot_path
    def retire(self, req):
        # the fold: one boundary call at retire, nothing inline
        if self.integrity is not DISABLED:
            self.integrity.fold(req)
        return self._finish(req)

    def _finish(self, req):
        return req
