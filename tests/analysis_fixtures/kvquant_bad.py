"""kv-quant-boundary: violations. Lines matter — test_analysis.py
pins them."""
import jax
import numpy as np

from gofr_tpu.ops.paged_kv import (gather_view, scatter_chunk,
                                   scatter_decode)


def fused_prefill(kc, vc, tables, k, v, kv_len, zeros):
    kc = scatter_chunk(kc, tables, k.astype(kc.dtype),  # L11: boundary cast
                       zeros, kv_len)
    vc = scatter_chunk(vc, tables,
                       v.astype(vc.dtype),              # L14: boundary cast
                       zeros, kv_len)
    return kc, vc


def fused_chunk(kp, vp, tables, offsets, width):
    k_view = gather_view(kp, tables)
    kp = scatter_decode(kp, tables,
                        k_view.astype(kp.dtype),        # L22: boundary cast
                        offsets, width)
    vp = vp.astype("bfloat16")                          # L24: pool cast
    return kp, vp


def debug_dump(pool, k_cache):
    host = np.asarray(pool["q"])                        # L29: host readback
    jax.device_get(k_cache)                             # L30: host readback
    k_cache.block_until_ready()                         # L31: host sync
    return host
