"""kv-quant-boundary: clean twin. The scatters own the pool
representation — callers hand them raw rows (quantize-on-write for
int8 pools, an internal cast for plain ones) and read the pool back
only through the jitted gather."""
from gofr_tpu.ops.paged_kv import (gather_view, scatter_chunk,
                                   scatter_decode)


def fused_prefill(kc, vc, tables, k, v, kv_len, zeros):
    # no .astype at the boundary: the scatter casts/quantizes on write
    kc = scatter_chunk(kc, tables, k, zeros, kv_len)
    vc = scatter_chunk(vc, tables, v, zeros, kv_len)
    return kc, vc


def fused_chunk(kp, vp, tables, offsets, width, view_dtype):
    # the gather dequantizes to the model dtype; rows written back raw
    k_view = gather_view(kp, tables, dtype=view_dtype)
    kp = scatter_decode(kp, tables, k_view, offsets, width)
    return kp, k_view


def sample_rows(k, kc):
    # casting NON-pool rows elsewhere is fine — only the pool and its
    # writer boundaries are protected
    return k.astype("float32")
