"""hot-path-purity: output fingerprinting inlined in the decode
collect — the anti-pattern serving/integrity.py exists to prevent.
Lines matter — test_analysis.py pins them."""
import numpy as np

from gofr_tpu.analysis import hot_path


class Engine:
    @hot_path
    def collect(self, step, reqs):
        # ad-hoc fingerprinting: a device download plus telemetry
        # writes inline in the collect path, once per PASS
        toks = np.asarray(step.tokens)                           # L14
        for req in reqs:
            req.fold.update(bytes(toks[req.row]))
            if req.fold.hexdigest() != req.expected:
                self.metrics.increment_counter("app_integrity")  # L18
                self.logger.warn("digest diverged", req=req.id)  # L19
        return self._stamp(reqs)

    def _stamp(self, reqs):
        # undecorated helper on the closure: its download flags too
        return [bytes(np.asarray(r.state)) for r in reqs]        # L24
