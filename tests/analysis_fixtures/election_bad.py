"""election contract: violations — unlocked lease-state mutations and
clock/RNG-driven election decisions (nondeterministic failover)."""
import random
import time
import threading


class Lease:
    def __init__(self):
        self._lock = threading.Lock()
        self.epoch = 0
        self.active = False

    def activate(self, worker_epoch):
        with self._lock:
            self.epoch = max(self.epoch, worker_epoch) + 1
            self.active = True          # establishes: lease state locked

    def racy_demote(self):
        self.active = False             # L20: unlocked assignment
        self.epoch = self.epoch - 1     # L21: unlocked assignment

    def choose(self, probes):
        # wall-clock tiebreak + RNG pick: the same probe list elects a
        # different leader on every run — a failover drill that cannot
        # reproduce under bisect (TestElectionContract bans both)
        if int(time.time()) % 2:
            return probes[0]
        return random.choice(probes)
