"""The router contract, honored: the async proxy path awaits all its
IO, and digest assembly exits the hot closure through a declared
@hot_path_boundary — the serving/router.py + Engine._refresh_prefix_
digest contract. None of this may flag."""
import asyncio
import time

from gofr_tpu.analysis import hot_path, hot_path_boundary


class Router:
    async def proxy(self, ctx):
        # pure async data plane: upstream IO awaits, the setpoint file
        # was read once at install time, health rides the heartbeats
        reader, writer = await asyncio.open_connection("worker", 8476)
        writer.write(b"POST /chat HTTP/1.1\r\n\r\n")
        await writer.drain()
        chunk = await reader.read(65536)
        writer.close()
        return chunk


class Engine:
    @hot_path
    def collect(self, batch):
        # the hot root only touches the declared boundary — digest
        # work happens at the throttled gauge cadence, not per pass
        self._refresh_prefix_digest()
        return len(batch)

    @hot_path_boundary(
        "digest assembly at the throttled gauge cadence: host-side "
        "hashing over cache keys already resident, published by "
        "atomic reference swap")
    def _refresh_prefix_digest(self):
        # inside the boundary the digest may consult clocks and write
        # its gauges — that is the point of the boundary
        self.digest_at = time.time()
        self.metrics.set_gauge("app_router_cache_hit_ratio", 1.0)
        self.logger.info("digest rebuilt")
