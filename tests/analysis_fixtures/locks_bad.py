"""lock-discipline: violations."""
import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []        # __init__ is exempt AND establishes nothing
        self._count = 0

    def put(self, x):
        with self._lock:
            self._items.append(x)     # establishes: _items is protected
            self._count += 1          # establishes: _count is protected

    def racy_put(self, x):
        self._items.append(x)         # L17: unlocked .append() mutation

    def racy_reset(self):
        self._count = 0               # L20: unlocked assignment

    def racy_pop(self):
        return self._items.pop()      # L23: unlocked .pop() mutation
