"""hot-path-purity: the clean twin — none of this may be flagged."""
import time
import numpy as np
import jax.numpy as jnp

from gofr_tpu.analysis import hot_path, hot_path_boundary


class Engine:
    @hot_path
    def dispatch(self, state, logits):
        t0 = time.perf_counter()        # sanctioned timer
        staged = jnp.asarray(state)     # h2d upload, stays on device
        buf = np.zeros(4, np.int32)     # host alloc, no device involved
        n = int(buf[0])                 # coerces a HOST value: legal
        self._retire(n)                 # boundary: walk stops there
        return staged, t0

    @hot_path_boundary("terminal path: host assembly at retire is the design")
    def _retire(self, n):
        # inside a boundary anything goes — this is the point of it
        self.metrics.increment_counter("app_engine_retires")
        self.logger.info("retired", n=n)
        return time.time()

    def cold_path(self):
        # undecorated and unreachable from a @hot_path root: not scanned
        self.metrics.increment_counter("app_cold")
        return time.time(), np.asarray([1])
