"""hot-path-purity: the clean twin — a per-signature CostModel whose
``observe`` is a @hot_path_boundary (the serving/costmodel.py
pattern): the EWMA fold, drift compare and counter bump are host-side
bookkeeping over durations the collect already measured, so the purity
walk stops at the model. None of this may be flagged."""

from gofr_tpu.analysis import hot_path, hot_path_boundary


class CostModel:
    @hot_path_boundary("cost-model fold at the collect boundary: EWMA "
                       "and drift compares over host floats the "
                       "collect already measured — the purity walk "
                       "stops here by design")
    def observe(self, kind, sig, dur_s):
        # inside the boundary anything goes — this models
        # serving/costmodel.py CostModel.observe
        rec = self.table.setdefault(sig, {"ewma": dur_s, "n": 0})
        rec["ewma"] += self.alpha * (dur_s - rec["ewma"])
        rec["n"] += 1
        self.metrics.increment_counter("app_cost_observed", kind=kind)
        return rec


DISABLED = CostModel()


class Engine:
    @hot_path
    def step(self, batch, dur_s):
        # the fold: one boundary call, nothing inline
        if self.costs is not DISABLED:
            self.costs.observe("decode", batch.sig, dur_s)
        return self._advance(batch)

    def _advance(self, batch):
        return batch
