"""recompile-hazard: clean twin — bucketing breaks the taint; constants
and config-derived statics are fine."""
import jax


def forward(tokens, width):
    return tokens


_jitted = jax.jit(forward, static_argnums=(1,))
_plain = jax.jit(forward)   # no statics: never a hazard source


def _bucket_for(n):
    b = 16
    while b < n:
        b *= 2
    return b


def serve(req, config):
    # routed through the bucketing helper: sanctioned
    out = _jitted(req.tokens, _bucket_for(len(req.tokens)))
    # config-derived static: compiles once per deployment, not per request
    out = _jitted(req.tokens, config.max_seq)
    # literal static
    out = _jitted(req.tokens, 128)
    # no statics involved at all
    return _plain(out)
