"""The router contract, violated both ways: blocking IO inside the
async proxy path, and prefix-digest assembly inlined in the engine's
hot loop. Lines matter — test_analysis.py pins them."""
import time

import requests

from gofr_tpu.analysis import hot_path


class Router:
    async def proxy(self, ctx):
        # the async data plane must never block the event loop: a
        # sleep, a setpoint-file read or a sync health probe stalls
        # EVERY stream the leader is proxying
        time.sleep(0.05)                                 # L16: blocks
        requests.get("http://worker:8476/healthz")       # L17: sync HTTP
        with open("/etc/router/setpoint.json") as f:     # L18: sync IO
            self.setpoint = f.read()
        return await self.forward(ctx)


class Engine:
    @hot_path
    def collect(self, batch):
        # digest assembly inlined in a hot root: hashing, clocks and
        # telemetry ride every decode pass instead of the throttled
        # gauge boundary
        self.digest_at = time.time()                     # L29: clock
        self.metrics.set_gauge(                          # L30: metric
            "app_router_cache_hit_ratio", 1.0)
        return self._hash_cache(batch)

    def _hash_cache(self, batch):
        # undecorated digest helper statically reached from the hot
        # root: the closure walk must flag it too
        self.logger.info("digest rebuilt")               # L37: logging
        return len(batch)
