"""election contract: clean twin — every lease mutation holds the
lock, and the election is a pure function of ranks and epochs."""
import threading


class Lease:
    def __init__(self):
        self._lock = threading.Lock()
        self.epoch = 0
        self.active = False

    def activate(self, worker_epoch):
        with self._lock:
            self.epoch = max(self.epoch, worker_epoch) + 1
            self.active = True

    def demote(self):
        with self._lock:
            self.active = False

    @staticmethod
    def choose(probes, known_epoch):
        # counts and epochs only: deterministic for a given probe list
        live = [p for p in probes
                if p["active"] and p["epoch"] >= known_epoch]
        if live:
            return min(live, key=lambda p: (-p["epoch"], p["rank"]))
        return min(probes, key=lambda p: p["rank"]) if probes else None
