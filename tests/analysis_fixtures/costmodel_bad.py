"""hot-path-purity: pass-cost accounting inlined in the hot loop —
the anti-pattern serving/costmodel.py exists to prevent. Lines matter
— test_analysis.py pins them."""
import time

from gofr_tpu.analysis import hot_path


class Engine:
    @hot_path
    def step(self, batch):
        # ad-hoc cost accounting: wall-clock read, counter and log
        # write from the dispatch path
        self.costs[batch.sig] = time.time() - self.t0            # L14
        self.metrics.increment_counter("app_cost_drift")         # L15
        self.logger.warn("pass cost drifted", sig=batch.sig)     # L16
        return self._price(batch)

    def _price(self, batch):
        # undecorated helper on the closure: its clock read flags too
        return batch, time.time() - self.t0                      # L21
