"""blocking-in-async: violations."""
import time
import subprocess
import requests
import urllib.request


async def agent_tick():
    time.sleep(0.5)                               # L9: blocks the loop
    requests.get("http://example.com/health")     # L10: sync HTTP
    urllib.request.urlopen("http://example.com")  # L11: sync HTTP
    subprocess.run(["true"])                      # L12: subprocess wait
    with open("/tmp/state.json") as f:            # L13: sync file IO
        return f.read()
