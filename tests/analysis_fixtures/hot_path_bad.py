"""hot-path-purity: violations. Lines matter — test_analysis.py pins them."""
import time
import datetime
import numpy as np
import jax
import jax.numpy as jnp

from gofr_tpu.analysis import hot_path


class Engine:
    @hot_path
    def dispatch(self, state, logits):
        t0 = time.time()                      # L14: wall clock
        host = np.asarray(state)              # L15: d2h sync
        n = int(jnp.argmax(logits))           # L16: coerce traced value
        logits.block_until_ready()            # L17: device sync
        jax.device_get(state)                 # L18: device sync
        v = state.item()                      # L19: device sync
        self.metrics.increment_counter("app_x")   # L20: metric write
        self.logger.info("dispatched")        # L21: logging
        when = datetime.datetime.now()        # L22: wall clock
        return host, n, v, t0, when

    @hot_path
    def step(self):
        return self._helper()

    def _helper(self):
        # not decorated, but statically called from a @hot_path root:
        # the closure walk must still reach it
        return time.time()                    # L32: wall clock via closure
