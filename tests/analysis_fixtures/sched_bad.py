"""hot-path-purity: scheduler bookkeeping INSIDE the hot loop — the
anti-pattern serving/scheduler.py exists to prevent. Lines matter —
test_analysis.py pins them."""
import time

from gofr_tpu.analysis import hot_path


class Engine:
    @hot_path
    def admit_pass(self, batch):
        # admission-policy work belongs behind a boundary (the real
        # Scheduler's put/note_retire); doing it inline in a hot root
        # drags wall clocks, metrics and logging into the decode loop
        now = time.time()                               # L15: wall clock
        self.metrics.increment_counter("app_sched_rejections")  # L16
        self.logger.warn("shedding load")               # L17: logging
        return self._account(batch, now)

    def _account(self, batch, now):
        # undecorated fair-share bookkeeping statically reached from
        # the hot root: the closure walk must flag it too
        self.metrics.set_gauge("app_sched_lane_depth", len(batch))  # L23
        return time.time()                              # L24: wall clock
