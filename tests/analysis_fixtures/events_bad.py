"""hot-path-purity: flight-recorder events inlined in the hot loop —
the anti-pattern serving/events.py exists to prevent. Lines matter —
test_analysis.py pins them."""
import time

from gofr_tpu.analysis import hot_path


class Engine:
    @hot_path
    def step(self, batch):
        # ad-hoc event recording: wall-clock stamp, counter and log
        # write from the dispatch path
        self.ring.append({"ts": time.time(), "kind": "step"})   # L14
        self.metrics.increment_counter("app_events_total")      # L15
        self.logger.warn("event recorded", kind="step")         # L16
        return self._stamp(batch)

    def _stamp(self, batch):
        # undecorated helper on the closure: its clock read flags too
        return batch, time.time()                               # L21
