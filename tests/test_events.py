"""Flight data recorder (serving/events.py): ring bounds and drop
accounting, the versioned JSONL contract, skew-corrected fleet merging
with epoch tie-breaks, incident bundles for all three trigger reasons,
and the hard invariant — the ledger + detector fully ON change not a
single greedy token."""

import json

import pytest

from gofr_tpu.container.container import Container
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.events import (
    EVENTS_FORMAT, EVENTS_VERSION, KINDS, NO_EVENTS, EventLedger,
    EventLedgerConfig, FleetEventMerger, IncidentDetector,
    event_timeline_diff, parse_events, resolve_ledger)
from gofr_tpu.serving.glue import demo_llama_engine


class FakeClock:
    def __init__(self, now: float = 1000.0) -> None:
        self.now = now

    def __call__(self) -> float:
        return self.now


# ------------------------------------------------------- ring + drops
class TestLedgerRing:
    def test_ring_bound_and_per_kind_drop_accounting(self):
        led = EventLedger(EventLedgerConfig(capacity=4), host="h1")
        for _ in range(3):
            led.emit("sched.reject", severity="warn", cause="shed")
        for _ in range(7):
            led.emit("engine.restart", severity="error")
        assert len(led) == 4
        state = led.state()
        assert state["seq"] == 10
        assert state["totals"] == {"sched.reject": 3,
                                   "engine.restart": 7}
        # 6 rotated out: the 3 rejects plus 3 restarts, by kind
        assert state["dropped"] == {"sched.reject": 3,
                                    "engine.restart": 3}
        # the survivors are the NEWEST 4, oldest first
        kept = [e["seq"] for e in led.snapshot()]
        assert kept == [7, 8, 9, 10]

    def test_emit_returns_record_with_optional_fields(self):
        led = EventLedger(EventLedgerConfig(capacity=8), host="h1")
        ev = led.emit("fleet.failover", severity="error", epoch=3,
                      cause="takeover", trace_id="t" * 32, rank=1)
        assert ev["host"] == "h1" and ev["epoch"] == 3
        assert ev["trace_id"] == "t" * 32
        assert ev["attrs"] == {"rank": 1}
        plain = led.emit("engine.drain")
        assert "attrs" not in plain and "epoch" not in plain

    def test_unknown_kind_and_severity_raise(self):
        led = EventLedger(EventLedgerConfig(capacity=2))
        with pytest.raises(ValueError, match="unknown event kind"):
            led.emit("engine.restrat")
        with pytest.raises(ValueError, match="unknown severity"):
            led.emit("engine.restart", severity="fatal")

    def test_disabled_singleton_is_inert(self):
        assert NO_EVENTS.emit("engine.restart") is None
        assert not NO_EVENTS.enabled and len(NO_EVENTS) == 0
        # disabled returns BEFORE validation: the hot guard costs two
        # comparisons, never a set lookup
        assert NO_EVENTS.emit("not-a-kind") is None

    def test_emit_declares_metrics(self):
        container = Container()
        container.register_framework_metrics()
        led = EventLedger(EventLedgerConfig(capacity=1),
                          metrics=container.metrics)
        led.emit("obs.recompile", severity="warn")
        led.emit("obs.recompile", severity="warn")  # rotates the first
        snap = container.metrics.snapshot()["metrics"]
        totals = {tuple(sorted(s["labels"].items())): s["value"]
                  for s in snap["app_events_total"]["series"]}
        drops = {tuple(sorted(s["labels"].items())): s["value"]
                 for s in snap["app_events_dropped"]["series"]}
        assert totals[(("kind", "obs.recompile"),)] == 2.0
        assert drops[(("kind", "obs.recompile"),)] == 1.0

    def test_resolve_ledger_contract(self, monkeypatch):
        assert resolve_ledger(False) is NO_EVENTS
        assert resolve_ledger(
            EventLedgerConfig(capacity=0)) is NO_EVENTS
        led = EventLedger(EventLedgerConfig(capacity=2))
        assert resolve_ledger(led) is led
        assert resolve_ledger(None).enabled
        monkeypatch.setenv("GOFR_EVENTS", "0")
        assert resolve_ledger(None) is NO_EVENTS
        with pytest.raises(TypeError):
            resolve_ledger(42)


# ---------------------------------------------------------- jsonl/v1
class TestEventsFormat:
    def test_jsonl_round_trip(self):
        led = EventLedger(EventLedgerConfig(capacity=8), host="h1")
        led.emit("engine.drain", cause="admission closed")
        led.emit("engine.recovery", restart=1)
        header, events = parse_events(led.to_jsonl())
        assert header["format"] == EVENTS_FORMAT
        assert header["version"] == EVENTS_VERSION
        assert [e["kind"] for e in events] == ["engine.drain",
                                               "engine.recovery"]

    def test_unknown_format_and_version_refused(self):
        led = EventLedger(EventLedgerConfig(capacity=2))
        led.emit("engine.drain")
        good = led.to_jsonl().splitlines()
        bad_fmt = dict(json.loads(good[0]), format="gofr-workload")
        with pytest.raises(ValueError, match="format"):
            parse_events("\n".join([json.dumps(bad_fmt)] + good[1:]))
        bad_ver = dict(json.loads(good[0]), version=99)
        with pytest.raises(ValueError, match="version"):
            parse_events("\n".join([json.dumps(bad_ver)] + good[1:]))

    def test_filters(self):
        clock = FakeClock()
        led = EventLedger(EventLedgerConfig(capacity=16), clock=clock)
        led.emit("sched.reject")
        clock.now += 10
        led.emit("sched.reject")
        led.emit("engine.restart")
        assert len(led.snapshot(kind="sched.reject")) == 2
        assert len(led.snapshot(since=clock.now)) == 2
        assert [e["kind"] for e in led.snapshot(n=1)] \
            == ["engine.restart"]


# ------------------------------------------------------- fleet merge
class TestFleetMerge:
    def test_skew_correction_orders_across_hosts(self):
        # host A's clock runs 100s fast; without correction its events
        # sort far in the future. The merger estimates the offset from
        # digest["now"] vs. arrival time and corrects it away.
        merge_clock = FakeClock(2000.0)
        merger = FleetEventMerger(clock=merge_clock)
        clock_a = FakeClock(2100.0)  # +100s skew
        clock_b = FakeClock(2000.0)  # true time
        led_a = EventLedger(EventLedgerConfig(capacity=16),
                            host="a", clock=clock_a)
        led_b = EventLedger(EventLedgerConfig(capacity=16),
                            host="b", clock=clock_b)
        led_a.emit("fleet.failover", severity="warn", epoch=2)
        clock_a.now += 5
        clock_b.now += 5
        merge_clock.now += 5
        led_b.emit("engine.recovery")
        merger.ingest("a", led_a.digest())
        merger.ingest("b", led_b.digest())
        timeline = merger.timeline()
        assert [e["kind"] for e in timeline] \
            == ["fleet.failover", "engine.recovery"]
        skews = {e["host"]: e["skew_s"] for e in timeline}
        assert skews["a"] == pytest.approx(-100.0, abs=1e-6)
        assert skews["b"] == pytest.approx(0.0, abs=1e-6)

    def test_epoch_breaks_timestamp_ties(self):
        clock = FakeClock(3000.0)
        merger = FleetEventMerger(clock=clock)
        led_new = EventLedger(EventLedgerConfig(capacity=8),
                              host="new", clock=clock)
        led_old = EventLedger(EventLedgerConfig(capacity=8),
                              host="old", clock=clock)
        # same instant on both clocks: the fence reject at epoch 1
        # must sort BEFORE the takeover commit at epoch 2
        led_old.emit("fleet.fence_reject", severity="warn", epoch=1)
        led_new.emit("fleet.epoch_bump", epoch=2)
        merger.ingest("new", led_new.digest())
        merger.ingest("old", led_old.digest())
        assert [e["kind"] for e in merger.timeline()] \
            == ["fleet.fence_reject", "fleet.epoch_bump"]

    def test_digest_dedup_and_per_host_bound(self):
        clock = FakeClock()
        merger = FleetEventMerger(capacity_per_host=4, clock=clock)
        led = EventLedger(EventLedgerConfig(capacity=16, digest_size=16),
                          host="a", clock=clock)
        for _ in range(3):
            led.emit("sched.reject")
        merger.ingest("a", led.digest())
        merger.ingest("a", led.digest())  # same events re-delivered
        assert len(merger.timeline()) == 3
        for _ in range(4):
            led.emit("engine.restart")
        merger.ingest("a", led.digest())
        assert len(merger.timeline()) == 4  # bounded, oldest evicted

    def test_merger_backfills_missing_host(self):
        # engine ledgers default host="" — the heartbeat's host_id is
        # authoritative for attribution
        clock = FakeClock()
        merger = FleetEventMerger(clock=clock)
        led = EventLedger(EventLedgerConfig(capacity=8), clock=clock)
        led.emit("engine.drain")
        merger.ingest("worker-7", led.digest())
        assert merger.timeline()[0]["host"] == "worker-7"


# ---------------------------------------------------------- incidents
def make_detector(clock, **cfg):
    config = EventLedgerConfig(**cfg)
    led = EventLedger(config, host="h1", clock=clock)
    det = IncidentDetector(config, ledger=led, host="h1", clock=clock)
    return led, det


class TestIncidents:
    @pytest.mark.parametrize("reason", IncidentDetector.REASONS)
    def test_each_reason_opens_a_bundle(self, reason):
        clock = FakeClock()
        led, det = make_detector(clock)
        meta = det.trigger(reason, cause="test")
        assert meta is not None and meta["reason"] == reason
        # the trigger itself lands on the ledger as incident.open
        opened = led.snapshot(kind="incident.open")
        assert len(opened) == 1 and opened[0]["cause"] == reason

    def test_unknown_reason_raises(self):
        _, det = make_detector(FakeClock())
        with pytest.raises(ValueError, match="unknown incident reason"):
            det.trigger("leaky_abstraction")

    def test_debounce_per_reason(self):
        clock = FakeClock()
        _, det = make_detector(clock, incident_debounce_s=30.0)
        assert det.trigger("fast_burn") is not None
        assert det.trigger("fast_burn") is None  # debounced
        assert det.trigger("failover") is not None  # other reason OK
        clock.now += 31.0
        assert det.trigger("fast_burn") is not None
        assert det.state()["debounced"] == {"fast_burn": 1}

    def test_bundle_completeness_and_lazy_seal(self):
        clock = FakeClock()
        led, det = make_detector(clock, incident_window_s=60.0,
                                 incident_debounce_s=0.0)
        det.sources["goodput"] = lambda: {"busy_s": 1.0}
        det.sources["broken"] = lambda: 1 / 0
        led.emit("obs.fast_burn", severity="error")
        meta = det.trigger("fast_burn", cause="burn 14.4x",
                           trace_id="a" * 32)
        bundle = det.get(meta["id"])
        assert bundle["format"] == "gofr-incident"
        assert bundle["reason"] == "fast_burn"
        assert bundle["trace_id"] == "a" * 32
        assert bundle["state"]["goodput"] == {"busy_s": 1.0}
        assert "ZeroDivisionError" in bundle["state"]["broken"]["error"]
        assert "commit" in bundle["git"] and "ref" in bundle["git"]
        assert bundle["ledger"]["enabled"] is True
        kinds = [e["kind"] for e in bundle["timeline"]]
        assert "obs.fast_burn" in kinds
        assert bundle["sealed"] is False  # window still open
        # an event INSIDE the post-trigger window tops up on read ...
        clock.now += 10.0
        led.emit("engine.restart", severity="error")
        clock.now += 61.0
        led.emit("engine.recovery")  # ... one outside it does not
        sealed = det.get(meta["id"])
        kinds = [e["kind"] for e in sealed["timeline"]]
        assert sealed["sealed"] is True
        assert "engine.restart" in kinds
        assert "engine.recovery" not in kinds

    def test_spool_bound_and_disk_mirror(self, tmp_path):
        clock = FakeClock()
        config = EventLedgerConfig(spool_max=2, incident_debounce_s=0.0,
                                   spool_dir=str(tmp_path))
        led = EventLedger(config, host="h1", clock=clock)
        det = IncidentDetector(config, ledger=led, host="h1",
                               clock=clock)
        ids = []
        for reason in ("fast_burn", "failover", "restart_budget"):
            ids.append(det.trigger(reason)["id"])
            clock.now += 1.0
        listed = [m["id"] for m in det.list()]
        assert listed == ids[1:]  # oldest pruned at spool_max=2
        assert det.get(ids[0]) is None
        on_disk = sorted(p.name for p in tmp_path.glob("*.json"))
        assert on_disk == sorted(f"incident-{i}.json" for i in ids[1:])
        doc = json.loads(
            (tmp_path / f"incident-{ids[1]}.json").read_text())
        assert doc["id"] == ids[1]


# -------------------------------------------------------- replay diff
class TestTimelineDiff:
    def test_identical_timelines_do_not_diverge(self):
        evs = [{"kind": "engine.drain"}, {"kind": "engine.recovery"}]
        diff = event_timeline_diff(evs, list(evs))
        assert diff["diverged"] is False

    def test_missing_extra_count_and_order(self):
        rec = [{"kind": "sched.reject"}, {"kind": "sched.reject"},
               {"kind": "engine.drain"}]
        rep = [{"kind": "sched.reject"}, {"kind": "engine.restart"}]
        diff = event_timeline_diff(rec, rep)
        assert diff["diverged"] is True
        assert diff["kinds_missing"] == ["engine.drain"]
        assert diff["kinds_extra"] == ["engine.restart"]
        assert diff["count_divergence"]["sched.reject"] \
            == {"recorded": 2, "replayed": 1}
        assert diff["order_divergence"] == {
            "index": 1, "recorded": "sched.reject",
            "replayed": "engine.restart"}


# --------------------------------------------- zero-perturbation proof
def _greedy_tokens(events_knob):
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, seed=7, events=events_knob))
    eng.start()
    sp = SamplingParams(temperature=0.0, max_new_tokens=12)
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7]]
    reqs = [eng.submit(p, sp) for p in prompts]
    import time as _time
    deadline = _time.time() + 120
    while _time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        _time.sleep(0.005)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [list(r.generated) for r in reqs], eng


def test_ledger_and_detector_on_change_no_greedy_token():
    """The acceptance invariant: flight recorder fully ON (default
    ledger + incident detector wired by the engine) produces the exact
    token streams of a ledger-less engine."""
    base, _ = _greedy_tokens(False)
    with_events, eng = _greedy_tokens(True)
    assert base == with_events
    assert eng.events.enabled
    assert eng.incidents is not None


def test_kind_catalog_matches_emitters():
    """Every kind the serving modules emit is in the catalog, and the
    catalog carries no dead kinds (a typo'd emitter raises at emit
    time, but a stale catalog entry rots silently — this pins both)."""
    import re
    from pathlib import Path
    serving = Path(__file__).resolve().parent.parent \
        / "gofr_tpu" / "serving"
    emitted = set()
    for path in serving.glob("*.py"):
        emitted.update(re.findall(
            r"\.emit\(\s*['\"]([a-z_.]+)['\"]", path.read_text()))
    assert emitted, "no emit sites found — the scan regex broke"
    unknown = sorted(emitted - KINDS)
    assert not unknown, f"emitted kinds missing from KINDS: {unknown}"
    dead = sorted(KINDS - emitted)
    assert not dead, f"catalog kinds nothing emits: {dead}"
