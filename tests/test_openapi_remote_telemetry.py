"""OpenAPI serving, remote log-level switching, usage telemetry."""

import asyncio
import functools
import json

from gofr_tpu.config.env import DictConfig
from gofr_tpu.logging.logger import DEBUG, INFO, MockLogger
from gofr_tpu.logging.remote import (RemoteLevelUpdater,
                                     parse_level_response)
from gofr_tpu import telemetry
from gofr_tpu.app import App
from gofr_tpu.openapi import (WELL_KNOWN_SPEC, WELL_KNOWN_UI,
                              generate_spec)


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))
    return wrapper


def make_app(**cfg) -> App:
    return App(config=DictConfig({"APP_NAME": "spec-app",
                                  "APP_VERSION": "1.2.3", **cfg}))


# ----------------------------------------------------------------- openapi
class TestGeneratedSpec:
    def test_routes_become_path_items(self):
        app = make_app()

        @app.get("/users/{id}")
        def get_user(ctx):
            """Fetch one user."""

        @app.post("/users")
        def create_user(ctx):
            pass

        spec = generate_spec(app)
        assert spec["openapi"].startswith("3.0")
        assert spec["info"] == {"title": "spec-app", "version": "1.2.3"}
        get_op = spec["paths"]["/users/{id}"]["get"]
        assert get_op["summary"] == "Fetch one user."
        assert get_op["parameters"][0] == {
            "name": "id", "in": "path", "required": True,
            "schema": {"type": "string"}}
        post_op = spec["paths"]["/users"]["post"]
        assert "requestBody" in post_op
        assert "201" in post_op["responses"]
        # health documented; spec/UI routes not self-listed
        assert "/.well-known/health" in spec["paths"]
        assert WELL_KNOWN_SPEC not in spec["paths"]

    def test_spec_and_ui_served_over_http(self):
        # exercise the real handlers through the router
        app = make_app()

        @app.get("/greet")
        def greet(ctx):
            return "hi"

        match = app.router.match("GET", WELL_KNOWN_SPEC)
        assert match is not None
        result = match[0].handler(None)
        spec = json.loads(json.dumps(result.data))  # Raw envelope
        assert "/greet" in spec["paths"]

        ui = app.router.match("GET", WELL_KNOWN_UI)[0].handler(None)
        assert ui.content_type == "text/html"
        assert b"OpenAPI explorer" in ui.content
        assert WELL_KNOWN_SPEC.encode() in ui.content

    def test_file_mode_wins_when_static_spec_exists(self, tmp_path):
        import os
        from gofr_tpu.openapi import make_openapi_handler
        static = tmp_path / "static"
        static.mkdir()
        (static / "openapi.json").write_text('{"openapi": "3.0.0"}')
        app = make_app()
        handler = make_openapi_handler(app, static_dir=str(static))
        out = handler(None)
        assert out.content == b'{"openapi": "3.0.0"}'
        assert out.content_type == "application/json"


# ------------------------------------------------------- remote log level
class _FakeResponse:
    def __init__(self, payload, ok=True):
        self._payload = payload
        self.ok = ok

    def json(self):
        return self._payload


class _FakeService:
    def __init__(self, payload, ok=True):
        self.payload = payload
        self.ok = ok
        self.calls = 0

    async def get(self, path):
        self.calls += 1
        return _FakeResponse(self.payload, self.ok)


class TestRemoteLevel:
    def test_parse_shapes(self):
        ref_shape = {"data": [{"serviceName": "x",
                               "logLevel": {"LOG_LEVEL": "DEBUG"}}]}
        assert parse_level_response(ref_shape) == "DEBUG"
        assert parse_level_response({"level": "WARN"}) == "WARN"
        assert parse_level_response({"data": {"LOG_LEVEL": "ERROR"}}) == "ERROR"
        assert parse_level_response({"nope": 1}) is None
        assert parse_level_response("garbage") is None

    @async_test
    async def test_poll_applies_level_change(self):
        logger = MockLogger(level=INFO)
        updater = RemoteLevelUpdater(logger, _FakeService({"level": "DEBUG"}))
        assert await updater.poll_once() is True
        assert logger.level == DEBUG
        # same level again: no-op
        assert await updater.poll_once() is False

    @async_test
    async def test_unknown_level_name_is_rejected(self):
        logger = MockLogger(level=DEBUG)
        updater = RemoteLevelUpdater(logger, _FakeService({"level": "TRACE"}))
        assert await updater.poll_once() is False
        assert logger.level == DEBUG  # not coerced to INFO

    @async_test
    async def test_poll_survives_fetch_failure(self):
        class Exploding:
            async def get(self, path):
                raise ConnectionError("down")
        logger = MockLogger(level=INFO)
        updater = RemoteLevelUpdater(logger, Exploding())
        assert await updater.poll_once() is False
        assert logger.level == INFO

    def test_from_config_gated_on_url(self):
        from gofr_tpu.logging.remote import from_config
        logger = MockLogger()
        assert from_config(DictConfig(), logger) is None
        updater = from_config(
            DictConfig({"REMOTE_LOG_URL": "http://cfg.svc/level?app=x",
                        "REMOTE_LOG_FETCH_INTERVAL": "3"}), logger)
        assert updater is not None
        assert updater.interval_s == 3.0
        assert updater.path == "/level?app=x"
        assert updater.service.base_url == "http://cfg.svc"


# ------------------------------------------------------------- telemetry
class TestTelemetry:
    def test_enabled_flag_parsing(self, monkeypatch):
        monkeypatch.delenv("GOFR_TELEMETRY", raising=False)
        assert telemetry.enabled(DictConfig()) is True
        assert telemetry.enabled(DictConfig({"GOFR_TELEMETRY": "false"})) is False
        assert telemetry.enabled(DictConfig({"GOFR_TELEMETRY": "0"})) is False
        # OS env opt-out reaches DictConfig-backed apps (conftest sets it)
        monkeypatch.setenv("GOFR_TELEMETRY", "false")
        assert telemetry.enabled(DictConfig()) is False

    @async_test
    async def test_ping_posts_payload(self, monkeypatch):
        monkeypatch.setenv("GOFR_TELEMETRY", "true")
        from gofr_tpu.container.container import Container
        received = {}

        async def handler(reader, writer):
            data = await reader.read(4096)
            received["raw"] = data
            writer.write(b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok")
            await writer.drain()
            writer.close()

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        c = Container(config=DictConfig({"APP_NAME": "ping-app"}))
        c.app_name = "ping-app"
        ok = await telemetry.ping(c, "start",
                                  url=f"http://127.0.0.1:{port}/ping")
        assert ok is True
        body = received["raw"].split(b"\r\n\r\n", 1)[1]
        payload = json.loads(body)
        assert payload["event"] == "start"
        assert payload["app_name"] == "ping-app"
        assert payload["framework_version"]
        server.close()

    @async_test
    async def test_ping_disabled_and_unreachable_never_raise(self, monkeypatch):
        from gofr_tpu.container.container import Container
        c = Container(config=DictConfig({"GOFR_TELEMETRY": "false"}))
        assert await telemetry.ping(c, "start") is False
        monkeypatch.setenv("GOFR_TELEMETRY", "true")
        c2 = Container(config=DictConfig())
        assert await telemetry.ping(
            c2, "start", url="http://127.0.0.1:9/x") is False
