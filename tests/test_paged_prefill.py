"""Ragged paged chunk-attention kernel (Sq > 1) + the native paged
prefill/verify engine paths it unlocks.

Kernel-level: interpret-mode parity against the dense XLA reference
across history lengths (0 / page-aligned / mid-page), chunk lengths
that end mid-page, zero-length tail slots and GQA group sizes 1 and 4
— only rows < chunk_len per slot are compared (padding rows are
defined as discarded garbage).

Engine-level: with the kernel path active, chunked prefill, prefix
reattachment and speculative verify must dispatch ZERO ``gather_view``
calls (the prefill-side twin of the decode transfer-guard) while
staying greedy-bit-identical to the view path.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.paged_attention import (paged_chunk_attention,
                                          paged_chunk_attention_pallas,
                                          paged_chunk_attention_xla)


def _chunk_case(key, *, hq=4, hkv=2, hd=16, page=8, max_pages=10,
                n_pages=32, hists=(0, 11, 16), clens=(13, 5, 0), sq=16):
    """Pools + per-slot tables covering history + chunk rows, with the
    history/chunk K/V already resident (the model writes the chunk
    before attending, exactly like decode)."""
    b = len(hists)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, sq, hq, hd), jnp.float32)
    k_pool = jax.random.normal(ks[1], (hkv, n_pages, page, hd),
                               jnp.float32)
    v_pool = jax.random.normal(ks[2], (hkv, n_pages, page, hd),
                               jnp.float32)
    rng = np.random.default_rng(0)
    tables = np.full((b, max_pages), n_pages, np.int32)  # OOB = unalloc
    for i, (h_, c_) in enumerate(zip(hists, clens)):
        need = -(-(h_ + c_) // page)
        if need:
            tables[i, :need] = rng.choice(n_pages, size=need,
                                          replace=False)
    return (q, k_pool, v_pool, jnp.asarray(tables),
            jnp.asarray(hists, jnp.int32), jnp.asarray(clens, jnp.int32))


def _assert_valid_rows_match(got, want, clens, rtol=2e-5, atol=2e-5):
    """Rows past each slot's chunk length are padding garbage by
    contract — compare only the defined rows."""
    got, want = np.asarray(got), np.asarray(want)
    assert not np.isnan(got).any()
    valid = np.arange(got.shape[1])[None, :] < np.asarray(clens)[:, None]
    np.testing.assert_allclose(got[valid], want[valid],
                               rtol=rtol, atol=atol)


@pytest.mark.parametrize("hists,clens", [
    ((0, 0, 0), (16, 9, 1)),          # fresh prompts, chunk ends mid-page
    ((8, 16, 24), (16, 13, 5)),       # page-aligned histories
    ((3, 11, 21), (16, 13, 7)),       # mid-page histories
    ((0, 19, 40), (16, 16, 0)),       # zero-length tail slot
])
def test_interpret_matches_xla_reference(hists, clens):
    case = _chunk_case(jax.random.key(0), hists=hists, clens=clens)
    q, kp, vp, tables, h, c = case
    got = paged_chunk_attention_pallas(q, kp, vp, tables, h, c,
                                       interpret=True)
    want = paged_chunk_attention_xla(q, kp, vp, tables, h, c)
    _assert_valid_rows_match(got, want, clens)


@pytest.mark.parametrize("hq,hkv", [(4, 4), (8, 2)])  # GQA groups 1, 4
def test_gqa_group_sizes(hq, hkv):
    case = _chunk_case(jax.random.key(1), hq=hq, hkv=hkv,
                       hists=(0, 11, 16), clens=(13, 16, 7))
    q, kp, vp, tables, h, c = case
    got = paged_chunk_attention_pallas(q, kp, vp, tables, h, c,
                                       interpret=True)
    want = paged_chunk_attention_xla(q, kp, vp, tables, h, c)
    _assert_valid_rows_match(got, want, np.asarray(c))


def test_multi_q_block_and_multi_kv_chunk():
    """Sq wide enough to split into several q-blocks, histories long
    enough that the page walk double-buffers several 128-row chunks."""
    case = _chunk_case(jax.random.key(2), page=16, max_pages=24,
                       n_pages=64, hists=(200, 77), clens=(64, 37),
                       sq=64)
    q, kp, vp, tables, h, c = case
    got = paged_chunk_attention_pallas(q, kp, vp, tables, h, c,
                                       block_q=16, interpret=True)
    want = paged_chunk_attention_xla(q, kp, vp, tables, h, c)
    _assert_valid_rows_match(got, want, np.asarray(c))


def test_causal_mask_ignores_future_chunk_rows():
    """Poison pool rows past each query's causal horizon (future
    in-chunk rows AND rows past history+chunk): outputs of valid rows
    must not move."""
    case = _chunk_case(jax.random.key(3), hists=(8,), clens=(5,), sq=8)
    q, kp, vp, tables, h, c = case
    got_clean = paged_chunk_attention_pallas(q, kp, vp, tables, h, c,
                                             interpret=True)
    # poison everything at logical positions >= hist + clen = 13
    page = kp.shape[2]
    tab = np.asarray(tables)[0]
    poisoned = np.asarray(kp).copy()
    for logical in range(13, tab.size * page):
        pid = tab[logical // page]
        if pid < kp.shape[1]:
            poisoned[:, pid, logical % page] = 1e6
    got_poisoned = paged_chunk_attention_pallas(
        q, jnp.asarray(poisoned), vp, tables, h, c, interpret=True)
    _assert_valid_rows_match(got_poisoned, got_clean, np.asarray(c))


def test_dispatch_auto_on_cpu_is_xla():
    case = _chunk_case(jax.random.key(4))
    q, kp, vp, tables, h, c = case
    got = paged_chunk_attention(q, kp, vp, tables, h, c,
                                implementation="auto")
    want = paged_chunk_attention_xla(q, kp, vp, tables, h, c)
    _assert_valid_rows_match(got, want, np.asarray(c))


def test_bad_block_q_rejected():
    case = _chunk_case(jax.random.key(5), sq=12)
    q, kp, vp, tables, h, c = case
    with pytest.raises(ValueError, match="block_q"):
        paged_chunk_attention_pallas(q, kp, vp, tables, h, c,
                                     block_q=5, interpret=True)


# ------------------------------------------------- engine-level guard

from gofr_tpu.serving.engine import EngineConfig, SamplingParams  # noqa: E402
from gofr_tpu.serving.glue import demo_llama_engine  # noqa: E402

PROMPT = list(np.random.RandomState(5).randint(3, 200, size=30))


def _run(cfg, prompts, n=5):
    eng = demo_llama_engine(cfg)
    eng.start()
    sp = SamplingParams(temperature=0.0, max_new_tokens=n)
    reqs = [eng.submit(p, sp) for p in prompts]
    deadline = time.time() + 240
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    eng.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [r.generated for r in reqs], dict(eng.stats)


def test_native_paged_hot_paths_never_gather_view(monkeypatch):
    """Chunked prefill (narrow buckets force a 4-chunk walk), prefix
    reattachment (shared head re-admitted after a retire) and
    speculative verify must all run without materialising a dense
    per-slot view — and stay greedy-bit-identical to the view path,
    which still gathers (sanity check that the spy sees real calls)."""
    import gofr_tpu.ops.paged_kv as paged_kv

    calls = []
    real = paged_kv.gather_view

    def spy(pool, tables, dtype=None):
        calls.append(jax.tree_util.tree_leaves(pool)[0].shape)
        return real(pool, tables, dtype=dtype)

    monkeypatch.setattr(paged_kv, "gather_view", spy)

    shared = PROMPT[:16]
    prompts = [PROMPT, shared + [9, 9], shared + [11, 4]]
    base = dict(max_batch=2, max_seq=128, prefill_buckets=(8,),
                page_size=16, kv_layout="paged", seed=7,
                speculative=True, spec_ngram=1)

    got, stats = _run(EngineConfig(paged_attention="interpret", **base),
                      prompts)
    assert calls == [], f"native path gathered views: {calls}"
    # every guarded path actually ran
    assert stats["prefill_calls"] > 0
    assert stats["prefix_hits"] > 0
    assert stats["spec_passes"] > 0
    assert stats["view_bytes_avoided"] > 0

    want, view_stats = _run(EngineConfig(paged_attention="view", **base),
                            prompts)
    assert calls, "view path should exercise the spy"
    assert view_stats["view_bytes_avoided"] == 0
    assert got == want


def test_native_chunk_walk_matches_slot_layout():
    """Long prompt through the native chunk walk (interpret kernel)
    reproduces the slot layout's greedy stream — the same contract the
    view path holds."""
    native = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, prefill_buckets=(8,), seed=7,
        kv_layout="paged", page_size=16, paged_attention="interpret"))
    assert native._native_chunk and native._native_verify
    native.start()
    got = native.submit_sync(PROMPT, SamplingParams(
        temperature=0.0, max_new_tokens=6))
    native.stop()
    assert got.error is None and len(got.prompt_tokens) == len(PROMPT)

    slot = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, prefill_buckets=(8,), seed=7))
    slot.start()
    want = slot.submit_sync(PROMPT, SamplingParams(
        temperature=0.0, max_new_tokens=6))
    slot.stop()
    assert got.generated == want.generated


def test_native_chunk_ignores_decode_windows():
    """decode_windows bound the VIEW path's gather; the native walk is
    length-bounded already and must not compile windowed chunk
    variants (nor crash when windows are configured)."""
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=256, prefill_buckets=(16,), seed=7,
        kv_layout="paged", page_size=16, paged_attention="interpret",
        decode_windows=(48,)))
    assert eng._chunk_window(16, 16) is None
    eng.warmup(prompt_lens=(16,), chunked=True)
    eng.start()
    req = eng.submit_sync(PROMPT + PROMPT, SamplingParams(
        temperature=0.0, max_new_tokens=4))
    eng.stop()
    assert req.error is None and len(req.generated) == 4
