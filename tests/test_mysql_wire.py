"""MySQL client/server protocol: client against the mini server —
real handshake bytes, verified native-password auth, COM_QUERY result
sets."""

from dataclasses import dataclass

import pytest

from gofr_tpu.datasource.mysql_wire import (MiniMySQLServer, MySQLError,
                                            MySQLWire, escape_literal,
                                            expand_qmarks)


@pytest.fixture(scope="module")
def server():
    srv = MiniMySQLServer(user="app", password="s3cr3t")
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def db(server):
    c = MySQLWire(host="127.0.0.1", port=server.port,
                  user="app", password="s3cr3t", database="appdb")
    c.connect()
    yield c
    c.close()


def test_handshake_and_version(db):
    assert db.server_version.startswith("8.0")


def test_query_roundtrip(db):
    db.exec("CREATE TABLE IF NOT EXISTS t_my (id INTEGER, name TEXT)")
    db.exec("DELETE FROM t_my")
    res = db.exec("INSERT INTO t_my VALUES (?, ?), (?, ?)",
                  1, "ada", 2, "grace")
    assert res.rowcount == 2
    rows = db.query("SELECT id, name FROM t_my ORDER BY id")
    # typed decode from the column-definition type bytes
    assert [(r["id"], r["name"]) for r in rows] \
        == [(1, "ada"), (2, "grace")]
    assert db.query_row("SELECT name FROM t_my WHERE id = ?", 2)["name"] \
        == "grace"


def test_null_and_escaping(db):
    db.exec("CREATE TABLE IF NOT EXISTS t_esc (v TEXT)")
    db.exec("DELETE FROM t_esc")
    tricky = "o'brien\\path\nline2"
    db.exec("INSERT INTO t_esc VALUES (?)", tricky)
    assert db.query("SELECT v FROM t_esc")[0]["v"] == tricky
    db.exec("INSERT INTO t_esc VALUES (?)", None)
    values = [r["v"] for r in db.query("SELECT v FROM t_esc")]
    assert None in values


def test_qmark_expansion_rules():
    assert expand_qmarks("SELECT 'a?b', ?", (1,)) == "SELECT 'a?b', 1"
    assert escape_literal(b"\xbe\xef") == "x'beef'"
    with pytest.raises(MySQLError):
        expand_qmarks("SELECT ?", ())
    with pytest.raises(MySQLError):
        expand_qmarks("SELECT 1", (5,))
    # '?' inside comments and backtick identifiers is not a placeholder
    assert expand_qmarks("SELECT `a?b`, ? -- ok?\n", (1,)) \
        == "SELECT `a?b`, 1 -- ok?\n"
    assert expand_qmarks("SELECT /* hm? */ ?", (2,)) \
        == "SELECT /* hm? */ 2"
    assert expand_qmarks("SELECT ? # tail?", (3,)) == "SELECT 3 # tail?"


def test_transactions(db):
    db.exec("CREATE TABLE IF NOT EXISTS t_tx (id INTEGER)")
    db.exec("DELETE FROM t_tx")
    with db.begin() as tx:
        tx.exec("INSERT INTO t_tx VALUES (?)", 1)
    assert len(db.query("SELECT * FROM t_tx")) == 1
    with pytest.raises(RuntimeError):
        with db.begin() as tx:
            tx.exec("INSERT INTO t_tx VALUES (?)", 2)
            raise RuntimeError("boom")
    assert len(db.query("SELECT * FROM t_tx")) == 1


def test_error_packet_and_recovery(db):
    with pytest.raises(MySQLError) as exc:
        db.query("SELECT * FROM missing_table")
    assert exc.value.code == 1064 and exc.value.sqlstate == "42000"
    assert db.query_row("SELECT 1 AS one")["one"] == 1


def test_select_orm_lite_coerces(db):
    @dataclass
    class Person:
        id: int
        name: str

    db.exec("CREATE TABLE IF NOT EXISTS people_my (id INTEGER, name TEXT)")
    db.exec("DELETE FROM people_my")
    db.exec("INSERT INTO people_my VALUES (?, ?)", 1, "ada")
    assert db.select(Person, "SELECT id, name FROM people_my") \
        == [Person(1, "ada")]


def test_wrong_password_rejected(server):
    bad = MySQLWire(host="127.0.0.1", port=server.port,
                    user="app", password="WRONG")
    with pytest.raises(MySQLError) as exc:
        bad.connect()
    assert exc.value.code == 1045


def test_env_driven_container_swap(server):
    from gofr_tpu.config.env import DictConfig
    from gofr_tpu.datasource.sql import new_sql

    cfg = DictConfig({"DB_DIALECT": "mysql", "DB_HOST": "127.0.0.1",
                      "DB_PORT": str(server.port), "DB_USER": "app",
                      "DB_PASSWORD": "s3cr3t", "DB_NAME": "appdb"})
    db = new_sql(cfg)
    assert isinstance(db, MySQLWire)
    assert db.health_check()["status"] == "UP"
    db.close()


def test_health(db):
    assert db.health_check()["status"] == "UP"
    assert MySQLWire(host="127.0.0.1", port=1).health_check()["status"] \
        == "DOWN"
