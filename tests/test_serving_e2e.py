"""End-to-end serving tests: /chat and /embed through the real HTTP stack."""

import json

import jax
import pytest

from gofr_tpu.models.bert import BertConfig, bert_init
from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.handlers import make_chat_handler, make_embed_handler
from gofr_tpu.serving.tokenizer import ByteTokenizer

from .apputil import AppRunner


@pytest.fixture(scope="module")
def serving_app():
    tokenizer = ByteTokenizer()
    engine = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128))
    engine.start()

    bert_config = BertConfig.tiny()
    bert_params = bert_init(jax.random.key(0), bert_config)

    def build(app):
        app.container.add_model("chat", engine)
        app.container.tpu = engine  # health surface
        app.post("/chat", make_chat_handler(engine, tokenizer))
        app.post("/embed", make_embed_handler(bert_params, bert_config, tokenizer))

    runner = AppRunner(build=build)
    with runner as app:
        yield app
    engine.stop()


def test_chat_completion(serving_app):
    status, headers, data = serving_app.request(
        "POST", "/chat",
        {"prompt": "hello", "max_tokens": 8, "temperature": 0.0})
    assert status == 201
    body = json.loads(data)["data"]
    assert len(body["tokens"]) == 8
    assert body["usage"]["completion_tokens"] == 8
    assert body["usage"]["ttft_ms"] is not None
    assert isinstance(body["text"], str)


def test_chat_streaming_sse(serving_app):
    status, headers, data = serving_app.request(
        "POST", "/chat",
        {"prompt": "stream me", "max_tokens": 5, "temperature": 0.0,
         "stream": True})
    assert status == 200  # wait -- streams return 200 via Stream path
    text = data.decode()
    events = [line for line in text.split("\n\n") if line.startswith("data: ")]
    assert events[-1] == "data: [DONE]"
    token_events = [json.loads(e[len("data: "):]) for e in events[:-1]]
    assert len(token_events) == 5
    assert all("token" in e for e in token_events)


def test_stream_client_disconnect_cancels_request(serving_app):
    """A client that vanishes mid-SSE must not keep its slot decoding
    to a dead socket: the engine cancels the request and stays healthy
    for everyone else (reference stance: one bad client never degrades
    the server)."""
    import http.client
    import time as _time

    engine = serving_app.app.container.get_model("chat")
    conn = http.client.HTTPConnection("127.0.0.1", serving_app.port,
                                      timeout=10)
    body = json.dumps({"prompt": "never-ending story", "stream": True,
                       "temperature": 0.0, "max_tokens": 4096})
    conn.request("POST", "/chat", body=body,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    resp.read(64)   # a few streamed bytes prove generation started
    # hold a reference to the live request before walking away
    abandoned = next(r for r in engine.active
                     if r is not None and r.params.max_new_tokens == 4096)
    conn.close()    # ...and the client vanishes

    deadline = _time.time() + 30
    while _time.time() < deadline and abandoned.finished_at is None:
        _time.sleep(0.05)
    assert abandoned.finished_at is not None, \
        "abandoned stream still holds a slot"
    # CANCELLED, not run-to-ceiling: the max_seq=128 cache would allow
    # ~110 generated tokens — cancellation must stop far earlier
    assert abandoned.cancelled
    assert len(abandoned.generated) <= 48, len(abandoned.generated)

    # and the engine keeps serving others
    status, _, data = serving_app.request(
        "POST", "/chat", {"prompt": "hi", "max_tokens": 3,
                          "temperature": 0.0})
    assert status == 201
    assert json.loads(data)["data"]["usage"]["completion_tokens"] == 3


def test_stream_engine_failure_visible_in_sse():
    """A stream cut short by an engine failure (shutdown, kv loss)
    must end with an error event, never the [DONE] sentinel — clients
    cannot be allowed to mistake truncation for completion."""
    import http.client
    import threading

    tokenizer = ByteTokenizer()
    # a deep sequence keeps the doomed stream ALIVE until stop() lands:
    # at max_seq=128 the generation caps out in ~50 ms and a loaded box
    # can finish (emitting [DONE]) before the stop thread is scheduled
    engine = demo_llama_engine(EngineConfig(max_batch=2, max_seq=4096))
    engine.start()
    try:
        with AppRunner() as runner:
            runner.app.post("/chat", make_chat_handler(engine, tokenizer))
            conn = http.client.HTTPConnection("127.0.0.1", runner.port,
                                              timeout=30)
            body = json.dumps({"prompt": "doomed stream", "stream": True,
                               "temperature": 0.0, "max_tokens": 4096})
            conn.request("POST", "/chat", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read(64)  # generation started
            threading.Thread(target=engine.stop, daemon=True).start()
            rest = resp.read().decode()
            conn.close()
        assert "[DONE]" not in rest
        assert '"error"' in rest and "engine stopped" in rest
    finally:
        engine.stop()


def test_overloaded_engine_returns_503():
    """With max_waiting bounded, a flood beyond slots+queue gets an
    immediate 503 instead of joining an ever-slower queue."""
    from concurrent.futures import ThreadPoolExecutor

    tokenizer = ByteTokenizer()
    engine = demo_llama_engine(
        EngineConfig(max_batch=1, max_seq=64, max_waiting=1, seed=1))
    engine.start()
    try:
        with AppRunner() as runner:
            runner.app.post("/chat", make_chat_handler(engine, tokenizer))

            def one(i):
                status, _, data = runner.request(
                    "POST", "/chat",
                    {"prompt": f"flood {i}", "max_tokens": 24,
                     "temperature": 0.0})
                return status

            with ThreadPoolExecutor(16) as pool:
                statuses = list(pool.map(one, range(16)))
        assert 503 in statuses          # backpressure is visible...
        ok = [s for s in statuses if s == 201]
        assert ok                       # ...while admitted work completes
    finally:
        engine.stop()


def test_chat_missing_prompt(serving_app):
    status, _, data = serving_app.request("POST", "/chat", {"nope": 1})
    assert status == 400
    assert "prompt" in json.loads(data)["error"]["message"]


def test_chat_bad_params(serving_app):
    status, _, _ = serving_app.request(
        "POST", "/chat", {"prompt": "x", "max_tokens": -5})
    assert status == 400
    status, _, _ = serving_app.request(
        "POST", "/chat", {"prompt": "x", "temperature": "hot"})
    assert status == 400


def test_embed_single_and_batch(serving_app):
    status, _, data = serving_app.request("POST", "/embed", {"input": "hello"})
    assert status == 201
    body = json.loads(data)
    assert len(body["embeddings"]) == 1
    assert body["dim"] == len(body["embeddings"][0])

    status, _, data = serving_app.request(
        "POST", "/embed", {"input": ["a", "b", "longer sentence here"]})
    body = json.loads(data)
    assert len(body["embeddings"]) == 3


def test_embed_missing_input(serving_app):
    status, _, _ = serving_app.request("POST", "/embed", {})
    assert status == 400


def test_health_shows_engine(serving_app):
    status, body = serving_app.get_json("/.well-known/health")
    assert status == 200
    checks = body["data"]["checks"]
    assert checks["tpu"]["status"] == "UP"
    assert checks["tpu"]["total_generated"] >= 0


def test_concurrent_chat_over_http(serving_app):
    import concurrent.futures as futures

    def one(i):
        # 8 concurrent generations on a loaded CI box can exceed the
        # 10s default while the suite churns around them
        status, _, data = serving_app.request(
            "POST", "/chat",
            {"prompt": f"req {i}", "max_tokens": 4, "temperature": 0.0},
            timeout=60)
        return status, json.loads(data)

    with futures.ThreadPoolExecutor(8) as pool:
        results = list(pool.map(one, range(8)))
    assert all(s == 201 for s, _ in results)
    assert all(len(b["data"]["tokens"]) == 4 for _, b in results)


def test_serve_model_wires_metrics_and_health():
    engine = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64))

    def build(app):
        app.serve_model("llm", engine, ByteTokenizer())

    with AppRunner(build=build) as app:
        status, _, data = app.request(
            "POST", "/chat", {"prompt": "hi", "max_tokens": 3, "temperature": 0.0})
        assert status == 201
        status, body = app.get_json("/.well-known/health")
        assert body["data"]["checks"]["tpu"]["status"] == "UP"
        _, _, metrics_data = app.request("GET", "/metrics", port=app.metrics_port)
        assert "app_chat_ttft_seconds_count" in metrics_data.decode()
    assert engine._running is False  # on_shutdown stopped it
