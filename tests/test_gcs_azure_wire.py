"""GCS JSON-API and Azure Blob REST wire clients against their mini
servers — Bearer and SharedKey auth enforced for real."""

import base64

import pytest

from gofr_tpu.datasource.azure_blob_wire import (
    AzureBlobError, AzureBlobWire, MiniAzureBlobServer)
from gofr_tpu.datasource.gcs_wire import GCSError, GCSWire, MiniGCSServer
from gofr_tpu.datasource.object_store import ObjectNotFound

KEY = base64.b64encode(b"super-secret-account-key").decode()


@pytest.fixture(scope="module")
def gcs_server():
    srv = MiniGCSServer(token="tok-123")
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def gcs(gcs_server):
    client = GCSWire(endpoint=f"127.0.0.1:{gcs_server.port}",
                     bucket="models", token="tok-123")
    client.connect()
    return client


@pytest.fixture(scope="module")
def az_server():
    srv = MiniAzureBlobServer(account="acct", key_b64=KEY)
    srv.start()
    yield srv
    srv.close()


@pytest.fixture()
def az(az_server):
    client = AzureBlobWire(endpoint=f"127.0.0.1:{az_server.port}",
                           account="acct", key_b64=KEY,
                           container="artifacts")
    client.connect()
    return client


# ------------------------------------------------------------------ GCS

def test_gcs_upload_download_delete(gcs):
    gcs.upload("ckpt/weights.bin", b"\x00\x01payload")
    assert gcs.download("ckpt/weights.bin") == b"\x00\x01payload"
    assert gcs.exists("ckpt/weights.bin") is True
    gcs.delete("ckpt/weights.bin")
    assert gcs.exists("ckpt/weights.bin") is False
    with pytest.raises(ObjectNotFound):
        gcs.download("ckpt/weights.bin")
    with pytest.raises(ObjectNotFound):
        gcs.delete("ckpt/weights.bin")


def test_gcs_list_with_prefix_and_pagination(gcs, gcs_server, monkeypatch):
    for i in range(7):
        gcs.upload(f"logs/{i:02d}", b"x")
    gcs.upload("other/1", b"y")
    assert gcs.list_blobs(prefix="logs/") == [f"logs/{i:02d}"
                                              for i in range(7)]
    # force tiny pages so the nextPageToken loop actually runs
    monkeypatch.setattr("gofr_tpu.datasource.gcs_wire._PAGE_SIZE", 3)
    assert gcs.list_blobs(prefix="logs/") == [f"logs/{i:02d}"
                                              for i in range(7)]


def test_gcs_wrong_token_is_401(gcs_server):
    bad = GCSWire(endpoint=f"127.0.0.1:{gcs_server.port}",
                  bucket="models", token="WRONG")
    with pytest.raises(GCSError, match="401"):
        bad.upload("x", b"y")
    assert bad.health_check()["status"] == "DOWN"


def test_gcs_health(gcs):
    assert gcs.health_check()["status"] == "UP"


# ---------------------------------------------------------------- Azure

def test_azure_upload_download_delete(az):
    az.upload_blob("run1/trace.json", b'{"spans": []}')
    assert az.download_blob("run1/trace.json") == b'{"spans": []}'
    az.delete_blob("run1/trace.json")
    with pytest.raises(ObjectNotFound):
        az.download_blob("run1/trace.json")
    with pytest.raises(ObjectNotFound):
        az.delete_blob("run1/trace.json")


def test_azure_no_overwrite_conflict(az):
    az.upload_blob("once", b"a")
    with pytest.raises(AzureBlobError, match="exists"):
        az.upload_blob("once", b"b", overwrite=False)
    az.upload_blob("once", b"c")  # overwrite=True wins
    assert az.download_blob("once") == b"c"


def test_azure_list_with_pagination(az, monkeypatch):
    for i in range(6):
        az.upload_blob(f"shard/{i}", b"x")
    assert az.list_blob_names(prefix="shard/") \
        == [f"shard/{i}" for i in range(6)]
    monkeypatch.setattr(
        "gofr_tpu.datasource.azure_blob_wire._PAGE_SIZE", 2)
    assert az.list_blob_names(prefix="shard/") \
        == [f"shard/{i}" for i in range(6)]


def test_azure_wrong_key_is_403(az_server):
    bad = AzureBlobWire(endpoint=f"127.0.0.1:{az_server.port}",
                        account="acct",
                        key_b64=base64.b64encode(b"wrong").decode(),
                        container="artifacts")
    with pytest.raises(AzureBlobError, match="403"):
        bad.upload_blob("x", b"y")


def test_azure_health(az):
    assert az.health_check()["status"] == "UP"
    assert AzureBlobWire(endpoint="127.0.0.1:1", account="a",
                         key_b64=KEY).health_check()["status"] == "DOWN"
