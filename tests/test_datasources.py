"""Datasource layer: SQL, Redis, KV, file store, container wiring.

Mirrors the reference's hermetic-fake test strategy (SURVEY §4):
sqlite-in-memory for SQL (go-sqlmock analog), the in-process Redis
(miniredis analog), tmp dirs for the file store.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import pytest

from gofr_tpu.config.env import DictConfig
from gofr_tpu.container.container import Container
from gofr_tpu.container.mock import MockContainer
from gofr_tpu.datasource.file_store import FileError, LocalFileSystem
from gofr_tpu.datasource.kv import FileKV, InMemoryKV, KeyNotFound
from gofr_tpu.datasource.redis import Redis, RedisError
from gofr_tpu.datasource.sql import (SQL, SQLError, placeholder,
                                     placeholders, quote_ident)
from gofr_tpu.logging.logger import MockLogger
from gofr_tpu.metrics.registry import Manager


@dataclass
class Employee:
    id: int
    name: str
    salary: float


class TestSQL:
    def make(self) -> SQL:
        db = SQL(database=":memory:")
        db.use_logger(MockLogger())
        m = Manager()
        m.new_histogram("app_sql_stats", "t")
        db.use_metrics(m)
        db.connect()
        return db

    def test_query_exec_roundtrip(self):
        db = self.make()
        db.exec("CREATE TABLE t (id INTEGER PRIMARY KEY, name TEXT)")
        db.exec("INSERT INTO t (name) VALUES (?)", "ada")
        rows = db.query("SELECT * FROM t")
        assert rows[0]["name"] == "ada"
        assert db.query_row("SELECT * FROM t WHERE id = ?", 1)["name"] == "ada"
        assert db.query_row("SELECT * FROM t WHERE id = ?", 99) is None

    def test_select_maps_dataclass(self):
        db = self.make()
        db.exec("CREATE TABLE employee (id INTEGER PRIMARY KEY, "
                "name TEXT, salary REAL)")
        db.exec("INSERT INTO employee (name, salary) VALUES (?, ?)",
                "grace", 120.5)
        out = db.select(Employee, "SELECT * FROM employee")
        assert out == [Employee(id=1, name="grace", salary=120.5)]

    def test_select_requires_dataclass(self):
        db = self.make()
        with pytest.raises(SQLError):
            db.select(dict, "SELECT 1")

    def test_transaction_commit_and_rollback(self):
        db = self.make()
        db.exec("CREATE TABLE t (id INTEGER PRIMARY KEY, v TEXT)")
        with db.begin() as tx:
            tx.exec("INSERT INTO t (v) VALUES (?)", "kept")
        with pytest.raises(RuntimeError):
            with db.begin() as tx:
                tx.exec("INSERT INTO t (v) VALUES (?)", "dropped")
                raise RuntimeError("boom")
        values = [r["v"] for r in db.query("SELECT v FROM t")]
        assert values == ["kept"]

    def test_metrics_and_logs_recorded(self):
        db = self.make()
        db.exec("CREATE TABLE t (id INTEGER)")
        db.query("SELECT * FROM t")
        assert db.metrics.get_histogram_count("app_sql_stats",
                                              type="select") == 1
        assert any("SQL" in str(line.get("message", ""))
                   for line in db.logger.lines)

    def test_unconnected_raises(self):
        with pytest.raises(SQLError):
            SQL().query("SELECT 1")

    def test_unsupported_dialect_connect(self):
        db = SQL(dialect="mysql")
        with pytest.raises(SQLError):
            db.connect()

    def test_unknown_dialect_rejected(self):
        with pytest.raises(SQLError):
            SQL(dialect="oracle")

    def test_health(self):
        db = self.make()
        assert db.health_check()["status"] == "UP"
        db.close()
        assert db.health_check()["status"] == "DOWN"

    def test_placeholder_styles(self):
        assert placeholder("sqlite", 1) == "?"
        assert placeholder("mysql", 2) == "?"
        assert placeholder("postgres", 2) == "$2"
        assert placeholders("postgres", 3) == "$1, $2, $3"
        assert placeholders("sqlite", 2) == "?, ?"

    def test_quote_ident_rejects_injection(self):
        assert quote_ident("salary") == "salary"
        with pytest.raises(SQLError):
            quote_ident("salary; DROP TABLE t")


class TestRedis:
    def make(self) -> Redis:
        r = Redis()
        m = Manager()
        m.new_histogram("app_redis_stats", "t")
        r.use_metrics(m)
        r.connect()
        return r

    def test_string_ops(self):
        r = self.make()
        assert r.set("k", "v")
        assert r.get("k") == "v"
        assert r.exists("k") == 1
        assert r.delete("k") == 1
        assert r.get("k") is None

    def test_expiry(self):
        r = self.make()
        r.set("k", "v", ex=0.02)
        assert r.get("k") == "v"
        assert 0 < r.ttl("k") <= 0.02
        time.sleep(0.03)
        assert r.get("k") is None
        assert r.ttl("k") == -2
        r.set("forever", 1)
        assert r.ttl("forever") == -1

    def test_incr_decr(self):
        r = self.make()
        assert r.incr("n") == 1
        assert r.incr("n", 5) == 6
        assert r.decr("n") == 5

    def test_hash_list_set_ops(self):
        r = self.make()
        assert r.hset("h", "f", "1") == 1
        assert r.hset("h", "f", "2") == 0
        assert r.hget("h", "f") == "2"
        assert r.hgetall("h") == {"f": "2"}
        assert r.hdel("h", "f") == 1

        r.rpush("l", "a", "b")
        r.lpush("l", "z")
        assert r.lrange("l", 0, -1) == ["z", "a", "b"]
        assert r.llen("l") == 3
        assert r.lpop("l") == "z"
        assert r.rpop("l") == "b"

        assert r.sadd("s", "x", "y") == 2
        assert r.sismember("s", "x")
        assert r.smembers("s") == {"x", "y"}
        assert r.srem("s", "x") == 1

    def test_wrongtype(self):
        r = self.make()
        r.set("k", "str")
        with pytest.raises(RedisError):
            r.hset("k", "f", "v")

    def test_keys_and_flush(self):
        r = self.make()
        r.set("user:1", "a")
        r.set("user:2", "b")
        r.set("other", "c")
        assert sorted(r.keys("user:*")) == ["user:1", "user:2"]
        r.flushdb()
        assert r.keys() == []

    def test_not_connected(self):
        with pytest.raises(RedisError):
            Redis().get("k")

    def test_health_and_metrics(self):
        r = self.make()
        r.set("k", "v")
        assert r.health_check()["status"] == "UP"
        assert r.metrics.get_histogram_count("app_redis_stats",
                                             type="set") == 1


class TestKV:
    @pytest.mark.parametrize("make", [
        lambda tmp: InMemoryKV(),
        lambda tmp: FileKV(str(tmp / "kv.db")),
    ], ids=["memory", "file"])
    def test_roundtrip(self, make, tmp_path):
        kv = make(tmp_path)
        kv.connect()
        kv.set("a", "1")
        kv.set("b", "2")
        kv.set("a", "3")
        assert kv.get("a") == "3"
        assert kv.keys() == ["a", "b"]
        kv.delete("a")
        with pytest.raises(KeyNotFound):
            kv.get("a")
        assert kv.health_check()["status"] == "UP"
        kv.close()

    def test_file_kv_persists(self, tmp_path):
        path = str(tmp_path / "kv.db")
        kv = FileKV(path)
        kv.connect()
        kv.set("k", "v")
        kv.close()
        kv2 = FileKV(path)
        kv2.connect()
        assert kv2.get("k") == "v"


class TestFileStore:
    def make(self, tmp_path) -> LocalFileSystem:
        fs = LocalFileSystem(str(tmp_path))
        fs.connect()
        return fs

    def test_create_read_append_remove(self, tmp_path):
        fs = self.make(tmp_path)
        fs.create("a/b.txt", "hello")
        assert fs.read_text("a/b.txt") == "hello"
        fs.append("a/b.txt", " world")
        assert fs.read_text("a/b.txt") == "hello world"
        info = fs.stat("a/b.txt")
        assert info.size == 11 and not info.is_dir
        fs.rename("a/b.txt", "a/c.txt")
        assert fs.exists("a/c.txt") and not fs.exists("a/b.txt")
        fs.remove("a/c.txt")
        assert not fs.exists("a/c.txt")

    def test_dirs_and_glob(self, tmp_path):
        fs = self.make(tmp_path)
        fs.mkdir("sub/deep")
        fs.create("sub/x.json", "{}")
        fs.create("sub/y.csv", "a,b")
        names = [i.name for i in fs.read_dir("sub")]
        assert names == ["deep", "x.json", "y.csv"]
        assert fs.glob("sub/*.json") == ["sub/x.json"]
        fs.remove_all("sub")
        assert not fs.exists("sub")

    def test_path_escape_blocked(self, tmp_path):
        fs = self.make(tmp_path)
        with pytest.raises(FileError):
            fs.read("../outside.txt")

    def test_row_readers(self, tmp_path):
        fs = self.make(tmp_path)
        fs.create("rows.json", '[{"a": 1}, {"a": 2}]')
        assert [r["a"] for r in fs.read_rows("rows.json")] == [1, 2]
        fs.create("rows.jsonl", '{"a": 1}\n{"a": 2}\n')
        assert len(fs.read_rows("rows.jsonl")) == 2
        fs.create("rows.csv", "a,b\n1,2\n3,4\n")
        rows = list(fs.read_rows("rows.csv"))
        assert rows[1] == {"a": "3", "b": "4"}
        fs.create("rows.txt", "plain")
        with pytest.raises(FileError):
            fs.read_rows("rows.txt", kind="txt")

    def test_health(self, tmp_path):
        fs = self.make(tmp_path)
        assert fs.health_check()["status"] == "UP"


class TestContainerWiring:
    def test_env_driven_creation(self):
        config = DictConfig({"DB_DIALECT": "sqlite", "DB_NAME": ":memory:",
                             "REDIS_HOST": "localhost"})
        c = Container.create(config)
        assert c.sql is not None and c.redis is not None
        c.sql.exec("CREATE TABLE t (id INTEGER)")
        c.redis.set("k", "v")
        health = c.health()
        assert health["checks"]["sql"]["status"] == "UP"
        assert health["checks"]["redis"]["status"] == "UP"

    def test_unconfigured_stays_none(self):
        c = Container.create(DictConfig())
        assert c.sql is None and c.redis is None

    def test_add_store_provider_order(self, tmp_path):
        c = Container.create(DictConfig())
        fs = c.add_file_store(LocalFileSystem(str(tmp_path)))
        assert fs.logger is c.logger and fs.metrics is c.metrics
        kv = c.add_kv_store(InMemoryKV())
        assert kv.logger is c.logger
        assert c.health()["checks"]["file"]["status"] == "UP"

    def test_mock_container_has_real_backends(self):
        mc = MockContainer()
        mc.sql.exec("CREATE TABLE t (id INTEGER)")
        mc.sql.exec("INSERT INTO t VALUES (1)")
        assert mc.sql.query("SELECT * FROM t")[0]["id"] == 1
        mc.redis.set("k", "v")
        assert mc.redis.get("k") == "v"
        mc.kv.set("a", "b")
        assert mc.kv.get("a") == "b"
        # mock() still swaps a slot for a recorder
        rec = mc.mock("sql")
        mc.sql.query("SELECT 1")
        assert rec.calls_to("query") == [(("SELECT 1",), {})]
